/* CPython extension for write-path hot loops that ctypes cannot reach
 * (they take Python object sequences, so a ctypes boundary would pay the
 * per-item conversion it exists to avoid), plus the fused GIL-free
 * chunk-prepare entry point: the whole-page-walk C call
 * (ptq_chunk_prepare, linked in from parquet_tpu_native.cc) runs under
 * Py_BEGIN_ALLOW_THREADS with every buffer bound through the buffer
 * protocol — no ctypes argument marshaling under the GIL, so the host
 * prepare pool scales with cores.
 *
 * Built by native/Makefile into parquet_tpu/_native_ext.so; every caller
 * degrades to the pure-Python implementation when the module is absent.
 */

#define PY_SSIZE_T_CLEAN
#include <Python.h>

/* _PyDict_SetItem_KnownHash left the private headers in 3.13; the
 * precomputed-hash insert is an optimization, not a dependency. */
#if PY_VERSION_HEX >= 0x030D0000
#define DICT_SETITEM_KNOWNHASH(d, k, v, h) PyDict_SetItem((d), (k), (v))
#else
#define DICT_SETITEM_KNOWNHASH(d, k, v, h) \
  _PyDict_SetItem_KnownHash((d), (k), (v), (h))
#endif

#include <stdint.h>
#include <string.h>

/* The fused whole-chunk walk from parquet_tpu_native.cc (plain C ABI; the
 * Makefile links the same object file into this extension). The prototype
 * lives in the shared header so the two translation units cannot drift. */
#include "parquet_tpu_native.h"

/* chunk_prepare(src, codec, validate_crc, max_def, max_rep, type_size,
 *               delta_nbits, expected_values, pages, def_out, rep_out,
 *               values_out, packed_out, delta_out, scratch, h_is_rle,
 *               h_counts, h_values, h_byteoff, d_widths, d_bytestart,
 *               d_outstart, d_mins, totals, stage_ns|None, err_info) -> rc
 *
 * The fused whole-chunk prepare: ONE Python->C transition per column chunk,
 * with the entire walk (page-header parse, CRC verify, decompress, level
 * decode, value prescan, repack) under Py_BEGIN_ALLOW_THREADS. Table
 * capacities derive from the buffer lengths (pages: 18 int64 per row;
 * h_is_rle: one byte per run slot; d_widths: 4 bytes per miniblock slot), so
 * the caller grows a table by handing in a bigger buffer — same retry
 * contract as the ctypes binding. Returns ptq_chunk_prepare's rc (page count
 * or negative code); err_info (int64[4] buffer) carries {stage, page, byte
 * offset, 0} when rc < 0.
 */
static PyObject *chunk_prepare(PyObject *self, PyObject *args) {
  Py_buffer src, pages, def_out, rep_out, values, packed, delta, scratch;
  Py_buffer h_is_rle, h_counts, h_values, h_byteoff;
  Py_buffer d_widths, d_bytestart, d_outstart, d_mins, totals, err_info;
  int codec, validate_crc, max_def, max_rep, type_size, delta_nbits;
  long long expected_values;
  PyObject *stage_obj;
  if (!PyArg_ParseTuple(
          args, "y*iiiiiiLw*w*w*w*w*w*w*w*w*w*w*w*w*w*w*w*Ow*", &src, &codec,
          &validate_crc, &max_def, &max_rep, &type_size, &delta_nbits,
          &expected_values, &pages, &def_out, &rep_out, &values, &packed,
          &delta, &scratch, &h_is_rle, &h_counts, &h_values, &h_byteoff,
          &d_widths, &d_bytestart, &d_outstart, &d_mins, &totals, &stage_obj,
          &err_info))
    return NULL;
  Py_buffer stage;
  stage.buf = NULL;
  if (stage_obj != Py_None &&
      PyObject_GetBuffer(stage_obj, &stage, PyBUF_CONTIG) < 0) {
    PyBuffer_Release(&src);
    PyBuffer_Release(&pages);
    PyBuffer_Release(&def_out);
    PyBuffer_Release(&rep_out);
    PyBuffer_Release(&values);
    PyBuffer_Release(&packed);
    PyBuffer_Release(&delta);
    PyBuffer_Release(&scratch);
    PyBuffer_Release(&h_is_rle);
    PyBuffer_Release(&h_counts);
    PyBuffer_Release(&h_values);
    PyBuffer_Release(&h_byteoff);
    PyBuffer_Release(&d_widths);
    PyBuffer_Release(&d_bytestart);
    PyBuffer_Release(&d_outstart);
    PyBuffer_Release(&d_mins);
    PyBuffer_Release(&totals);
    PyBuffer_Release(&err_info);
    return NULL;
  }
  Py_ssize_t rc;
  Py_BEGIN_ALLOW_THREADS
  rc = ptq_chunk_prepare(
      (const uint8_t *)src.buf, (size_t)src.len, codec, validate_crc, max_def,
      max_rep, type_size, delta_nbits, (int64_t)expected_values,
      (int64_t *)pages.buf, (size_t)(pages.len / (18 * 8)),
      (uint16_t *)def_out.buf, (uint16_t *)rep_out.buf, (uint8_t *)values.buf,
      (size_t)values.len, (uint8_t *)packed.buf, (size_t)packed.len,
      (uint8_t *)delta.buf, (size_t)delta.len, (uint8_t *)scratch.buf,
      (size_t)scratch.len, (uint8_t *)h_is_rle.buf, (int64_t *)h_counts.buf,
      (uint64_t *)h_values.buf, (int64_t *)h_byteoff.buf,
      (size_t)h_is_rle.len, (uint32_t *)d_widths.buf,
      (int64_t *)d_bytestart.buf, (int32_t *)d_outstart.buf,
      (uint64_t *)d_mins.buf, (size_t)(d_widths.len / 4),
      (int64_t *)totals.buf, stage.buf ? (int64_t *)stage.buf : NULL,
      err_info.len >= 32 ? (int64_t *)err_info.buf : NULL);
  Py_END_ALLOW_THREADS
  PyBuffer_Release(&err_info);
  PyBuffer_Release(&src);
  PyBuffer_Release(&pages);
  PyBuffer_Release(&def_out);
  PyBuffer_Release(&rep_out);
  PyBuffer_Release(&values);
  PyBuffer_Release(&packed);
  PyBuffer_Release(&delta);
  PyBuffer_Release(&scratch);
  PyBuffer_Release(&h_is_rle);
  PyBuffer_Release(&h_counts);
  PyBuffer_Release(&h_values);
  PyBuffer_Release(&h_byteoff);
  PyBuffer_Release(&d_widths);
  PyBuffer_Release(&d_bytestart);
  PyBuffer_Release(&d_outstart);
  PyBuffer_Release(&d_mins);
  PyBuffer_Release(&totals);
  if (stage.buf) PyBuffer_Release(&stage);
  return PyLong_FromSsize_t(rc);
}

/* chunk_encode(route, values, ba_offsets|None, nv, type_size, dict_width,
 *              dict_raw|None, dict_num, def_levels|None, num_entries,
 *              max_def, codec, dpv, with_crc, per_page, out, scratch,
 *              pages, totals, stage_ns|None, err_info) -> rc
 *
 * The fused whole-chunk ENCODE walk (ptq_chunk_encode): ONE Python->C
 * transition per column chunk with the entire page split + level pack +
 * value encode + compress + Thrift framing under Py_BEGIN_ALLOW_THREADS —
 * the write-side mirror of chunk_prepare, so parallel encoders scale with
 * cores instead of the GIL. Table capacity derives from the pages buffer
 * length (8 int64 per row). Returns the data-page count or a negative
 * PTQ_E_* code; err_info (int64[4]) carries {stage, page, 0, 0} on
 * failure.
 */
static PyObject *chunk_encode(PyObject *self, PyObject *args) {
  int route, type_size, dict_width, max_def, codec, dpv, with_crc;
  long long nv, dict_num, num_entries, per_page;
  Py_buffer values, out, scratch, pages, totals, err_info;
  PyObject *ba_obj, *dict_obj, *def_obj, *stage_obj;
  if (!PyArg_ParseTuple(args, "iy*OLiiOLOLiiiiLw*w*w*w*Ow*", &route, &values,
                        &ba_obj, &nv, &type_size, &dict_width, &dict_obj,
                        &dict_num, &def_obj, &num_entries, &max_def, &codec,
                        &dpv, &with_crc, &per_page, &out, &scratch, &pages,
                        &totals, &stage_obj, &err_info))
    return NULL;
  Py_buffer ba, dict_raw, def_b, stage;
  ba.buf = NULL;
  dict_raw.buf = NULL;
  def_b.buf = NULL;
  stage.buf = NULL;
  int ok = 1;
  if (ba_obj != Py_None && PyObject_GetBuffer(ba_obj, &ba, PyBUF_CONTIG_RO) < 0)
    ok = 0;
  if (ok && dict_obj != Py_None &&
      PyObject_GetBuffer(dict_obj, &dict_raw, PyBUF_CONTIG_RO) < 0)
    ok = 0;
  if (ok && def_obj != Py_None &&
      PyObject_GetBuffer(def_obj, &def_b, PyBUF_CONTIG_RO) < 0)
    ok = 0;
  if (ok && stage_obj != Py_None &&
      PyObject_GetBuffer(stage_obj, &stage, PyBUF_CONTIG) < 0)
    ok = 0;
  Py_ssize_t rc = -1;
  if (ok) {
    Py_BEGIN_ALLOW_THREADS
    rc = ptq_chunk_encode(
        route, (const uint8_t *)values.buf, (size_t)values.len,
        ba.buf ? (const int64_t *)ba.buf : NULL, (int64_t)nv, type_size,
        dict_width, dict_raw.buf ? (const uint8_t *)dict_raw.buf : NULL,
        dict_raw.buf ? (size_t)dict_raw.len : 0, (int64_t)dict_num,
        def_b.buf ? (const uint16_t *)def_b.buf : NULL, (int64_t)num_entries,
        max_def, codec, dpv, with_crc, (int64_t)per_page, (uint8_t *)out.buf,
        (size_t)out.len, (uint8_t *)scratch.buf, (size_t)scratch.len,
        (int64_t *)pages.buf, (size_t)(pages.len / (8 * 8)),
        (int64_t *)totals.buf, stage.buf ? (int64_t *)stage.buf : NULL,
        err_info.len >= 32 ? (int64_t *)err_info.buf : NULL);
    Py_END_ALLOW_THREADS
  }
  if (ba.buf) PyBuffer_Release(&ba);
  if (dict_raw.buf) PyBuffer_Release(&dict_raw);
  if (def_b.buf) PyBuffer_Release(&def_b);
  if (stage.buf) PyBuffer_Release(&stage);
  PyBuffer_Release(&values);
  PyBuffer_Release(&out);
  PyBuffer_Release(&scratch);
  PyBuffer_Release(&pages);
  PyBuffer_Release(&totals);
  PyBuffer_Release(&err_info);
  if (!ok) return NULL;
  return PyLong_FromSsize_t(rc);
}

/* dict_indices_str(list_of_str, max_uniques)
 *   -> (uniques_list, indices_u32_bytes, total_utf8, uniq_utf8)
 *   | None (unique count exceeds max_uniques)
 *   | False (an item is not exactly `str`: caller takes the byte-domain path)
 *
 * The OBJECT-domain dictionary probe for string columns: dedup the Python
 * str objects BEFORE any UTF-8 materialization, so a dictionary-encoded
 * chunk only ever encodes its (few) uniques to bytes — the 1M-row string
 * column's byte conversion was the serial write path's single biggest cost.
 * Byte-identical to probing the encoded bytes because str -> UTF-8 is
 * injective (first occurrences coincide, so the dictionary order matches).
 * total_utf8/uniq_utf8 are the summed encoded lengths of all values /
 * of the uniques (the inputs of the dict-vs-plain size cutoff), computed
 * from the cached UTF-8 forms during the same pass.
 */
typedef struct {
  Py_hash_t hash; /* cached str hash of the unique */
  uint32_t uid;   /* 0xffffffff = empty slot */
} dstr_slot;

static PyObject *dict_indices_str(PyObject *self, PyObject *args) {
  PyObject *seq;
  Py_ssize_t max_uniques;
  if (!PyArg_ParseTuple(args, "On", &seq, &max_uniques)) return NULL;
  PyObject *fast = PySequence_Fast(seq, "dict_indices_str expects a sequence");
  if (fast == NULL) return NULL;
  Py_ssize_t n = PySequence_Fast_GET_SIZE(fast);
  PyObject **items = PySequence_Fast_ITEMS(fast);

  /* open-addressed (hash, uid) table instead of a PyDict: no PyLong boxing
   * per hit, no dict-resize churn — str objects cache their hash after the
   * first PyObject_Hash, so warm probes are a table walk plus (rarely more
   * than) one string equality check. The table starts SMALL and doubles as
   * uniques arrive (rehash over the few uniques is cheap), so the probe's
   * random accesses stay cache-resident for low-cardinality columns — the
   * case dictionary encoding exists for. */
  size_t tsize = 4096;
  dstr_slot *table = (dstr_slot *)malloc(tsize * sizeof(dstr_slot));
  int64_t *ulens = (int64_t *)malloc((size_t)(max_uniques + 1) * sizeof(int64_t));
  PyObject *indices = PyBytes_FromStringAndSize(NULL, n * 4);
  PyObject *uniques = PyList_New(0);
  if (table == NULL || ulens == NULL || indices == NULL || uniques == NULL) {
    if (table == NULL || ulens == NULL) PyErr_NoMemory();
    goto fail;
  }
  memset(table, 0xff, tsize * sizeof(dstr_slot));
  {
    size_t tmask = tsize - 1;
    uint32_t *idx = (uint32_t *)PyBytes_AS_STRING(indices);
    int64_t total_utf8 = 0;
    int64_t uniq_utf8 = 0;
    for (Py_ssize_t i = 0; i < n; i++) {
      PyObject *it = items[i];
      if (!PyUnicode_CheckExact(it)) {
        /* mixed/other input: the byte-domain probe is the oracle there
         * (object equality and byte equality diverge across types) */
        free(table);
        free(ulens);
        Py_DECREF(indices);
        Py_DECREF(uniques);
        Py_DECREF(fast);
        Py_RETURN_FALSE;
      }
      /* the cached str hash, without the tp_hash dispatch per item (str
       * computes it once and memoizes; -1 means not yet computed) */
      Py_hash_t h = ((PyASCIIObject *)it)->hash;
      if (h == -1) {
        h = PyObject_Hash(it);
        if (h == -1) goto fail;
      }
      size_t slot = (size_t)h & tmask;
      for (;;) {
        dstr_slot *s = &table[slot];
        if (s->uid == 0xffffffffu) {
          Py_ssize_t next = PyList_GET_SIZE(uniques);
          if (next >= max_uniques) {
            /* would exceed the cutoff: dictionary encoding does not pay */
            free(table);
            free(ulens);
            Py_DECREF(indices);
            Py_DECREF(uniques);
            Py_DECREF(fast);
            Py_RETURN_NONE;
          }
          Py_ssize_t ul;
          if (PyUnicode_AsUTF8AndSize(it, &ul) == NULL) goto fail;
          s->hash = h;
          s->uid = (uint32_t)next;
          ulens[next] = (int64_t)ul;
          uniq_utf8 += (int64_t)ul;
          total_utf8 += (int64_t)ul;
          if (PyList_Append(uniques, it) < 0) goto fail;
          idx[i] = (uint32_t)next;
          if ((size_t)(next + 2) * 2 > tsize) {
            /* double + rehash over the (few) uniques so the load factor —
             * and the probe's working set — stays small */
            size_t nsize = tsize * 2;
            dstr_slot *nt = (dstr_slot *)malloc(nsize * sizeof(dstr_slot));
            if (nt == NULL) {
              PyErr_NoMemory();
              goto fail;
            }
            memset(nt, 0xff, nsize * sizeof(dstr_slot));
            for (size_t o = 0; o < tsize; o++) {
              if (table[o].uid == 0xffffffffu) continue;
              size_t ns = (size_t)table[o].hash & (nsize - 1);
              while (nt[ns].uid != 0xffffffffu) ns = (ns + 1) & (nsize - 1);
              nt[ns] = table[o];
            }
            free(table);
            table = nt;
            tsize = nsize;
            tmask = nsize - 1;
          }
          break;
        }
        if (s->hash == h) {
          PyObject *u = PyList_GET_ITEM(uniques, (Py_ssize_t)s->uid);
          if (u == it || PyUnicode_Compare(u, it) == 0) {
            idx[i] = s->uid;
            total_utf8 += ulens[s->uid];
            break;
          }
          if (PyErr_Occurred()) goto fail;
        }
        slot = (slot + 1) & tmask;
      }
    }
    free(table);
    free(ulens);
    Py_DECREF(fast);
    PyObject *out = Py_BuildValue("(OOLL)", uniques, indices,
                                  (long long)total_utf8, (long long)uniq_utf8);
    Py_DECREF(uniques);
    Py_DECREF(indices);
    return out;
  }

fail:
  free(table);
  free(ulens);
  Py_XDECREF(indices);
  Py_XDECREF(uniques);
  Py_DECREF(fast);
  return NULL;
}

/* encode_items(seq) -> (flat_bytes, lengths_int64_le_bytes)
 *
 * One C pass over a sequence of str/bytes: str encodes UTF-8, bytes copies
 * verbatim. Raises TypeError on any other item type (callers fall back to
 * the general Python path).
 */
static PyObject *encode_items(PyObject *self, PyObject *arg) {
  PyObject *fast = PySequence_Fast(arg, "encode_items expects a sequence");
  if (fast == NULL) return NULL;
  Py_ssize_t n = PySequence_Fast_GET_SIZE(fast);
  PyObject **items = PySequence_Fast_ITEMS(fast);

  PyObject *lengths = PyBytes_FromStringAndSize(NULL, n * 8);
  if (lengths == NULL) {
    Py_DECREF(fast);
    return NULL;
  }
  int64_t *lens = (int64_t *)PyBytes_AS_STRING(lengths);

  /* pass 1: sizes (PyUnicode_AsUTF8AndSize caches the UTF-8 form on the
   * unicode object, so pass 2 reuses it without re-encoding) */
  int64_t total = 0;
  for (Py_ssize_t i = 0; i < n; i++) {
    PyObject *it = items[i];
    Py_ssize_t len;
    if (PyUnicode_Check(it)) {
      if (PyUnicode_AsUTF8AndSize(it, &len) == NULL) goto fail;
    } else if (PyBytes_Check(it)) {
      len = PyBytes_GET_SIZE(it);
    } else {
      PyErr_Format(PyExc_TypeError,
                   "encode_items: item %zd is %.80s, expected str or bytes", i,
                   Py_TYPE(it)->tp_name);
      goto fail;
    }
    lens[i] = (int64_t)len;
    total += (int64_t)len;
  }

  PyObject *flat = PyBytes_FromStringAndSize(NULL, (Py_ssize_t)total);
  if (flat == NULL) goto fail;
  char *dst = PyBytes_AS_STRING(flat);

  for (Py_ssize_t i = 0; i < n; i++) {
    PyObject *it = items[i];
    const char *src;
    Py_ssize_t len;
    if (PyUnicode_Check(it)) {
      src = PyUnicode_AsUTF8AndSize(it, &len);
      if (src == NULL) {
        Py_DECREF(flat);
        goto fail;
      }
    } else {
      src = PyBytes_AS_STRING(it);
      len = PyBytes_GET_SIZE(it);
    }
    memcpy(dst, src, (size_t)len);
    dst += len;
  }

  Py_DECREF(fast);
  PyObject *out = PyTuple_Pack(2, flat, lengths);
  Py_DECREF(flat);
  Py_DECREF(lengths);
  return out;

fail:
  Py_DECREF(lengths);
  Py_DECREF(fast);
  return NULL;
}

/* dict_indices(list_of_bytes, max_uniques) -> (uniques_list, indices_u32_bytes)
 * or None when the unique count exceeds max_uniques.
 *
 * The write-side dictionary decision over byte values: one C pass with a
 * Python dict as the hash table (C-API calls, no interpreter dispatch).
 */
static PyObject *dict_indices(PyObject *self, PyObject *args) {
  PyObject *seq;
  Py_ssize_t max_uniques;
  if (!PyArg_ParseTuple(args, "On", &seq, &max_uniques)) return NULL;
  PyObject *fast = PySequence_Fast(seq, "dict_indices expects a sequence");
  if (fast == NULL) return NULL;
  Py_ssize_t n = PySequence_Fast_GET_SIZE(fast);
  PyObject **items = PySequence_Fast_ITEMS(fast);

  PyObject *indices = PyBytes_FromStringAndSize(NULL, n * 4);
  PyObject *table = PyDict_New();
  PyObject *uniques = PyList_New(0);
  if (indices == NULL || table == NULL || uniques == NULL) goto fail;
  uint32_t *idx = (uint32_t *)PyBytes_AS_STRING(indices);

  for (Py_ssize_t i = 0; i < n; i++) {
    PyObject *it = items[i];
    PyObject *found = PyDict_GetItemWithError(table, it); /* borrowed */
    if (found != NULL) {
      idx[i] = (uint32_t)PyLong_AsUnsignedLong(found);
      continue;
    }
    if (PyErr_Occurred()) goto fail; /* unhashable */
    Py_ssize_t next = PyList_GET_SIZE(uniques);
    if (next >= max_uniques) {
      /* would exceed the cutoff: dictionary encoding does not pay */
      Py_DECREF(indices);
      Py_DECREF(table);
      Py_DECREF(uniques);
      Py_DECREF(fast);
      Py_RETURN_NONE;
    }
    PyObject *num = PyLong_FromSsize_t(next);
    if (num == NULL || PyDict_SetItem(table, it, num) < 0) {
      Py_XDECREF(num);
      goto fail;
    }
    Py_DECREF(num);
    if (PyList_Append(uniques, it) < 0) goto fail;
    idx[i] = (uint32_t)next;
  }

  Py_DECREF(table);
  Py_DECREF(fast);
  PyObject *out = PyTuple_Pack(2, uniques, indices);
  Py_DECREF(uniques);
  Py_DECREF(indices);
  return out;

fail:
  Py_XDECREF(indices);
  Py_XDECREF(table);
  Py_XDECREF(uniques);
  Py_DECREF(fast);
  return NULL;
}

/* rows_from_slices(elems_list, offsets_buf_int64, null_mask_or_None)
 *   -> [elems[a:b] | None, ...]
 *
 * The per-row tail of the vectorized LIST/MAP assembly: one PyList_GetSlice
 * per row instead of an interpreter-dispatched comprehension. offsets is a
 * contiguous int64 buffer of n+1 entries; null_mask (optional) is a
 * contiguous uint8/bool buffer of n entries — rows flagged there become None.
 */
static PyObject *rows_from_slices(PyObject *self, PyObject *args) {
  PyObject *elems, *off_obj, *mask_obj;
  if (!PyArg_ParseTuple(args, "O!OO", &PyList_Type, &elems, &off_obj, &mask_obj))
    return NULL;
  Py_buffer ob, mb;
  mb.buf = NULL;
  if (PyObject_GetBuffer(off_obj, &ob, PyBUF_CONTIG_RO) < 0) return NULL;
  if (mask_obj != Py_None &&
      PyObject_GetBuffer(mask_obj, &mb, PyBUF_CONTIG_RO) < 0) {
    PyBuffer_Release(&ob);
    return NULL;
  }
  Py_ssize_t n = (Py_ssize_t)(ob.len / 8) - 1;
  const int64_t *off = (const int64_t *)ob.buf;
  const uint8_t *mask = mb.buf ? (const uint8_t *)mb.buf : NULL;
  Py_ssize_t ne = PyList_GET_SIZE(elems);
  PyObject *out = NULL;
  if (n < 0 || (mask && (Py_ssize_t)mb.len < n)) {
    PyErr_SetString(PyExc_ValueError, "rows_from_slices: bad offsets/mask");
    goto done;
  }
  out = PyList_New(n);
  if (out == NULL) goto done;
  for (Py_ssize_t i = 0; i < n; i++) {
    if (mask && mask[i]) {
      Py_INCREF(Py_None);
      PyList_SET_ITEM(out, i, Py_None);
      continue;
    }
    int64_t a = off[i], b = off[i + 1];
    if (a < 0 || b < a || b > (int64_t)ne) {
      Py_DECREF(out);
      out = NULL;
      PyErr_SetString(PyExc_ValueError, "rows_from_slices: offsets out of range");
      goto done;
    }
    PyObject *s = PyList_GetSlice(elems, (Py_ssize_t)a, (Py_ssize_t)b);
    if (s == NULL) {
      Py_DECREF(out);
      out = NULL;
      goto done;
    }
    PyList_SET_ITEM(out, i, s);
  }
done:
  PyBuffer_Release(&ob);
  if (mb.buf) PyBuffer_Release(&mb);
  return out;
}

/* take_bytes(data, offsets_i64, indices_i64) -> (new_offsets_bytes, out_bytes)
 *
 * Byte-array gather (dictionary expansion) in one pass with ONE output
 * allocation: PyBytes_FromStringAndSize(NULL, ...) skips both the memset a
 * ctypes string buffer pays and the extra copy string_at() makes. Offsets
 * come back as raw int64 little-endian bytes (np.frombuffer views them).
 */
static PyObject *take_bytes(PyObject *self, PyObject *args) {
  Py_buffer db, ob, ib;
  if (!PyArg_ParseTuple(args, "y*y*y*", &db, &ob, &ib)) return NULL;
  const char *src = (const char *)db.buf;
  const int64_t *off = (const int64_t *)ob.buf;
  const int64_t *idx = (const int64_t *)ib.buf;
  Py_ssize_t n_src = ob.len / 8 - 1;
  Py_ssize_t n = ib.len / 8;
  PyObject *off_out = NULL, *data_out = NULL, *result = NULL;
  if (n_src < 0) {
    PyErr_SetString(PyExc_ValueError, "take_bytes: empty offsets");
    goto done;
  }
  off_out = PyBytes_FromStringAndSize(NULL, (n + 1) * 8);
  if (off_out == NULL) goto done;
  int64_t *no = (int64_t *)PyBytes_AS_STRING(off_out);
  int64_t total = 0;
  int bad = 0; /* 1 = index out of range, 2 = corrupt offsets */
  /* both passes are pure C over held buffers: release the GIL so gathers
   * running on prepare worker threads overlap instead of serializing */
  Py_BEGIN_ALLOW_THREADS
  no[0] = 0;
  for (Py_ssize_t i = 0; i < n; i++) {
    int64_t k = idx[i];
    if (k < 0 || k >= (int64_t)n_src) {
      bad = 1;
      break;
    }
    int64_t len = off[k + 1] - off[k];
    if (len < 0 || off[k] < 0 || off[k + 1] > (int64_t)db.len) {
      bad = 2;
      break;
    }
    total += len;
    no[i + 1] = total;
  }
  Py_END_ALLOW_THREADS
  if (bad) {
    if (bad == 1)
      PyErr_SetString(PyExc_IndexError, "take_bytes: index out of range");
    else
      PyErr_SetString(PyExc_ValueError, "take_bytes: corrupt offsets");
    goto done;
  }
  data_out = PyBytes_FromStringAndSize(NULL, (Py_ssize_t)total);
  if (data_out == NULL) goto done;
  char *dst = PyBytes_AS_STRING(data_out);
  Py_BEGIN_ALLOW_THREADS
  for (Py_ssize_t i = 0; i < n; i++) {
    int64_t k = idx[i];
    memcpy(dst + no[i], src + off[k], (size_t)(no[i + 1] - no[i]));
  }
  Py_END_ALLOW_THREADS
  result = PyTuple_Pack(2, off_out, data_out);
done:
  Py_XDECREF(off_out);
  Py_XDECREF(data_out);
  PyBuffer_Release(&db);
  PyBuffer_Release(&ob);
  PyBuffer_Release(&ib);
  return result;
}

/* dict_rows(names_tuple, columns_tuple) -> [ {name: col[i] ...}, ... ]
 *
 * The final zip of column value lists into row dicts (flat rows, structs,
 * list<struct> elements): one PyDict_SetItem per cell at C speed. Each
 * column is a value list, OR a ("slices", elems, offsets_buf, mask_or_None)
 * spec that slices a LIST column's element values straight into the row
 * dict (no intermediate per-row list-of-lists pass). `elems` is a Python
 * list, or a contiguous numeric ndarray — then each row's element list is
 * built DIRECTLY from the buffer (PyLong/PyFloat per cell), skipping the
 * whole-column tolist() pass entirely.
 */
#define COLK_LIST 0
#define COLK_SLICES 1
#define COLK_SLICES_ARR 2
typedef struct {
  int kind;
  PyObject *name;      /* interned column name */
  Py_hash_t hash;      /* precomputed name hash: the per-cell insert skips
                          PyObject_Hash (one call per CELL otherwise) */
  PyObject *list;      /* COLK_LIST: values; COLK_SLICES: elems */
  const int64_t *off;  /* COLK_SLICES* */
  const uint8_t *mask; /* COLK_SLICES*, may be NULL */
  const char *data;    /* COLK_SLICES_ARR: element buffer */
  char fmt;            /* COLK_SLICES_ARR: buffer format char */
  Py_ssize_t itemsize; /* COLK_SLICES_ARR */
  Py_buffer ob, mb, eb; /* held buffers to release */
  int has_mb, has_eb;
} colspec;

/* one element of a COLK_SLICES_ARR buffer as a Python object */
static inline PyObject *arr_cell(const colspec *s, int64_t idx) {
  const char *p = s->data + idx * s->itemsize;
  switch (s->fmt) {
    case 'b': return PyLong_FromLong(*(const int8_t *)p);
    case 'B': return PyLong_FromLong(*(const uint8_t *)p);
    case 'h': return PyLong_FromLong(*(const int16_t *)p);
    case 'H': return PyLong_FromLong(*(const uint16_t *)p);
    case 'i': return PyLong_FromLong(*(const int32_t *)p);
    case 'I': return PyLong_FromUnsignedLong(*(const uint32_t *)p);
    case 'l': case 'q': return PyLong_FromLongLong(*(const int64_t *)p);
    case 'L': case 'Q':
      return PyLong_FromUnsignedLongLong(*(const uint64_t *)p);
    case 'f': return PyFloat_FromDouble(*(const float *)p);
    case 'd': return PyFloat_FromDouble(*(const double *)p);
    case '?': {
      PyObject *v = *(const uint8_t *)p ? Py_True : Py_False;
      Py_INCREF(v);
      return v;
    }
  }
  PyErr_SetString(PyExc_TypeError, "dict_rows: unsupported element format");
  return NULL;
}

static PyObject *dict_rows(PyObject *self, PyObject *args) {
  PyObject *names, *cols;
  if (!PyArg_ParseTuple(args, "O!O!", &PyTuple_Type, &names, &PyTuple_Type,
                        &cols))
    return NULL;
  Py_ssize_t k = PyTuple_GET_SIZE(names);
  if (PyTuple_GET_SIZE(cols) != k) {
    PyErr_SetString(PyExc_ValueError, "dict_rows: names/columns mismatch");
    return NULL;
  }
  if (k > 256) {
    PyErr_SetString(PyExc_ValueError, "dict_rows: too many columns");
    return NULL;
  }
  colspec cs[256];
  Py_ssize_t n = -1;
  Py_ssize_t parsed = 0;
  PyObject *out = NULL;
  for (Py_ssize_t j = 0; j < k; j++, parsed++) {
    PyObject *c = PyTuple_GET_ITEM(cols, j);
    colspec *s = &cs[j];
    s->has_mb = 0;
    s->has_eb = 0;
    s->name = PyTuple_GET_ITEM(names, j);
    s->hash = PyObject_Hash(s->name);
    if (s->hash == -1) goto fail;
    Py_ssize_t cn;
    if (PyList_Check(c)) {
      s->kind = COLK_LIST;
      s->list = c;
      cn = PyList_GET_SIZE(c);
    } else if (PyTuple_Check(c) && PyTuple_GET_SIZE(c) == 4) {
      Py_ssize_t ne;
      PyObject *elems = PyTuple_GET_ITEM(c, 1);
      if (PyList_Check(elems)) {
        s->kind = COLK_SLICES;
        s->list = elems;
        ne = PyList_GET_SIZE(elems);
      } else {
        if (PyObject_GetBuffer(elems, &s->eb,
                               PyBUF_CONTIG_RO | PyBUF_FORMAT) < 0) {
          PyErr_SetString(PyExc_TypeError,
                          "dict_rows: slices elems must be a list or buffer");
          goto fail;
        }
        s->kind = COLK_SLICES_ARR;
        s->has_eb = 1;
        s->data = (const char *)s->eb.buf;
        s->itemsize = s->eb.itemsize;
        /* accept native-order markers ('@'/'='): itemsize always comes from
         * the view, so standard-size vs native-size is moot here */
        const char *f = s->eb.format ? s->eb.format : "";
        if (*f == '@' || *f == '=') f++;
        s->fmt = (f[0] != '\0' && f[1] == '\0') ? f[0] : '\0';
        Py_ssize_t want_size = 0;
        switch (s->fmt) {
          case 'b': case 'B': case '?': want_size = 1; break;
          case 'h': case 'H': want_size = 2; break;
          case 'i': case 'I': case 'f': want_size = 4; break;
          case 'l': case 'L': case 'q': case 'Q': case 'd': want_size = 8; break;
        }
        if (want_size == 0 || s->itemsize != want_size) {
          PyErr_SetString(PyExc_TypeError,
                          "dict_rows: unsupported elems buffer format");
          PyBuffer_Release(&s->eb);
          goto fail;
        }
        ne = s->eb.len / s->itemsize;
      }
      if (PyObject_GetBuffer(PyTuple_GET_ITEM(c, 2), &s->ob, PyBUF_CONTIG_RO) < 0) {
        if (s->has_eb) PyBuffer_Release(&s->eb);
        goto fail;
      }
      s->off = (const int64_t *)s->ob.buf;
      cn = (Py_ssize_t)(s->ob.len / 8) - 1;
      PyObject *m = PyTuple_GET_ITEM(c, 3);
      s->mask = NULL;
      if (m != Py_None) {
        if (PyObject_GetBuffer(m, &s->mb, PyBUF_CONTIG_RO) < 0) {
          PyBuffer_Release(&s->ob);
          if (s->has_eb) PyBuffer_Release(&s->eb);
          goto fail;
        }
        s->has_mb = 1;
        if ((Py_ssize_t)s->mb.len < cn) {
          PyErr_SetString(PyExc_ValueError, "dict_rows: mask too short");
          parsed++;
          goto fail;
        }
        s->mask = (const uint8_t *)s->mb.buf;
      }
      /* validate offsets once: monotone within elems bounds */
      for (Py_ssize_t i = 0; i <= cn; i++) {
        if (s->off[i] < 0 || s->off[i] > (int64_t)ne ||
            (i && s->off[i] < s->off[i - 1])) {
          PyErr_SetString(PyExc_ValueError, "dict_rows: offsets out of range");
          parsed++;
          goto fail;
        }
      }
    } else {
      PyErr_SetString(PyExc_TypeError,
                      "dict_rows: column must be a list or slices spec");
      goto fail;
    }
    if (n < 0)
      n = cn;
    else if (cn != n) {
      PyErr_SetString(PyExc_ValueError, "dict_rows: column length mismatch");
      parsed++;
      goto fail;
    }
  }
  if (n < 0) n = 0;
  out = PyList_New(n);
  if (out == NULL) goto fail;
  for (Py_ssize_t i = 0; i < n; i++) {
    PyObject *d = _PyDict_NewPresized(k);
    if (d == NULL) goto fail_out;
    for (Py_ssize_t j = 0; j < k; j++) {
      colspec *s = &cs[j];
      if (s->kind == COLK_LIST) {
        if (DICT_SETITEM_KNOWNHASH(d, s->name, PyList_GET_ITEM(s->list, i),
                                    s->hash) < 0) {
          Py_DECREF(d);
          goto fail_out;
        }
      } else {
        PyObject *v;
        if (s->mask && s->mask[i]) {
          v = Py_None;
          Py_INCREF(v);
        } else if (s->kind == COLK_SLICES_ARR) {
          int64_t a = s->off[i], b = s->off[i + 1];
          v = PyList_New((Py_ssize_t)(b - a));
          if (v == NULL) {
            Py_DECREF(d);
            goto fail_out;
          }
          for (int64_t e = a; e < b; e++) {
            PyObject *cell = arr_cell(s, e);
            if (cell == NULL) {
              Py_DECREF(v);
              Py_DECREF(d);
              goto fail_out;
            }
            PyList_SET_ITEM(v, (Py_ssize_t)(e - a), cell);
          }
        } else {
          v = PyList_GetSlice(s->list, (Py_ssize_t)s->off[i],
                              (Py_ssize_t)s->off[i + 1]);
          if (v == NULL) {
            Py_DECREF(d);
            goto fail_out;
          }
        }
        int rc = DICT_SETITEM_KNOWNHASH(d, s->name, v, s->hash);
        Py_DECREF(v);
        if (rc < 0) {
          Py_DECREF(d);
          goto fail_out;
        }
      }
    }
    PyList_SET_ITEM(out, i, d);
  }
  for (Py_ssize_t j = 0; j < parsed; j++)
    if (cs[j].kind != COLK_LIST) {
      PyBuffer_Release(&cs[j].ob);
      if (cs[j].has_mb) PyBuffer_Release(&cs[j].mb);
      if (cs[j].has_eb) PyBuffer_Release(&cs[j].eb);
    }
  return out;
fail_out:
  Py_DECREF(out);
  out = NULL;
fail:
  for (Py_ssize_t j = 0; j < parsed; j++)
    if (cs[j].kind != COLK_LIST) {
      PyBuffer_Release(&cs[j].ob);
      if (cs[j].has_mb) PyBuffer_Release(&cs[j].mb);
      if (cs[j].has_eb) PyBuffer_Release(&cs[j].eb);
    }
  return out;
}

static PyMethodDef methods[] = {
    {"chunk_prepare", chunk_prepare, METH_VARARGS,
     "chunk_prepare(src, ints..., buffers..., stage_ns|None) -> rc; the "
     "fused GIL-free whole-chunk prepare walk"},
    {"chunk_encode", chunk_encode, METH_VARARGS,
     "chunk_encode(route, values, buffers..., stage_ns|None, err) -> rc; the "
     "fused GIL-free whole-chunk encode walk"},
    {"dict_indices_str", dict_indices_str, METH_VARARGS,
     "dict_indices_str(seq, max_uniques) -> (uniques, u32le_indices, "
     "total_utf8, uniq_utf8) | None | False"},
    {"encode_items", encode_items, METH_O,
     "encode_items(seq) -> (flat_bytes, int64le_lengths_bytes)"},
    {"dict_indices", dict_indices, METH_VARARGS,
     "dict_indices(seq, max_uniques) -> (uniques, u32le_indices_bytes) | None"},
    {"rows_from_slices", rows_from_slices, METH_VARARGS,
     "rows_from_slices(elems, offsets_i64, null_mask|None) -> list of slices"},
    {"dict_rows", dict_rows, METH_VARARGS,
     "dict_rows(names_tuple, columns_tuple) -> list of dicts"},
    {"take_bytes", take_bytes, METH_VARARGS,
     "take_bytes(data, offsets_i64, indices_i64) -> (new_offsets_bytes, data_bytes)"},
    {NULL, NULL, 0, NULL}};

static struct PyModuleDef moduledef = {PyModuleDef_HEAD_INIT, "_native_ext",
                                       NULL, -1, methods};

PyMODINIT_FUNC PyInit__native_ext(void) { return PyModule_Create(&moduledef); }

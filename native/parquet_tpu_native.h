/* Single source of truth for the ptq_chunk_prepare C ABI.
 *
 * Included (inside extern "C") by BOTH parquet_tpu_native.cc and pyext.c so
 * the 33-argument prototype cannot drift between translation units — C does
 * no cross-TU type checking, and a silently-misaligned call here would be
 * heap corruption, not a compile error. The ctypes binding in
 * utils/native.py mirrors this signature; change all three together.
 */
#ifndef PARQUET_TPU_NATIVE_H
#define PARQUET_TPU_NATIVE_H

#include <stddef.h>
#include <stdint.h>
#include <sys/types.h> /* ssize_t */

#ifdef __cplusplus
extern "C" {
#endif

/* Negative return codes of ptq_chunk_prepare. -2/-3/-4 are retryable with
 * bigger tables; -1/-5/-6 abort the walk (err_info carries the detail). */
#define PTQ_E_CORRUPT (-1)    /* corrupt or unsupported input */
#define PTQ_E_PAGES_FULL (-2) /* page table full (retry larger) */
#define PTQ_E_RUNS_FULL (-3)  /* hybrid run table full (retry larger) */
#define PTQ_E_MINIS_FULL (-4) /* delta miniblock table full (retry larger) */
#define PTQ_E_CAPACITY (-5)   /* level/value capacity exceeded */
#define PTQ_E_CRC (-6)        /* stored page CRC mismatch (definite rot) */

/* err_info[0] stage codes: the walk phase that was active when it failed. */
#define PTQ_STAGE_NONE 0
#define PTQ_STAGE_HEADER 1     /* Thrift page-header parse / size checks */
#define PTQ_STAGE_CRC 2        /* stored-CRC verification */
#define PTQ_STAGE_DECOMPRESS 3 /* snappy/gzip/lz4 block decode */
#define PTQ_STAGE_LEVELS 4     /* R/D level hybrid decode */
#define PTQ_STAGE_PRESCAN 5    /* dict-run / delta-miniblock prescan */
#define PTQ_STAGE_VALUES 6     /* value-stream routing / copies */

ssize_t ptq_chunk_prepare(
    const uint8_t* src, size_t src_len, int codec, int validate_crc,
    int max_def, int max_rep, int type_size, int delta_nbits,
    int64_t expected_values, int64_t* pages, size_t max_pages,
    uint16_t* def_out, uint16_t* rep_out, uint8_t* values_out,
    size_t values_cap, uint8_t* packed_out, size_t packed_cap,
    uint8_t* delta_out, size_t delta_cap, uint8_t* scratch, size_t scratch_cap,
    uint8_t* h_is_rle, int64_t* h_counts, uint64_t* h_values,
    int64_t* h_byteoff, size_t max_runs, uint32_t* d_widths,
    int64_t* d_bytestart, int32_t* d_outstart, uint64_t* d_mins,
    size_t max_minis, int64_t* totals, int64_t* stage_ns,
    int64_t* err_info /* nullable [4]: stage, page index, page byte offset in
                         chunk, 0; meaningful only when the return is < 0 */);

#ifdef __cplusplus
}
#endif

#endif /* PARQUET_TPU_NATIVE_H */

/* Single source of truth for the ptq_chunk_prepare C ABI.
 *
 * Included (inside extern "C") by BOTH parquet_tpu_native.cc and pyext.c so
 * the 33-argument prototype cannot drift between translation units — C does
 * no cross-TU type checking, and a silently-misaligned call here would be
 * heap corruption, not a compile error. The ctypes binding in
 * utils/native.py mirrors this signature; change all three together.
 */
#ifndef PARQUET_TPU_NATIVE_H
#define PARQUET_TPU_NATIVE_H

#include <stddef.h>
#include <stdint.h>
#include <sys/types.h> /* ssize_t */

#ifdef __cplusplus
extern "C" {
#endif

/* Negative return codes of ptq_chunk_prepare. -2/-3/-4 are retryable with
 * bigger tables; -1/-5/-6 abort the walk (err_info carries the detail). */
#define PTQ_E_CORRUPT (-1)    /* corrupt or unsupported input */
#define PTQ_E_PAGES_FULL (-2) /* page table full (retry larger) */
#define PTQ_E_RUNS_FULL (-3)  /* hybrid run table full (retry larger) */
#define PTQ_E_MINIS_FULL (-4) /* delta miniblock table full (retry larger) */
#define PTQ_E_CAPACITY (-5)   /* level/value capacity exceeded */
#define PTQ_E_CRC (-6)        /* stored page CRC mismatch (definite rot) */

/* err_info[0] stage codes: the walk phase that was active when it failed. */
#define PTQ_STAGE_NONE 0
#define PTQ_STAGE_HEADER 1     /* Thrift page-header parse / size checks */
#define PTQ_STAGE_CRC 2        /* stored-CRC verification */
#define PTQ_STAGE_DECOMPRESS 3 /* snappy/gzip/lz4 block decode */
#define PTQ_STAGE_LEVELS 4     /* R/D level hybrid decode */
#define PTQ_STAGE_PRESCAN 5    /* dict-run / delta-miniblock prescan */
#define PTQ_STAGE_VALUES 6     /* value-stream routing / copies */

/* ptq_chunk_encode err_info[0] stage codes (the encode walk's phases). */
#define PTQ_ENC_STAGE_SPLIT 1    /* page-split arithmetic / input validation */
#define PTQ_ENC_STAGE_LEVELS 2   /* def-level hybrid pack */
#define PTQ_ENC_STAGE_VALUES 3   /* value-stream encode (plain/dict/delta) */
#define PTQ_ENC_STAGE_COMPRESS 4 /* page block compression */
#define PTQ_ENC_STAGE_FRAME 5    /* Thrift page-header framing / output copy */

/* Fused whole-chunk ENCODE walk: the write-side inverse of
 * ptq_chunk_prepare. One call splits a typed column chunk into pages,
 * packs levels, encodes the value stream (PLAIN numeric/byte-array,
 * RLE_DICTIONARY indices, DELTA_BINARY_PACKED), compresses
 * (UNCOMPRESSED/SNAPPY/GZIP) and frames compact-Thrift page headers —
 * byte-identical to the staged Python encoder in sink/encoder.py.
 *
 * route: 0 PLAIN fixed-width (values = contiguous elements of type_size
 *          bytes), 1 PLAIN byte-array (values = flat data, ba_offsets =
 *          int64[nv+1]), 2 RLE_DICTIONARY (values = uint32 indices,
 *          dict_raw = pre-encoded PLAIN dictionary payload framed as the
 *          leading dictionary page), 3 DELTA_BINARY_PACKED (values =
 *          int32/int64 by type_size), 4 BOOLEAN RLE (values = uint16 0/1,
 *          type_size 2; 4-byte-prefixed width-1 hybrid stream).
 * Returns the DATA page count (>= 0), or: -1 corrupt/unsupported input,
 * -2 page table full (retry larger), -5 out/scratch capacity exceeded
 * (retry larger or fall back). pages is int64[max_pages][8]:
 * {offset, framed size, header len, level entries, non-null count,
 *  raw (uncompressed block) size, 0, 0}. totals[8]: {bytes written,
 * uncompressed total (headers + raw), data page count, dict page offset
 * (-1 when absent), first data page offset, dict page framed size, 0, 0}.
 * stage_ns (nullable int64[5]): levels/values/compress/frame/crc wall ns. */
/* gzip compress with the fused encode walk's exact deflate parameters (the
 * startup byte-identity probe against CPython's zlib). Returns size or -1. */
ssize_t ptq_gzip_compress(const uint8_t* src, size_t src_len, uint8_t* dst,
                          size_t dst_cap);

ssize_t ptq_chunk_encode(
    int route, const uint8_t* values, size_t values_len,
    const int64_t* ba_offsets, int64_t nv, int type_size, int dict_width,
    const uint8_t* dict_raw, size_t dict_raw_len, int64_t dict_num,
    const uint16_t* def_levels, int64_t num_entries, int max_def, int codec,
    int dpv, int with_crc, int64_t per_page, uint8_t* out, size_t out_cap,
    uint8_t* scratch, size_t scratch_cap, int64_t* pages, size_t max_pages,
    int64_t* totals, int64_t* stage_ns, int64_t* err_info);

ssize_t ptq_chunk_prepare(
    const uint8_t* src, size_t src_len, int codec, int validate_crc,
    int max_def, int max_rep, int type_size, int delta_nbits,
    int64_t expected_values, int64_t* pages, size_t max_pages,
    uint16_t* def_out, uint16_t* rep_out, uint8_t* values_out,
    size_t values_cap, uint8_t* packed_out, size_t packed_cap,
    uint8_t* delta_out, size_t delta_cap, uint8_t* scratch, size_t scratch_cap,
    uint8_t* h_is_rle, int64_t* h_counts, uint64_t* h_values,
    int64_t* h_byteoff, size_t max_runs, uint32_t* d_widths,
    int64_t* d_bytestart, int32_t* d_outstart, uint64_t* d_mins,
    size_t max_minis, int64_t* totals, int64_t* stage_ns,
    int64_t* err_info /* nullable [4]: stage, page index, page byte offset in
                         chunk, 0; meaningful only when the return is < 0 */);

#ifdef __cplusplus
}
#endif

#endif /* PARQUET_TPU_NATIVE_H */

/* Single source of truth for the ptq_chunk_prepare C ABI.
 *
 * Included (inside extern "C") by BOTH parquet_tpu_native.cc and pyext.c so
 * the 31-argument prototype cannot drift between translation units — C does
 * no cross-TU type checking, and a silently-misaligned call here would be
 * heap corruption, not a compile error. The ctypes binding in
 * utils/native.py mirrors this signature; change all three together.
 */
#ifndef PARQUET_TPU_NATIVE_H
#define PARQUET_TPU_NATIVE_H

#include <stddef.h>
#include <stdint.h>
#include <sys/types.h> /* ssize_t */

#ifdef __cplusplus
extern "C" {
#endif

ssize_t ptq_chunk_prepare(
    const uint8_t* src, size_t src_len, int codec, int max_def, int max_rep,
    int type_size, int delta_nbits, int64_t expected_values, int64_t* pages,
    size_t max_pages, uint16_t* def_out, uint16_t* rep_out, uint8_t* values_out,
    size_t values_cap, uint8_t* packed_out, size_t packed_cap,
    uint8_t* delta_out, size_t delta_cap, uint8_t* scratch, size_t scratch_cap,
    uint8_t* h_is_rle, int64_t* h_counts, uint64_t* h_values,
    int64_t* h_byteoff, size_t max_runs, uint32_t* d_widths,
    int64_t* d_bytestart, int32_t* d_outstart, uint64_t* d_mins,
    size_t max_minis, int64_t* totals, int64_t* stage_ns);

#ifdef __cplusplus
}
#endif

#endif /* PARQUET_TPU_NATIVE_H */

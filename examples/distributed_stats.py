"""Sharded columnar scan: row groups decode straight onto mesh devices and
per-column statistics reduce with ICI/DCN collectives. In a multi-host
program each process only touches its own slice of the file
(process_row_groups); here the collective runs over the local devices."""

import sys as _sys
from pathlib import Path as _Path

_sys.path.insert(0, str(_Path(__file__).resolve().parent.parent))

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq

import jax

import parquet_tpu as ptq
from parquet_tpu.parallel.scan import column_stats, distributed_column_stats

path = "/tmp/example_dstats.parquet"
pq.write_table(
    pa.table(
        {
            "x": pa.array(np.arange(500_000, dtype=np.int64)),
            "f": pa.array(np.linspace(-1, 1, 500_000)),
        }
    ),
    path,
    row_group_size=50_000,
    use_dictionary=False,
)

with ptq.FileReader(path) as r:
    print("devices:", [d.platform for d in jax.local_devices()])
    print("mesh scan:", column_stats(r, jax.local_devices()))
    print("multi-host shape:", distributed_column_stats(r))

"""Streaming training over a multi-file dataset, with mid-epoch resume.

The production input-pipeline shape: a glob of Parquet shards streams
through ParquetDataset — footers planned once, units sharded and shuffled
per epoch, decode prefetched on background threads, rows rebatched to a
fixed shape, batches double-buffer-uploaded to the device — and the jitted
train step compiles once. Halfway through, the job "crashes": we snapshot
the iterator's state_dict, build a fresh dataset, resume, and verify the
resumed stream is byte-identical to the one the uninterrupted job saw.

Runs anywhere jax runs — on CPU it uses a virtual 8-device mesh:
    XLA_FLAGS=--xla_force_host_platform_device_count=8 \
    JAX_PLATFORMS=cpu python examples/streaming_train_loop.py
"""

import os
import tempfile

if "xla_force_host_platform_device_count" not in os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=8"
    ).strip()

import sys as _sys
from pathlib import Path as _Path

_sys.path.insert(0, str(_Path(__file__).resolve().parent.parent))

import jax
import jax.numpy as jnp
import numpy as np

from parquet_tpu import FileWriter, ParquetDataset, parse_schema

BATCH = 2048
SHARDS = 6
ROWS_PER_SHARD = 8192


def write_shards(d: str) -> str:
    """y = sign(1.5*x1 - 2*x2 + noise), split over SHARDS files."""
    rng = np.random.default_rng(0)
    schema = parse_schema("""
    message samples {
      required float x1;
      required float x2;
      required float label;
    }""")
    for i in range(SHARDS):
        x1 = rng.standard_normal(ROWS_PER_SHARD).astype(np.float32)
        x2 = rng.standard_normal(ROWS_PER_SHARD).astype(np.float32)
        y = (
            (1.5 * x1 - 2.0 * x2 + 0.1 * rng.standard_normal(ROWS_PER_SHARD)) > 0
        ).astype(np.float32)
        with FileWriter(
            os.path.join(d, f"shard-{i:03d}.parquet"), schema, codec="snappy"
        ) as w:
            w.write_column("x1", x1)
            w.write_column("x2", x2)
            w.write_column("label", y)
    return os.path.join(d, "shard-*.parquet")


def make_dataset(pattern: str, device) -> ParquetDataset:
    # In a multi-host job, shard="jax" stripes units over
    # (process_index, process_count); worker=(w, W) sub-shards per host.
    return ParquetDataset(
        pattern,
        batch_size=BATCH,
        shuffle=True,
        seed=42,
        num_epochs=2,
        prefetch=2,       # decode 2 units ahead on pqt-data threads
        device=device,    # double-buffered jax.device_put per batch
        on_error="skip",  # a corrupt shard degrades the epoch, not the job
    )


@jax.jit
def train_step(params, x, y):
    def loss_fn(p):
        logits = x @ p["w"] + p["b"]
        return jnp.mean(
            jnp.maximum(logits, 0) - logits * y
            + jnp.log1p(jnp.exp(-jnp.abs(logits)))
        )

    loss, grads = jax.value_and_grad(loss_fn)(params)
    return (
        jax.tree_util.tree_map(lambda v, g: v - 0.5 * g, params, grads),
        loss,
    )


def step_on(params, batch):
    x = jnp.stack([batch[("x1",)], batch[("x2",)]], axis=1)
    return train_step(params, x, batch[("label",)])


def main() -> None:
    pattern = write_shards(tempfile.mkdtemp())
    device = jax.devices()[0]
    params = {"w": jnp.zeros(2, jnp.float32), "b": jnp.zeros((), jnp.float32)}

    # -- the uninterrupted job, remembering every batch id after the cut ----
    ds = make_dataset(pattern, device)
    it = iter(ds)
    cut = 9
    first = last = None
    checkpoint = None
    seen_after_cut = []
    for step, batch in enumerate(it):
        params, loss = step_on(params, batch)
        if first is None:
            first = float(loss)
        last = float(loss)
        if step + 1 == cut:
            checkpoint = it.state_dict()  # covers batches 0..cut-1
        if checkpoint is not None and step + 1 > cut:
            seen_after_cut.append(np.asarray(batch[("x1",)]))
    print(
        f"trained {step + 1} steps over {SHARDS} shards: "
        f"loss {first:.4f} -> {last:.4f}"
    )
    assert last < first, "loss should decrease"

    # -- the "restarted" job: fresh dataset, resume from the checkpoint -----
    ds2 = make_dataset(pattern, device)
    resumed = [
        np.asarray(b[("x1",)]) for b in ds2.iterator(state=checkpoint)
    ]
    assert len(resumed) == len(seen_after_cut), (
        len(resumed), len(seen_after_cut),
    )
    for a, b in zip(seen_after_cut, resumed):
        assert np.array_equal(a, b)
    print(
        f"resume from step {cut} replayed {len(resumed)} remaining batches "
        "byte-identically (sharded + shuffled, mid-epoch)"
    )


if __name__ == "__main__":
    main()

"""TPU-backend columnar scan: decode on device, reduce on device.

The decoded arrays come back byte-identical to the host path; the decode
(hybrid RLE expansion, dictionary gather, delta cumsum) runs as batched XLA
programs on the accelerator.
"""

import sys as _sys
from pathlib import Path as _Path

_sys.path.insert(0, str(_Path(__file__).resolve().parent.parent))

import sys

import parquet_tpu as pq

path = sys.argv[1] if len(sys.argv) > 1 else "example.parquet"
with pq.FileReader(path) as r:
    for i in range(r.num_row_groups):
        for col_path, chunk in r.read_row_group(i).items():
            name = ".".join(col_path)
            if hasattr(chunk.values, "dtype"):
                print(f"rg{i} {name}: n={len(chunk.values)} dtype={chunk.values.dtype}")

"""TPU-backend columnar scan: decode on device, reduce on device.

The decoded arrays come back byte-identical to the host path; the decode
(hybrid RLE expansion, dictionary gather, delta cumsum) runs as batched XLA
programs on the accelerator.
"""

import sys

import parquet_tpu as pq

path = sys.argv[1] if len(sys.argv) > 1 else "example.parquet"
with pq.FileReader(path, backend="tpu") as r:
    for i in range(r.num_row_groups):
        for col_path, chunk in r.read_row_group(i).items():
            name = ".".join(col_path)
            if hasattr(chunk.values, "dtype"):
                print(f"rg{i} {name}: n={len(chunk.values)} dtype={chunk.values.dtype}")

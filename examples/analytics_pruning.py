"""The round-3 analytics stack on one file: page index + bloom filters +
selective page decode + device-batch filter pushdown.

Writes a 2M-row file with every pruning structure enabled, then shows each
layer at work:
  1. bloom filters prove an absent ID is in NO row group (min/max can't);
  2. the page index narrows a range predicate to row ranges;
  3. a filtered scan decodes ONLY the admitted pages (selective page decode);
  4. iter_device_batches(filters=...) skips excluded groups before any
     device upload.
(All beyond the reference, which writes chunk statistics and consumes none.)
"""

import datetime as dt
import sys as _sys
import time
from pathlib import Path as _Path

_sys.path.insert(0, str(_Path(__file__).resolve().parent.parent))

import numpy as np

import parquet_tpu as ptq

path = "/tmp/example_analytics.parquet"
n = 2_000_000
rng = np.random.default_rng(0)
schema = ptq.parse_schema(
    "message trips { required int64 trip_id; required int64 ts "
    "(TIMESTAMP_MICROS); required double fare; }"
)
GROUP = n // 8
with ptq.FileWriter(
    path,
    schema,
    codec="snappy",
    write_page_index=True,
    bloom_filters=["trip_id"],
    sorting_columns=["ts"],
    use_dictionary=False,
) as w:
    for base in range(0, n, GROUP):  # 8 row groups (columnar flush per group)
        w.write_column("trip_id", rng.integers(0, 1 << 40, GROUP))
        w.write_column(
            "ts",
            1_700_000_000_000_000 + np.arange(base, base + GROUP, dtype=np.int64),
        )
        w.write_column("fare", rng.uniform(2, 80, GROUP))
        w.flush_row_group()

with ptq.FileReader(path) as r:
    # 1. bloom: equality on a value inside every [min, max] but never written
    ghost = (1 << 41) + 7
    print("groups admitting ghost trip_id:", r.prune_row_groups([("trip_id", "==", ghost)]))

    # 2. page index: a time band maps to row ranges, not whole groups
    # (filters use the ergonomic domain iter_rows yields: datetimes)
    cutoff = dt.datetime.fromtimestamp(
        (1_700_000_000_000_000 + n - 5_000) / 1e6, tz=dt.timezone.utc
    ).replace(tzinfo=None)
    band = [("ts", ">=", cutoff)]
    print("admitted row ranges:", r.prune_pages(r.num_row_groups - 1, band))

    # 3. selective page decode: only admitted pages are read + decompressed
    t0 = time.perf_counter()
    rows = list(r.iter_rows(filters=band))
    print(f"filtered scan: {len(rows)} rows in {(time.perf_counter()-t0)*1e3:.0f} ms")

    # 4. device batches with pushdown: excluded groups never touch the chip
    batches = 0
    for batch in r.iter_device_batches(65_536, filters=band, drop_remainder=False):
        batches += 1
    print(f"device batches after pushdown: {batches}")

"""High-level dataclass mapping (the analogue of the reference's floor examples)."""

import sys as _sys
from pathlib import Path as _Path

_sys.path.insert(0, str(_Path(__file__).resolve().parent.parent))

import datetime as dt
from dataclasses import dataclass
from typing import Optional

from parquet_tpu import floor


@dataclass
class Trip:
    id: int
    vendor: Optional[str]
    ts: dt.datetime
    tags: list[str]


with floor.Writer("trips.parquet", Trip, codec="snappy") as w:
    w.write(Trip(1, "CMT", dt.datetime.now(dt.timezone.utc), ["fast"]))
    w.write(Trip(2, None, dt.datetime.now(dt.timezone.utc), []))

for trip in floor.Reader("trips.parquet", Trip):
    print(trip)

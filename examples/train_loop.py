"""TPU-native training loop: parquet -> device batches -> jitted SGD.

The consumption pattern the decode pipeline is built for: encoded pages
ship to the device, decode into HBM, and every fixed-shape batch feeds a
jit-compiled train step WITHOUT the decoded values ever visiting host
memory. Sharding spreads each batch over a device mesh (data parallel
here; any jax.sharding works).

Runs anywhere jax runs — on CPU it uses a virtual 8-device mesh:
    XLA_FLAGS=--xla_force_host_platform_device_count=8 \
    JAX_PLATFORMS=cpu python examples/train_loop.py
"""

import os
import tempfile

if "xla_force_host_platform_device_count" not in os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=8"
    ).strip()

import sys as _sys
from pathlib import Path as _Path

_sys.path.insert(0, str(_Path(__file__).resolve().parent.parent))

import jax
import jax.numpy as jnp
import numpy as np

from parquet_tpu import FileReader, FileWriter, parse_schema

BATCH = 4096
STEPS_PER_EPOCH = 16


def make_dataset(path: str, rows: int = BATCH * STEPS_PER_EPOCH) -> None:
    """A linearly-separable-ish dataset: y = x @ w_true + noise > 0."""
    rng = np.random.default_rng(0)
    x1 = rng.standard_normal(rows).astype(np.float32)
    x2 = rng.standard_normal(rows).astype(np.float32)
    y = (1.5 * x1 - 2.0 * x2 + 0.1 * rng.standard_normal(rows)) > 0
    schema = parse_schema("""
    message samples {
      required float x1;
      required float x2;
      required boolean label;
    }""")
    with FileWriter(path, schema, codec="snappy") as w:
        w.write_column("x1", x1)
        w.write_column("x2", x2)
        w.write_column("label", y)


def main() -> None:
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    path = os.path.join(tempfile.mkdtemp(), "train.parquet")
    make_dataset(path)

    devices = np.array(jax.devices())
    mesh = Mesh(devices, ("data",))
    batch_sharding = NamedSharding(mesh, P("data"))  # rows over the mesh
    replicated = NamedSharding(mesh, P())

    @jax.jit
    def train_step(params, x, y):
        def loss_fn(p):
            logits = x @ p["w"] + p["b"]
            return jnp.mean(
                jnp.maximum(logits, 0) - logits * y + jnp.log1p(jnp.exp(-jnp.abs(logits)))
            )

        loss, grads = jax.value_and_grad(loss_fn)(params)
        new_params = jax.tree_util.tree_map(lambda v, g: v - 0.5 * g, params, grads)
        return new_params, loss

    params = jax.device_put(
        {"w": jnp.zeros(2, jnp.float32), "b": jnp.zeros((), jnp.float32)},
        replicated,
    )

    first = last = None
    for epoch in range(3):
        with FileReader(path) as r:
            # decoded batches land in HBM already sharded over the mesh;
            # the jitted step compiles ONCE (static batch shape)
            for batch in r.iter_device_batches(BATCH, sharding=batch_sharding):
                x = jnp.stack(
                    [batch[("x1",)], batch[("x2",)]], axis=1
                )
                y = batch[("label",)].astype(jnp.float32)
                params, loss = train_step(params, x, y)
                if first is None:
                    first = float(loss)
                last = float(loss)
        print(f"epoch {epoch}: loss {last:.4f}  w={np.asarray(params['w']).round(3)}")
    assert last < first, "loss should decrease"
    w = np.asarray(params["w"])
    assert w[0] > 0 > w[1], "learned signs should match the generator"
    print("learned w matches the generating weights' signs — training works")


if __name__ == "__main__":
    main()

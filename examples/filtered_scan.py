"""Statistics-driven filtered scan: row groups provably outside the
predicate never load or decode; surviving rows are checked exactly.
(The reference writes chunk statistics but never consumes them on read.)"""

import sys as _sys
from pathlib import Path as _Path

_sys.path.insert(0, str(_Path(__file__).resolve().parent.parent))

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq

import parquet_tpu as ptq

path = "/tmp/example_filtered.parquet"
pq.write_table(
    pa.table(
        {
            "ts": pa.array(np.arange(1_000_000, dtype=np.int64)),
            "fare": pa.array(np.random.default_rng(0).uniform(2, 80, 1_000_000)),
        }
    ),
    path,
    row_group_size=100_000,
)

with ptq.FileReader(path) as r:
    keep = r.prune_row_groups([("ts", ">=", 850_000)])
    print(f"row groups: {r.num_row_groups}, surviving pruning: {keep}")
    n = 0
    total = 0.0
    for row in r.iter_rows(filters=[("ts", ">=", 850_000), ("fare", ">", 75.0)]):
        n += 1
        total += row["fare"]
    print(f"{n} matching rows, mean fare {total / max(n, 1):.2f}")

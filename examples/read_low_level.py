"""Low-level columnar read (the analogue of the reference's
examples/read-low-level): open a file, walk row groups, get typed arrays."""

import sys as _sys
from pathlib import Path as _Path

_sys.path.insert(0, str(_Path(__file__).resolve().parent.parent))

import sys

import parquet_tpu as pq

path = sys.argv[1] if len(sys.argv) > 1 else "example.parquet"
with pq.FileReader(path) as r:  # read_row_group_device() for device decode
    print(f"{r.num_rows} rows, {r.num_row_groups} row groups")
    for i in range(r.num_row_groups):
        chunks = r.read_row_group(i)
        for col_path, chunk in chunks.items():
            print(f"  rg{i} {'.'.join(col_path)}: {len(chunk.values)} values, "
                  f"{type(chunk.values).__name__}")

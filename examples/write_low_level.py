"""Low-level write with the schema DSL (the analogue of the reference's
examples/write-low-level)."""

import sys as _sys
from pathlib import Path as _Path

_sys.path.insert(0, str(_Path(__file__).resolve().parent.parent))

import parquet_tpu as pq

schema = pq.parse_schema("""
message example {
  required int64 id;
  optional binary name (STRING);
  optional group scores (LIST) {
    repeated group list {
      optional double element;
    }
  }
}
""")

with pq.FileWriter("example.parquet", schema, codec="snappy") as w:
    w.write_row({"id": 1, "name": "alice", "scores": [9.5, 8.0]})
    w.write_row({"id": 2, "name": None, "scores": []})
print("wrote example.parquet")

"""Benchmark: rows/sec decoded on the TPU backend vs the host baseline.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "rows/s", "vs_baseline": N}

Shape follows the north star (BASELINE.json): a NYC-taxi-like file with an
int64 id column and a dictionary-encoded string column (plus a delta-encoded
int64 timestamp column), decoded columnar (no row assembly) with
FileReader(backend="tpu") on the real chip. Decoded output is verified
byte-identical to the host path before timing counts.

vs_baseline: the Go reference cannot run in this image (no Go toolchain;
BASELINE.md notes the reference publishes no numbers), so the baseline is this
framework's own vectorized host (NumPy) decode path — the stand-in for the
"pure host decode" the north star compares against. Details go to stderr; the
JSON line stays one line.

Env knobs: PQT_BENCH_ROWS (default 2_000_000), PQT_BENCH_REPEATS (default 3).
"""

from __future__ import annotations

import json
import os
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent))

import numpy as np

ROWS = int(os.environ.get("PQT_BENCH_ROWS", 2_000_000))
REPEATS = int(os.environ.get("PQT_BENCH_REPEATS", 3))
CACHE = Path(f"/tmp/pqt_bench_{ROWS}.parquet")


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def build_file() -> Path:
    if CACHE.exists():
        return CACHE
    import pyarrow as pa
    import pyarrow.parquet as pq

    log(f"bench: generating {ROWS:,}-row taxi-like file at {CACHE}")
    rng = np.random.default_rng(42)
    vendors = np.array([f"vendor_{i:03d}" for i in range(200)])
    t = pa.table(
        {
            "trip_id": pa.array(np.arange(ROWS, dtype=np.int64)),
            "vendor": pa.array(vendors[rng.integers(0, len(vendors), ROWS)]),
            "ts": pa.array(
                (1_600_000_000_000_000 + np.cumsum(rng.integers(0, 1000, ROWS))).astype(
                    np.int64
                )
            ),
        }
    )
    pq.write_table(
        t,
        CACHE,
        compression="snappy",
        row_group_size=1 << 20,
        use_dictionary=["vendor"],
        column_encoding={"trip_id": "PLAIN", "ts": "DELTA_BINARY_PACKED"},
    )
    log(f"bench: file size {CACHE.stat().st_size / 1e6:.1f} MB")
    return CACHE


def decode_all(path, backend: str):
    from parquet_tpu.core.reader import FileReader

    with FileReader(path, backend=backend) as r:
        out = [r.read_row_group(i) for i in range(r.num_row_groups)]
    return out


def verify_identical(host, tpu) -> None:
    from parquet_tpu.core.arrays import ByteArrayData

    for rg_h, rg_t in zip(host, tpu):
        assert rg_h.keys() == rg_t.keys()
        for path in rg_h:
            a, b = rg_h[path].values, rg_t[path].values
            if isinstance(a, ByteArrayData):
                assert isinstance(b, ByteArrayData)
                assert np.array_equal(a.offsets, b.offsets) and a.data == b.data, path
            else:
                av, bv = np.asarray(a), np.asarray(b)
                assert av.dtype == bv.dtype, (path, av.dtype, bv.dtype)
                assert np.array_equal(
                    av.view((np.uint8, av.dtype.itemsize)),
                    bv.view((np.uint8, bv.dtype.itemsize)),
                ), path
    log("bench: byte-identical host vs tpu ✓")


def timed(fn, repeats: int) -> float:
    best = float("inf")
    for i in range(repeats):
        t0 = time.perf_counter()
        fn()
        dt = time.perf_counter() - t0
        log(f"bench:   run {i + 1}/{repeats}: {dt:.3f}s ({ROWS / dt / 1e6:.2f} M rows/s)")
        best = min(best, dt)
    return best


def _device_ready(timeout_s: float = 240.0) -> bool:
    """Probe the accelerator with a tiny jit under a watchdog; the axon tunnel
    can wedge (observed), and a hung bench is worse than a host-only result."""
    import threading

    result = {}

    def probe():
        try:
            import jax
            import jax.numpy as jnp

            # jax.devices() itself initializes the backend and can wedge —
            # keep every backend-touching call inside the watchdogged thread.
            result["platform"] = jax.devices()[0].platform
            r = jax.jit(lambda x: x * 2)(jnp.arange(128, dtype=jnp.int32))
            r.block_until_ready()
            result["ok"] = True
        except Exception as e:  # pragma: no cover
            result["err"] = str(e)

    t = threading.Thread(target=probe, daemon=True)
    t.start()
    t.join(timeout_s)
    if result.get("ok"):
        return True
    log(f"bench: device probe failed ({result.get('err', 'timed out')})")
    return False


def main() -> None:
    path = build_file()
    if not _device_ready():
        log("bench: accelerator unavailable — reporting host path only")
        t_host = timed(lambda: decode_all(path, "host"), REPEATS)
        print(
            json.dumps(
                {
                    "metric": (
                        "rows/sec decoded, NYC-taxi-like file (int64 + dict-string "
                        "+ delta-ts cols), HOST fallback (accelerator unreachable)"
                    ),
                    "value": round(ROWS / t_host, 1),
                    "unit": "rows/s",
                    "vs_baseline": 1.0,
                }
            )
        )
        return

    # warmup (compile) + verification
    log("bench: warmup + parity check")
    host = decode_all(path, "host")
    tpu = decode_all(path, "tpu")
    verify_identical(host, tpu)
    del host, tpu

    log("bench: timing host baseline")
    t_host = timed(lambda: decode_all(path, "host"), REPEATS)
    log("bench: timing tpu backend")
    t_tpu = timed(lambda: decode_all(path, "tpu"), REPEATS)

    rate = ROWS / t_tpu
    vs = t_host / t_tpu
    log(
        f"bench: host {ROWS / t_host / 1e6:.2f} M rows/s | "
        f"tpu {rate / 1e6:.2f} M rows/s | speedup {vs:.2f}x"
    )
    print(
        json.dumps(
            {
                "metric": (
                    "rows/sec decoded, NYC-taxi-like file "
                    "(int64 + dict-string + delta-ts cols), TPU decode backend"
                ),
                "value": round(rate, 1),
                "unit": "rows/s",
                "vs_baseline": round(vs, 3),
            }
        )
    )


if __name__ == "__main__":
    main()

"""Benchmark: decoded columns delivered into TPU HBM — device decode vs host.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "rows/s", "vs_baseline": N}

The metric is the TPU-native delivery point (BASELINE.json north star, SURVEY
§7.1): a TPU framework's decode ends with typed column arrays resident in
device memory, ready for jitted compute — not host arrays. Two ways to get
there, on a NYC-taxi-like file (int64 id PLAIN, dict-encoded vendor string,
DELTA_BINARY_PACKED int64 timestamp; snappy; the north-star column mix):

  baseline   host-path decode (vectorized NumPy) + upload of the decoded
             columns to the device — what a JAX user does with any host
             parquet library.
  ours       FileReader.read_row_group_device(): encoded value streams are
             prescanned on host, shipped to the device *encoded* (dict
             indices at index width, packed deltas — several times smaller
             than the decoded output) and decoded by the batched XLA kernels
             in HBM. Decoded values never cross the host<->device link.

Both deliveries are verified logically identical (byte-level for numerics,
string-level for dictionary columns) before any timing run. The classic
decode-to-host rows/s comparison is also measured and logged to stderr.

vs_baseline: the Go reference cannot run in this image (no Go toolchain;
BASELINE.md notes the reference publishes no numbers), so the baseline is the
host-decode-plus-upload path above — the stand-in for "pure host decode" in
the north star, measured at the same delivery point.

Env knobs: PQT_BENCH_ROWS (default 2_000_000), PQT_BENCH_REPEATS (default 3),
PQT_BENCH_MATRIX=0 to skip the BASELINE.md 5-config matrix (on by default),
PQT_MATRIX_ROWS (default 1_000_000) rows per matrix config,
PQT_DATASET_ROWS / PQT_DATASET_FILES (default 2_000_000 over 8 files) and
PQT_DATASET_STEP_MS (default 2) for the `--dataset` loader benchmark,
PQT_BENCH_DATASET=0 to skip it in a full run. PQT_IO_ROWS (default 400_000)
and PQT_IO_LAT_MS (default 0.3) shape the `--io` io-layer sweep;
PQT_BENCH_IO=0 skips it in a full run.

`--assembly` benchmarks record assembly: the vectorized level-scan engine
(core/assembly_vec, the iter_rows default) vs the scalar cursor walk
(PQT_VEC_ASSEMBLY=0) vs pyarrow to_pylist, on flat / 1-level (the
BENCH_r02 cfg5 LIST<int32> shape) / 2-level nested tables. Vec and scalar
assemble the SAME pre-decoded chunks and the vec rows are asserted
identical to the scalar rows before timing. PQT_ASSEMBLY_ROWS (default
300_000) sizes the tables; PQT_BENCH_ASSEMBLY=0 skips it in a full run.
The result rides the --json artifact under "assembly".

`--io` benchmarks the io layer (parquet_tpu.io) against a latency-injected
FlakySource (every read pays a simulated range-GET latency plus a transient
EIO rate absorbed by the retry ladder): a coalesce-gap sweep (0 / 64 KiB /
1 MiB) over a gappy 4-of-8-column projection, then a readahead-depth sweep
(0/2/4 row groups prefetched into a shared block cache on the pqt-io pool).
The result rides the --json artifact under "io".

`--io-remote` benchmarks the REMOTE io stack (io.remote + io.tiercache +
io.autotune) over real loopback HTTP: testing.httpstub serves the fixture
at injected RTT 0/5/25 ms and a 4-of-8 projection scans through HttpSource
with fixed local knobs vs coalesce_gap="auto" (the latency-aware tuner),
plus a tiered RAM->disk cache whose warm re-scan is asserted to read ZERO
source bytes before timing. PQT_IO_REMOTE_ROWS (default 200_000) and
PQT_IO_REMOTE_REPEATS (default 3) size it; PQT_BENCH_IO_REMOTE=0 skips it
in a full run. The result rides the --json artifact under "io_remote".

`--io-write` benchmarks the remote WRITE path (io.remote_sink) over real
loopback HTTP: an IO_WRITE_MB payload streams through HttpSink's multipart
protocol into a writable testing.httpstub at injected RTT 0/5/25 ms,
sweeping the part size (2/4/8 MiB), with every committed object asserted
byte-identical to the payload before its time counts. PQT_IO_WRITE_MB
(default 32) and PQT_IO_WRITE_REPEATS (default 3) size it;
PQT_BENCH_IO_WRITE=0 skips it in a full run. The result rides the --json
artifact under "io_write".

`--write` benchmarks the write path: FileWriter vs pyarrow (snappy headline)
plus the pqt-encode PARALLELISM sweep — pool 1/4/8 x 8/16 row groups on a
GZIP log-ingest table (PQT_WRITE_ROWS rows, default 400K), every parallel
output asserted byte-identical to the serial file before timing. The result
rides the --json artifact under "write" (also as the matrix "write" config).

`--dataset` benchmarks the streaming loader (parquet_tpu.data) end to end
over a multi-file glob: rows/s through ParquetDataset at a sweep of prefetch
depths against a device-bound consumer (host blocked PQT_DATASET_STEP_MS per
batch, the shape of block_until_ready on an accelerator step), with the
wait-time share (consumer starvation) per depth — the overlap-is-real check
is depth>=2 beating depth 0, and `loader_rows_s` records the step-free pure
decode+rebatch rate. Host-only (jax forced to CPU); the result rides the
--json artifact under "dataset".

`--serve` benchmarks the scan/query daemon (parquet_tpu.serve) over real
HTTP against an in-process `ScanServer` on an ephemeral port: requests/s
and p50/p99 request latency at client concurrency 1/4/16 (each request a
full jsonl shard scan, round-robin over a PQT_SERVE_FILES-file corpus of
PQT_SERVE_ROWS total rows, PQT_SERVE_REQUESTS per level) against a WARM
daemon, plus the cold-vs-warm /v1/plan latency ratio the footer/block
caches buy. PQT_BENCH_SERVE=0 skips it in a full run; the result rides
the --json artifact under "serve".

`--serve-mesh` benchmarks the sharded-serve router (parquet_tpu.serve.mesh)
over REAL subprocess replica daemons: routed req/s at replica counts 1 and
4 under fixed client concurrency (the `mesh.rps_1r`/`mesh.rps_4r` trend
pins — read the scaling ratio against the fingerprint's nproc), every
routed response checked byte-identical against a direct replica answer,
plus a chaos leg that SIGKILLs one replica mid-hammer and pins typed
retries only (no torn streams, no untyped errors).
PQT_SERVE_MESH_REQUESTS / PQT_SERVE_MESH_CONC size it;
PQT_BENCH_SERVE_MESH=0 skips it in a full run; the result rides the
--json artifact under "mesh".

`--chaos` benchmarks graceful degradation under the scripted fault schedule
(testing/chaos.py: latency spike -> error burst -> blackout -> recovery,
driven through every source the process opens): the SLO-controlled dataset
pipeline vs the same pipeline uncontrolled (per-phase p50/p99 consumer
waits; the pin is p99 within the SLO in the steady spike phase WITH the
controller and over it WITHOUT), hedged-read win rate, the breakered vs
un-breakered time-to-error on a blacked-out source (pin: < 10%), and the
serve daemon under brownout (statuses, sheds, typed-responses-only pin).
PQT_CHAOS_ROWS / PQT_CHAOS_FILES / PQT_CHAOS_PHASE_S size it;
PQT_CHAOS_SMOKE=1 is the make-check-sized smoke; PQT_BENCH_CHAOS=0 skips
it in a full run. The result rides the --json artifact under "chaos".

`--ingest` benchmarks the data-lake write loop (parquet_tpu.lake):
sustained append rows/s into a sort-keyed table with every batch flushed
(each flush a real sort+encode+manifest generation), then the compaction
payoff — a sort-key point probe's pruned-unit ratio and filtered-scan
wall before vs after one compaction folds the overlapping ingest files
into clustered row groups. Tracked pins: ingest.append_rows_s,
ingest.pruned_ratio_gain, ingest.scan_speedup. PQT_INGEST_ROWS /
PQT_INGEST_BATCH size it; PQT_BENCH_INGEST=0 skips it in a full run.
The result rides the --json artifact under "ingest".

`--json out.json` (or PQT_BENCH_JSON=out.json) additionally writes the
final structured result — headline + per-stage prepare breakdown + matrix —
to a file, so the BENCH_* trajectory artifacts are produced by the harness
itself instead of by hand. Works in phase mode too
(`bench.py --phase prepare --json out.json` writes that phase's object).

`--compare old.json new.json [--threshold 0.10]` diffs two --json artifacts
section by section: every tracked metric (throughputs like rows_s/req_s and
the headline `value` are higher-better; latencies/walls like *_ms, p50_ms,
`t` are lower-better) prints a new/old ratio, and the run exits non-zero
when any tracked metric REGRESSES beyond the threshold (default 10%) — the
`make bench-compare OLD=... NEW=...` gate future PRs hold the BENCH_r0x
trajectory against. Untracked leaves (counts, depths, config echoes) are
reported as changed/unchanged but never gate; two artifacts with NO
tracked metric in common also exit non-zero (a gate that compared
nothing must not read as green). With ONE path, the old side defaults to
the LATEST round recorded in BENCH_history.jsonl — `bench.py --compare
/tmp/now.json` is the whole regression check.

`--record artifact.json [--label rNN] [--history PATH]` appends the
artifact to the persistent trend store BENCH_history.jsonl together with
its provenance (git rev, a fingerprint of the PQT_* config env, python/
platform, timestamp) — the per-PR trajectory record the BENCH_r0x files
used to be by hand. `--trend [--history PATH] [--section S]` renders
every tracked metric's value across the recorded rounds with the
last-vs-first ratio, newest round on the right; it also validates the
store's schema (a malformed entry exits non-zero), which is what the
`make check` trend smoke asserts.
"""

from __future__ import annotations

import json
import os
import re
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent))

import numpy as np

ROWS = int(os.environ.get("PQT_BENCH_ROWS", 2_000_000))
REPEATS = int(os.environ.get("PQT_BENCH_REPEATS", 5))
CACHE = Path(f"/tmp/pqt_bench_{ROWS}.parquet")

# `--json PATH` / PQT_BENCH_JSON: where to write the final structured result
_JSON_OUT = os.environ.get("PQT_BENCH_JSON")


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def _write_artifact(obj) -> None:
    """Write the structured result to the --json/PQT_BENCH_JSON path (no-op
    when unset). The artifact carries the config fingerprint of the env
    the benchmark ACTUALLY ran under, so a later `--record` from a
    different shell cannot stamp the wrong provenance (string leaves:
    invisible to the --compare gate)."""
    if _JSON_OUT:
        digest, basis = _config_fingerprint()
        obj = {**obj, "bench_config": {"fingerprint": digest, "basis": basis}}
        try:
            Path(_JSON_OUT).write_text(json.dumps(obj, indent=1) + "\n")
        except OSError as e:  # pragma: no cover
            log(f"bench: could not write {_JSON_OUT}: {e}")


def _emit(obj) -> None:
    """Print the result line (the machine-readable contract) and, when
    --json/PQT_BENCH_JSON is set, write the same object to that file."""
    print(json.dumps(obj))
    _write_artifact(obj)


def build_file() -> Path:
    if CACHE.exists():
        return CACHE
    import pyarrow as pa
    import pyarrow.parquet as pq

    log(f"bench: generating {ROWS:,}-row taxi-like file at {CACHE}")
    rng = np.random.default_rng(42)
    vendors = np.array([f"vendor_{i:03d}" for i in range(200)])
    t = pa.table(
        {
            "trip_id": pa.array(np.arange(ROWS, dtype=np.int64)),
            "vendor": pa.array(vendors[rng.integers(0, len(vendors), ROWS)]),
            "ts": pa.array(
                (1_600_000_000_000_000 + np.cumsum(rng.integers(0, 1000, ROWS))).astype(
                    np.int64
                )
            ),
        }
    )
    pq.write_table(
        t,
        CACHE,
        compression="snappy",
        row_group_size=1 << 20,
        use_dictionary=["vendor"],
        column_encoding={"trip_id": "PLAIN", "ts": "DELTA_BINARY_PACKED"},
    )
    log(f"bench: file size {CACHE.stat().st_size / 1e6:.1f} MB")
    return CACHE


# -- the two delivery paths ----------------------------------------------------


def deliver_baseline(path):
    """Host decode, then upload decoded columns — block until resident."""
    import jax
    import jax.numpy as jnp

    from parquet_tpu.core.arrays import ByteArrayData
    from parquet_tpu.core.reader import FileReader

    out = []
    with FileReader(path, backend="host") as r:
        for i in range(r.num_row_groups):
            for p, chunk in r.read_row_group(i).items():
                v = chunk.values
                if isinstance(v, ByteArrayData):
                    out.append(
                        (
                            p,
                            jnp.asarray(np.frombuffer(v.data, dtype=np.uint8)),
                            jnp.asarray(v.offsets),
                        )
                    )
                else:
                    arr = np.asarray(v)
                    if arr.dtype.kind == "f":
                        u = np.uint32 if arr.itemsize == 4 else np.uint64
                        out.append((p, jnp.asarray(arr.view(u))))
                    else:
                        out.append((p, jnp.asarray(arr)))
    jax.block_until_ready([a for item in out for a in item[1:]])
    return out


def deliver_device(path):
    """Encoded upload + device decode — block until resident."""
    import jax

    from parquet_tpu.core.reader import FileReader

    out = []
    arrays = []
    with FileReader(path) as r:
        for rg in r.read_row_groups_device():
            for p, dc in rg.items():
                out.append((p, dc))
                for a in (dc.values, dc.indices, dc.data, dc.offsets, dc.dict_data, dc.dict_offsets):
                    if a is not None:
                        arrays.append(a)
    jax.block_until_ready(arrays)
    return out


def deliver_pyarrow(path):
    """External-implementation baseline: pyarrow (Arrow C++) decodes, then
    the decoded Arrow buffers upload to the device — the strongest host
    decoder a JAX user could reach for today, at the same delivery point."""
    import jax
    import jax.numpy as jnp
    import pyarrow.parquet as pq_mod

    t = pq_mod.read_table(path)
    arrays = []
    for name in t.column_names:
        col = t.column(name).combine_chunks()
        for chunk in col.chunks if hasattr(col, "chunks") else [col]:
            for buf in chunk.buffers():
                if buf is not None and buf.size:
                    arrays.append(jnp.asarray(np.frombuffer(buf, dtype=np.uint8)))
    jax.block_until_ready(arrays)
    return arrays


def verify_deliveries(path) -> None:
    """Both paths must deliver the same logical columns."""
    from parquet_tpu.core.arrays import ByteArrayData
    from parquet_tpu.core.reader import FileReader

    with FileReader(path, backend="host") as r:
        host = [r.read_row_group(i) for i in range(r.num_row_groups)]
    with FileReader(path) as r:
        dev = [r.read_row_group_device(i) for i in range(r.num_row_groups)]
    for rg_h, rg_d in zip(host, dev):
        assert rg_h.keys() == rg_d.keys()
        for p in rg_h:
            h, d = rg_h[p], rg_d[p]
            if d.indices is not None:
                got = d.dictionary.take(np.asarray(d.indices).astype(np.int64))
                assert isinstance(h.values, ByteArrayData)
                assert np.array_equal(got.offsets, h.values.offsets), p
                assert got.data == h.values.data, p
            elif d.offsets is not None:
                assert isinstance(h.values, ByteArrayData)
                assert np.array_equal(np.asarray(d.offsets), h.values.offsets), p
                assert bytes(np.asarray(d.data)) == h.values.data, p
            else:
                got = np.asarray(d.values)
                want = np.asarray(h.values)
                assert got.dtype == want.dtype, (p, got.dtype, want.dtype)
                assert np.array_equal(
                    got.view((np.uint8, got.dtype.itemsize)),
                    want.view((np.uint8, want.dtype.itemsize)),
                ), p
    log("bench: deliveries logically identical (host+upload vs device decode) ✓")


def decode_all_host(path):
    from parquet_tpu.core.reader import FileReader

    with FileReader(path, backend="host") as r:
        return [r.read_row_group(i) for i in range(r.num_row_groups)]


def decode_all_tpu_to_host(path):
    """Explicit device decode + fetch-back (backend="tpu" itself auto-routes
    host-bound reads to the host path; the roundtrip backend is the parity
    oracle and the honest measure of fetch-back cost)."""
    from parquet_tpu.core.reader import FileReader

    with FileReader(path, backend="tpu_roundtrip") as r:
        return [r.read_row_group(i) for i in range(r.num_row_groups)]


# -- the BASELINE.md 5-config matrix ------------------------------------------
#
# Per-config rows/s + bytes/s (encoded and decoded) + byte-equality, per the
# first-milestone deliverable table in BASELINE.md. Each config runs in its
# own subprocess (same isolation rationale as the phases below) and orders
# device timing BEFORE any device->host fetch so the verification fetch can't
# poison the measured transfer path.

MATRIX_ROWS = int(os.environ.get("PQT_MATRIX_ROWS", 1_000_000))


def _matrix_table(cfg: int, rows: int):
    import pyarrow as pa

    rng = np.random.default_rng(cfg)
    if cfg == 1:  # PLAIN int64, flat, uncompressed, DataPage V1
        return pa.table({"v": pa.array(rng.integers(0, 1 << 60, rows), pa.int64())})
    if cfg == 2:  # hybrid (dict-index) int32, SNAPPY, DataPage V2
        return pa.table({"v": pa.array(rng.integers(0, 1000, rows).astype(np.int32))})
    if cfg == 3:  # dict STRING, 100K-key dictionary
        keys = np.array([f"key_{i:06d}" for i in range(100_000)])
        return pa.table({"v": pa.array(keys[rng.integers(0, len(keys), rows)])})
    if cfg == 4:  # DELTA_BINARY_PACKED int64 timestamps, GZIP
        ts = 1_600_000_000_000_000 + np.cumsum(rng.integers(0, 1000, rows))
        return pa.table({"v": pa.array(ts.astype(np.int64))})
    if cfg == 5:  # nested LIST<int32> via the floor-equivalent reader
        lengths = rng.integers(0, 5, rows)
        flat = rng.integers(0, 1 << 30, int(lengths.sum())).astype(np.int32)
        offsets = np.zeros(rows + 1, dtype=np.int64)
        np.cumsum(lengths, out=offsets[1:])
        return pa.table(
            {"v": pa.ListArray.from_arrays(pa.array(offsets, pa.int32()), pa.array(flat))}
        )
    raise ValueError(cfg)


def _matrix_write_opts(cfg: int) -> dict:
    if cfg == 1:
        return dict(compression="none", column_encoding={"v": "PLAIN"}, use_dictionary=False, data_page_version="1.0")
    if cfg == 2:
        return dict(compression="snappy", use_dictionary=["v"], data_page_version="2.0")
    if cfg == 3:
        # raise pyarrow's 1MB dictionary-page ceiling: the config SPEC is a
        # dictionary-encoded column with 100K keys (~1.1MB of values), and
        # the default limit silently spills half the pages to PLAIN
        return dict(compression="snappy", use_dictionary=["v"], data_page_version="1.0",
                    dictionary_pagesize_limit=16 << 20)
    if cfg == 4:
        return dict(compression="gzip", column_encoding={"v": "DELTA_BINARY_PACKED"}, use_dictionary=False, data_page_version="1.0")
    return dict(compression="snappy", data_page_version="1.0")


def _matrix_file(cfg: int) -> Path:
    import hashlib

    import pyarrow.parquet as pq

    # cache key includes the write options so editing a config invalidates
    # the cached fixture instead of silently benchmarking the stale file
    tag = hashlib.sha1(repr(sorted(_matrix_write_opts(cfg).items())).encode()).hexdigest()[:10]
    path = Path(f"/tmp/pqt_matrix_{cfg}_{MATRIX_ROWS}_{tag}.parquet")
    if not path.exists():
        pq.write_table(
            _matrix_table(cfg, MATRIX_ROWS), path, row_group_size=1 << 20, **_matrix_write_opts(cfg)
        )
    return path


def _decoded_bytes(chunks_list) -> int:
    from parquet_tpu.core.arrays import ByteArrayData

    total = 0
    for chunks in chunks_list:
        for c in chunks.values():
            v = c.values
            if isinstance(v, ByteArrayData):
                total += len(v.data) + v.offsets.nbytes
            else:
                total += np.asarray(v).nbytes
    return total


def _phase_matrix(cfg: int) -> None:
    """One matrix config: device + baseline timings, then byte-equality.

    Timing reuses the headline delivery functions (deliver_device /
    deliver_baseline) so the matrix and headline measure the identical
    delivery point."""
    from parquet_tpu.core.reader import FileReader

    path = _matrix_file(cfg)
    rows = MATRIX_ROWS

    deliver_device(path)  # warm (compile cache + connection)
    s_dev = timed_stats(lambda: deliver_device(path), REPEATS, f"cfg{cfg} device", rows=rows)
    s_base = timed_stats(
        lambda: deliver_baseline(path), REPEATS, f"cfg{cfg} baseline", rows=rows
    )
    s_pa = timed_stats(
        lambda: deliver_pyarrow(path), REPEATS, f"cfg{cfg} pyarrow", rows=rows
    )
    t_dev, t_base, t_pa = s_dev["t"], s_base["t"], s_pa["t"]
    t_rows = None
    t_arrow = None
    if cfg == 5:
        # the floor-equivalent read: nested LIST assembly on host over the
        # decoded leaf (BASELINE.md config 5's mixed host/TPU shape)
        def assembled():
            with FileReader(path) as r:
                return sum(1 for _ in r.iter_rows())

        t_rows = timed(assembled, REPEATS, f"cfg{cfg} assembled-rows", rows=rows)

        # the columnar nested lane (vectorized Dremel-levels -> Arrow): the
        # product path for bulk nested reads; dict-row materialization above
        # is bounded by CPython object allocation (~200ns/row just for the
        # row dicts), this one is not
        def columnar():
            with FileReader(path) as r:
                return r.to_arrow().num_rows

        t_arrow = timed(columnar, REPEATS, f"cfg{cfg} to-arrow", rows=rows)

    # verification LAST (fetches poison the transfer path)
    with FileReader(path, backend="host") as r:
        host = [r.read_row_group(i) for i in range(r.num_row_groups)]
    with FileReader(path, backend="tpu_roundtrip") as r:
        rt = [r.read_row_group(i) for i in range(r.num_row_groups)]
    try:
        _verify_host_paths(host, rt)
        equal = True
    except AssertionError as e:
        log(f"bench: cfg{cfg} parity FAILED: {e}")
        equal = False
    enc = path.stat().st_size
    dec = _decoded_bytes(host)
    out = {
        "config": cfg,
        "rows_s_device": round(rows / t_dev, 1),
        "rows_s_baseline": round(rows / t_base, 1),
        "rows_s_pyarrow": round(rows / t_pa, 1),
        "vs_baseline": round(t_base / t_dev, 3),
        "vs_pyarrow": round(t_pa / t_dev, 3),
        "encoded_MB_s": round(enc / t_dev / 1e6, 1),
        "decoded_MB_s": round(dec / t_dev / 1e6, 1),
        "byte_equal": bool(equal),
        # medians over REPEATS samples; every sample recorded so the prose
        # can be audited against the artifact
        "stat": "median",
        "samples_device_s": s_dev["samples"],
        "samples_baseline_s": s_base["samples"],
        "samples_pyarrow_s": s_pa["samples"],
    }
    if t_rows is not None:
        out["rows_s_assembled"] = round(rows / t_rows, 1)
    if t_arrow is not None:
        out["rows_s_to_arrow"] = round(rows / t_arrow, 1)
    _emit(out)


WRITE_ROWS = int(os.environ.get("PQT_WRITE_ROWS", 400_000))


def _phase_write() -> None:
    """Write-path benchmark (matrix config "write"; `bench.py --write`).

    Part 1 (headline): rows/s writing the headline-like 3-column table
    (dict-int64 + dict-string + delta-ts) with our FileWriter vs
    pyarrow.write_table, both SNAPPY. Output is verified by reading it back
    with pyarrow (cross-implementation) before timing.

    Part 2 (parallelism sweep): the pqt-encode pipeline vs the serial
    writer on a log-ingest-shaped table (PQT_WRITE_ROWS rows: random int64
    id, ~90-byte log-line strings, delta timestamps, random doubles; GZIP,
    no dictionary — the archival-ingest shape where encode+compress
    dominate and the encode work is native/GIL-free). Sweeps pool size
    1/4/8 x row-group count 8/16; every parallel output is asserted
    BYTE-IDENTICAL to the serial file before any timing run. The result
    rides the --json artifact's "write" section."""
    import pyarrow as pa
    import pyarrow.parquet as pq

    from parquet_tpu.core.writer import FileWriter
    from parquet_tpu.schema.dsl import parse_schema

    rows = MATRIX_ROWS
    rng = np.random.default_rng(99)
    ints = rng.integers(0, 1000, rows).astype(np.int64)
    keys = np.array([f"key_{i:05d}" for i in range(5000)])
    strs = keys[rng.integers(0, len(keys), rows)]
    ts = (1_600_000_000_000_000 + np.cumsum(rng.integers(0, 1000, rows))).astype(
        np.int64
    )
    table = pa.table({"i": pa.array(ints), "s": pa.array(strs), "ts": pa.array(ts)})
    schema = parse_schema(
        "message m { required int64 i; required binary s (UTF8); "
        "required int64 ts (TIMESTAMP_MICROS); }"
    )
    strs_l = strs.tolist()

    def ours():
        with FileWriter(
            "/tmp/pqt_bench_write_ours.parquet",
            schema,
            codec="snappy",
            column_encodings={"ts": "DELTA_BINARY_PACKED"},
        ) as w:
            w.write_column("i", ints)
            w.write_column("s", strs_l)
            w.write_column("ts", ts)

    def ours_arrow():
        # same input class as pyarrow gets (arrow arrays, zero-copy ingest)
        with FileWriter(
            "/tmp/pqt_bench_write_ours_arrow.parquet",
            schema,
            codec="snappy",
            column_encodings={"ts": "DELTA_BINARY_PACKED"},
        ) as w:
            w.write_column("i", table.column("i"))
            w.write_column("s", table.column("s"))
            w.write_column("ts", table.column("ts"))

    # correctness FIRST: pyarrow must read our output back identically
    ours()
    ours_arrow()
    for f in (
        "/tmp/pqt_bench_write_ours.parquet",
        "/tmp/pqt_bench_write_ours_arrow.parquet",
    ):
        got = pq.read_table(f)
        assert got.column("i").to_pylist() == ints.tolist()
        assert got.column("s").to_pylist() == strs_l
        assert got.column("ts").cast(pa.int64()).to_pylist() == ts.tolist()
    log("bench: write output verified by pyarrow readback ✓")

    s_ours = timed_stats(ours, REPEATS, "write ours", rows=rows)
    s_ours_arrow = timed_stats(ours_arrow, REPEATS, "write ours(arrow-in)", rows=rows)
    s_pa = timed_stats(
        lambda: pq.write_table(
            table, "/tmp/pqt_bench_write_pa.parquet", compression="snappy"
        ),
        REPEATS,
        "write pyarrow",
        rows=rows,
    )
    t_ours, t_ours_arrow, t_pa = s_ours["t"], s_ours_arrow["t"], s_pa["t"]
    out = {
        "config": "write",
        "rows_s_ours": round(rows / t_ours, 1),
        "rows_s_ours_arrow_in": round(rows / t_ours_arrow, 1),
        "rows_s_pyarrow": round(rows / t_pa, 1),
        "vs_pyarrow": round(t_pa / t_ours, 3),
        "vs_pyarrow_arrow_in": round(t_pa / t_ours_arrow, 3),
        "written_MB": round(
            Path("/tmp/pqt_bench_write_ours.parquet").stat().st_size / 1e6, 1
        ),
        "readback_ok": True,
        "stat": "median",
        "samples_ours_s": s_ours["samples"],
        "samples_ours_arrow_in_s": s_ours_arrow["samples"],
        "samples_pyarrow_s": s_pa["samples"],
    }

    # -- part 2: the pqt-encode parallelism sweep ------------------------------
    wrows = WRITE_ROWS
    rng = np.random.default_rng(7)
    ids = rng.integers(0, 1 << 60, wrows).astype(np.int64)
    hexes = rng.integers(0, 1 << 40, wrows)
    logs = pa.array(
        [
            f"2026-08-03T12:00:00Z level=info svc=ingest "
            f"shard-{int(h) % 64:02d} req={int(h):012x} status=200"
            for h in hexes
        ]
    )
    wts = (
        1_600_000_000_000_000 + np.cumsum(rng.integers(0, 1000, wrows))
    ).astype(np.int64)
    wx = rng.random(wrows)
    wschema = parse_schema(
        "message m { required int64 id; required binary s (UTF8); "
        "required int64 ts (TIMESTAMP_MICROS); required double x; }"
    )

    def write_ingest(path, parallel, n_groups):
        with FileWriter(
            path,
            wschema,
            codec="gzip",
            column_encodings={"ts": "DELTA_BINARY_PACKED"},
            use_dictionary=False,
            parallel=parallel,
        ) as w:
            per = wrows // n_groups
            for g in range(n_groups):
                a = g * per
                b = wrows if g == n_groups - 1 else (g + 1) * per
                w.write_column("id", ids[a:b])
                w.write_column("s", logs.slice(a, b - a))
                w.write_column("ts", wts[a:b])
                w.write_column("x", wx[a:b])
                w.flush_row_group()

    # PAIRED sampling: every repeat times the serial writer and then each
    # pool config back to back, and the reported speedup is the MEDIAN OF
    # PAIRED RATIOS. On a shared box the load drift between runs dwarfs the
    # config effect (observed serial spread ~1.3x across minutes); pairing
    # puts both sides of each ratio in the same load window, the same
    # rationale that picked medians over best-of (VERDICT r3).
    pools = (1, 4, 8)
    sweep = {}
    best_speedup = 0.0
    for n_groups in (8, 16):
        ser_path = f"/tmp/pqt_write_serial_{n_groups}.parquet"
        write_ingest(ser_path, False, n_groups)  # warm + the identity oracle
        ser_bytes = Path(ser_path).read_bytes()
        for pool in pools:  # warm each pool config + the identity check
            par_path = f"/tmp/pqt_write_pool{pool}_{n_groups}.parquet"
            write_ingest(par_path, pool, n_groups)
            if Path(par_path).read_bytes() != ser_bytes:
                # a divergence is a correctness bug, not a data point:
                # timing divergent configs would launder it into the artifact
                raise SystemExit(
                    f"bench: write pool={pool} g={n_groups} output is NOT "
                    "byte-identical to the serial writer"
                )
        ser_samples = []
        par_samples = {p: [] for p in pools}
        ratios = {p: [] for p in pools}
        for rep in range(REPEATS):
            t0 = time.perf_counter()
            write_ingest(ser_path, False, n_groups)
            t_s = time.perf_counter() - t0
            ser_samples.append(round(t_s, 5))
            for pool in pools:
                par_path = f"/tmp/pqt_write_pool{pool}_{n_groups}.parquet"
                t0 = time.perf_counter()
                write_ingest(par_path, pool, n_groups)
                t_p = time.perf_counter() - t0
                par_samples[pool].append(round(t_p, 5))
                ratios[pool].append(t_s / t_p)
            log(
                f"bench:   write g={n_groups} rep {rep + 1}/{REPEATS}: "
                f"serial {t_s:.3f}s, " + ", ".join(
                    f"pool{p} {par_samples[p][-1]:.3f}s "
                    f"({ratios[p][-1]:.2f}x)" for p in pools
                )
            )
        med_ser = sorted(ser_samples)[len(ser_samples) // 2]
        entry = {
            "serial_rows_s": round(wrows / med_ser, 1),
            "serial_samples_s": ser_samples,
        }
        for pool in pools:
            med_par = sorted(par_samples[pool])[len(par_samples[pool]) // 2]
            r = sorted(ratios[pool])[len(ratios[pool]) // 2]
            entry[f"pool_{pool}"] = {
                "rows_s": round(wrows / med_par, 1),
                "speedup": round(r, 3),  # median of PAIRED ratios
                "samples_s": par_samples[pool],
            }
            if pool >= 4 and n_groups >= 8:
                best_speedup = max(best_speedup, round(r, 3))
        sweep[f"groups_{n_groups}"] = entry
    out["parallel_rows"] = wrows
    out["parallel_codec"] = "gzip"
    out["parallel_sweep"] = sweep
    # every config was asserted byte-identical above (divergence exits)
    out["parallel_byte_identical"] = True
    # the acceptance pin: best (pool >= 4, >= 8 groups) config vs serial
    out["parallel_speedup"] = best_speedup
    log(
        f"bench: write parallel sweep: best pool>=4 speedup "
        f"{best_speedup:.2f}x vs serial (all configs byte-identical)"
    )
    _emit(out)


def run_matrix() -> list:
    results = []
    for cfg in (1, 2, 3, 4, 5):
        _matrix_file(cfg)  # build outside the timed subprocess
        r = _run_phase(f"matrix{cfg}")
        if r is not None:
            log(f"bench: matrix config {cfg}: {json.dumps(r)}")
            results.append(r)
        else:
            log(f"bench: matrix config {cfg} FAILED")
    r = _run_phase("write")
    if r is not None:
        log(f"bench: matrix config write: {json.dumps(r)}")
        results.append(r)
    else:
        log("bench: write config FAILED")
    return results


def timed(fn, repeats: int, label: str, rows: int | None = None) -> float:
    """Median-of-repeats wall time (all samples logged; see timed_stats)."""
    return timed_stats(fn, repeats, label, rows)["t"]


def timed_stats(fn, repeats: int, label: str, rows: int | None = None) -> dict:
    """Run fn `repeats` times; report the MEDIAN with min/max and every
    sample. Medians, not best-of: the tunnel's run-to-run drift is the
    dominant noise here, and a best-of headline overstates what a user
    sees (VERDICT r3: single-run entries can't support prose claims)."""
    rows = ROWS if rows is None else rows
    samples = []
    for i in range(repeats):
        t0 = time.perf_counter()
        fn()
        dt = time.perf_counter() - t0
        log(f"bench:   {label} run {i + 1}/{repeats}: {dt:.3f}s ({rows / dt / 1e6:.2f} M rows/s)")
        samples.append(dt)
    s = sorted(samples)
    med = s[len(s) // 2] if len(s) % 2 else 0.5 * (s[len(s) // 2 - 1] + s[len(s) // 2])
    return {
        "t": med,
        "t_min": s[0],
        "t_max": s[-1],
        "samples": [round(x, 5) for x in samples],
    }


def _device_ready(timeout_s: float = 240.0) -> bool:
    """Probe the accelerator with a tiny jit under a watchdog; the axon tunnel
    can wedge (observed), and a hung bench is worse than a host-only result."""
    import threading

    result = {}

    def probe():
        try:
            import jax
            import jax.numpy as jnp

            # jax.devices() itself initializes the backend and can wedge —
            # keep every backend-touching call inside the watchdogged thread.
            result["platform"] = jax.devices()[0].platform
            r = jax.jit(lambda x: x * 2)(jnp.arange(128, dtype=jnp.int32))
            r.block_until_ready()
            result["ok"] = True
        except Exception as e:  # pragma: no cover
            result["err"] = str(e)

    t = threading.Thread(target=probe, daemon=True)
    t.start()
    t.join(timeout_s)
    if result.get("ok"):
        return True
    log(f"bench: device probe failed ({result.get('err', 'timed out')})")
    return False


# -- phase isolation -----------------------------------------------------------
#
# Every measurement phase runs in its OWN subprocess. Reason (measured, not
# theoretical): on the axon TPU backend, the first device->host DMA of a
# process permanently switches that process's transfer path into a slow
# synchronous mode (~30x slower uploads, async dispatch gone). Any phase that
# fetches results (verification, decode-to-host) would poison the timing of
# every phase after it. Process isolation gives each phase a fresh, fast
# connection; the persistent XLA compile cache (kernels/device_ops.py) makes
# the per-process compile cost a few seconds after the first-ever run.


def _phase_verify(path) -> None:
    verify_deliveries(path)
    host = decode_all_host(path)
    tpu = decode_all_tpu_to_host(path)
    _verify_host_paths(host, tpu)
    _emit({"ok": True})


def _phase_prepare() -> None:
    """Host-prepare microbench (`make bench-prepare`): the serial prepare wall
    named in BASELINE.md, split per stage by the fused native walk's internal
    clocks (decompress / levels / prescan / copy), plus thread scaling of the
    GIL-free path. Host-only — runs with or without an accelerator."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")  # never touch the tunnel here
    path = build_file()
    import concurrent.futures as cf

    from parquet_tpu.core.chunk import ChunkWindow, chunk_byte_range
    from parquet_tpu.core.reader import FileReader
    from parquet_tpu.kernels.pipeline import prepare_chunk_plan
    from parquet_tpu.utils.trace import decode_trace

    with FileReader(path) as r:
        rows = int(r.metadata.num_rows or 0)
        work = []
        for i in range(r.num_row_groups):
            for _p, cc, column in r._selected_chunks(i):
                off, total = chunk_byte_range(cc)
                work.append((r._pread(off, total), off, cc, column))

    def prep_one(item):
        buf, off, cc, column = item
        return prepare_chunk_plan(ChunkWindow(buf, off), cc, column)

    def prep_all():
        for it in work:
            prep_one(it)

    prep_all()  # warm: lazy imports, native load, per-thread buffer pools
    with decode_trace() as tr:
        t0 = time.perf_counter()
        prep_all()
        serial_probe = time.perf_counter() - t0
    stages = {
        name: round(s.seconds * 1e3, 3)
        for name, s in sorted(tr.stages.items())
        if name.startswith("prepare.")
    }
    engaged = tr.stages.get("prepare_fused_engaged")
    declined = tr.stages.get("prepare_fused_declined")
    serial = timed_stats(prep_all, REPEATS, "prepare-serial", rows)["t"]

    # thread scaling: the same chunk list split over N workers; the fused
    # walk holds no lock and no GIL, so wall should shrink ~linearly until
    # memory bandwidth saturates
    scaling = {}
    ncpu = os.cpu_count() or 1
    for nthreads in sorted({2, 4, min(8, ncpu), ncpu}):
        if nthreads < 2 or nthreads > ncpu:
            continue
        with cf.ThreadPoolExecutor(max_workers=nthreads) as pool:
            list(pool.map(prep_one, work))  # per-thread warmup (scratch pools)
            t0 = time.perf_counter()
            list(pool.map(prep_one, work))
            wall = time.perf_counter() - t0
        scaling[str(nthreads)] = {
            "t": round(wall, 5),
            "effective_cores": round(serial / wall, 2),
        }
    from parquet_tpu.utils.native import get_native

    nlib = get_native()
    out = {
        "rows": rows,
        # which binding ran: the extension (explicit Py_BEGIN_ALLOW_THREADS)
        # vs the ctypes fallback — scaling numbers are not comparable across
        # the two, so the artifact must say which produced them
        "gil_free_binding": bool(nlib is not None and nlib.fused_gil_free),
        "prepare_serial_s": round(serial, 5),
        "prepare_serial_probe_s": round(serial_probe, 5),
        "prepare_ms_per_1m_rows": round(serial / max(rows, 1) * 1e6 * 1e3, 3),
        "rows_s_prepare": round(rows / serial, 1),
        "stage_ms": stages,
        "fused_engaged": engaged.calls if engaged else 0,
        "fused_declined": declined.calls if declined else 0,
        "thread_scaling": scaling,
    }
    log(f"bench: prepare breakdown {out}")
    _emit(out)


# -- the record-assembly benchmark (--assembly / phase "assembly") -------------

ASSEMBLY_ROWS = int(os.environ.get("PQT_ASSEMBLY_ROWS", 300_000))


def _assembly_tables(rows: int) -> dict:
    """flat / 1-level / 2-level tables for the assembly-engine sweep. The
    1-level config reproduces the BENCH_r02 cfg5 shape (LIST<int32>, avg 2
    elements, empties) PLUS a null mask over ~1/16 of the rows, so the
    pre-timing vec==scalar identity assert also covers the null-list
    (slices-mask) path cfg5 itself never exercises."""
    import pyarrow as pa

    rng = np.random.default_rng(5)
    flat = pa.table(
        {
            "i": pa.array(rng.integers(0, 1 << 50, rows), pa.int64()),
            "f": pa.array(rng.standard_normal(rows)),
            "s": pa.array(
                [None if k % 11 == 0 else f"v{k % 97}" for k in range(rows)]
            ),
        }
    )
    lengths = rng.integers(0, 5, rows)
    null_rows = rng.integers(0, 16, rows) == 0
    lengths[null_rows] = 0
    flat_vals = rng.integers(0, 1 << 30, int(lengths.sum())).astype(np.int32)
    offsets = np.zeros(rows + 1, dtype=np.int64)
    np.cumsum(lengths, out=offsets[1:])
    lst = pa.table(
        {
            "v": pa.ListArray.from_arrays(
                pa.array(offsets, pa.int32()),
                pa.array(flat_vals),
                mask=pa.array(null_rows),
            )
        }
    )
    ll = pa.table(
        {
            "ll": pa.array(
                [
                    None
                    if i % 13 == 0
                    else [list(range(j % 3)) for j in range(i % 4)]
                    for i in range(rows)
                ],
                pa.list_(pa.list_(pa.int64())),
            )
        }
    )
    return {"flat": flat, "list1": lst, "list2": ll}


def _phase_assembly() -> None:
    """Record-assembly engine sweep: the vectorized level-scan engine
    (core/assembly_vec) vs the scalar cursor walk vs pyarrow to_pylist, on
    flat / 1-level / 2-level tables. Vec and scalar assemble from the SAME
    pre-decoded chunks (pure engine time, gc paused like the production
    reader's windows); pyarrow's to_pylist includes its own decode — it is
    the external "rows in Python" comparator, not an engine isolate. Vec
    output is asserted identical to scalar BEFORE any timing. The result
    rides the --json artifact under "assembly"."""
    import gc

    import pyarrow.parquet as pq

    from parquet_tpu.core.assembly import RecordAssembler
    from parquet_tpu.core.assembly_vec import assemble_rows
    from parquet_tpu.core.reader import FileReader

    rows = ASSEMBLY_ROWS
    scalar_repeats = max(1, REPEATS - 2)
    out = {"config": "assembly", "rows": rows, "tables": {}}
    for name, table in _assembly_tables(rows).items():
        path = Path(f"/tmp/pqt_assembly_{name}_{rows}.parquet")
        pq.write_table(table, path, row_group_size=1 << 20, compression="snappy")
        with FileReader(str(path)) as r:
            chunks = [r.read_row_group(i) for i in range(r.num_row_groups)]
            schema = r.schema

        def vec_all():
            gc.disable()
            try:
                return [assemble_rows(schema, c, False) for c in chunks]
            finally:
                gc.enable()

        def scalar_all():
            gc.disable()
            try:
                return [
                    list(RecordAssembler(schema, c, raw=False, engine="scalar"))
                    for c in chunks
                ]
            finally:
                gc.enable()

        # identity BEFORE timing: the engines must agree on every row
        v, s = vec_all(), scalar_all()
        assert all(g is not None for g in v), f"{name}: vec engine declined"
        assert v == s, f"{name}: vec rows differ from scalar rows"
        del v, s

        t_vec = timed(vec_all, REPEATS, f"assembly {name} vec", rows=rows)
        t_scl = timed(
            scalar_all, scalar_repeats, f"assembly {name} scalar", rows=rows
        )
        t_pa = timed(
            lambda: pq.read_table(path).to_pylist(),
            REPEATS,
            f"assembly {name} pyarrow",
            rows=rows,
        )
        out["tables"][name] = {
            "rows_s_vec": round(rows / t_vec, 1),
            "rows_s_scalar": round(rows / t_scl, 1),
            "rows_s_pyarrow": round(rows / t_pa, 1),
            "vs_scalar": round(t_scl / t_vec, 2),
            "vs_pyarrow": round(t_pa / t_vec, 2),
            "t_vec": round(t_vec, 4),
            "t_scalar": round(t_scl, 4),
            "t_pyarrow": round(t_pa, 4),
        }
        log(
            f"bench: assembly {name}: vec {rows / t_vec / 1e6:.2f} M rows/s | "
            f"scalar {rows / t_scl / 1e6:.3f} M rows/s | pyarrow "
            f"{rows / t_pa / 1e6:.2f} M rows/s | vec/scalar "
            f"{t_scl / t_vec:.1f}x | vec/pyarrow {t_pa / t_vec:.1f}x"
        )
    # the acceptance pin: >= 10x over the scalar engine on the cfg5-style
    # 1-level nested table
    out["nested_vec_vs_scalar"] = out["tables"]["list1"]["vs_scalar"]
    _emit(out)


# -- the IO-layer benchmark (--io / phase "io") --------------------------------

IO_ROWS = int(os.environ.get("PQT_IO_ROWS", 400_000))
IO_LAT_MS = float(os.environ.get("PQT_IO_LAT_MS", "0.3"))


def _io_file() -> Path:
    """An 8-column fixture for the io sweeps: wide enough that a projected
    read leaves real gaps between selected chunks (what coalescing has to
    decide about) and several row groups so readahead has a pipeline."""
    import pyarrow as pa
    import pyarrow.parquet as pq

    path = Path(f"/tmp/pqt_io_{IO_ROWS}.parquet")
    if not path.exists():
        rng = np.random.default_rng(11)
        log(f"bench: generating {IO_ROWS:,}-row 8-column io fixture at {path}")
        t = pa.table(
            {
                f"c{k}": pa.array(
                    rng.integers(0, 1 << 40, IO_ROWS).astype(np.int64)
                )
                for k in range(8)
            }
        )
        pq.write_table(
            t, path, compression="snappy", row_group_size=1 << 16,
            use_dictionary=False,
        )
    return path


def _phase_io() -> None:
    """IO-layer sweeps against a latency-injected flaky source.

    Models an object-store read: every source read pays PQT_IO_LAT_MS of
    injected latency (the range-GET shape) plus a small transient-EIO rate
    the retry ladder must absorb. Sweep 1 holds the projection fixed
    (4 of 8 columns — real gaps between selected chunks) and sweeps the
    coalesce gap 0 / 64 KiB / 1 MiB: wall time falls as read calls merge.
    Sweep 2 fixes the gap and sweeps readahead depth 0/2/4 row groups via
    the pqt-io scheduler fetching into a shared block cache ahead of
    decode. Host-only; the result rides the --json artifact as "io"."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    from parquet_tpu.core.reader import FileReader
    from parquet_tpu.io import (
        BlockCache,
        LocalFileSource,
        Readahead,
        RetryingSource,
        plan_ranges,
    )
    from parquet_tpu.testing.flaky import FlakySource
    from parquet_tpu.utils import metrics

    path = _io_file()
    cols = [f"c{k}" for k in range(0, 8, 2)]  # 4-of-8 projection: gappy
    lat_s = IO_LAT_MS / 1e3

    def flaky(seed=3):
        return RetryingSource(
            FlakySource(
                LocalFileSource(path), seed=seed, error_rate=0.02,
                latency_s=lat_s,
            ),
            attempts=5,
            base_delay_s=0.001,
            max_delay_s=0.01,
            seed=seed,
        )

    def read_all(gap, cache_bytes=0, readahead_depth=0):
        # a FRESH cache per run: a warm cache across repeats would measure
        # memory hits, not the readahead overlap under test
        cache = BlockCache(cache_bytes) if cache_bytes else None
        src = flaky()
        try:
            with FileReader(
                src, columns=cols, block_cache=cache, coalesce_gap=gap
            ) as r:
                ra = None
                ra_srcs = []
                if readahead_depth and cache is not None:
                    ra = Readahead(cache, gap=gap)
                    paths = {tuple(c.split(".")) for c in cols}
                    spans = [
                        plan_ranges(
                            r.metadata, row_groups=[g], columns=paths
                        )
                        for g in range(r.num_row_groups)
                    ]
                rows = 0
                scheduled = set()
                for g in range(r.num_row_groups):
                    if ra is not None:
                        for j in range(g + 1, min(g + 1 + readahead_depth,
                                                  r.num_row_groups)):
                            if j in scheduled:
                                continue
                            scheduled.add(j)
                            # one PRIVATE source per scheduled fetch: the
                            # seeded fault/latency rngs are not thread-safe,
                            # so sharing `src` with pqt-io workers would make
                            # the schedule racy and the sweep irreproducible
                            s2 = flaky(seed=100 + j)
                            ra_srcs.append(s2)
                            ra.schedule(s2, spans[j])
                    cols_g = r.read_row_group(g)
                    rows += next(iter(cols_g.values())).num_values
                if ra is not None:
                    ra.drain()
                for s2 in ra_srcs:
                    s2.close()
                return rows
        finally:
            src.close()

    out = {
        "config": "io",
        "rows": IO_ROWS,
        "file_mb": round(path.stat().st_size / 1e6, 2),
        "projection": cols,
        "latency_ms_per_read": IO_LAT_MS,
        "stat": "median",
    }
    gap_sweep = {}
    for gap in (0, 64 << 10, 1 << 20):
        s0 = metrics.snapshot()
        t = timed_stats(
            lambda g=gap: read_all(g), REPEATS, f"io gap={gap}", rows=IO_ROWS
        )
        d = metrics.delta(s0)
        gap_sweep[str(gap)] = {
            "t": t["t"],
            "rows_s": round(IO_ROWS / t["t"], 1),
            "read_calls": d.get("io_read_calls_total", 0) // REPEATS,
            "bytes_read": d.get("io_bytes_read_total", 0) // REPEATS,
            "retries": sum(
                v for k, v in d.items() if k.startswith("io_retries_total")
            ),
            "samples_s": t["samples"],
        }
    out["gap_sweep"] = gap_sweep
    ra_sweep = {}
    for depth in (0, 2, 4):
        s0 = metrics.snapshot()
        t = timed_stats(
            lambda d=depth: read_all(64 << 10, cache_bytes=256 << 20,
                                     readahead_depth=d),
            REPEATS, f"io readahead={depth}", rows=IO_ROWS,
        )
        d = metrics.delta(s0)
        hits = d.get("io_cache_hits_total", 0)
        misses = d.get("io_cache_misses_total", 0)
        ra_sweep[str(depth)] = {
            "t": t["t"],
            "rows_s": round(IO_ROWS / t["t"], 1),
            "cache_hit_rate": (
                round(hits / (hits + misses), 4) if hits + misses else None
            ),
            "samples_s": t["samples"],
        }
    out["readahead_sweep"] = ra_sweep
    best_gap = min(gap_sweep, key=lambda k: gap_sweep[k]["t"])
    out["best_gap"] = int(best_gap)
    out["gap_speedup"] = round(
        gap_sweep["0"]["t"] / gap_sweep[best_gap]["t"], 3
    )
    log(
        f"bench: io gap sweep best={best_gap} "
        f"({out['gap_speedup']:.2f}x over gap 0); readahead "
        + ", ".join(
            f"d{k}={v['rows_s'] / 1e6:.2f}M rows/s"
            for k, v in ra_sweep.items()
        )
    )
    _emit(out)


# -- the remote-IO benchmark (--io-remote / phase "io_remote") -----------------

IO_REMOTE_ROWS = int(os.environ.get("PQT_IO_REMOTE_ROWS", 200_000))
IO_REMOTE_RTTS_MS = (0.0, 5.0, 25.0)
IO_REMOTE_REPEATS = int(os.environ.get("PQT_IO_REMOTE_REPEATS", 3))


def _io_remote_file() -> Path:
    """A smaller-row-group variant of the io fixture for the remote sweep:
    ~128 KiB column chunks leave per-group gaps the auto-tuner's
    bandwidth-delay verdict has to decide about at every injected RTT."""
    import pyarrow as pa
    import pyarrow.parquet as pq

    path = Path(f"/tmp/pqt_io_remote_{IO_REMOTE_ROWS}.parquet")
    if not path.exists():
        rng = np.random.default_rng(13)
        log(
            f"bench: generating {IO_REMOTE_ROWS:,}-row 8-column remote "
            f"fixture at {path}"
        )
        t = pa.table(
            {
                f"c{k}": pa.array(
                    rng.integers(0, 1 << 40, IO_REMOTE_ROWS).astype(np.int64)
                )
                for k in range(8)
            }
        )
        pq.write_table(
            t, path, compression="snappy", row_group_size=1 << 14,
            use_dictionary=False,
        )
    return path


def _phase_io_remote() -> None:
    """Remote-latency profile sweep (`bench.py --io-remote` /
    `make bench-io-remote`).

    Serves the fixture through testing.httpstub (real loopback HTTP,
    range GETs on pooled connections) at injected RTT 0/5/25 ms and scans
    a 4-of-8 projection via io.remote.HttpSource three ways per RTT:

      fixed   the local-profile knobs (64 KiB coalesce gap) — what a
              reader naive about the transport pays
      auto    coalesce_gap="auto": the io.autotune profile observed from
              this sweep's own reads (reset per run) — the acceptance
              pin: auto beats fixed at the 25 ms RTT
      warm    a tiered (RAM->disk) cache filled by one cold auto scan,
              then re-scanned — asserted to read ZERO source bytes (the
              ROADMAP pin) before timing

    Host-only; the result rides the --json artifact as "io_remote"."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    from parquet_tpu.core.reader import FileReader
    from parquet_tpu.io import FooterCache, TieredCache, io_tuner
    from parquet_tpu.testing.httpstub import RangeHttpStub
    from parquet_tpu.utils import metrics

    path = _io_remote_file()
    data = path.read_bytes()
    cols = [f"c{k}" for k in range(0, 8, 2)]  # 4-of-8: gappy projection

    def scan(url, gap, fc=None, cache=None) -> int:
        with FileReader(
            url, columns=cols, footer_cache=fc, block_cache=cache,
            coalesce_gap=gap,
        ) as r:
            rows = 0
            for g in range(r.num_row_groups):
                rows += next(iter(r.read_row_group(g).values())).num_values
            assert rows == IO_REMOTE_ROWS
            return rows

    out = {
        "config": "io_remote",
        "rows": IO_REMOTE_ROWS,
        "file_mb": round(len(data) / 1e6, 2),
        "projection": cols,
        "stat": "median",
        "repeats": IO_REMOTE_REPEATS,
    }
    sweep = {}
    for rtt_ms in IO_REMOTE_RTTS_MS:
        with RangeHttpStub(
            files={"c.parquet": data}, latency_s=rtt_ms / 1e3
        ) as stub:
            url = stub.url_for("c.parquet")

            def run(gap):
                # a COLD tuner per SAMPLE (reset inside the timed fn):
                # "auto" must earn its knobs from each scan's own
                # observations, or samples 2..n would measure a
                # pre-trained tuner the comment's "cold" claim belies
                def one_cold_scan():
                    io_tuner().reset()
                    scan(url, gap)

                s0 = metrics.snapshot()
                t = timed_stats(
                    one_cold_scan, IO_REMOTE_REPEATS,
                    f"io-remote rtt={rtt_ms:g}ms gap={gap}",
                    rows=IO_REMOTE_ROWS,
                )
                d = metrics.delta(s0)
                return t, {
                    "t": t["t"],
                    "rows_s": round(IO_REMOTE_ROWS / t["t"], 1),
                    "http_requests": sum(
                        v for k, v in d.items()
                        if k.startswith("io_http_requests_total")
                    ) // IO_REMOTE_REPEATS,
                    "bytes_read": d.get("io_bytes_read_total", 0)
                    // IO_REMOTE_REPEATS,
                }

            _, fixed = run(None)
            _, auto = run("auto")
            # tiered warm: one cold fill, then the warm re-scan (zero
            # source bytes asserted BEFORE timing)
            io_tuner().reset()
            fc = FooterCache()
            with TieredCache(
                ram_bytes=32 << 20, disk_bytes=256 << 20
            ) as cache:
                scan(url, "auto", fc, cache)  # cold fill
                s0 = metrics.snapshot()
                scan(url, "auto", fc, cache)
                d0 = metrics.delta(s0)
                assert d0.get("io_bytes_read_total", 0) == 0, (
                    "warm tiered scan touched the source"
                )
                tw = timed_stats(
                    lambda: scan(url, "auto", fc, cache),
                    IO_REMOTE_REPEATS,
                    f"io-remote rtt={rtt_ms:g}ms warm-tiered",
                    rows=IO_REMOTE_ROWS,
                )
            sweep[f"{rtt_ms:g}"] = {
                "fixed": fixed,
                "auto": auto,
                "auto_speedup": round(fixed["t"] / auto["t"], 3),
                "warm_tiered": {
                    "t": tw["t"],
                    "rows_s": round(IO_REMOTE_ROWS / tw["t"], 1),
                    "zero_source_bytes": True,
                },
            }
    out["rtt_sweep"] = sweep
    hot = sweep[f"{IO_REMOTE_RTTS_MS[-1]:g}"]
    out["auto_speedup_at_max_rtt"] = hot["auto_speedup"]
    out["warm_vs_fixed_at_max_rtt"] = round(
        hot["fixed"]["t"] / hot["warm_tiered"]["t"], 3
    )
    log(
        "bench: io-remote @"
        + ", ".join(
            f"{k}ms auto {v['auto_speedup']:.2f}x fixed"
            f" ({v['fixed']['http_requests']}->{v['auto']['http_requests']}"
            " reqs)"
            for k, v in sweep.items()
        )
        + f"; warm tiered {out['warm_vs_fixed_at_max_rtt']:.1f}x fixed "
        f"at {IO_REMOTE_RTTS_MS[-1]:g}ms (zero source bytes)"
    )
    _emit(out)


# -- the remote-WRITE benchmark (--io-write / phase "io_write") ----------------

IO_WRITE_MB = int(os.environ.get("PQT_IO_WRITE_MB", 32))
IO_WRITE_RTTS_MS = (0.0, 5.0, 25.0)
IO_WRITE_PART_MB = (2, 4, 8)
IO_WRITE_REPEATS = int(os.environ.get("PQT_IO_WRITE_REPEATS", 3))


def _phase_io_write() -> None:
    """Remote write-throughput sweep (`bench.py --io-write` /
    `make bench-io-write`).

    Streams an IO_WRITE_MB payload through io.remote_sink.HttpSink into a
    WRITABLE testing.httpstub (real loopback HTTP, multipart initiate ->
    part PUTs -> complete) at injected RTT 0/5/25 ms, sweeping the
    multipart part size — the knob that trades request count (each part
    pays one RTT) against in-flight memory (part_bytes x max_in_flight).
    Every sample's committed object is asserted BYTE-IDENTICAL to the
    payload before its time counts: a fast write of wrong bytes is not a
    result. Host-only; rides the --json artifact as "io_write"."""
    from parquet_tpu.io.remote_sink import HttpSink
    from parquet_tpu.testing.httpstub import RangeHttpStub
    from parquet_tpu.utils import metrics

    data = (
        np.random.default_rng(23)
        .integers(0, 256, IO_WRITE_MB << 20, dtype=np.uint8)
        .tobytes()
    )
    chunk = 1 << 20  # writer-shaped: row groups arrive in ~MiB runs
    out = {
        "config": "io_write",
        "file_mb": IO_WRITE_MB,
        "stat": "median",
        "repeats": IO_WRITE_REPEATS,
        "part_mb_sweep": list(IO_WRITE_PART_MB),
    }
    sweep = {}
    for rtt_ms in IO_WRITE_RTTS_MS:
        with RangeHttpStub(
            writable=True, latency_s=rtt_ms / 1e3
        ) as stub:
            url = stub.url_for("bench.bin")
            per_part = {}
            for part_mb in IO_WRITE_PART_MB:

                def one_write():
                    with HttpSink(url, part_bytes=part_mb << 20) as s:
                        for i in range(0, len(data), chunk):
                            s.write(data[i : i + chunk])

                s0 = metrics.snapshot()
                t = timed_stats(
                    one_write,
                    IO_WRITE_REPEATS,
                    f"io-write rtt={rtt_ms:g}ms part={part_mb}MiB",
                    rows=IO_WRITE_MB,
                )
                d = metrics.delta(s0)
                assert stub.object_bytes("bench.bin") == data, (
                    "committed object differs from the written payload"
                )
                per_part[f"{part_mb}"] = {
                    "t": t["t"],
                    "mb_s": round(len(data) / 1e6 / t["t"], 1),
                    "put_requests": sum(
                        v
                        for k, v in d.items()
                        if k.startswith("io_put_requests_total")
                    )
                    // IO_WRITE_REPEATS,
                }
            best = max(per_part, key=lambda k: per_part[k]["mb_s"])
            sweep[f"{rtt_ms:g}"] = {
                "parts": per_part,
                "best_part_mb": int(best),
                "mb_s": per_part[best]["mb_s"],
            }
    out["rtt_sweep"] = sweep
    out["mb_s_at_max_rtt"] = sweep[f"{IO_WRITE_RTTS_MS[-1]:g}"]["mb_s"]
    log(
        "bench: io-write @"
        + ", ".join(
            f"{k}ms {v['mb_s']:.0f} MB/s (best part {v['best_part_mb']}MiB)"
            for k, v in sweep.items()
        )
        + "; every committed object verified byte-identical"
    )
    _emit(out)


# -- the scan-service benchmark (--serve / phase "serve") ----------------------

SERVE_ROWS = int(os.environ.get("PQT_SERVE_ROWS", 160_000))
SERVE_FILES = int(os.environ.get("PQT_SERVE_FILES", 8))
SERVE_REQUESTS = int(os.environ.get("PQT_SERVE_REQUESTS", 32))


def _serve_dir(
    rows: int | None = None, files: int | None = None, row_group: int = 1 << 14
) -> Path:
    """A cached multi-file corpus for the daemon: `rows` int64+float64
    rows over `files` files of `row_group`-row groups, so one request
    decodes a few units and concurrent requests spread across files."""
    import pyarrow as pa
    import pyarrow.parquet as pq

    rows = SERVE_ROWS if rows is None else rows
    files = SERVE_FILES if files is None else files
    d = Path(f"/tmp/pqt_serve_{rows}_{files}_{row_group}")
    if d.exists():
        return d
    d.mkdir(parents=True)
    rng = np.random.default_rng(17)
    per = rows // files
    log(f"bench: generating {files}x{per:,}-row serve corpus at {d}")
    for i in range(files):
        t = pa.table(
            {
                "id": pa.array(
                    np.arange(i * per, (i + 1) * per, dtype=np.int64)
                ),
                "v": pa.array(rng.standard_normal(per)),
            }
        )
        pq.write_table(
            t, str(d / f"shard-{i:03d}.parquet"),
            compression="snappy", row_group_size=row_group,
        )
    return d


def _phase_serve() -> None:
    """Scan-service benchmark (`bench.py --serve` / `make bench-serve`).

    Drives a real in-process daemon (parquet_tpu.serve, ephemeral port)
    over HTTP, the way clients will: requests/s and p50/p99 request
    latency at client concurrency 1/4/16 against a WARM daemon (each
    request a full jsonl scan of one shard, round-robin across the
    corpus), plus the cold-vs-warm /v1/plan latency ratio — the number
    the footer/block caches exist to move (a warm plan is pure in-memory
    metadata work; a cold one parses every footer). Host-only; the result
    rides the --json artifact as "serve"."""
    import http.client

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import threading

    from parquet_tpu.serve import ScanServer, ServeConfig

    d = _serve_dir()

    def one_request(host, port, body):
        t0 = time.perf_counter()
        conn = http.client.HTTPConnection(host, port, timeout=120)
        try:
            conn.request("POST", "/v1/scan", body=body)
            resp = conn.getresponse()
            payload = resp.read()
            assert resp.status == 200, payload[:200]
            return time.perf_counter() - t0, len(payload)
        finally:
            conn.close()

    def plan_latency(host, port):
        t0 = time.perf_counter()
        conn = http.client.HTTPConnection(host, port, timeout=120)
        try:
            conn.request("GET", "/v1/plan?paths=shard-*.parquet")
            resp = conn.getresponse()
            assert resp.status == 200, resp.read()[:200]
            resp.read()
            return time.perf_counter() - t0
        finally:
            conn.close()

    # cold plan: a FRESH daemon's first /v1/plan parses every footer; one
    # sample per daemon, so take a few daemons and keep the median
    cold = []
    for _ in range(3):
        with ScanServer(ServeConfig(port=0, root=str(d))) as srv:
            srv.start_background()
            cold.append(plan_latency(srv.host, srv.port))
    cold_ms = float(np.median(cold) * 1e3)

    out = {
        "config": "serve",
        "rows_per_file": SERVE_ROWS // SERVE_FILES,
        "files": SERVE_FILES,
        "requests_per_level": SERVE_REQUESTS,
        "stat": "median",
    }
    bodies = [
        json.dumps({"paths": f"shard-{i % SERVE_FILES:03d}.parquet"}).encode()
        for i in range(SERVE_REQUESTS)
    ]
    # caps above the sweep's widest concurrency: this measures throughput,
    # not admission control (tests pin the 429 behavior)
    with ScanServer(
        ServeConfig(
            port=0, root=str(d), cache_mb=256,
            max_inflight=64, tenant_concurrent=64,
        )
    ) as srv:
        srv.start_background()
        host, port = srv.host, srv.port
        warm = [plan_latency(host, port) for _ in range(20)][5:]
        warm_ms = float(np.median(warm) * 1e3)
        # warm the daemon's caches end to end before timing the sweep
        for i in range(SERVE_FILES):
            one_request(host, port, bodies[i])
        sweep = {}
        for conc in (1, 4, 16):
            lat: list = []
            lock = threading.Lock()
            idx = iter(range(SERVE_REQUESTS))

            def worker():
                while True:
                    with lock:
                        i = next(idx, None)
                    if i is None:
                        return
                    t, _n = one_request(host, port, bodies[i])
                    with lock:
                        lat.append(t)

            t0 = time.perf_counter()
            threads = [
                threading.Thread(target=worker) for _ in range(conc)
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            wall = time.perf_counter() - t0
            assert len(lat) == SERVE_REQUESTS
            sweep[str(conc)] = {
                "rps": round(SERVE_REQUESTS / wall, 2),
                "p50_ms": round(float(np.percentile(lat, 50)) * 1e3, 2),
                "p99_ms": round(float(np.percentile(lat, 99)) * 1e3, 2),
                "wall_s": round(wall, 4),
            }
            log(
                f"bench: serve conc={conc}: {sweep[str(conc)]['rps']} req/s, "
                f"p50 {sweep[str(conc)]['p50_ms']} ms, "
                f"p99 {sweep[str(conc)]['p99_ms']} ms"
            )
    out["concurrency_sweep"] = sweep
    # headline latency/throughput at the widest sweep level, hoisted to a
    # stable dotted path (serve.p99_ms / serve.rps) so the trend store and
    # the --compare gate track serve latency regressions like throughput —
    # independent of which concurrency levels the sweep happens to run
    top = sweep[max(sweep, key=int)]
    out["p99_ms"] = top["p99_ms"]
    out["p50_ms"] = top["p50_ms"]
    out["rps"] = top["rps"]
    out["plan_cold_ms"] = round(cold_ms, 3)
    out["plan_warm_ms"] = round(warm_ms, 3)
    out["plan_cold_vs_warm"] = round(cold_ms / warm_ms, 2) if warm_ms else None
    log(
        f"bench: serve plan cold {out['plan_cold_ms']} ms vs warm "
        f"{out['plan_warm_ms']} ms = {out['plan_cold_vs_warm']}x"
    )
    _emit(out)


# -- the mesh-router benchmark (--serve-mesh / phase "serve_mesh") -------------

SERVE_MESH_REQUESTS = int(os.environ.get("PQT_SERVE_MESH_REQUESTS", 32))
SERVE_MESH_CONC = int(os.environ.get("PQT_SERVE_MESH_CONC", 8))


def _phase_serve_mesh() -> None:
    """Mesh-router benchmark (`bench.py --serve-mesh` / `make
    bench-serve-mesh`).

    Spawns REAL replica daemons as subprocesses (each its own process =
    its own GIL, the deployment shape) plus an in-process MeshRouter, and
    measures routed req/s at replica counts 1 and 4 under a fixed client
    concurrency — rps_1r/rps_4r are the trend-store scaling pins (read
    them against the fingerprint's nproc: a 1-core box cannot scale).
    Then the chaos leg: the same hammer with one replica SIGKILLed
    mid-run — every response must be byte-identical or a typed error
    record, never torn; the router's mesh_retries_total counters report
    what the kill actually cost."""
    import http.client
    import re as _re
    import subprocess
    import threading

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    from parquet_tpu.serve.mesh import MeshConfig, MeshRouter

    d = _serve_dir()
    env = dict(os.environ, JAX_PLATFORMS="cpu")

    def spawn_replica():
        proc = subprocess.Popen(
            [
                sys.executable, "-m", "parquet_tpu.tools.parquet_tool",
                "serve", "--port", "0", "--root", str(d),
                "--cache-mb", "256", "--max-inflight", "64",
                "--tenant-concurrent", "64",
            ],
            stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
            text=True, env=env,
        )
        for line in proc.stdout:
            m = _re.search(r"listening on (http://\S+)", line)
            if m:
                return proc, m.group(1)
        raise SystemExit("bench: replica daemon never reported its port")

    def one_request(host, port, body):
        t0 = time.perf_counter()
        conn = http.client.HTTPConnection(host, port, timeout=120)
        try:
            conn.request("POST", "/v1/scan", body=body)
            resp = conn.getresponse()
            payload = resp.read()
            return time.perf_counter() - t0, resp.status, payload
        finally:
            conn.close()

    bodies = [
        json.dumps({"paths": f"shard-{i % SERVE_FILES:03d}.parquet"}).encode()
        for i in range(SERVE_MESH_REQUESTS)
    ]

    def hammer(host, port, on_result):
        lock = threading.Lock()
        idx = iter(range(SERVE_MESH_REQUESTS))

        def worker():
            while True:
                with lock:
                    i = next(idx, None)
                if i is None:
                    return
                try:
                    t, status, payload = one_request(host, port, bodies[i])
                except http.client.HTTPException as e:
                    with lock:
                        on_result(i, "torn", repr(e), None)
                    continue
                with lock:
                    on_result(i, "ok" if status == 200 else "error",
                              status, payload)

        t0 = time.perf_counter()
        threads = [
            threading.Thread(target=worker) for _ in range(SERVE_MESH_CONC)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        return time.perf_counter() - t0

    procs = []
    out = {
        "config": "serve_mesh",
        "requests_per_level": SERVE_MESH_REQUESTS,
        "concurrency": SERVE_MESH_CONC,
        "stat": "wall-clock req/s",
    }
    try:
        for _ in range(4):
            procs.append(spawn_replica())
        urls = [u for _p, u in procs]
        # reference payloads straight from a replica: the byte-identity
        # oracle every routed response is judged against
        rhost, rport = urls[0].split("//")[1].rsplit(":", 1)
        expect = {}
        for i, body in enumerate(bodies):
            _t, status, payload = one_request(rhost, int(rport), body)
            assert status == 200, payload[:200]
            expect[i] = payload
        for n_replicas in (1, 4):
            router = MeshRouter(
                MeshConfig(
                    port=0, replicas=tuple(urls[:n_replicas]),
                    max_inflight=64, tenant_concurrent=64,
                )
            ).start_background()
            try:
                # warm each file through the routed path before timing
                for i in range(SERVE_FILES):
                    one_request(router.host, router.port, bodies[i])
                lat, bad = [], []

                def on_result(i, kind, detail, payload):
                    if kind != "ok" or payload != expect[i]:
                        bad.append((i, kind, detail))

                wall = hammer(router.host, router.port, on_result)
                assert not bad, f"mesh bench: non-identical responses: {bad[:4]}"
                rps = round(SERVE_MESH_REQUESTS / wall, 2)
                out[f"rps_{n_replicas}r"] = rps
                log(f"bench: serve-mesh {n_replicas} replica(s): {rps} req/s")
            finally:
                router.close()
        out["scaling_ratio"] = (
            round(out["rps_4r"] / out["rps_1r"], 2) if out["rps_1r"] else None
        )
        # chaos leg: SIGKILL one replica mid-hammer; typed retries only
        router = MeshRouter(
            MeshConfig(
                port=0, replicas=tuple(urls),
                max_inflight=64, tenant_concurrent=64,
            )
        ).start_background()
        try:
            for i in range(SERVE_FILES):
                one_request(router.host, router.port, bodies[i])
            outcomes = {"ok": 0, "typed": 0, "untyped": 0, "torn": 0}
            killed = threading.Event()

            def on_chaos_result(i, kind, detail, payload):
                if outcomes["ok"] >= SERVE_MESH_REQUESTS // 4:
                    if not killed.is_set():
                        procs[2][0].kill()  # mid-hammer, requests in flight
                        killed.set()
                if kind == "ok" and payload == expect[i]:
                    outcomes["ok"] += 1
                elif kind == "torn":
                    outcomes["torn"] += 1
                elif kind == "error":
                    try:
                        json.loads(payload)["error"]["code"]
                        outcomes["typed"] += 1
                    except (ValueError, KeyError):
                        outcomes["untyped"] += 1
                else:
                    outcomes["untyped"] += 1

            hammer(router.host, router.port, on_chaos_result)
            if not killed.is_set():
                procs[2][0].kill()
            status, retries = 0, {}
            conn = http.client.HTTPConnection(
                router.host, router.port, timeout=30
            )
            try:
                conn.request("GET", "/metrics")
                resp = conn.getresponse()
                text = resp.read().decode()
            finally:
                conn.close()
            for m in _re.finditer(
                r'parquet_tpu_mesh_retries_total\{reason="([a-z0-9_]+)"\} (\d+)',
                text,
            ):
                retries[m.group(1)] = int(m.group(2))
            out["chaos"] = {
                "replica_killed": killed.is_set(),
                "responses": dict(outcomes),
                "typed_only": outcomes["untyped"] == 0
                and outcomes["torn"] == 0,
                "retries": retries,
            }
            log(
                f"bench: serve-mesh chaos: {outcomes}, retries {retries}, "
                f"typed_only={out['chaos']['typed_only']}"
            )
        finally:
            router.close()
    finally:
        for proc, _u in procs:
            proc.terminate()
        for proc, _u in procs:
            try:
                proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                proc.kill()
    log(
        f"bench: serve-mesh scaling {out['rps_1r']} -> {out['rps_4r']} req/s "
        f"(x{out['scaling_ratio']}, nproc={os.cpu_count()})"
    )
    _emit(out)


# -- the query push-down benchmark (--query / phase "query") ------------------

QUERY_ROWS = int(os.environ.get("PQT_QUERY_ROWS", 1_000_000))
QUERY_REQUESTS = int(os.environ.get("PQT_QUERY_REQUESTS", 24))


def _query_file() -> Path:
    """A cached 1M-row numeric file for the vec-vs-scalar residual-filter
    sweep (int64 id + float64 v, several row groups)."""
    import pyarrow as pa
    import pyarrow.parquet as pq

    p = Path(f"/tmp/pqt_query_{QUERY_ROWS}.parquet")
    if p.exists():
        return p
    rng = np.random.default_rng(23)
    t = pa.table(
        {
            "id": pa.array(np.arange(QUERY_ROWS, dtype=np.int64)),
            "v": pa.array(rng.standard_normal(QUERY_ROWS)),
        }
    )
    pq.write_table(t, str(p), compression="snappy", row_group_size=1 << 17)
    return p


def _phase_query() -> None:
    """Query push-down benchmark (`bench.py --query` / `make bench-query`).

    Two ceilings, measured head-on:
      * residual filtering: rows/s of a filtered iter_rows over a 1M-row
        numeric predicate, vectorized mask pipeline (core/filter_vec) vs
        the scalar row_matches walk (PQT_VEC_FILTER=0) — outputs asserted
        identical before timing;
      * the serialization plateau: req/s of a filtered AGGREGATE query
        (POST /v1/query — kilobyte bodies) vs the row-streaming jsonl scan
        of the same predicate (POST /v1/scan) against a warm daemon.
    Host-only; the result rides the --json artifact as "query"."""
    import http.client

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    from parquet_tpu.core.reader import FileReader
    from parquet_tpu.serve import ScanServer, ServeConfig

    out = {"config": "query", "stat": "median"}

    # -- vec vs scalar residual filtering ------------------------------------
    path = _query_file()
    predicate = [["v", ">", 2.0]]  # ~2.3% selectivity: the dashboard shape

    def filtered_rows() -> int:
        with FileReader(str(path)) as r:
            return sum(1 for _ in r.iter_rows(filters=predicate))

    # restore the caller's engine choice afterwards: the serve comparison
    # below (and any later phase) must run whatever the round configured
    prior = os.environ.get("PQT_VEC_FILTER")
    try:
        os.environ["PQT_VEC_FILTER"] = "1"
        k_vec = filtered_rows()  # warm + correctness reference
        t_vec = timed_stats(
            filtered_rows, REPEATS, "filter-vec", rows=QUERY_ROWS
        )
        os.environ["PQT_VEC_FILTER"] = "0"
        k_scalar = filtered_rows()
        assert k_scalar == k_vec, f"engines disagree: {k_vec} vs {k_scalar}"
        t_scalar = timed_stats(
            filtered_rows, max(1, REPEATS // 2), "filter-scalar",
            rows=QUERY_ROWS,
        )
    finally:
        if prior is None:
            os.environ.pop("PQT_VEC_FILTER", None)
        else:
            os.environ["PQT_VEC_FILTER"] = prior
    out["filter"] = {
        "rows": QUERY_ROWS,
        "predicate": "v > 2.0",
        "rows_matched": k_vec,
        "rows_s_vec": round(QUERY_ROWS / t_vec["t"], 1),
        "rows_s_scalar": round(QUERY_ROWS / t_scalar["t"], 1),
        "vec_vs_scalar": round(t_scalar["t"] / t_vec["t"], 2),
    }
    log(
        f"bench: query filter 1M-row predicate: vec "
        f"{out['filter']['rows_s_vec'] / 1e6:.2f} M rows/s vs scalar "
        f"{out['filter']['rows_s_scalar'] / 1e6:.2f} M rows/s = "
        f"{out['filter']['vec_vs_scalar']}x"
    )

    # -- filtered aggregate vs row streaming on the serve corpus --------------
    # a production-shaped corpus: analytics files carry LARGE row groups
    # (64Ki rows here vs the serve bench's concurrency-shaped 16Ki), and
    # the aggregate's response is near-constant in result size while row
    # streaming pays per matching row — the contrast push-down exists for
    q_rows = int(os.environ.get("PQT_QUERY_SERVE_ROWS", 4 * SERVE_ROWS))
    d = _serve_dir(q_rows, SERVE_FILES, row_group=1 << 16)
    filt = [["v", ">", 0.0]]  # ~half the corpus survives: streaming hurts
    scan_body = json.dumps(
        {"paths": "shard-*.parquet", "filters": filt}
    ).encode()
    query_body = json.dumps(
        {
            "paths": "shard-*.parquet",
            "filters": filt,
            "aggregates": ["count", ["sum", "v"], ["min", "id"], ["max", "id"]],
        }
    ).encode()

    def one(host, port, route, body):
        conn = http.client.HTTPConnection(host, port, timeout=300)
        try:
            conn.request("POST", route, body=body)
            resp = conn.getresponse()
            payload = resp.read()
            assert resp.status == 200, payload[:200]
            return payload
        finally:
            conn.close()

    def hammer(host, port, route, body, n, conc=4):
        """Throughput at client concurrency `conc` — the production shape
        (and the serve bench's): req/s is what the ratio pin is about."""
        import threading

        lat: list = []
        sizes: list = []
        lock = threading.Lock()
        idx = iter(range(n))

        def worker():
            while True:
                with lock:
                    i = next(idx, None)
                if i is None:
                    return
                t1 = time.perf_counter()
                payload = one(host, port, route, body)
                with lock:
                    lat.append(time.perf_counter() - t1)
                    sizes.append(len(payload))

        t0 = time.perf_counter()
        threads = [threading.Thread(target=worker) for _ in range(conc)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        wall = time.perf_counter() - t0
        assert len(lat) == n
        return {
            "rps": round(n / wall, 2),
            "p50_ms": round(float(np.percentile(lat, 50)) * 1e3, 2),
            "p99_ms": round(float(np.percentile(lat, 99)) * 1e3, 2),
        }, sizes[-1]

    with ScanServer(
        ServeConfig(port=0, root=str(d), cache_mb=256, max_inflight=64)
    ) as srv:
        srv.start_background()
        host, port = srv.host, srv.port
        # warm caches end to end on both routes before timing
        hammer(host, port, "/v1/query", query_body, 2, conc=2)
        hammer(host, port, "/v1/scan", scan_body, 1, conc=1)
        agg, agg_bytes = hammer(
            host, port, "/v1/query", query_body, QUERY_REQUESTS
        )
        stream, stream_bytes = hammer(
            host, port, "/v1/scan", scan_body, max(4, QUERY_REQUESTS // 4)
        )
    out["serve"] = {
        "requests": QUERY_REQUESTS,
        "rows": q_rows,
        "files": SERVE_FILES,
        "aggregate": agg,
        "stream": stream,
        "aggregate_bytes": agg_bytes,
        "stream_bytes": stream_bytes,
        "aggregate_vs_stream": round(agg["rps"] / stream["rps"], 2),
    }
    log(
        f"bench: query serve: aggregate {agg['rps']} req/s "
        f"({agg_bytes} B/resp) vs row-stream {stream['rps']} req/s "
        f"({stream_bytes} B/resp) = {out['serve']['aggregate_vs_stream']}x"
    )
    _emit(out)


# -- the device-resident query/write benchmark (--device / make bench-device) --

DEVICE_QUERY_ROWS = int(os.environ.get("PQT_DEVICE_QUERY_ROWS", 500_000))


def _device_corpus() -> Path:
    """A cached numeric corpus written by OUR writer (int64 id + uint32 tag
    + float64 v, several row groups) — the device query lanes filter and
    aggregate it, and the write lane re-encodes its columns."""
    from parquet_tpu.core.writer import FileWriter
    from parquet_tpu.schema.dsl import parse_schema

    p = Path(f"/tmp/pqt_device_{DEVICE_QUERY_ROWS}.parquet")
    if p.exists():
        return p
    schema = parse_schema(
        """
        message bench {
          required int64 id;
          required int32 tag (UINT_32);
          required double v;
        }
        """
    )
    rng = np.random.default_rng(19)
    with FileWriter(
        str(p), schema, codec="snappy", row_group_size=1 << 21
    ) as w:
        done = 0
        while done < DEVICE_QUERY_ROWS:
            n = min(1 << 16, DEVICE_QUERY_ROWS - done)
            w.write_column(
                "id", np.arange(done, done + n, dtype=np.int64)
            )
            w.write_column(
                "tag",
                rng.integers(0, 1 << 32, n, dtype=np.uint64)
                .astype(np.uint32)
                .view(np.int32),
            )
            w.write_column("v", rng.standard_normal(n))
            w.flush_row_group()
            done += n
    return p


def _phase_device() -> None:
    """Device-resident query + write benchmark (`bench.py --device` /
    `make bench-device`). Three lanes, each asserted byte-identical to its
    host twin BEFORE any timing:
      * filter: iter_device_batches(filter_rows=True) — the resident mask
        + one shared compaction gather — vs host vec-mask filtering with a
        post-filter upload;
      * aggregate: POST /v1/query units on ServeConfig(device=True) vs the
        host pyarrow unit path (render_query_body compared verbatim);
      * write: FileWriter.write_device_column (device DELTA block scans +
        dictionary probe) vs write_column, full-file bytes compared.
    On CPU jax the speedups are INFORMATIONAL — identity is the contract
    here, and the ratios only become meaningful with real HBM behind the
    arrays. Rides the --json artifact as "device"."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import jax
    import jax.numpy as jnp

    from parquet_tpu.core.filter import normalize_dnf
    from parquet_tpu.core.filter_vec import dnf_mask
    from parquet_tpu.core.reader import FileReader

    out = {"config": "device", "stat": "median", "rows": DEVICE_QUERY_ROWS}
    path = _device_corpus()
    lo, hi = DEVICE_QUERY_ROWS // 10, (DEVICE_QUERY_ROWS * 9) // 10
    pred = [[["id", ">=", lo], ["id", "<", hi], ["tag", ">=", 1 << 31]]]

    # -- lane 1: device-resident row filtering --------------------------------

    def device_filtered():
        ids = []
        with FileReader(str(path)) as r:
            for b in r.iter_device_batches(
                1 << 15,
                columns=["id", "v"],
                drop_remainder=False,
                filters=pred,
                filter_rows=True,
            ):
                ids.append(b[("id",)])
        jax.block_until_ready(ids)
        return np.concatenate([np.asarray(a) for a in ids]) if ids else np.empty(0, np.int64)

    def host_filtered():
        ids = []
        with FileReader(str(path)) as r:
            nd = normalize_dnf(r.schema, pred)
            for i in range(r.num_row_groups):
                chunks = r._read_row_group(i, None, pack=False)
                n = int(r.row_group(i).num_rows or 0)
                mask = dnf_mask(chunks, nd, n)
                kept = np.asarray(chunks[("id",)].values)[mask]
                ids.append(jnp.asarray(kept))
                jnp.asarray(np.asarray(chunks[("v",)].values)[mask])
        jax.block_until_ready(ids)
        return np.concatenate([np.asarray(a) for a in ids]) if ids else np.empty(0, np.int64)

    d_ids = device_filtered()  # also warms the jit caches
    h_ids = host_filtered()
    assert np.array_equal(d_ids, h_ids), (
        f"device/host filtered rows diverge: {d_ids.shape} vs {h_ids.shape}"
    )
    log(f"bench: device filter identity ✓ ({d_ids.shape[0]} rows kept)")
    t_dev = timed_stats(device_filtered, REPEATS, "filter-device", rows=DEVICE_QUERY_ROWS)
    t_host = timed_stats(host_filtered, REPEATS, "filter-host", rows=DEVICE_QUERY_ROWS)
    out["filter"] = {
        "rows_matched": int(d_ids.shape[0]),
        "rows_s_device": round(DEVICE_QUERY_ROWS / t_dev["t"], 1),
        "rows_s_host": round(DEVICE_QUERY_ROWS / t_host["t"], 1),
        "device_vs_host": round(t_host["t"] / t_dev["t"], 2),
    }
    log(
        f"bench: device filter {out['filter']['rows_s_device'] / 1e6:.2f} M rows/s "
        f"vs host-filter+upload {out['filter']['rows_s_host'] / 1e6:.2f} M rows/s "
        f"= {out['filter']['device_vs_host']}x"
    )

    # -- lane 2: device partial aggregation through the serve executor --------
    from parquet_tpu.serve.aggregate import render_query_body
    from parquet_tpu.serve.protocol import parse_query_request
    from parquet_tpu.serve.server import ScanService, ServeConfig

    q = parse_query_request(
        json.dumps(
            {
                "paths": [str(path)],
                "filters": pred,
                "aggregates": [
                    "count",
                    {"op": "sum", "column": "id"},
                    {"op": "min", "column": "id"},
                    {"op": "max", "column": "tag"},
                ],
            }
        ).encode()
    )
    svc_dev = ScanService(ServeConfig(root=str(path.parent), device=True))
    svc_host = ScanService(ServeConfig(root=str(path.parent)))

    def run_agg(svc):
        ticket, got = svc.query(q, "bench")
        ticket.release()
        return render_query_body(got)

    b_dev, b_host = run_agg(svc_dev), run_agg(svc_host)
    assert b_dev == b_host, f"aggregate bodies diverge: {b_dev} vs {b_host}"
    log(f"bench: device aggregate identity ✓ ({b_dev})")
    t_adev = timed_stats(lambda: run_agg(svc_dev), REPEATS, "agg-device", rows=DEVICE_QUERY_ROWS)
    t_ahost = timed_stats(lambda: run_agg(svc_host), REPEATS, "agg-host", rows=DEVICE_QUERY_ROWS)
    out["aggregate"] = {
        "rows_s_device": round(DEVICE_QUERY_ROWS / t_adev["t"], 1),
        "rows_s_host": round(DEVICE_QUERY_ROWS / t_ahost["t"], 1),
        "device_vs_host": round(t_ahost["t"] / t_adev["t"], 2),
    }
    log(
        f"bench: device aggregate {out['aggregate']['rows_s_device'] / 1e6:.2f} "
        f"M rows/s vs host {out['aggregate']['rows_s_host'] / 1e6:.2f} M rows/s "
        f"= {out['aggregate']['device_vs_host']}x"
    )

    # -- lane 3: the device write path ----------------------------------------
    from parquet_tpu.core.writer import FileWriter
    from parquet_tpu.schema.dsl import parse_schema

    wschema = parse_schema(
        """
        message w {
          required int64 seq;
          required int64 bucket;
        }
        """
    )
    rng = np.random.default_rng(5)
    w_rows = min(DEVICE_QUERY_ROWS, 1 << 19)
    seq = np.cumsum(rng.integers(0, 9, w_rows)).astype(np.int64)
    bucket = rng.integers(0, 128, w_rows, dtype=np.int64)
    d_seq, d_bucket = jnp.asarray(seq), jnp.asarray(bucket)
    enc = {"seq": "DELTA_BINARY_PACKED"}

    def write_host(dst):
        with FileWriter(
            dst, wschema, codec="snappy", column_encodings=enc,
            row_group_size=1 << 22,
        ) as w:
            w.write_column("seq", seq)
            w.write_column("bucket", bucket)

    def write_device(dst):
        with FileWriter(
            dst, wschema, codec="snappy", column_encodings=enc,
            row_group_size=1 << 22,
        ) as w:
            w.write_device_column("seq", d_seq)
            w.write_device_column("bucket", d_bucket)

    ph, pd = "/tmp/pqt_dev_write_h.parquet", "/tmp/pqt_dev_write_d.parquet"
    write_host(ph)
    write_device(pd)  # warms the device encode jit cache
    hb, db = Path(ph).read_bytes(), Path(pd).read_bytes()
    assert hb == db, f"write bytes diverge: {len(hb)} vs {len(db)}"
    log(f"bench: device write identity ✓ ({len(hb)} bytes)")
    t_wdev = timed_stats(lambda: write_device(pd), REPEATS, "write-device", rows=w_rows)
    t_whost = timed_stats(lambda: write_host(ph), REPEATS, "write-host", rows=w_rows)
    out["write"] = {
        "rows": w_rows,
        "rows_s_device": round(w_rows / t_wdev["t"], 1),
        "rows_s_host": round(w_rows / t_whost["t"], 1),
        "device_vs_host": round(t_whost["t"] / t_wdev["t"], 2),
    }
    log(
        f"bench: device write {out['write']['rows_s_device'] / 1e6:.2f} M rows/s "
        f"vs host {out['write']['rows_s_host'] / 1e6:.2f} M rows/s "
        f"= {out['write']['device_vs_host']}x"
    )
    _emit(out)


# -- the streaming-loader benchmark (--dataset / phase "dataset") -------------

DATASET_ROWS = int(os.environ.get("PQT_DATASET_ROWS", 2_000_000))
DATASET_FILES = int(os.environ.get("PQT_DATASET_FILES", 8))


def _dataset_glob() -> str:
    """A cached multi-file shard set: DATASET_ROWS taxi-like rows (int64 id
    PLAIN + DELTA_BINARY_PACKED int64 ts, snappy) split over DATASET_FILES
    files of several row groups each — enough units that prefetch depth has
    something to schedule."""
    import pyarrow as pa
    import pyarrow.parquet as pq

    d = Path(f"/tmp/pqt_dataset_{DATASET_ROWS}_{DATASET_FILES}")
    marker = d / "DONE"
    if not marker.exists():
        d.mkdir(parents=True, exist_ok=True)
        rng = np.random.default_rng(7)
        per = DATASET_ROWS // DATASET_FILES
        log(f"bench: generating {DATASET_FILES} x {per:,}-row shard files in {d}")
        for i in range(DATASET_FILES):
            base = i * per
            t = pa.table(
                {
                    "trip_id": pa.array(
                        np.arange(base, base + per, dtype=np.int64)
                    ),
                    "ts": pa.array(
                        (
                            1_600_000_000_000_000
                            + np.cumsum(rng.integers(0, 1000, per))
                        ).astype(np.int64)
                    ),
                }
            )
            pq.write_table(
                t,
                d / f"shard-{i:03d}.parquet",
                compression="snappy",
                row_group_size=1 << 16,
                use_dictionary=False,
                column_encoding={
                    "trip_id": "PLAIN", "ts": "DELTA_BINARY_PACKED"
                },
            )
        marker.write_text("ok\n")
    return str(d / "shard-*.parquet")


def _phase_dataset() -> None:
    """Training-loop throughput at a prefetch-depth sweep over the shard glob.

    The consumer models a DEVICE-BOUND train step: after touching the
    delivered batch it blocks for PQT_DATASET_STEP_MS (default 2 ms — the
    host-side shape of `block_until_ready()` on an accelerator step: host
    blocked, cores free). rows/s therefore measures the PIPELINE — with
    depth 0 the loop pays decode + step serially; with depth >= 1 unit
    decode on the pqt-data workers overlaps the blocked consumer, and the
    wait-time share shows how much starvation remains. `loader_rows_s` is
    the step-free depth-0 reference (pure decode+rebatch capability).

    Measured constraint (why the consumer is not host compute): on a
    host whose cores the step itself saturates — e.g. an XLA CPU matmul on
    a 2-core box — there is nothing left for decode threads to overlap
    with, and prefetch can only lose; against a blocked consumer the
    overlap is the loader's to win."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")  # host loader: no tunnel
    import time as _time

    from parquet_tpu.data import ParquetDataset
    from parquet_tpu.utils import metrics

    pattern = _dataset_glob()
    batch = 16384
    step_s = float(os.environ.get("PQT_DATASET_STEP_MS", "2")) / 1e3
    sweep = {}

    def run_epoch(depth: int, step: float):
        ds = ParquetDataset(
            pattern, batch_size=batch, prefetch=depth, num_epochs=1,
            remainder="keep",
        )
        total = 0
        with ds:
            for b in ds:
                int(b[("trip_id",)][0])  # touch the delivery
                if step:
                    _time.sleep(step)
                total += int(next(iter(b.values())).shape[0])
        return total

    rows = run_epoch(0, 0.0)  # warm: page cache + lazy imports + native load
    t_loader = timed_stats(
        lambda: run_epoch(0, 0.0), REPEATS, "dataset loader-only", rows=rows
    )
    for depth in (0, 1, 2, 4):
        s0 = metrics.snapshot()
        t = timed_stats(
            lambda d=depth: run_epoch(d, step_s), REPEATS,
            f"dataset depth={depth}", rows=rows,
        )
        d = metrics.delta(s0)
        # share = total wait / total sampled wall across the SAME repeats —
        # mixing a mean wait with the median time would let one outlier run
        # report a >100% share against a clean median
        wall_total = sum(t["samples"])
        wait_total = d.get("dataset_wait_seconds_sum", 0.0)
        sweep[str(depth)] = {
            "rows_s": round(rows / t["t"], 1),
            "t": t["t"],
            "wait_s": round(wait_total / REPEATS, 5),
            "wait_share": (
                round(wait_total / wall_total, 4) if wall_total > 0 else None
            ),
            "samples_s": t["samples"],
        }
    best = max((k for k in sweep if int(k) >= 2), key=lambda k: sweep[k]["rows_s"])
    out = {
        "config": "dataset",
        "rows": rows,
        "files": DATASET_FILES,
        "batch_size": batch,
        "step_ms": step_s * 1e3,
        "rows_s": sweep[best]["rows_s"],
        "best_depth": int(best),
        "vs_depth0": round(sweep["0"]["t"] / sweep[best]["t"], 3),
        "wait_share": sweep[best]["wait_share"],
        "loader_rows_s": round(rows / t_loader["t"], 1),
        "stat": "median",
        "sweep": sweep,
    }
    log(
        f"bench: dataset pipeline: depth {best} {out['rows_s'] / 1e6:.2f} M rows/s "
        f"({out['vs_depth0']:.2f}x over depth 0, wait share "
        f"{out['wait_share']:.1%}; loader-only "
        f"{out['loader_rows_s'] / 1e6:.2f} M rows/s)"
    )
    _emit(out)


# -- the chaos benchmark (--chaos / phase "chaos") -----------------------------

CHAOS_ROWS = int(os.environ.get("PQT_CHAOS_ROWS", 400_000))
CHAOS_FILES = int(os.environ.get("PQT_CHAOS_FILES", 6))
CHAOS_PHASE_S = float(os.environ.get("PQT_CHAOS_PHASE_S", 2.0))
# PQT_CHAOS_SMOKE=1: the `make check` fast gate — tiny corpus, sub-second
# phases, same code paths
CHAOS_SMOKE = os.environ.get("PQT_CHAOS_SMOKE", "0") == "1"


def _chaos_glob() -> str:
    """A cached shard set for the chaos runs (its own corpus: the dataset
    bench's files are sized for throughput, these for many quick units so
    phases see plenty of reads)."""
    import pyarrow as pa
    import pyarrow.parquet as pq

    rows = 60_000 if CHAOS_SMOKE else CHAOS_ROWS
    files = 3 if CHAOS_SMOKE else CHAOS_FILES
    d = Path(f"/tmp/pqt_chaos_{rows}_{files}")
    marker = d / "DONE"
    if not marker.exists():
        d.mkdir(parents=True, exist_ok=True)
        rng = np.random.default_rng(13)
        per = rows // files
        log(f"bench: generating {files} x {per:,}-row chaos shards in {d}")
        for i in range(files):
            t = pa.table(
                {
                    "id": pa.array(
                        np.arange(i * per, (i + 1) * per, dtype=np.int64)
                    ),
                    "v": pa.array(
                        rng.integers(0, 1 << 30, per).astype(np.int64)
                    ),
                }
            )
            pq.write_table(
                t, d / f"shard-{i:03d}.parquet", compression="snappy",
                row_group_size=1 << 13, use_dictionary=False,
            )
        marker.write_text("ok\n")
    return str(d / "shard-*.parquet")


def _chaos_schedule(phase_s: float, base: dict):
    """The bench timeline: the standard acts, with the latency spike split
    into a CONVERGE phase (the controller is still adapting) and a STEADY
    phase (the acceptance pin reads this one: p99 within SLO once
    converged)."""
    from parquet_tpu.testing.chaos import FaultSchedule, Phase

    spike = {**base, "spike_rate": 0.5, "spike_s": 0.15}
    return FaultSchedule([
        Phase("warmup", phase_s * 0.5, base),
        Phase("spike_converge", phase_s, spike),
        Phase("spike_steady", phase_s, spike),
        Phase("error_burst", phase_s * 0.5, {**base, "error_rate": 0.3}),
        Phase("blackout", phase_s * 0.5, {**base, "permanent": True}),
        Phase("recovery", phase_s * 0.5, base),
    ])


def _chaos_dataset_run(pattern: str, *, slo_ms: float, phase_s: float,
                       controlled: bool) -> dict:
    """One dataset pass under the scripted schedule: breaker + retry (+
    hedge when controlled) installed, controller attached per
    `controlled`. Returns the run_dataset_chaos report."""
    from parquet_tpu.data.controller import AIMDController
    from parquet_tpu.testing.chaos import ChaosHarness, run_dataset_chaos

    base = {"latency_s": 0.001}
    schedule = _chaos_schedule(phase_s, base)
    controller = (
        AIMDController(
            slo_wait_ms=slo_ms, initial_depth=1, max_depth=16,
            window_s=max(0.2, phase_s / 8), violation_share=0.02,
            increase_step=2, idle_windows=6,
        )
        if controlled
        else None
    )
    with ChaosHarness(
        schedule,
        seed=17,
        breaker=True,
        retry=True,
        hedge=controlled,
        breaker_kw={"failure_threshold": 5, "open_s": phase_s / 4},
        retry_kw={"attempts": 3, "base_delay_s": 0.002, "max_delay_s": 0.02,
                  "sleep": time.sleep},
        hedge_kw={"delay_quantile": 0.9, "min_delay_s": 0.005,
                  "initial_delay_s": 0.02, "max_delay_s": 0.2},
    ) as chaos:
        return run_dataset_chaos(
            pattern,
            chaos=chaos,
            batch_size=4096,
            slo_wait_ms=slo_ms,
            enable_controller=controlled,
            controller=controller,
            prefetch=1,
            # a DEVICE-BOUND consumer (the block_until_ready shape): the
            # controller's depth buys real overlap against it, and a spike
            # that outruns depth-1 pipelining lands squarely on next()
            step_s=0.02,
        )


def _chaos_breaker_probe(pattern: str) -> dict:
    """Micro-measure of the blackout fast-fail: time-to-typed-error on a
    permanently failing source through the retry ladder alone vs through
    an OPEN breaker. The acceptance pin: breakered < 10% of un-breakered."""
    import glob as _glob

    from parquet_tpu.io import (
        BreakerSource,
        CircuitBreaker,
        LocalFileSource,
        RetryingSource,
    )
    from parquet_tpu.testing.flaky import FlakySource

    path = sorted(_glob.glob(pattern))[0]

    def t_read(src):
        t0 = time.perf_counter()
        try:
            src.read_at(0, 64)
        except OSError:
            pass
        return time.perf_counter() - t0

    # the un-breakered shape: every read spins the full ladder (real
    # backoff sleeps — that IS the cost being measured)
    ladder = RetryingSource(
        FlakySource(LocalFileSource(path), seed=5, permanent=True),
        attempts=4, base_delay_s=0.02, max_delay_s=0.1, seed=5,
    )
    t_unbreakered = min(t_read(ladder) for _ in range(3))
    ladder.close()
    # the breakered shape: ladder under a breaker; trip it, then measure
    # the steady-state fast-fail
    breaker = CircuitBreaker("bench-blackout", failure_threshold=1, open_s=60.0)
    gated = BreakerSource(
        RetryingSource(
            FlakySource(LocalFileSource(path), seed=5, permanent=True),
            attempts=4, base_delay_s=0.02, max_delay_s=0.1, seed=5,
        ),
        breaker,
    )
    t_read(gated)  # trips the breaker (pays one full ladder)
    t_breakered = min(t_read(gated) for _ in range(3))
    gated.close()
    return {
        "time_to_error_ms": round(t_unbreakered * 1e3, 3),
        "fast_fail_ms": round(t_breakered * 1e3, 3),
        "fast_fail_ratio": round(t_breakered / t_unbreakered, 5),
        "pin_under_10pct": t_breakered < 0.1 * t_unbreakered,
    }


def _chaos_serve_run(pattern: str, *, phase_s: float) -> dict:
    """Hammer an in-process daemon while its sources run the fault
    schedule: every response must be typed (2xx with a complete body, a
    structured error body, or a torn stream ENDING in a typed terminator
    record) — never a hang or a traceback. Brownout sheds and breaker
    fast-fails are counted from the metrics delta."""
    import glob as _glob
    import http.client
    import threading as _threading

    from parquet_tpu.io import (
        BreakerRegistry,
        BreakerSource,
        LocalFileSource,
        RetryingSource,
    )
    from parquet_tpu.serve import ScanServer, ServeConfig
    from parquet_tpu.testing.chaos import ChaosHarness, standard_schedule
    from parquet_tpu.utils import metrics

    files = sorted(_glob.glob(pattern))
    root = str(Path(files[0]).parent)
    names = [Path(f).name for f in files]
    schedule = standard_schedule(
        phase_s=phase_s * 0.5, spike_p=0.4, spike_ms=60.0, error_rate=0.4,
        base={"latency_s": 0.001},
    )
    chaos = ChaosHarness(schedule, seed=23)
    breakers = BreakerRegistry(failure_threshold=4, open_s=phase_s / 2)

    def factory(p):
        # the production resilience stack over the injected faults:
        # breaker under a short retry ladder — the blackout phase trips
        # the breaker, and the executor's fast-fail shows up as
        # serve_shed_total{reason="breaker_open"} 503s
        return RetryingSource(
            BreakerSource(chaos.wrap(LocalFileSource(p)), registry=breakers),
            attempts=2, base_delay_s=0.002, max_delay_s=0.01, seed=23,
        )

    config = ServeConfig(
        port=0,
        root=root,
        cache_mb=0,  # chaos must hit the source, not the block cache
        default_timeout_s=max(1.0, phase_s),
        brownout_wait_ms=200.0,
        brownout_window_s=max(0.25, phase_s / 4),
        source_factory=factory,
    )
    statuses: dict = {}
    anomalies = {"hang": 0, "untyped": 0, "torn_typed": 0}
    lock = _threading.Lock()
    snap0 = metrics.snapshot()
    schedule.start(time.monotonic())
    stop = time.monotonic() + schedule.total_s

    def tally(key):
        with lock:
            statuses[key] = statuses.get(key, 0) + 1

    def client(i: int):
        body = json.dumps(
            {"paths": [names[i % len(names)]], "format": "jsonl"}
        )
        while time.monotonic() < stop:
            conn = http.client.HTTPConnection(
                "127.0.0.1", server.port, timeout=max(10.0, 4 * phase_s)
            )
            try:
                conn.request(
                    "POST", "/v1/scan", body=body,
                    headers={"Content-Type": "application/json"},
                )
                resp = conn.getresponse()
                try:
                    payload = resp.read()
                    complete = True
                except http.client.IncompleteRead as e:
                    payload, complete = e.partial, False
                tally(str(resp.status))
                if resp.status == 200 and not complete:
                    # torn stream: acceptable ONLY with a typed terminator
                    last = payload.rstrip(b"\n").rsplit(b"\n", 1)[-1]
                    try:
                        ok = "error" in json.loads(last)
                    except ValueError:
                        ok = False
                    with lock:
                        anomalies["torn_typed" if ok else "untyped"] += 1
                elif resp.status != 200:
                    try:
                        json.loads(payload)["error"]["code"]
                    except (ValueError, KeyError):
                        with lock:
                            anomalies["untyped"] += 1
            except (TimeoutError, OSError):
                with lock:
                    anomalies["hang"] += 1
            finally:
                conn.close()

    with ScanServer(config) as server:
        server.start_background()
        threads = [
            _threading.Thread(target=client, args=(i,), daemon=True)
            for i in range(6)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=schedule.total_s + 30.0)
        hung_workers = sum(1 for t in threads if t.is_alive())
    d = metrics.delta(snap0)
    total = sum(statuses.values())
    return {
        "requests": total,
        "statuses": statuses,
        "torn_with_typed_terminator": anomalies["torn_typed"],
        "untyped_responses": anomalies["untyped"],
        "client_hangs": anomalies["hang"] + hung_workers,
        "shed_queue_wait": d.get('serve_shed_total{reason="queue_wait"}', 0),
        "shed_breaker_open": d.get('serve_shed_total{reason="breaker_open"}', 0),
        "typed_only": anomalies["untyped"] == 0
        and anomalies["hang"] + hung_workers == 0,
    }


def _phase_chaos() -> None:
    """Graceful-degradation measurement: the scripted fault schedule
    (latency spike -> error burst -> blackout -> recovery) against (a) the
    SLO-controlled dataset pipeline vs the same pipeline uncontrolled,
    (b) a breakered vs un-breakered blacked-out source, and (c) the serve
    daemon under brownout. Emits the "chaos" --json section; the three
    acceptance pins ride it as booleans. PQT_CHAOS_SMOKE=1 shrinks
    everything to a make-check-sized smoke."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")

    pattern = _chaos_glob()
    phase_s = 0.8 if CHAOS_SMOKE else CHAOS_PHASE_S
    # the SLO sits between the healthy wait (~ms) and a raw 150 ms spike:
    # absorbing a spike needs real depth/hedging, not luck
    slo_ms = 100.0
    controlled = _chaos_dataset_run(
        pattern, slo_ms=slo_ms, phase_s=phase_s, controlled=True
    )
    uncontrolled = _chaos_dataset_run(
        pattern, slo_ms=slo_ms, phase_s=phase_s, controlled=False
    )
    steady_c = controlled["phases"].get("spike_steady", {})
    steady_u = uncontrolled["phases"].get("spike_steady", {})
    hedges = controlled["hedge"]
    launched = hedges.get("launched", 0)
    breaker = _chaos_breaker_probe(pattern)
    serve = _chaos_serve_run(pattern, phase_s=phase_s)
    out = {
        "config": "chaos",
        "smoke": CHAOS_SMOKE,
        "phase_s": phase_s,
        "slo_ms": slo_ms,
        "controlled": controlled,
        "uncontrolled": uncontrolled,
        "slo_held_controlled": (
            steady_c.get("p99_ms") is not None
            and steady_c["p99_ms"] <= slo_ms
        ),
        "slo_violated_uncontrolled": (
            steady_u.get("p99_ms") is not None
            and steady_u["p99_ms"] > slo_ms
        ),
        "hedge_win_rate": (
            round(hedges.get("win_hedge", 0) / launched, 4) if launched else None
        ),
        "breaker": breaker,
        "serve": serve,
    }
    log(
        f"bench: chaos: spike-steady p99 {steady_c.get('p99_ms')} ms "
        f"controlled vs {steady_u.get('p99_ms')} ms uncontrolled "
        f"(slo {slo_ms} ms); breaker fast-fail "
        f"{breaker['fast_fail_ratio']:.1%} of ladder; serve typed-only="
        f"{serve['typed_only']} (shed {serve['shed_queue_wait']} brownout, "
        f"{serve['shed_breaker_open']} breaker)"
    )
    _emit(out)


# -- the data-lake ingest benchmark (--ingest / phase "ingest") ----------------

INGEST_ROWS = int(os.environ.get("PQT_INGEST_ROWS", 150_000))
INGEST_BATCH = int(os.environ.get("PQT_INGEST_BATCH", 5_000))


def _phase_ingest() -> None:
    """Data-lake loop benchmark (`bench.py --ingest` / `make bench-ingest`).

    Sustained append throughput into a lake table (every batch flushed:
    each commit is a real sort+encode+manifest-publish), then the
    compaction payoff: a sort-key point probe's pruned-unit ratio and the
    filtered-scan wall, before vs after ONE compaction pass folds the
    overlapping ingest files into clustered row groups. Batches
    interleave keys so pre-compaction files ALL overlap — the worst case
    compaction exists to fix. Tracked pins: ingest.append_rows_s (+),
    ingest.pruned_ratio_gain (+), ingest.scan_speedup (+). Host-only;
    the result rides the --json artifact as "ingest"."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import tempfile

    from parquet_tpu.core.reader import FileReader
    from parquet_tpu.lake import Compactor, IngestWriter, LakeTable, pruned_ratio

    batches = max(INGEST_ROWS // INGEST_BATCH, 4)
    rows_total = batches * INGEST_BATCH

    def filtered_scan_s(paths, filters):
        t0 = time.perf_counter()
        n = 0
        for p in paths:
            with FileReader(p) as r:
                for _row in r.iter_rows(filters=filters):
                    n += 1
        return time.perf_counter() - t0, n

    with tempfile.TemporaryDirectory(prefix="pqt_bench_lake_") as d:
        table = LakeTable.create(
            os.path.join(d, "tbl"),
            "message m { required int64 k; optional binary v (STRING); }",
            sort_key="k",
        )
        writer = IngestWriter(table)
        t0 = time.perf_counter()
        for b in range(batches):
            # batch b holds keys b, b+B, b+2B, ... — every flushed file
            # spans the whole key range, so nothing prunes until compaction
            writer.append(
                [
                    {"k": i * batches + b, "v": f"row-{b}-{i}"}
                    for i in range(INGEST_BATCH)
                ],
                flush=True,
            )
        append_s = time.perf_counter() - t0
        snap = table.manifest.open_snapshot()
        assert snap.total_rows == rows_total, snap.total_rows
        paths_before = table.snapshot_paths()
        probe = [("k", "==", rows_total // 2)]
        ratio_before = pruned_ratio(paths_before, probe)
        scan_before_s, hits_before = filtered_scan_s(paths_before, probe)

        t0 = time.perf_counter()
        result = Compactor(
            table, max_files=batches + 1, row_group_size=INGEST_BATCH
        ).compact_once()
        compact_s = time.perf_counter() - t0
        assert result is not None and result.rows == rows_total
        paths_after = table.snapshot_paths()
        ratio_after = pruned_ratio(paths_after, probe)
        scan_after_s, hits_after = filtered_scan_s(paths_after, probe)
        assert hits_after == hits_before, (hits_before, hits_after)

    out = {
        "config": "ingest",
        "rows": rows_total,
        "batch_rows": INGEST_BATCH,
        "flushes": batches,
        "append_rows_s": round(rows_total / append_s, 1),
        "append_wall_s": round(append_s, 4),
        "compact_wall_s": round(compact_s, 4),
        "files_before": len(paths_before),
        "files_after": len(paths_after),
        "pruned_ratio_before": round(ratio_before, 4),
        "pruned_ratio_after": round(ratio_after, 4),
        # the compaction payoff, as one trend-store-tracked leaf: how much
        # MORE of the table a sort-key point probe prunes after the fold
        "pruned_ratio_gain": round(ratio_after - ratio_before, 4),
        "scan_rows_s_before": round(rows_total / scan_before_s, 1),
        "scan_rows_s_after": round(rows_total / scan_after_s, 1),
        "scan_speedup": round(scan_before_s / scan_after_s, 3),
    }
    log(
        f"bench: ingest: {out['append_rows_s']:,} rows/s appended over "
        f"{batches} flushed generations; compaction folded "
        f"{out['files_before']} files -> {out['files_after']}, probe "
        f"pruned ratio {ratio_before:.2f} -> {ratio_after:.2f} "
        f"(gain {out['pruned_ratio_gain']:.2f}), filtered scan "
        f"{out['scan_speedup']}x faster"
    )
    _emit(out)


_PHASE_FNS = {
    "host": decode_all_host,
    "tpu_host": decode_all_tpu_to_host,
    "baseline": deliver_baseline,
    "device": deliver_device,
    "pyarrow": deliver_pyarrow,
}


def _phase_timed(name: str, path) -> None:
    fn = _PHASE_FNS[name]
    fn(path)  # warmup: compile (disk-cached) + connection establishment
    # the two headline phases take extra samples: the tunnel's run-to-run
    # drift is the dominant noise in the reported ratio
    reps = max(REPEATS, 7) if name in ("baseline", "device", "pyarrow") else REPEATS
    _emit(timed_stats(lambda: fn(path), reps, name))


def _run_phase(name: str, timeout_s: float = 1800.0) -> dict | None:
    import subprocess

    cmd = [sys.executable, os.path.abspath(__file__), "--phase", name]
    # strip the artifact path from phase subprocesses: only the TOP-level
    # invocation writes the --json/PQT_BENCH_JSON file, otherwise each phase
    # would clobber it mid-run and a crash would leave a mislabeled partial
    env = {k: v for k, v in os.environ.items() if k != "PQT_BENCH_JSON"}
    try:
        proc = subprocess.run(
            cmd, stdout=subprocess.PIPE, timeout=timeout_s, env=env,
            cwd=str(Path(__file__).parent)
        )
    except subprocess.TimeoutExpired:
        log(f"bench: phase {name} timed out after {timeout_s:.0f}s")
        return None
    if proc.returncode != 0:
        log(f"bench: phase {name} exited {proc.returncode}")
        return None
    for line in reversed(proc.stdout.decode().splitlines()):
        line = line.strip()
        if line.startswith("{"):
            return json.loads(line)
    log(f"bench: phase {name} produced no result line")
    return None


def _phase_encode() -> dict | None:
    """Fused-vs-staged encode ladder microbench (`bench.py --encode`,
    `make bench-encode`).

    Per column shape (dict-string / dict-int64 / delta-int64 / plain-double
    / plain-string), write one single-column file serially with the fused
    native encoder and again with PQT_FUSED_ENCODE=0 (the staged Python
    rung), assert the outputs BYTE-IDENTICAL before any timing, then report
    rows/s for both sides and the median of PAIRED fused/staged ratios.
    Skips cleanly (exit 0, "skipped" artifact) when the native extension
    is not built — the staged rung is then the only encoder and there is
    nothing to compare."""
    from parquet_tpu.core.writer import FileWriter
    from parquet_tpu.schema.dsl import parse_schema
    from parquet_tpu.sink import MemorySink
    from parquet_tpu.utils.native import get_native

    lib = get_native()
    if lib is None or not getattr(lib, "has_chunk_encode", False):
        out = {"config": "encode", "skipped": "native chunk_encode unavailable"}
        log("bench: encode — native chunk_encode unavailable, skipping cleanly")
        _emit(out)
        return out

    rows = int(os.environ.get("PQT_ENCODE_ROWS", "500000"))
    rng = np.random.default_rng(11)
    keys = [f"key_{i:05d}" for i in range(5000)]
    shapes = {
        "dict_string": (
            "message m { required binary s (UTF8); }",
            {"s": [keys[k] for k in rng.integers(0, len(keys), rows)]},
            {},
        ),
        "dict_int64": (
            "message m { required int64 a; }",
            {"a": rng.integers(0, 1000, rows).astype(np.int64)},
            {},
        ),
        "delta_int64": (
            "message m { required int64 ts; }",
            {"ts": np.cumsum(rng.integers(0, 1000, rows)).astype(np.int64)},
            {"column_encodings": {"ts": "DELTA_BINARY_PACKED"},
             "use_dictionary": False},
        ),
        "plain_double": (
            "message m { required double x; }",
            {"x": rng.random(rows)},
            {"use_dictionary": False},
        ),
        "plain_string": (
            # all-unique strings: the dictionary probe must bail and the
            # PLAIN byte-array route carries the page
            "message m { required binary u (UTF8); }",
            {"u": [f"u{i:07d}x{i % 911}" for i in range(rows)]},
            {},
        ),
    }

    def write(schema_text, cols, kw):
        schema = parse_schema(schema_text)
        sink = MemorySink()
        w = FileWriter(sink, schema, codec="snappy", **kw)
        for name, vals in cols.items():
            w.write_column(name, vals)
        w.close()
        return sink.getvalue()

    out = {"config": "encode", "rows": rows, "codec": "snappy", "shapes": {}}
    for name, (schema_text, cols, kw) in shapes.items():
        fused = write(schema_text, cols, kw)
        os.environ["PQT_FUSED_ENCODE"] = "0"
        try:
            staged = write(schema_text, cols, kw)
        finally:
            del os.environ["PQT_FUSED_ENCODE"]
        if fused != staged:
            raise SystemExit(
                f"bench: encode shape {name}: fused output is NOT "
                "byte-identical to the staged encoder"
            )
        # PAIRED sampling: each repeat times staged then fused back to back
        # (same load window), speedup = median of paired ratios
        ratios, t_f, t_s = [], [], []
        for _ in range(REPEATS):
            t0 = time.perf_counter()
            os.environ["PQT_FUSED_ENCODE"] = "0"
            try:
                write(schema_text, cols, kw)
            finally:
                del os.environ["PQT_FUSED_ENCODE"]
            s = time.perf_counter() - t0
            t0 = time.perf_counter()
            write(schema_text, cols, kw)
            f = time.perf_counter() - t0
            t_s.append(round(s, 5))
            t_f.append(round(f, 5))
            ratios.append(s / f)
        med_f = sorted(t_f)[len(t_f) // 2]
        med_s = sorted(t_s)[len(t_s) // 2]
        r = sorted(ratios)[len(ratios) // 2]
        out["shapes"][name] = {
            "fused_rows_s": round(rows / med_f, 1),
            "staged_rows_s": round(rows / med_s, 1),
            "fused_speedup": round(r, 3),
            "samples_fused_s": t_f,
            "samples_staged_s": t_s,
        }
        log(
            f"bench: encode {name}: fused {rows / med_f / 1e6:.2f} M rows/s "
            f"vs staged {rows / med_s / 1e6:.2f} M rows/s "
            f"({r:.2f}x, byte-identical ✓)"
        )
    out["byte_identical"] = True
    _emit(out)
    return out


def main() -> None:
    path = build_file()
    if not _device_ready():
        log("bench: accelerator unavailable — reporting host path only")
        t_host = timed(lambda: decode_all_host(path), REPEATS, "host")
        _emit(
            {
                "metric": (
                    "rows/sec decoded, NYC-taxi-like file (int64 + dict-string "
                    "+ delta-ts cols), HOST fallback (accelerator unreachable)"
                ),
                "value": round(ROWS / t_host, 1),
                "unit": "rows/s",
                "vs_baseline": 1.0,
            }
        )
        return

    log("bench: parity checks (isolated process; also warms the compile cache)")
    if _run_phase("verify") is None:
        raise SystemExit("bench: verification phase failed")

    # host prepare breakdown (PQT_BENCH_PREPARE=0 to skip): the serial
    # prepare wall + per-stage split that bounds the device pipeline
    r_prep = None
    if os.environ.get("PQT_BENCH_PREPARE", "1") != "0":
        r_prep = _run_phase("prepare")
        if r_prep:
            log(
                f"bench: prepare: {r_prep['prepare_ms_per_1m_rows']:.1f} ms/1M rows "
                f"serial, stages {r_prep['stage_ms']}, fused "
                f"{r_prep['fused_engaged']}/{r_prep['fused_engaged'] + r_prep['fused_declined']} "
                f"chunks, scaling {r_prep['thread_scaling']}"
            )

    # secondary metric (stderr): classic decode-to-host rows/s
    r_h = _run_phase("host")
    r_t = _run_phase("tpu_host")
    if r_h and r_t:
        log(
            f"bench: decode-to-host: host {ROWS / r_h['t'] / 1e6:.2f} M rows/s | "
            f"tpu {ROWS / r_t['t'] / 1e6:.2f} M rows/s | ratio {r_h['t'] / r_t['t']:.2f}x"
        )

    # streaming loader (PQT_BENCH_DATASET=0 to skip): multi-file rows/s at a
    # prefetch-depth sweep — the training-input side of the north star
    r_ds = None
    if os.environ.get("PQT_BENCH_DATASET", "1") != "0":
        r_ds = _run_phase("dataset")
        if r_ds:
            log(
                f"bench: dataset loader {r_ds['rows_s'] / 1e6:.2f} M rows/s at "
                f"depth {r_ds['best_depth']} "
                f"({r_ds['vs_depth0']:.2f}x over depth 0)"
            )

    # record-assembly engine sweep (PQT_BENCH_ASSEMBLY=0 to skip): vec vs
    # scalar vs pyarrow on flat/1-level/2-level tables
    r_asm = None
    if os.environ.get("PQT_BENCH_ASSEMBLY", "1") != "0":
        r_asm = _run_phase("assembly")
        if r_asm:
            t1 = r_asm["tables"]["list1"]
            log(
                f"bench: assembly: nested vec {t1['rows_s_vec'] / 1e6:.2f} M rows/s, "
                f"{r_asm['nested_vec_vs_scalar']:.1f}x over the scalar engine"
            )

    # fused-vs-staged encode ladder (PQT_BENCH_ENCODE=0 to skip): per-shape
    # serial chunk-encode throughput, byte-identity asserted pre-timing
    r_enc = None
    if os.environ.get("PQT_BENCH_ENCODE", "1") != "0":
        r_enc = _run_phase("encode")
        if r_enc and "shapes" in r_enc:
            log(
                "bench: encode ladder: "
                + ", ".join(
                    f"{k} {v['fused_speedup']:.2f}x"
                    for k, v in r_enc["shapes"].items()
                )
            )

    # io-layer sweeps (PQT_BENCH_IO=0 to skip): coalesce gap + readahead
    # depth against a latency-injected flaky source
    r_io = None
    if os.environ.get("PQT_BENCH_IO", "1") != "0":
        r_io = _run_phase("io")
        if r_io:
            log(
                f"bench: io coalesce best gap {r_io['best_gap']} "
                f"({r_io['gap_speedup']:.2f}x over gap 0)"
            )

    # remote-IO sweep (PQT_BENCH_IO_REMOTE=0 to skip): httpstub at 0/5/25ms
    # injected RTT, auto-tuned vs fixed knobs, tiered-cache warm re-scan
    r_io_remote = None
    if os.environ.get("PQT_BENCH_IO_REMOTE", "1") != "0":
        r_io_remote = _run_phase("io_remote")
        if r_io_remote:
            log(
                f"bench: io-remote auto-tune "
                f"{r_io_remote['auto_speedup_at_max_rtt']:.2f}x fixed knobs "
                f"at {IO_REMOTE_RTTS_MS[-1]:g}ms RTT; warm tiered "
                f"{r_io_remote['warm_vs_fixed_at_max_rtt']:.1f}x"
            )

    # remote-WRITE sweep (PQT_BENCH_IO_WRITE=0 to skip): multipart HttpSink
    # into a writable httpstub at 0/5/25ms RTT, part-size sweep, every
    # committed object byte-verified
    r_io_write = None
    if os.environ.get("PQT_BENCH_IO_WRITE", "1") != "0":
        r_io_write = _run_phase("io_write")
        if r_io_write:
            log(
                f"bench: io-write {r_io_write['mb_s_at_max_rtt']:.0f} MB/s "
                f"at {IO_WRITE_RTTS_MS[-1]:g}ms RTT"
            )

    # chaos sweep (PQT_BENCH_CHAOS=0 to skip): the scripted fault schedule
    # against the SLO-controlled pipeline, breaker fast-fail, serve brownout
    r_chaos = None
    if os.environ.get("PQT_BENCH_CHAOS", "1") != "0":
        r_chaos = _run_phase("chaos")
        if r_chaos:
            log(
                f"bench: chaos: slo held (controlled) = "
                f"{r_chaos['slo_held_controlled']}, breaker fast-fail "
                f"{r_chaos['breaker']['fast_fail_ratio']:.1%} of ladder, "
                f"serve typed-only = {r_chaos['serve']['typed_only']}"
            )

    # scan-service sweep (PQT_BENCH_SERVE=0 to skip): requests/s + p50/p99
    # at client concurrency 1/4/16 against a warm daemon, cold-vs-warm plan
    r_serve = None
    if os.environ.get("PQT_BENCH_SERVE", "1") != "0":
        r_serve = _run_phase("serve")
        if r_serve:
            c16 = r_serve["concurrency_sweep"]["16"]
            log(
                f"bench: serve {c16['rps']} req/s at conc 16 "
                f"(p50 {c16['p50_ms']} ms, p99 {c16['p99_ms']} ms), "
                f"warm plan {r_serve['plan_cold_vs_warm']}x faster than cold"
            )

    # mesh-router scaling + chaos (PQT_BENCH_SERVE_MESH=0 to skip):
    # routed req/s at 1 vs 4 subprocess replicas + kill-one-replica leg
    r_mesh = None
    if os.environ.get("PQT_BENCH_SERVE_MESH", "1") != "0":
        r_mesh = _run_phase("serve_mesh")
        if r_mesh:
            log(
                f"bench: serve-mesh {r_mesh['rps_1r']} -> "
                f"{r_mesh['rps_4r']} req/s at 1->4 replicas "
                f"(x{r_mesh['scaling_ratio']}), chaos typed_only = "
                f"{r_mesh['chaos']['typed_only']}"
            )

    # data-lake ingest loop (PQT_BENCH_INGEST=0 to skip): sustained append
    # rows/s + the compaction payoff (pruned-ratio gain, filtered-scan
    # speedup) over one table
    r_ingest = None
    if os.environ.get("PQT_BENCH_INGEST", "1") != "0":
        r_ingest = _run_phase("ingest")
        if r_ingest:
            log(
                f"bench: ingest {r_ingest['append_rows_s']:,} rows/s "
                f"appended; compaction pruned-ratio gain "
                f"{r_ingest['pruned_ratio_gain']} and filtered-scan "
                f"speedup {r_ingest['scan_speedup']}x"
            )

    # query push-down sweep (PQT_BENCH_QUERY=0 to skip): vec-vs-scalar
    # residual filtering + filtered-aggregate vs row-streaming req/s
    r_query = None
    if os.environ.get("PQT_BENCH_QUERY", "1") != "0":
        r_query = _run_phase("query")
        if r_query:
            log(
                f"bench: query filter vec "
                f"{r_query['filter']['vec_vs_scalar']}x over scalar; "
                f"aggregate {r_query['serve']['aggregate_vs_stream']}x "
                "req/s over row streaming"
            )

    # BASELINE.md 5-config matrix (per-config JSON on stderr + BENCH_MATRIX.json)
    results = None
    if os.environ.get("PQT_BENCH_MATRIX", "1") != "0":
        results = run_matrix()
        try:
            Path(__file__).parent.joinpath("BENCH_MATRIX.json").write_text(
                json.dumps(results, indent=1) + "\n"
            )
        except OSError as e:  # pragma: no cover
            log(f"bench: could not write BENCH_MATRIX.json: {e}")

    # headline: columns delivered into HBM, each path in a clean process
    r_base = _run_phase("baseline")
    r_dev = _run_phase("device")
    if not (r_base and r_dev):
        raise SystemExit("bench: to-HBM phases failed")
    t_base, t_dev = r_base["t"], r_dev["t"]
    r_pa = _run_phase("pyarrow")
    if r_pa:
        log(
            f"bench: external check: pyarrow decode+upload "
            f"{ROWS / r_pa['t'] / 1e6:.2f} M rows/s | device/pyarrow ratio "
            f"{r_pa['t'] / t_dev:.2f}x"
        )

    rate = ROWS / t_dev
    vs = t_base / t_dev
    log(
        f"bench: to-HBM: baseline {ROWS / t_base / 1e6:.2f} M rows/s | "
        f"device decode {rate / 1e6:.2f} M rows/s | speedup {vs:.2f}x "
        f"(medians of {max(REPEATS, 7)}; device spread "
        f"{ROWS / r_dev['t_max'] / 1e6:.1f}-{ROWS / r_dev['t_min'] / 1e6:.1f} M rows/s)"
    )
    headline = {
        "metric": (
            "rows/sec decoded into TPU HBM, NYC-taxi-like file "
            "(int64 + dict-string + delta-ts cols), device decode "
            "vs host decode + upload"
        ),
        "value": round(rate, 1),
        "unit": "rows/s",
        "vs_baseline": round(vs, 3),
        "stat": "median",
        "value_min": round(ROWS / r_dev["t_max"], 1),
        "value_max": round(ROWS / r_dev["t_min"], 1),
        "vs_baseline_min": round(r_base["t_min"] / r_dev["t_max"], 3),
        "vs_baseline_max": round(r_base["t_max"] / r_dev["t_min"], 3),
        # the EXTERNAL comparator (pyarrow decode + upload at the
        # same delivery point): stable across rounds, unlike our
        # own host baseline, which each round's host-lane work
        # speeds up (see BASELINE.md "Headline trajectory")
        **(
            {
                "rows_s_pyarrow": round(ROWS / r_pa["t"], 1),
                "vs_pyarrow": round(r_pa["t"] / t_dev, 3),
            }
            if r_pa
            else {}
        ),
        # host prepare breakdown (make bench-prepare for the full
        # standalone report): the serial stage split that bounds
        # prepare/RPC overlap
        **(
            {
                "prepare_ms_per_1m_rows": r_prep["prepare_ms_per_1m_rows"],
                "prepare_stage_ms": r_prep["stage_ms"],
                "prepare_thread_scaling": r_prep["thread_scaling"],
            }
            if r_prep
            else {}
        ),
    }
    print(json.dumps(headline))
    # the file artifact carries the full structured round: headline +
    # complete prepare breakdown + the matrix configs (stdout keeps the
    # one-line headline contract)
    artifact = dict(headline)
    if r_prep:
        artifact["prepare"] = r_prep
    if r_ds:
        artifact["dataset"] = r_ds
    if r_io:
        artifact["io"] = r_io
    if r_io_remote:
        artifact["io_remote"] = r_io_remote
    if r_io_write:
        artifact["io_write"] = r_io_write
    if r_serve:
        artifact["serve"] = r_serve
    if r_mesh:
        artifact["mesh"] = r_mesh
    if r_query:
        artifact["query"] = r_query
    if r_ingest:
        artifact["ingest"] = r_ingest
    if r_chaos:
        artifact["chaos"] = r_chaos
    if r_asm:
        artifact["assembly"] = r_asm
    if r_enc:
        artifact["encode"] = r_enc
    if results is not None:
        artifact["matrix"] = results
        for r in results:
            if r.get("config") == "write":
                artifact["write"] = r  # the write-path result, addressable
    _write_artifact(artifact)


def _verify_host_paths(host, tpu) -> None:
    from parquet_tpu.core.arrays import ByteArrayData

    for rg_h, rg_t in zip(host, tpu):
        assert rg_h.keys() == rg_t.keys()
        for path in rg_h:
            a, b = rg_h[path].values, rg_t[path].values
            if isinstance(a, ByteArrayData):
                assert isinstance(b, ByteArrayData)
                assert np.array_equal(a.offsets, b.offsets) and a.data == b.data, path
            else:
                av, bv = np.asarray(a), np.asarray(b)
                assert av.dtype == bv.dtype, (path, av.dtype, bv.dtype)
                assert np.array_equal(
                    av.view((np.uint8, av.dtype.itemsize)),
                    bv.view((np.uint8, bv.dtype.itemsize)),
                ), path
            for attr in ("def_levels", "rep_levels"):
                la, lb = getattr(rg_h[path], attr), getattr(rg_t[path], attr)
                assert (la is None) == (lb is None), (path, attr)
                assert la is None or np.array_equal(la, lb), (path, attr)
    log("bench: byte-identical host vs tpu decode (values + levels) ✓")


def _metric_direction(key: str) -> int:
    """+1: higher is better (throughputs, speedups). -1: lower is better
    (latencies, walls). 0: untracked (counts, depths, config echoes) —
    reported but never gating. Keyed on the LEAF name only, so the rule
    set survives new sections without a registry."""
    k = key.lower()
    if k.endswith("_ms") or "ms_per" in k or k in ("t", "wall_s", "wait_s"):
        return -1
    if (
        "rows_s" in k
        or "req_s" in k
        or k.startswith("rps")  # serve "rps", mesh "rps_1r"/"rps_4r"
        or "speedup" in k
        or k.startswith("vs_")
        or k.endswith("_ratio")
        or k.endswith("_gain")  # ingest.pruned_ratio_gain and kin
        or k == "value"
    ):
        return +1
    return 0


def _numeric_leaves(obj, prefix=""):
    """Flatten nested dicts AND lists to {dotted.path: float} (bools
    excluded). Lists index positionally (`matrix.0.t`) — the artifact's
    matrix section is ordered by config, so position is identity; skipping
    lists would silently exempt the whole matrix from the gate."""
    out = {}
    if isinstance(obj, dict):
        for k, v in obj.items():
            out.update(_numeric_leaves(v, f"{prefix}{k}."))
    elif isinstance(obj, list):
        for i, v in enumerate(obj):
            out.update(_numeric_leaves(v, f"{prefix}{i}."))
    elif isinstance(obj, (int, float)) and not isinstance(obj, bool):
        out[prefix[:-1]] = float(obj)
    return out


# -- the persistent bench trend store ------------------------------------------

_HISTORY_DEFAULT = Path(__file__).resolve().parent / "BENCH_history.jsonl"


def _git_rev() -> str:
    import subprocess

    try:
        out = subprocess.run(
            ["git", "rev-parse", "--short=12", "HEAD"],
            stdout=subprocess.PIPE,
            stderr=subprocess.DEVNULL,
            cwd=str(Path(__file__).resolve().parent),
            timeout=10,
        )
        if out.returncode == 0:
            rev = out.stdout.decode().strip()
            if rev:
                return rev
    except (OSError, subprocess.SubprocessError):
        pass
    return "unknown"


def _config_fingerprint() -> tuple:
    """(digest, basis): a short stable hash of everything that shapes a
    bench round's numbers besides the code — the PQT_* size knobs, the jax
    platform selection, python and machine — so the trend view can tell a
    real regression from a config change."""
    import hashlib
    import platform

    basis = {
        "env": {
            k: v
            for k, v in sorted(os.environ.items())
            if k.startswith("PQT_") or k == "JAX_PLATFORMS"
        },
        "python": platform.python_version(),
        "machine": platform.machine(),
        # core count shapes every pool sweep (thread scaling, parallel
        # encode, serve concurrency): a 1.0x pool result on an nproc=1
        # box is the MACHINE, not a regression — record it so the trend
        # reader can tell
        "nproc": os.cpu_count() or 1,
    }
    digest = hashlib.sha256(
        json.dumps(basis, sort_keys=True).encode()
    ).hexdigest()[:12]
    return digest, basis


def _read_history(path) -> list:
    """Parse + schema-validate the trend store. Every entry must carry
    label/recorded_at/git_rev/config/artifact — a malformed line is a
    hard exit, not a skip: silently dropping rounds would make the trend
    LIE about the trajectory."""
    entries = []
    for i, line in enumerate(Path(path).read_text().splitlines()):
        if not line.strip():
            continue
        try:
            e = json.loads(line)
        except ValueError:
            raise SystemExit(
                f"bench history: {path} line {i + 1} is not valid JSON"
            ) from None
        if not isinstance(e, dict):
            raise SystemExit(f"bench history: {path} line {i + 1} is not an object")
        for k in ("label", "recorded_at", "git_rev", "config", "artifact"):
            if k not in e:
                raise SystemExit(
                    f"bench history: {path} line {i + 1} missing {k!r}"
                )
        if not isinstance(e["artifact"], dict):
            raise SystemExit(
                f"bench history: {path} line {i + 1} artifact is not an object"
            )
        entries.append(e)
    return entries


def _phase_record(artifact_path: str, history_path, label) -> None:
    """Append one --json artifact to the trend store with its provenance."""
    from datetime import datetime, timezone

    art = json.loads(Path(artifact_path).read_text())
    if not isinstance(art, dict):
        raise SystemExit(f"bench record: {artifact_path} is not a JSON object")
    history = Path(history_path)
    entries = _read_history(history) if history.exists() else []
    if label is None:
        # continue the rNN sequence from the HIGHEST recorded round (the
        # store ships seeded at r06; plain len+1 would collide with it)
        ns = [
            int(e["label"][1:])
            for e in entries
            if re.fullmatch(r"r\d+", e["label"])
        ]
        label = f"r{(max(ns) if ns else len(entries)) + 1:02d}"
    if any(e["label"] == label for e in entries):
        raise SystemExit(
            f"bench record: label {label!r} already recorded in {history} "
            "(pass --label to name this round)"
        )
    # provenance preference: the fingerprint the artifact captured at RUN
    # time (bench_config, stamped by _write_artifact) — the env of this
    # --record invocation may differ from the env the numbers ran under
    embedded = art.get("bench_config")
    if isinstance(embedded, dict) and embedded.get("fingerprint"):
        digest = embedded["fingerprint"]
        basis = embedded.get("basis", {})
    else:
        digest, basis = _config_fingerprint()
    entry = {
        "label": label,
        "recorded_at": datetime.now(timezone.utc).isoformat(
            timespec="seconds"
        ),
        "git_rev": _git_rev(),
        "config": digest,
        "config_basis": basis,
        "artifact": art,
    }
    with open(history, "a") as f:
        f.write(json.dumps(entry) + "\n")
    n_tracked = sum(
        1
        for k in _numeric_leaves(art)
        if _metric_direction(k.rsplit(".", 1)[-1]) != 0
    )
    print(
        f"bench record: {label} <- {artifact_path} "
        f"(git {entry['git_rev']}, cfg {digest}, {n_tracked} tracked "
        f"metrics) -> {history} ({len(entries) + 1} rounds)"
    )


def _phase_trend(history_path, section=None) -> None:
    """Render every tracked metric across the recorded rounds (newest on
    the right) with the last-vs-first ratio, direction-aware."""
    history = Path(history_path)
    if not history.exists():
        raise SystemExit(
            f"bench trend: no trend store at {history} "
            "(record a round first: bench.py --record artifact.json)"
        )
    entries = _read_history(history)
    if not entries:
        raise SystemExit(f"bench trend: {history} is empty")
    labels = [e["label"] for e in entries]
    leaves = [_numeric_leaves(e["artifact"]) for e in entries]
    keys = []  # tracked leaves, in first-seen order across rounds
    seen = set()
    for lv in leaves:
        for k in lv:
            if k in seen or _metric_direction(k.rsplit(".", 1)[-1]) == 0:
                continue
            seen.add(k)
            keys.append(k)
    if section is not None:
        keys = [
            k
            for k in keys
            if (k.split(".", 1)[0] if "." in k else "(headline)") == section
        ]
    configs = {e["config"] for e in entries}
    rounds = ", ".join(
        "{}@{}".format(e["label"], e["git_rev"][:7]) for e in entries
    )
    print(f"bench trend: {len(entries)} rounds in {history} ({rounds})")
    if len(configs) > 1:
        print(
            "bench trend: NOTE rounds span "
            f"{len(configs)} config fingerprints — deltas may reflect "
            "config changes, not code"
        )
    # surface the recorded core count: pool-scaling metrics (thread
    # sweeps, parallel encode, serve concurrency) are meaningless to
    # compare across machines with different nproc — and read as flat
    # "regressions" on an nproc=1 box
    nproc_cells = [
        str(e.get("config_basis", {}).get("nproc", "?")) for e in entries
    ]
    if any(c != "?" for c in nproc_cells):
        print(f"bench trend: nproc per round: {' -> '.join(nproc_cells)}")
    last_section = None
    width = max((len(k) for k in keys), default=10)
    for k in keys:
        sec = k.split(".", 1)[0] if "." in k else "(headline)"
        if sec != last_section:
            print(f"  [{sec}]")
            last_section = sec
        vals = [lv.get(k) for lv in leaves]
        cells = " -> ".join("-" if v is None else f"{v:g}" for v in vals)
        present = [v for v in vals if v is not None]
        tail = ""
        if len(present) >= 2 and present[0]:
            ratio = present[-1] / present[0]
            direction = _metric_direction(k.rsplit(".", 1)[-1])
            better = (ratio > 1) if direction > 0 else (ratio < 1)
            verdict = "improved" if better else "regressed"
            if 0.98 <= ratio <= 1.02:
                verdict = "held"
            tail = f"  x{ratio:.3f} {verdict}"
        print(f"    {k:<{width}}  {cells}{tail}")
    print(
        f"bench trend: {len(keys)} tracked metrics across "
        f"{len(labels)} rounds ✓"
    )


def _phase_compare(old_path, new_path: str, threshold: float) -> None:
    """Diff two --json artifacts; exit 1 when a tracked metric regresses
    past `threshold` (fractional, default 0.10). `old_path` may be a
    (name, dict) pair — how the single-path form passes the latest
    recorded history round in."""
    if isinstance(old_path, tuple):
        old_path, old = old_path
    else:
        old = json.loads(Path(old_path).read_text())
    new = json.loads(Path(new_path).read_text())
    ol, nl = _numeric_leaves(old), _numeric_leaves(new)
    shared = sorted(set(ol) & set(nl))
    only_old = sorted(set(ol) - set(nl))
    only_new = sorted(set(nl) - set(ol))
    regressions = []
    compared = 0
    last_section = None
    print(f"bench compare: {old_path} -> {new_path} (threshold {threshold:.0%})")
    for path in shared:
        section = path.split(".", 1)[0] if "." in path else "(headline)"
        leaf = path.rsplit(".", 1)[-1]
        direction = _metric_direction(leaf)
        a, b = ol[path], nl[path]
        if direction == 0:
            continue  # tracked table first; untracked summarized below
        compared += 1
        if section != last_section:
            print(f"  [{section}]")
            last_section = section
        ratio = (b / a) if a else float("inf")
        # the regression sign follows the metric's direction: a throughput
        # regresses by FALLING, a latency by RISING
        delta = (b - a) / a if a else 0.0
        regressed = (
            (direction > 0 and delta < -threshold)
            or (direction < 0 and delta > threshold)
        )
        better = "lower" if direction < 0 else "higher"
        flag = "  REGRESSED" if regressed else ""
        print(
            f"    {path}: {a:g} -> {b:g}  x{ratio:.3f} "
            f"({better}-is-better){flag}"
        )
        if regressed:
            regressions.append((path, a, b))
    changed = sum(
        1
        for p in shared
        if _metric_direction(p.rsplit(".", 1)[-1]) == 0 and ol[p] != nl[p]
    )
    print(
        f"bench compare: {len(shared)} shared leaves "
        f"({changed} untracked changed), "
        f"{len(only_old)} only in old, {len(only_new)} only in new"
    )
    if only_new:
        print(f"bench compare: new sections/leaves: {', '.join(only_new[:8])}"
              + (" ..." if len(only_new) > 8 else ""))
    # a tracked metric that VANISHED can't gate numerically, but silence
    # would read as "held" — name it so the reader decides
    lost = [
        p for p in only_old if _metric_direction(p.rsplit(".", 1)[-1]) != 0
    ]
    for p in lost:
        print(f"bench compare: WARNING tracked metric only in old: {p}")
    if regressions:
        for path, a, b in regressions:
            print(f"bench compare: REGRESSION {path}: {a:g} -> {b:g}")
        raise SystemExit(1)
    if compared == 0:
        # disjoint artifacts (different phases, a crashed run): exiting 0
        # here would green a CI gate that compared NOTHING
        raise SystemExit(
            "bench compare: no tracked metrics in common — nothing was "
            "compared (are these artifacts from the same bench phase?)"
        )
    print(f"bench compare: no tracked regressions in {compared} metrics ✓")


def _pop_opt(args: list, name: str):
    """Pop `NAME VALUE` out of args (mutating); None when absent, clean
    SystemExit when the value is missing — the one copy of the edge case
    every hand-rolled flag below shares."""
    if name not in args:
        return None
    k = args.index(name)
    if k + 1 >= len(args):
        raise SystemExit(f"bench: {name} needs a value")
    val = args[k + 1]
    del args[k : k + 2]
    return val


if __name__ == "__main__":
    argv = sys.argv[1:]
    if "--json" in argv:
        k = argv.index("--json")
        if k + 1 >= len(argv):
            raise SystemExit("bench: --json needs a path")
        _JSON_OUT = argv[k + 1]
        del argv[k : k + 2]
    if argv and argv[0] == "--compare":
        rest = argv[1:]
        raw_thr = _pop_opt(rest, "--threshold")
        if raw_thr is None:
            thr = 0.10
        else:
            try:
                thr = float(raw_thr)
            except ValueError:
                raise SystemExit(
                    f"bench: --threshold needs a number, got {raw_thr!r}"
                ) from None
        history = _pop_opt(rest, "--history") or _HISTORY_DEFAULT
        paths = [a for a in rest if not a.startswith("--")]
        if len(paths) not in (1, 2) or len(paths) != len(rest):
            raise SystemExit(
                "bench: --compare needs [OLD.json] NEW.json "
                "[--threshold FRACTION] [--history PATH] — with one path "
                "the old side is the latest round in BENCH_history.jsonl"
            )
        if len(paths) == 1:
            # old side defaults to the LATEST recorded round: the one-arg
            # form IS the trajectory gate against the trend store
            if not Path(history).exists():
                raise SystemExit(
                    f"bench compare: no trend store at {history} to "
                    "compare against (record a round first, or pass "
                    "OLD.json explicitly)"
                )
            entries = _read_history(history)
            if not entries:
                raise SystemExit(f"bench compare: {history} is empty")
            latest = entries[-1]
            old_side = (
                f"{history}[{latest['label']}]",
                latest["artifact"],
            )
            _phase_compare(old_side, paths[0], thr)
        else:
            _phase_compare(paths[0], paths[1], thr)
    elif argv and argv[0] == "--record":
        rest = argv[1:]
        history = _pop_opt(rest, "--history") or _HISTORY_DEFAULT
        label = _pop_opt(rest, "--label")
        paths = [a for a in rest if not a.startswith("--")]
        if not paths and _JSON_OUT:
            paths = [_JSON_OUT]  # record the artifact --json just named
        if len(paths) != 1 or [a for a in rest if a.startswith("--")]:
            raise SystemExit(
                "bench: --record needs ARTIFACT.json "
                "[--label NAME] [--history PATH]"
            )
        _phase_record(paths[0], history, label)
    elif argv and argv[0] == "--trend":
        rest = argv[1:]
        history = _pop_opt(rest, "--history") or _HISTORY_DEFAULT
        section = _pop_opt(rest, "--section")
        if rest:
            raise SystemExit(
                "bench: --trend takes [--history PATH] [--section NAME]"
            )
        _phase_trend(history, section)
    elif argv and argv[0] == "--dataset":
        _phase_dataset()
    elif argv and argv[0] == "--assembly":
        _phase_assembly()
    elif argv and argv[0] == "--io":
        _phase_io()
    elif argv and argv[0] == "--io-remote":
        _phase_io_remote()
    elif argv and argv[0] == "--io-write":
        _phase_io_write()
    elif argv and argv[0] == "--write":
        _phase_write()
    elif argv and argv[0] == "--encode":
        _phase_encode()
    elif argv and argv[0] == "--serve":
        _phase_serve()
    elif argv and argv[0] == "--serve-mesh":
        _phase_serve_mesh()
    elif argv and argv[0] == "--query":
        _phase_query()
    elif argv and argv[0] == "--device":
        _phase_device()
    elif argv and argv[0] == "--chaos":
        _phase_chaos()
    elif argv and argv[0] == "--ingest":
        _phase_ingest()
    elif len(argv) >= 2 and argv[0] == "--phase":
        name = argv[1]
        if name.startswith("matrix"):
            _phase_matrix(int(name[len("matrix") :]))
        elif name == "write":
            _phase_write()
        elif name == "encode":
            _phase_encode()
        elif name == "verify":
            _phase_verify(build_file())
        elif name == "prepare":
            _phase_prepare()
        elif name == "dataset":
            _phase_dataset()
        elif name == "io":
            _phase_io()
        elif name == "io_remote":
            _phase_io_remote()
        elif name == "io_write":
            _phase_io_write()
        elif name == "serve":
            _phase_serve()
        elif name == "serve_mesh":
            _phase_serve_mesh()
        elif name == "query":
            _phase_query()
        elif name == "device_query":
            _phase_device()
        elif name == "chaos":
            _phase_chaos()
        elif name == "ingest":
            _phase_ingest()
        elif name == "assembly":
            _phase_assembly()
        else:
            _phase_timed(name, build_file())
    else:
        main()

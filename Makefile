# Developer entry points. `make check` is the local quality gate mirrored by
# .github/workflows/ci.yml (ruff runs there; this image has no linter, so the
# syntax gate is compileall).

.PHONY: check test native bench bench-prepare dryrun

check: native
	python -m compileall -q parquet_tpu tests bench.py __graft_entry__.py
	python -m pytest tests/ -q

test:
	python -m pytest tests/ -q

native:
	$(MAKE) -C native

bench:
	python bench.py

# host prepare microbench: serial wall + per-stage breakdown (decompress /
# levels / prescan / copy) + GIL-free thread scaling; no accelerator needed
bench-prepare: native
	python bench.py --phase prepare

dryrun:
	python -c "from __graft_entry__ import dryrun_multichip; dryrun_multichip(8)"

# Developer entry points. `make check` is the local quality gate mirrored by
# .github/workflows/ci.yml (ruff runs there; this image has no linter, so the
# syntax gate is compileall).

.PHONY: check test native bench dryrun

check: native
	python -m compileall -q parquet_tpu tests bench.py __graft_entry__.py
	python -m pytest tests/ -q

test:
	python -m pytest tests/ -q

native:
	$(MAKE) -C native

bench:
	python bench.py

dryrun:
	python -c "from __graft_entry__ import dryrun_multichip; dryrun_multichip(8)"

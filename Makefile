# Developer entry points. `make check` is the local quality gate mirrored by
# .github/workflows/ci.yml (ruff runs there; this image has no linter, so the
# syntax gate is compileall).

.PHONY: check test native bench bench-prepare dryrun fuzz

# tier-1 excludes `slow` (extended fault sweeps); `make fuzz` includes them
check: native
	python -m compileall -q parquet_tpu tests bench.py __graft_entry__.py
	python -m pytest tests/ -q -m 'not slow'

test:
	python -m pytest tests/ -q -m 'not slow'

native:
	$(MAKE) -C native

bench:
	python bench.py

# host prepare microbench: serial wall + per-stage breakdown (decompress /
# levels / prescan / copy) + GIL-free thread scaling; no accelerator needed
bench-prepare: native
	python bench.py --phase prepare

dryrun:
	python -c "from __graft_entry__ import dryrun_multichip; dryrun_multichip(8)"

# seeded fault-injection matrix, fast subset AND the extended `slow` sweep —
# fully deterministic (numpy default_rng from fixed seeds), so a failure here
# replays exactly; the fast subset also rides the tier-1 `-m 'not slow'` run
fuzz: native
	python -m pytest tests/test_faults.py -q


# Developer entry points. `make check` is the local quality gate mirrored by
# .github/workflows/ci.yml.

.PHONY: check test lint native bench bench-prepare bench-dataset bench-io bench-io-remote bench-io-write remote-write-smoke bench-write bench-encode encode-smoke bench-assembly bench-serve bench-query bench-device device-smoke bench-chaos chaos-smoke bench-compare bench-record bench-trend obs-smoke fleet-smoke mesh-smoke ingest-smoke bench-ingest bench-serve-mesh profile-live dryrun fuzz profile

# tier-1 excludes `slow` (extended fault sweeps); `make fuzz` includes them;
# chaos-smoke runs the scripted fault schedule end to end at smoke scale;
# obs-smoke validates the bench trend store's schema and pins the
# sampling profiler's overhead on a decode loop; encode-smoke pins the
# fused native encoder byte-identical to the staged Python rung;
# device-smoke pins the device query/write paths byte-identical to the
# host engines (fast subset of tests/test_device_query.py);
# remote-write-smoke pins the multipart sink's zero-torn-object contract
# over real loopback HTTP (fast subset of tests/test_remote_sink.py);
# fleet-smoke pins the mesh telemetry plane (fast subset of
# tests/test_mesh.py): two in-process daemons -> federated /metrics
# scrape (counters summed exactly) -> cross-process trace-merge round trip;
# mesh-smoke pins the sharded-serve router (fast subset of
# tests/test_mesh_router.py): routed scan/query byte-identical to one
# daemon + a replica killed mid-hammer costing typed retries only;
# ingest-smoke pins the data-lake write loop (fast subset of
# tests/test_lake.py): the append/scan/compact concurrency hammer,
# crash-mid-compact zero-loss, and time-travel byte-identity
check: native lint chaos-smoke obs-smoke encode-smoke device-smoke remote-write-smoke fleet-smoke mesh-smoke ingest-smoke
	python -m pytest tests/ -q -m 'not slow'

# ruff (config in ruff.toml) when installed; images without it fall back to
# the compileall syntax gate so `make check` stays runnable everywhere
lint:
	@if command -v ruff >/dev/null 2>&1; then \
		ruff check parquet_tpu/ tests/ bench.py; \
	else \
		echo "lint: ruff not installed; running compileall syntax gate instead"; \
		python -m compileall -q parquet_tpu tests bench.py __graft_entry__.py; \
	fi

test:
	python -m pytest tests/ -q -m 'not slow'

native:
	$(MAKE) -C native

bench:
	python bench.py

# host prepare microbench: serial wall + per-stage breakdown (decompress /
# levels / prescan / copy) + GIL-free thread scaling; no accelerator needed
bench-prepare: native
	python bench.py --phase prepare

# streaming-loader bench: multi-file glob through ParquetDataset at a
# prefetch-depth sweep (rows/s + wait-time share); host-only, no accelerator
bench-dataset: native
	python bench.py --dataset

# io-layer bench: coalesce-gap + readahead-depth sweeps against a
# latency-injected FlakySource (the object-store shape); host-only
bench-io: native
	python bench.py --io

# remote-IO bench: httpstub (real loopback HTTP range GETs) at injected
# RTT 0/5/25 ms — auto-tuned coalesce/readahead vs the fixed local knobs,
# plus the tiered RAM->disk cache's warm re-scan (asserted to read ZERO
# source bytes before timing); host-only
bench-io-remote: native
	python bench.py --io-remote

# remote-WRITE bench: HttpSink's multipart protocol into a writable
# httpstub at injected RTT 0/5/25 ms, part-size sweep 2/4/8 MiB, every
# committed object asserted byte-identical before timing; host-only
bench-io-write: native
	python bench.py --io-write

# the make-check-sized remote-write gate: a signed FileWriter(url) ->
# FileReader(url) round trip plus the atomicity pins (no object visible
# before complete, none after abort) over real loopback HTTP
remote-write-smoke: native
	JAX_PLATFORMS=cpu python -m pytest tests/test_remote_sink.py -q -k 'roundtrip or torn or signed or abort'

# write-path bench: FileWriter vs pyarrow + the pqt-encode parallelism
# sweep (pool 1/4/8 x 8/16 row groups, byte-identical to serial); host-only
bench-write: native
	python bench.py --write

# fused-vs-staged encode ladder: per-shape serial chunk-encode throughput
# (dict-string/dict-int/delta/plain), byte-identity asserted pre-timing;
# skips cleanly when the native extension is not built
bench-encode: native
	python bench.py --encode

# the make-check-sized encode gate: the fused native encoder must produce
# bytes IDENTICAL to the staged Python rung across the small
# encodings x codecs x dpv matrix (skips cleanly without the extension)
encode-smoke: native
	JAX_PLATFORMS=cpu python -m pytest tests/test_sink.py -q -k 'FusedEncodeLadder and (matrix or crc or page)'

# scan-service bench: requests/s + p50/p99 latency at client concurrency
# 1/4/16 against a warm in-process daemon over real HTTP, plus the
# cold-vs-warm /v1/plan latency ratio; host-only, no accelerator
bench-serve: native
	python bench.py --serve

# query push-down bench: vectorized vs scalar residual filtering on a
# 1M-row numeric predicate, and filtered-AGGREGATE req/s (POST /v1/query)
# vs row-streaming req/s of the same predicate; host-only, no accelerator
bench-query: native
	python bench.py --query

# HBM-loop bench: device-vs-host filter / aggregate / write timings on CPU
# jax (byte identity asserted before any timer starts; real speedups need a
# real accelerator — the ratios here are informational)
bench-device: native
	python bench.py --device

device-smoke: native
	JAX_PLATFORMS=cpu python -m pytest tests/test_device_query.py -q -k 'engages or fast or requires or host_config'

# chaos bench: the scripted fault schedule (latency spike -> error burst ->
# blackout -> recovery) against the SLO-controlled dataset pipeline vs
# uncontrolled, breaker fast-fail vs the retry ladder, and the serve daemon
# under brownout; "SLO held through the schedule" as a measured artifact
bench-chaos: native
	python bench.py --chaos

# the make-check-sized chaos gate: same code paths, sub-second phases
chaos-smoke: native
	PQT_CHAOS_SMOKE=1 JAX_PLATFORMS=cpu python bench.py --chaos

# record-assembly bench: vectorized level-scan engine vs scalar cursor walk
# vs pyarrow to_pylist on flat/1-level/2-level tables (rows asserted
# identical before timing); host-only, no accelerator
bench-assembly: native
	python bench.py --assembly

# regression gate over two --json artifacts: every tracked metric's
# new/old ratio, non-zero exit on a >THRESHOLD regression — how future
# PRs hold the BENCH_r0x trajectory. Usage:
#   make bench-compare OLD=BENCH_r06.json NEW=/tmp/bench_now.json
# (omit OLD to diff against the latest round in BENCH_history.jsonl)
bench-compare:
	python bench.py --compare $(OLD) $(NEW) --threshold $(or $(THRESHOLD),0.10)

# capture a full bench round AND append it to the persistent trend store
# (BENCH_history.jsonl: artifact + git rev + config fingerprint). LABEL
# names the round (default rNN); the trend renders with `make bench-trend`
bench-record: native
	python bench.py --json /tmp/pqt_bench_now.json
	python bench.py --record /tmp/pqt_bench_now.json $(if $(LABEL),--label $(LABEL))

# every tracked metric across the recorded rounds, last-vs-first ratio
bench-trend:
	python bench.py --trend $(if $(SECTION),--section $(SECTION))

# the make-check-sized observability gate: the trend store's schema must
# parse (a malformed BENCH_history.jsonl exits non-zero) and the sampling
# profiler's measured overhead on a decode loop must hold its <5% pin
obs-smoke: native
	python bench.py --trend > /dev/null
	JAX_PLATFORMS=cpu python -m pytest tests/test_prof.py -q -k overhead

# the make-check-sized mesh-telemetry gate: two in-process daemons, a
# federated /metrics scrape whose merged counters equal the arithmetic
# sum of the replica scrapes, and a client trace-id ridden through two
# daemons' remote GETs then stitched by `parquet-tool trace-merge`
fleet-smoke: native
	JAX_PLATFORMS=cpu python -m pytest tests/test_mesh.py -q -k 'fleet_smoke or round_trip or Exactness'

# sharded-serve smoke: replicas + router in-process, routed results
# byte-identical to a single daemon, one replica killed mid-hammer
mesh-smoke: native
	JAX_PLATFORMS=cpu python -m pytest tests/test_mesh_router.py -q -k 'mesh_smoke or byte_identical or killed'

# the make-check-sized data-lake gate: concurrent append/scan/compact
# with every scan pinning exactly one generation, a crash-mid-compact
# losing nothing, and open_snapshot(gen=k) byte-identical across later
# compactions
ingest-smoke: native
	JAX_PLATFORMS=cpu python -m pytest tests/test_lake.py -q -k 'hammer or exactly_one or crash or time_travel or byte_identical'

# data-lake loop benchmark (writes the "ingest" artifact section):
# sustained append rows/s + the compaction payoff (pruned-ratio gain,
# filtered-scan speedup)
bench-ingest: native
	python bench.py --ingest

# router scaling + chaos benchmark (writes the "mesh" artifact section)
bench-serve-mesh:
	python bench.py --serve-mesh


# live-profile a RUNNING daemon (flamegraph-compatible collapsed stacks,
# lane-attributed to the pqt-* pools): make profile-live URL=host:port
profile-live:
	python -m parquet_tpu.tools.parquet_tool profile --live $(or $(URL),http://127.0.0.1:8080) --seconds $(or $(SECONDS),2)

dryrun:
	python -c "from __graft_entry__ import dryrun_multichip; dryrun_multichip(8)"

# seeded fault-injection matrix, fast subset AND the extended `slow` sweep —
# fully deterministic (numpy default_rng from fixed seeds), so a failure here
# replays exactly; the fast subset also rides the tier-1 `-m 'not slow'` run
fuzz: native
	python -m pytest tests/test_faults.py -q

# observability smoke: generate a file, decode it under the span tracer via
# `parquet-tool profile` (jax forced onto CPU so the accelerator tunnel is
# never touched), then validate the Chrome trace-event JSON parses
profile:
	python -c "import numpy as np; from parquet_tpu.core.writer import FileWriter; from parquet_tpu.schema.dsl import parse_schema; s = parse_schema('message m { required int64 id; required binary name (UTF8); }'); w = FileWriter('/tmp/pqt_profile.parquet', s, codec='snappy'); w.write_column('id', np.arange(200000, dtype=np.int64)); w.write_column('name', ['n%d' % (i % 97) for i in range(200000)]); w.close()"
	python -m parquet_tpu.tools.parquet_tool profile /tmp/pqt_profile.parquet -o /tmp/pqt_profile_trace.json --metrics --cpu
	python -c "import json; d = json.load(open('/tmp/pqt_profile_trace.json')); assert d['traceEvents'], 'empty trace'; print('profile: %d trace events parse OK' % len(d['traceEvents']))"


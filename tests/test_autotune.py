"""IOTuner tests: profile keying, the local-profile noise guard, the
bandwidth-delay-product knob math, and the fetch_ranges feed/resolve
wiring ("auto" gap)."""

import pytest

from parquet_tpu.io import (
    IOTuner,
    MemorySource,
    Readahead,
    TieredCache,
    fetch_ranges,
    io_tuner,
    profile_key,
)
from parquet_tpu.io.autotune import (
    LOCAL_GAP,
    LOCAL_READAHEAD,
    MAX_GAP,
    MAX_READAHEAD,
)

HTTP_ID = 'http:http://store:9000/bucket/shard-000.parquet#"e1":12345'


@pytest.fixture(autouse=True)
def _clean_global_tuner():
    io_tuner().reset()
    yield
    io_tuner().reset()


class TestProfileKey:
    def test_http_source_ids_collapse_to_origin(self):
        assert profile_key(HTTP_ID) == "http://store:9000"
        assert (
            profile_key('http:https://s3.example#"e":1') == "https://s3.example"
        )

    def test_plain_urls(self):
        assert profile_key("http://h:8080/x.parquet") == "http://h:8080"
        assert profile_key("https://h/a/b") == "https://h"

    def test_local_shapes(self):
        assert profile_key("file:/data/x.parquet:41:9:17") == "local"
        assert profile_key("mem:0x7f:128") == "local"
        assert profile_key("/data/x.parquet") == "local"

    def test_two_files_one_store_share_a_profile(self):
        t = IOTuner(min_observations=1)
        t.observe(HTTP_ID, 1 << 20, 0.1, 1)
        other = 'http:http://store:9000/bucket/shard-999.parquet#"e9":7'
        assert t.params_for(other).observations == 1


class TestLocalGuard:
    def test_unknown_source_is_local(self):
        t = IOTuner()
        p = t.params_for("file:/x:1:2:3")
        assert p.coalesce_gap == LOCAL_GAP
        assert p.readahead_bytes == LOCAL_READAHEAD
        assert not p.remote

    def test_below_floor_latency_stays_local_exactly(self):
        # a noisy-but-fast transport (sub-2ms per run) must keep the
        # byte-for-byte default whatever its bandwidth says
        t = IOTuner()
        for _ in range(50):
            t.observe(HTTP_ID, 8 << 20, 0.001, 1)  # 1ms, 8 GB/s
        assert t.params_for(HTTP_ID).coalesce_gap == LOCAL_GAP

    def test_min_observations_gate(self):
        t = IOTuner(min_observations=3)
        t.observe(HTTP_ID, 1 << 20, 0.025, 1)
        t.observe(HTTP_ID, 1 << 20, 0.025, 1)
        assert t.params_for(HTTP_ID).coalesce_gap == LOCAL_GAP  # 2 < 3
        t.observe(HTTP_ID, 1 << 20, 0.025, 1)
        assert t.params_for(HTTP_ID).coalesce_gap > LOCAL_GAP


class TestKnobMath:
    def _trained(self, latency_s, bandwidth_bps, n=5):
        t = IOTuner(min_observations=1)
        nbytes = int(bandwidth_bps * latency_s)
        for _ in range(n):
            t.observe(HTTP_ID, nbytes, latency_s, 1)
        return t.params_for(HTTP_ID)

    def test_bandwidth_delay_product(self):
        # 10ms at 100 MB/s -> ~1 MB break-even gap
        p = self._trained(0.010, 100e6)
        assert (512 << 10) < p.coalesce_gap < (2 << 20)
        assert p.remote

    def test_higher_latency_means_bigger_gap(self):
        gaps = [
            self._trained(lat, 50e6).coalesce_gap
            for lat in (0.005, 0.010, 0.025, 0.100)
        ]
        assert gaps == sorted(gaps)
        assert gaps[0] > LOCAL_GAP

    def test_clamped_to_ceiling(self):
        p = self._trained(2.0, 500e6)  # absurd: 1 GB bdp
        assert p.coalesce_gap == MAX_GAP
        assert p.readahead_bytes == MAX_READAHEAD

    def test_readahead_deepens_with_latency(self):
        p = self._trained(0.025, 40e6)  # 1 MB bdp
        assert p.readahead_bytes > LOCAL_READAHEAD

    def test_ewma_recovers_to_local(self):
        # a transport that WAS slow and got fast decays back to local
        t = IOTuner(min_observations=1, alpha=0.5)
        for _ in range(5):
            t.observe(HTTP_ID, 1 << 20, 0.050, 1)
        assert t.params_for(HTTP_ID).remote
        for _ in range(20):
            t.observe(HTTP_ID, 1 << 20, 0.0002, 1)
        assert t.params_for(HTTP_ID).coalesce_gap == LOCAL_GAP

    def test_degenerate_observations_dropped(self):
        t = IOTuner(min_observations=1)
        t.observe(HTTP_ID, 0, 0.1, 1)
        t.observe(HTTP_ID, 100, 0.0, 1)
        t.observe(HTTP_ID, 100, 0.1, 0)
        assert t.params_for(HTTP_ID).observations == 0

    def test_max_profiles_lru_bound(self):
        t = IOTuner(max_profiles=4, min_observations=1)
        for i in range(8):
            t.observe(f"http://h{i}/x", 1 << 20, 0.025, 1)
        assert len(t.stats()) == 4
        assert "http://h7" in t.stats()
        assert "http://h0" not in t.stats()

    def test_reset_and_stats_shape(self):
        t = IOTuner(min_observations=1)
        t.observe(HTTP_ID, 1 << 20, 0.025, 1)
        st = t.stats()["http://store:9000"]
        assert set(st) == {
            "latency_ms", "bandwidth_mb_s", "observations",
            "coalesce_gap", "readahead_bytes", "remote",
        }
        t.reset()
        assert t.stats() == {}

    def test_validation(self):
        with pytest.raises(ValueError):
            IOTuner(alpha=0)
        with pytest.raises(ValueError):
            IOTuner(min_observations=0)


class _FakeRemote:
    """A source whose source_id claims a remote origin; records the run
    spans fetch_ranges actually issued."""

    source_id = HTTP_ID

    def __init__(self, size=8 << 20):
        self._size = size
        self.calls = []

    def size(self):
        return self._size

    def read_ranges(self, ranges):
        self.calls.append(list(ranges))
        return [b"\x00" * n for _o, n in ranges]

    def read_at(self, off, n):
        return b"\x00" * n


class TestWiring:
    def test_fetch_ranges_feeds_the_global_tuner(self):
        src = MemorySource(b"x" * 4096)
        before = io_tuner().params_for(src.source_id).observations
        fetch_ranges(src, [(0, 1024)])
        assert io_tuner().params_for(src.source_id).observations == before + 1

    def test_auto_gap_resolves_from_the_profile(self):
        # train the global tuner: 25ms/run at 40 MB/s -> ~1 MB gap
        for _ in range(5):
            io_tuner().observe(HTTP_ID, 1 << 20, 0.025, 1)
        src = _FakeRemote()
        # two ranges 512 KiB apart: the LOCAL 64 KiB gap keeps them as two
        # reads; the tuned gap merges them into ONE run
        ranges = [(0, 1024), ((512 << 10) + 1024, 1024)]
        fetch_ranges(src, ranges, gap=64 << 10)
        assert len(src.calls[-1]) == 2
        fetch_ranges(src, ranges, gap="auto")
        assert len(src.calls[-1]) == 1

    def test_auto_gap_on_untrained_source_is_the_local_default(self):
        src = _FakeRemote()
        ranges = [(0, 1024), ((512 << 10) + 1024, 1024)]
        fetch_ranges(src, ranges, gap="auto")
        assert len(src.calls[-1]) == 2  # nothing observed yet: 64 KiB

    def test_readahead_autotune_deepens_budget(self):
        for _ in range(5):
            io_tuner().observe(HTTP_ID, 1 << 20, 0.025, 1)
        with TieredCache(ram_bytes=1 << 20, disk_bytes=1 << 20) as tc:
            fixed = Readahead(tc, budget_bytes=10)
            assert not fixed.schedule(_FakeRemote(), [(0, 4096)])  # over budget
            auto = Readahead(tc, budget_bytes=10, autotune=True)
            assert auto.gap == "auto"
            assert auto.schedule(_FakeRemote(), [(0, 4096)])  # tuned budget
            auto.drain()
            auto.close()
            fixed.close()

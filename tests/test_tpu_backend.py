"""TPU decoder backend: byte-identical parity vs the host path.

The write-side oracle of the north star (BASELINE.json): for every supported
shape, FileReader(backend="tpu_roundtrip") must produce byte-identical ChunkData to the
host path. On CPU the device ops run through the same XLA code path (jit on the
cpu backend); bench.py exercises the same code on the real chip.
"""

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from parquet_tpu.core.arrays import ByteArrayData
from parquet_tpu.core.reader import FileReader


def assert_chunks_identical(a, b):
    assert a.num_values == b.num_values
    if isinstance(a.values, ByteArrayData) or isinstance(b.values, ByteArrayData):
        assert isinstance(a.values, ByteArrayData) and isinstance(b.values, ByteArrayData)
        np.testing.assert_array_equal(a.values.offsets, b.values.offsets)
        assert a.values.data == b.values.data
    else:
        av, bv = np.asarray(a.values), np.asarray(b.values)
        assert av.dtype == bv.dtype
        if av.dtype.kind == "f":
            np.testing.assert_array_equal(
                av.view(np.uint32 if av.itemsize == 4 else np.uint64),
                bv.view(np.uint32 if bv.itemsize == 4 else np.uint64),
            )
        else:
            np.testing.assert_array_equal(av, bv)
    for lv in ("def_levels", "rep_levels"):
        la, lb = getattr(a, lv), getattr(b, lv)
        assert (la is None) == (lb is None)
        if la is not None:
            np.testing.assert_array_equal(la, lb)


def both_backends(path):
    with FileReader(path, backend="host") as r:
        host = {i: r.read_row_group(i) for i in range(r.num_row_groups)}
    with FileReader(path, backend="tpu_roundtrip") as r:
        tpu = {i: r.read_row_group(i) for i in range(r.num_row_groups)}
    assert host.keys() == tpu.keys()
    for i in host:
        assert host[i].keys() == tpu[i].keys()
        for col_path in host[i]:
            assert_chunks_identical(host[i][col_path], tpu[i][col_path])
    return host


rng = np.random.default_rng(11)


class TestTpuParity:
    def test_plain_int64(self, tmp_path):
        # BASELINE config 1: PLAIN int64 flat, uncompressed, V1
        t = pa.table({"x": pa.array(rng.integers(-(2**62), 2**62, 20_000), pa.int64())})
        path = str(tmp_path / "c1.parquet")
        pq.write_table(t, path, use_dictionary=False, compression="none")
        both_backends(path)

    def test_dict_int32_snappy_v2(self, tmp_path):
        # BASELINE config 2 shape: hybrid int32, SNAPPY, V2 pages
        t = pa.table({"x": pa.array(rng.integers(0, 1000, 50_000).astype(np.int32))})
        path = str(tmp_path / "c2.parquet")
        pq.write_table(t, path, compression="snappy", data_page_version="2.0")
        both_backends(path)

    def test_dict_strings_100k(self, tmp_path):
        # BASELINE config 3 shape: dictionary strings
        keys = [f"key_{i:05d}" for i in range(5000)]
        vals = [keys[i % 5000] for i in range(60_000)]
        t = pa.table({"s": pa.array(vals)})
        path = str(tmp_path / "c3.parquet")
        pq.write_table(t, path, compression="snappy")
        both_backends(path)

    def test_delta_int64_gzip(self, tmp_path):
        # BASELINE config 4: delta-bp int64 timestamps, GZIP
        ts = (1_600_000_000_000_000 + np.cumsum(rng.integers(0, 1000, 30_000))).astype(np.int64)
        t = pa.table({"ts": pa.array(ts)})
        path = str(tmp_path / "c4.parquet")
        pq.write_table(
            t, path, compression="gzip", use_dictionary=False,
            column_encoding={"ts": "DELTA_BINARY_PACKED"},
        )
        both_backends(path)

    def test_nested_list_levels(self, tmp_path):
        # BASELINE config 5: nested LIST<int32> with R/D levels
        data = [list(range(i % 6)) if i % 7 else None for i in range(5000)]
        t = pa.table({"l": pa.array(data, pa.list_(pa.int32()))})
        path = str(tmp_path / "c5.parquet")
        pq.write_table(t, path, compression="snappy")
        both_backends(path)

    def test_nullable_dict_column(self, tmp_path):
        vals = [f"v{i % 50}" if i % 3 else None for i in range(10_000)]
        t = pa.table({"s": pa.array(vals)})
        path = str(tmp_path / "nd.parquet")
        pq.write_table(t, path)
        both_backends(path)

    def test_multi_page_dict(self, tmp_path):
        t = pa.table({"x": pa.array(rng.integers(0, 100, 40_000).astype(np.int64))})
        path = str(tmp_path / "mp.parquet")
        pq.write_table(t, path, data_page_size=2048)
        both_backends(path)

    def test_multi_row_group(self, tmp_path):
        t = pa.table({"x": pa.array(rng.integers(0, 30, 10_000).astype(np.int64)),
                      "y": pa.array(rng.standard_normal(10_000))})
        path = str(tmp_path / "mrg.parquet")
        pq.write_table(t, path, row_group_size=1111)
        both_backends(path)

    def test_plain_doubles_floats(self, tmp_path):
        t = pa.table({
            "f": pa.array(rng.standard_normal(8000).astype(np.float32)),
            "d": pa.array(np.concatenate([rng.standard_normal(7999), [np.nan]])),
        })
        path = str(tmp_path / "fd.parquet")
        pq.write_table(t, path, use_dictionary=False)
        both_backends(path)

    def test_byte_arrays_fall_back_to_host(self, tmp_path):
        # plain (non-dict) strings: host fallback path inside tpu backend
        t = pa.table({"s": pa.array([f"unique_{i}" for i in range(40_000)])})
        path = str(tmp_path / "ba.parquet")
        pq.write_table(t, path)  # 40k uniques > dict? pyarrow spills to plain
        both_backends(path)

    def test_empty_and_all_null(self, tmp_path):
        t = pa.table({"x": pa.array([None] * 100, pa.int64())})
        path = str(tmp_path / "an.parquet")
        pq.write_table(t, path)
        both_backends(path)

    def test_rows_match_through_assembly(self, tmp_path):
        t = pa.table({
            "id": pa.array(range(5000), pa.int64()),
            "cat": pa.array([f"c{i%7}" for i in range(5000)]),
        })
        path = str(tmp_path / "rows.parquet")
        pq.write_table(t, path, compression="snappy")
        with FileReader(path, backend="tpu_roundtrip") as r:
            rows = list(r.iter_rows())
        assert rows == t.to_pylist()


class TestDeviceOpBuckets:
    def test_bucket_reuse_avoids_recompiles(self, tmp_path):
        # different data sizes should land in a bounded set of compiled shapes
        from parquet_tpu.kernels.pipeline import _bucket

        assert _bucket(1000) == 1024
        assert _bucket(1024) == 1024
        assert _bucket(1025) == 2048
        assert _bucket(3) == 1024

    def test_delta_multi_page_segmented_cumsum(self, tmp_path):
        # many small pages force per-page segmentation inside one device batch
        v = rng.integers(-(2**40), 2**40, 50_000).astype(np.int64)
        t = pa.table({"x": pa.array(v)})
        path = str(tmp_path / "dseg.parquet")
        pq.write_table(
            t, path, use_dictionary=False, data_page_size=2048,
            column_encoding={"x": "DELTA_BINARY_PACKED"},
        )
        both_backends(path)

    def test_delta_int32_negatives(self, tmp_path):
        v = rng.integers(-(2**30), 2**30, 20_000).astype(np.int32)
        t = pa.table({"x": pa.array(v)})
        path = str(tmp_path / "d32.parquet")
        pq.write_table(
            t, path, use_dictionary=False,
            column_encoding={"x": "DELTA_BINARY_PACKED"},
        )
        both_backends(path)

    def test_delta_batch_split_at_bits_cap(self, tmp_path, monkeypatch):
        from parquet_tpu.kernels import pipeline
        from parquet_tpu.kernels.pipeline import TpuDecodeStats, plan_chunk_tpu

        v = np.cumsum(rng.integers(-500, 500, 30_000)).astype(np.int64)
        t = pa.table({"x": pa.array(v)})
        path = str(tmp_path / "dsplit.parquet")
        pq.write_table(
            t, path, use_dictionary=False, data_page_size=2048,
            column_encoding={"x": "DELTA_BINARY_PACKED"},
        )
        monkeypatch.setattr(pipeline, "_BATCH_BITS_CAP", 4096 * 8)
        stats = TpuDecodeStats()
        with FileReader(path) as r:
            cc = r.row_group(0).columns[0]
            col = r.schema.column(("x",))
            tpu_chunk = plan_chunk_tpu(r._f, cc, col, stats=stats).finalize()
        assert stats.device_batches > 1
        with FileReader(path, backend="host") as r:
            host_chunk = r.read_row_group(0)[("x",)]
        assert_chunks_identical(host_chunk, tpu_chunk)

    def test_hybrid_batch_split_at_bits_cap(self, tmp_path, monkeypatch):
        # Force the int32-safety batch cap down so one chunk needs several
        # device batches; output must stay byte-identical.
        from parquet_tpu.kernels import pipeline
        from parquet_tpu.kernels.pipeline import TpuDecodeStats, plan_chunk_tpu

        t = pa.table({"x": pa.array(rng.integers(0, 100, 40_000).astype(np.int64))})
        path = str(tmp_path / "split.parquet")
        pq.write_table(t, path, data_page_size=2048)
        monkeypatch.setattr(pipeline, "_BATCH_BITS_CAP", 4096 * 8)
        stats = TpuDecodeStats()
        with FileReader(path) as r:
            cc = r.row_group(0).columns[0]
            col = r.schema.column(("x",))
            plan = plan_chunk_tpu(r._f, cc, col, stats=stats)
            tpu_chunk = plan.finalize()
        assert stats.device_batches > 1
        with FileReader(path, backend="host") as r:
            host_chunk = r.read_row_group(0)[("x",)]
        assert_chunks_identical(host_chunk, tpu_chunk)


def device_vs_host(path):
    """Check read_row_group_device delivers the same values as the host path."""
    with FileReader(path, backend="host") as r:
        host = {i: r.read_row_group(i) for i in range(r.num_row_groups)}
    with FileReader(path) as r:
        dev = {i: r.read_row_group_device(i) for i in range(r.num_row_groups)}
    for i in host:
        assert host[i].keys() == dev[i].keys()
        for p in host[i]:
            h, d = host[i][p], dev[i][p]
            assert d.num_values == h.num_values
            if d.indices is not None:  # dictionary-encoded byte arrays
                idx = np.asarray(d.indices)
                got = d.dictionary.take(idx.astype(np.int64))
                assert isinstance(h.values, ByteArrayData)
                np.testing.assert_array_equal(got.offsets, h.values.offsets)
                assert got.data == h.values.data
                # device-side dictionary copy matches too
                np.testing.assert_array_equal(
                    np.asarray(d.dict_offsets), d.dictionary.offsets
                )
                assert bytes(np.asarray(d.dict_data)) == d.dictionary.data
            elif d.offsets is not None:  # byte arrays uploaded flat
                assert isinstance(h.values, ByteArrayData)
                np.testing.assert_array_equal(np.asarray(d.offsets), h.values.offsets)
                assert bytes(np.asarray(d.data)) == h.values.data
            else:
                got = np.asarray(d.values)
                want = np.asarray(h.values)
                assert got.dtype == want.dtype
                if got.dtype.kind == "f":
                    u = np.uint32 if got.itemsize == 4 else np.uint64
                    np.testing.assert_array_equal(got.view(u), want.view(u))
                else:
                    np.testing.assert_array_equal(got, want)
            for lv in ("def_levels", "rep_levels"):
                la, lb = getattr(h, lv), getattr(d, lv)
                assert (la is None) == (lb is None)
                if la is not None:
                    np.testing.assert_array_equal(la, lb)


class TestDecodeToDevice:
    def test_numeric_dict_column(self, tmp_path):
        t = pa.table({"x": pa.array(rng.integers(0, 500, 30_000).astype(np.int64))})
        path = str(tmp_path / "dd.parquet")
        pq.write_table(t, path, compression="snappy")
        device_vs_host(path)

    def test_string_dict_column_stays_encoded(self, tmp_path):
        vals = [f"cat_{i % 40}" for i in range(20_000)]
        t = pa.table({"s": pa.array(vals)})
        path = str(tmp_path / "ds.parquet")
        pq.write_table(t, path)
        with FileReader(path) as r:
            dc = r.read_row_group_device(0)[("s",)]
        assert dc.indices is not None  # delivered Arrow-dictionary style
        device_vs_host(path)

    def test_delta_and_plain_numeric(self, tmp_path):
        ts = (10**15 + np.cumsum(rng.integers(0, 900, 25_000))).astype(np.int64)
        t = pa.table({
            "ts": pa.array(ts),
            "v": pa.array(rng.standard_normal(25_000)),
            "f": pa.array(rng.standard_normal(25_000).astype(np.float32)),
        })
        path = str(tmp_path / "dp.parquet")
        pq.write_table(
            t, path, use_dictionary=False,
            column_encoding={"ts": "DELTA_BINARY_PACKED", "v": "PLAIN", "f": "PLAIN"},
        )
        device_vs_host(path)

    def test_plain_strings_upload_path(self, tmp_path):
        t = pa.table({"s": pa.array([f"unique_{i}" for i in range(15_000)])})
        path = str(tmp_path / "du.parquet")
        pq.write_table(t, path, use_dictionary=False)
        device_vs_host(path)

    def test_optional_and_nested(self, tmp_path):
        data = [list(range(i % 5)) if i % 6 else None for i in range(4000)]
        t = pa.table({
            "l": pa.array(data, pa.list_(pa.int64())),
            "o": pa.array([i if i % 4 else None for i in range(4000)], pa.int64()),
        })
        path = str(tmp_path / "don.parquet")
        pq.write_table(t, path, compression="snappy")
        device_vs_host(path)

    def test_all_null_dict_column(self, tmp_path):
        # regression: every page is kind 'empty' — must not crash on concat
        t = pa.table({"s": pa.array([None] * 5000, pa.string())})
        path = str(tmp_path / "allnull.parquet")
        pq.write_table(t, path)
        device_vs_host(path)

    def test_oversized_page_falls_back_to_host(self, tmp_path, monkeypatch):
        # regression: a single page above the int32 bit-offset cap must be
        # host-decoded, not silently wrapped into negative offsets
        from parquet_tpu.kernels import pipeline
        from parquet_tpu.kernels.pipeline import TpuDecodeStats, plan_chunk_tpu

        t = pa.table({
            "x": pa.array(rng.integers(0, 64, 20_000).astype(np.int64)),
            "ts": pa.array(np.cumsum(rng.integers(0, 9, 20_000)).astype(np.int64)),
        })
        path = str(tmp_path / "big.parquet")
        pq.write_table(
            t, path, data_page_size=1 << 30,
            use_dictionary=["x"], column_encoding={"ts": "DELTA_BINARY_PACKED"},
        )
        monkeypatch.setattr(pipeline, "_BATCH_BITS_CAP", 128)  # absurdly small
        with FileReader(path, backend="host") as r:
            host = r.read_row_group(0)
        with FileReader(path) as r:
            for j, cc in enumerate(r.row_group(0).columns):
                p = tuple(cc.meta_data.path_in_schema)
                stats = TpuDecodeStats()
                plan = plan_chunk_tpu(r._f, cc, r.schema.column(p), stats=stats)
                assert stats.host_fallback_pages > 0, p
                assert_chunks_identical(host[p], plan.finalize())

    def test_mixed_string_chunk_splits_on_device(self, tmp_path):
        """A byte-array chunk mixing dictionary-coded and PLAIN pages
        (pyarrow's mid-chunk fallback when the dict page overflows) keeps
        the dict pages' index batches on device; PLAIN pages upload raw and
        a ragged device gather merges both in output-index space. The
        finalize (roundtrip) oracle stays byte-identical."""
        import jax

        from parquet_tpu.kernels.pipeline import plan_chunk_tpu

        rng = np.random.default_rng(3)
        # mostly-unique strings overflow a tiny dictionary page quickly
        t = pa.table({"s": pa.array([f"v{int(x):08d}" for x in rng.integers(0, 1 << 30, 20_000)])})
        path = str(tmp_path / "mixed.parquet")
        pq.write_table(t, path, use_dictionary=["s"], dictionary_pagesize_limit=4096)
        with FileReader(path, backend="host") as r:
            host = r.read_row_group(0)
        with FileReader(path) as r:
            cc = r.row_group(0).columns[0]
            p = tuple(cc.meta_data.path_in_schema)
            plan = plan_chunk_tpu(r._f, cc, r.schema.column(p))
            kinds = {k for _, _, _, k, _ in plan.page_infos if k != "empty"}
            if len(kinds) <= 1:
                pytest.skip(
                    "pyarrow no longer mixes page encodings under "
                    f"dictionary_pagesize_limit (kinds={kinds}); regression "
                    "guard needs a new trigger"
                )
            assert plan.dev_hybrid  # dict pages device-bound, not demoted
            dc = plan.device_column()
            assert isinstance(dc.data, jax.Array) and isinstance(dc.offsets, jax.Array)
            hv = host[p].values
            off = np.asarray(dc.offsets)
            np.testing.assert_array_equal(off, hv.offsets)
            # data may carry padding past offsets[-1]; the extent must match
            np.testing.assert_array_equal(
                np.asarray(dc.data)[: off[-1]],
                np.frombuffer(hv.data, dtype=np.uint8),
            )
        with FileReader(path, backend="tpu_roundtrip") as r:
            assert_chunks_identical(host[p], r.read_row_group(0)[p])

    def test_mixed_numeric_chunk_merges_on_device(self, tmp_path):
        """A numeric chunk mixing dictionary pages with a mid-chunk PLAIN
        fallback keeps dict pages on the device (expansion + gather) and
        merges PLAIN pages in output-index order — no value round-trips to
        the host (the split replacing the old demote-everything policy)."""
        import jax

        from parquet_tpu.kernels.pipeline import TpuDecodeStats, plan_chunk_tpu

        rng = np.random.default_rng(11)
        # mostly-unique int64s overflow a tiny dictionary page mid-chunk
        t = pa.table({"x": pa.array(rng.integers(0, 1 << 60, 30_000).astype(np.int64))})
        path = str(tmp_path / "mixnum.parquet")
        pq.write_table(t, path, use_dictionary=["x"], dictionary_pagesize_limit=4096)
        with FileReader(path, backend="host") as r:
            host = r.read_row_group(0)
        with FileReader(path) as r:
            cc = r.row_group(0).columns[0]
            p = tuple(cc.meta_data.path_in_schema)
            stats = TpuDecodeStats()
            plan = plan_chunk_tpu(r._f, cc, r.schema.column(p), stats=stats)
            kinds = {k for _, _, _, k, _ in plan.page_infos if k != "empty"}
            if kinds != {"dict", "values"}:
                pytest.skip(
                    "pyarrow no longer mixes page encodings under "
                    f"dictionary_pagesize_limit (kinds={kinds})"
                )
            assert plan.dev_hybrid  # dict pages stayed on device
            assert stats.host_fallback_pages == 0
            dc = plan.device_column()
            assert isinstance(dc.values, jax.Array)
            np.testing.assert_array_equal(
                np.asarray(dc.values), np.asarray(host[p].values)
            )
        # the roundtrip oracle agrees too
        with FileReader(path, backend="tpu_roundtrip") as r:
            assert_chunks_identical(host[p], r.read_row_group(0)[p])

    def test_values_live_on_device(self, tmp_path):
        import jax

        t = pa.table({"x": pa.array(np.arange(1000, dtype=np.int64))})
        path = str(tmp_path / "dev.parquet")
        pq.write_table(t, path, use_dictionary=False)
        with FileReader(path) as r:
            dc = r.read_row_group_device(0)[("x",)]
        assert isinstance(dc.values, jax.Array)
        # usable directly by jitted compute without a host trip
        total = jax.jit(lambda a: a.sum())(dc.values)
        assert int(total) == int(np.arange(1000).sum())


class TestDeviceBatches:
    """iter_device_batches: the file as fixed-size HBM-resident batches."""

    def _file(self, tmp_path, n=10_000, rg=3_000):
        t = pa.table({
            "x": pa.array(np.arange(n, dtype=np.int64)),
            "v": pa.array([f"k{i%7}" for i in range(n)]),
        })
        path = str(tmp_path / "b.parquet")
        pq.write_table(t, path, row_group_size=rg, use_dictionary=["v"])
        return path

    def test_static_shapes_and_values(self, tmp_path):
        import jax

        path = self._file(tmp_path)
        with FileReader(path) as r:
            batches = list(r.iter_device_batches(1024))
        assert len(batches) == 10_000 // 1024
        seen = []
        for b in batches:
            assert isinstance(b[("x",)], jax.Array)
            assert b[("x",)].shape == (1024,) and b[("v",)].shape == (1024,)
            seen.append(np.asarray(b[("x",)]))
        flat = np.concatenate(seen)
        assert np.array_equal(flat, np.arange(len(flat)))  # order preserved

    def test_remainder_modes(self, tmp_path):
        path = self._file(tmp_path, n=2_500, rg=1_000)
        with FileReader(path) as r:
            dropped = list(r.iter_device_batches(1_000))
            assert [b[("x",)].shape[0] for b in dropped] == [1_000, 1_000]
        with FileReader(path) as r:
            kept = list(r.iter_device_batches(1_000, drop_remainder=False))
            assert [b[("x",)].shape[0] for b in kept] == [1_000, 1_000, 500]
            assert int(np.asarray(kept[-1][("x",)])[-1]) == 2_499

    def test_batch_spans_row_groups(self, tmp_path):
        path = self._file(tmp_path, n=5_000, rg=700)  # batches cross rg edges
        with FileReader(path) as r:
            batches = list(r.iter_device_batches(1_999, drop_remainder=False))
        flat = np.concatenate([np.asarray(b[("x",)]) for b in batches])
        assert np.array_equal(flat, np.arange(5_000))

    def test_raw_byte_array_rejected_and_projectable(self, tmp_path):
        t = pa.table({
            "x": pa.array(np.arange(1000, dtype=np.int64)),
            "s": pa.array([f"unique-{i}" for i in range(1000)]),  # no dict win
        })
        path = str(tmp_path / "raw.parquet")
        pq.write_table(t, path, use_dictionary=False)
        with FileReader(path) as r:
            with pytest.raises(ValueError):
                list(r.iter_device_batches(100))
        with FileReader(path) as r:
            batches = list(r.iter_device_batches(100, columns=["x"]))
        assert len(batches) == 10 and set(batches[0]) == {("x",)}

    def test_feeds_jitted_step(self, tmp_path):
        import jax

        path = self._file(tmp_path, n=4_096, rg=2_048)

        @jax.jit
        def step(batch):
            return batch[("x",)].sum()

        with FileReader(path) as r:
            total = sum(int(step(b)) for b in r.iter_device_batches(512))
        assert total == sum(range(4_096))

    def test_nullable_column_rejected(self, tmp_path):
        t = pa.table({
            "x": pa.array(np.arange(1000, dtype=np.int64)),
            "n": pa.array([None if i % 5 == 0 else i for i in range(1000)], pa.int64()),
        })
        path = str(tmp_path / "nulls.parquet")
        pq.write_table(t, path, use_dictionary=False)
        with FileReader(path) as r:
            with pytest.raises(ValueError, match="nulls"):
                list(r.iter_device_batches(100))
        # projecting the nullable column out makes it batchable again
        with FileReader(path) as r:
            assert len(list(r.iter_device_batches(100, columns=["x"]))) == 10

    def test_repeated_column_rejected(self, tmp_path):
        t = pa.table({
            "x": pa.array(np.arange(100, dtype=np.int64)),
            "l": pa.array([[i, i + 1] for i in range(100)], pa.list_(pa.int32())),
        })
        path = str(tmp_path / "lst.parquet")
        pq.write_table(t, path)
        with FileReader(path) as r:
            with pytest.raises(ValueError, match="repeated"):
                list(r.iter_device_batches(10))
        with FileReader(path) as r:
            assert len(list(r.iter_device_batches(10, columns=["x"]))) == 10

    def test_invalid_batch_size_raises_eagerly(self, tmp_path):
        path = self._file(tmp_path, n=100, rg=100)
        with FileReader(path) as r:
            with pytest.raises(ValueError):
                r.iter_device_batches(0)  # raises at call, not first next()


class TestWorkerPoolPath:
    """The multi-worker prepare branch never runs on a 1-core host by
    default; force it so the pool + dispatch-thread interplay is tested."""

    def test_parallel_prepare_parity(self, tmp_path, monkeypatch):
        monkeypatch.setenv("PQT_HOST_THREADS", "4")
        import parquet_tpu.core.reader as reader_mod

        # fresh pool under the forced knob
        monkeypatch.setattr(reader_mod, "_pool", None)
        t = pa.table({
            "a": pa.array(rng.integers(0, 50, 30_000).astype(np.int64)),
            "b": pa.array([f"k{i%11}" for i in range(30_000)]),
            "c": pa.array(np.cumsum(rng.integers(0, 9, 30_000)).astype(np.int64)),
            "d": pa.array(rng.standard_normal(30_000)),
        })
        path = str(tmp_path / "pool.parquet")
        pq.write_table(
            t, path, row_group_size=7_000, compression="snappy",
            use_dictionary=["b"], column_encoding={"c": "DELTA_BINARY_PACKED"},
        )
        assert reader_mod._host_pool() is not None  # the branch under test
        both_backends(path)
        with FileReader(path) as r:
            groups = r.read_row_groups_device()
        assert sum(g[("a",)].num_values for g in groups) == 30_000
        monkeypatch.setattr(reader_mod, "_pool", None)  # don't leak the pool


def test_sharded_batches_over_mesh(tmp_path):
    """Batches lay out over a data-parallel mesh axis and feed a
    shard_map-style jitted step (the distributed input pipeline)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    t = pa.table({"x": pa.array(np.arange(8_192, dtype=np.int64))})
    path = str(tmp_path / "shard.parquet")
    pq.write_table(t, path, row_group_size=4_096, use_dictionary=False)
    mesh = Mesh(np.array(jax.devices("cpu")[:8]), ("data",))
    sharding = NamedSharding(mesh, P("data"))

    @jax.jit
    def step(b):
        return b[("x",)].sum()

    total = 0
    with FileReader(path) as r:
        for b in r.iter_device_batches(2_048, sharding=sharding):
            arr = b[("x",)]
            assert arr.sharding == sharding and arr.shape == (2_048,)
            total += int(step(b))
    assert total == sum(range(8_192))


def test_sharded_remainder_batch_keeps_sharding(tmp_path):
    import jax
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    t = pa.table({"x": pa.array(np.arange(2_560, dtype=np.int64))})
    path = str(tmp_path / "shard_rem.parquet")
    pq.write_table(t, path, use_dictionary=False)
    mesh = Mesh(np.array(jax.devices("cpu")[:8]), ("data",))
    sharding = NamedSharding(mesh, P("data"))
    with FileReader(path) as r:
        batches = list(
            r.iter_device_batches(1_024, drop_remainder=False, sharding=sharding)
        )
    assert [b[("x",)].shape[0] for b in batches] == [1_024, 1_024, 512]
    assert all(b[("x",)].sharding == sharding for b in batches)  # incl. the tail


def test_sharded_indivisible_remainder_delivered_unsharded(tmp_path):
    import jax
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    t = pa.table({"x": pa.array(np.arange(2_500, dtype=np.int64))})
    path = str(tmp_path / "shard_odd.parquet")
    pq.write_table(t, path, use_dictionary=False)
    mesh = Mesh(np.array(jax.devices("cpu")[:8]), ("data",))
    sharding = NamedSharding(mesh, P("data"))
    with FileReader(path) as r:
        batches = list(
            r.iter_device_batches(1_024, drop_remainder=False, sharding=sharding)
        )
    assert [b[("x",)].shape[0] for b in batches] == [1_024, 1_024, 452]
    assert batches[0][("x",)].sharding == sharding
    # 452 % 8 != 0: the tail arrives, just without the mesh layout
    assert int(np.asarray(batches[-1][("x",)])[-1]) == 2_499


def test_nullable_batches_masked_mean_over_mesh(tmp_path):
    """A nullable int64 column streams as MaskedColumn (device-expanded
    values + validity mask) through a jitted masked-mean step over the
    8-device mesh — the TPU-native null representation (real training data
    has nulls; an error is not an answer)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from parquet_tpu import MaskedColumn

    n = 8_192
    vals = [None if i % 5 == 0 else i for i in range(n)]
    t = pa.table({"x": pa.array(vals, pa.int64())})
    path = str(tmp_path / "nullable.parquet")
    pq.write_table(t, path, row_group_size=4_096, use_dictionary=False)
    mesh = Mesh(np.array(jax.devices("cpu")[:8]), ("data",))
    sharding = NamedSharding(mesh, P("data"))

    @jax.jit
    def masked_mean(b):
        col = b[("x",)]
        m = col.mask
        return jnp.where(m, col.values, 0).sum(), m.sum()

    total = cnt = 0
    with FileReader(path) as r:
        for b in r.iter_device_batches(2_048, sharding=sharding, nullable="mask"):
            col = b[("x",)]
            assert isinstance(col, MaskedColumn)
            assert col.values.sharding == sharding and col.mask.sharding == sharding
            s, c = masked_mean(b)
            total += int(s)
            cnt += int(c)
    expect = [v for v in vals if v is not None]
    assert total == sum(expect) and cnt == len(expect)
    # values row-aligned: null rows zero-filled, non-null rows in place
    with FileReader(path) as r:
        b = next(r.iter_device_batches(4_096, nullable="mask"))
        col = b[("x",)]
        got = np.asarray(col.values)
        mask = np.asarray(col.mask)
        ref = np.array([0 if v is None else v for v in vals[:4_096]])
        np.testing.assert_array_equal(got, ref)
        np.testing.assert_array_equal(mask, [v is not None for v in vals[:4_096]])


def test_nullable_batches_default_still_errors(tmp_path):
    t = pa.table({"x": pa.array([1, None, 3], pa.int64())})
    path = str(tmp_path / "nerr.parquet")
    pq.write_table(t, path)
    from parquet_tpu.meta import ParquetFileError

    with FileReader(path) as r:
        with pytest.raises(ParquetFileError):
            next(r.iter_device_batches(2, nullable="error"))


def test_device_batches_filter_pushdown(tmp_path):
    """filters= on iter_device_batches prunes row groups (stats + bloom)
    before any prepare/upload; surviving groups stream whole."""
    from parquet_tpu import FileWriter
    from parquet_tpu.schema.dsl import parse_schema

    schema = parse_schema("message m { required int64 id; }")
    path = str(tmp_path / "push.parquet")
    with FileWriter(
        path, schema, bloom_filters=True, use_dictionary=False
    ) as w:
        for base in (0, 100_000, 200_000):
            # even ids only: odd values inside [min, max] exist for the
            # bloom (and only the bloom) to exclude
            w.write_column(
                "id", np.arange(base, base + 8_192, 2, dtype=np.int64)
            )
            w.flush_row_group()
    with FileReader(path) as r:
        batches = list(
            r.iter_device_batches(4_096, filters=[("id", ">=", 200_000)])
        )
        assert len(batches) == 1
        np.testing.assert_array_equal(
            np.asarray(batches[0][("id",)]),
            np.arange(200_000, 208_192, 2, dtype=np.int64),
        )
        # bloom-only exclusion: an ODD value inside group 1's [min, max] —
        # statistics admit it, only the bloom can prove it absent
        assert r.prune_row_groups([("id", "==", 100_001)]) == []
        assert list(
            r.iter_device_batches(4_096, filters=[("id", "==", 100_001)])
        ) == []
        # and a present value keeps exactly its group
        assert len(list(
            r.iter_device_batches(4_096, filters=[("id", "==", 100_002)])
        )) == 1
        # no filters: everything streams
        assert len(list(r.iter_device_batches(4_096))) == 3


def test_ragged_device_batches(tmp_path):
    """LIST columns batch as RaggedColumn: values row-padded on device to
    [rows, max_list_len], lengths per row; null/empty lists -> length 0."""
    import jax
    import jax.numpy as jnp

    from parquet_tpu import RaggedColumn

    n = 5_000
    lists = [
        None if i % 13 == 0 else [int(x) for x in range(i % 6)] for i in range(n)
    ]
    t = pa.table({
        "tags": pa.array(lists, pa.list_(pa.int32())),
        "id": pa.array(range(n), pa.int64()),
    })
    path = str(tmp_path / "ragged.parquet")
    pq.write_table(t, path, row_group_size=2_000, use_dictionary=False)

    @jax.jit
    def masked_sum(b):
        col = b[("tags", "list", "element")]
        k = col.values.shape[1]
        m = jnp.arange(k)[None, :] < col.lengths[:, None]
        return jnp.where(m, col.values, 0).sum()

    total = 0
    seen = 0
    with FileReader(path) as r:
        for b in r.iter_device_batches(1_000, lists="pad", max_list_len=8):
            col = b[("tags", "list", "element")]
            assert isinstance(col, RaggedColumn)
            assert col.values.shape == (1_000, 8)
            total += int(masked_sum(b))
            # row alignment with the flat column
            ids = np.asarray(b[("id",)])
            lens = np.asarray(col.lengths)
            for rid in (0, 500, 999):
                row = lists[int(ids[rid])]
                assert lens[rid] == (len(row) if row else 0)
            seen += 1_000
    expect = sum(sum(x) for x in lists[:seen] if x)
    assert total == expect
    # exactness of padded values for a spot row
    with FileReader(path) as r:
        b = next(r.iter_device_batches(1_000, lists="pad", max_list_len=8))
        vals = np.asarray(b[("tags", "list", "element")].values)
        assert vals[5].tolist() == [0, 1, 2, 3, 4, 0, 0, 0]  # row 5: range(5)


def test_ragged_rejects_oversize_and_bad_args(tmp_path):
    t = pa.table({"l": pa.array([[1] * 20], pa.list_(pa.int32()))})
    path = str(tmp_path / "big.parquet")
    pq.write_table(t, path, use_dictionary=False)
    from parquet_tpu.meta import ParquetFileError

    with FileReader(path) as r:
        with pytest.raises(ParquetFileError, match="max_list_len"):
            next(r.iter_device_batches(1, lists="pad", max_list_len=8,
                                       drop_remainder=False))
        with pytest.raises(ValueError, match="max_list_len"):
            r.iter_device_batches(1, lists="pad")
        with pytest.raises(ValueError, match="lists"):
            r.iter_device_batches(1, lists="bogus")


def test_ragged_null_elements_and_nested_rejected(tmp_path):
    """Null elements INSIDE lists would silently left-shift positions; the
    ragged path refuses them. Nested list<list<>> fails eagerly at the call
    (review regressions)."""
    from parquet_tpu.meta import ParquetFileError

    t = pa.table({"l": pa.array([[1, None, 3]], pa.list_(pa.int32()))})
    p1 = str(tmp_path / "nullelem.parquet")
    pq.write_table(t, p1, use_dictionary=False)
    with FileReader(p1) as r:
        with pytest.raises(ParquetFileError, match="null elements"):
            next(r.iter_device_batches(1, lists="pad", max_list_len=4,
                                       drop_remainder=False))
    t2 = pa.table({
        "ll": pa.array([[[1, 2]]], pa.list_(pa.list_(pa.int32())))
    })
    p2 = str(tmp_path / "nested.parquet")
    pq.write_table(t2, p2, use_dictionary=False)
    with FileReader(p2) as r:
        with pytest.raises(ParquetFileError, match="single-level"):
            r.iter_device_batches(1, lists="pad", max_list_len=4)  # EAGER

"""FileReader integration tests — cross-implementation conformance vs pyarrow.

The analogue of the reference's golden-corpus suites (reference:
parquet_test.go apache/parquet-testing, parquet_compatibility_test.go): every
test writes a file with pyarrow (the canonical C++ implementation) and checks
our decode matches to_pylist(), across page versions, codecs and encodings
(reference readwrite_test.go parameterization, SURVEY §4.4).
"""

import math

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from parquet_tpu.core.alloc import AllocError
from parquet_tpu.core.reader import FileReader
from parquet_tpu.meta import ParquetFileError


def eq(a, b):
    if isinstance(a, float) and isinstance(b, float):
        return (math.isnan(a) and math.isnan(b)) or a == b
    if isinstance(a, dict) and isinstance(b, dict):
        return a.keys() == b.keys() and all(eq(a[k], b[k]) for k in a)
    if isinstance(a, (list, tuple)) and isinstance(b, (list, tuple)):
        return len(a) == len(b) and all(eq(x, y) for x, y in zip(a, b))
    return a == b


def check_parity(table, tmp_path, name="f.parquet", fix_maps=(), **write_kw):
    path = str(tmp_path / name)
    pq.write_table(table, path, **write_kw)
    with FileReader(path) as r:
        rows = list(r.iter_rows())
    expected = table.to_pylist()
    for e in expected:
        for m in fix_maps:
            if e.get(m) is not None:
                e[m] = dict(e[m])
    assert len(rows) == len(expected)
    for i, (a, b) in enumerate(zip(rows, expected)):
        assert eq(a, b), f"row {i}: ours={a!r} theirs={b!r}"
    return rows


MIXED = pa.table(
    {
        "i64": pa.array([1, 2, None, 4, 5], pa.int64()),
        "i32": pa.array([10, None, 30, 40, 50], pa.int32()),
        "f32": pa.array([0.5, None, 2.5, 3.5, 4.5], pa.float32()),
        "f64": pa.array([1.5, 2.5, None, 4.5, float("nan")]),
        "s": pa.array(["apple", None, "cherry", "apple", "elderberry"]),
        "b": pa.array([True, False, None, True, False]),
        "bin": pa.array([b"\x00\xff", b"", None, b"xyz", b"q"], pa.binary()),
    }
)


class TestFlatTypes:
    @pytest.mark.parametrize("codec", ["none", "snappy", "gzip", "zstd"])
    def test_mixed_types_all_codecs(self, codec, tmp_path):
        check_parity(MIXED, tmp_path, compression=codec)

    @pytest.mark.parametrize("version", ["1.0", "2.4", "2.6"])
    def test_format_versions(self, version, tmp_path):
        check_parity(MIXED, tmp_path, version=version)

    @pytest.mark.parametrize("dpv", ["1.0", "2.0"])
    def test_data_page_versions(self, dpv, tmp_path):
        check_parity(MIXED, tmp_path, data_page_version=dpv, compression="snappy")

    def test_required_columns(self, tmp_path):
        t = pa.table(
            {
                "a": pa.array(range(100), pa.int64()),
                "b": pa.array([f"v{i}" for i in range(100)]),
            }
        )
        schema = pa.schema(
            [pa.field("a", pa.int64(), nullable=False), pa.field("b", pa.string(), nullable=False)]
        )
        check_parity(t.cast(schema), tmp_path)

    def test_plain_no_dictionary(self, tmp_path):
        t = pa.table({"x": pa.array(range(1000), pa.int64())})
        check_parity(t, tmp_path, use_dictionary=False)

    def test_dictionary_heavy(self, tmp_path):
        vals = [f"key_{i % 37}" for i in range(5000)]
        check_parity(pa.table({"s": pa.array(vals)}), tmp_path, compression="snappy")

    def test_delta_binary_packed(self, tmp_path):
        t = pa.table({"ts": pa.array(np.cumsum(np.arange(2000) % 97).astype(np.int64))})
        check_parity(
            t,
            tmp_path,
            use_dictionary=False,
            column_encoding={"ts": "DELTA_BINARY_PACKED"},
        )

    def test_delta_byte_array(self, tmp_path):
        t = pa.table({"s": pa.array([f"prefix_common_{i:06d}" for i in range(500)])})
        check_parity(
            t,
            tmp_path,
            use_dictionary=False,
            column_encoding={"s": "DELTA_BYTE_ARRAY"},
        )

    def test_delta_length_byte_array(self, tmp_path):
        t = pa.table({"s": pa.array([("x" * (i % 17)) for i in range(500)])})
        check_parity(
            t,
            tmp_path,
            use_dictionary=False,
            column_encoding={"s": "DELTA_LENGTH_BYTE_ARRAY"},
        )

    def test_fixed_len_byte_array(self, tmp_path):
        t = pa.table({"f": pa.array([b"abcd", b"efgh", None, b"ijkl"], pa.binary(4))})
        check_parity(t, tmp_path)

    def test_multiple_pages_per_chunk(self, tmp_path):
        t = pa.table({"x": pa.array(range(50_000), pa.int64())})
        check_parity(t, tmp_path, data_page_size=1024, use_dictionary=False)

    def test_multiple_row_groups(self, tmp_path):
        t = pa.table({"x": pa.array(range(1000), pa.int64())})
        path = str(tmp_path / "rg.parquet")
        pq.write_table(t, path, row_group_size=100)
        with FileReader(path) as r:
            assert r.num_row_groups == 10
            assert r.num_rows == 1000
            assert [row["x"] for row in r.iter_rows()] == list(range(1000))

    def test_all_nulls_column(self, tmp_path):
        t = pa.table({"x": pa.array([None] * 10, pa.int64()),
                      "s": pa.array([None] * 10, pa.string())})
        check_parity(t, tmp_path)

    def test_empty_table(self, tmp_path):
        t = pa.table({"x": pa.array([], pa.int64())})
        path = str(tmp_path / "empty.parquet")
        pq.write_table(t, path)
        with FileReader(path) as r:
            assert r.num_rows == 0
            assert list(r.iter_rows()) == []


class TestNested:
    def test_lists(self, tmp_path):
        t = pa.table(
            {"l": pa.array([[1, 2], [3], None, [], [4, 5, 6]], pa.list_(pa.int32()))}
        )
        check_parity(t, tmp_path)

    def test_maps(self, tmp_path):
        t = pa.table(
            {
                "m": pa.array(
                    [{"a": 1}, {"b": 2, "c": 3}, None, {}, {"d": 4}],
                    pa.map_(pa.string(), pa.int32()),
                )
            }
        )
        check_parity(t, tmp_path, fix_maps=("m",))

    def test_list_of_structs(self, tmp_path):
        t = pa.table(
            {
                "los": pa.array(
                    [[{"x": 1, "y": "a"}, {"x": 2, "y": None}], [], None, [{"x": None, "y": "d"}]],
                    pa.list_(pa.struct([("x", pa.int64()), ("y", pa.string())])),
                )
            }
        )
        check_parity(t, tmp_path)

    def test_struct_of_lists(self, tmp_path):
        t = pa.table(
            {
                "sol": pa.array(
                    [{"v": [1, 2]}, {"v": None}, {"v": []}, None],
                    pa.struct([("v", pa.list_(pa.int64()))]),
                )
            }
        )
        check_parity(t, tmp_path)

    def test_list_of_lists(self, tmp_path):
        t = pa.table(
            {
                "ll": pa.array(
                    [[[1], [2, 3]], None, [[]], [None, [4]]],
                    pa.list_(pa.list_(pa.int64())),
                )
            }
        )
        check_parity(t, tmp_path)

    def test_nested_multi_row_group(self, tmp_path):
        data = [[list(range(i % 5))] * (i % 3) for i in range(100)]
        t = pa.table({"x": pa.array(data, pa.list_(pa.list_(pa.int64())))})
        check_parity(t, tmp_path, row_group_size=7)

    def test_raw_mode_preserves_structure(self, tmp_path):
        t = pa.table({"l": pa.array([[1, 2]], pa.list_(pa.int32()))})
        path = str(tmp_path / "raw.parquet")
        pq.write_table(t, path)
        with FileReader(path) as r:
            (row,) = list(r.iter_rows(raw=True))
        # raw mode keeps the LIST 3-level wrapper (reference NextRow shape)
        assert "l" in row
        inner = row["l"]
        assert isinstance(inner, dict)


class TestOptions:
    def test_projection(self, tmp_path):
        path = str(tmp_path / "p.parquet")
        pq.write_table(MIXED, path)
        with FileReader(path, columns=["i64", "s"]) as r:
            rows = list(r.iter_rows())
        assert set(rows[0].keys()) == {"i64", "s"}

    def test_projection_unknown_column_rejected(self, tmp_path):
        path = str(tmp_path / "p.parquet")
        pq.write_table(MIXED, path)
        with pytest.raises(ParquetFileError):
            FileReader(path, columns=["nope"])

    def test_columnar_read(self, tmp_path):
        t = pa.table({"x": pa.array(range(100), pa.int64())})
        path = str(tmp_path / "c.parquet")
        pq.write_table(t, path, use_dictionary=False)
        with FileReader(path) as r:
            chunks = r.read_row_group(0)
        cd = chunks[("x",)]
        np.testing.assert_array_equal(cd.values, np.arange(100, dtype=np.int64))

    def test_memory_ceiling_triggers(self, tmp_path):
        t = pa.table({"x": pa.array(range(100_000), pa.int64())})
        path = str(tmp_path / "big.parquet")
        pq.write_table(t, path, compression="gzip", use_dictionary=False)
        with FileReader(path, max_memory=1000) as r:
            with pytest.raises(AllocError):
                r.read_row_group(0)

    def test_crc_validation_passes_on_pyarrow_files(self, tmp_path):
        t = pa.table({"x": pa.array(range(1000), pa.int64())})
        path = str(tmp_path / "crc.parquet")
        pq.write_table(t, path, write_page_checksum=True)
        with FileReader(path, validate_crc=True) as r:
            assert [row["x"] for row in r.iter_rows()] == list(range(1000))

    def test_crc_detects_corruption(self, tmp_path):
        t = pa.table({"x": pa.array(range(1000), pa.int64())})
        path = str(tmp_path / "crc2.parquet")
        pq.write_table(t, path, write_page_checksum=True, use_dictionary=False, compression="none")
        data = bytearray(open(path, "rb").read())
        # flip one byte inside the data region (past header, before footer)
        data[200] ^= 0xFF
        corrupted = tmp_path / "corrupt.parquet"
        corrupted.write_bytes(bytes(data))
        with FileReader(str(corrupted), validate_crc=True) as r:
            with pytest.raises(Exception):
                list(r.iter_rows())

    def test_key_value_metadata(self, tmp_path):
        t = pa.table({"x": pa.array([1], pa.int64())})
        path = str(tmp_path / "kv.parquet")
        pq.write_table(t.replace_schema_metadata({"mykey": "myvalue"}), path)
        with FileReader(path) as r:
            assert r.key_value_metadata.get("mykey") == "myvalue"


class TestStress:
    def test_wide_table(self, tmp_path):
        cols = {f"c{i}": pa.array(range(50), pa.int64()) for i in range(60)}
        check_parity(pa.table(cols), tmp_path)

    def test_large_strings(self, tmp_path):
        t = pa.table({"s": pa.array(["x" * 10_000, "y" * 50_000, None])})
        check_parity(t, tmp_path, compression="snappy")

    def test_random_roundtrip_int64(self, tmp_path):
        rng = np.random.default_rng(7)
        vals = rng.integers(np.iinfo(np.int64).min, np.iinfo(np.int64).max, 10_000)
        t = pa.table({"x": pa.array(vals, pa.int64())})
        path = str(tmp_path / "rand.parquet")
        pq.write_table(t, path, use_dictionary=False, compression="snappy")
        with FileReader(path) as r:
            cd = r.read_row_group(0)[("x",)]
        np.testing.assert_array_equal(cd.values, vals)


class TestFastNestedAssembly:
    """The vectorized LIST/MAP fast paths must match the Dremel assembler
    exactly (and fall back for shapes they don't cover)."""

    def _roundtrip_both(self, table, tmp_path):
        import pyarrow.parquet as pq

        from parquet_tpu.core.assembly import RecordAssembler
        from parquet_tpu.core.assembly_vec import assemble_rows

        path = str(tmp_path / "f.parquet")
        pq.write_table(table, path, compression="snappy")
        with FileReader(path) as r:
            fast = assemble_rows(r.schema, r.read_row_group(0), False)
            slow = list(
                RecordAssembler(r.schema, r.read_row_group(0), raw=False,
                                engine="scalar")
            )
        return fast, slow

    def test_list_all_shapes(self, tmp_path):
        rows = []
        rng = np.random.default_rng(8)
        for i in range(5000):
            if i % 13 == 0:
                rows.append(None)
            elif i % 5 == 0:
                rows.append([])
            else:
                rows.append(
                    [None if j % 3 == 0 else int(rng.integers(0, 99)) for j in range(i % 7)]
                )
        t = pa.table({"xs": pa.array(rows, pa.list_(pa.int64()))})
        fast, slow = self._roundtrip_both(t, tmp_path)
        assert fast is not None and fast == slow
        assert fast[0]["xs"] is None and fast[5]["xs"] == []

    def test_map_matches_assembler(self, tmp_path):
        maps = [
            None,
            [],
            [("a", 1), ("b", None)],
            [("k", 7)],
        ] * 500
        t = pa.table({"m": pa.array(maps, pa.map_(pa.string(), pa.int64()))})
        fast, slow = self._roundtrip_both(t, tmp_path)
        assert fast is not None and fast == slow
        assert fast[2]["m"] == {"a": 1, "b": None}

    def test_struct_of_scalars_vectorized(self, tmp_path):
        recs = [
            None if i % 7 == 0 else {"a": i, "b": None if i % 3 == 0 else f"s{i%11}"}
            for i in range(5000)
        ]
        t = pa.table(
            {"r": pa.array(recs, pa.struct([("a", pa.int64()), ("b", pa.string())]))}
        )
        fast, slow = self._roundtrip_both(t, tmp_path)
        assert fast is not None and fast == slow
        assert fast[0]["r"] is None and fast[1]["r"] == {"a": 1, "b": "s1"}

    def test_deep_nesting_takes_vector_path(self, tmp_path):
        """Shapes past the old canonical fast paths (struct-of-list) ride
        the unified engine, not the per-row assembler."""
        from parquet_tpu.core.assembly_vec import assemble_rows

        t = pa.table(
            {
                "r": pa.array(
                    [{"xs": [1, 2]}] * 10,
                    pa.struct([("xs", pa.list_(pa.int64()))]),
                )
            }
        )
        import pyarrow.parquet as pq

        path = str(tmp_path / "deep.parquet")
        pq.write_table(t, path)
        with FileReader(path) as r:
            chunks = r.read_row_group(0)
            assert assemble_rows(r.schema, chunks, False) is not None
            rows = list(r.iter_rows())
        assert rows[0]["r"] == {"xs": [1, 2]}

    def test_list_of_struct_vectorized(self, tmp_path):
        """LIST<struct-of-scalars> (e.g. list[Point]) with null lists, empty
        lists, null elements and null leaf values."""
        rng = np.random.default_rng(5)
        rows = []
        for i in range(8000):
            if i % 13 == 0:
                rows.append(None)
            elif i % 5 == 0:
                rows.append([])
            else:
                rows.append([
                    None if (i + j) % 11 == 0
                    else {"x": float(j), "y": None if j % 3 == 0 else int(rng.integers(0, 9))}
                    for j in range(i % 4)
                ])
        t = pa.table({
            "pts": pa.array(rows, pa.list_(pa.struct([("x", pa.float64()), ("y", pa.int64())]))),
        })
        fast, slow = self._roundtrip_both(t, tmp_path)
        assert fast is not None and fast == slow
        assert [r["pts"] for r in fast] == rows


class TestVectorAssembly:
    """The vectorized engine (assembly_vec) must match the per-row Dremel
    walk exactly on ARBITRARY nesting — list-of-list, struct-of-list,
    map-of-struct, 3-level list<struct<list>> — in both ergonomic and raw
    modes."""

    def _both(self, table, tmp_path, raw=False):
        import pyarrow.parquet as pq

        from parquet_tpu.core.assembly import RecordAssembler
        from parquet_tpu.core.assembly_vec import assemble_rows

        path = str(tmp_path / "v.parquet")
        pq.write_table(table, path, compression="snappy")
        with FileReader(path) as r:
            chunks = r.read_row_group(0)
            vec = assemble_rows(r.schema, chunks, raw)
            slow = list(RecordAssembler(r.schema, chunks, raw=raw, engine="scalar"))
        assert vec is not None
        assert vec == slow
        return vec

    def test_list_of_list(self, tmp_path):
        rows = [
            None if i % 7 == 0
            else [[k for k in range(j % 3)] if j % 5 else None for j in range(i % 4)]
            for i in range(4000)
        ]
        t = pa.table({"ll": pa.array(rows, pa.list_(pa.list_(pa.int64())))})
        vec = self._both(t, tmp_path)
        assert [r["ll"] for r in vec] == rows

    def test_struct_of_list(self, tmp_path):
        rows = [
            None if i % 6 == 0
            else {"a": i, "l": [j for j in range(i % 3)] if i % 4 else None}
            for i in range(4000)
        ]
        t = pa.table({
            "s": pa.array(rows, pa.struct([("a", pa.int64()), ("l", pa.list_(pa.int64()))]))
        })
        vec = self._both(t, tmp_path)
        assert [r["s"] for r in vec] == rows

    def test_map_of_struct(self, tmp_path):
        def row(i):
            if i % 9 == 0:
                return None
            return {
                f"k{j}": ({"x": i + j, "y": None if j % 2 else float(j)} if j % 3 else None)
                for j in range(i % 3)
            }

        rows = [row(i) for i in range(4000)]
        t = pa.table({
            "m": pa.array(rows, pa.map_(pa.string(),
                                        pa.struct([("x", pa.int64()), ("y", pa.float64())])))
        })
        vec = self._both(t, tmp_path)
        got = [r["m"] for r in vec]
        assert got == [None if r is None else dict(r) for r in rows]

    def test_three_level_list_struct_list(self, tmp_path):
        """list<struct{p, q: list<int>}> — the VERDICT 3-level fixture."""
        rows = [
            None if i % 11 == 0
            else [{"p": j, "q": [j, j + 1] if j % 2 else []} for j in range(i % 3)]
            for i in range(4000)
        ]
        t = pa.table({
            "z": pa.array(rows, pa.list_(pa.struct(
                [("p", pa.int64()), ("q", pa.list_(pa.int64()))])))
        })
        vec = self._both(t, tmp_path)
        assert [r["z"] for r in vec] == rows
        # raw mode agrees with the assembler too
        self._both(t, tmp_path, raw=True)

    def test_iter_rows_uses_vector_path_end_to_end(self, tmp_path):
        import pyarrow.parquet as pq

        rows = [[[1, 2], [3]], None, [], [[], [4]]] * 50
        t = pa.table({"ll": pa.array(rows, pa.list_(pa.list_(pa.int64())))})
        path = str(tmp_path / "e2e.parquet")
        pq.write_table(t, path)
        with FileReader(path) as r:
            got = [x["ll"] for x in r.iter_rows()]
        assert got == rows
        assert got == pq.read_table(path).column("ll").to_pylist()


class TestToArrow:
    def test_flat_table_with_nulls_matches_pyarrow(self, tmp_path):
        import pyarrow as pa
        import pyarrow.parquet as pq

        n = 10_000
        rng2 = np.random.default_rng(8)
        t = pa.table({
            "i": pa.array(
                [None if k % 7 == 0 else int(v)
                 for k, v in enumerate(rng2.integers(0, 1 << 40, n))],
                pa.int64(),
            ),
            "f": pa.array(rng2.standard_normal(n)),
            "s": pa.array([None if k % 11 == 0 else f"u{k % 97}" for k in range(n)]),
            "b": pa.array([bool(k % 3) for k in range(n)]),
        })
        path = str(tmp_path / "ta.parquet")
        pq.write_table(t, path, row_group_size=3_000, compression="zstd")
        with FileReader(path) as r:
            out = r.to_arrow()
        for c in t.column_names:
            assert out.column(c).to_pylist() == t.column(c).to_pylist(), c
        assert out.column("s").type == pa.large_string()
        assert out.column("i").null_count == t.column("i").null_count
        # projection + row-group subset
        with FileReader(path) as r:
            sub = r.to_arrow(row_groups=[1], columns=["f"])
        assert sub.column_names == ["f"]
        assert sub.num_rows == 3_000
        np.testing.assert_array_equal(
            np.asarray(sub.column("f")), np.asarray(t.column("f"))[3_000:6_000]
        )

    def test_fixed_and_binary(self, tmp_path):
        import pyarrow as pa
        import pyarrow.parquet as pq

        t = pa.table({
            "fx": pa.array([bytes([k] * 4) for k in range(200)], pa.binary(4)),
            "raw": pa.array([bytes([k, k]) for k in range(200)], pa.binary()),
        })
        path = str(tmp_path / "fx.parquet")
        pq.write_table(t, path, use_dictionary=False)
        with FileReader(path) as r:
            out = r.to_arrow()
        assert out.column("fx").to_pylist() == t.column("fx").to_pylist()
        assert out.column("raw").to_pylist() == t.column("raw").to_pylist()

    def test_deep_nesting_supported(self, tmp_path):
        """list<list<>> and structs assemble via the nested builder."""
        import pyarrow as pa
        import pyarrow.parquet as pq

        t = pa.table({
            "ll": pa.array(
                [[[1]], None, [[], [2, None], None]],
                pa.list_(pa.list_(pa.int32())),
            ),
            "g": pa.array(
                [{"a": 1}, None, {"a": None}], pa.struct([("a", pa.int64())])
            ),
        })
        path = str(tmp_path / "nst.parquet")
        pq.write_table(t, path)
        with FileReader(path) as r:
            out = r.to_arrow()
        for c in t.column_names:
            assert out.column(c).to_pylist() == t.column(c).to_pylist(), c

    def test_all_null_column(self, tmp_path):
        import pyarrow as pa
        import pyarrow.parquet as pq

        t = pa.table({"x": pa.array([None] * 50, pa.float64()),
                      "s": pa.array([None] * 50, pa.string())})
        path = str(tmp_path / "an.parquet")
        pq.write_table(t, path)
        with FileReader(path) as r:
            out = r.to_arrow()
        assert out.column("x").null_count == 50
        assert out.column("s").to_pylist() == [None] * 50

    def test_nullable_fixed_and_empty_groups(self, tmp_path):
        """Review regressions: nullable binary(4) scatters dense values to
        row positions; row_groups=[] keeps the (selected) schema."""
        import pyarrow as pa
        import pyarrow.parquet as pq

        t = pa.table({
            "fx": pa.array([b"aaaa", None, b"bbbb", None, b"cccc"], pa.binary(4)),
            "i": pa.array([1, 2, None, 4, 5], pa.int64()),
        })
        path = str(tmp_path / "nfx.parquet")
        pq.write_table(t, path, use_dictionary=False)
        with FileReader(path) as r:
            out = r.to_arrow()
            assert out.column("fx").to_pylist() == t.column("fx").to_pylist()
            empty = r.to_arrow(row_groups=[])
            assert empty.num_rows == 0
            assert set(empty.column_names) == {"fx", "i"}
            assert pa.concat_tables(
                [out, empty.cast(out.schema)]
            ).num_rows == 5

    def test_list_columns(self, tmp_path):
        import pyarrow as pa
        import pyarrow.parquet as pq

        n = 5_000
        rng2 = np.random.default_rng(6)
        t = pa.table({
            "tags": pa.array(
                [None if i % 13 == 0 else
                 [None if rng2.random() < 0.1 else int(x)
                  for x in rng2.integers(0, 99, i % 6)]
                 for i in range(n)],
                pa.list_(pa.int32()),
            ),
            "names": pa.array(
                [None if i % 17 == 0 else [f"w{j}" for j in range(i % 4)]
                 for i in range(n)],
                pa.list_(pa.string()),
            ),
            "id": pa.array(range(n), pa.int64()),
        })
        path = str(tmp_path / "lists.parquet")
        pq.write_table(t, path, row_group_size=1_500, compression="snappy")
        with FileReader(path) as r:
            out = r.to_arrow()
        for c in t.column_names:
            assert out.column(c).to_pylist() == t.column(c).to_pylist(), c
        assert out.column("tags").type == pa.large_list(pa.int32())
        # required-outer lists (our writer) roundtrip too
        from parquet_tpu import FileWriter, parse_schema

        schema = parse_schema(
            "message m { required group l (LIST) "
            "{ repeated group list { required int64 element; } } }"
        )
        p2 = str(tmp_path / "req.parquet")
        with FileWriter(p2, schema) as w:
            w.write_rows([{"l": [1, 2]}, {"l": []}, {"l": [3]}])
        with FileReader(p2) as r:
            got = r.to_arrow().column("l").to_pylist()
        assert got == [[1, 2], [], [3]]

    def test_noncanonical_repeated_shape(self, tmp_path):
        """An optional group holding a bare repeated leaf has non-canonical
        level semantics; the nested builder assembles it (pyarrow oracle)."""
        import pyarrow.parquet as pq

        from parquet_tpu import FileWriter, parse_schema

        schema = parse_schema(
            "message m { required group a { optional group b "
            "{ repeated int32 c; } } }"
        )
        path = str(tmp_path / "odd.parquet")
        with FileWriter(path, schema) as w:
            w.write_rows([
                {"a": {"b": {"c": [5, 6]}}},
                {"a": {"b": {"c": []}}},
                {"a": {"b": None}},
            ])
        want = pq.read_table(path)
        with FileReader(path) as r:
            out = r.to_arrow()
        assert out.column("a").to_pylist() == want.column("a").to_pylist()

    def test_empty_groups_list_schema(self, tmp_path):
        import pyarrow as pa
        import pyarrow.parquet as pq

        t = pa.table({
            "tags": pa.array([[1]], pa.list_(pa.int32())),
            "names": pa.array([["x"]], pa.list_(pa.string())),
        })
        path = str(tmp_path / "els.parquet")
        pq.write_table(t, path)
        with FileReader(path) as r:
            empty = r.to_arrow(row_groups=[])
            assert empty.column_names == ["tags", "names"]
            assert empty.column("tags").type == pa.large_list(pa.int32())

    def test_legacy_list_of_struct(self, tmp_path):
        """A repeated group with several fields assembles as a struct whose
        repeated member is a list of structs (pyarrow oracle), and the
        zero-group schema agrees with the data branch."""
        import pyarrow.parquet as pq

        from parquet_tpu import FileWriter, parse_schema

        schema = parse_schema(
            "message m { optional group owner { repeated group contacts "
            "{ required binary name (UTF8); required int64 phone; } } }"
        )
        path = str(tmp_path / "los.parquet")
        with FileWriter(path, schema) as w:
            w.write_rows([
                {"owner": {"contacts": [{"name": "a", "phone": 1},
                                        {"name": "b", "phone": 2}]}},
                {"owner": {"contacts": []}},
                {"owner": None},
            ])
        want = pq.read_table(path)
        with FileReader(path) as r:
            out = r.to_arrow()
            empty = r.to_arrow(row_groups=[])
        assert out.column("owner").to_pylist() == want.column("owner").to_pylist()
        assert empty.num_rows == 0
        assert empty.column("owner").type == out.column("owner").type

    def test_fixed_list_elements_both_branches(self, tmp_path):
        """Fixed-width list elements route through the nested builder (the
        canonical-list fast path doesn't cover them); zero-group schema
        matches the data branch's type."""
        import pyarrow as pa
        import pyarrow.parquet as pq

        t = pa.table({
            "fl": pa.array(
                [[b"abcd", None], None, [b"efgh"]], pa.list_(pa.binary(4))
            ),
        })
        path = str(tmp_path / "fl.parquet")
        pq.write_table(t, path, use_dictionary=False)
        with FileReader(path) as r:
            out = r.to_arrow()
            empty = r.to_arrow(row_groups=[])
        assert out.column("fl").to_pylist() == t.column("fl").to_pylist()
        assert empty.column("fl").type == out.column("fl").type
        assert empty.column("fl").type == pa.large_list(pa.binary(4))

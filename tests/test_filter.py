"""Statistics-driven row-group pruning + exact row filtering.

The reference writes chunk statistics but never consumes them on read
(reference README.md:47); iter_rows(filters=...) prunes provably-excluded
row groups from the written min/max/null-count and re-checks surviving rows
exactly, so results are correct even with absent or coarse statistics.
"""

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from parquet_tpu.core.filter import FilterError
from parquet_tpu.core.reader import FileReader


@pytest.fixture(scope="module")
def sorted_file(tmp_path_factory):
    path = str(tmp_path_factory.mktemp("f") / "sorted.parquet")
    pq.write_table(
        pa.table(
            {
                "x": pa.array(np.arange(100_000, dtype=np.int64)),
                "s": pa.array([f"k{i:05d}" for i in range(100_000)]),
                "n": pa.array([None if i % 2 else float(i) for i in range(100_000)]),
            }
        ),
        path,
        row_group_size=20_000,
    )
    return path


class TestPruning:
    def test_range_prunes_to_matching_groups(self, sorted_file):
        with FileReader(sorted_file) as r:
            assert r.prune_row_groups([("x", ">=", 60_000)]) == [3, 4]
            assert r.prune_row_groups([("x", "<", 20_000)]) == [0]
            assert r.prune_row_groups([("x", "==", 50_000)]) == [2]
            assert r.prune_row_groups([("x", ">", 99_999)]) == []
            # strings prune lexicographically on the raw bytes
            assert r.prune_row_groups([("s", ">=", "k08000"), ("s", "<", "k08100")]) == [0]

    def test_null_ops(self, sorted_file):
        with FileReader(sorted_file) as r:
            assert r.prune_row_groups([("n", "is_null")]) == [0, 1, 2, 3, 4]
            assert r.prune_row_groups([("x", "is_null")]) == []  # no nulls written

    def test_exact_rows_after_pruning(self, sorted_file):
        with FileReader(sorted_file) as r:
            rows = list(r.iter_rows(filters=[("x", ">=", 39_998), ("x", "<", 40_003)]))
        assert [row["x"] for row in rows] == [39_998, 39_999, 40_000, 40_001, 40_002]

    def test_row_level_filtering_is_exact(self, sorted_file):
        with FileReader(sorted_file) as r:
            nn = [row["x"] for row in r.iter_rows(filters=[("n", "not_null"), ("x", "<", 10)])]
            assert nn == [0, 2, 4, 6, 8]
            assert sum(1 for _ in r.iter_rows(filters=[("n", "is_null"), ("x", "<", 100)])) == 50

    def test_unknown_column_and_op_rejected(self, sorted_file):
        with FileReader(sorted_file) as r:
            with pytest.raises(FilterError):
                r.prune_row_groups([("nope", "==", 1)])
            with pytest.raises(FilterError):
                r.prune_row_groups([("x", "~", 1)])

    def test_missing_statistics_never_prune(self, tmp_path):
        path = str(tmp_path / "nostats.parquet")
        pq.write_table(
            pa.table({"x": pa.array(np.arange(1000, dtype=np.int64))}),
            path,
            row_group_size=500,
            write_statistics=False,
        )
        with FileReader(path) as r:
            assert r.prune_row_groups([("x", "==", 5)]) == [0, 1]  # conservative
            rows = list(r.iter_rows(filters=[("x", "==", 5)]))
        assert [row["x"] for row in rows] == [5]  # still exact


class TestTypedFilters:
    def test_unsigned_int_column(self, tmp_path):
        """uint64 stats decode unsigned; values past 2^63 must not flip the
        bounds negative and prune matching groups."""
        path = str(tmp_path / "u.parquet")
        pq.write_table(pa.table({"u": pa.array([5, 2**63 + 10], pa.uint64())}), path)
        with FileReader(path) as r:
            rows = list(r.iter_rows(filters=[("u", "==", 5)]))
        assert [x["u"] for x in rows] == [5]

    def test_timestamp_column(self, tmp_path):
        import datetime as dt

        ts = [
            dt.datetime(2020, 1, 1, tzinfo=dt.timezone.utc) + dt.timedelta(hours=i)
            for i in range(1000)
        ]
        path = str(tmp_path / "ts.parquet")
        pq.write_table(
            pa.table({"ts": pa.array(ts, pa.timestamp("us", tz="UTC"))}),
            path,
            row_group_size=250,
        )
        with FileReader(path) as r:
            assert r.prune_row_groups([("ts", ">=", ts[800])]) == [3]
            got = [g["ts"] for g in r.iter_rows(filters=[("ts", ">=", ts[997])])]
        assert got == ts[997:]

    def test_date_and_decimal_columns(self, tmp_path):
        import datetime as dt
        import decimal

        path = str(tmp_path / "dd.parquet")
        pq.write_table(
            pa.table(
                {
                    "d": pa.array(
                        [dt.date(2020, 1, 1) + dt.timedelta(days=i) for i in range(100)]
                    ),
                    "dec": pa.array(
                        [decimal.Decimal(i) / 100 for i in range(100)], pa.decimal128(9, 2)
                    ),
                }
            ),
            path,
            row_group_size=25,
        )
        with FileReader(path) as r:
            assert r.prune_row_groups([("d", ">=", dt.date(2020, 3, 20))]) == [3]
            # binary-backed decimal: stats unprunable (conservative) but row
            # filtering stays exact
            got = list(r.iter_rows(filters=[("dec", ">=", decimal.Decimal("0.97"))]))
        assert len(got) == 3

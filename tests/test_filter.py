"""Statistics-driven row-group pruning + exact row filtering.

The reference writes chunk statistics but never consumes them on read
(reference README.md:47); iter_rows(filters=...) prunes provably-excluded
row groups from the written min/max/null-count and re-checks surviving rows
exactly, so results are correct even with absent or coarse statistics.
"""

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from parquet_tpu.core.filter import FilterError
from parquet_tpu.core.reader import FileReader


@pytest.fixture(scope="module")
def sorted_file(tmp_path_factory):
    path = str(tmp_path_factory.mktemp("f") / "sorted.parquet")
    pq.write_table(
        pa.table(
            {
                "x": pa.array(np.arange(100_000, dtype=np.int64)),
                "s": pa.array([f"k{i:05d}" for i in range(100_000)]),
                "n": pa.array([None if i % 2 else float(i) for i in range(100_000)]),
            }
        ),
        path,
        row_group_size=20_000,
    )
    return path


class TestPruning:
    def test_range_prunes_to_matching_groups(self, sorted_file):
        with FileReader(sorted_file) as r:
            assert r.prune_row_groups([("x", ">=", 60_000)]) == [3, 4]
            assert r.prune_row_groups([("x", "<", 20_000)]) == [0]
            assert r.prune_row_groups([("x", "==", 50_000)]) == [2]
            assert r.prune_row_groups([("x", ">", 99_999)]) == []
            # strings prune lexicographically on the raw bytes
            assert r.prune_row_groups([("s", ">=", "k08000"), ("s", "<", "k08100")]) == [0]

    def test_null_ops(self, sorted_file):
        with FileReader(sorted_file) as r:
            assert r.prune_row_groups([("n", "is_null")]) == [0, 1, 2, 3, 4]
            assert r.prune_row_groups([("x", "is_null")]) == []  # no nulls written

    def test_exact_rows_after_pruning(self, sorted_file):
        with FileReader(sorted_file) as r:
            rows = list(r.iter_rows(filters=[("x", ">=", 39_998), ("x", "<", 40_003)]))
        assert [row["x"] for row in rows] == [39_998, 39_999, 40_000, 40_001, 40_002]

    def test_row_level_filtering_is_exact(self, sorted_file):
        with FileReader(sorted_file) as r:
            nn = [row["x"] for row in r.iter_rows(filters=[("n", "not_null"), ("x", "<", 10)])]
            assert nn == [0, 2, 4, 6, 8]
            assert sum(1 for _ in r.iter_rows(filters=[("n", "is_null"), ("x", "<", 100)])) == 50

    def test_unknown_column_and_op_rejected(self, sorted_file):
        with FileReader(sorted_file) as r:
            with pytest.raises(FilterError):
                r.prune_row_groups([("nope", "==", 1)])
            with pytest.raises(FilterError):
                r.prune_row_groups([("x", "~", 1)])

    def test_missing_statistics_never_prune(self, tmp_path):
        path = str(tmp_path / "nostats.parquet")
        pq.write_table(
            pa.table({"x": pa.array(np.arange(1000, dtype=np.int64))}),
            path,
            row_group_size=500,
            write_statistics=False,
        )
        with FileReader(path) as r:
            assert r.prune_row_groups([("x", "==", 5)]) == [0, 1]  # conservative
            rows = list(r.iter_rows(filters=[("x", "==", 5)]))
        assert [row["x"] for row in rows] == [5]  # still exact


class TestTypedFilters:
    def test_unsigned_int_column(self, tmp_path):
        """uint64 stats decode unsigned; values past 2^63 must not flip the
        bounds negative and prune matching groups."""
        path = str(tmp_path / "u.parquet")
        pq.write_table(pa.table({"u": pa.array([5, 2**63 + 10], pa.uint64())}), path)
        with FileReader(path) as r:
            rows = list(r.iter_rows(filters=[("u", "==", 5)]))
        assert [x["u"] for x in rows] == [5]

    def test_timestamp_column(self, tmp_path):
        import datetime as dt

        ts = [
            dt.datetime(2020, 1, 1, tzinfo=dt.timezone.utc) + dt.timedelta(hours=i)
            for i in range(1000)
        ]
        path = str(tmp_path / "ts.parquet")
        pq.write_table(
            pa.table({"ts": pa.array(ts, pa.timestamp("us", tz="UTC"))}),
            path,
            row_group_size=250,
        )
        with FileReader(path) as r:
            assert r.prune_row_groups([("ts", ">=", ts[800])]) == [3]
            got = [g["ts"] for g in r.iter_rows(filters=[("ts", ">=", ts[997])])]
        assert got == ts[997:]

    def test_decimal_int_backed_directional_rounding(self, tmp_path):
        """Inexact filter values must not round toward the value when pruning:
        DECIMAL(9,2) group holding 1.01; '> 1.006' must keep the group (half-
        even to_integral_value would coerce 100.6 -> 101 and wrongly prune)."""
        import decimal

        from parquet_tpu.core.writer import FileWriter
        from parquet_tpu.schema.dsl import parse_schema

        path = str(tmp_path / "dec.parquet")
        schema = parse_schema("message m { required int32 x (DECIMAL(9,2)); }")
        with FileWriter(path, schema) as w:
            w.write_rows([{"x": 101}])  # unscaled: 1.01
        with FileReader(path) as r:
            assert r.prune_row_groups([("x", ">", decimal.Decimal("1.006"))]) == [0]
            got = [v["x"] for v in r.iter_rows(filters=[("x", ">", decimal.Decimal("1.006"))])]
            assert got == [decimal.Decimal("1.01")]
            # other direction: '< 1.014' must also keep the group and match
            assert r.prune_row_groups([("x", "<", decimal.Decimal("1.014"))]) == [0]
            got = [v["x"] for v in r.iter_rows(filters=[("x", "<", decimal.Decimal("1.014"))])]
            assert got == [decimal.Decimal("1.01")]
            # '==' an unrepresentable value proves emptiness — prune to nothing
            assert r.prune_row_groups([("x", "==", decimal.Decimal("1.006"))]) == []

    def test_timestamp_millis_directional_rounding(self, tmp_path):
        """TIMESTAMP(MILLIS) group holding t=1ms; '< 1.5ms' must keep the
        group (floor division would coerce to 1 and 'lo >= value' prune)."""
        import datetime as dt

        from parquet_tpu.core.writer import FileWriter
        from parquet_tpu.schema.dsl import parse_schema

        path = str(tmp_path / "ms.parquet")
        schema = parse_schema("message m { required int64 ts (TIMESTAMP_MILLIS); }")
        with FileWriter(path, schema) as w:
            w.write_rows([{"ts": 1}])  # 1ms after epoch
        t_15 = dt.datetime(1970, 1, 1, 0, 0, 0, 1500, tzinfo=dt.timezone.utc)
        with FileReader(path) as r:
            assert r.prune_row_groups([("ts", "<", t_15)]) == [0]
            assert sum(1 for _ in r.iter_rows(filters=[("ts", "<", t_15)])) == 1
            # and '>' just under the stored value keeps the group too
            t_05 = dt.datetime(1970, 1, 1, 0, 0, 0, 500, tzinfo=dt.timezone.utc)
            assert r.prune_row_groups([("ts", ">", t_05)]) == [0]
            assert sum(1 for _ in r.iter_rows(filters=[("ts", ">", t_05)])) == 1

    def test_legacy_unsigned_stats_never_prune(self):
        """Deprecated min/max were written with signed comparison: for a
        legacy UINT32 chunk holding {1, 0x80000000} they store min=2^31,
        max=1 — decoded unsigned the bounds invert, so they are unusable."""
        import struct

        from parquet_tpu.core.filter import normalize_filters, row_group_may_match
        from parquet_tpu.meta.parquet_types import (
            ColumnChunk,
            ColumnMetaData,
            RowGroup,
            Statistics,
        )
        from parquet_tpu.schema.dsl import parse_schema

        schema = parse_schema("message m { required int32 u (UINT_32); }")
        st = Statistics(
            min=struct.pack("<I", 0x80000000), max=struct.pack("<I", 1), null_count=0
        )
        rg = RowGroup(
            columns=[
                ColumnChunk(
                    meta_data=ColumnMetaData(
                        path_in_schema=["u"], num_values=2, statistics=st
                    )
                )
            ],
            num_rows=2,
        )
        normalized = normalize_filters(schema, [("u", "==", 1)])
        assert row_group_may_match(rg, normalized)  # must NOT prune
        # with modern min_value/max_value the same bytes ARE unsigned-ordered
        st2 = Statistics(
            min_value=struct.pack("<I", 1),
            max_value=struct.pack("<I", 0x80000000),
            null_count=0,
        )
        rg.columns[0].meta_data.statistics = st2
        assert row_group_may_match(rg, normalize_filters(schema, [("u", "==", 1)]))
        assert not row_group_may_match(
            rg, normalize_filters(schema, [("u", "==", 2**31 + 5)])
        )

    def test_time_sub_microsecond_filter_value(self, tmp_path):
        """A nanos-precision filter value on a TIME(MICROS) column must
        compare exactly in both the stat and row domains (truncating it to
        dt.time would flip '<' and '==')."""
        from parquet_tpu.core.writer import FileWriter
        from parquet_tpu.floor.time import Time
        from parquet_tpu.schema.dsl import parse_schema

        path = str(tmp_path / "t.parquet")
        schema = parse_schema("message m { required int64 t (TIME_MICROS); }")
        with FileWriter(path, schema) as w:
            w.write_rows([{"t": 1}])  # 1 microsecond after midnight
        with FileReader(path) as r:
            got = list(r.iter_rows(filters=[("t", "<", Time.from_nanos(1500))]))
            assert len(got) == 1  # 1000ns < 1500ns
            assert list(r.iter_rows(filters=[("t", ">", Time.from_nanos(1500))])) == []
            # '==' an unrepresentable instant matches nothing
            assert list(r.iter_rows(filters=[("t", "==", Time.from_nanos(1500))])) == []

    def test_nonfinite_and_bogus_values_raise_filter_error(self, sorted_file):
        import decimal

        with FileReader(sorted_file) as r:
            for bad in (
                decimal.Decimal("Infinity"),
                decimal.Decimal("NaN"),
                float("nan"),
                float("inf"),
                object(),
            ):
                with pytest.raises(FilterError):
                    r.prune_row_groups([("x", ">", bad)])
            # numeric strings keep working on integer columns
            assert r.prune_row_groups([("x", "==", "50000")]) == [2]

    def test_unsigned_string_value_and_decimal_nonfinite(self, tmp_path):
        import decimal

        pq.write_table(
            pa.table({"u": pa.array([5, 9], pa.uint64())}), str(tmp_path / "u.parquet")
        )
        with FileReader(str(tmp_path / "u.parquet")) as r:
            assert [x["u"] for x in r.iter_rows(filters=[("u", "==", "5")])] == [5]
            with pytest.raises(FilterError):
                r.prune_row_groups([("u", "==", -1)])
        # int-backed DECIMAL: non-finite values must raise FilterError too
        from parquet_tpu.core.writer import FileWriter
        from parquet_tpu.schema.dsl import parse_schema

        path = str(tmp_path / "dnf.parquet")
        with FileWriter(path, parse_schema("message m { required int32 x (DECIMAL(9,2)); }")) as w:
            w.write_rows([{"x": 101}])
        with FileReader(path) as r:
            for bad in (decimal.Decimal("NaN"), decimal.Decimal("Infinity"), float("inf")):
                with pytest.raises(FilterError):
                    r.prune_row_groups([("x", ">", bad)])

    def test_raw_rows_with_filters_rejected(self, sorted_file):
        """raw=True rows are wire-shaped; the converted-domain predicate
        cannot be applied to them (mirrors floor.Reader's unmarshal-only
        pruning)."""
        with FileReader(sorted_file) as r:
            with pytest.raises(FilterError):
                next(r.iter_rows(raw=True, filters=[("x", "==", 1)]))

    def test_date_and_decimal_columns(self, tmp_path):
        import datetime as dt
        import decimal

        path = str(tmp_path / "dd.parquet")
        pq.write_table(
            pa.table(
                {
                    "d": pa.array(
                        [dt.date(2020, 1, 1) + dt.timedelta(days=i) for i in range(100)]
                    ),
                    "dec": pa.array(
                        [decimal.Decimal(i) / 100 for i in range(100)], pa.decimal128(9, 2)
                    ),
                }
            ),
            path,
            row_group_size=25,
        )
        with FileReader(path) as r:
            assert r.prune_row_groups([("d", ">=", dt.date(2020, 3, 20))]) == [3]
            # binary-backed decimal: stats unprunable (conservative) but row
            # filtering stays exact
            got = list(r.iter_rows(filters=[("dec", ">=", decimal.Decimal("0.97"))]))
        assert len(got) == 3


class TestTruncatedBinaryStats:
    def test_long_binary_stats_truncated_not_dropped(self, tmp_path):
        """Values past 64 bytes used to void min/max entirely; now they
        truncate (max: increment-last-byte) with exactness flags, so range
        pruning keeps working on long strings."""
        from parquet_tpu import FileReader, FileWriter, parse_schema

        schema = parse_schema("message m { required binary s (UTF8); }")
        long = ["x" * 100 + f"{i:04d}" for i in range(1000)]
        path = str(tmp_path / "long.parquet")
        with FileWriter(
            path, schema, write_page_index=True, use_dictionary=False
        ) as w:
            w.write_column("s", long)
        with FileReader(path) as r:
            st = r.row_group(0).columns[0].meta_data.statistics
            assert st.min_value == b"x" * 64
            assert st.max_value == b"x" * 63 + b"y"  # truncated + incremented
            assert st.is_min_value_exact is False
            assert st.is_max_value_exact is False
            assert st.min is None and st.max is None  # legacy has no flags
            # pruning with truncated bounds stays conservative + useful
            assert list(r.iter_rows(filters=[("s", "==", "zzz")])) == []
            got = list(r.iter_rows(filters=[("s", "==", long[77])]))
            assert got == [{"s": long[77]}]
            # page index survives too (used to be voided)
            ci, _ = r.read_page_index(0)[("s",)]
            assert ci is not None
        import pyarrow.parquet as pq

        assert pq.read_table(path).column("s").to_pylist() == long

    def test_all_ff_prefix_max_dropped(self):
        from parquet_tpu.core.stats import _truncate_max, _truncate_min

        assert _truncate_max(b"\xff" * 70) == (None, False)
        assert _truncate_max(b"a" * 70)[0] == b"a" * 63 + b"b"
        assert _truncate_max(b"a" * 63 + b"\xff" + b"q" * 10)[0] == b"a" * 62 + b"b"
        assert _truncate_min(b"m" * 70) == (b"m" * 64, False)
        assert _truncate_min(b"short") == (b"short", True)


class TestFilterOutsideProjection:
    def test_filter_column_projected_out_still_applies(self, tmp_path):
        """A predicate on a column outside the projection must FILTER (decode
        it transiently, strip it from output) — not silently return nothing."""
        import numpy as np

        from parquet_tpu import FileReader, FileWriter, parse_schema

        schema = parse_schema(
            "message m { required int64 id; required binary s (UTF8); }"
        )
        path = str(tmp_path / "proj.parquet")
        with FileWriter(path, schema, write_page_index=True) as w:
            w.write_column("id", np.arange(100, dtype=np.int64))
            w.write_column("s", [f"v{i % 5}" for i in range(100)])
        with FileReader(path, columns=["id"]) as r:
            rows = list(r.iter_rows(filters=[("s", "==", "v3")]))
            assert [row["id"] for row in rows] == list(range(3, 100, 5))
            assert all(set(row) == {"id"} for row in rows)  # s stripped
        # and with the column IN the projection, it stays in the rows
        with FileReader(path) as r:
            rows = list(r.iter_rows(filters=[("s", "==", "v3")]))
            assert all(set(row) == {"id", "s"} for row in rows)

    def test_shared_root_and_mixed_missing_leaves(self, tmp_path):
        """Leaf-granular stripping: a filter on g.c with g.b projected keeps
        g.b rows (and strips only c); an extra whole-root filter column
        vanishes entirely (review regressions)."""
        from parquet_tpu import FileReader, FileWriter, parse_schema

        schema = parse_schema(
            "message m { required group g { required int64 b; required int64 c; } "
            "required int64 x; }"
        )
        path = str(tmp_path / "shared.parquet")
        with FileWriter(path, schema) as w:
            w.write_rows(
                [{"g": {"b": i, "c": i % 3}, "x": i} for i in range(30)]
            )
        with FileReader(path, columns=["g.b"]) as r:
            rows = list(r.iter_rows(filters=[("g.c", "==", 1)]))
            assert [row["g"]["b"] for row in rows] == list(range(1, 30, 3))
            assert all(set(row["g"]) == {"b"} for row in rows)  # c stripped
            rows = list(
                r.iter_rows(filters=[("g.c", "==", 1), ("x", ">=", 10)])
            )
            assert [row["g"]["b"] for row in rows] == list(range(10, 30, 3))
            assert all(set(row) == {"g"} for row in rows)  # x stripped
            assert all(set(row["g"]) == {"b"} for row in rows)


class TestInOperator:
    def test_in_and_not_in_with_full_pruning_stack(self, tmp_path):
        from parquet_tpu import FileReader, FileWriter, parse_schema

        schema = parse_schema(
            "message m { required int64 id; required binary city (UTF8); }"
        )
        path = str(tmp_path / "in.parquet")
        with FileWriter(
            path, schema, write_page_index=True, bloom_filters=["id"],
            row_group_size=1 << 30, use_dictionary=False,
        ) as w:
            for base in (0, 100_000):
                w.write_column(
                    "id", np.arange(base, base + 5_000, 2, dtype=np.int64)
                )
                w.write_column(
                    "city", [f"c{(base + i) % 7}" for i in range(0, 5_000, 2)]
                )
                w.flush_row_group()
        with FileReader(path) as r:
            got = [row["id"] for row in r.iter_rows(filters=[("id", "in", [4, 100_002, 99])])]
            assert got == [4, 100_002]  # 99 is odd: absent
            # strings, set form
            rows = list(r.iter_rows(filters=[("city", "in", {"c3"}), ("id", "<", 50)]))
            assert all(row["city"] == "c3" for row in rows) and rows
            # not_in is exact
            n_all = sum(1 for _ in r.iter_rows())
            n_in = sum(1 for _ in r.iter_rows(filters=[("city", "in", ["c0", "c1"])]))
            n_out = sum(1 for _ in r.iter_rows(filters=[("city", "not_in", ["c0", "c1"])]))
            assert n_in + n_out == n_all and n_in and n_out
            # empty in-list matches nothing
            assert list(r.iter_rows(filters=[("id", "in", [])])) == []
            # stats pruning: members all in group 2's range -> group 1 skipped
            assert r.prune_row_groups([("id", "in", [100_002, 100_004])]) == [1]
            # bloom pruning: all members odd (absent) but inside [min, max]
            assert r.prune_row_groups([("id", "in", [101, 103])]) == []
            # bad value shape rejected
            with pytest.raises(FilterError):
                r.prune_row_groups([("id", "in", 5)])


class TestDnfOrFilters:
    def test_or_of_ands_full_stack(self, tmp_path):
        """pyarrow's DNF convention: a list of LISTS of triples is an OR of
        conjunctions, pruned per-conjunction and unioned."""
        from parquet_tpu import FileReader, FileWriter, parse_schema

        schema = parse_schema(
            "message m { required int64 id; required binary c (UTF8); }"
        )
        path = str(tmp_path / "dnf.parquet")
        with FileWriter(
            path, schema, write_page_index=True, bloom_filters=["id"],
            use_dictionary=False, max_page_size=8_192,
        ) as w:
            for base in (0, 1_000_000, 2_000_000):
                w.write_column("id", np.arange(base, base + 10_000, dtype=np.int64))
                w.write_column("c", [f"c{(base + i) % 4}" for i in range(10_000)])
                w.flush_row_group()
        with FileReader(path) as r:
            # OR across distant groups
            got = [
                row["id"]
                for row in r.iter_rows(
                    filters=[[("id", "<", 3)], [("id", ">=", 2_009_997)]]
                )
            ]
            assert got == [0, 1, 2, 2_009_997, 2_009_998, 2_009_999]
            # group pruning is the union of the conjunctions' groups
            assert r.prune_row_groups(
                [[("id", "==", 5)], [("id", "==", 1_000_005)]]
            ) == [0, 1]
            # page ranges union within one group
            ranges = r.prune_pages(
                0, [[("id", "<", 10)], [("id", ">=", 9_990)]]
            )
            covered = sum(e - s for s, e in ranges)
            assert 0 < covered < 10_000 and len(ranges) == 2
            # AND inside each conjunct still applies
            got = list(
                r.iter_rows(
                    filters=[
                        [("id", "<", 8), ("c", "==", "c1")],
                        [("id", "in", [1_000_001])],
                    ]
                )
            )
            assert [row["id"] for row in got] == [1, 5, 1_000_001]
            # flat form still means one conjunction
            assert len(list(r.iter_rows(filters=[("id", "==", 7)]))) == 1
            # empty conjunction in DNF form rejected
            with pytest.raises(FilterError):
                r.prune_row_groups([[("id", "==", 1)], []])

    def test_dnf_device_batches(self, tmp_path):
        from parquet_tpu import FileReader, FileWriter, parse_schema

        schema = parse_schema("message m { required int64 id; }")
        path = str(tmp_path / "dnfdev.parquet")
        with FileWriter(path, schema, use_dictionary=False) as w:
            for base in (0, 50_000, 100_000):
                w.write_column("id", np.arange(base, base + 4_096, dtype=np.int64))
                w.flush_row_group()
        with FileReader(path) as r:
            batches = list(
                r.iter_device_batches(
                    4_096,
                    filters=[[("id", "<", 10)], [("id", ">=", 100_000)]],
                )
            )
            assert len(batches) == 2  # groups 0 and 2, group 1 pruned

    def test_generator_filters_and_json_list_triples(self, tmp_path):
        """Review regressions: generator filters must not be silently
        consumed into a match-all, and JSON-style list triples stay a flat
        conjunction."""
        from parquet_tpu import FileReader, FileWriter, parse_schema

        schema = parse_schema("message m { required int64 id; }")
        path = str(tmp_path / "gen.parquet")
        with FileWriter(path, schema) as w:
            w.write_column("id", np.arange(10, dtype=np.int64))
        with FileReader(path) as r:
            got = [row["id"] for row in r.iter_rows(
                filters=(f for f in [("id", "==", 3)])
            )]
            assert got == [3]
            got = [row["id"] for row in r.iter_rows(filters=[["id", "==", 4]])]
            assert got == [4]  # list-triple == flat conjunction
            got = [row["id"] for row in r.iter_rows(
                filters=[[["id", "==", 1]], [["id", "==", 8]]]
            )]
            assert got == [1, 8]  # DNF with list-triples

    def test_time_in_list_mixed_domains(self, tmp_path):
        """TIME in-lists mixing sub-microsecond (Time) and whole-microsecond
        (dt.time) members must match regardless of member order."""
        import datetime as dt

        from parquet_tpu import FileReader, FileWriter, parse_schema
        from parquet_tpu.floor.time import Time

        schema = parse_schema("message m { required int64 t (TIME_MICROS); }")
        path = str(tmp_path / "time_in.parquet")
        with FileWriter(path, schema) as w:
            w.write_rows([{"t": 3_600_000_000}])  # 01:00:00
        members_a = [Time.from_nanos(500), dt.time(1, 0, 0)]
        members_b = [dt.time(1, 0, 0), Time.from_nanos(500)]
        with FileReader(path) as r:
            for members in (members_a, members_b):
                got = list(r.iter_rows(filters=[("t", "in", members)]))
                assert len(got) == 1, members
                assert list(r.iter_rows(filters=[("t", "not_in", members)])) == []


class TestFilterCombineMemo:
    def test_column_in_many_conjunctions_combines_once(self, tmp_path, monkeypatch):
        """In the pyarrow-compute FALLBACK path a column referenced in N
        DNF conjunctions must pay its combine_chunks exactly once per mask
        evaluation (pinned by the filter_combine_chunks trace counter).
        The vectorized fast path (PR 12) masks straight off the decoded
        chunk buffers and never combines at all — pinned as zero."""
        from parquet_tpu import FileReader, FileWriter, parse_schema
        from parquet_tpu.utils.trace import decode_trace

        schema = parse_schema(
            "message m { required int64 id; required binary c (UTF8); }"
        )
        path = str(tmp_path / "memo.parquet")
        with FileWriter(path, schema, use_dictionary=False) as w:
            for base in (0, 10_000):
                w.write_column("id", np.arange(base, base + 10_000, dtype=np.int64))
                w.write_column("c", [f"c{(base + i) % 5}" for i in range(10_000)])
                w.flush_row_group()
        filters = [
            [("id", "<", 5), ("c", "==", "c1")],
            [("id", ">=", 19_998)],
            [("id", "in", [7, 8]), ("c", "!=", "c0")],
        ]
        want = sorted([1, 7, 8, 19_998, 19_999])
        with FileReader(path) as r:
            with decode_trace() as tr:
                got = r.to_arrow(filters=filters)
            assert sorted(got.column("id").to_pylist()) == want
        # fast path: no table-level masks, so no combines at all
        combines = tr.stages.get("filter_combine_chunks")
        assert combines is None
        monkeypatch.setenv("PQT_VEC_FILTER", "0")
        with FileReader(path) as r:
            with decode_trace() as tr:
                got = r.to_arrow(filters=filters)
            assert sorted(got.column("id").to_pylist()) == want
        combines = tr.stages.get("filter_combine_chunks")
        # two distinct leaves referenced across five predicates: two combines
        assert combines is not None and combines.calls == 2

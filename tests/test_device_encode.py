"""Device ENCODE kernels pinned byte-identical to the host encoders.

The write-side mirror of the decode pins: bitpack_encode_device /
rle_hybrid_encode_device / dict_indices_device are the jittable inverses of
ops/bitpack.py, ops/rle_hybrid.py and the dictionary probes, and
kernels/pipeline.encode_device_column materializes a device-resident numeric
column into parquet pages whose bytes must equal sink.encoder.encode_chunk's
for the same values. Runs under CPU jax (the differential contract is
platform-independent: same bytes everywhere).
"""

import numpy as np
import pytest

from parquet_tpu.core.column_store import ColumnChunkBuilder
from parquet_tpu.kernels.device_ops import (
    bitpack_encode_device,
    dict_indices_device,
    rle_hybrid_encode_device,
)
from parquet_tpu.kernels.pipeline import (
    assemble_hybrid_device_stream,
    encode_device_column,
)
from parquet_tpu.ops.bitpack import pack_bits
from parquet_tpu.ops.rle_hybrid import encode_hybrid
from parquet_tpu.schema.dsl import parse_schema
from parquet_tpu.sink.encoder import EncoderConfig, encode_chunk

import jax.numpy as jnp


def _device_hybrid_bytes(values: np.ndarray, width: int) -> bytes:
    v = jnp.asarray(values.astype(np.uint32))
    in_rle, rle_break, packed, _n_bp = rle_hybrid_encode_device(v, width)
    return assemble_hybrid_device_stream(
        np.asarray(in_rle),
        np.asarray(rle_break),
        np.asarray(packed),
        width,
        lambda p: int(values[p]),
    )


class TestBitpackEncodeDevice:
    @pytest.mark.parametrize("width", [1, 2, 3, 5, 8, 13, 16])
    def test_matches_pack_bits(self, width):
        rng = np.random.default_rng(width)
        n = 8 * 97  # whole groups (the hybrid format's contract)
        vals = rng.integers(0, 1 << width, n).astype(np.uint32)
        words = np.asarray(bitpack_encode_device(jnp.asarray(vals), width))
        got = memoryview(words).cast("B")[: (n * width + 7) // 8]
        assert bytes(got) == pack_bits(vals, width)

    def test_zero_width_and_empty(self):
        assert (
            np.asarray(bitpack_encode_device(jnp.zeros(8, jnp.uint32), 0)).sum()
            == 0
        )
        np.asarray(bitpack_encode_device(jnp.zeros(0, jnp.uint32), 4))


class TestHybridEncodeDevice:
    @pytest.mark.parametrize(
        "maker",
        [
            lambda rng, n: rng.integers(0, 7, n),  # low width, no long runs
            lambda rng, n: np.repeat(
                rng.integers(0, 50, n // 20 + 1), 20
            )[:n],  # long runs -> RLE windows
            lambda rng, n: np.full(n, 3),  # one giant RLE run
            lambda rng, n: np.arange(n) % 1000,  # no runs at all
            lambda rng, n: np.concatenate(
                [np.zeros(5), np.full(40, 9), rng.integers(0, 100, max(n - 45, 0))]
            )[:n],  # unaligned run start (8-alignment arithmetic)
        ],
    )
    @pytest.mark.parametrize("n", [1, 7, 8, 65, 4096])
    def test_matches_encode_hybrid(self, maker, n):
        rng = np.random.default_rng(n)
        vals = np.asarray(maker(rng, n)).astype(np.uint32)
        width = max(int(vals.max()).bit_length(), 1)
        assert _device_hybrid_bytes(vals, width) == encode_hybrid(vals, width)

    def test_width_zero_stream(self):
        vals = np.zeros(123, dtype=np.uint32)
        assert _device_hybrid_bytes(vals, 0) == encode_hybrid(vals, 0)


class TestDictIndicesDevice:
    @pytest.mark.parametrize("seed", range(5))
    def test_first_occurrence_order(self, seed):
        rng = np.random.default_rng(seed)
        n = int(rng.integers(1, 3000))
        vals = rng.integers(0, int(rng.integers(2, 400)), n).astype(np.int64)
        idx, firsts, nu = dict_indices_device(jnp.asarray(vals))
        idx, firsts, nu = np.asarray(idx), np.asarray(firsts), int(nu)
        # reference: plain first-occurrence probe
        seen: dict = {}
        ref_idx = np.empty(n, dtype=np.int64)
        ref_firsts = []
        for i, v in enumerate(vals.tolist()):
            if v not in seen:
                seen[v] = len(seen)
                ref_firsts.append(i)
            ref_idx[i] = seen[v]
        assert nu == len(seen)
        assert np.array_equal(idx, ref_idx)
        assert np.array_equal(firsts[:nu], np.asarray(ref_firsts))

    def test_float_bit_patterns(self):
        # NaN payloads dedup by bits, like the host probe
        vals = np.array([1.0, np.nan, 1.0, -0.0, 0.0, np.nan], dtype=np.float64)
        bits = vals.view(np.uint64)
        idx, firsts, nu = dict_indices_device(jnp.asarray(bits))
        assert int(nu) == 4  # 1.0, nan, -0.0, +0.0
        assert np.asarray(idx).tolist() == [0, 1, 0, 2, 3, 1]


class TestEncodeDeviceColumn:
    def _cfg(self, **kw):
        base = dict(
            codec=0,
            data_page_version=1,
            max_page_size=1 << 20,
            with_crc=False,
            write_page_index=False,
            column_encodings={},
            bloom_specs={},
        )
        base.update(kw)
        return EncoderConfig(**base)

    def _host_chunk(self, column, values, cfg):
        b = ColumnChunkBuilder(column, True)
        b.set_columnar(values)
        return encode_chunk(cfg, b, None)

    @pytest.mark.parametrize("codec", [0, 1])  # uncompressed, snappy
    @pytest.mark.parametrize("dpv", [1, 2])
    def test_dict_int64_byte_identical(self, codec, dpv):
        schema = parse_schema("message m { required int64 a; }")
        column = schema.column("a")
        rng = np.random.default_rng(7)
        vals = rng.integers(0, 300, 50_000).astype(np.int64)
        cfg = self._cfg(codec=codec, data_page_version=dpv)
        host = self._host_chunk(column, vals, cfg)
        dev = encode_device_column(column, jnp.asarray(vals), cfg)
        assert b"".join(bytes(p) for p in dev.parts) == b"".join(
            bytes(p) for p in host.parts
        )
        assert dev.nbytes == host.nbytes
        assert dev.chunk.meta_data.dumps() == host.chunk.meta_data.dumps()

    def test_plain_double_and_crc(self):
        schema = parse_schema("message m { required double x; }")
        column = schema.column("x")
        vals = np.random.default_rng(9).random(20_000)  # all-unique: no dict
        cfg = self._cfg(codec=1, with_crc=True, max_page_size=1 << 15)
        host = self._host_chunk(column, vals, cfg)
        dev = encode_device_column(column, jnp.asarray(vals), cfg)
        assert b"".join(bytes(p) for p in dev.parts) == b"".join(
            bytes(p) for p in host.parts
        )
        assert dev.chunk.meta_data.dumps() == host.chunk.meta_data.dumps()

    def test_multi_page_dict_stream(self):
        schema = parse_schema("message m { required int32 v; }")
        column = schema.column("v")
        rng = np.random.default_rng(3)
        # repeats + runs across page boundaries, tiny pages
        vals = np.repeat(rng.integers(0, 40, 3000), 4)[:10_000].astype(np.int32)
        cfg = self._cfg(codec=1, max_page_size=4096)
        host = self._host_chunk(column, vals, cfg)
        dev = encode_device_column(column, jnp.asarray(vals), cfg)
        assert b"".join(bytes(p) for p in dev.parts) == b"".join(
            bytes(p) for p in host.parts
        )

    def test_rejects_nested_and_optional(self):
        schema = parse_schema("message m { optional int64 a; }")
        with pytest.raises(ValueError):
            encode_device_column(
                schema.column("a"), jnp.zeros(4, jnp.int64), self._cfg()
            )

"""Process-wide metrics registry tests: always-on counting with NO trace
active, snapshot/delta semantics, histogram accounting, Prometheus text
exposition, bump() dual-reporting, and the meta summary helper."""

import re

import numpy as np
import pytest

from parquet_tpu.core.reader import FileReader
from parquet_tpu.core.writer import FileWriter
from parquet_tpu.meta.parquet_types import Type
from parquet_tpu.schema.builder import message, required, string
from parquet_tpu.utils import metrics
from parquet_tpu.utils.trace import active, bump


@pytest.fixture(scope="module")
def sample(tmp_path_factory):
    path = str(tmp_path_factory.mktemp("metrics") / "m.parquet")
    schema = message(required("id", Type.INT64), required("name", string()))
    with FileWriter(path, schema, codec="snappy") as w:
        w.write_column("id", np.arange(3000, dtype=np.int64))
        w.write_column("name", [f"n{i % 41}" for i in range(3000)])
    return path


class TestAlwaysOn:
    def test_plain_read_reports_pages_bytes_encodings(self, sample):
        """The acceptance bar: nonzero page/byte/encoding counters after a
        plain FileReader read with NO trace active."""
        assert not active()
        snap0 = metrics.snapshot()
        with FileReader(sample) as r:
            for i in range(r.num_row_groups):
                r.read_row_group(i)
        d = metrics.delta(snap0)
        page_keys = [k for k in d if k.startswith("pages_decoded_total")]
        assert page_keys and all(d[k] > 0 for k in page_keys)
        # encoding labels are real parquet encoding names
        assert any(
            'encoding="PLAIN"' in k or 'encoding="RLE_DICTIONARY"' in k
            or 'encoding="PLAIN_DICTIONARY"' in k
            for k in page_keys
        ), page_keys
        assert sum(
            v for k, v in d.items() if k.startswith("bytes_compressed_total")
        ) > 0
        assert sum(
            v for k, v in d.items() if k.startswith("bytes_uncompressed_total")
        ) > 0
        assert d.get("chunk_decode_seconds_count", 0) >= 2  # one per chunk
        assert d.get("chunk_decode_seconds_sum", 0) > 0

    def test_device_plan_read_also_reports(self, sample):
        snap0 = metrics.snapshot()
        with FileReader(sample, backend="tpu_roundtrip") as r:
            r.read_row_group(0)
        d = metrics.delta(snap0)
        assert any(k.startswith("pages_decoded_total") for k in d), d
        assert sum(
            v for k, v in d.items() if k.startswith("bytes_uncompressed_total")
        ) > 0


class TestSnapshotDelta:
    def test_counter_delta_exact(self):
        s0 = metrics.snapshot()
        metrics.inc("pqt_test_counter_total", 3, kind="x")
        metrics.inc("pqt_test_counter_total", 2, kind="x")
        d = metrics.delta(s0)
        assert d['pqt_test_counter_total{kind="x"}'] == 5

    def test_delta_omits_unchanged(self):
        metrics.inc("pqt_test_quiet_total", 1)
        s0 = metrics.snapshot()
        assert metrics.delta(s0) == {}

    def test_delta_skips_hist_min_max(self):
        s0 = metrics.snapshot()
        metrics.observe("pqt_test_seconds", 0.25)
        d = metrics.delta(s0)
        assert d["pqt_test_seconds_count"] == 1
        assert d["pqt_test_seconds_sum"] == pytest.approx(0.25)
        assert not any(
            k.startswith("pqt_test_seconds_min")
            or k.startswith("pqt_test_seconds_max")
            for k in d
        )

    def test_histogram_snapshot_min_max(self):
        metrics.observe("pqt_test_hist2", 0.5)
        metrics.observe("pqt_test_hist2", 1.5)
        s = metrics.snapshot()
        assert s["pqt_test_hist2_count"] >= 2
        assert s["pqt_test_hist2_min"] <= 0.5
        assert s["pqt_test_hist2_max"] >= 1.5

    def test_get(self):
        metrics.inc("pqt_test_get_total", 7, who="me")
        assert metrics.get("pqt_test_get_total", who="me") == 7
        assert metrics.get("pqt_test_get_total", who="nobody") == 0


class TestBumpDualReport:
    def test_bump_counts_without_trace(self):
        assert not active()
        before = metrics.get("events_total", event="pqt_test_event")
        bump("pqt_test_event")
        bump("pqt_test_event")
        assert metrics.get("events_total", event="pqt_test_event") == before + 2


class TestPrometheus:
    def test_exposition_format(self):
        metrics.inc("pqt_test_prom_total", 4, encoding="PLAIN")
        metrics.observe("pqt_test_prom_seconds", 0.02)
        text = metrics.render_prometheus()
        assert "# TYPE parquet_tpu_pqt_test_prom_total counter" in text
        assert 'parquet_tpu_pqt_test_prom_total{encoding="PLAIN"} ' in text
        assert "# TYPE parquet_tpu_pqt_test_prom_seconds histogram" in text
        assert 'le="+Inf"' in text
        assert text.endswith("\n")
        # every sample line is "name{labels} value" with a numeric value
        for line in text.splitlines():
            if line.startswith("#") or not line:
                continue
            m = re.match(r"^parquet_tpu_\S+ (\S+)$", line)
            assert m, line
            float(m.group(1))

    def test_histogram_bucket_counts_cumulative(self):
        metrics.observe("pqt_test_buckets", 0.0001)
        metrics.observe("pqt_test_buckets", 100.0)
        text = metrics.render_prometheus()
        lines = [
            line for line in text.splitlines() if "pqt_test_buckets_bucket" in line
        ]
        counts = [int(line.rsplit(" ", 1)[1]) for line in lines]
        assert counts == sorted(counts)  # cumulative
        assert counts[-1] >= 1  # +Inf sees everything... via _count below
        assert "parquet_tpu_pqt_test_buckets_count 2" in text


class TestExpositionGolden:
    """The exposition-correctness contract on a FRESH registry (the
    process-wide one accumulates across the test run): label values are
    escaped per the Prometheus text format, histogram `le` bounds render
    as plain decimals, and documented families carry `# HELP` lines."""

    def test_golden_document(self):
        reg = metrics.MetricsRegistry()
        reg.inc("io_retries_total", 2, reason='back\\slash"quote\nnewline')
        reg.set("pool_queue_depth", 3, pool="pqt-io")
        reg.observe("chunk_decode_seconds", 0.002)
        reg.observe("chunk_decode_seconds", 2.0)
        assert reg.render_prometheus() == (
            '# HELP parquet_tpu_io_retries_total failed source attempts absorbed by the retry ladder\n'
            '# TYPE parquet_tpu_io_retries_total counter\n'
            'parquet_tpu_io_retries_total{reason="back\\\\slash\\"quote\\nnewline"} 2\n'
            '# HELP parquet_tpu_pool_queue_depth tasks submitted to a pqt-* pool and not yet running\n'
            '# TYPE parquet_tpu_pool_queue_depth gauge\n'
            'parquet_tpu_pool_queue_depth{pool="pqt-io"} 3\n'
            '# HELP parquet_tpu_chunk_decode_seconds per-chunk decode wall time\n'
            '# TYPE parquet_tpu_chunk_decode_seconds histogram\n'
            'parquet_tpu_chunk_decode_seconds_bucket{le="0.0005"} 0\n'
            'parquet_tpu_chunk_decode_seconds_bucket{le="0.001"} 0\n'
            'parquet_tpu_chunk_decode_seconds_bucket{le="0.005"} 1\n'
            'parquet_tpu_chunk_decode_seconds_bucket{le="0.01"} 1\n'
            'parquet_tpu_chunk_decode_seconds_bucket{le="0.05"} 1\n'
            'parquet_tpu_chunk_decode_seconds_bucket{le="0.1"} 1\n'
            'parquet_tpu_chunk_decode_seconds_bucket{le="0.5"} 1\n'
            'parquet_tpu_chunk_decode_seconds_bucket{le="1"} 1\n'
            'parquet_tpu_chunk_decode_seconds_bucket{le="5"} 2\n'
            'parquet_tpu_chunk_decode_seconds_bucket{le="+Inf"} 2\n'
            'parquet_tpu_chunk_decode_seconds_sum 2.002\n'
            'parquet_tpu_chunk_decode_seconds_count 2\n'
        )

    def test_label_escaping_round_trips(self):
        """An escaped sample line still parses: unescaping recovers the
        original value exactly (what a scraper's parser will do)."""
        reg = metrics.MetricsRegistry()
        hostile = 'a\\b"c\nd\\\\e""'
        reg.inc("pqt_test_escape_total", 1, v=hostile)
        [line] = [
            ln for ln in reg.render_prometheus().splitlines()
            if ln.startswith("parquet_tpu_pqt_test_escape_total")
        ]
        assert "\n" not in line  # the raw newline would split the sample
        quoted = line[line.index('v="') + 3 : line.rindex('"')]
        unescaped = (
            quoted.replace("\\\\", "\x00")
            .replace('\\"', '"')
            .replace("\\n", "\n")
            .replace("\x00", "\\")
        )
        assert unescaped == hostile

    def test_le_bounds_never_scientific(self):
        """repr() would render tight bounds as 5e-05; the exposition must
        print plain decimals for every bound."""
        h = metrics._Hist(buckets=(0.00005, 0.5, 1.0, 10.0))
        reg = metrics.MetricsRegistry()
        reg._hists[("pqt_test_le_seconds", ())] = h
        text = reg.render_prometheus()
        assert 'le="0.00005"' in text
        assert 'le="1"' in text and 'le="1.0"' not in text
        assert 'le="10"' in text
        assert "e-" not in text.lower().replace('le="+inf"', "")

    def test_help_precedes_type_once_per_family(self):
        reg = metrics.MetricsRegistry()
        reg.inc("io_retries_total", 1, reason="eio")
        reg.inc("io_retries_total", 1, reason="short_read")
        lines = reg.render_prometheus().splitlines()
        help_ix = [i for i, ln in enumerate(lines) if ln.startswith("# HELP")]
        assert len(help_ix) == 1  # one HELP per family, not per sample
        assert lines[help_ix[0] + 1].startswith(
            "# TYPE parquet_tpu_io_retries_total"
        )

    def test_undocumented_family_renders_without_help(self):
        reg = metrics.MetricsRegistry()
        reg.inc("pqt_test_undoc_total", 1)
        text = reg.render_prometheus()
        assert "# HELP" not in text
        assert "# TYPE parquet_tpu_pqt_test_undoc_total counter" in text


class TestOpenMetricsGolden:
    """The content-negotiated OpenMetrics variant: counter families drop
    their _total suffix in # TYPE while samples keep it, histogram bucket
    samples carry exemplars in the spec's ` # {labels} value ts` syntax,
    the document terminates with # EOF — and the CLASSIC exposition stays
    byte-for-byte unchanged for existing scrapers."""

    def _reg(self):
        reg = metrics.MetricsRegistry()
        reg.inc("io_retries_total", 2, reason="eio")
        reg.set("pool_queue_depth", 3, pool="pqt-io")
        reg.observe(
            "serve_request_seconds",
            0.26,
            exemplar={"request_id": "abc123"},
            endpoint="/v1/scan",
        )
        return reg

    def test_counter_family_drops_total_suffix(self):
        om = self._reg().render_openmetrics()
        assert "# TYPE parquet_tpu_io_retries counter" in om
        assert 'parquet_tpu_io_retries_total{reason="eio"} 2' in om
        # the classic format keeps the full name in TYPE
        classic = self._reg().render_prometheus()
        assert "# TYPE parquet_tpu_io_retries_total counter" in classic

    def test_document_terminates_with_eof(self):
        om = self._reg().render_openmetrics()
        assert om.endswith("# EOF\n")
        assert om.count("# EOF") == 1

    def test_exemplar_rides_the_canonical_bucket_only(self):
        om = self._reg().render_openmetrics()
        ex_lines = [ln for ln in om.splitlines() if " # {" in ln]
        assert len(ex_lines) == 1
        [line] = ex_lines
        # 0.26 lands in the le="0.5" bucket (its first admitting bound)
        assert 'le="0.5"' in line
        sample, _, exemplar = line.partition(" # ")
        assert sample.endswith(" 1")
        labels, _, rest = exemplar.partition("} ")
        assert labels == '{request_id="abc123"'
        value, ts = rest.split(" ")
        assert float(value) == 0.26
        assert float(ts) > 0  # unix timestamp, spec-optional but emitted

    def test_exemplar_label_values_escape(self):
        reg = metrics.MetricsRegistry()
        reg.observe(
            "serve_request_seconds",
            0.002,
            exemplar={"request_id": 'a"b\\c\nd'},
            endpoint="/v1/plan",
        )
        om = reg.render_openmetrics()
        [line] = [ln for ln in om.splitlines() if " # {" in ln]
        assert '{request_id="a\\"b\\\\c\\nd"}' in line
        assert "\n" not in line  # the raw newline would split the sample

    def test_classic_format_is_unchanged_by_exemplars(self):
        """An existing scraper must see identical bytes whether or not
        exemplars were ever attached."""
        with_ex = self._reg()
        without = metrics.MetricsRegistry()
        without.inc("io_retries_total", 2, reason="eio")
        without.set("pool_queue_depth", 3, pool="pqt-io")
        without.observe("serve_request_seconds", 0.26, endpoint="/v1/scan")
        assert with_ex.render_prometheus() == without.render_prometheus()
        classic = with_ex.render_prometheus()
        assert "# EOF" not in classic and " # {" not in classic

    def test_histograms_and_gauges_render_in_openmetrics(self):
        om = self._reg().render_openmetrics()
        assert "# TYPE parquet_tpu_pool_queue_depth gauge" in om
        assert "# TYPE parquet_tpu_serve_request_seconds histogram" in om
        assert (
            'parquet_tpu_serve_request_seconds_bucket{endpoint="/v1/scan",le="+Inf"} 1'
            in om
        )
        assert 'parquet_tpu_serve_request_seconds_count{endpoint="/v1/scan"} 1' in om

    def test_module_render_refreshes_uptime_gauge(self):
        text = metrics.render_prometheus()
        assert "parquet_tpu_process_uptime_seconds" in text
        assert "# TYPE parquet_tpu_process_uptime_seconds gauge" in text
        up = metrics.get("process_uptime_seconds")
        assert up >= 0
        om = metrics.render_openmetrics()
        assert "parquet_tpu_process_uptime_seconds" in om


class TestGauges:
    def test_set_last_write_wins(self):
        metrics.set_gauge("pqt_test_gauge", 3)
        metrics.set_gauge("pqt_test_gauge", 1)
        assert metrics.get("pqt_test_gauge") == 1
        assert metrics.snapshot()["pqt_test_gauge"] == 1

    def test_labeled_gauges_are_independent(self):
        metrics.set_gauge("pqt_test_gauge_lbl", 2, lane="a")
        metrics.set_gauge("pqt_test_gauge_lbl", 5, lane="b")
        assert metrics.get("pqt_test_gauge_lbl", lane="a") == 2
        assert metrics.get("pqt_test_gauge_lbl", lane="b") == 5

    def test_exposition_declares_gauge_type(self):
        metrics.set_gauge("pqt_test_gauge_expo", 7)
        text = metrics.render_prometheus()
        assert "# TYPE parquet_tpu_pqt_test_gauge_expo gauge" in text
        assert "parquet_tpu_pqt_test_gauge_expo 7" in text

    def test_delta_skips_gauges(self):
        snap = metrics.snapshot()
        metrics.set_gauge("pqt_test_gauge_delta", 42)
        metrics.inc("pqt_test_gauge_sibling_counter")
        d = metrics.delta(snap)
        assert "pqt_test_gauge_delta" not in d  # non-monotonic: no diff
        assert d.get("pqt_test_gauge_sibling_counter") == 1


class TestReportAndSummary:
    def test_human_report(self, sample):
        with FileReader(sample) as r:
            r.read_row_group(0)
        text = metrics.report()
        assert "pages decoded" in text
        assert "compression ratio" in text

    def test_summarize_columns(self, sample):
        with FileReader(sample) as r:
            s = metrics.summarize_columns(r.metadata)
        assert set(s) == {"id", "name"}
        for col in s.values():
            assert col["compressed"] > 0
            assert col["uncompressed"] > 0
            assert col["ratio"] is not None and col["ratio"] > 0
            assert col["encodings"]

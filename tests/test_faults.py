"""Fault-injection matrix: corrupt input must surface as TYPED errors.

Contracts pinned here (the corruption-hardening ladder):
  * every mutation the seeded harness (parquet_tpu.testing.faults) produces
    reads as a typed Parquet error or a byte-identical success — never a raw
    struct.error/zlib.error/IndexError/OverflowError, never a hang (each
    case runs under a watchdog), never silently wrong data;
  * the contract holds on BOTH ladder rungs: the staged per-page Python walk
    (host backend) and the fused native prepare (tpu_roundtrip backend);
  * with validate_crc=True the fused native path stays ENGAGED on clean
    files (prepare_fused_engaged, not prepare_fused_declined) and a CRC
    mismatch falls fused -> staged -> typed ChunkError;
  * FileReader(on_error=...) quarantines corrupt chunks/groups instead of
    aborting, with chunks_quarantined/row_groups_quarantined counters;
  * the committed corpus under tests/data/corrupt/ stays typed-failing.

The fast subset here is tier-1; the extended sweep is `slow` (make fuzz).
"""

from __future__ import annotations

import glob
import io
import os
import threading

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from parquet_tpu.core.reader import PARQUET_ERRORS, FileReader
from parquet_tpu.testing.faults import (
    FaultViolation,
    _read_all,
    iter_fault_cases,
    map_pages,
    run_case,
)
from parquet_tpu.utils.trace import decode_trace

WATCHDOG_SECONDS = 30.0

CORPUS_DIR = os.path.join(os.path.dirname(__file__), "data", "corrupt")


def with_watchdog(fn, timeout: float = WATCHDOG_SECONDS):
    """Run fn on a daemon thread; a case that hangs FAILS instead of
    stalling the suite (the thread leaks, but the test dies loudly)."""
    result: dict = {}

    def target():
        try:
            result["value"] = fn()
        except BaseException as e:  # noqa: BLE001 — re-raised on the main thread
            result["error"] = e

    t = threading.Thread(target=target, daemon=True)
    t.start()
    t.join(timeout)
    if t.is_alive():
        pytest.fail(f"watchdog: case still running after {timeout}s (hang)")
    if "error" in result:
        raise result["error"]
    return result.get("value")


def _base_table(rows: int = 3000):
    rng = np.random.default_rng(42)
    mask = rng.random(rows) < 0.2
    return pa.table(
        {
            "i": pa.array(rng.integers(0, 1 << 40, rows), pa.int64(), mask=mask),
            "s": pa.array([f"v{k % 37}" for k in range(rows)]),
            "f": pa.array(rng.random(rows).astype(np.float64)),
        }
    )


def _base_bytes(version: str, compression: str = "snappy", crc: bool = True):
    buf = io.BytesIO()
    pq.write_table(
        _base_table(),
        buf,
        compression=compression,
        data_page_version=version,
        write_page_checksum=crc,
        row_group_size=1500,
    )
    return buf.getvalue()


# -- the seeded quick-fuzz (fast subset: tier-1) -------------------------------


@pytest.mark.parametrize("version", ["1.0", "2.0"])
@pytest.mark.parametrize("backend", ["host", "tpu_roundtrip"])
def test_quick_fuzz(version, backend):
    data = _base_bytes(version)
    pristine = _read_all(data, True, backend)
    cases = list(iter_fault_cases(data, seed=7))
    assert len(cases) >= 12  # the matrix families are all represented
    for case in cases:
        with_watchdog(lambda c=case: run_case(c, pristine=pristine, backend=backend))


def test_quick_fuzz_no_crc():
    """CRC-less files: mutations may be benign or undetectable, but raw
    exceptions and hangs are still forbidden on both ladder rungs."""
    data = _base_bytes("1.0", compression="none", crc=False)
    pristine = _read_all(data, False, "host")
    for case in iter_fault_cases(data, seed=13, validate_crc=False):
        for backend in ("host", "tpu_roundtrip"):
            with_watchdog(
                lambda c=case, b=backend: run_case(c, pristine=pristine, backend=b)
            )


@pytest.mark.slow
@pytest.mark.parametrize("version", ["1.0", "2.0"])
@pytest.mark.parametrize("compression,crc", [("snappy", True), ("gzip", True), ("none", False)])
def test_extended_sweep(version, compression, crc):
    """The full codec x version x seed sweep (make fuzz / -m slow)."""
    data = _base_bytes(version, compression=compression, crc=crc)
    pristine = _read_all(data, crc, "host")
    for seed in (3, 11, 99, 1234, 31337):
        for case in iter_fault_cases(
            data, seed=seed, truncations=8, bit_flips=8, header_flips=6,
            validate_crc=crc,
        ):
            for backend in ("host", "tpu_roundtrip"):
                with_watchdog(
                    lambda c=case, b=backend: run_case(
                        c, pristine=pristine, backend=b
                    )
                )


def test_harness_flags_raw_exceptions():
    """The harness itself must catch a decoder leaking a raw exception —
    pin that FaultViolation machinery actually trips (meta-test)."""
    from parquet_tpu.testing.faults import FaultCase

    case = FaultCase(
        name="meta", data=b"PAR1 not a real file PAR1", must_fail=True,
        validate_crc=False,
    )
    # a garbage file raises typed -> run_case returns "error", no violation
    assert run_case(case) == "error"
    ok_case = FaultCase(
        name="meta2", data=_base_bytes("1.0"), must_fail=True, validate_crc=True
    )
    with pytest.raises(FaultViolation):
        run_case(ok_case)  # pristine file "must fail" -> violation


# -- committed corpus ----------------------------------------------------------


def _corpus_files():
    return sorted(
        p
        for p in glob.glob(os.path.join(CORPUS_DIR, "*.parquet"))
        if not p.endswith("pristine.parquet")
    )


def test_corpus_exists():
    assert len(_corpus_files()) >= 8


@pytest.mark.parametrize("backend", ["host", "tpu_roundtrip"])
@pytest.mark.parametrize(
    "path", _corpus_files(), ids=[os.path.basename(p) for p in _corpus_files()]
)
def test_corpus_raises_typed(path, backend):
    with open(path, "rb") as f:
        data = f.read()

    def read():
        with pytest.raises(PARQUET_ERRORS):
            _read_all(data, True, backend)

    with_watchdog(read)


def test_corpus_pristine_control():
    with open(os.path.join(CORPUS_DIR, "pristine.parquet"), "rb") as f:
        data = f.read()
    host = _read_all(data, True, "host")
    fused = _read_all(data, True, "tpu_roundtrip")
    assert host == fused and host


# -- fused CRC validation keeps the fast path ----------------------------------


@pytest.mark.parametrize("version", ["1.0", "2.0"])
def test_fused_crc_keeps_fast_path(version, tmp_path):
    """validate_crc=True no longer forfeits the fused walk: clean pages
    verify INSIDE the native prepare (prepare_fused_engaged bumps)."""
    data = _base_bytes(version)
    p = tmp_path / "clean.parquet"
    p.write_bytes(data)
    with decode_trace() as tr:
        with FileReader(str(p), validate_crc=True, backend="tpu_roundtrip") as r:
            for gi in range(r.num_row_groups):
                r.read_row_group(gi)
    engaged = tr.stages.get("prepare_fused_engaged")
    assert engaged is not None and engaged.calls > 0
    assert "prepare_fused_declined" not in tr.stages
    assert "prepare.crc" in tr.stages  # the walk really checksummed


def test_fused_crc_mismatch_falls_back_typed(tmp_path):
    """A rotted payload under validate_crc: the fused walk aborts at stage
    crc, the staged walk re-raises the exact typed ChunkError."""
    data = _base_bytes("1.0")
    sites = [s for s in map_pages(data) if s.kind in (0, 3)]
    mutated = bytearray(data)
    mutated[sites[0].payload_offset + 3] ^= 0x01
    p = tmp_path / "rotten.parquet"
    p.write_bytes(bytes(mutated))
    with decode_trace() as tr:
        with FileReader(str(p), validate_crc=True, backend="tpu_roundtrip") as r:
            with pytest.raises(PARQUET_ERRORS, match="CRC mismatch"):
                for gi in range(r.num_row_groups):
                    r.read_row_group(gi)
    assert tr.stages.get("prepare_fused_fault_crc") is not None


def test_fallback_recovered_counter(tmp_path, monkeypatch):
    """The ladder's middle rung: when the native walk ABORTS on a chunk the
    staged walk can decode, the read still succeeds and
    prepare_fallback_recovered records the save. Forced here by making the
    native binding report a fault for every chunk (the natural triggers are
    native-walk limitations, which the differential suite keeps rare)."""
    from parquet_tpu.utils.native import NativeLib, PrepareFault, get_native

    if get_native() is None:
        pytest.skip("native library not built")
    data = _base_bytes("1.0")
    p = tmp_path / "clean.parquet"
    p.write_bytes(data)
    forced = PrepareFault(code=-1, stage="prescan", page=0, offset=0)
    monkeypatch.setattr(
        NativeLib, "chunk_prepare", lambda self, *a, **kw: forced
    )
    with decode_trace() as tr:
        with FileReader(str(p), backend="tpu_roundtrip") as r:
            out = [r.read_row_group(gi) for gi in range(r.num_row_groups)]
    assert all(out)
    rec = tr.stages.get("prepare_fallback_recovered")
    assert rec is not None and rec.calls > 0
    assert tr.stages.get("prepare_fused_fault_prescan").calls == rec.calls
    # clean reads never touch the counter
    with decode_trace() as tr2:
        monkeypatch.undo()
        with FileReader(str(p), backend="tpu_roundtrip") as r:
            [r.read_row_group(gi) for gi in range(r.num_row_groups)]
    assert "prepare_fallback_recovered" not in tr2.stages


# -- on_error quarantine modes -------------------------------------------------


def _poisoned_file(tmp_path):
    """3-group checksummed file with one bit-flipped chunk in group 1."""
    from parquet_tpu.core.chunk import chunk_byte_range

    rng = np.random.default_rng(5)
    rows = 6000
    mask = rng.random(rows) < 0.25
    t = pa.table(
        {
            "a": pa.array(rng.integers(0, 1000, rows), pa.int64(), mask=mask),
            "b": pa.array([f"s{i % 50}" for i in range(rows)]),
        }
    )
    p = str(tmp_path / "poisoned.parquet")
    pq.write_table(
        t, p, compression="snappy", row_group_size=2000,
        write_page_checksum=True, use_dictionary=False,
        column_encoding={"a": "PLAIN", "b": "PLAIN"},
    )
    data = bytearray(open(p, "rb").read())
    with FileReader(p) as r:
        cc = r.row_group(1).columns[0]  # column "a" of group 1
        off, total = chunk_byte_range(cc)
    data[off + total // 2] ^= 0xFF
    bad = str(tmp_path / "poisoned_bad.parquet")
    open(bad, "wb").write(bytes(data))
    return bad


def test_on_error_raise_default(tmp_path):
    bad = _poisoned_file(tmp_path)
    with FileReader(bad, validate_crc=True) as r:
        with pytest.raises(PARQUET_ERRORS):
            list(r.iter_rows())


def test_on_error_skip_quarantines_group(tmp_path):
    bad = _poisoned_file(tmp_path)
    with decode_trace() as tr:
        with FileReader(bad, validate_crc=True, on_error="skip") as r:
            rows = list(r.iter_rows())
            tbl = r.to_arrow()
    assert len(rows) == 4000  # groups 0 and 2 survive
    assert tbl.num_rows == 4000
    assert tr.stages["chunks_quarantined"].calls == 2  # iter_rows + to_arrow
    assert tr.stages["row_groups_quarantined"].calls == 2


def test_on_error_null_keeps_rows(tmp_path):
    bad = _poisoned_file(tmp_path)
    with decode_trace() as tr:
        with FileReader(bad, validate_crc=True, on_error="null") as r:
            rows = list(r.iter_rows())
    assert len(rows) == 6000
    # quarantined column delivered as nulls in group 1, intact elsewhere
    assert all(row["a"] is None for row in rows[2000:4000])
    assert any(row["a"] is not None for row in rows[:2000])
    assert all(row["b"] is not None for row in rows[2000:4000])
    assert tr.stages["chunks_nulled"].calls == 1


def test_on_error_rejects_unknown_mode(tmp_path):
    bad = _poisoned_file(tmp_path)
    with pytest.raises(ValueError, match="on_error"):
        FileReader(bad, on_error="ignore")


# -- thrift preflight guards ---------------------------------------------------


def test_thrift_list_size_preflight():
    from parquet_tpu.meta.thrift import CompactReader, ThriftError

    # list header claiming 2^35 elements in a 4-byte buffer
    r = CompactReader(bytes([0xF6, 0xFF, 0xFF, 0xFF, 0xFF, 0x7F]))
    with pytest.raises(ThriftError, match="exceeds remaining"):
        r.read_list_header()


def test_thrift_map_skip_no_hang():
    from parquet_tpu.meta.thrift import CT_MAP, CompactReader, ThriftError

    # map with a huge claimed size and bool value type: each kv would skip
    # zero bytes without the preflight guard (an unbounded loop)
    payload = b"\xff\xff\xff\xff\xff\xff\xff\xff\x7f" + b"\x11"
    r = CompactReader(payload)

    def skip():
        with pytest.raises(ThriftError):
            r.skip(CT_MAP)

    with_watchdog(skip, timeout=10.0)

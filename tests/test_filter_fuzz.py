"""Differential fuzz of the predicate/pruning stack.

Random DNF predicates over random typed data, on files written WITH page
indexes and bloom filters so every pruning layer (row-group statistics,
bloom consultation, page-index ranges, selective page decode) is armed.
The oracle is a plain Python evaluation of the same predicate over the
unfiltered rows — any conservative-pruning bug that silently drops a
matching row, or an exactness bug that leaks a non-matching one, fails
the seed. to_arrow(filters=) is cross-checked against
pyarrow.read_table(filters=) where its tuple DSL can express the
predicate.
"""

import operator

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from parquet_tpu import FileReader, FileWriter, parse_schema

N_SEEDS = 16
N_ROWS = 4_000

_OPS = {
    "==": operator.eq,
    "!=": operator.ne,
    "<": operator.lt,
    "<=": operator.le,
    ">": operator.gt,
    ">=": operator.ge,
}


def _make_file(tmp_path, rng, seed):
    n = N_ROWS
    rows = []
    for i in range(n):
        rows.append({
            "id": int(rng.integers(0, 3_000)),
            "cat": None if rng.random() < 0.1 else f"c{int(rng.integers(0, 40))}",
            "x": float(rng.standard_normal()),
        })
    schema = parse_schema(
        "message m { required int64 id; optional binary cat (UTF8); "
        "required double x; }"
    )
    p = str(tmp_path / f"f{seed}.parquet")
    with FileWriter(
        p, schema,
        codec=str(rng.choice(["snappy", "uncompressed"])),
        write_page_index=True,
        bloom_filters=["id", "cat"],
        max_page_size=int(rng.choice([2_048, 16_384])),
    ) as w:
        chunk = n // int(rng.choice([1, 4]))
        for lo in range(0, n, chunk):
            for row in rows[lo : lo + chunk]:
                w.write_row(row)
            w.flush_row_group()
    return p, rows


def _rand_pred(rng):
    col = str(rng.choice(["id", "cat", "x"]))
    if col == "id":
        if rng.random() < 0.3:
            members = [int(v) for v in rng.integers(0, 3_500, int(rng.integers(1, 5)))]
            return (col, str(rng.choice(["in", "not_in"])), members)
        return (col, str(rng.choice(list(_OPS))), int(rng.integers(-10, 3_200)))
    if col == "cat":
        k = rng.random()
        if k < 0.2:
            return (col, str(rng.choice(["is_null", "not_null"])), None)
        if k < 0.4:
            return (col, "in", [f"c{int(v)}" for v in rng.integers(0, 50, 3)])
        return (col, str(rng.choice(["==", "!=", "<", ">="])), f"c{int(rng.integers(0, 50))}")
    return (col, str(rng.choice(["<", ">", "<=", ">="])), float(rng.standard_normal()))


def _row_matches(row, pred):
    col, op, val = pred
    v = row[col]
    if op == "is_null":
        return v is None
    if op == "not_null":
        return v is not None
    if v is None:
        return False
    if op == "in":
        return v in val
    if op == "not_in":
        return v not in val
    return _OPS[op](v, val)


def _dnf_matches(row, dnf):
    return any(all(_row_matches(row, p) for p in conj) for conj in dnf)


@pytest.mark.parametrize("seed", range(N_SEEDS))
def test_random_predicates_exact(tmp_path, seed):
    rng = np.random.default_rng(9_000_000 + seed)
    path, rows = _make_file(tmp_path, rng, seed)
    for trial in range(6):
        n_conj = int(rng.integers(1, 3))
        dnf = [
            [_rand_pred(rng) for _ in range(int(rng.integers(1, 3)))]
            for _ in range(n_conj)
        ]
        filters = dnf if n_conj > 1 else dnf[0]
        want = [r for r in rows if _dnf_matches(r, dnf)]
        with FileReader(path) as r:
            got = list(r.iter_rows(filters=[list(c) for c in dnf] if n_conj > 1 else list(dnf[0])))
        assert got == want, (seed, trial, filters, len(got), len(want))
        # columnar lane: same predicate semantics except not_in-with-null
        # (documented pyarrow-parity divergence)
        has_notin = any(p[1] == "not_in" for c in dnf for p in c)
        if not has_notin:
            with FileReader(path) as r:
                t = r.to_arrow(filters=[list(c) for c in dnf] if n_conj > 1 else list(dnf[0]))
            assert t.num_rows == len(want), (seed, trial, filters)
            assert t.column("id").to_pylist() == [w["id"] for w in want]


@pytest.mark.parametrize("seed", range(6))
def test_to_arrow_filters_vs_pyarrow(tmp_path, seed):
    """Where pyarrow's tuple DSL can express the predicate, the two
    libraries' filtered reads agree row for row."""
    rng = np.random.default_rng(11_000_000 + seed)
    path, rows = _make_file(tmp_path, rng, seed)
    for trial in range(4):
        pred = _rand_pred(rng)
        if pred[1] in ("is_null", "not_null", "not_in"):
            continue  # outside pyarrow's tuple DSL / divergent semantics
        pa_op = {"in": "in"}.get(pred[1], pred[1])
        want = pq.read_table(path, filters=[(pred[0], pa_op, pred[2])])
        with FileReader(path) as r:
            got = r.to_arrow(filters=[pred])
        assert got.num_rows == want.num_rows, (seed, trial, pred)
        assert got.column("id").to_pylist() == want.column("id").to_pylist()

"""floor high-level API + autoschema tests (reference: floor/writeread_test.go,
autoschema/gen_test.go)."""

import dataclasses
import datetime as dt
from dataclasses import dataclass, field
from typing import Optional

import pyarrow.parquet as pq
import pytest

from parquet_tpu import floor
from parquet_tpu.floor.autoschema import AutoSchemaError, schema_from_dataclass
from parquet_tpu.meta.parquet_types import Type
from parquet_tpu.schema.dsl import schema_to_string, validate_strict


@dataclass
class Pos:
    lat: float
    lon: float


@dataclass
class Trip:
    id: int
    vendor: Optional[str]
    ts: dt.datetime
    day: dt.date
    pickup: dt.time
    tags: list[str]
    attrs: dict[str, Optional[int]]
    pos: Optional[Pos]
    renamed: int = field(default=0, metadata={"parquet": "other_name"})


TRIPS = [
    Trip(
        1,
        "CMT",
        dt.datetime(2024, 5, 1, 12, 30, tzinfo=dt.timezone.utc),
        dt.date(2024, 5, 1),
        dt.time(12, 30, 5, 123),
        ["a", "b"],
        {"k": 1, "n": None},
        Pos(40.7, -74.0),
        9,
    ),
    Trip(
        2,
        None,
        dt.datetime(2024, 5, 2, 9, 0, tzinfo=dt.timezone.utc),
        dt.date(2024, 5, 2),
        dt.time(0, 0),
        [],
        {},
        None,
        0,
    ),
]


class TestAutoschema:
    def test_schema_shape(self):
        s = schema_from_dataclass(Trip)
        assert s.column("id").type == Type.INT64
        assert s.column("vendor").is_string()
        assert s.column("ts").logical_type.TIMESTAMP is not None
        assert s.column("day").type == Type.INT32
        assert "tags.list.element" in s
        assert "attrs.key_value.value" in s
        assert s.column("pos.lat").type == Type.DOUBLE
        assert "other_name" in s  # metadata rename
        validate_strict(s)

    def test_roundtrips_through_dsl(self):
        s = schema_from_dataclass(Trip)
        from parquet_tpu.schema.dsl import parse_schema

        s2 = parse_schema(schema_to_string(s))
        assert [l.path for l in s2.leaves] == [l.path for l in s.leaves]

    def test_non_dataclass_rejected(self):
        with pytest.raises(AutoSchemaError):
            schema_from_dataclass(dict)

    def test_unsupported_type_rejected(self):
        @dataclass
        class Bad:
            x: complex

        with pytest.raises(AutoSchemaError):
            schema_from_dataclass(Bad)


class TestFloorRoundtrip:
    def test_dataclass_roundtrip(self, tmp_path):
        path = str(tmp_path / "t.parquet")
        with floor.Writer(path, Trip, codec="snappy") as w:
            w.write_all(TRIPS)
        assert list(floor.Reader(path, Trip)) == TRIPS

    def test_pyarrow_reads_floor_files(self, tmp_path):
        path = str(tmp_path / "t.parquet")
        with floor.Writer(path, Trip) as w:
            w.write_all(TRIPS)
        t = pq.read_table(path)
        assert t.num_rows == 2
        assert t.column("id").to_pylist() == [1, 2]
        assert str(t.schema.field("ts").type).startswith("timestamp[us")

    def test_dict_rows_without_record_type(self, tmp_path):
        path = str(tmp_path / "d.parquet")
        with floor.Writer(path, Trip) as w:
            w.write({"id": 3, "vendor": "VTS", "ts": dt.datetime(2024, 1, 1, tzinfo=dt.timezone.utc),
                     "day": dt.date(2024, 1, 1), "pickup": dt.time(1, 2, 3),
                     "tags": ["z"], "attrs": {}, "pos": None, "other_name": 1})
        rows = list(floor.Reader(path))  # no record type: plain dicts
        assert rows[0]["id"] == 3
        assert rows[0]["vendor"] == "VTS"

    def test_marshaller_hooks(self, tmp_path):
        @dataclass
        class Custom:
            a: int

            def to_parquet(self):
                return {"a": self.a * 10}

            @classmethod
            def from_parquet(cls, row):
                return cls(a=row["a"] // 10)

        path = str(tmp_path / "c.parquet")
        with floor.Writer(path, Custom) as w:
            w.write(Custom(a=5))
        assert list(floor.Reader(path, Custom)) == [Custom(a=5)]

    def test_naive_datetime_treated_as_utc(self, tmp_path):
        @dataclass
        class R:
            ts: dt.datetime

        path = str(tmp_path / "n.parquet")
        with floor.Writer(path, R) as w:
            w.write(R(ts=dt.datetime(2020, 6, 1, 12, 0)))
        (back,) = list(floor.Reader(path, R))
        assert back.ts == dt.datetime(2020, 6, 1, 12, 0, tzinfo=dt.timezone.utc)

    def test_wrong_object_type_rejected(self, tmp_path):
        path = str(tmp_path / "w.parquet")
        w = floor.Writer(path, Trip)
        with pytest.raises(TypeError):
            w.write(42)


class TestMarshallerObjectModel:
    """The reference's explicit Marshaller/Unmarshaller object model
    (reference: floor/interfaces/marshaller.go:13-175, unmarshaller.go:105-293)."""

    def test_marshal_unmarshal_roundtrip(self, tmp_path):
        from parquet_tpu import floor as fl
        from parquet_tpu import parse_schema

        class Record:
            def __init__(self, rid=None, name=None, tags=None, attrs=None):
                self.rid, self.name, self.tags, self.attrs = rid, name, tags, attrs

            def marshal_parquet(self, obj):
                obj.add_field("rid").set_int64(self.rid)
                obj.add_field("name").set_string(self.name)
                lst = obj.add_field("tags").list()
                for t in self.tags:
                    lst.add().set_string(t)
                m = obj.add_field("attrs").map()
                for k, v in self.attrs.items():
                    ke, ve = m.add()
                    ke.set_string(k)
                    ve.set_int64(v)

            def unmarshal_parquet(self, obj):
                self.rid = obj.get_field("rid").int64()
                self.name = obj.get_field("name").string()
                self.tags = [e.string() for e in obj.get_field("tags").list_()]
                self.attrs = {
                    k.string(): v.int64() for k, v in obj.get_field("attrs").map_()
                }

            def __eq__(self, other):
                return (self.rid, self.name, self.tags, self.attrs) == (
                    other.rid, other.name, other.tags, other.attrs,
                )

        schema = parse_schema("""
        message record {
          required int64 rid;
          required binary name (STRING);
          optional group tags (LIST) {
            repeated group list { optional binary element (STRING); }
          }
          optional group attrs (MAP) {
            repeated group key_value {
              required binary key (STRING);
              optional int64 value;
            }
          }
        }""")
        path = str(tmp_path / "m.parquet")
        recs = [
            Record(1, "a", ["x", "y"], {"k1": 10}),
            Record(2, "b", [], {}),
        ]
        with fl.Writer(path, schema=schema) as w:
            for r in recs:
                w.write(r)
        back = list(fl.Reader(path, Record))
        assert back == recs
        # cross-check with pyarrow
        import pyarrow.parquet as pq

        t = pq.read_table(path).to_pylist()
        assert t[0]["rid"] == 1 and t[0]["tags"] == ["x", "y"]

    def test_field_not_present(self):
        from parquet_tpu import floor as fl

        obj = fl.UnmarshalObject({"a": 1, "b": None})
        assert obj.get_field("a").int64() == 1
        with pytest.raises(fl.FieldNotPresentError):
            obj.get_field("b")
        with pytest.raises(fl.FieldNotPresentError):
            obj.get_field("missing")

    def test_unmarshal_accepts_athena_bag(self, tmp_path):
        """LIST written with Athena's bag/array_element naming reads through
        both the ergonomic reader and the Unmarshal object model
        (reference: floor/reader.go:392-397)."""
        from parquet_tpu import FileReader, FileWriter, parse_schema
        from parquet_tpu import floor as fl

        sch = parse_schema("""
        message athena {
          optional group xs (LIST) {
            repeated group bag { optional int32 array_element; }
          }
        }""")
        path = str(tmp_path / "athena.parquet")
        with FileWriter(path, schema=sch) as w:
            w.write_row({"xs": {"bag": [{"array_element": 7}, {"array_element": 8}]}})
            w.write_row({"xs": {"bag": []}})
        with FileReader(path) as r:
            assert [row["xs"] for row in r.iter_rows()] == [[7, 8], []]
        with FileReader(path) as r:
            (raw, raw2) = list(r.iter_rows(raw=True))
        lst = fl.UnmarshalObject(raw).get_field("xs").list_()
        assert [e.int32() for e in lst] == [7, 8]


class TestNanoTime:
    """TIME(NANOS) fidelity via floor.Time (reference: floor/time.go:10-13)."""

    def test_time_type_basics(self):
        from parquet_tpu.floor import Time

        t = Time(13, 45, 30, 123456789)
        assert (t.hour, t.minute, t.second, t.nanosecond) == (13, 45, 30, 123456789)
        assert t.isoformat() == "13:45:30.123456789"
        assert Time.from_nanos(t.nanos) == t
        assert t.to_time() == dt.time(13, 45, 30, 123456, tzinfo=dt.timezone.utc)
        assert Time(1) < Time(2)
        with pytest.raises(ValueError):
            Time.from_nanos(-1)

    def test_nanos_survive_roundtrip(self, tmp_path):
        from parquet_tpu.floor import Time

        @dataclass
        class R:
            t: Time

        path = str(tmp_path / "nt.parquet")
        val = Time(23, 59, 59, 999999999)
        with floor.Writer(path, R) as w:
            w.write(R(t=val))
        (back,) = list(floor.Reader(path, R))
        assert back.t == val  # no precision loss
        # schema carries TIME(NANOS)
        from parquet_tpu import FileReader

        with FileReader(path) as r:
            lt = r.schema.column(("t",)).logical_type
            assert lt.TIME is not None and lt.TIME.unit.NANOS is not None

    def test_pyarrow_time64_ns_reads_as_nanotime(self, tmp_path):
        import pyarrow as pa
        import pyarrow.parquet as pq

        from parquet_tpu import FileReader
        from parquet_tpu.floor import Time

        path = str(tmp_path / "pa_ns.parquet")
        # 1ns past 12:00:00 — unrepresentable in datetime.time
        pq.write_table(
            pa.table({"t": pa.array([43200 * 10**9 + 1], pa.time64("ns"))}), path
        )
        with FileReader(path) as r:
            (row,) = list(r.iter_rows())
        # pyarrow writes TIME with isAdjustedToUTC=false
        assert row["t"] == Time.from_nanos(43200 * 10**9 + 1, utc=False)

    def test_time_units_in_object_model(self):
        from parquet_tpu.floor import MarshalObject, Time, UnmarshalObject

        mo = MarshalObject()
        noon = Time(12, 0, 0)
        mo.add_field("ms").set_time(noon, unit="MILLIS")
        mo.add_field("us").set_time(noon, unit="MICROS")
        mo.add_field("ns").set_time(noon)
        assert mo.data == {
            "ms": 43_200_000,
            "us": 43_200_000_000,
            "ns": 43_200_000_000_000,
        }
        uo = UnmarshalObject(mo.data)
        assert uo.get_field("ms").time(unit="MILLIS") == noon
        assert uo.get_field("us").time(unit="MICROS") == noon
        assert uo.get_field("ns").time() == noon

    def test_non_utc_time_column_roundtrip(self, tmp_path):
        from parquet_tpu import FileReader, FileWriter, parse_schema
        from parquet_tpu.floor import Time

        sch = parse_schema(
            "message m { required int64 t (TIME(NANOS, false)); }"
        )
        path = str(tmp_path / "local.parquet")
        with FileWriter(path, schema=sch) as w:
            w.write_row({"t": 1234})
        with FileReader(path) as r:
            (row,) = list(r.iter_rows())
        assert row["t"] == Time.from_nanos(1234, utc=False)
        assert row["t"] != Time.from_nanos(1234, utc=True)


class TestFloorFilters:
    def test_filters_flow_through_reader(self, tmp_path):
        @dataclass
        class R:
            x: int
            s: str

        path = str(tmp_path / "ff.parquet")
        with floor.Writer(path, R) as w:
            for i in range(10_000):
                w.write(R(x=i, s=f"s{i % 5}"))
        got = list(floor.Reader(path, R, filters=[("x", ">=", 9_995), ("s", "==", "s1")]))
        assert got == [R(x=9_996, s="s1")]

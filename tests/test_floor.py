"""floor high-level API + autoschema tests (reference: floor/writeread_test.go,
autoschema/gen_test.go)."""

import dataclasses
import datetime as dt
from dataclasses import dataclass, field
from typing import Optional

import pyarrow.parquet as pq
import pytest

from parquet_tpu import floor
from parquet_tpu.floor.autoschema import AutoSchemaError, schema_from_dataclass
from parquet_tpu.meta.parquet_types import Type
from parquet_tpu.schema.dsl import schema_to_string, validate_strict


@dataclass
class Pos:
    lat: float
    lon: float


@dataclass
class Trip:
    id: int
    vendor: Optional[str]
    ts: dt.datetime
    day: dt.date
    pickup: dt.time
    tags: list[str]
    attrs: dict[str, Optional[int]]
    pos: Optional[Pos]
    renamed: int = field(default=0, metadata={"parquet": "other_name"})


TRIPS = [
    Trip(
        1,
        "CMT",
        dt.datetime(2024, 5, 1, 12, 30, tzinfo=dt.timezone.utc),
        dt.date(2024, 5, 1),
        dt.time(12, 30, 5, 123),
        ["a", "b"],
        {"k": 1, "n": None},
        Pos(40.7, -74.0),
        9,
    ),
    Trip(
        2,
        None,
        dt.datetime(2024, 5, 2, 9, 0, tzinfo=dt.timezone.utc),
        dt.date(2024, 5, 2),
        dt.time(0, 0),
        [],
        {},
        None,
        0,
    ),
]


class TestAutoschema:
    def test_schema_shape(self):
        s = schema_from_dataclass(Trip)
        assert s.column("id").type == Type.INT64
        assert s.column("vendor").is_string()
        assert s.column("ts").logical_type.TIMESTAMP is not None
        assert s.column("day").type == Type.INT32
        assert "tags.list.element" in s
        assert "attrs.key_value.value" in s
        assert s.column("pos.lat").type == Type.DOUBLE
        assert "other_name" in s  # metadata rename
        validate_strict(s)

    def test_roundtrips_through_dsl(self):
        s = schema_from_dataclass(Trip)
        from parquet_tpu.schema.dsl import parse_schema

        s2 = parse_schema(schema_to_string(s))
        assert [l.path for l in s2.leaves] == [l.path for l in s.leaves]

    def test_non_dataclass_rejected(self):
        with pytest.raises(AutoSchemaError):
            schema_from_dataclass(dict)

    def test_unsupported_type_rejected(self):
        @dataclass
        class Bad:
            x: complex

        with pytest.raises(AutoSchemaError):
            schema_from_dataclass(Bad)


class TestFloorRoundtrip:
    def test_dataclass_roundtrip(self, tmp_path):
        path = str(tmp_path / "t.parquet")
        with floor.Writer(path, Trip, codec="snappy") as w:
            w.write_all(TRIPS)
        assert list(floor.Reader(path, Trip)) == TRIPS

    def test_pyarrow_reads_floor_files(self, tmp_path):
        path = str(tmp_path / "t.parquet")
        with floor.Writer(path, Trip) as w:
            w.write_all(TRIPS)
        t = pq.read_table(path)
        assert t.num_rows == 2
        assert t.column("id").to_pylist() == [1, 2]
        assert str(t.schema.field("ts").type).startswith("timestamp[us")

    def test_dict_rows_without_record_type(self, tmp_path):
        path = str(tmp_path / "d.parquet")
        with floor.Writer(path, Trip) as w:
            w.write({"id": 3, "vendor": "VTS", "ts": dt.datetime(2024, 1, 1, tzinfo=dt.timezone.utc),
                     "day": dt.date(2024, 1, 1), "pickup": dt.time(1, 2, 3),
                     "tags": ["z"], "attrs": {}, "pos": None, "other_name": 1})
        rows = list(floor.Reader(path))  # no record type: plain dicts
        assert rows[0]["id"] == 3
        assert rows[0]["vendor"] == "VTS"

    def test_marshaller_hooks(self, tmp_path):
        @dataclass
        class Custom:
            a: int

            def to_parquet(self):
                return {"a": self.a * 10}

            @classmethod
            def from_parquet(cls, row):
                return cls(a=row["a"] // 10)

        path = str(tmp_path / "c.parquet")
        with floor.Writer(path, Custom) as w:
            w.write(Custom(a=5))
        assert list(floor.Reader(path, Custom)) == [Custom(a=5)]

    def test_naive_datetime_treated_as_utc(self, tmp_path):
        @dataclass
        class R:
            ts: dt.datetime

        path = str(tmp_path / "n.parquet")
        with floor.Writer(path, R) as w:
            w.write(R(ts=dt.datetime(2020, 6, 1, 12, 0)))
        (back,) = list(floor.Reader(path, R))
        assert back.ts == dt.datetime(2020, 6, 1, 12, 0, tzinfo=dt.timezone.utc)

    def test_wrong_object_type_rejected(self, tmp_path):
        path = str(tmp_path / "w.parquet")
        w = floor.Writer(path, Trip)
        with pytest.raises(TypeError):
            w.write(42)

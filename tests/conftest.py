"""Test configuration: force JAX onto a virtual 8-device CPU mesh.

Real-TPU execution is exercised by bench.py; tests validate kernels in
interpret/CPU mode and shardings on the virtual mesh, per the build brief.
"""
import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

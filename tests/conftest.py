"""Test configuration: force JAX onto a virtual 8-device CPU mesh.

The axon TPU plugin in this image ignores the JAX_PLATFORMS environment
variable, so the platform is forced via jax.config (verified to work) before
any test imports jax. Real-TPU execution is exercised by bench.py; tests
validate kernels and shardings on the virtual mesh, per the build brief.
"""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

import jax

jax.config.update("jax_platforms", "cpu")


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "slow: extended sweeps (fault-injection etc.) excluded from the "
        "tier-1 `-m 'not slow'` run; `make fuzz` includes them",
    )

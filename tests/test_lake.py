"""parquet_tpu.lake: the snapshot manifest, streaming ingest, and the
background compactor — plus their serve (/v1/append) and CLI surfaces.

Pinned here, per the issue's acceptance list:

  * atomicity: the append+scan+compact concurrency hammer — every scan
    pins EXACTLY ONE generation (the manifest's internal row/file counts
    always match what the files on disk hold; no torn file list ever);
  * crash-mid-compact: a rewrite that died before its manifest commit
    loses nothing — the orphan output (and the sink's tmp debris) is
    reaped, every committed row still scans;
  * time travel: open_snapshot(gen=k) is byte-identical across later
    ingest flushes AND compactions for every retained k;
  * the serve surface: /v1/append accepts both wire formats, answers
    typed errors from the lake taxonomy, and a daemon scan of the table
    directory reads the committed generation.
"""

from __future__ import annotations

import http.client
import json
import os
import threading
import time

import pytest

from parquet_tpu.core.reader import FileReader
from parquet_tpu.data.plan import build_plan, expand_paths
from parquet_tpu.lake import (
    Compactor,
    FileEntry,
    IngestWriter,
    LakeError,
    LakeManifest,
    LakeTable,
    is_lake_table,
    manifest_ref_root,
    rows_from_payload,
)
from parquet_tpu.serve import ScanServer, ServeConfig

WATCHDOG_S = 30.0

SCHEMA = "message m { required int64 k; optional binary v (STRING); }"


def _mk_table(d, **kw):
    return LakeTable.create(str(d), SCHEMA, sort_key="k", **kw)


def _rows(base, n):
    return [{"k": base + i, "v": f"v{base + i}"} for i in range(n)]


def _scan_rows(paths):
    out = []
    for p in paths:
        with FileReader(p) as r:
            out.extend(r.iter_rows())
    return out


# -- manifest ------------------------------------------------------------------


class TestManifest:
    def test_empty_table_is_generation_zero(self, tmp_path):
        t = _mk_table(tmp_path / "t")
        assert t.manifest.current_generation() == 0
        snap = t.manifest.open_snapshot()
        assert snap.generation == 0 and snap.files == ()
        assert t.snapshot_paths() == []

    def test_commit_points_and_time_travel(self, tmp_path):
        t = _mk_table(tmp_path / "t")
        w = IngestWriter(t, parallel=False)
        for base in (0, 100, 200):
            w.append(_rows(base, 10), flush=True)
        m = t.manifest
        assert m.current_generation() == 3
        assert m.generations() == [1, 2, 3]
        # each generation is a strict superset of its parent
        for g in (1, 2, 3):
            snap = m.open_snapshot(g)
            assert snap.generation == g
            assert len(snap.files) == g
            assert snap.total_rows == 10 * g
        # a never-committed generation is a typed error, not a KeyError
        with pytest.raises(LakeError) as ei:
            m.open_snapshot(9)
        assert ei.value.code == "no_such_generation"

    def test_expect_generation_conflict_is_typed(self, tmp_path):
        t = _mk_table(tmp_path / "t")
        IngestWriter(t, parallel=False).append(_rows(0, 5), flush=True)
        with pytest.raises(LakeError) as ei:
            t.manifest.commit(add=[], expect_generation=0)
        assert ei.value.code == "commit_conflict"

    def test_remove_unreferenced_and_double_add_are_typed(self, tmp_path):
        t = _mk_table(tmp_path / "t")
        IngestWriter(t, parallel=False).append(_rows(0, 5), flush=True)
        rel = t.manifest.open_snapshot().files[0].path
        with pytest.raises(LakeError) as ei:
            t.manifest.commit(remove=["data/never-was.parquet"])
        assert ei.value.code == "commit_conflict"
        with pytest.raises(LakeError) as ei:
            t.manifest.commit(add=[FileEntry(rel, 1, 1)])
        assert ei.value.code == "commit_conflict"

    def test_manifest_paths_are_confined(self, tmp_path):
        t = _mk_table(tmp_path / "t")
        for bad in ("/etc/passwd", "../outside.parquet", "a/../../b"):
            with pytest.raises(LakeError) as ei:
                t.manifest.commit(add=[FileEntry(bad, 1, 1)])
            assert ei.value.code == "bad_manifest"

    def test_retention_drops_old_gens_but_keeps_referenced_files(
        self, tmp_path
    ):
        t = LakeTable.create(
            str(tmp_path / "t"), SCHEMA, sort_key="k", retain=2
        )
        w = IngestWriter(t, parallel=False)
        for base in range(0, 50, 10):
            w.append(_rows(base, 10), flush=True)
        m = t.manifest
        assert m.generations() == [4, 5]
        # time travel inside the window still works; outside is typed
        assert m.open_snapshot(4).total_rows == 40
        with pytest.raises(LakeError):
            m.open_snapshot(1)
        # on-disk data files == union of the retained generations' refs
        referenced = {
            os.path.basename(f.path)
            for g in m.generations()
            for f in m.open_snapshot(g).files
        }
        on_disk = {
            n for n in os.listdir(m.data_dir) if n.endswith(".parquet")
        }
        assert on_disk == referenced

    def test_open_bad_table_is_typed(self, tmp_path):
        with pytest.raises(LakeError) as ei:
            LakeTable.open(str(tmp_path / "nope"))
        assert ei.value.code == "no_such_table"
        with pytest.raises(LakeError) as ei:
            LakeTable.create(str(tmp_path / "t"), SCHEMA, sort_key="zz")
        assert ei.value.code == "bad_schema"
        _mk_table(tmp_path / "t")
        with pytest.raises(LakeError) as ei:
            _mk_table(tmp_path / "t")
        assert ei.value.code == "table_exists"


# -- ingest --------------------------------------------------------------------


class TestIngest:
    def test_buffer_then_flush_publishes_one_generation(self, tmp_path):
        t = _mk_table(tmp_path / "t")
        w = IngestWriter(t, parallel=False)
        ack = w.append(_rows(0, 7))
        assert ack == {
            "rows": 7, "buffered_rows": 7,
            "flushed": False, "generation": None,
        }
        ack = w.append(_rows(100, 3), flush=True)
        assert ack["flushed"] is True and ack["generation"] == 1
        assert ack["buffered_rows"] == 0
        snap = t.manifest.open_snapshot()
        assert snap.total_rows == 10 and len(snap.files) == 1

    def test_size_bound_triggers_flush(self, tmp_path):
        t = _mk_table(tmp_path / "t")
        w = IngestWriter(t, flush_bytes=64, parallel=False)
        ack = w.append(_rows(0, 50))
        assert ack["flushed"] is True and ack["generation"] == 1

    def test_flushed_file_is_key_sorted_with_minmax(self, tmp_path):
        t = _mk_table(tmp_path / "t")
        w = IngestWriter(t, parallel=False)
        w.append(
            [{"k": 9, "v": "a"}, {"k": 1, "v": "b"}, {"k": 5, "v": None}],
            flush=True,
        )
        entry = t.manifest.open_snapshot().files[0]
        assert (entry.min_key, entry.max_key) == (1, 9)
        rows = _scan_rows(t.snapshot_paths())
        assert [r["k"] for r in rows] == [1, 5, 9]

    def test_close_flushes_the_tail_then_refuses(self, tmp_path):
        t = _mk_table(tmp_path / "t")
        w = IngestWriter(t, parallel=False)
        w.append(_rows(0, 4))
        snap = w.close()
        assert snap is not None and snap.total_rows == 4
        with pytest.raises(LakeError) as ei:
            w.append(_rows(9, 1))
        assert ei.value.code == "closed"

    def test_payload_decoding(self):
        jsonl = b'{"k": 1}\n\n{"k": 2, "v": "x"}\n'
        assert rows_from_payload(jsonl, "application/x-ndjson") == [
            {"k": 1}, {"k": 2, "v": "x"},
        ]
        pa = pytest.importorskip("pyarrow")
        table = pa.table({"k": [3, 4]})
        import io as _io

        buf = _io.BytesIO()
        with pa.ipc.new_stream(buf, table.schema) as wr:
            wr.write_table(table)
        got = rows_from_payload(
            buf.getvalue(), "application/vnd.apache.arrow.stream"
        )
        assert got == [{"k": 3}, {"k": 4}]
        with pytest.raises(LakeError) as ei:
            rows_from_payload(b"not json\n", "application/json")
        assert ei.value.code == "bad_payload"
        with pytest.raises(LakeError) as ei:
            rows_from_payload(b"[1, 2]", "application/json")
        assert ei.value.code == "bad_payload"
        with pytest.raises(LakeError) as ei:
            rows_from_payload(b"k\n1\n", "text/csv")
        assert ei.value.code == "unsupported_format"


# -- plan/dataset integration --------------------------------------------------


class TestLakeRefs:
    def test_expand_paths_pins_current_and_named_generations(self, tmp_path):
        t = _mk_table(tmp_path / "t")
        w = IngestWriter(t, parallel=False)
        w.append(_rows(0, 10), flush=True)
        w.append(_rows(100, 10), flush=True)
        root = str(tmp_path / "t")
        assert is_lake_table(root)
        cur = expand_paths(root)
        assert len(cur) == 2 and all(p.endswith(".parquet") for p in cur)
        pinned = expand_paths(os.path.join(root, "_lake", "gen-1.json"))
        assert pinned == cur[:1]
        assert manifest_ref_root(os.path.join(root, "_lake", "gen-1.json"))
        # build_plan sees the committed rows through the same ref
        assert build_plan(root).total_rows == 20
        assert build_plan([root]).total_rows == 20

    def test_non_lake_paths_are_untouched(self, tmp_path):
        f = tmp_path / "plain.txt"
        f.write_text("x")
        assert expand_paths(str(f)) == [str(f)]


# -- compaction ----------------------------------------------------------------


class TestCompactor:
    def _fill(self, d, *, flushes=5, rows_per=200):
        t = _mk_table(d)
        w = IngestWriter(t, parallel=False)
        # interleave key ranges so pre-compaction files all overlap and a
        # point probe prunes nothing
        for i in range(flushes):
            w.append(
                [
                    {"k": j * flushes + i, "v": f"r{i}.{j}"}
                    for j in range(rows_per)
                ],
                flush=True,
            )
        return t

    def test_fold_preserves_rows_and_improves_pruning(self, tmp_path):
        t = self._fill(tmp_path / "t", flushes=6, rows_per=300)
        before_rows = sorted(
            r["k"] for r in _scan_rows(t.snapshot_paths())
        )
        c = Compactor(t, row_group_size=256)
        result = c.compact_once()
        assert result is not None
        assert result.files_in == 6 and result.rows == 1800
        snap = t.manifest.open_snapshot()
        assert snap.generation == result.generation
        assert len(snap.files) == 1 and snap.total_rows == 1800
        after_rows = sorted(r["k"] for r in _scan_rows(t.snapshot_paths()))
        assert after_rows == before_rows
        # the point of the exercise: the sorted rewrite prunes where the
        # overlapping ingest files could not
        assert result.pruned_ratio_before is not None
        assert result.pruned_ratio_after > result.pruned_ratio_before
        # nothing left worth folding
        assert c.compact_once() is None

    def test_time_travel_is_byte_identical_across_compaction(self, tmp_path):
        t = self._fill(tmp_path / "t", flushes=4, rows_per=100)
        pin = 3  # a pre-compaction generation
        paths = t.snapshot_paths(pin)
        before = [open(p, "rb").read() for p in paths]
        Compactor(t).compact_once()
        IngestWriter(t, parallel=False).append(_rows(10_000, 5), flush=True)
        snap = t.manifest.open_snapshot(pin)
        assert snap.generation == pin
        assert t.snapshot_paths(pin) == paths
        assert [open(p, "rb").read() for p in paths] == before

    def test_crash_mid_compact_loses_nothing(self, tmp_path):
        t = self._fill(tmp_path / "t", flushes=3, rows_per=50)
        committed = sorted(r["k"] for r in _scan_rows(t.snapshot_paths()))
        m = t.manifest
        # simulate the crash: the rewrite landed, the commit never ran —
        # plus the sink's tmp debris from a writer killed mid-write
        orphan = os.path.join(m.data_dir, "compact-99999-000001.parquet")
        with open(t.snapshot_paths()[0], "rb") as f:
            payload = f.read()
        with open(orphan, "wb") as f:
            f.write(payload)
        tmp_debris = os.path.join(m.data_dir, ".dead.1234.0.tmp")
        with open(tmp_debris, "wb") as f:
            f.write(b"partial")
        reaped = m.reap_orphans(grace_s=0.0)
        assert reaped == 2
        assert not os.path.exists(orphan)
        assert not os.path.exists(tmp_debris)
        # zero data loss: every committed row still scans
        assert (
            sorted(r["k"] for r in _scan_rows(t.snapshot_paths()))
            == committed
        )

    def test_reap_respects_the_grace_window(self, tmp_path):
        t = self._fill(tmp_path / "t", flushes=2, rows_per=10)
        fresh = os.path.join(t.manifest.data_dir, "inflight.parquet")
        with open(fresh, "wb") as f:
            f.write(b"x")
        assert t.manifest.reap_orphans(grace_s=3600.0) == 0
        assert os.path.exists(fresh)

    def test_no_sort_key_falls_back_to_rowgroup_fold(self, tmp_path):
        t = LakeTable.create(str(tmp_path / "t"), SCHEMA)
        w = IngestWriter(t, parallel=False)
        w.append(_rows(0, 100), flush=True)
        w.append(_rows(100, 100), flush=True)
        result = Compactor(t).compact_once()
        assert result is not None and result.rows == 200
        assert result.pruned_ratio_before is None
        assert len(t.manifest.open_snapshot().files) == 1
        got = sorted(r["k"] for r in _scan_rows(t.snapshot_paths()))
        assert got == list(range(200))

    def test_background_thread_runs_on_its_own_lane(self, tmp_path):
        from parquet_tpu.obs.prof import POOL_LANES, lane_of

        assert "pqt-compact" in POOL_LANES
        assert lane_of("pqt-compact") == "pqt-compact"
        t = self._fill(tmp_path / "t", flushes=3, rows_per=20)
        c = Compactor(t, interval_s=0.01)
        c.start()
        try:
            deadline = time.time() + WATCHDOG_S
            while c.compactions == 0 and time.time() < deadline:
                time.sleep(0.01)
        finally:
            c.stop()
        assert c.compactions >= 1
        assert len(t.manifest.open_snapshot().files) == 1


# -- the concurrency hammer ----------------------------------------------------


class TestHammer:
    def test_every_scan_pins_exactly_one_generation(self, tmp_path):
        """Appends, flushes, and compactions race while scanners replan
        continuously; every scan must see an internally consistent
        snapshot — the planned row total equals the manifest's claim for
        that generation, and every referenced file opens. A torn commit
        (file list without its data, CURRENT ahead of its gen file)
        fails here."""
        t = _mk_table(tmp_path / "t")
        w = IngestWriter(t, parallel=False)
        c = Compactor(t, min_files=2, max_files=8)
        stop = threading.Event()
        failures: list[str] = []
        gen_rows: dict[int, int] = {}
        gen_lock = threading.Lock()

        def writer():
            total = 0
            try:
                for i in range(24):
                    ack = w.append(_rows(i * 50, 50), flush=(i % 3 == 2))
                    total += 50
                    if ack["flushed"]:
                        with gen_lock:
                            gen_rows[ack["generation"]] = total
            except Exception as e:  # noqa: BLE001
                failures.append(f"writer: {type(e).__name__}: {e}")
            finally:
                stop.set()

        def compactor():
            while not stop.wait(0.002):
                try:
                    r = c.compact_once()
                    if r is not None:
                        with gen_lock:
                            # compaction rewrites, never changes totals
                            gen_rows[r.generation] = r.rows
                except LakeError:
                    continue  # lost a commit race; re-plan next tick
                except Exception as e:  # noqa: BLE001
                    failures.append(f"compactor: {type(e).__name__}: {e}")
                    return

        def scanner():
            last_gen = 0
            while not stop.is_set() or last_gen == 0:
                try:
                    snap = t.manifest.open_snapshot()
                    if snap.generation == 0:
                        continue
                    plan = build_plan(t.snapshot_paths(snap.generation))
                    if plan.total_rows != snap.total_rows:
                        failures.append(
                            f"scanner: gen {snap.generation} planned "
                            f"{plan.total_rows} rows, manifest says "
                            f"{snap.total_rows}"
                        )
                        return
                    if snap.generation < last_gen:
                        failures.append("scanner: generation went backward")
                        return
                    last_gen = snap.generation
                except LakeError:
                    continue  # pinned gen aged out mid-scan: retry
                except Exception as e:  # noqa: BLE001
                    failures.append(f"scanner: {type(e).__name__}: {e}")
                    return

        threads = [
            threading.Thread(target=writer),
            threading.Thread(target=compactor),
            threading.Thread(target=scanner),
            threading.Thread(target=scanner),
        ]
        for th in threads:
            th.start()
        for th in threads:
            th.join(WATCHDOG_S)
            assert not th.is_alive(), "hammer thread wedged"
        assert not failures, failures
        # quiesce: one final fold, then the table holds every row exactly
        # once in key order per file
        w.close()
        while c.compact_once() is not None:
            pass
        rows = sorted(r["k"] for r in _scan_rows(t.snapshot_paths()))
        assert rows == list(range(0, 24 * 50))
        with gen_lock:
            final = t.manifest.open_snapshot()
            assert final.total_rows == 24 * 50


# -- the serve surface ---------------------------------------------------------


def _request(server, method, path, body=b"", headers=None):
    conn = http.client.HTTPConnection(
        server.host, server.port, timeout=WATCHDOG_S
    )
    try:
        conn.request(method, path, body=body, headers=headers or {})
        resp = conn.getresponse()
        return resp.status, resp.read()
    finally:
        conn.close()


def _append(server, body, content_type="application/x-ndjson", flush=False):
    return _request(
        server,
        "POST",
        "/v1/append" + ("?flush=1" if flush else ""),
        body=body,
        headers={"Content-Type": content_type},
    )


@pytest.fixture()
def lake_server(tmp_path):
    cfg = ServeConfig(
        port=0,
        root=str(tmp_path),
        lake_root=str(tmp_path / "tbl"),
        lake_schema=SCHEMA,
        lake_sort_key="k",
        max_append_bytes=4096,
    )
    with ScanServer(cfg) as s:
        s.start_background()
        yield s


class TestAppendHTTP:
    def test_buffered_then_flushed_acks(self, lake_server):
        body = b'{"k": 1, "v": "a"}\n{"k": 2, "v": "b"}\n'
        status, raw = _append(lake_server, body)
        assert status == 200
        ack = json.loads(raw)
        assert ack["rows"] == 2 and ack["flushed"] is False
        assert ack["generation"] is None
        status, raw = _append(lake_server, b'{"k": 3}\n', flush=True)
        ack = json.loads(raw)
        assert status == 200 and ack["flushed"] is True
        assert ack["generation"] == 1

    def test_arrow_ipc_append(self, lake_server):
        pa = pytest.importorskip("pyarrow")
        import io as _io

        table = pa.table({"k": [7, 8], "v": ["x", None]})
        buf = _io.BytesIO()
        with pa.ipc.new_stream(buf, table.schema) as wr:
            wr.write_table(table)
        status, raw = _append(
            lake_server,
            buf.getvalue(),
            content_type="application/vnd.apache.arrow.stream",
            flush=True,
        )
        assert status == 200
        assert json.loads(raw)["generation"] == 1

    def test_typed_errors(self, lake_server):
        cases = [
            (b"k,v\n1,a\n", "text/csv", 415, "unsupported_format"),
            (b"not json\n", "application/x-ndjson", 400, "bad_payload"),
            (b"", "application/x-ndjson", 400, "bad_request"),
            (b'{"k": "not-an-int"}\n', "application/x-ndjson", 422,
             "bad_rows"),
        ]
        for body, ct, want_status, want_code in cases:
            status, raw = _append(
                lake_server, body, content_type=ct, flush=True
            )
            assert status == want_status, (body, status, raw)
            assert json.loads(raw)["error"]["code"] == want_code

    def test_oversized_body_is_413(self, lake_server):
        big = b'{"k": 1}\n' * 1000  # over the 4096-byte test cap
        status, raw = _append(lake_server, big)
        assert status == 413
        assert json.loads(raw)["error"]["code"] == "body_too_large"

    def test_append_disabled_without_a_lake(self, tmp_path):
        with ScanServer(
            ServeConfig(port=0, root=str(tmp_path))
        ) as s:
            s.start_background()
            status, raw = _append(s, b'{"k": 1}\n')
        assert status == 503
        assert json.loads(raw)["error"]["code"] == "ingest_disabled"

    def test_daemon_scan_reads_the_committed_generation(self, lake_server):
        lines = b"".join(
            json.dumps({"k": i, "v": f"v{i}"}).encode() + b"\n"
            for i in (5, 3, 1, 4, 2)
        )
        status, _ = _append(lake_server, lines, flush=True)
        assert status == 200
        status, raw = _request(
            lake_server,
            "POST",
            "/v1/scan",
            body=json.dumps({"paths": ["tbl"]}).encode(),
            headers={"Content-Type": "application/json"},
        )
        assert status == 200, raw
        rows = [json.loads(ln) for ln in raw.splitlines() if ln]
        assert [r["k"] for r in rows] == [1, 2, 3, 4, 5]

    def test_close_flushes_the_tail(self, tmp_path):
        cfg = ServeConfig(
            port=0,
            root=str(tmp_path),
            lake_root=str(tmp_path / "tbl"),
            lake_schema=SCHEMA,
            lake_sort_key="k",
        )
        with ScanServer(cfg) as s:
            s.start_background()
            status, raw = _append(s, b'{"k": 1}\n')
            assert status == 200
            assert json.loads(raw)["flushed"] is False
        t = LakeTable.open(str(tmp_path / "tbl"))
        assert t.manifest.open_snapshot().total_rows == 1


# -- the CLI -------------------------------------------------------------------


class TestLakeCLI:
    def test_init_append_manifest_compact(self, tmp_path, capsys, monkeypatch):
        from parquet_tpu.tools.parquet_tool import main

        tbl = str(tmp_path / "t")
        assert (
            main(["lake", "init", tbl, "--schema", SCHEMA, "--sort-key", "k"])
            == 0
        )
        src = tmp_path / "rows.jsonl"
        src.write_text('{"k": 2}\n{"k": 1}\n')
        assert main(["lake", "append", tbl, str(src)]) == 0
        src.write_text('{"k": 4}\n{"k": 3}\n')
        assert main(["lake", "append", tbl, str(src)]) == 0
        capsys.readouterr()
        assert main(["lake", "manifest", tbl, "--json"]) == 0
        doc = json.loads(capsys.readouterr().out)
        assert doc["generation"] == 2 and len(doc["files"]) == 2
        assert (
            main(["lake", "compact", tbl, "--reap", "--reap-grace-s", "0"])
            == 0
        )
        capsys.readouterr()
        assert main(["lake", "manifest", tbl, "--gen", "3", "--json"]) == 0
        doc = json.loads(capsys.readouterr().out)
        assert len(doc["files"]) == 1
        assert doc["files"][0]["rows"] == 4

    def test_errors_are_messages_not_tracebacks(self, tmp_path, capsys):
        from parquet_tpu.tools.parquet_tool import main

        assert main(["lake", "manifest", str(tmp_path / "nope")]) == 1
        err = capsys.readouterr().err
        assert "no table" in err and "Traceback" not in err

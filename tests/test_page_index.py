"""Parquet page index (ColumnIndex/OffsetIndex) — beyond-reference coverage.

The reference has no page-index support at all. Here the writer emits both
structures between the last row group and the footer (write_page_index=True),
the reader parses either writer's output (read_page_index), and prune_pages
turns them into provably-sufficient row ranges for a predicate. pyarrow is
the cross-implementation oracle in both directions (write_page_index=True on
its writer; has_column_index/has_offset_index on its metadata for ours).
"""

import io

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from parquet_tpu import FileReader, FileWriter, parse_schema
from parquet_tpu.meta.parquet_types import BoundaryOrder


def _ours(tmp_path, n=40_000, **kw):
    path = str(tmp_path / "ours_idx.parquet")
    schema = parse_schema(
        "message m { required int64 a; optional binary s (UTF8); }"
    )
    vals = np.arange(n, dtype=np.int64)
    strs = [None if i % 997 == 0 else f"k{i // 1000:03d}" for i in range(n)]
    kw.setdefault("max_page_size", 32_768)
    with FileWriter(path, schema, write_page_index=True, **kw) as w:
        w.write_column("a", vals)
        w.write_column(
            "s",
            [x for x in strs if x is not None],
            def_levels=[0 if x is None else 1 for x in strs],
        )
    return path, vals, strs


class TestWriteSide:
    def test_pyarrow_sees_our_index(self, tmp_path):
        path, vals, strs = _ours(tmp_path, use_dictionary=False, codec="snappy")
        pf = pq.ParquetFile(path)
        col = pf.metadata.row_group(0).column(0)
        assert col.has_column_index and col.has_offset_index
        t = pq.read_table(path)
        assert t.column("a").to_pylist() == vals.tolist()
        assert t.column("s").to_pylist() == strs

    @pytest.mark.parametrize("version", [1, 2])
    @pytest.mark.parametrize("use_dict", [False, True])
    def test_own_roundtrip_matches_data(self, tmp_path, version, use_dict):
        path, vals, strs = _ours(
            tmp_path, data_page_version=version, use_dictionary=use_dict
        )
        with FileReader(path) as r:
            ci, oi = r.read_page_index(0)[("a",)]
            assert ci is not None and oi is not None
            assert ci.boundary_order == int(BoundaryOrder.ASCENDING)
            n_pages = len(oi.page_locations)
            assert (
                len(ci.min_values) == len(ci.max_values)
                == len(ci.null_pages) == len(ci.null_counts) == n_pages
            )
            for k, loc in enumerate(oi.page_locations):
                first = loc.first_row_index
                last = (
                    oi.page_locations[k + 1].first_row_index
                    if k + 1 < n_pages
                    else len(vals)
                ) - 1
                assert int(np.frombuffer(ci.min_values[k], np.int64)[0]) == vals[first]
                assert int(np.frombuffer(ci.max_values[k], np.int64)[0]) == vals[last]
            # string column: null counts accounted per page
            ci_s, oi_s = r.read_page_index(0)[("s",)]
            assert sum(ci_s.null_counts) == sum(1 for x in strs if x is None)
            # page locations point at real page headers (offsets ascend)
            offs = [loc.offset for loc in oi.page_locations]
            assert offs == sorted(offs) and offs[0] > 0

    def test_descending_and_unordered(self, tmp_path):
        schema = parse_schema("message m { required int64 a; }")
        path = str(tmp_path / "desc.parquet")
        with FileWriter(
            path, schema, write_page_index=True, max_page_size=8_192,
            use_dictionary=False,
        ) as w:
            w.write_column("a", np.arange(10_000, 0, -1, dtype=np.int64))
        with FileReader(path) as r:
            ci, _ = r.read_page_index(0)[("a",)]
            assert ci.boundary_order == int(BoundaryOrder.DESCENDING)
        path2 = str(tmp_path / "unord.parquet")
        rng = np.random.default_rng(0)
        with FileWriter(
            path2, schema, write_page_index=True, max_page_size=8_192,
            use_dictionary=False,
        ) as w:
            w.write_column("a", rng.permutation(10_000).astype(np.int64))
        with FileReader(path2) as r:
            ci, _ = r.read_page_index(0)[("a",)]
            assert ci.boundary_order == int(BoundaryOrder.UNORDERED)

    def test_string_boundary_order(self, tmp_path):
        """Sorted BYTE_ARRAY pages report ASCENDING (lexicographic IS the
        defined order for binary columns — readers keep their binary
        search); unsorted strings and DECIMAL-over-FLBA stay UNORDERED."""
        schema = parse_schema("message m { required binary s (UTF8); }")
        path = str(tmp_path / "sstr.parquet")
        sorted_vals = [f"k{i:06d}" for i in range(20_000)]
        with FileWriter(
            path, schema, write_page_index=True, max_page_size=8_192,
            use_dictionary=False,
        ) as w:
            w.write_column("s", sorted_vals)
        with FileReader(path) as r:
            ci, oi = r.read_page_index(0)[("s",)]
            assert len(oi.page_locations) > 1  # multiple pages, real ordering
            assert ci.boundary_order == int(BoundaryOrder.ASCENDING)

        path2 = str(tmp_path / "ustr.parquet")
        rng = np.random.default_rng(3)
        with FileWriter(
            path2, schema, write_page_index=True, max_page_size=8_192,
            use_dictionary=False,
        ) as w:
            w.write_column("s", [f"k{i}" for i in rng.permutation(20_000)])
        with FileReader(path2) as r:
            ci, _ = r.read_page_index(0)[("s",)]
            assert ci.boundary_order == int(BoundaryOrder.UNORDERED)

        # DECIMAL over FLBA: signed order, lexicographic bytes would lie
        dschema = parse_schema(
            "message m { required fixed_len_byte_array(4) d (DECIMAL(9,2)); }"
        )
        path3 = str(tmp_path / "dec.parquet")
        with FileWriter(
            path3, dschema, write_page_index=True, max_page_size=2_048,
            use_dictionary=False,
        ) as w:
            w.write_column(
                "d", [int(i).to_bytes(4, "big", signed=True) for i in range(3_000)]
            )
        with FileReader(path3) as r:
            ci, _ = r.read_page_index(0)[("d",)]
            assert ci.boundary_order == int(BoundaryOrder.UNORDERED)

    def test_default_off(self, tmp_path):
        schema = parse_schema("message m { required int64 a; }")
        path = str(tmp_path / "noidx.parquet")
        with FileWriter(path, schema) as w:
            w.write_column("a", np.arange(100, dtype=np.int64))
        with FileReader(path) as r:
            assert r.read_page_index(0)[("a",)] == (None, None)
            # pruning degrades to the whole group
            assert r.prune_pages(0, [("a", ">", 50)]) == [(0, 100)]


class TestReadPyarrowIndex:
    def test_mins_match_and_prune(self, tmp_path):
        n = 60_000
        vals = np.arange(n, dtype=np.int64)
        path = str(tmp_path / "pa_idx.parquet")
        pq.write_table(
            pa.table({"x": vals}), path, row_group_size=n,
            data_page_size=16_384, write_page_index=True, use_dictionary=False,
        )
        with FileReader(path) as r:
            ci, oi = r.read_page_index(0)[("x",)]
            firsts = [loc.first_row_index for loc in oi.page_locations]
            mins = [int(np.frombuffer(m, np.int64)[0]) for m in ci.min_values]
            assert mins == [int(vals[f]) for f in firsts]
            ranges = r.prune_pages(0, [("x", "<", 100)])
            # oracle: every matching row is inside the returned ranges
            assert len(ranges) == 1 and ranges[0][0] == 0 and ranges[0][1] >= 100
            assert r.prune_pages(0, [("x", "==", -5)]) == []

    def test_nullable_string_prune(self, tmp_path):
        n = 30_000
        vals = [None if i % 5 == 0 else f"v{i // 3000}" for i in range(n)]
        path = str(tmp_path / "pa_str.parquet")
        pq.write_table(
            pa.table({"s": pa.array(vals)}), path, row_group_size=n,
            data_page_size=8_192, write_page_index=True, use_dictionary=False,
        )
        with FileReader(path) as r:
            ranges = r.prune_pages(0, [("s", "==", "v9")])
            covered = set()
            for s, e in ranges:
                covered.update(range(s, e))
            matches = {i for i, v in enumerate(vals) if v == "v9"}
            assert matches <= covered  # conservative: no matching row pruned
            assert len(covered) < n  # and it actually pruned something


class TestPruneOracle:
    """prune_pages must never drop a matching row (fuzzed predicates)."""

    def test_fuzzed_predicates(self, tmp_path):
        rng = np.random.default_rng(7)
        n = 20_000
        vals = np.sort(rng.integers(0, 1_000, n)).astype(np.int64)
        schema = parse_schema("message m { required int64 a; }")
        path = str(tmp_path / "fuzz.parquet")
        with FileWriter(
            path, schema, write_page_index=True, max_page_size=4_096,
            use_dictionary=False,
        ) as w:
            w.write_column("a", vals)
        with FileReader(path) as r:
            for op in ("==", "!=", "<", "<=", ">", ">="):
                for v in (int(rng.integers(-10, 1010)), 0, 500, 999):
                    ranges = r.prune_pages(0, [("a", op, v)])
                    covered = np.zeros(n, dtype=bool)
                    for s, e in ranges:
                        covered[s:e] = True
                    mask = {
                        "==": vals == v, "!=": vals != v, "<": vals < v,
                        "<=": vals <= v, ">": vals > v, ">=": vals >= v,
                    }[op]
                    assert not (mask & ~covered).any(), (op, v)


class TestFilteredIterRows:
    def test_filtered_scan_uses_index_and_stays_exact(self, tmp_path):
        n = 50_000
        vals = np.arange(n, dtype=np.int64)
        strs = [f"g{i // 5000}" for i in range(n)]
        schema = parse_schema(
            "message m { required int64 a; required binary s (UTF8); }"
        )
        for wpi in (False, True):
            path = str(tmp_path / f"scan_{wpi}.parquet")
            with FileWriter(
                path, schema, write_page_index=wpi, max_page_size=16_384,
                use_dictionary=False,
            ) as w:
                w.write_column("a", vals)
                w.write_column("s", strs)
            with FileReader(path) as r:
                got = list(r.iter_rows(filters=[("a", ">=", 47_000)]))
                assert [row["a"] for row in got] == list(range(47_000, n))
                got2 = list(
                    r.iter_rows(filters=[("a", "<", 2_000), ("s", "==", "g0")])
                )
                assert [row["a"] for row in got2] == list(range(2_000))
                assert list(r.iter_rows(filters=[("a", "==", -1)])) == []


class TestUnsignedStats:
    def test_uint32_crossing_sign_bit(self, tmp_path):
        """min/max for UINT columns must compare unsigned (review regression:
        signed order inverted around 2^31, silently pruning matching rows)."""
        schema = parse_schema("message m { required int32 a (UINT_32); }")
        vals = np.arange(2_147_480_000, 2_147_500_000, dtype=np.uint32)
        path = str(tmp_path / "uint.parquet")
        with FileWriter(
            path, schema, write_page_index=True, max_page_size=8_192,
            use_dictionary=False,
        ) as w:
            w.write_column("a", vals.view(np.int32))
        target = 2_147_483_700
        with FileReader(path) as r:
            got = list(r.iter_rows(filters=[("a", "==", target)]))
            assert len(got) == 1 and got[0]["a"] == target
            # chunk statistics also carry unsigned order now
            st = r.row_group(0).columns[0].meta_data.statistics
            import struct as _s

            lo = _s.unpack("<I", st.min_value)[0]
            hi = _s.unpack("<I", st.max_value)[0]
            assert (lo, hi) == (int(vals.min()), int(vals.max()))
            # deprecated fields omitted: they are specified signed-compared
            assert st.min is None and st.max is None
        # pyarrow agrees on the written stats
        col = pq.ParquetFile(path).metadata.row_group(0).column(0)
        assert col.statistics.min == int(vals.min())
        assert col.statistics.max == int(vals.max())


class TestSelectivePageDecode:
    """Filtered scans decode only the pages the index admits."""

    def test_exactness_with_nulls_and_strings(self, tmp_path):
        n = 100_000
        vals = np.arange(n, dtype=np.int64)
        strs = [None if i % 7 == 0 else f"u{i}" for i in range(n)]
        schema = parse_schema(
            "message m { required int64 a; optional binary s (UTF8); }"
        )
        path = str(tmp_path / "sel.parquet")
        with FileWriter(
            path, schema, codec="snappy", write_page_index=True,
            max_page_size=16_384, use_dictionary=False,
        ) as w:
            w.write_column("a", vals)
            w.write_column(
                "s",
                [x for x in strs if x is not None],
                def_levels=[0 if x is None else 1 for x in strs],
            )
        with FileReader(path) as r:
            for lo, hi in [(0, 50), (41_000, 42_000), (n - 10, n), (0, n)]:
                got = list(
                    r.iter_rows(filters=[("a", ">=", lo), ("a", "<", hi)])
                )
                assert [row["a"] for row in got] == list(range(lo, hi))
                assert [row["s"] for row in got] == strs[lo:hi]

    def test_dictionary_chunks_and_disjoint_ranges(self, tmp_path):
        n = 60_000
        cats = [f"cat_{i // 20_000}" for i in range(n)]  # 3 blocks of one value
        schema = parse_schema(
            "message m { required int64 a; required binary c (UTF8); }"
        )
        path = str(tmp_path / "dict_sel.parquet")
        with FileWriter(
            path, schema, write_page_index=True, max_page_size=8_192
        ) as w:
            w.write_column("a", np.arange(n, dtype=np.int64))
            w.write_column("c", cats)
        with FileReader(path) as r:
            # two disjoint admitted bands via an OR-like double scan
            got = list(
                r.iter_rows(filters=[("c", "==", "cat_1"), ("a", "<", 25_000)])
            )
            assert [row["a"] for row in got] == list(range(20_000, 25_000))
            assert all(row["c"] == "cat_1" for row in got)

    def test_dnf_or_engages_selective_decode(self, tmp_path):
        """An OR of conjunctions pushes its UNION of admitted ranges into
        selective page decode (trace counter proves engagement), across
        different conjunction columns, with exact results."""
        from parquet_tpu.utils.trace import decode_trace

        n = 120_000
        a = np.arange(n, dtype=np.int64)
        b = (np.arange(n)[::-1]).astype(np.int64)
        schema = parse_schema("message m { required int64 a; required int64 b; }")
        path = str(tmp_path / "dnf_sel.parquet")
        with FileWriter(
            path, schema, codec="snappy", write_page_index=True,
            max_page_size=8_192, use_dictionary=False,
        ) as w:
            w.write_column("a", a)
            w.write_column("b", b)
        dnf = [
            [("a", "<", 300)],                       # head band via column a
            [("b", "<", 200), ("a", ">=", 100)],     # tail band via column b
        ]
        with decode_trace() as t:
            with FileReader(path) as r:
                got = [row["a"] for row in r.iter_rows(filters=dnf)]
        sel = t.stages.get("selective_page_decode")
        assert sel is not None and sel.calls >= 1, t.stages
        want = [i for i in range(n) if i < 300 or (b[i] < 200 and i >= 100)]
        assert got == want

    def test_matches_full_decode(self, tmp_path):
        rng2 = np.random.default_rng(3)
        n = 50_000
        vals = np.sort(rng2.integers(0, 5_000, n)).astype(np.int64)
        schema = parse_schema("message m { required int64 a; }")
        p1 = str(tmp_path / "with_idx.parquet")
        p2 = str(tmp_path / "no_idx.parquet")
        for path, wpi in ((p1, True), (p2, False)):
            with FileWriter(
                path, schema, write_page_index=wpi, max_page_size=4_096,
                use_dictionary=False,
            ) as w:
                w.write_column("a", vals)
        for flt in ([("a", "==", 777)], [("a", ">", 4_990)], [("a", "<=", 3)]):
            with FileReader(p1) as r1, FileReader(p2) as r2:
                assert list(r1.iter_rows(filters=flt)) == list(
                    r2.iter_rows(filters=flt)
                ), flt

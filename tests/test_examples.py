"""Every example runs to completion as a subprocess (its own assertions are
the checks) — the documented entry points must not rot. Each runs in an
isolated temp cwd; the two low-level READER examples get
write_low_level's output produced there first."""

import subprocess
import sys
from pathlib import Path

import pytest

ROOT = Path(__file__).resolve().parent.parent
EXAMPLES = sorted(p.name for p in (ROOT / "examples").glob("*.py"))

# readers of example.parquet (cwd-relative): produce it first
NEEDS_WRITE = {"read_low_level.py", "tpu_columnar_scan.py"}


def _run(name, cwd):
    env = {
        "PATH": "/usr/bin:/bin",
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": "--xla_force_host_platform_device_count=8",
        "HOME": str(cwd),
    }
    return subprocess.run(
        [sys.executable, str(ROOT / "examples" / name)],
        capture_output=True,
        text=True,
        timeout=240,
        env=env,
        cwd=cwd,
    )


@pytest.mark.parametrize("name", EXAMPLES)
def test_example_runs(name, tmp_path):
    if name in NEEDS_WRITE:
        pre = _run("write_low_level.py", tmp_path)
        assert pre.returncode == 0, pre.stderr[-1500:]
    r = _run(name, tmp_path)
    assert r.returncode == 0, f"{name}:\n{r.stdout[-2000:]}\n{r.stderr[-2000:]}"

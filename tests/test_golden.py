"""Golden-corpus conformance: frozen externally-written files, frozen
externally-decoded expectations.

The binaries in tests/golden/data/ were written by pyarrow (Arrow C++) at
fixture-generation time and are committed frozen, with the rows pyarrow
decoded from them frozen as canon()-encoded JSON in tests/golden/expected/.
Reading them here exercises our reader against a genuinely independent
producer — no same-process pyarrow writes — the analogue of the reference's
apache/parquet-testing + Impala golden suites (reference: parquet_test.go:11-38,
parquet_compatibility_test.go:77).

Each fixture is read through BOTH decode backends (host, tpu_roundtrip), and
one write-back lap checks ours -> pyarrow readability of re-encoded goldens.
"""

import json
from pathlib import Path

import pyarrow.parquet as pq
import pytest

from parquet_tpu.core.reader import FileReader

from golden.canon import canon_rows

GOLDEN = Path(__file__).resolve().parent / "golden"
FIXTURES = sorted(p.stem for p in (GOLDEN / "data").glob("*.parquet"))

# fixtures whose values survive a row-level dict comparison through our
# row model (all of them — kept explicit so additions must opt in)
assert FIXTURES, "golden corpus missing — run tests/golden/generate.py"


def _expected(name):
    return json.loads((GOLDEN / "expected" / f"{name}.json").read_text())


@pytest.mark.parametrize("name", FIXTURES)
@pytest.mark.parametrize("backend", ["host", "tpu_roundtrip"])
def test_golden_read(name, backend):
    with FileReader(GOLDEN / "data" / f"{name}.parquet", backend=backend) as r:
        rows = list(r.iter_rows())
    got = canon_rows(rows)
    want = _expected(name)
    assert len(got) == len(want), (len(got), len(want))
    for i, (g, w) in enumerate(zip(got, want)):
        assert g == w, f"{name} row {i}: {g!r} != {w!r}"


@pytest.mark.parametrize("name", ["alltypes_plain_v1_none", "delta_binary_packed"])
def test_golden_rewrite_readable_by_pyarrow(name, tmp_path):
    """ours -> external -> ours: re-encode a golden file with our writer and
    confirm the canonical external implementation reads it identically."""
    from parquet_tpu.core.schema import Schema
    from parquet_tpu.core.writer import FileWriter

    src = GOLDEN / "data" / f"{name}.parquet"
    with FileReader(src) as r:
        schema = Schema.from_thrift(r.metadata.schema)
        rows = list(r.iter_rows(raw=True))
    out = tmp_path / "rewritten.parquet"
    with FileWriter(out, schema=schema) as w:
        w.write_rows(rows)
    back = pq.read_table(out).to_pylist()
    assert canon_rows(back) == _expected(name)


def test_golden_kv_metadata_exposed():
    with FileReader(GOLDEN / "data" / "kv_metadata_and_empty_tail.parquet") as r:
        kv = r.key_value_metadata
    assert kv.get("origin") == "golden-corpus" and kv.get("answer") == "42"


def test_golden_nanotime_precision():
    """The ns-time fixture's odd nanosecond values survive exactly through
    floor.Time (datetime.time would truncate them)."""
    from parquet_tpu.floor import Time

    with FileReader(GOLDEN / "data" / "time_units.parquet") as r:
        (first, *_) = list(r.iter_rows())
    t = first["t_ns"]
    assert isinstance(t, Time) and t.nanos % 2 == 1  # generator forced odd

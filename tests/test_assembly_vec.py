"""The vectorized assembly engine (core/assembly_vec) vs the scalar oracle.

Differential matrix: every nested shape the engine claims — optional
scalars, LIST-of-LIST, MAP, struct-in-list, empty lists, all-null rows,
page boundaries mid-row — must produce byte-identical rows to the scalar
cursor walk (RecordAssembler engine="scalar"), in BOTH ergonomic and raw
modes. Corrupt inputs must fail with the same typed errors under either
engine. The level prefix scans (ops/levels) and their jittable device twin
(kernels/device_ops.list_layout_device) are pinned against each other.
"""

import os

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from parquet_tpu.core.assembly import AssemblyError, RecordAssembler
from parquet_tpu.core import assembly_vec as av
from parquet_tpu.core.reader import PARQUET_ERRORS, FileReader
from parquet_tpu.ops.levels import (
    list_layout,
    rows_from_rep,
    slot_ids,
    validity_from_def,
)
from parquet_tpu.utils import metrics
from parquet_tpu.utils.trace import decode_trace

CORPUS_DIR = os.path.join(os.path.dirname(__file__), "data", "corrupt")


def _nested_tables():
    """The differential-matrix shapes (name -> pyarrow table)."""
    rng = np.random.default_rng(11)
    n = 400

    def some(i, v):
        return None if i % 11 == 0 else v

    t = {}
    t["optional_scalars"] = pa.table(
        {
            "i": pa.array([some(i, i) for i in range(n)], pa.int64()),
            "s": pa.array([some(i + 1, f"v{i % 13}") for i in range(n)]),
        }
    )
    t["list_int"] = pa.table(
        {
            "v": pa.array(
                [
                    some(i, [None if j % 5 == 0 else int(rng.integers(0, 99))
                             for j in range(i % 6)])
                    for i in range(n)
                ],
                pa.list_(pa.int32()),
            )
        }
    )
    t["list_of_list"] = pa.table(
        {
            "ll": pa.array(
                [
                    some(i, [some(j, list(range(j % 4))) for j in range(i % 4)])
                    for i in range(n)
                ],
                pa.list_(pa.list_(pa.int64())),
            )
        }
    )
    t["map"] = pa.table(
        {
            "m": pa.array(
                [
                    some(i, [(f"k{j}", some(j, j * i)) for j in range(i % 4)])
                    for i in range(n)
                ],
                pa.map_(pa.string(), pa.int64()),
            )
        }
    )
    t["struct_in_list"] = pa.table(
        {
            "pts": pa.array(
                [
                    some(i, [some(j + 1, {"x": float(j), "y": some(j, j)})
                             for j in range(i % 5)])
                    for i in range(n)
                ],
                pa.list_(pa.struct([("x", pa.float64()), ("y", pa.int64())])),
            )
        }
    )
    t["struct_of_list"] = pa.table(
        {
            "s": pa.array(
                [
                    some(i, {"l": some(i + 1, list(range(i % 3))), "y": some(i + 2, i)})
                    for i in range(n)
                ],
                pa.struct([("l", pa.list_(pa.int64())), ("y", pa.int32())]),
            )
        }
    )
    t["empty_lists"] = pa.table(
        {"v": pa.array([[] for _ in range(n)], pa.list_(pa.int64()))}
    )
    t["all_null_rows"] = pa.table(
        {
            "v": pa.array([None] * n, pa.list_(pa.int32())),
            "m": pa.array([None] * n, pa.map_(pa.string(), pa.int32())),
        }
    )
    return t


def _engine_rows(path, raw, scalar):
    env = {"PQT_VEC_ASSEMBLY": "0"} if scalar else {}
    old = {k: os.environ.get(k) for k in env}
    os.environ.update(env)
    try:
        with FileReader(path) as r:
            return list(r.iter_rows(raw=raw))
    finally:
        for k, v in old.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


class TestDifferentialMatrix:
    @pytest.mark.parametrize("name", sorted(_nested_tables()))
    @pytest.mark.parametrize("raw", [False, True])
    def test_vec_matches_scalar(self, tmp_path, name, raw):
        table = _nested_tables()[name]
        path = str(tmp_path / f"{name}.parquet")
        pq.write_table(table, path, compression="snappy")
        with FileReader(path) as r:
            chunks = r.read_row_group(0)
            vec = av.assemble_rows(r.schema, chunks, raw)
            scalar = list(
                RecordAssembler(r.schema, chunks, raw=raw, engine="scalar")
            )
        assert vec is not None, f"{name}: engine declined a claimed shape"
        assert vec == scalar

    @pytest.mark.parametrize("raw", [False, True])
    def test_page_boundary_mid_row(self, tmp_path, raw):
        """Multi-entry rows split across pages (tiny data_page_size): the
        whole-chunk level scan must stitch them identically to the walk."""
        rows = [
            None if i % 17 == 0 else [int(x) for x in range(i % 9)]
            for i in range(4000)
        ]
        t = pa.table({"v": pa.array(rows, pa.list_(pa.int64()))})
        path = str(tmp_path / "paged.parquet")
        pq.write_table(
            t, path, data_page_size=512, write_batch_size=64, compression="none"
        )
        with FileReader(path) as r:
            chunks = r.read_row_group(0)
            vec = av.assemble_rows(r.schema, chunks, raw)
            scalar = list(
                RecordAssembler(r.schema, chunks, raw=raw, engine="scalar")
            )
        assert vec is not None and vec == scalar
        if not raw:
            assert [x["v"] for x in vec] == rows

    def test_projection_matches_scalar(self, tmp_path):
        """Partial leaf selection (struct member / map value projected)."""
        t = _nested_tables()["struct_in_list"]
        path = str(tmp_path / "proj.parquet")
        pq.write_table(t, path)
        with FileReader(path, columns=["pts.list.element.x"]) as r:
            chunks = r.read_row_group(0)
            vec = av.assemble_rows(r.schema, chunks, False)
            scalar = list(
                RecordAssembler(r.schema, chunks, raw=False, engine="scalar")
            )
        assert vec is not None and vec == scalar


class TestEngineSelection:
    def test_env_knob_forces_scalar(self, tmp_path, monkeypatch):
        t = _nested_tables()["list_int"]
        path = str(tmp_path / "knob.parquet")
        pq.write_table(t, path)
        monkeypatch.setenv("PQT_VEC_ASSEMBLY", "0")
        with decode_trace() as tr:
            with FileReader(path) as r:
                rows_scalar = list(r.iter_rows())
        assert tr.counters().get("assemble_cursor", 0) >= 1
        assert tr.counters().get("assemble_vec", 0) == 0
        monkeypatch.delenv("PQT_VEC_ASSEMBLY")
        with decode_trace() as tr:
            with FileReader(path) as r:
                rows_vec = list(r.iter_rows())
        assert tr.counters().get("assemble_vec", 0) >= 1
        assert tr.counters().get("assemble_cursor", 0) == 0
        assert rows_scalar == rows_vec

    def test_record_assembler_engine_param(self, tmp_path):
        t = _nested_tables()["list_of_list"]
        path = str(tmp_path / "eng.parquet")
        pq.write_table(t, path)
        with FileReader(path) as r:
            chunks = r.read_row_group(0)
            auto = list(RecordAssembler(r.schema, chunks))
            vec = list(RecordAssembler(r.schema, chunks, engine="vec"))
            scalar = list(RecordAssembler(r.schema, chunks, engine="scalar"))
        assert auto == vec == scalar

    def test_metrics_and_trace_stage(self, tmp_path):
        t = _nested_tables()["list_int"]
        path = str(tmp_path / "met.parquet")
        pq.write_table(t, path)
        before = metrics.snapshot()
        with decode_trace() as tr:
            with FileReader(path) as r:
                n = sum(1 for _ in r.iter_rows())
        d = metrics.delta(before)
        assert d.get('assembly_rows_total{engine="vec"}', 0) == n
        assert d.get("assembly_seconds_count", 0) >= 1
        assert "assembly.rows" in tr.stages
        # scalar engine reports under its own label
        before = metrics.snapshot()
        _engine_rows(path, False, scalar=True)
        d = metrics.delta(before)
        assert d.get('assembly_rows_total{engine="scalar"}', 0) == n


class TestTypedErrors:
    """Corrupt input must fail IDENTICALLY typed under either engine."""

    def _outcome(self, path, scalar):
        try:
            return ("rows", _engine_rows(path, False, scalar))
        except PARQUET_ERRORS + (AssemblyError,) as e:
            return ("error", type(e).__name__)

    def test_corrupt_corpus_parity(self):
        names = sorted(
            f for f in os.listdir(CORPUS_DIR) if f.endswith(".parquet")
        )
        assert names, "corrupt corpus missing"
        for name in names:
            path = os.path.join(CORPUS_DIR, name)
            vec = self._outcome(path, scalar=False)
            scl = self._outcome(path, scalar=True)
            assert vec[0] == scl[0], (name, vec, scl)
            if vec[0] == "rows":
                assert vec[1] == scl[1], name

    def test_injected_level_faults_parity(self, tmp_path):
        """Seeded level-stream corruption on a NESTED table: both engines
        deliver identical rows or raise the same typed error family."""
        from parquet_tpu.testing.faults import iter_fault_cases

        t = pa.table(
            {
                "v": pa.array(
                    [[1, 2], None, [], [3, None, 4]] * 40, pa.list_(pa.int64())
                )
            }
        )
        base = str(tmp_path / "base.parquet")
        pq.write_table(t, base, compression="none")
        data = open(base, "rb").read()
        ran = 0
        for case in iter_fault_cases(data, seed=5):
            if "level" not in case.name:
                continue
            ran += 1
            p = str(tmp_path / f"{case.name}.parquet")
            with open(p, "wb") as f:
                f.write(case.data)
            vec = self._outcome(p, scalar=False)
            scl = self._outcome(p, scalar=True)
            assert vec[0] == scl[0], (case.name, vec, scl)
            if vec[0] == "rows":
                assert vec[1] == scl[1], case.name
        assert ran >= 1

    def test_value_count_mismatch_raises_assembly_error(self, tmp_path):
        """A chunk whose values disagree with its def levels raises the
        typed AssemblyError from the vec engine too (not a silent wrong
        answer, not an internal numpy error)."""
        t = pa.table({"v": pa.array([[1, 2], [3]], pa.list_(pa.int64()))})
        path = str(tmp_path / "vc.parquet")
        pq.write_table(t, path)
        with FileReader(path) as r:
            chunks = r.read_row_group(0)
            (path_key,) = chunks
            cd = chunks[path_key]
            cd.values = np.asarray(cd.values)[:-1]  # drop one value
            with pytest.raises(AssemblyError):
                av.assemble_rows(r.schema, chunks, False)
            with pytest.raises(AssemblyError):
                list(RecordAssembler(r.schema, chunks, raw=False, engine="scalar"))


class TestLevelScans:
    def test_rows_from_rep(self):
        rep = np.array([0, 1, 1, 0, 0, 1], np.uint16)
        assert rows_from_rep(rep).tolist() == [0, 3, 4]
        assert rows_from_rep(None, 4).tolist() == [0, 1, 2, 3]
        with pytest.raises(ValueError):
            rows_from_rep(None)

    def test_slot_ids(self):
        rep = np.array([0, 1, 2, 1, 0], np.uint16)
        assert slot_ids(rep, 0).tolist() == [0, 0, 0, 0, 1]
        assert slot_ids(rep, 1).tolist() == [0, 1, 1, 2, 3]

    def test_list_layout(self):
        # two rows: [a, b] and [] (placeholder entry def<elem_def)
        rep = np.array([0, 1, 0], np.uint16)
        dfl = np.array([3, 3, 0], np.uint16)
        so = slot_ids(rep, 0)
        offs, elem_start, exists = list_layout(rep, dfl, so, 2, 1, 1)
        assert offs.tolist() == [0, 2, 2]
        assert elem_start.tolist() == [True, True, False]
        assert exists.tolist() == [True, True, False]

    def test_validity_from_def(self):
        assert validity_from_def(np.array([2, 2]), 0) is None
        assert validity_from_def(np.array([2, 2]), 1) is None
        assert validity_from_def(np.array([2, 0, 1]), 2).tolist() == [0, 1, 1]


class TestDeviceKernel:
    def test_matches_host_scan_random(self):
        import jax.numpy as jnp

        from parquet_tpu.kernels.device_ops import (
            list_layout_device,
            record_starts_device,
        )

        rng = np.random.default_rng(3)
        for trial in range(4):
            n = int(rng.integers(10, 3000))
            rep = np.zeros(n, np.int32)
            rep[rng.random(n) < 0.6] = 1
            rep[0] = 0
            dfl = rng.integers(0, 4, n).astype(np.int32)
            dfl[rep == 1] = np.maximum(dfl[rep == 1], 2)
            so = slot_ids(rep, 0)
            n_slots = len(rows_from_rep(rep))
            offs, _es, _ex = list_layout(rep, dfl, so, n_slots, 1, 2)
            d_offs, d_first, d_n = list_layout_device(
                jnp.asarray(rep), jnp.asarray(dfl), 0, 2
            )
            assert int(d_n) == n_slots
            assert np.array_equal(np.asarray(d_offs)[: n_slots + 1], offs)
            starts = np.flatnonzero(rep == 0)
            assert np.array_equal(np.asarray(d_first)[:n_slots], dfl[starts])
            row_of, n_rows = record_starts_device(jnp.asarray(rep))
            assert int(n_rows) == n_slots
            assert np.array_equal(np.asarray(row_of), so)

    def test_device_column_list_layout(self, tmp_path):
        """Device-decoded level streams assemble on device: the offsets the
        kernel computes in HBM equal the host engine's for the same chunk."""
        t = pa.table(
            {
                "v": pa.array(
                    [[1, 2, 3], [], None, [4]] * 100, pa.list_(pa.int32())
                )
            }
        )
        path = str(tmp_path / "dev.parquet")
        pq.write_table(t, path, compression="none")
        with FileReader(path, backend="tpu") as r:
            cols = r.read_row_group_device(0)
            (dc,) = cols.values()
            leaf = [c for c in r.schema.leaves][0]
            offs, first_def, n_slots = dc.list_layout(0, leaf.max_def - 1)
        with FileReader(path) as r:
            chunks = r.read_row_group(0)
            (cd,) = chunks.values()
            so = slot_ids(np.asarray(cd.rep_levels), 0)
            n_host = len(rows_from_rep(np.asarray(cd.rep_levels)))
            h_offs, _es, _ex = list_layout(
                np.asarray(cd.rep_levels),
                np.asarray(cd.def_levels),
                so,
                n_host,
                1,
                leaf.max_def - 1,
            )
        assert int(n_slots) == n_host
        assert np.array_equal(np.asarray(offs)[: n_host + 1], h_offs)


class TestArrowHandoff:
    def test_nested_to_arrow_rides_engine(self, tmp_path):
        """to_arrow's nested path consumes the engine's IR and still
        matches pyarrow exactly."""
        for name in ("list_of_list", "struct_in_list", "struct_of_list", "map"):
            t = _nested_tables()[name]
            path = str(tmp_path / f"{name}.parquet")
            pq.write_table(t, path)
            with FileReader(path) as r:
                out = r.to_arrow()
            want = pq.read_table(path)
            for c in want.column_names:
                assert out.column(c).to_pylist() == want.column(c).to_pylist(), name

    def test_build_field_vec_arrow_mode(self, tmp_path):
        t = _nested_tables()["list_int"]
        path = str(tmp_path / "ir.parquet")
        pq.write_table(t, path)
        with FileReader(path) as r:
            chunks = r.read_row_group(0)
            vec, n = av.build_field_vec(r.schema, "v", chunks, mode="arrow")
        assert isinstance(vec, av.ListVec)
        assert n == t.num_rows
        assert len(vec.offsets) == n + 1
        assert int(vec.offsets[-1]) == sum(
            len(x) for x in t.column("v").to_pylist() if x
        )

"""Explicit device placement (`device=`) must reach the dispatch thread.

Round-3 regression (MULTICHIP_r03 rc=1): CPU pinning via a caller-side
`jax.default_device(...)` context is THREAD-LOCAL, so uploads issued from the
reader's single `pqt-dispatch` worker landed on the process-default platform
instead. These tests reproduce that exact shape deterministically on the
virtual CPU mesh: the process default is one device, the reader is pinned to
a DIFFERENT one, and every delivered array must land on the pinned device —
which only happens if the placement travels with the work onto the dispatch
thread (core/reader.py:_with_device).
"""

import contextlib

import jax
import numpy as np
import pytest

from parquet_tpu.core.reader import FileReader, MaskedColumn
from parquet_tpu.core.writer import FileWriter
from parquet_tpu.parallel.scan import scan_row_groups
from parquet_tpu.schema.dsl import parse_schema


@contextlib.contextmanager
def process_default_device(dev):
    """Set the process-GLOBAL default device (what the dispatch thread sees
    when no placement travels with the work)."""
    prev = jax.config.jax_default_device
    jax.config.update("jax_default_device", dev)
    try:
        yield
    finally:
        jax.config.update("jax_default_device", prev)


@pytest.fixture(scope="module")
def mixed_file(tmp_path_factory):
    """One file that exercises every dispatch path: dict-encoded ints,
    delta-packed ints, plain floats, dict byte arrays, a nullable column."""
    path = tmp_path_factory.mktemp("devpin") / "mixed.parquet"
    n = 4_000
    rng = np.random.default_rng(3)
    schema = parse_schema(
        "message m { required int64 a; required int64 ts; required double x;"
        " required binary s (STRING); optional int64 opt; }"
    )
    with FileWriter(
        str(path),
        schema,
        codec="snappy",
        row_group_size=1_024,  # bytes: forces a flush at every 1000-row check -> 4 groups
        column_encodings={"ts": "DELTA_BINARY_PACKED"},
    ) as w:
        rows = [
            {
                "a": int(rng.integers(0, 40)),
                "ts": 100 + i,
                "x": float(i) * 0.5,
                "s": b"v%d" % (i % 25),
                "opt": None if i % 7 == 0 else i,
            }
            for i in range(n)
        ]
        for lo in range(0, n, 1_000):  # four explicit row groups
            w.write_rows(rows[lo : lo + 1_000])
            w.flush_row_group()
    return str(path)


def _leaf_arrays(dc):
    for name in ("values", "indices", "data", "offsets", "dict_data", "dict_offsets"):
        arr = getattr(dc, name, None)
        if arr is not None and hasattr(arr, "devices"):
            yield name, arr


def test_reader_device_overrides_process_default(mixed_file):
    cpus = jax.devices("cpu")
    assert len(cpus) >= 4
    with process_default_device(cpus[1]):
        with FileReader(mixed_file, backend="tpu", device=cpus[3]) as r:
            groups = r.read_row_groups_device()
    assert groups
    seen = 0
    for g in groups:
        for path, dc in g.items():
            for name, arr in _leaf_arrays(dc):
                assert arr.devices() == {cpus[3]}, (path, name, arr.devices())
                seen += 1
    assert seen > 0


def test_per_call_device_overrides_reader_default(mixed_file):
    cpus = jax.devices("cpu")
    with process_default_device(cpus[1]):
        with FileReader(mixed_file, backend="tpu", device=cpus[2]) as r:
            g = r.read_row_group_device(0, device=cpus[5])
            for dc in g.values():
                for _, arr in _leaf_arrays(dc):
                    assert arr.devices() == {cpus[5]}
            # and without the override, the reader default applies
            g2 = r.read_row_group_device(1)
            for dc in g2.values():
                for _, arr in _leaf_arrays(dc):
                    assert arr.devices() == {cpus[2]}


def test_iter_device_batches_honors_device(mixed_file):
    cpus = jax.devices("cpu")
    with process_default_device(cpus[1]):
        with FileReader(mixed_file, backend="tpu", columns=["a", "ts", "opt"]) as r:
            total = 0
            for batch in r.iter_device_batches(
                512, nullable="mask", device=cpus[4], drop_remainder=False
            ):
                for col in batch.values():
                    arrs = (
                        [col.values, col.mask]
                        if isinstance(col, MaskedColumn)
                        else [col]
                    )
                    for arr in arrs:
                        assert arr.devices() == {cpus[4]}
                total += next(iter(batch.values())).shape[0] if not isinstance(
                    next(iter(batch.values())), MaskedColumn
                ) else next(iter(batch.values())).values.shape[0]
            assert total == 4_000
        # the device pin must not leak into the consumer's frame after
        # iteration: thread-local default is unchanged
        probe = jax.numpy.zeros(1)
        assert probe.devices() == {cpus[1]}


def test_scan_round_robin_places_shards(mixed_file):
    cpus = jax.devices("cpu")
    placed = []
    with process_default_device(cpus[1]):
        with FileReader(mixed_file, backend="tpu") as r:
            out = scan_row_groups(
                r,
                [cpus[2], cpus[6]],
                map_fn=lambda cols: (
                    placed.append(
                        next(iter(cols[("a",)].values.devices()))
                    )
                    or cols[("a",)].values.sum()
                ),
                reduce_fn=lambda x, y: x + y,
            )
    assert int(out) >= 0
    # groups alternate devices 2,6,2,6: the per-shard decode landed where
    # the round-robin said, not on the process default
    assert placed == [cpus[2], cpus[6], cpus[2], cpus[6]]


def test_leak_shape_regression(mixed_file):
    """The round-3 failure shape: pinning ONLY via a caller-thread context
    must be insufficient (documents why device= exists) — dispatch-thread
    uploads follow the process default, not the caller's thread-local."""
    cpus = jax.devices("cpu")
    with process_default_device(cpus[1]):
        with jax.default_device(cpus[3]):  # thread-local only
            with FileReader(mixed_file, backend="tpu") as r:
                g = r.read_row_group_device(0)
    landed = {
        next(iter(arr.devices()))
        for dc in g.values()
        for _, arr in _leaf_arrays(dc)
    }
    # at least one dispatch-thread upload escaped the caller's context
    assert cpus[1] in landed

"""Sharded row-group scans on the virtual 8-device CPU mesh (conftest)."""

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq

import jax

from parquet_tpu.core.reader import FileReader
from parquet_tpu.parallel.scan import column_stats, scan_row_groups

rng = np.random.default_rng(21)


def _file(tmp_path, n=40_000, rg=5_000):
    t = pa.table(
        {
            "x": pa.array(rng.integers(-(2**40), 2**40, n).astype(np.int64)),
            "f": pa.array(rng.standard_normal(n)),
            "cat": pa.array([f"c{i % 9}" for i in range(n)]),
        }
    )
    path = str(tmp_path / "scan.parquet")
    pq.write_table(t, path, row_group_size=rg, compression="snappy")
    return path, t


class TestShardedScan:
    def test_column_stats_match_numpy(self, tmp_path):
        path, t = _file(tmp_path)
        with FileReader(path) as r:
            stats = column_stats(r, jax.devices(), columns=["x", "f"])
        x = np.asarray(t.column("x"))
        f = np.asarray(t.column("f"))
        assert stats[("x",)]["min"] == x.min()
        assert stats[("x",)]["max"] == x.max()
        assert stats[("x",)]["count"] == len(x)
        np.testing.assert_allclose(stats[("f",)]["min"], f.min())
        np.testing.assert_allclose(stats[("f",)]["max"], f.max())

    def test_shards_land_on_distinct_devices(self, tmp_path):
        path, _ = _file(tmp_path)
        seen = []

        def map_fn(cols):
            v = cols[("x",)].values
            seen.append(next(iter(v.devices())))
            return v.sum()

        with FileReader(path) as r:
            total = scan_row_groups(
                r, jax.devices(), map_fn, lambda a, b: a + b, columns=["x"]
            )
        assert len(set(seen)) == min(8, len(seen))  # round-robin placement
        with FileReader(path) as r:
            want = sum(
                int(np.asarray(c[("x",)].values).sum())
                for c in r.iter_row_groups(columns=["x"])
            )
        assert int(total) == want

    def test_jitted_map_per_shard(self, tmp_path):
        path, t = _file(tmp_path)
        fare_gt_zero = jax.jit(lambda v: (v > 0).sum())

        def map_fn(cols):
            return fare_gt_zero(cols[("f",)].values)

        with FileReader(path) as r:
            total = scan_row_groups(
                r, jax.devices(), map_fn, lambda a, b: a + b, columns=["f"]
            )
        assert int(total) == int((np.asarray(t.column("f")) > 0).sum())

    def test_empty_selection_and_no_devices(self, tmp_path):
        path, _ = _file(tmp_path, n=100, rg=100)
        with FileReader(path) as r:
            stats = column_stats(r, jax.devices(), columns=["cat"])
        assert stats == {}  # dict strings have no numeric values array
        import pytest

        with FileReader(path) as r:
            with pytest.raises(ValueError, match="no devices"):
                scan_row_groups(r, [], lambda c: 0, lambda a, b: a)

    def test_all_null_column_has_no_bounds(self, tmp_path):
        # a column with zero values in every row group must not surface the
        # fold identity (inverted dtype extremes) as real min/max
        t = pa.table({"x": pa.array([None] * 2000, pa.int64())})
        path = str(tmp_path / "allnull.parquet")
        pq.write_table(t, path, row_group_size=500)
        with FileReader(path) as r:
            stats = column_stats(r, jax.devices(), columns=["x"])
        assert stats[("x",)] == {"min": None, "max": None, "count": 0}

    def test_all_null_boolean_shard(self, tmp_path):
        # regression: empty bool values array must yield identity stats,
        # not a jnp.iinfo(bool) crash
        t = pa.table({"b": pa.array([None] * 1000 + [True, False] * 500, pa.bool_())})
        path = str(tmp_path / "nb.parquet")
        pq.write_table(t, path, row_group_size=1000)
        with FileReader(path) as r:
            stats = column_stats(r, jax.devices(), columns=["b"])
        assert stats[("b",)]["min"] == False  # noqa: E712
        assert stats[("b",)]["max"] == True  # noqa: E712


class TestDistributedStats:
    """Multi-host shape of the stats scan: per-process row-group sharding +
    global mesh reduction (simulated with replicas on the virtual mesh)."""

    def test_process_row_groups_partition(self):
        from parquet_tpu.parallel.scan import process_row_groups

        shards = [process_row_groups(10, pi, 4) for pi in range(4)]
        assert sorted(i for s in shards for i in s) == list(range(10))
        assert shards[1] == [1, 5, 9]

    def test_single_process_stats(self, tmp_path):
        from parquet_tpu.parallel.scan import distributed_column_stats

        t = pa.table(
            {
                "x": pa.array(np.arange(50_000, dtype=np.int64)),
                "f": pa.array(np.linspace(-5, 5, 50_000)),
            }
        )
        path = str(tmp_path / "d.parquet")
        pq.write_table(t, path, row_group_size=8_000, use_dictionary=False)
        with FileReader(path) as r:
            out = distributed_column_stats(r)
        assert out[("x",)] == {"min": 0, "max": 49_999, "count": 50_000}
        assert out[("f",)]["count"] == 50_000
        assert abs(out[("f",)]["min"] + 5) < 1e-9

    def test_mesh_reduce_partials(self):
        """Eight replicated partials reduce to one global result, identical
        everywhere — the DCN/ICI collective of the multi-host path."""
        import jax
        import jax.numpy as jnp
        from jax.sharding import Mesh

        from parquet_tpu.parallel.scan import mesh_reduce_stats

        mesh = Mesh(np.array(jax.devices("cpu")[:8]), ("hosts",))
        partial = {
            ("x",): {
                "min": jnp.asarray(3, jnp.int64),
                "max": jnp.asarray(9, jnp.int64),
                "count": jnp.asarray(5, jnp.int64),
            }
        }
        out = mesh_reduce_stats(partial, mesh)
        assert int(out[("x",)]["count"]) == 40  # psum over 8 participants
        # with the 8 positions declared as replicas of ONE participant the
        # count divides back out
        out1 = mesh_reduce_stats(partial, mesh, replicas_per_participant=8)
        assert int(out1[("x",)]["count"]) == 5
        assert int(out[("x",)]["min"]) == 3 and int(out[("x",)]["max"]) == 9

    def test_forced_mesh_reduction_end_to_end(self, tmp_path):
        """distributed_column_stats with an explicit mesh exercises the
        collective even in a single-process program."""
        import jax
        from jax.sharding import Mesh

        from parquet_tpu.parallel.scan import distributed_column_stats

        t = pa.table({"x": pa.array(np.arange(10_000, dtype=np.int64))})
        path = str(tmp_path / "m.parquet")
        pq.write_table(t, path, row_group_size=2_500, use_dictionary=False)
        mesh = Mesh(np.array(jax.devices("cpu")[:4]), ("hosts",))
        with FileReader(path) as r:
            out = distributed_column_stats(r, mesh=mesh)
        # this single process owns all 4 mesh positions (replicas), so the
        # psum'd count divides back to the true count
        assert out[("x",)]["count"] == 10_000
        assert out[("x",)]["min"] == 0 and out[("x",)]["max"] == 9_999


def test_column_stats_with_filter_pushdown(tmp_path):
    """filters= prunes row groups before the distributed scan decodes them."""
    from parquet_tpu import FileWriter, parse_schema

    schema = parse_schema("message m { required int64 x; }")
    path = str(tmp_path / "scanf.parquet")
    with FileWriter(path, schema, use_dictionary=False) as w:
        for base in (0, 1_000_000):
            w.write_column("x", np.arange(base, base + 4_096, dtype=np.int64))
            w.flush_row_group()
    devices = jax.devices("cpu")[:4]
    with FileReader(path) as r:
        full = column_stats(r, devices)
        assert full[("x",)]["count"] == 8_192
        part = column_stats(r, devices, filters=[("x", ">=", 1_000_000)])
        assert part[("x",)]["count"] == 4_096
        assert part[("x",)]["min"] == 1_000_000
        empty = column_stats(r, devices, filters=[("x", "<", -1)])
        assert empty == {} or all(v["count"] == 0 for v in empty.values())


def test_distributed_column_stats_with_filters(tmp_path):
    from parquet_tpu import FileWriter, parse_schema
    from parquet_tpu.parallel.scan import distributed_column_stats

    schema = parse_schema("message m { required int64 x; }")
    path = str(tmp_path / "dscanf.parquet")
    with FileWriter(path, schema, use_dictionary=False) as w:
        for base in (0, 1_000_000):
            w.write_column("x", np.arange(base, base + 2_048, dtype=np.int64))
            w.flush_row_group()
    with FileReader(path) as r:
        st = distributed_column_stats(
            r, devices=jax.devices("cpu")[:4], filters=[("x", "<", 1_000)]
        )
        assert st[("x",)]["count"] == 2_048  # group 0 whole, group 1 pruned
        assert st[("x",)]["max"] == 2_047

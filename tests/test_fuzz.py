"""Adversarial-input tests: mutation fuzzing + frozen crasher corpus.

The reference's main defense for untrusted Parquet input is go-fuzz plus
crashers frozen as unit tests (reference: reader_fuzz.go, fuzz_test.go:11,
SURVEY §4.3). Here: deterministic byte-mutation sweeps over valid files — every
mutation must either decode (possibly to different values) or raise a clean
ValueError subclass; never segfault, hang, or leak internal exceptions — plus
a frozen corpus of inputs that were found to misbehave during development.
"""

import io
from pathlib import Path

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from parquet_tpu.core.reader import FileReader
from parquet_tpu.meta import ParquetFileError, read_file_metadata

CLEAN_ERRORS = (ValueError, IndexError, EOFError, OverflowError, MemoryError)
# ValueError covers all framework errors (ParquetFileError, ChunkError, ...);
# IndexError/EOFError can escape numpy slicing on truncated buffers — accepted
# as "clean" (no corruption, no hang), matching the reference's recovered-panic
# model (reference: file_reader.go:177-184).


def _try_read(data: bytes) -> None:
    try:
        with FileReader(io.BytesIO(data)) as r:
            for _ in r.iter_rows():
                pass
    except CLEAN_ERRORS:
        pass


@pytest.fixture(scope="module")
def valid_file() -> bytes:
    t = pa.table(
        {
            "i": pa.array(range(500), pa.int64()),
            "s": pa.array([f"s{i % 13}" for i in range(500)]),
            "l": pa.array([[i, i + 1] if i % 3 else None for i in range(500)], pa.list_(pa.int32())),
        }
    )
    buf = io.BytesIO()
    pq.write_table(t, buf, compression="snappy")
    return buf.getvalue()


class TestMutationSweep:
    def test_single_byte_flips(self, valid_file):
        rng = np.random.default_rng(1234)
        data = bytearray(valid_file)
        for _ in range(300):
            pos = int(rng.integers(0, len(data)))
            old = data[pos]
            data[pos] ^= int(rng.integers(1, 256))
            _try_read(bytes(data))
            data[pos] = old

    def test_truncations(self, valid_file):
        for cut in range(1, len(valid_file), max(len(valid_file) // 64, 1)):
            _try_read(valid_file[:cut])

    def test_footer_region_mutations(self, valid_file):
        rng = np.random.default_rng(99)
        data = bytearray(valid_file)
        start = max(len(data) - 400, 0)
        for _ in range(300):
            pos = int(rng.integers(start, len(data)))
            old = data[pos]
            data[pos] ^= int(rng.integers(1, 256))
            _try_read(bytes(data))
            data[pos] = old

    def test_random_garbage(self):
        rng = np.random.default_rng(7)
        for n in [0, 1, 8, 12, 100, 5000]:
            blob = rng.integers(0, 256, n).astype(np.uint8).tobytes()
            _try_read(blob)
            _try_read(b"PAR1" + blob + b"PAR1")

    def test_shuffled_pages(self, valid_file):
        # swap two interior chunks of the file body
        data = bytearray(valid_file)
        if len(data) > 600:
            a, b = 50, 300
            data[a : a + 100], data[b : b + 100] = data[b : b + 100], data[a : a + 100]
            _try_read(bytes(data))


class TestFrozenCrashers:
    """Inputs that exposed real bugs during development, frozen forever
    (the reference's fuzz_test.go pattern)."""

    def test_thrift_nesting_bomb(self):
        payload = b"\x1c" * 5000 + b"\x00" * 5000
        f = io.BytesIO(
            b"PAR1" + payload + len(payload).to_bytes(4, "little") + b"PAR1"
        )
        with pytest.raises(ParquetFileError):
            read_file_metadata(f)

    def test_delta_allocation_bomb(self):
        from parquet_tpu.ops.delta import DeltaError, prescan_delta
        from parquet_tpu.ops.varint import emit_uvarint

        bomb = bytearray()
        emit_uvarint(bomb, 128)
        emit_uvarint(bomb, 4)
        emit_uvarint(bomb, 1 << 30)
        bomb += b"\x00\x00" + bytes(4)
        with pytest.raises(DeltaError):
            prescan_delta(bytes(bomb), 32)

    def test_hybrid_group_count_overflow(self):
        from parquet_tpu.ops.rle_hybrid import HybridError, prescan_hybrid
        from parquet_tpu.ops.varint import emit_uvarint

        bomb = bytearray()
        emit_uvarint(bomb, ((1 << 58) << 1) | 1)
        with pytest.raises(HybridError):
            prescan_hybrid(bytes(bomb), 10, 64)

    def test_schema_child_count_lies(self):
        from parquet_tpu.core.schema import Schema, SchemaError
        from parquet_tpu.meta.parquet_types import SchemaElement

        elements = [
            SchemaElement(name="root", num_children=1),
            SchemaElement(name="A", num_children=2),
            SchemaElement(name="X", num_children=1),
            SchemaElement(name="Y", type=1),
        ]
        with pytest.raises(SchemaError):
            Schema.from_thrift(elements)

    def test_empty_rowgroup_zero_data_offset(self, tmp_path):
        # pyarrow writes data_page_offset=0 for empty row groups
        path = str(tmp_path / "e.parquet")
        pq.write_table(pa.table({"x": pa.array([], pa.int64())}), path)
        with FileReader(path) as r:
            assert list(r.iter_rows()) == []

    def test_snappy_corrupt_stream(self):
        from parquet_tpu.core.compress import CompressionError, decompress_block
        from parquet_tpu.meta import CompressionCodec

        with pytest.raises(CompressionError):
            decompress_block(b"\xff\xff\xff\xff\xff", CompressionCodec.SNAPPY, 10)

    def test_native_page_header_varint_near_2e64_no_hang(self):
        """A binary field whose varint length is near 2^64 must not wrap the
        native parser's bound check into an infinite loop (cp_skip used an
        addition-form check; now subtraction-form)."""
        from parquet_tpu.ops.varint import emit_uvarint
        from parquet_tpu.utils.native import get_native

        lib = get_native()
        if lib is None or not lib.has_parse_page_header:
            pytest.skip("native library not built")
        crafted = bytearray([0xF8])  # unknown field, delta 15, wire 8 (binary)
        emit_uvarint(crafted, 2**64 - 11)
        crafted += bytes(64)
        # must terminate promptly: either "window truncated" (None) or raise
        result = lib.parse_page_header(bytes(crafted))
        assert result is None

    def test_native_delta_prescan_parity_and_negative_bound(self):
        from parquet_tpu.ops.delta import DeltaError, encode_delta, prescan_delta_packed
        from parquet_tpu.utils.native import get_native

        vals = np.arange(5000, dtype=np.int64) * 7 - 123456
        enc = encode_delta(vals, 64)
        t_bound = prescan_delta_packed(enc, 64, max_total=5000)  # native if built
        t_py = prescan_delta_packed(enc, 64, max_total=None)  # always Python
        assert np.array_equal(t_bound.widths, t_py.widths)
        assert np.array_equal(t_bound.byte_starts, t_py.byte_starts)
        assert np.array_equal(t_bound.out_starts, t_py.out_starts)
        assert np.array_equal(t_bound.mins, t_py.mins)
        assert (t_bound.first_value, t_bound.total, t_bound.consumed) == (
            t_py.first_value,
            t_py.total,
            t_py.consumed,
        )
        if get_native() is not None:
            # a negative bound clamps to 0 on both paths -> rejects any values
            with pytest.raises(DeltaError):
                prescan_delta_packed(enc, 64, max_total=-5)
        with pytest.raises(DeltaError):
            prescan_delta_packed(enc, 64, max_total=4999)

    def test_native_delta_prescan_huge_bound_tiny_stream(self):
        """A lying page header (huge num_values) must not drive table
        allocation: entries are bounded by the stream length too."""
        from parquet_tpu.ops.delta import encode_delta, prescan_delta_packed

        enc = encode_delta(np.arange(100, dtype=np.int64), 64)
        t = prescan_delta_packed(enc, 64, max_total=2**40)
        assert t.total == 100


class TestInt96:
    def test_roundtrip(self):
        import datetime as dt

        from parquet_tpu.utils.int96 import datetime_to_int96, int96_to_datetime

        for ts in [
            dt.datetime(2024, 5, 1, 12, 30, 45, 123456, tzinfo=dt.timezone.utc),
            dt.datetime(1969, 12, 31, 23, 59, 59, tzinfo=dt.timezone.utc),
            dt.datetime(1970, 1, 1, tzinfo=dt.timezone.utc),
        ]:
            assert int96_to_datetime(datetime_to_int96(ts)) == ts

    def test_epoch_check(self):
        import datetime as dt

        from parquet_tpu.utils.int96 import datetime_to_int96, is_after_unix_epoch

        assert is_after_unix_epoch(
            datetime_to_int96(dt.datetime(2020, 1, 1, tzinfo=dt.timezone.utc))
        )
        assert not is_after_unix_epoch(
            datetime_to_int96(dt.datetime(1960, 1, 1, tzinfo=dt.timezone.utc))
        )

    def test_vectorized_matches_scalar(self):
        import numpy as np

        from parquet_tpu.utils.int96 import (
            int96_array_to_unix_nanos,
            int96_to_unix_nanos,
        )

        rng = np.random.default_rng(5)
        # realistic encodings: nanos within one day, julian day near the epoch
        nanos = rng.integers(0, 86_400_000_000_000, 50).astype("<u8")
        jday = rng.integers(2_400_000, 2_500_000, 50).astype("<u4")
        arr = np.concatenate(
            [nanos.view(np.uint8).reshape(50, 8), jday.view(np.uint8).reshape(50, 4)],
            axis=1,
        )
        vec = int96_array_to_unix_nanos(arr)
        for i in range(50):
            assert vec[i] == int96_to_unix_nanos(arr[i].tobytes())

    def test_pyarrow_int96_file(self, tmp_path):
        import datetime as dt

        ts = [dt.datetime(2015, 6, 1, 10, 30, tzinfo=dt.timezone.utc), None]
        t = pa.table({"ts": pa.array(ts, pa.timestamp("ns", tz="UTC"))})
        path = str(tmp_path / "i96.parquet")
        pq.write_table(t, path, use_deprecated_int96_timestamps=True)
        with FileReader(path) as r:
            rows = list(r.iter_rows())
        assert rows[0]["ts"] == ts[0]
        assert rows[1]["ts"] is None


class TestTrace:
    def test_stage_report(self, tmp_path):
        from parquet_tpu.utils.trace import decode_trace

        t = pa.table({"x": pa.array(range(1000), pa.int64())})
        path = str(tmp_path / "t.parquet")
        pq.write_table(t, path, compression="gzip")
        with decode_trace() as tr:
            with FileReader(path) as r:
                list(r.iter_rows())
        assert "decompress" in tr.stages
        assert "decode" in tr.stages
        assert tr.stages["io"].bytes > 0
        assert "MB/s" in tr.report() or "ms" in tr.report()

    def test_no_overhead_when_inactive(self, tmp_path):
        from parquet_tpu.utils import trace

        assert trace.current() is None  # nothing leaks between tests
        assert not trace.active()


class TestAllocCeiling:
    """The decoded-memory ceiling must bound ACTUAL decoded buffers, not the
    (attacker-controlled) advertised page sizes (reference: alloc.go:10-89)."""

    def test_rle_expansion_bomb(self, tmp_path):
        """A few KB of RLE dict indices expanding to tens of MB of decoded
        values must trip a small ceiling, on both decode backends."""
        from parquet_tpu.core.alloc import AllocError

        t = pa.table({"s": pa.array(["same-value"] * 1_000_000)})
        path = str(tmp_path / "bomb.parquet")
        pq.write_table(t, path, use_dictionary=["s"], compression="snappy")
        assert Path(path).stat().st_size < 200_000  # tiny on disk
        for backend in ("host", "tpu_roundtrip"):
            with FileReader(path, backend=backend, max_memory=1 << 20) as r:
                with pytest.raises(AllocError):
                    for i in range(r.num_row_groups):
                        r.read_row_group(i)

    def test_ceiling_released_per_row_group(self, tmp_path):
        from parquet_tpu.core.alloc import AllocError

        t = pa.table({"x": pa.array(np.arange(200_000, dtype=np.int64))})
        path = str(tmp_path / "rg.parquet")
        pq.write_table(t, path, row_group_size=50_000, use_dictionary=False)
        # each group decodes to ~400KB; a 1MB ceiling passes only if the
        # budget is released between groups
        with FileReader(path, max_memory=1 << 20) as r:
            total = sum(1 for _ in r.iter_rows())
        assert total == 200_000
        with FileReader(path, max_memory=100_000) as r:
            with pytest.raises(AllocError):
                list(r.iter_rows())

    def test_gzip_inflation_stops_at_advertised_size(self):
        """A gzip stream inflating far past the advertised size must raise
        without materializing the excess."""
        import zlib

        from parquet_tpu.core.compress import CompressionError, decompress_block
        from parquet_tpu.meta import CompressionCodec

        c = zlib.compressobj(wbits=31)
        bomb = c.compress(b"\x00" * (64 << 20)) + c.flush()  # 64MB of zeros
        with pytest.raises(CompressionError):
            decompress_block(bomb, CompressionCodec.GZIP, 100)

    def test_dictionary_gather_bomb_single_page(self, tmp_path):
        """One page, tiny on disk, whose dict gather would materialize
        hundreds of MB: the gather is charged BEFORE materialization."""
        from parquet_tpu.core.alloc import AllocError

        t = pa.table({"s": pa.array(["x" * 1000] * 200_000)})
        path = str(tmp_path / "gather.parquet")
        pq.write_table(
            t, path, use_dictionary=["s"], compression="snappy", data_page_size=1 << 30
        )
        assert Path(path).stat().st_size < 100_000
        with FileReader(path, max_memory=1 << 20) as r:
            with pytest.raises(AllocError):
                r.read_row_group(0)

    def test_gzip_truncated_trailer_rejected(self):
        """A gzip stream with its CRC trailer cut off must not decode
        silently even when the body yields exactly the advertised size."""
        import zlib

        from parquet_tpu.core.compress import CompressionError, decompress_block
        from parquet_tpu.meta import CompressionCodec

        c = zlib.compressobj(wbits=31)
        full = c.compress(b"hello world") + c.flush()
        with pytest.raises(CompressionError):
            decompress_block(full[:-8], CompressionCodec.GZIP, 11)

    def test_data_page_without_header_struct(self):
        """A page typed DATA_PAGE whose per-version header struct is absent
        must raise a clean PageError in the device pipeline's page splitter
        (found by mutation fuzzing; the host path already guarded it)."""
        from parquet_tpu.core.page import PageError
        from parquet_tpu.core.schema import Schema
        from parquet_tpu.kernels.pipeline import _split_page
        from parquet_tpu.meta.parquet_types import PageHeader, PageType, SchemaElement

        schema = Schema.from_thrift(
            [
                SchemaElement(name="root", num_children=1),
                SchemaElement(name="x", type=2, repetition_type=0),
            ]
        )
        column = schema.column(("x",))

        class _Raw:
            payload = b""
            offset = 0

        for pt in (int(PageType.DATA_PAGE), int(PageType.DATA_PAGE_V2)):
            header = PageHeader(
                type=pt, uncompressed_page_size=0, compressed_page_size=0
            )
            raw = _Raw()
            raw.header = header
            with pytest.raises(PageError):
                _split_page(raw, header, pt, 0, column)

    def test_page_header_region_flips_stay_clean(self, tmp_path):
        """Single-byte flips across the first page header must never escape
        as non-ValueError exceptions on the device pipeline."""
        import io

        t = pa.table({"i": pa.array(range(100), pa.int64())})
        buf = io.BytesIO()
        pq.write_table(t, buf, use_dictionary=False, compression="none")
        data = bytearray(buf.getvalue())
        with FileReader(io.BytesIO(bytes(data))) as r:
            off = r.row_group(0).columns[0].meta_data.data_page_offset
        seen_unclean = []
        for delta in range(40):
            mutated = bytearray(data)
            mutated[off + delta] ^= 0xFF
            try:
                with FileReader(io.BytesIO(bytes(mutated)), backend="tpu_roundtrip") as r:
                    for i in range(r.num_row_groups):
                        r.read_row_group(i)
            except (ValueError, IndexError, EOFError, OverflowError, MemoryError, KeyError):
                pass
            except Exception as e:  # pragma: no cover
                seen_unclean.append((delta, type(e).__name__))
        assert not seen_unclean, seen_unclean


class TestMutationSweepIndexed:
    """Mutation sweep over a file carrying the round-3 aux structures (page
    index, bloom filters, BYTE_STREAM_SPLIT, LZ4): every mutation must decode
    or fail cleanly, and a filtered read must never leak an internal error."""

    @pytest.fixture(scope="class")
    def indexed_file(self) -> bytes:
        from parquet_tpu.core.writer import FileWriter
        from parquet_tpu.schema.dsl import parse_schema

        schema = parse_schema(
            "message m { required int64 id; required double x; "
            "optional binary s (UTF8); }"
        )
        buf = io.BytesIO()
        ids = np.arange(800, dtype=np.int64)
        strs = [None if i % 9 == 0 else f"v{i % 37}" for i in range(800)]
        with FileWriter(
            buf, schema, codec="lz4_raw", write_page_index=True,
            bloom_filters=["id"], max_page_size=512,
            column_encodings={"x": "BYTE_STREAM_SPLIT"}, use_dictionary=False,
        ) as w:
            w.write_column("id", ids)
            w.write_column("x", ids.astype(np.float64))
            w.write_column(
                "s",
                [v for v in strs if v is not None],
                def_levels=[0 if v is None else 1 for v in strs],
            )
        return buf.getvalue()

    @staticmethod
    def _try_filtered(data: bytes) -> None:
        try:
            with FileReader(io.BytesIO(data)) as r:
                list(r.iter_rows(filters=[("id", ">=", 700)]))
                for i in range(r.num_row_groups):
                    r.read_page_index(i)
                    r.read_bloom_filter(i, "id")
        except CLEAN_ERRORS:
            pass

    def test_byte_flips_everywhere(self, indexed_file):
        rng = np.random.default_rng(4321)
        data = bytearray(indexed_file)
        for _ in range(400):
            pos = int(rng.integers(0, len(data)))
            old = data[pos]
            data[pos] ^= int(rng.integers(1, 256))
            blob = bytes(data)
            _try_read(blob)
            self._try_filtered(blob)
            data[pos] = old

    def test_tail_region_flips(self, indexed_file):
        # index + bloom + footer all live in the tail: hammer it specifically
        rng = np.random.default_rng(777)
        data = bytearray(indexed_file)
        start = max(len(data) - 2_000, 0)
        for _ in range(400):
            pos = int(rng.integers(start, len(data)))
            old = data[pos]
            data[pos] ^= int(rng.integers(1, 256))
            self._try_filtered(bytes(data))
            data[pos] = old

    def test_truncations(self, indexed_file):
        step = max(len(indexed_file) // 80, 1)
        for cut in range(1, len(indexed_file), step):
            blob = indexed_file[:cut]
            _try_read(blob)
            self._try_filtered(blob)


class TestToArrowMutationSweep:
    """The round-5 columnar lanes (to_arrow incl. nested assembly, logical
    retype, dictionary-preserving reads, filters) must fail CLEANLY on
    corrupt bytes — never leak numpy/pyarrow internals past the boundary."""

    @pytest.fixture(scope="class")
    def rich_file(self) -> bytes:
        import datetime as dt
        import decimal

        t = pa.table({
            "i": pa.array(range(400), pa.int64()),
            "cat": pa.array([f"c{i % 7}" for i in range(400)]),
            "ts": pa.array(
                [dt.datetime(2024, 1, 1) + dt.timedelta(hours=i) for i in range(400)],
                pa.timestamp("us"),
            ),
            "dec": pa.array(
                [decimal.Decimal(i) / 100 for i in range(400)], pa.decimal128(10, 2)
            ),
            "g": pa.array(
                [{"a": i, "b": [i, i + 1]} if i % 5 else None for i in range(400)],
                pa.struct([("a", pa.int64()), ("b", pa.list_(pa.int32()))]),
            ),
        })
        buf = io.BytesIO()
        pq.write_table(t, buf, compression="snappy", use_dictionary=["cat"])
        return buf.getvalue()

    def _try(self, data: bytes) -> None:
        try:
            with FileReader(io.BytesIO(data)) as r:
                r.to_arrow(read_dictionary=["cat"], filters=[("i", ">=", 100)])
        except CLEAN_ERRORS as e:
            # ArrowInvalid subclasses ValueError: without this check a raw
            # pyarrow internal would count as clean
            assert not isinstance(e, pa.lib.ArrowException), (
                f"pyarrow internal escaped to_arrow: {e!r}"
            )
        except (KeyError, TypeError) as e:
            raise AssertionError(f"unclean error escaped to_arrow: {e!r}") from e

    def test_single_byte_flips(self, rich_file):
        rng2 = np.random.default_rng(77)
        data = bytearray(rich_file)
        for _ in range(250):
            pos = int(rng2.integers(0, len(data)))
            old = data[pos]
            data[pos] ^= int(rng2.integers(1, 256))
            self._try(bytes(data))
            data[pos] = old

    def test_truncations(self, rich_file):
        for cut in range(1, len(rich_file), max(len(rich_file) // 60, 1)):
            self._try(rich_file[:cut])

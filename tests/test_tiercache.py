"""TieredCache tests: RAM->disk spill, budgets, crash-safe restart
readback, torn-segment discard, and concurrent-sharing integrity.

Acceptance pins from the issue: a disk-tier block survives a cache-object
restart (persistent cache_dir), and a torn spill segment is DISCARDED at
replay, never served."""

import os
import struct
import threading

import numpy as np
import pytest

from parquet_tpu.io import LocalFileSource, TieredCache, fetch_ranges
from parquet_tpu.io.tiercache import _HEADER, _MAGIC
from parquet_tpu.utils import metrics


def _blk(i: int, size: int = 1024) -> bytes:
    """Deterministic content per block id — integrity checks recompute it."""
    return bytes([i & 0xFF]) * size


class TestRamTier:
    def test_put_get_roundtrip(self):
        with TieredCache(ram_bytes=1 << 20, disk_bytes=1 << 20) as tc:
            tc.put("s", 0, 1024, _blk(1))
            assert tc.get("s", 0, 1024) == _blk(1)
            assert tc.get("s", 1024, 1024) is None
            assert tc.get("other", 0, 1024) is None

    def test_counters(self):
        with TieredCache(ram_bytes=1 << 20, disk_bytes=1 << 20) as tc:
            s0 = metrics.snapshot()
            tc.put("s", 0, 1024, _blk(1))
            tc.get("s", 0, 1024)
            tc.get("s", 9, 9)
            d = metrics.delta(s0)
            assert d.get('cache_tier_hits_total{tier="ram"}', 0) == 1
            assert d.get("cache_tier_misses_total", 0) == 1
            # the io_cache_* families keep counting (scan/tenant surfaces)
            assert d.get("io_cache_hits_total", 0) == 1
            assert d.get("io_cache_misses_total", 0) == 1

    def test_validation(self):
        with pytest.raises(ValueError):
            TieredCache(ram_bytes=0, disk_bytes=1)
        with pytest.raises(ValueError):
            TieredCache(ram_bytes=1, disk_bytes=0)
        with pytest.raises(ValueError):
            TieredCache(ram_bytes=1, disk_bytes=1, segment_bytes=0)


class TestSpill:
    def test_ram_eviction_spills_and_disk_hit_promotes(self):
        # RAM holds 8 x 1 KiB; 16 puts spill the oldest 8 to disk
        with TieredCache(ram_bytes=8 << 10, disk_bytes=1 << 20) as tc:
            s0 = metrics.snapshot()
            for i in range(16):
                tc.put("s", i * 1024, 1024, _blk(i))
            d = metrics.delta(s0)
            assert d.get("cache_tier_spills_total", 0) == 8
            assert d.get('cache_tier_evictions_total{tier="ram"}', 0) == 8
            st = tc.stats()
            assert st["ram"]["blocks"] == 8
            assert st["disk"]["blocks"] == 8
            # an evicted block comes back from disk, byte-identical...
            s1 = metrics.snapshot()
            assert tc.get("s", 0, 1024) == _blk(0)
            d1 = metrics.delta(s1)
            assert d1.get('cache_tier_hits_total{tier="disk"}', 0) == 1
            assert d1.get("cache_tier_promotions_total", 0) == 1
            # ...and the promotion makes the NEXT hit a RAM hit
            s2 = metrics.snapshot()
            assert tc.get("s", 0, 1024) == _blk(0)
            assert metrics.delta(s2).get(
                'cache_tier_hits_total{tier="ram"}', 0
            ) == 1

    def test_every_spilled_block_is_byte_identical(self):
        with TieredCache(
            ram_bytes=4 << 10, disk_bytes=1 << 20, segment_bytes=8 << 10
        ) as tc:
            for i in range(64):
                tc.put("s", i * 1000, 777, _blk(i, 777))
            for i in range(64):
                assert tc.get("s", i * 1000, 777) == _blk(i, 777), i

    def test_block_bigger_than_ram_goes_straight_to_disk(self):
        with TieredCache(ram_bytes=1 << 10, disk_bytes=1 << 20) as tc:
            big = _blk(7, 4096)
            tc.put("s", 0, 4096, big)
            assert tc.stats()["ram"]["blocks"] == 0
            assert tc.get("s", 0, 4096) == big  # served from disk

    def test_block_bigger_than_both_tiers_is_not_cached(self):
        with TieredCache(ram_bytes=1 << 10, disk_bytes=2 << 10) as tc:
            tc.put("s", 0, 8192, _blk(1, 8192))
            assert tc.get("s", 0, 8192) is None

    def test_disk_budget_drops_oldest_segment(self):
        # segments of ~4 KiB, disk budget ~12 KiB: old segments fall off
        with TieredCache(
            ram_bytes=1 << 10, disk_bytes=12 << 10, segment_bytes=4 << 10
        ) as tc:
            s0 = metrics.snapshot()
            for i in range(32):
                tc.put("s", i * 1024, 1024, _blk(i))
            d = metrics.delta(s0)
            assert d.get('cache_tier_evictions_total{tier="disk"}', 0) > 0
            st = tc.stats()
            assert st["disk"]["bytes"] <= 12 << 10
            # the NEWEST spilled blocks still serve; the oldest are gone
            assert tc.get("s", 0 * 1024, 1024) is None
            # find a key that survived (walk newest backwards)
            assert any(
                tc.get("s", i * 1024, 1024) == _blk(i)
                for i in range(31, 20, -1)
            )

    def test_invalidate_drops_both_tiers(self):
        with TieredCache(ram_bytes=2 << 10, disk_bytes=1 << 20) as tc:
            for i in range(8):  # spills the first ~6
                tc.put("a", i * 1024, 1024, _blk(i))
            tc.put("b", 0, 1024, _blk(99))
            tc.invalidate("a")
            for i in range(8):
                assert tc.get("a", i * 1024, 1024) is None, i
            assert tc.get("b", 0, 1024) == _blk(99)

    def test_clear(self):
        with TieredCache(ram_bytes=2 << 10, disk_bytes=1 << 20) as tc:
            for i in range(8):
                tc.put("s", i * 1024, 1024, _blk(i))
            tc.clear()
            st = tc.stats()
            assert st["ram"]["blocks"] == 0 and st["disk"]["blocks"] == 0
            assert all(
                tc.get("s", i * 1024, 1024) is None for i in range(8)
            )


class TestRestart:
    def _fill_and_spill(self, cache_dir, n=16):
        tc = TieredCache(
            ram_bytes=2 << 10, disk_bytes=1 << 20, cache_dir=cache_dir
        )
        for i in range(n):
            tc.put("s", i * 1024, 1024, _blk(i))
        spilled = [
            i for i in range(n) if (("s", i * 1024, 1024) in tc._disk)
        ]
        tc.close()
        return spilled

    def test_disk_readback_after_restart(self, tmp_path):
        """The issue's restart pin: a NEW cache object over the same
        cache_dir re-serves every intact spilled block from disk."""
        d = str(tmp_path / "cache")
        spilled = self._fill_and_spill(d)
        assert spilled, "expected RAM pressure to spill"
        s0 = metrics.snapshot()
        with TieredCache(
            ram_bytes=2 << 10, disk_bytes=1 << 20, cache_dir=d
        ) as tc2:
            delta = metrics.delta(s0)
            assert delta.get("cache_tier_restored_blocks_total", 0) == len(
                spilled
            )
            for i in spilled:
                assert tc2.get("s", i * 1024, 1024) == _blk(i), i

    def test_private_tempdir_is_removed_on_close(self):
        tc = TieredCache(ram_bytes=1 << 10, disk_bytes=1 << 20)
        d = tc.cache_dir
        for i in range(8):
            tc.put("s", i * 1024, 1024, _blk(i))
        assert os.path.isdir(d)
        tc.close()
        assert not os.path.exists(d)
        tc.close()  # idempotent

    def test_torn_tail_is_discarded_not_served(self, tmp_path):
        d = str(tmp_path / "cache")
        spilled = self._fill_and_spill(d)
        segs = sorted(p for p in os.listdir(d) if p.endswith(".dat"))
        assert segs
        # tear the LAST record: chop half of the newest segment's tail
        last = os.path.join(d, segs[-1])
        size = os.path.getsize(last)
        with open(last, "r+b") as f:
            f.truncate(size - 100)
        s0 = metrics.snapshot()
        with TieredCache(
            ram_bytes=2 << 10, disk_bytes=1 << 20, cache_dir=d
        ) as tc2:
            d1 = metrics.delta(s0)
            assert d1.get("cache_tier_torn_segments_total", 0) >= 1
            restored = d1.get("cache_tier_restored_blocks_total", 0)
            assert restored < len(spilled)  # the torn record is gone
            # every block it DOES serve is byte-identical
            served = 0
            for i in spilled:
                got = tc2.get("s", i * 1024, 1024)
                if got is not None:
                    assert got == _blk(i)
                    served += 1
            assert served == restored

    def test_corrupt_crc_abandons_rest_of_segment(self, tmp_path):
        d = str(tmp_path / "cache")
        self._fill_and_spill(d)
        seg = os.path.join(
            d, sorted(p for p in os.listdir(d) if p.endswith(".dat"))[0]
        )
        # flip one payload byte INSIDE the first record: its CRC fails,
        # and replay must stop serving that segment there
        with open(seg, "r+b") as f:
            hdr = f.read(_HEADER.size)
            magic, key_len, data_len, _crc = _HEADER.unpack(hdr)
            assert magic == _MAGIC
            f.seek(_HEADER.size + key_len + data_len // 2)
            b = f.read(1)
            f.seek(-1, os.SEEK_CUR)
            f.write(bytes([b[0] ^ 0xFF]))
        s0 = metrics.snapshot()
        with TieredCache(
            ram_bytes=2 << 10, disk_bytes=1 << 20, cache_dir=d
        ) as tc2:
            assert metrics.delta(s0).get(
                "cache_tier_torn_segments_total", 0
            ) >= 1
            # whatever survives is still byte-identical
            for key in list(tc2._disk):
                sid, off, ln = key
                assert tc2.get(sid, off, ln) == _blk(off // 1024, ln)


class TestSharing:
    def test_fetch_ranges_reads_through_tiered_cache(self, tmp_path):
        data = np.random.default_rng(5).integers(
            0, 256, 1 << 16
        ).astype(np.uint8).tobytes()
        p = tmp_path / "blob.bin"
        p.write_bytes(data)
        with TieredCache(ram_bytes=1 << 20, disk_bytes=1 << 20) as tc, \
                LocalFileSource(p) as src:
            ranges = [(0, 4096), (32768, 4096)]
            out = fetch_ranges(src, ranges, cache=tc, gap=0)
            assert bytes(out[(0, 4096)]) == data[:4096]
            s0 = metrics.snapshot()
            out2 = fetch_ranges(src, ranges, cache=tc, gap=0)
            d = metrics.delta(s0)
            assert d.get("io_bytes_read_total", 0) == 0  # warm: zero reads
            assert bytes(out2[(32768, 4096)]) == data[32768:36864]

    def test_concurrent_hammer_no_corruption(self):
        """8 threads × puts/gets over one small tiered cache: every get
        must return either None or EXACTLY the deterministic content of
        its key — spill/promote/evict races must never mix blocks."""
        errors = []
        with TieredCache(
            ram_bytes=8 << 10, disk_bytes=64 << 10, segment_bytes=16 << 10
        ) as tc:
            def worker(tid):
                rng = np.random.default_rng(tid)
                try:
                    for _ in range(300):
                        i = int(rng.integers(0, 64))
                        if rng.random() < 0.5:
                            tc.put("s", i * 1024, 512, _blk(i, 512))
                        else:
                            got = tc.get("s", i * 1024, 512)
                            if got is not None and got != _blk(i, 512):
                                errors.append((tid, i))
                except Exception as e:  # noqa: BLE001
                    errors.append((tid, repr(e)))

            threads = [
                threading.Thread(target=worker, args=(t,)) for t in range(8)
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=60)
        assert not errors, errors[:5]

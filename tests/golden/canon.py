"""Canonical JSON-safe encoding shared by the golden-corpus generator and
the tests that read the frozen fixtures.

Both sides (the external implementation's decoded rows at generation time,
our reader's decoded rows at test time) pass through canon() before
comparison, so representation differences that are NOT semantic — pyarrow
returns MAP columns as lists of (key, value) tuples where we return dicts,
float32 promotes to Python float, etc. — are normalized away while every
semantic bit (float bit patterns via hex, exact bytes via base64, timestamp
instants + zone-awareness) is preserved.
"""

from __future__ import annotations

import base64
import datetime as dt
import decimal
import json
import math


def _pair_key(pair):
    return json.dumps(pair, sort_keys=True, default=str)


def _trim_fraction(iso: str) -> str:
    """Normalize trailing zeros in fractional seconds: pandas trims to the
    shortest form while numpy datetime64 always prints nine digits."""
    if "." not in iso:
        return iso
    head, frac = iso.rsplit(".", 1)
    frac = frac.rstrip("0")
    return head if not frac else f"{head}.{frac}"


def canon(v):
    if v is None or isinstance(v, (bool, str)):
        return v
    if isinstance(v, int):
        return int(v)
    if isinstance(v, float):
        # exact bit pattern; NaN hex differs by payload, collapse to one tag
        return {"f": "nan"} if math.isnan(v) else {"f": v.hex()}
    if isinstance(v, (bytes, bytearray, memoryview)):
        return {"b64": base64.b64encode(bytes(v)).decode()}
    if isinstance(v, dt.datetime):
        # tz-aware and naive-UTC represent the same instant across readers
        if v.tzinfo is not None:
            v = v.astimezone(dt.timezone.utc).replace(tzinfo=None)
        return {"dt": _trim_fraction(v.isoformat())}
    if type(v).__name__ == "datetime64":  # numpy ns-precision timestamps
        return {"dt": _trim_fraction(str(v))}
    if isinstance(v, dt.date):
        return {"d": v.isoformat()}
    if isinstance(v, dt.time):
        return {"t": v.replace(tzinfo=None).isoformat()}
    # floor.Time (nanosecond TIME): compare at microsecond fidelity — the
    # most pyarrow's to_pylist retains (full-precision behavior is covered
    # by unit tests)
    to_time = getattr(v, "to_time", None)
    if to_time is not None and hasattr(v, "nanos"):
        return {"t": to_time().replace(tzinfo=None).isoformat()}
    if isinstance(v, decimal.Decimal):
        return {"dec": str(v)}
    if isinstance(v, dict):
        if not v:
            return []  # empty MAP: pyarrow renders [], we render {}
        return {
            "pairs": sorted(
                ([canon(k), canon(x)] for k, x in v.items()), key=_pair_key
            )
        }
    if isinstance(v, (list, tuple)):
        seq = list(v)
        if seq and all(isinstance(e, tuple) and len(e) == 2 for e in seq):
            # a MAP rendered as key/value tuples (pyarrow's to_pylist form)
            return {
                "pairs": sorted(
                    ([canon(k), canon(x)] for k, x in seq), key=_pair_key
                )
            }
        return [canon(e) for e in seq]
    # numpy scalars: defer to their Python equivalents
    item = getattr(v, "item", None)
    if item is not None:
        return canon(item())
    raise TypeError(f"canon: unsupported type {type(v)!r}")


def canon_rows(rows):
    return [
        {k: canon(v) for k, v in row.items()} for row in rows
    ]

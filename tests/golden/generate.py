"""Generate the frozen golden-corpus fixtures (run once, outputs committed).

The fixtures under data/ are written by an EXTERNAL implementation (pyarrow /
Arrow C++) in a separate generation step and committed as binary files, with
the externally-decoded rows frozen alongside as canon()-encoded JSON. Tests
then read the binaries with OUR reader and compare against the frozen
expectations — independent of any same-process pyarrow write at test time,
the analogue of the reference's apache/parquet-testing + Impala-file suites
(reference: parquet_test.go:11-38, parquet_compatibility_test.go:77).

Regenerate (only when adding fixtures — existing binaries must stay frozen):
    python tests/golden/generate.py
"""

from __future__ import annotations

import datetime as dt
import decimal
import json
from pathlib import Path

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq

from canon import canon_rows

HERE = Path(__file__).resolve().parent
DATA = HERE / "data"
EXPECTED = HERE / "expected"

N = 1500
rng = np.random.default_rng(20260730)


def _alltypes_table() -> pa.Table:
    return pa.table(
        {
            "i32": pa.array(rng.integers(-(2**31), 2**31, N).astype(np.int32)),
            "i64": pa.array(rng.integers(-(2**62), 2**62, N).astype(np.int64)),
            "f32": pa.array(rng.standard_normal(N).astype(np.float32)),
            "f64": pa.array(rng.standard_normal(N)),
            "flag": pa.array(rng.random(N) < 0.5),
            "name": pa.array([f"name_{i % 97}" for i in range(N)]),
            "blob": pa.array([rng.bytes(i % 23) for i in range(N)], pa.binary()),
        }
    )


def _fixtures():
    yield (
        "alltypes_plain_v1_none",
        _alltypes_table(),
        dict(compression="none", use_dictionary=False, data_page_version="1.0",
             column_encoding={c: "PLAIN" for c in
                              ("i32", "i64", "f32", "f64", "name", "blob")}),
    )
    yield (
        "alltypes_dict_snappy_v1",
        _alltypes_table(),
        dict(compression="snappy", use_dictionary=True, data_page_version="1.0"),
    )
    yield (
        "alltypes_v2_gzip",
        _alltypes_table(),
        dict(compression="gzip", use_dictionary=True, data_page_version="2.0"),
    )
    yield (
        "alltypes_zstd_v2_nodict",
        _alltypes_table(),
        dict(compression="zstd", use_dictionary=False, data_page_version="2.0",
             column_encoding={c: "PLAIN" for c in
                              ("i32", "i64", "f32", "f64", "name", "blob")}),
    )
    yield (
        "delta_binary_packed",
        pa.table(
            {
                "d32": pa.array(
                    np.cumsum(rng.integers(-50, 50, N)).astype(np.int32)
                ),
                "d64": pa.array(
                    (1_600_000_000_000_000 + np.cumsum(rng.integers(0, 1000, N))).astype(np.int64)
                ),
            }
        ),
        dict(compression="snappy", use_dictionary=False,
             column_encoding={"d32": "DELTA_BINARY_PACKED", "d64": "DELTA_BINARY_PACKED"}),
    )
    yield (
        "delta_byte_array",
        pa.table(
            {
                "sorted_keys": pa.array(sorted(f"key_{int(x):09d}" for x in rng.integers(0, 1 << 30, N))),
                "dlba": pa.array([f"value-{i}-{'x' * (i % 17)}" for i in range(N)]),
            }
        ),
        dict(compression="none", use_dictionary=False,
             column_encoding={"sorted_keys": "DELTA_BYTE_ARRAY", "dlba": "DELTA_LENGTH_BYTE_ARRAY"}),
    )
    yield (
        "int96_timestamps",
        pa.table(
            {
                "ts": pa.array(
                    [
                        dt.datetime(1999, 12, 31, 23, 59, 59, tzinfo=dt.timezone.utc),
                        None,
                        dt.datetime(2026, 7, 30, 12, 0, 0, 123456, tzinfo=dt.timezone.utc),
                        dt.datetime(1970, 1, 1, tzinfo=dt.timezone.utc),
                        dt.datetime(1883, 11, 18, 12, 4, 0, tzinfo=dt.timezone.utc),
                    ]
                    * 100,
                    pa.timestamp("ns", tz="UTC"),
                )
            }
        ),
        dict(use_deprecated_int96_timestamps=True, compression="snappy"),
    )
    lengths = rng.integers(0, 4, N)
    flat = rng.integers(-(2**30), 2**30, int(lengths.sum())).astype(np.int32)
    offsets = np.zeros(N + 1, dtype=np.int64)
    np.cumsum(lengths, out=offsets[1:])
    lists = pa.ListArray.from_arrays(pa.array(offsets, pa.int32()), pa.array(flat))
    yield (
        "nested_lists_maps",
        pa.table(
            {
                "ints": lists,
                "deep": pa.array(
                    [[[i, i + 1], []], None, [[i]]][i % 3] if i % 5 else None
                    for i in range(N)
                ),
                "m": pa.array(
                    [
                        [(f"k{j}", i + j) for j in range(i % 4)] if i % 7 else None
                        for i in range(N)
                    ],
                    pa.map_(pa.string(), pa.int64()),
                ),
                "rec": pa.array(
                    [{"a": i, "b": f"s{i % 11}"} if i % 3 else None for i in range(N)],
                    pa.struct([("a", pa.int64()), ("b", pa.string())]),
                ),
            }
        ),
        dict(compression="snappy"),
    )
    yield (
        "nulls_heavy",
        pa.table(
            {
                "mostly_null": pa.array(
                    [None if i % 10 else i for i in range(N)], pa.int64()
                ),
                "all_null": pa.array([None] * N, pa.float64()),
                "opt_str": pa.array([None if i % 3 == 0 else f"s{i}" for i in range(N)]),
            }
        ),
        dict(compression="gzip", data_page_version="2.0"),
    )
    yield (
        "decimal_flba_date_time",
        pa.table(
            {
                "dec": pa.array(
                    [decimal.Decimal(int(x)) / 100 for x in rng.integers(-(10**10), 10**10, N)],
                    pa.decimal128(18, 2),
                ),
                "fsb": pa.array([rng.bytes(8) for _ in range(N)], pa.binary(8)),
                "day": pa.array(
                    [dt.date(2020, 1, 1) + dt.timedelta(days=int(i)) for i in range(N)]
                ),
                "tod": pa.array(
                    [dt.time(i % 24, (i * 7) % 60, (i * 13) % 60, (i * 1001) % 1000000) for i in range(N)],
                    pa.time64("us"),
                ),
            }
        ),
        dict(compression="snappy"),
    )
    yield (
        "dict_overflow_mixed_pages",
        pa.table(
            {"s": pa.array([f"v{int(x):09d}" for x in rng.integers(0, 1 << 30, 8000)])}
        ),
        dict(use_dictionary=["s"], dictionary_pagesize_limit=4096, compression="snappy"),
    )
    yield (
        "multi_rowgroup_small_pages",
        _alltypes_table(),
        dict(compression="snappy", row_group_size=256, data_page_size=512),
    )
    yield (
        "unsigned_ints",
        pa.table(
            {
                "u8": pa.array(rng.integers(0, 1 << 8, N), pa.uint8()),
                "u16": pa.array(rng.integers(0, 1 << 16, N), pa.uint16()),
                "u32": pa.array(rng.integers(0, 1 << 32, N), pa.uint32()),
                "u64": pa.array(
                    rng.integers(0, 1 << 62, N).astype(np.uint64) + (1 << 63),
                    pa.uint64(),
                ),
                "i8": pa.array(rng.integers(-128, 128, N), pa.int8()),
                "i16": pa.array(rng.integers(-(1 << 15), 1 << 15, N), pa.int16()),
            }
        ),
        dict(compression="snappy"),
    )
    yield (
        "time_units",
        pa.table(
            {
                "t_ms": pa.array(
                    rng.integers(0, 86_400_000, N).astype(np.int32), pa.time32("ms")
                ),
                "t_us": pa.array(
                    rng.integers(0, 86_400_000_000, N), pa.time64("us")
                ),
                "t_ns": pa.array(
                    # odd nanos: sub-microsecond precision that datetime.time
                    # cannot carry (floor.Time path)
                    rng.integers(0, 86_400 * 10**9 // 2, N) * 2 + 1,
                    pa.time64("ns"),
                ),
                "ts_ms": pa.array(
                    rng.integers(0, 1 << 40, N), pa.timestamp("ms", tz="UTC")
                ),
                "ts_ns": pa.array(
                    rng.integers(0, 1 << 60, N), pa.timestamp("ns")
                ),
            }
        ),
        dict(compression="snappy"),
    )
    yield (
        "bool_heavy_v2",
        pa.table(
            {
                "runs": pa.array([bool((i // 97) % 2) for i in range(N)]),
                "noise": pa.array((rng.random(N) < 0.5).tolist()),
                "opt": pa.array([None if i % 5 == 0 else bool(i % 2) for i in range(N)]),
            }
        ),
        dict(compression="snappy", data_page_version="2.0", use_dictionary=False),
    )
    yield (
        "kv_metadata_and_empty_tail",
        pa.Table.from_arrays(
            [pa.array(list(range(N)), pa.int64())], names=["x"]
        ).replace_schema_metadata({"origin": "golden-corpus", "answer": "42"}),
        dict(compression="none"),
    )


def main() -> None:
    DATA.mkdir(exist_ok=True)
    EXPECTED.mkdir(exist_ok=True)
    manifest = {}
    for name, table, opts in _fixtures():
        path = DATA / f"{name}.parquet"
        expected = EXPECTED / f"{name}.json"
        if path.exists():
            if not expected.exists():
                # canon encoding evolved: re-derive expectations from the
                # FROZEN binary (the fixture bytes never change)
                rows = pq.read_table(path).to_pylist()
                expected.write_text(json.dumps(canon_rows(rows), separators=(",", ":")))
                print(f"re-derived expectations: {name}")
            else:
                print(f"frozen, skipping: {name}")
            continue
        pq.write_table(table, path, **opts)
        rows = pq.read_table(path).to_pylist()
        expected.write_text(
            json.dumps(canon_rows(rows), separators=(",", ":"))
        )
        manifest[name] = {"rows": len(rows), "bytes": path.stat().st_size}
        print(f"wrote {name}: {len(rows)} rows, {path.stat().st_size} bytes")
    if manifest:
        print(json.dumps(manifest, indent=1))


if __name__ == "__main__":
    main()

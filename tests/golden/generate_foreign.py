"""Generate frozen FOREIGN-WRITER-FORM fixtures (run once, outputs committed).

The main golden corpus is pyarrow-written, so on-disk forms that pyarrow
never produces — the quirks of OTHER writers the reference validates against
(apache/parquet-testing + Impala files, reference: parquet_test.go:11-38,
parquet_compatibility_test.go:77) — were uncovered. This generator builds
those byte-level forms with our own encoder primitives:

  foreign_legacy_2level_list  legacy parquet-mr 2-level LIST (repeated leaf
                              directly under the LIST group, no middle group)
  foreign_athena_bag          Athena/Hive form: repeated group named `bag`
                              with an optional `array_element` leaf
  foreign_bool_rle_v2         boolean column RLE-encoded in DataPage V2
                              (modern parquet-mr writes booleans this way)
  foreign_int96_impala        INT96 julian-day timestamps (Impala convention)

Each file is then decoded by PYARROW — the independent implementation — and
its rows frozen as the expectation, so the oracle never saw our reader.
The binaries must stay frozen once committed:
    python tests/golden/generate_foreign.py
"""

from __future__ import annotations

import datetime as dt
import json
import sys
from pathlib import Path

import numpy as np
import pyarrow.parquet as pq

HERE = Path(__file__).resolve().parent
sys.path.insert(0, str(HERE))
sys.path.insert(0, str(HERE.parent.parent))

from canon import canon_rows  # noqa: E402

from parquet_tpu.core.writer import FileWriter  # noqa: E402
from parquet_tpu.schema.dsl import parse_schema  # noqa: E402

DATA = HERE / "data"
EXPECTED = HERE / "expected"

N = 1200
rng = np.random.default_rng(20260730)


def _legacy_2level_list(path: Path) -> None:
    schema = parse_schema(
        "message m { optional group xs (LIST) { repeated int32 array; } }"
    )
    rows = []
    for i in range(N):
        if i % 9 == 0:
            rows.append({"xs": None})
        elif i % 5 == 0:
            rows.append({"xs": []})
        else:
            rows.append({"xs": [int(v) for v in rng.integers(-999, 999, i % 6)]})
    with FileWriter(path, schema, codec="snappy") as w:
        w.write_rows(rows)


def _athena_bag(path: Path) -> None:
    schema = parse_schema(
        "message m { optional group xs (LIST) { repeated group bag "
        "{ optional int32 array_element; } } }"
    )
    rows = []
    for i in range(N):
        if i % 9 == 0:
            rows.append({"xs": None})
        elif i % 5 == 0:
            rows.append({"xs": []})
        else:
            rows.append(
                {
                    "xs": [
                        None if (i + j) % 7 == 0 else int(j * i % 1000)
                        for j in range(i % 5)
                    ]
                }
            )
    with FileWriter(path, schema, codec="snappy") as w:
        w.write_rows(rows)


def _bool_rle_v2(path: Path) -> None:
    schema = parse_schema("message m { required boolean b; optional boolean ob; }")
    rows = [
        {
            "b": bool(i % 11 < 7),
            "ob": None if i % 6 == 0 else bool(i % 3 == 0),
        }
        for i in range(N)
    ]
    with FileWriter(
        path,
        schema,
        codec="snappy",
        data_page_version=2,
        column_encodings={"b": "RLE", "ob": "RLE"},
        enable_dictionary=False,
    ) as w:
        w.write_rows(rows)


def _int96_impala(path: Path) -> None:
    schema = parse_schema("message m { required int96 ts; }")
    base = dt.datetime(1999, 12, 31, 23, 59, 58, 500_000, tzinfo=dt.timezone.utc)
    rows = [
        {"ts": base + dt.timedelta(seconds=int(s), microseconds=int(u))}
        for s, u in zip(
            rng.integers(0, 10**7, N), rng.integers(0, 1_000_000, N)
        )
    ]
    with FileWriter(path, schema, codec="snappy", enable_dictionary=False) as w:
        w.write_rows(rows)


FOREIGN = {
    "foreign_legacy_2level_list": _legacy_2level_list,
    "foreign_athena_bag": _athena_bag,
    "foreign_bool_rle_v2": _bool_rle_v2,
    "foreign_int96_impala": _int96_impala,
}


def main() -> None:
    for name, build in FOREIGN.items():
        path = DATA / f"{name}.parquet"
        if path.exists():
            print(f"{name}: frozen, skipping")
            continue
        build(path)
        # the INDEPENDENT oracle decodes the bytes and freezes the answer
        rows = pq.read_table(path).to_pylist()
        (EXPECTED / f"{name}.json").write_text(
            json.dumps(canon_rows(rows), separators=(",", ":"))
        )
        print(f"{name}: {path.stat().st_size} bytes, {len(rows)} rows frozen")


if __name__ == "__main__":
    main()

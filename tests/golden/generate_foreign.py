"""Generate frozen FOREIGN-WRITER-FORM fixtures (run once, outputs committed).

The main golden corpus is pyarrow-written, so on-disk forms that pyarrow
never produces — the quirks of OTHER writers the reference validates against
(apache/parquet-testing + Impala files, reference: parquet_test.go:11-38,
parquet_compatibility_test.go:77) — were uncovered. This generator builds
those byte-level forms with our own encoder primitives:

  foreign_legacy_2level_list  legacy parquet-mr 2-level LIST (repeated leaf
                              directly under the LIST group, no middle group)
  foreign_athena_bag          Athena/Hive form: repeated group named `bag`
                              with an optional `array_element` leaf
  foreign_bool_rle_v2         boolean column RLE-encoded in DataPage V2
                              (modern parquet-mr writes booleans this way)
  foreign_int96_impala        INT96 julian-day timestamps (Impala convention)
  foreign_mixed_page_versions one chunk holding a V1 page then a V2 page
                              (writers migrating page versions mid-file)
  foreign_v2_uncompressed_pages SNAPPY chunk, V2 pages is_compressed=false
                              (parquet-cpp ships incompressible pages raw)
  foreign_empty_pages         zero-value data page mid-chunk
  foreign_zero_row            footer-only file, zero rows, nested schema
  foreign_big_dict_page       ~3.5MB dictionary page + RLE_DICTIONARY pages
  foreign_int96_dict          INT96 through a dictionary (Impala layout)
  foreign_bool_rle_shapes     non-canonical hybrid runs: single-value and
                              adjacent same-value RLE runs, 1-group
                              bit-packed runs, padded final group
  foreign_nonnullable_impala  REQUIRED-everywhere struct+list+map nesting
                              (nonnullable.impala.parquet's shape)
  foreign_plain_dictionary_legacy  old parquet-mr 1.x PLAIN_DICTIONARY
                              encoding ids on dict + data pages

Each file is then decoded by PYARROW — the independent implementation — and
its rows frozen as the expectation, so the oracle never saw our reader.
The binaries must stay frozen once committed:
    python tests/golden/generate_foreign.py
"""

from __future__ import annotations

import datetime as dt
import json
import sys
from pathlib import Path

import numpy as np
import pyarrow.parquet as pq

HERE = Path(__file__).resolve().parent
sys.path.insert(0, str(HERE))
sys.path.insert(0, str(HERE.parent.parent))

from canon import canon_rows  # noqa: E402

from parquet_tpu.core.writer import FileWriter  # noqa: E402
from parquet_tpu.schema.dsl import parse_schema  # noqa: E402

DATA = HERE / "data"
EXPECTED = HERE / "expected"

N = 1200
rng = np.random.default_rng(20260730)


def _legacy_2level_list(path: Path) -> None:
    schema = parse_schema(
        "message m { optional group xs (LIST) { repeated int32 array; } }"
    )
    rows = []
    for i in range(N):
        if i % 9 == 0:
            rows.append({"xs": None})
        elif i % 5 == 0:
            rows.append({"xs": []})
        else:
            rows.append({"xs": [int(v) for v in rng.integers(-999, 999, i % 6)]})
    with FileWriter(path, schema, codec="snappy") as w:
        w.write_rows(rows)


def _athena_bag(path: Path) -> None:
    schema = parse_schema(
        "message m { optional group xs (LIST) { repeated group bag "
        "{ optional int32 array_element; } } }"
    )
    rows = []
    for i in range(N):
        if i % 9 == 0:
            rows.append({"xs": None})
        elif i % 5 == 0:
            rows.append({"xs": []})
        else:
            rows.append(
                {
                    "xs": [
                        None if (i + j) % 7 == 0 else int(j * i % 1000)
                        for j in range(i % 5)
                    ]
                }
            )
    with FileWriter(path, schema, codec="snappy") as w:
        w.write_rows(rows)


def _bool_rle_v2(path: Path) -> None:
    schema = parse_schema("message m { required boolean b; optional boolean ob; }")
    rows = [
        {
            "b": bool(i % 11 < 7),
            "ob": None if i % 6 == 0 else bool(i % 3 == 0),
        }
        for i in range(N)
    ]
    with FileWriter(
        path,
        schema,
        codec="snappy",
        data_page_version=2,
        column_encodings={"b": "RLE", "ob": "RLE"},
        enable_dictionary=False,
    ) as w:
        w.write_rows(rows)


def _int96_impala(path: Path) -> None:
    schema = parse_schema("message m { required int96 ts; }")
    base = dt.datetime(1999, 12, 31, 23, 59, 58, 500_000, tzinfo=dt.timezone.utc)
    rows = [
        {"ts": base + dt.timedelta(seconds=int(s), microseconds=int(u))}
        for s, u in zip(
            rng.integers(0, 10**7, N), rng.integers(0, 1_000_000, N)
        )
    ]
    with FileWriter(path, schema, codec="snappy", enable_dictionary=False) as w:
        w.write_rows(rows)


# -- handcrafted byte-level forms ---------------------------------------------
#
# These build files page-by-page (headers, blocks, footer) to freeze on-disk
# shapes our FileWriter never produces but other writers do — the layouts the
# reference proves itself against via apache/parquet-testing and Impala files
# (reference: parquet_test.go:11-38). pyarrow remains the oracle.


def _handcraft(path: Path, schema, columns_pages, num_rows: int, codec: int):
    """Write a single-row-group file from per-column page lists.

    columns_pages: [(leaf Column, [(PageHeader, block_bytes), ...],
                     num_level_entries, encoding ints)]"""
    from parquet_tpu.meta.file_meta import MAGIC, serialize_footer
    from parquet_tpu.meta.parquet_types import (
        ColumnChunk,
        ColumnMetaData,
        ColumnOrder,
        FileMetaData,
        RowGroup,
        TypeDefinedOrder,
    )

    with open(path, "wb") as f:
        f.write(MAGIC)
        pos = len(MAGIC)
        chunks = []
        total_bytes = 0
        for col, pages, n_entries, encodings in columns_pages:
            first = pos
            dict_off = None
            data_off = None
            uncompressed = 0
            for header, block in pages:
                if header.type == 2 and dict_off is None:
                    dict_off = pos
                elif header.type in (0, 3) and data_off is None:
                    data_off = pos
                hb = header.dumps()
                f.write(hb)
                f.write(block)
                pos += len(hb) + len(block)
                uncompressed += len(hb) + (header.uncompressed_page_size or 0)
            md = ColumnMetaData(
                type=int(col.type),
                encodings=sorted(set(encodings)),
                path_in_schema=list(col.path),
                codec=codec,
                num_values=n_entries,
                total_uncompressed_size=uncompressed,
                total_compressed_size=pos - first,
                data_page_offset=data_off,
                dictionary_page_offset=dict_off,
            )
            chunks.append(ColumnChunk(file_offset=first, meta_data=md))
            total_bytes += pos - first
        rg = RowGroup(
            columns=chunks, total_byte_size=total_bytes, num_rows=num_rows
        )
        meta = FileMetaData(
            version=2,
            schema=schema.to_thrift(),
            num_rows=num_rows,
            row_groups=[rg],
            created_by="foreign-writer-form 1.0",
            column_orders=[
                ColumnOrder(TYPE_ORDER=TypeDefinedOrder())
                for _ in schema.leaves
            ],
        )
        f.write(serialize_footer(meta))


def _mixed_page_versions(path: Path) -> None:
    """One chunk holding a DataPage V1 FOLLOWED BY a DataPage V2 (writers
    migrating versions mid-file produced these; the two page forms frame
    their levels differently: V1 length-prefixed inside the compressed
    block, V2 raw RLE outside it)."""
    from parquet_tpu.core.page import encode_data_page_v1, encode_data_page_v2
    from parquet_tpu.meta.parquet_types import Encoding

    schema = parse_schema("message m { optional int64 v; }")
    col = schema.leaves[0]
    codec = 1  # snappy
    rows_a = [None if i % 7 == 0 else i * 3 for i in range(400)]
    rows_b = [None if i % 5 == 0 else -i for i in range(300)]

    def parts(rows):
        dense = np.array([r for r in rows if r is not None], dtype=np.int64)
        dl = np.array([0 if r is None else 1 for r in rows], dtype=np.uint16)
        return dense, dl

    da, la = parts(rows_a)
    db, lb = parts(rows_b)
    p1 = encode_data_page_v1(col, da, la, None, Encoding.PLAIN, codec)
    p2 = encode_data_page_v2(col, db, lb, None, Encoding.PLAIN, codec)
    _handcraft(
        path, schema,
        [(col, [p1, p2], len(rows_a) + len(rows_b),
          [int(Encoding.RLE), int(Encoding.PLAIN)])],
        len(rows_a) + len(rows_b), codec,
    )


def _v2_uncompressed_pages(path: Path) -> None:
    """SNAPPY chunk whose V2 pages set is_compressed=false (parquet-cpp
    ships incompressible pages raw while the chunk codec stays set)."""
    from parquet_tpu.core.page import encode_data_page_v2
    from parquet_tpu.meta.parquet_types import Encoding

    schema = parse_schema("message m { required double x; }")
    col = schema.leaves[0]
    vals_a = rng.standard_normal(500)  # incompressible: shipped raw
    vals_b = np.zeros(300)  # compressible: shipped compressed
    pa_hdr, pa_blk = encode_data_page_v2(col, vals_a, None, None, Encoding.PLAIN, 0)
    pa_hdr.data_page_header_v2.is_compressed = False
    pb = encode_data_page_v2(col, vals_b, None, None, Encoding.PLAIN, 1)
    _handcraft(
        path, schema,
        [(col, [(pa_hdr, pa_blk), pb], 800, [int(Encoding.PLAIN)])],
        800, 1,
    )


def _empty_pages(path: Path) -> None:
    """A zero-value data page sandwiched between real pages (flush-happy
    foreign writers emit these): the reader must step over the empty page
    without desyncing the chunk walk or the value/level alignment."""
    from parquet_tpu.core.page import encode_data_page_v1
    from parquet_tpu.meta.parquet_types import Encoding

    schema = parse_schema("message m { optional int32 v; }")
    col = schema.leaves[0]
    codec = 1

    def page(rows):
        dense = np.array([r for r in rows if r is not None], dtype=np.int32)
        dl = np.array([0 if r is None else 1 for r in rows], dtype=np.uint16)
        return encode_data_page_v1(col, dense, dl, None, Encoding.PLAIN, codec)

    p1 = page([1, None, 3, 4])
    p_empty = page([])
    p2 = page([None, 6])
    _handcraft(
        path, schema,
        [(col, [p1, p_empty, p2], 6, [int(Encoding.RLE), int(Encoding.PLAIN)])],
        6, codec,
    )


def _zero_row(path: Path) -> None:
    """Zero rows, nested schema: footer-only file with an empty row-group
    list (foreign producers write these for empty partitions)."""
    schema = parse_schema(
        "message m { optional int64 id; optional group xs (LIST) "
        "{ repeated group list { optional binary element (UTF8); } } }"
    )
    with FileWriter(path, schema, codec="snappy") as w:
        w.write_rows([])


def _big_dict_page(path: Path) -> None:
    """A ~3.5MB dictionary page (larger than any single decompress window /
    scratch sizing heuristic) feeding RLE_DICTIONARY data pages."""
    from parquet_tpu.core.page import encode_data_page_v1, encode_dict_page
    from parquet_tpu.meta.parquet_types import Encoding

    schema = parse_schema("message m { required binary s (UTF8); }")
    col = schema.leaves[0]
    codec = 1
    n_dict = 30_000
    uniques = [(f"value_{i:06d}_" + "x" * (80 + i % 40)).encode() for i in range(n_dict)]
    dict_page = encode_dict_page(col, uniques, codec)
    n = 50_000
    indices = rng.integers(0, n_dict, n).astype(np.int64)
    pages = [dict_page]
    for lo in range(0, n, 20_000):
        idx = indices[lo : lo + 20_000]
        pages.append(
            encode_data_page_v1(
                col, idx, None, None, Encoding.RLE_DICTIONARY, codec, n_dict
            )
        )
    _handcraft(
        path, schema,
        [(col, pages, n,
          [int(Encoding.RLE), int(Encoding.PLAIN), int(Encoding.RLE_DICTIONARY)])],
        n, codec,
    )


def _int96_dict(path: Path) -> None:
    """INT96 timestamps THROUGH A DICTIONARY (Impala's layout for repeated
    timestamps: dict page of 12-byte values + RLE_DICTIONARY indices)."""
    from parquet_tpu.core.page import encode_data_page_v1, encode_dict_page
    from parquet_tpu.meta.parquet_types import Encoding
    from parquet_tpu.utils.int96 import datetime_to_int96

    schema = parse_schema("message m { required int96 ts; }")
    col = schema.leaves[0]
    codec = 1
    base = dt.datetime(2001, 2, 3, 4, 5, 6, tzinfo=dt.timezone.utc)
    uniq = np.stack([
        np.frombuffer(
            datetime_to_int96(base + dt.timedelta(hours=int(h))), dtype=np.uint8
        )
        for h in range(300)
    ])
    dict_page = encode_dict_page(col, uniq, codec)
    n = 4_000
    indices = rng.integers(0, len(uniq), n).astype(np.int64)
    data_page = encode_data_page_v1(
        col, indices, None, None, Encoding.RLE_DICTIONARY, codec, len(uniq)
    )
    _handcraft(
        path, schema,
        [(col, [dict_page, data_page], n,
          [int(Encoding.RLE), int(Encoding.PLAIN), int(Encoding.RLE_DICTIONARY)])],
        n, codec,
    )


def _bool_rle_shapes(path: Path) -> None:
    """BOOLEAN column whose RLE hybrid stream uses NON-CANONICAL run shapes:
    single-value RLE runs, adjacent same-value runs, one-group bit-packed
    runs, and a final bit-packed group padded past num_values — all legal,
    none produced by tidy encoders."""
    import struct as st

    from parquet_tpu.core.page import PageHeader
    from parquet_tpu.meta.parquet_types import (
        DataPageHeader,
        Encoding,
    )
    from parquet_tpu.ops.varint import emit_uvarint

    schema = parse_schema("message m { required boolean b; }")
    col = schema.leaves[0]

    stream = bytearray()
    expect = []

    def rle(count, value):
        emit_uvarint(stream, count << 1)
        stream.append(1 if value else 0)
        expect.extend([bool(value)] * count)

    def bitpacked(bits):  # len(bits) multiple of 8
        groups = len(bits) // 8
        emit_uvarint(stream, (groups << 1) | 1)
        stream.extend(np.packbits(np.array(bits, np.uint8), bitorder="little").tobytes())
        expect.extend(bool(b) for b in bits)

    rle(1, True)            # single-value run
    rle(1, True)            # adjacent run, same value (un-merged)
    bitpacked([1, 0, 1, 0, 1, 0, 1, 0])
    rle(3, False)
    rle(2, False)           # adjacent same-value again
    bitpacked([0, 0, 1, 1, 0, 0, 1, 1, 1, 1, 1, 1, 0, 0, 0, 0])
    rle(7, True)
    # final group padded: only 4 of its 8 values are real
    pad = [1, 0, 0, 1, 0, 0, 0, 0]
    groups = 1
    emit_uvarint(stream, (groups << 1) | 1)
    stream.extend(np.packbits(np.array(pad, np.uint8), bitorder="little").tobytes())
    expect.extend([True, False, False, True])
    n = len(expect)

    raw = st.pack("<I", len(stream)) + bytes(stream)  # V1 length-prefixed RLE
    header = PageHeader(
        type=0,
        uncompressed_page_size=len(raw),
        compressed_page_size=len(raw),
        data_page_header=DataPageHeader(
            num_values=n,
            encoding=int(Encoding.RLE),
            definition_level_encoding=int(Encoding.RLE),
            repetition_level_encoding=int(Encoding.RLE),
        ),
    )
    _handcraft(
        path, schema,
        [(col, [(header, raw)], n, [int(Encoding.RLE)])],
        n, 0,
    )


def _nonnullable_impala(path: Path) -> None:
    """REQUIRED-everywhere nesting (struct + list + map), the shape of
    Impala's notorious nonnullable.impala.parquet: zero definition-level
    freedom anywhere except inside the repeated groups."""
    schema = parse_schema("""
    message m {
      required group s {
        required int64 id;
        required group tags (LIST) {
          repeated group list { required binary element (UTF8); }
        }
        required group attrs (MAP) {
          repeated group key_value {
            required binary key (UTF8);
            required int32 value;
          }
        }
      }
    }""")
    rows = []
    for i in range(600):
        rows.append({
            "s": {
                "id": i,
                "tags": [f"t{j}" for j in range(i % 4)],
                "attrs": {f"k{j}": i * j for j in range(i % 3)},
            }
        })
    with FileWriter(path, schema, codec="snappy") as w:
        w.write_rows(rows)



def _plain_dictionary_legacy(path: Path) -> None:
    """Old parquet-mr 1.x form: data pages tagged PLAIN_DICTIONARY (id 2)
    instead of the modern RLE_DICTIONARY (id 8), dict page also tagged
    PLAIN_DICTIONARY — both mean the same bytes on the wire."""
    from parquet_tpu.core.page import encode_data_page_v1, encode_dict_page
    from parquet_tpu.meta.parquet_types import Encoding

    schema = parse_schema("message m { required binary s (UTF8); required int64 v; }")
    col_s, col_v = schema.leaves
    codec = 1
    uniques = [f"word_{i:03d}".encode() for i in range(120)]
    n = 3_000
    idx = rng.integers(0, len(uniques), n).astype(np.int64)
    dict_page = encode_dict_page(col_s, uniques, codec)
    dict_page[0].dictionary_page_header.encoding = int(Encoding.PLAIN_DICTIONARY)
    data_page = encode_data_page_v1(
        col_s, idx, None, None, Encoding.RLE_DICTIONARY, codec, len(uniques)
    )
    data_page[0].data_page_header.encoding = int(Encoding.PLAIN_DICTIONARY)
    vals = np.cumsum(rng.integers(0, 9, n)).astype(np.int64)
    v_page = encode_data_page_v1(col_v, vals, None, None, Encoding.PLAIN, codec)
    _handcraft(
        path, schema,
        [
            (col_s, [dict_page, data_page], n,
             [int(Encoding.RLE), int(Encoding.PLAIN_DICTIONARY)]),
            (col_v, [v_page], n, [int(Encoding.RLE), int(Encoding.PLAIN)]),
        ],
        n, codec,
    )


FOREIGN = {
    "foreign_legacy_2level_list": _legacy_2level_list,
    "foreign_athena_bag": _athena_bag,
    "foreign_bool_rle_v2": _bool_rle_v2,
    "foreign_int96_impala": _int96_impala,
    "foreign_mixed_page_versions": _mixed_page_versions,
    "foreign_v2_uncompressed_pages": _v2_uncompressed_pages,
    "foreign_empty_pages": _empty_pages,
    "foreign_zero_row": _zero_row,
    "foreign_big_dict_page": _big_dict_page,
    "foreign_int96_dict": _int96_dict,
    "foreign_bool_rle_shapes": _bool_rle_shapes,
    "foreign_nonnullable_impala": _nonnullable_impala,
    "foreign_plain_dictionary_legacy": _plain_dictionary_legacy,
}


def main() -> None:
    for name, build in FOREIGN.items():
        path = DATA / f"{name}.parquet"
        if path.exists():
            print(f"{name}: frozen, skipping")
            continue
        build(path)
        # the INDEPENDENT oracle decodes the bytes and freezes the answer
        rows = pq.read_table(path).to_pylist()
        (EXPECTED / f"{name}.json").write_text(
            json.dumps(canon_rows(rows), separators=(",", ":"))
        )
        print(f"{name}: {path.stat().st_size} bytes, {len(rows)} rows frozen")


if __name__ == "__main__":
    main()

"""The continuous sampling profiler's contracts (obs/prof.py).

Pinned here:
  * lane attribution: thread names map onto the bounded pqt-* lane
    vocabulary (the accept loop does NOT pollute the worker lane);
  * determinism: sample_once() with injected frame/thread sources is a
    pure fold — N identical samples produce exactly-N counts, no clock
    and no thread involved;
  * bounds: distinct stacks cap at max_stacks (overflow folds into the
    per-lane ~overflow~ bucket, totals exact), depth caps at max_depth;
  * the live thread actually samples busy pqt-* workers and renders
    non-empty collapsed/top output;
  * one capture window per process (ProfilerBusy), and
  * the OVERHEAD PIN: sampling at the default 10 ms interval costs <5%
    on a scan-shaped decode loop (the bench.py headline's shape at smoke
    scale) — `make obs-smoke` runs this pin standalone.
"""

from __future__ import annotations

import sys
import threading
import time

import numpy as np
import pytest

from parquet_tpu.obs.prof import (
    POOL_LANES,
    ProfilerBusy,
    SamplingProfiler,
    capture,
    lane_of,
)

WATCHDOG_S = 30.0


# -- lane attribution ----------------------------------------------------------


class TestLanes:
    @pytest.mark.parametrize(
        "name,lane",
        [
            ("pqt-io_0", "pqt-io"),
            ("pqt-data_3", "pqt-data"),
            ("pqt-serve_1", "pqt-serve"),
            ("pqt-encode_0", "pqt-encode"),
            ("pqt-hedge_2", "pqt-hedge"),
            ("pqt-dispatch_0", "pqt-dispatch"),
            ("MainThread", "main"),
            ("Thread-12", "other"),
            ("", "other"),
        ],
    )
    def test_lane_of(self, name, lane):
        assert lane_of(name) == lane

    def test_accept_loop_does_not_pollute_worker_lane(self):
        # the daemon's HTTP accept loop idles in select(); on the
        # pqt-serve WORKER lane that would read as serve CPU
        assert lane_of("pqt-serve-http") == "pqt-serve-http"
        assert lane_of("pqt-serve-drain") == "pqt-serve-drain"
        assert lane_of("pqt-serve_0") == "pqt-serve"

    def test_lane_vocabulary_is_bounded(self):
        # the metrics label set is code-controlled: every possible output
        # is a POOL_LANES member, "main" or "other"
        outputs = {lane_of(n) for n in (
            "pqt-io_9", "pqt-serve-http", "x", "MainThread", "pqt-bogus"
        )}
        assert outputs <= set(POOL_LANES) | {"main", "other"}


# -- deterministic synchronous sampling ----------------------------------------


def _leaf_frame():
    """A real frame captured inside a known call chain (the profiler
    walks f_back, so synthetic stacks come from real nested calls)."""

    def inner():
        return sys._getframe()

    def outer():
        return inner()

    return outer()


class TestDeterministic:
    def _prof(self, frames, names, **kw):
        return SamplingProfiler(
            0.01,
            frames_fn=lambda: dict(frames),
            threads_fn=lambda: dict(names),
            **kw,
        )

    def test_fixed_schedule_counts_exactly(self):
        frame = _leaf_frame()
        prof = self._prof({101: frame}, {101: "pqt-data_0"})
        for _ in range(7):
            prof.sample_once(exclude=set())
        snap = prof.snapshot()
        assert snap["samples"] == 7
        assert snap["lanes"] == {"pqt-data": 7}
        [stack] = snap["stacks"]
        assert stack["count"] == 7 and stack["lane"] == "pqt-data"
        # outermost-first, innermost last; frame ids are file:func:defline
        assert stack["stack"][-1].split(":")[1] == "inner"
        assert any(":outer:" in f for f in stack["stack"])

    def test_collapsed_format(self):
        frame = _leaf_frame()
        prof = self._prof({1: frame, 2: frame}, {1: "pqt-io_0", 2: "Thread-3"})
        prof.sample_once(exclude=set())
        lines = prof.collapsed().splitlines()
        assert len(lines) == 2
        for line in lines:
            stack, _, count = line.rpartition(" ")
            assert count == "1"
            parts = stack.split(";")
            assert parts[0] in ("pqt-io", "other")
            assert parts[-1].split(":")[1] == "inner"

    def test_top_self_time(self):
        frame = _leaf_frame()
        prof = self._prof({1: frame}, {1: "pqt-io_0"})
        for _ in range(3):
            prof.sample_once(exclude=set())
        [row] = prof.top(1)
        assert row["self"] == 3 and row["pct"] == 100.0
        assert row["lanes"] == {"pqt-io": 3}
        assert ":inner:" in row["frame"]
        assert "inner" in prof.render_top(3)

    def test_excludes_requested_threads(self):
        frame = _leaf_frame()
        prof = self._prof({1: frame, 2: frame}, {1: "a", 2: "b"})
        assert prof.sample_once(exclude={1}) == 1
        assert prof.snapshot()["samples"] == 1

    def test_fake_clock_pins_duration(self):
        """The capture duration comes from the injected clock, so a
        replayed schedule reports a deterministic window length."""
        ticks = iter([100.0, 100.0, 103.5])
        frame = _leaf_frame()
        prof = self._prof(
            {1: frame}, {1: "pqt-io_0"}, clock=lambda: next(ticks)
        )
        prof._t_start = prof._clock()  # what start() records
        assert prof.duration_s == 0.0  # live read: second tick
        prof.sample_once(exclude=set())
        prof._duration = prof._clock() - prof._t_start  # what stop() seals
        prof._t_start = None
        assert prof.duration_s == 3.5
        assert prof.snapshot()["duration_s"] == 3.5


class TestBounds:
    def test_max_depth_truncates(self):
        def deep(n):
            if n == 0:
                return sys._getframe()
            return deep(n - 1)

        frame = deep(40)
        prof = SamplingProfiler(
            0.01,
            max_depth=5,
            frames_fn=lambda: {1: frame},
            threads_fn=lambda: {1: "pqt-io_0"},
        )
        prof.sample_once(exclude=set())
        [stack] = prof.snapshot()["stacks"]
        assert len(stack["stack"]) == 5

    def test_max_stacks_overflow_folds_and_totals_stay_exact(self):
        frame = _leaf_frame()
        prof = SamplingProfiler(
            0.01,
            max_stacks=3,
            frames_fn=lambda: {1: frame},
            threads_fn=lambda: {1: "pqt-io_0"},
        )
        # distinct (lane, stack) keys via distinct thread lanes: drive
        # more distinct keys than max_stacks through one profiler
        for i, lane in enumerate(
            ("pqt-io_0", "pqt-data_0", "pqt-serve_0", "pqt-encode_0", "Thread-1")
        ):
            prof._frames_fn = lambda: {1: _leaf_frame()}
            prof._threads_fn = lambda lane=lane: {1: lane}
            prof.sample_once(exclude=set())
        snap = prof.snapshot()
        assert snap["samples"] == 5  # nothing lost
        assert sum(s["count"] for s in snap["stacks"]) == 5  # totals exact
        assert snap["truncated_samples"] >= 1
        assert any(s["stack"] == ["~overflow~"] for s in snap["stacks"])

    def test_validation(self):
        with pytest.raises(ValueError):
            SamplingProfiler(0)
        with pytest.raises(ValueError):
            SamplingProfiler(0.01, max_stacks=0)
        with pytest.raises(ValueError):
            SamplingProfiler(0.01, max_depth=0)
        with pytest.raises(ValueError):
            capture(0)


# -- the live daemon thread ----------------------------------------------------


class TestLive:
    def test_samples_busy_pool_threads(self):
        stop = threading.Event()

        def spin():
            x = 0
            while not stop.is_set():
                x += 1

        workers = [
            threading.Thread(target=spin, name=f"pqt-encode_{i}", daemon=True)
            for i in range(2)
        ]
        for w in workers:
            w.start()
        try:
            prof = capture(0.25, 0.005)
        finally:
            stop.set()
            for w in workers:
                w.join(WATCHDOG_S)
        snap = prof.snapshot()
        assert snap["samples"] > 0
        assert snap["lanes"].get("pqt-encode", 0) > 0
        assert "pqt-encode;" in prof.collapsed()
        assert prof.duration_s > 0

    def test_one_capture_window_per_process(self):
        hold = threading.Event()
        results = {}

        def long_capture():
            try:
                results["prof"] = capture(
                    5.0, 0.01, sleep=lambda s: hold.wait(WATCHDOG_S)
                )
            except ProfilerBusy as e:  # pragma: no cover - ordering guard
                results["err"] = e

        t = threading.Thread(target=long_capture, daemon=True)
        t.start()
        deadline = time.monotonic() + WATCHDOG_S
        from parquet_tpu.obs import prof as prof_mod

        while not prof_mod._capture_lock.locked():
            assert time.monotonic() < deadline, "capture never started"
            time.sleep(0.005)
        with pytest.raises(ProfilerBusy):
            capture(0.1)
        hold.set()
        t.join(WATCHDOG_S)
        assert "prof" in results

    def test_capture_excludes_its_own_caller(self):
        """The requesting thread spends the window asleep inside
        capture(); sampling it would fill the 'other'/'main' lane with
        the profiling request itself."""
        prof = capture(0.15, 0.005)
        me = "main"  # pytest drives this test on MainThread
        lanes = prof.snapshot()["lanes"]
        assert lanes.get(me, 0) == 0, lanes

    def test_start_twice_raises_and_stop_is_idempotent(self):
        prof = SamplingProfiler(0.005)
        prof.start()
        with pytest.raises(RuntimeError):
            prof.start()
        prof.stop()
        prof.stop()


# -- the overhead pin ----------------------------------------------------------


@pytest.fixture(scope="module")
def scan_file(tmp_path_factory):
    """A smoke-scale slice of the bench headline's file shape (int64 +
    dict string + int64, snappy)."""
    import pyarrow as pa
    import pyarrow.parquet as pq

    n = 120_000
    rng = np.random.default_rng(11)
    vendors = np.array([f"vendor_{i:03d}" for i in range(50)])
    t = pa.table(
        {
            "trip_id": pa.array(np.arange(n, dtype=np.int64)),
            "vendor": pa.array(vendors[rng.integers(0, len(vendors), n)]),
            "ts": pa.array(np.cumsum(rng.integers(0, 1000, n)).astype(np.int64)),
        }
    )
    path = tmp_path_factory.mktemp("prof_scan") / "scan.parquet"
    pq.write_table(
        t, str(path), compression="snappy", row_group_size=40_000,
        use_dictionary=["vendor"],
    )
    return str(path)


class TestOverheadPin:
    def _scan_wall(self, path, repeats=2) -> float:
        from parquet_tpu.core.reader import FileReader

        best = float("inf")
        for _ in range(repeats):
            t0 = time.perf_counter()
            with FileReader(path, backend="host") as r:
                for i in range(r.num_row_groups):
                    r.read_row_group(i)
            best = min(best, time.perf_counter() - t0)
        return best

    def test_sampling_overhead_under_5pct_on_scan(self, scan_file):
        """The acceptance pin: a live profiler at the default 10 ms
        interval costs <5% on the scan headline (smoke scale). Measured
        as best-of ratio with a retry ladder so one scheduler hiccup on
        a noisy CI box does not fail the build — the LAST attempt must
        hold the pin."""
        self._scan_wall(scan_file, repeats=1)  # warm page cache / imports
        ratio = None
        for _attempt in range(3):
            plain = self._scan_wall(scan_file)
            prof = SamplingProfiler(0.010)
            prof.start()
            try:
                profiled = self._scan_wall(scan_file)
            finally:
                prof.stop()
            ratio = profiled / plain
            if ratio < 1.05:
                break
        assert ratio is not None and ratio < 1.05, (
            f"sampling overhead {ratio:.3f}x exceeds the 1.05x pin"
        )
        # and the window actually sampled this process while it scanned
        assert prof.snapshot()["samples"] > 0

"""parquet_tpu.serve: the concurrent scan/query daemon's contracts.

Pinned here:
  * protocol: every malformed request shape fails with a typed 400 body
    (stable `code`, never a traceback), and the JSON filter-spec parser is
    the SAME one `parquet-tool scan --filters` uses;
  * correctness: streamed jsonl and arrow-ipc responses are byte-/value-
    identical to direct FileReader scans — single client and N concurrent
    clients hammering one daemon;
  * warm-cache planning: a repeated request performs ZERO byte-source
    reads (footer + block cache hits only), asserted via io counter
    deltas;
  * admission: queue-full and tenant budgets reject with typed 429s,
    drain rejects with typed 503s, deadlines expire mid-scan as typed
    504s — and the daemon stays healthy through all of it;
  * graceful drain: SIGTERM completes the in-flight request byte-
    identically while new ones are refused;
  * chaos: a latency-spiked source (FlakySource.latency_spike) produces
    slow-but-correct responses or typed timeouts, never a hung worker or
    a torn-but-complete-looking body.

Real-sleep hammer variants are marked `slow`; the fast subset rides the
tier-1 `-m 'not slow'` run.
"""

from __future__ import annotations

import http.client
import json
import os
import signal
import threading
import time

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from parquet_tpu.core.reader import FileReader
from parquet_tpu.data.plan import build_plan
from parquet_tpu.io.cache import BlockCache, FooterCache
from parquet_tpu.io.source import LocalFileSource
from parquet_tpu.serve import ScanServer, ServeConfig, ServeError
from parquet_tpu.serve.admission import AdmissionController, Deadline
from parquet_tpu.serve.protocol import (
    filters_from_spec,
    json_default,
    parse_scan_request,
    scan_request_from_query,
)
from parquet_tpu.testing.flaky import FlakySource
from parquet_tpu.utils import metrics

WATCHDOG_S = 30.0  # every blocking wait in this file is bounded by this

ROWS_A, ROWS_B = 2400, 1800
ROW_GROUP = 800


# -- fixtures ------------------------------------------------------------------


def _write_corpus(d):
    """Two files, several row groups each, ids globally sorted so min/max
    statistics can prune whole groups."""
    rng = np.random.default_rng(7)
    rows = {"a.parquet": (0, ROWS_A), "b.parquet": (ROWS_A, ROWS_B)}
    for name, (base, n) in rows.items():
        t = pa.table(
            {
                "id": pa.array(np.arange(base, base + n, dtype=np.int64)),
                "v": pa.array(rng.standard_normal(n).astype(np.float64)),
                "name": pa.array([f"n{i % 13}" for i in range(n)]),
            }
        )
        pq.write_table(t, str(d / name), row_group_size=ROW_GROUP)
    return d


@pytest.fixture(scope="module")
def corpus(tmp_path_factory):
    return _write_corpus(tmp_path_factory.mktemp("serve_corpus"))


@pytest.fixture()
def server(corpus):
    with ScanServer(ServeConfig(port=0, root=str(corpus), cache_mb=32)) as s:
        s.start_background()
        yield s


def _request(
    server,
    method,
    path,
    body=None,
    headers=None,
    timeout=WATCHDOG_S,
):
    """One HTTP exchange with a hard socket timeout (a hang fails the test
    instead of wedging the run). Returns (status, headers, body_bytes)."""
    conn = http.client.HTTPConnection(server.host, server.port, timeout=timeout)
    try:
        conn.request(
            method,
            path,
            body=json.dumps(body).encode() if body is not None else None,
            headers=headers or {},
        )
        resp = conn.getresponse()
        return resp.status, dict(resp.getheaders()), resp.read()
    finally:
        conn.close()


def _scan(server, body, headers=None, timeout=WATCHDOG_S):
    return _request(server, "POST", "/v1/scan", body, headers, timeout)


def _expected_jsonl(corpus, names, columns=None, filters=None, limit=None):
    """The daemon contract: rows of every file in sorted path order,
    serialized exactly as the executor does."""
    out = []
    n = 0
    for name in sorted(names):
        with FileReader(str(corpus / name), columns=columns) as r:
            for row in r.iter_rows(filters=filters):
                out.append(json.dumps(row, default=json_default) + "\n")
                n += 1
                if limit is not None and n >= limit:
                    return "".join(out).encode()
    return "".join(out).encode()


def _settled_delta(snap, key: str, *, want: int = 1, timeout_s: float = 5.0):
    """metrics.delta(snap) once `key` reaches `want`. The handler finishes
    a request (counter + SLI sample + flight-record close) AFTER the
    response bytes flush, so a delta taken the instant the client reads
    the body can race it — poll briefly, then assert on the settled view."""
    deadline = time.time() + timeout_s
    while True:
        d = metrics.delta(snap)
        if d.get(key, 0) >= want or time.time() >= deadline:
            return d
        time.sleep(0.002)


def _settled_record(server, rid: str, *, timeout_s: float = 5.0):
    """GET /v1/debug/requests/<rid> once the record has CLOSED — the
    recorder's finish step runs after the response flushes, the same race
    _settled_delta absorbs. Returns (status, doc)."""
    deadline = time.time() + timeout_s
    while True:
        s, _h, b = _request(server, "GET", f"/v1/debug/requests/{rid}")
        doc = json.loads(b)
        if s != 200 or doc.get("open") is False or time.time() >= deadline:
            return s, doc
        time.sleep(0.002)


def _error_code(body: bytes) -> str:
    doc = json.loads(body)
    assert set(doc) == {"error"}, doc
    # request_id and trace_id ride every error body produced inside a
    # recorded request (the correlation keys for /v1/debug/requests and
    # cross-process trace-merge); pre-record errors (bad route, oversized
    # body) legitimately lack them
    extra = {"request_id", "trace_id"}
    assert set(doc["error"]) - extra == {"code", "message", "status"}, doc
    rid = doc["error"].get("request_id")
    if rid is not None:
        assert isinstance(rid, str) and 0 < len(rid) <= 64, doc
    tid = doc["error"].get("trace_id")
    if tid is not None:
        assert isinstance(tid, str) and len(tid) == 32, doc
    return doc["error"]["code"]


class _GatedSource:
    """A ByteSource whose data reads block until the test opens the gate —
    the deterministic way to hold a request in flight."""

    def __init__(self, path, gate):
        self._inner = LocalFileSource(path)
        self._gate = gate

    @property
    def source_id(self):
        return self._inner.source_id

    def size(self):
        return self._inner.size()

    def read_at(self, offset, n):
        assert self._gate.wait(WATCHDOG_S), "test gate never opened"
        return self._inner.read_at(offset, n)

    def read_ranges(self, ranges):
        return [self.read_at(o, n) for o, n in ranges]

    def close(self):
        self._inner.close()


# -- protocol ------------------------------------------------------------------


class TestProtocol:
    def test_filter_spec_shapes(self):
        assert filters_from_spec(None) is None
        assert filters_from_spec([]) is None
        assert filters_from_spec([["id", "<", 3]]) == [("id", "<", 3)]
        dnf = filters_from_spec([[["id", "<", 3]], [["id", ">=", 9]]])
        assert dnf == [[("id", "<", 3)], [("id", ">=", 9)]]

    @pytest.mark.parametrize(
        "spec",
        [
            "id < 3",  # not a list
            [["id"]],  # not a triple
            [[3, "<", 3]],  # column not a string
            [["id", "~", 3]],  # unknown op
        ],
    )
    def test_filter_spec_rejections(self, spec):
        with pytest.raises(ServeError) as ei:
            filters_from_spec(spec)
        assert ei.value.status == 400
        assert ei.value.code == "bad_filters"

    @pytest.mark.parametrize(
        "raw,code",
        [
            (b"", "bad_request"),
            (b"not json", "bad_request"),
            (b"[1,2]", "bad_request"),
            (b'{"paths": []}', "bad_request"),
            (b'{"paths": "a", "nope": 1}', "bad_request"),
            (b'{"paths": "a", "limit": -1}', "bad_request"),
            (b'{"paths": "a", "limit": true}', "bad_request"),
            (b'{"paths": "a", "format": "csv"}', "bad_request"),
            (b'{"paths": "a", "shard": [2, 2]}', "bad_request"),
            (b'{"paths": "a", "timeout_ms": 0}', "bad_request"),
            (b'{"paths": "a", "filters": [["id", "~", 1]]}', "bad_filters"),
        ],
    )
    def test_parse_rejections_are_typed(self, raw, code):
        with pytest.raises(ServeError) as ei:
            parse_scan_request(raw)
        assert ei.value.status == 400
        assert ei.value.code == code
        assert _error_code(json.dumps(ei.value.to_body()).encode()) == code

    def test_parse_accepts_full_request(self):
        req = parse_scan_request(
            json.dumps(
                {
                    "paths": "a.parquet",
                    "columns": "id,v",
                    "filters": [["id", "<", 10]],
                    "limit": 5,
                    "format": "arrow-ipc",
                    "shard": "1/2",
                    "timeout_ms": 1000,
                }
            ).encode()
        )
        assert req.paths == ["a.parquet"]
        assert req.columns == ["id", "v"]
        assert req.filters == [("id", "<", 10)]
        assert req.limit == 5 and req.format == "arrow-ipc"
        assert req.shard == (1, 2) and req.timeout_ms == 1000

    def test_query_request(self):
        req = scan_request_from_query(
            {
                "paths": ["a.parquet,b.parquet"],
                "columns": ["id"],
                "filters": ['[["id", ">=", 7]]'],
                "limit": ["3"],
                "shard": ["0/2"],
            }
        )
        assert req.paths == ["a.parquet", "b.parquet"]
        assert req.columns == ["id"]
        assert req.filters == [("id", ">=", 7)]
        assert req.limit == 3 and req.shard == (0, 2)
        with pytest.raises(ServeError):
            scan_request_from_query({})


# -- admission (clock-injected unit level) -------------------------------------


class _FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


class TestAdmission:
    def test_queue_full_and_release(self):
        a = AdmissionController(max_inflight=2)
        t1, t2 = a.admit("x"), a.admit("y")
        with pytest.raises(ServeError) as ei:
            a.admit("z")
        assert ei.value.status == 429 and ei.value.code == "queue_full"
        t1.release()
        t1.release()  # idempotent
        a.admit("z").release()
        t2.release()
        assert a.in_flight == 0

    def test_tenant_concurrency_is_per_tenant(self):
        a = AdmissionController(max_inflight=10, tenant_concurrent=1)
        t = a.admit("alice")
        with pytest.raises(ServeError) as ei:
            a.admit("alice")
        assert ei.value.code == "tenant_concurrency"
        a.admit("bob").release()  # other tenants unaffected
        t.release()
        a.admit("alice").release()

    def test_tenant_budget_token_bucket(self):
        clock = _FakeClock()
        a = AdmissionController(
            tenant_budget_bytes=1000, budget_window_s=10.0, clock=clock
        )
        a.charge("t", 600)
        with pytest.raises(ServeError) as ei:
            a.charge("t", 600)  # 400 left
        assert ei.value.status == 429
        assert ei.value.code == "tenant_over_budget"
        assert ei.value.retry_after_s >= 1
        clock.t += 2.0  # +200 tokens
        a.charge("t", 600)
        # a full bucket admits one oversized scan rather than never serving it
        clock.t += 100.0
        a.charge("t", 5000)
        with pytest.raises(ServeError):
            a.charge("t", 1)

    def test_deadline(self):
        clock = _FakeClock()
        d = Deadline(5.0, clock=clock)
        d.check()
        assert d.remaining() == 5.0
        clock.t = 5.0
        with pytest.raises(ServeError) as ei:
            d.check()
        assert ei.value.status == 504 and ei.value.code == "deadline_exceeded"
        assert Deadline(None, clock=clock).remaining() is None

    def test_tenant_table_is_bounded(self):
        overflow = AdmissionController.OVERFLOW_TENANT
        a = AdmissionController(max_tenants=2, tenant_concurrent=4)
        # the label set saturates at max_tenants for the LIFE of the
        # process — a flood of distinct X-Tenant values (on any endpoint,
        # admitted or not) cannot grow memory or the metrics label set
        assert a.resolve_tenant("x") == "x"
        assert a.resolve_tenant("y") == "y"
        assert a.resolve_tenant("z") == overflow
        assert a.resolve_tenant("w") == overflow
        assert a.resolve_tenant("x") == "x"  # known names keep their key
        # sanitization: empty/whitespace -> "default", long names truncated
        assert a.resolve_tenant(None) == overflow  # set already saturated
        b = AdmissionController(max_tenants=8)
        assert b.resolve_tenant(None) == "default"
        assert b.resolve_tenant("  ") == "default"
        assert len(b.resolve_tenant("q" * 200)) == 64
        # the per-tenant STATE table is bounded too: full + all active ->
        # overflow bucket; an idle tenant is evicted to make room
        t1, t2 = a.admit("x"), a.admit("y")
        t3 = a.admit("z")
        assert t3.tenant == overflow
        t1.release()
        t3.release()
        t4 = a.admit("v")
        assert t4.tenant == "v"  # "x" (idle) was evicted
        assert len(a._tenants) <= 3  # y/v + at most the overflow bucket
        for t in (t2, t4):
            t.release()

    def test_drain_semantics(self):
        a = AdmissionController()
        t = a.admit("x")
        a.begin_drain()
        with pytest.raises(ServeError) as ei:
            a.admit("y")
        assert ei.value.status == 503 and ei.value.code == "draining"
        assert a.wait_drained(timeout=0.05) is False
        t.release()
        assert a.wait_drained(timeout=WATCHDOG_S) is True


# -- plan: pruning summary + push-down -----------------------------------------


class TestPlan:
    def test_build_plan_pruning_summary(self, corpus):
        paths = str(corpus / "*.parquet")
        plan = build_plan(paths)
        total = -(-ROWS_A // ROW_GROUP) + -(-ROWS_B // ROW_GROUP)
        assert plan.pruning_summary() == {
            "units_total": total,
            "units_pruned_stats": 0,
            "units_pruned_bloom": 0,
            "units_admitted": total,
        }
        plan = build_plan(paths, filters=[("id", "<", ROW_GROUP)])
        assert plan.units_total == total
        assert plan.units_pruned_stats == total - 1
        assert plan.num_units == 1
        assert (
            plan.units_total
            - plan.units_pruned_stats
            - plan.units_pruned_bloom
            == plan.num_units
        )

    def test_plan_endpoint_reports_pruning(self, server):
        flt = json.dumps([["id", "<", ROW_GROUP]])
        status, _h, body = _request(
            server,
            "GET",
            "/v1/plan?paths=a.parquet,b.parquet&filters=" + flt.replace(" ", ""),
        )
        assert status == 200
        doc = json.loads(body)
        assert doc["units_admitted"] == doc["units"] == 1
        assert doc["units_pruned_stats"] == doc["units_total"] - 1
        assert doc["rows"] == ROW_GROUP
        assert doc["estimated_bytes"] > 0

    def test_plan_post_matches_get(self, server):
        _s, _h, via_get = _request(server, "GET", "/v1/plan?paths=a.parquet")
        _s, _h, via_post = _request(
            server, "POST", "/v1/plan", {"paths": "a.parquet"}
        )
        assert via_get == via_post

    def test_warm_plan_zero_source_reads(self, server):
        flt = json.dumps([["id", "<", 100]]).replace(" ", "")
        path = "/v1/plan?paths=a.parquet,b.parquet&filters=" + flt
        _request(server, "GET", path)  # cold: parses footers
        s0 = metrics.snapshot()
        status, _h, _b = _request(server, "GET", path)
        d = metrics.delta(s0)
        assert status == 200
        assert d.get("io_bytes_read_total", 0) == 0
        assert d.get("io_read_calls_total", 0) == 0
        assert d.get("io_footer_cache_hits_total", 0) >= 2

    def test_bloom_pruning_counted_and_cached(self, tmp_path):
        from parquet_tpu.core.writer import FileWriter as PqtWriter
        from parquet_tpu.schema.dsl import parse_schema

        path = str(tmp_path / "bloomed.parquet")
        schema = parse_schema("message m { required binary s (UTF8); }")
        with PqtWriter(path, schema, bloom_filters=["s"]) as w:
            for part in (
                [f"k{i}" for i in range(500)],
                [f"k{i}" for i in range(500, 1000)],
            ):
                w.write_column("s", part)
                w.flush_row_group()
        # stats cannot prune "zzz" (within k0..k999 lexically? no: > k999)
        # so probe a value INSIDE the min/max range that no group contains
        fc, bc = FooterCache(), BlockCache(8 << 20)
        plan = build_plan(
            path,
            filters=[("s", "==", "k499x")],
            footer_cache=fc,
            block_cache=bc,
        )
        assert plan.num_units == 0
        assert plan.units_pruned_bloom + plan.units_pruned_stats == 2
        assert plan.units_pruned_bloom >= 1
        # warm re-plan: bloom pages come from the block cache, footers from
        # the footer cache — zero source reads
        s0 = metrics.snapshot()
        plan2 = build_plan(
            path,
            filters=[("s", "==", "k499x")],
            footer_cache=fc,
            block_cache=bc,
        )
        d = metrics.delta(s0)
        assert plan2.pruning_summary() == plan.pruning_summary()
        assert d.get("io_bytes_read_total", 0) == 0


# -- scan correctness ----------------------------------------------------------


class TestScanCorrectness:
    def test_jsonl_matches_filereader(self, server, corpus):
        status, headers, body = _scan(
            server, {"paths": ["a.parquet", "b.parquet"]}
        )
        assert status == 200
        assert headers["Content-Type"] == "application/x-ndjson"
        assert body == _expected_jsonl(corpus, ["a.parquet", "b.parquet"])

    def test_glob_columns_filters_limit(self, server, corpus):
        body_spec = {
            "paths": "*.parquet",
            "columns": ["id", "name"],
            "filters": [["id", ">=", ROWS_A - 5]],
            "limit": 8,
        }
        status, _h, body = _scan(server, body_spec)
        assert status == 200
        assert body == _expected_jsonl(
            corpus,
            ["a.parquet", "b.parquet"],
            columns=["id", "name"],
            filters=[("id", ">=", ROWS_A - 5)],
            limit=8,
        )

    def test_arrow_ipc_matches_to_arrow(self, server, corpus):
        status, headers, body = _scan(
            server, {"paths": "a.parquet", "format": "arrow-ipc"}
        )
        assert status == 200
        assert headers["Content-Type"] == "application/vnd.apache.arrow.stream"
        got = pa.ipc.open_stream(body).read_all()
        with FileReader(str(corpus / "a.parquet")) as r:
            want = r.to_arrow()
        assert got.equals(want)

    def test_arrow_ipc_empty_result_is_valid_stream(self, server):
        status, _h, body = _scan(
            server,
            {
                "paths": "a.parquet",
                "format": "arrow-ipc",
                "filters": [["id", "<", -1]],
            },
        )
        assert status == 200
        got = pa.ipc.open_stream(body).read_all()
        assert got.num_rows == 0 and "id" in got.column_names

    def test_shard_request_partitions_corpus(self, server, corpus):
        parts = []
        for i in (0, 1):
            status, _h, body = _scan(
                server, {"paths": "*.parquet", "shard": [i, 2]}
            )
            assert status == 200
            parts.append(body)
        whole = _expected_jsonl(corpus, ["a.parquet", "b.parquet"])
        got_ids = sorted(
            json.loads(ln)["id"]
            for part in parts
            for ln in part.decode().splitlines()
        )
        want_ids = [
            json.loads(ln)["id"] for ln in whole.decode().splitlines()
        ]
        assert got_ids == want_ids  # every row exactly once across shards

    def test_request_errors_are_typed(self, server):
        for body_spec, status, code in [
            ({"paths": "missing.parquet"}, 404, "not_found"),
            ({"paths": "../etc/passwd"}, 403, "path_outside_root"),
            ({"paths": "/etc/passwd"}, 403, "path_outside_root"),
            ({"paths": "a.parquet", "columns": ["nope"]}, 400, "bad_columns"),
            (
                {"paths": "a.parquet", "filters": [["nope", "<", 1]]},
                400,
                "bad_request",
            ),
        ]:
            s, _h, b = _scan(server, body_spec)
            assert (s, _error_code(b)) == (status, code), body_spec
        s, _h, b = _request(server, "GET", "/v1/nope")
        assert s == 404 and _error_code(b) == "no_such_route"

    def test_warm_scan_zero_source_reads(self, server, corpus):
        spec = {"paths": "a.parquet", "columns": ["id", "v"]}
        cold = _scan(server, spec)[2]  # populates footer + block caches
        s0 = metrics.snapshot()
        status, _h, warm = _scan(server, spec)
        d = metrics.delta(s0)
        assert status == 200 and warm == cold
        assert d.get("io_bytes_read_total", 0) == 0
        assert d.get("io_read_calls_total", 0) == 0
        assert d.get("io_cache_hits_total", 0) > 0

    def test_concurrent_clients_byte_identical(self, server, corpus):
        want = _expected_jsonl(corpus, ["a.parquet", "b.parquet"])
        n_threads, per_thread = 8, 2
        results: dict[int, list] = {i: [] for i in range(n_threads)}
        errors: list = []

        def hammer(i):
            try:
                for _ in range(per_thread):
                    status, _h, body = _scan(server, {"paths": "*.parquet"})
                    results[i].append((status, body))
            except Exception as e:  # noqa: BLE001 - collected for the assert
                errors.append(e)

        threads = [
            threading.Thread(target=hammer, args=(i,)) for i in range(n_threads)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(WATCHDOG_S)
            assert not t.is_alive(), "hammer thread hung"
        assert not errors
        for i in range(n_threads):
            assert len(results[i]) == per_thread
            for status, body in results[i]:
                assert status == 200 and body == want

    def test_internal_bugs_render_typed_500(self, server):
        # a NON-ServeError escaping a handler is a bug, but the client
        # still sees the structured 500 body — never a traceback — and the
        # request is counted exactly once
        def boom(request):
            raise RuntimeError("wat")

        orig = server.service.session.plan
        server.service.session.plan = boom
        try:
            s0 = metrics.snapshot()
            status, _h, body = _scan(server, {"paths": "a.parquet"})
            d = _settled_delta(
                s0, 'serve_requests_total{status="500",tenant="default"}'
            )
            assert status == 500 and _error_code(body) == "internal"
            assert b"Traceback" not in body
            counted = [
                (k, v)
                for k, v in d.items()
                if k.startswith("serve_requests_total")
            ]
            assert counted == [
                ('serve_requests_total{status="500",tenant="default"}', 1)
            ]
        finally:
            server.service.session.plan = orig
        assert _scan(server, {"paths": "a.parquet", "limit": 1})[0] == 200

    def test_metrics_and_healthz(self, server):
        _scan(server, {"paths": "a.parquet", "limit": 1})
        s, _h, body = _request(server, "GET", "/metrics")
        text = body.decode()
        assert s == 200
        assert "parquet_tpu_serve_requests_total" in text
        assert "parquet_tpu_serve_queue_depth" in text
        assert "parquet_tpu_serve_request_seconds" in text
        assert "parquet_tpu_serve_scan_bytes_total" in text
        s, _h, body = _request(server, "GET", "/healthz")
        assert s == 200 and json.loads(body)["status"] == "ok"


# -- admission through HTTP ----------------------------------------------------


class TestAdmissionHTTP:
    def _gated_server(self, corpus, gate, **cfg):
        config = ServeConfig(
            port=0,
            root=str(corpus),
            cache_mb=0,
            source_factory=lambda p: _GatedSource(p, gate),
            **cfg,
        )
        return ScanServer(config)

    def _hold_one(self, server, errors):
        """Start a scan that blocks on the gate; returns its thread and a
        slot the response lands in."""
        out = {}

        def go():
            try:
                out["resp"] = _scan(server, {"paths": "a.parquet"})
            except Exception as e:  # noqa: BLE001
                errors.append(e)

        t = threading.Thread(target=go)
        t.start()
        deadline = time.monotonic() + WATCHDOG_S
        while server.service.admission.in_flight < 1:
            assert time.monotonic() < deadline, "request never admitted"
            time.sleep(0.005)
        return t, out

    def test_queue_full_429_then_recovers(self, corpus):
        gate = threading.Event()
        errors: list = []
        with self._gated_server(corpus, gate, max_inflight=1) as server:
            server.start_background()
            t, out = self._hold_one(server, errors)
            status, headers, body = _scan(server, {"paths": "a.parquet"})
            assert status == 429 and _error_code(body) == "queue_full"
            assert "Retry-After" in headers
            gate.set()
            t.join(WATCHDOG_S)
            assert not t.is_alive() and not errors
            assert out["resp"][0] == 200
            # slot freed: the same request now succeeds
            assert _scan(server, {"paths": "a.parquet"})[0] == 200

    def test_tenant_budget_exhaustion_429(self, corpus):
        # budget 1 MiB/window; one a.parquet scan estimate is ~tens of KiB,
        # so the FIRST drains the warm bucket and the SECOND is refused
        with ScanServer(
            ServeConfig(
                port=0,
                root=str(corpus),
                tenant_budget_mb=1,
                budget_window_s=3600.0,
            )
        ) as server:
            server.start_background()
            est = json.loads(
                _request(server, "GET", "/v1/plan?paths=a.parquet")[2]
            )["estimated_bytes"]
            assert est > 0
            headers = {"X-Tenant": "alice"}
            for _ in range((1 << 20) // est + 1):
                status, _h, body = _scan(
                    server, {"paths": "a.parquet", "limit": 1}, headers
                )
                if status != 200:
                    break
            assert status == 429 and _error_code(body) == "tenant_over_budget"
            # budgets are per tenant: bob is unaffected
            s2 = _scan(server, {"paths": "a.parquet", "limit": 1}, {"X-Tenant": "bob"})
            assert s2[0] == 200

    def test_deadline_expiry_mid_scan_leaves_daemon_healthy(self, corpus):
        slow = lambda p: FlakySource(  # noqa: E731
            LocalFileSource(p), seed=0, latency_s=0.25
        )
        with ScanServer(
            ServeConfig(port=0, root=str(corpus), cache_mb=0, source_factory=slow)
        ) as server:
            server.start_background()
            status, _h, body = _scan(
                server,
                {"paths": "*.parquet"},
                headers={"X-Timeout-Ms": "120"},
            )
            assert status == 504 and _error_code(body) == "deadline_exceeded"
            # the daemon is fine: healthy, and an unhurried scan completes
            assert _request(server, "GET", "/healthz")[0] == 200
            status, _h, body = _scan(server, {"paths": "a.parquet", "limit": 2})
            assert status == 200 and body.count(b"\n") == 2
            assert server.service.admission.in_flight == 0

    def test_stalled_client_frees_thread_and_slot(self, corpus):
        """A client that sends headers and then stalls (never the body, or
        never reads the response) must not pin a handler thread forever:
        the socket timeout tears the connection down and the daemon stays
        fully available."""
        import socket

        with ScanServer(
            ServeConfig(port=0, root=str(corpus), socket_timeout_s=0.3)
        ) as server:
            server.start_background()
            stalled = socket.create_connection(
                (server.host, server.port), timeout=WATCHDOG_S
            )
            try:
                # promise a body, never send it: the handler blocks in
                # _read_body until the socket timeout frees it
                stalled.sendall(
                    b"POST /v1/scan HTTP/1.1\r\n"
                    b"Host: x\r\nContent-Length: 100\r\n\r\n"
                )
                deadline = time.monotonic() + WATCHDOG_S
                stalled.settimeout(WATCHDOG_S)
                while True:
                    assert time.monotonic() < deadline, "stall never torn down"
                    if stalled.recv(4096) == b"":
                        break  # server closed the stalled connection
            finally:
                stalled.close()
            # the daemon is healthy and no admission slot leaked
            assert server.service.admission.in_flight == 0
            assert _scan(server, {"paths": "a.parquet", "limit": 1})[0] == 200

    def test_graceful_drain_on_sigterm(self, corpus):
        gate = threading.Event()
        errors: list = []
        prev_term = signal.getsignal(signal.SIGTERM)
        prev_int = signal.getsignal(signal.SIGINT)
        server = self._gated_server(corpus, gate)
        try:
            server.start_background()
            server.install_signal_handlers()
            t, out = self._hold_one(server, errors)
            os.kill(os.getpid(), signal.SIGTERM)
            deadline = time.monotonic() + WATCHDOG_S
            while not server.service.admission.draining:
                assert time.monotonic() < deadline, "SIGTERM never drained"
                time.sleep(0.005)
            # new requests refused with the typed 503 while draining
            status, _h, body = _scan(server, {"paths": "a.parquet"})
            assert status == 503 and _error_code(body) == "draining"
            s, _h, body = _request(server, "GET", "/healthz")
            assert s == 503 and json.loads(body)["status"] == "draining"
            # ... but the in-flight request runs to byte-identical completion
            gate.set()
            t.join(WATCHDOG_S)
            assert not t.is_alive() and not errors
            status, _h, body = out["resp"]
            assert status == 200
            assert body == _expected_jsonl(corpus, ["a.parquet"])
        finally:
            signal.signal(signal.SIGTERM, prev_term)
            signal.signal(signal.SIGINT, prev_int)
            server.close()


# -- failure streaming ---------------------------------------------------------


class TestTornStream:
    def test_mid_stream_corruption_tears_the_response(self, tmp_path):
        # file ordering puts the corrupt file SECOND, so the first unit
        # streams (200 sent) before the decode error surfaces
        d = _write_corpus(tmp_path)
        bad = d / "b.parquet"
        raw = bytearray(bad.read_bytes())
        # stomp every page header of the first row group (the bytes right
        # after the magic): decode MUST fail, footer stays parseable
        raw[4:2048] = b"\xde" * 2044
        bad.write_bytes(bytes(raw))
        with ScanServer(ServeConfig(port=0, root=str(d), window=1)) as server:
            server.start_background()
            conn = http.client.HTTPConnection(
                server.host, server.port, timeout=WATCHDOG_S
            )
            try:
                conn.request(
                    "POST",
                    "/v1/scan",
                    body=json.dumps({"paths": "*.parquet"}).encode(),
                )
                resp = conn.getresponse()
                assert resp.status == 200
                with pytest.raises(http.client.IncompleteRead) as ei:
                    resp.read()
                partial = ei.value.partial
            finally:
                conn.close()
            # the body carries a typed terminal error record, and the torn
            # chunked encoding is DETECTABLE (no terminating 0-chunk)
            last = partial.decode().splitlines()[-1]
            assert json.loads(last)["error"]["code"] == "unreadable_file"
            # the daemon survives and still serves the healthy file
            status, _h, body = _scan(server, {"paths": "a.parquet", "limit": 1})
            assert status == 200

    def test_routed_mid_stream_corruption_tears_the_same_way(self, tmp_path):
        """The PR 19 extension of the pin above: through the mesh ROUTER,
        a corrupt second file still yields a detectably torn stream whose
        terminal record is the replica's typed error — the healthy file's
        units stream first, the corrupt unit's typed 422 (sent by every
        replica BEFORE its 200) surfaces mid-stream, and the router never
        fabricates a clean end-of-stream."""
        from parquet_tpu.serve.mesh import MeshConfig, MeshRouter

        d = _write_corpus(tmp_path)
        bad = d / "b.parquet"
        raw = bytearray(bad.read_bytes())
        raw[4:2048] = b"\xde" * 2044
        bad.write_bytes(bytes(raw))
        replicas = [
            ScanServer(
                ServeConfig(port=0, root=str(d), window=1)
            ).start_background()
            for _ in range(3)
        ]
        router = MeshRouter(
            MeshConfig(port=0, replicas=tuple(r.url for r in replicas))
        ).start_background()
        try:
            conn = http.client.HTTPConnection(
                router.host, router.port, timeout=WATCHDOG_S
            )
            try:
                conn.request(
                    "POST",
                    "/v1/scan",
                    body=json.dumps({"paths": "*.parquet"}).encode(),
                )
                resp = conn.getresponse()
                assert resp.status == 200
                with pytest.raises(http.client.IncompleteRead) as ei:
                    resp.read()
                partial = ei.value.partial
            finally:
                conn.close()
            lines = partial.decode().splitlines()
            # the healthy file streamed before the tear...
            assert len(lines) > 1
            # ...and the terminal record is the replica's typed error
            assert json.loads(lines[-1])["error"]["code"] == "unreadable_file"
            # the router survives and still serves the healthy file
            status, _h, body = _request(
                router, "POST", "/v1/scan", {"paths": "a.parquet", "limit": 1}
            )
            assert status == 200
        finally:
            router.close()
            for s in replicas:
                s.close()


# -- chaos: the latency-spiked source ------------------------------------------


class TestLatencySpikes:
    def test_flaky_latency_spike_preset(self):
        data = b"0123456789" * 100
        from parquet_tpu.io.source import MemorySource

        sleeps: list = []
        src = FlakySource.latency_spike(
            MemorySource(data), seed=3, p=0.5, ms=40.0, sleep=sleeps.append
        )
        got = [src.read_at(i * 10, 10) for i in range(50)]
        assert got == [data[i * 10 : i * 10 + 10] for i in range(50)]
        assert 0 < src.spikes_injected < 50
        assert sleeps == [0.04] * src.spikes_injected
        # seeded: the same seed replays the same spike schedule
        src2 = FlakySource.latency_spike(
            MemorySource(data), seed=3, p=0.5, ms=40.0, sleep=lambda s: None
        )
        for i in range(50):
            src2.read_at(i * 10, 10)
        assert src2.spikes_injected == src.spikes_injected

    def test_spiked_source_slow_or_typed_timeout_never_hung(self, corpus):
        spiky = lambda p: FlakySource.latency_spike(  # noqa: E731
            LocalFileSource(p), seed=11, p=0.3, ms=20.0
        )
        with ScanServer(
            ServeConfig(port=0, root=str(corpus), cache_mb=0, source_factory=spiky)
        ) as server:
            server.start_background()
            want = _expected_jsonl(corpus, ["a.parquet"])
            # generous deadline: spikes slow the response but bytes are right
            for _ in range(3):
                status, _h, body = _scan(server, {"paths": "a.parquet"})
                assert status == 200 and body == want
            # hostile deadline: a clean typed 504, a clean completion, or a
            # DETECTABLY torn stream whose terminal record is the typed
            # deadline error (the deadline fired after the 200 went out) —
            # and the worker slot is always released, never a hung worker
            for _ in range(4):
                try:
                    status, _h, body = _scan(
                        server,
                        {"paths": "*.parquet"},
                        headers={"X-Timeout-Ms": "40"},
                    )
                except http.client.IncompleteRead as e:
                    last = e.partial.decode().splitlines()[-1]
                    assert (
                        json.loads(last)["error"]["code"] == "deadline_exceeded"
                    )
                    continue
                assert status in (200, 504)
                if status != 200:
                    assert _error_code(body) == "deadline_exceeded"
            deadline = time.monotonic() + WATCHDOG_S
            while server.service.admission.in_flight:
                assert time.monotonic() < deadline, "worker slot leaked"
                time.sleep(0.01)
            status, _h, body = _scan(server, {"paths": "a.parquet"})
            assert status == 200 and body == want

    @pytest.mark.slow
    def test_spiked_hammer(self, corpus):
        """8 concurrent clients against a spiking source with mixed
        deadlines: every response is byte-identical or a typed timeout."""
        spiky = lambda p: FlakySource.latency_spike(  # noqa: E731
            LocalFileSource(p), seed=29, p=0.2, ms=15.0
        )
        with ScanServer(
            ServeConfig(port=0, root=str(corpus), cache_mb=0, source_factory=spiky)
        ) as server:
            server.start_background()
            want = _expected_jsonl(corpus, ["a.parquet", "b.parquet"])
            errors: list = []

            def hammer(i):
                try:
                    for k in range(3):
                        hdrs = (
                            {"X-Timeout-Ms": "60"} if (i + k) % 3 == 0 else {}
                        )
                        try:
                            status, _h, body = _scan(
                                server, {"paths": "*.parquet"}, hdrs
                            )
                        except http.client.IncompleteRead as e:
                            last = e.partial.decode().splitlines()[-1]
                            code = json.loads(last)["error"]["code"]
                            assert code == "deadline_exceeded"
                            continue
                        if status == 200:
                            assert body == want
                        else:
                            assert status == 504
                            assert _error_code(body) == "deadline_exceeded"
                except Exception as e:  # noqa: BLE001
                    errors.append(e)

            threads = [
                threading.Thread(target=hammer, args=(i,)) for i in range(8)
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join(WATCHDOG_S * 2)
                assert not t.is_alive(), "hammer thread hung"
            assert not errors


# -- the CLI face --------------------------------------------------------------


class TestServeCLI:
    def test_serve_daemon_subprocess_sigterm(self, corpus):
        import subprocess
        import sys
        import urllib.request

        proc = subprocess.Popen(
            [
                sys.executable,
                "-m",
                "parquet_tpu.tools.parquet_tool",
                "serve",
                "--port",
                "0",
                "--root",
                str(corpus),
            ],
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
        )
        try:
            line = proc.stdout.readline()
            assert line.startswith("serve: listening on http://"), line
            url = line.split()[-1]
            body = json.dumps({"paths": "a.parquet", "limit": 2}).encode()
            req = urllib.request.Request(url + "/v1/scan", data=body, method="POST")
            got = urllib.request.urlopen(req, timeout=WATCHDOG_S).read()
            assert got.count(b"\n") == 2
            proc.send_signal(signal.SIGTERM)
            out, _ = proc.communicate(timeout=WATCHDOG_S)
            assert proc.returncode == 0
            assert "drained, bye" in out
        finally:
            if proc.poll() is None:
                proc.kill()
                proc.communicate(timeout=5)

    def test_cli_import_stays_serve_lazy(self):
        import subprocess
        import sys

        # `parquet-tool cat/head/meta` must not pay the serve-package
        # (http.server, pools) import — only `serve`/`scan --filters` do
        code = (
            "import sys; import parquet_tpu.tools.parquet_tool; "
            "assert 'parquet_tpu.serve' not in sys.modules, 'serve imported eagerly'; "
            "assert 'http.server' not in sys.modules, 'http.server imported eagerly'"
        )
        subprocess.run(
            [sys.executable, "-c", code], check=True, timeout=WATCHDOG_S * 2
        )

    def test_scan_filters_json_shares_the_spec_parser(self, corpus, capsys):
        from parquet_tpu.tools.parquet_tool import main as tool_main

        rc = tool_main(
            [
                "scan",
                str(corpus / "a.parquet"),
                "--columns",
                "id",
                "--filters",
                json.dumps([["id", "<", ROW_GROUP]]),
                "--json",
            ]
        )
        out = capsys.readouterr().out
        assert rc == 0
        doc = json.loads(out.strip().splitlines()[-1])
        assert doc["pruning"]["units_admitted"] == 1
        assert doc["pruning"]["units_pruned_stats"] == (
            doc["pruning"]["units_total"] - 1
        )
        assert doc["rows"] == ROW_GROUP
        # a bad spec fails with the shared parser's message, not a traceback
        rc = tool_main(
            [
                "scan",
                str(corpus / "a.parquet"),
                "--filters",
                json.dumps([["id", "~", 1]]),
            ]
        )
        err = capsys.readouterr().err
        assert rc == 1 and "unknown filter op" in err
        # --filter and --filters are mutually exclusive
        rc = tool_main(
            [
                "scan",
                str(corpus / "a.parquet"),
                "--filter",
                "id < 5",
                "--filters",
                "[]",
            ]
        )
        err = capsys.readouterr().err
        assert rc == 1 and "not both" in err


class TestRemoteMap:
    """PR 17: `--remote-map prefix=URL` — requested paths under a mapped
    prefix resolve to object-store URLs and scan through the ordinary
    remote read path, while everything else keeps the root confinement
    (escapes through a mapping still die with the typed 403)."""

    def test_mapped_prefix_plans_and_scans_from_the_stub(self, corpus):
        from parquet_tpu.testing.httpstub import RangeHttpStub

        data = (corpus / "a.parquet").read_bytes()
        with RangeHttpStub(files={"a.parquet": data}) as stub:
            with ScanServer(
                ServeConfig(
                    port=0,
                    root=str(corpus),
                    remote_map={"warm": stub.base_url},
                )
            ) as server:
                server.start_background()
                status, _, body = _request(
                    server,
                    "POST",
                    "/v1/plan",
                    {"paths": "warm/a.parquet"},
                )
                assert status == 200, body
                assert json.loads(body)["rows"] == ROWS_A
                status, _, body = _scan(
                    server, {"paths": "warm/a.parquet", "columns": ["id"]}
                )
                assert status == 200, body
                assert body == _expected_jsonl(
                    corpus, ["a.parquet"], columns=["id"]
                )
                assert stub.requests > 0  # the bytes really came remotely
                # local paths still work side by side with the mapping
                status, _, _ = _scan(server, {"paths": "b.parquet", "limit": 1})
                assert status == 200

    def test_escape_through_a_mapping_is_typed_403(self, corpus):
        from parquet_tpu.testing.httpstub import RangeHttpStub

        with RangeHttpStub(files={"x": b"irrelevant"}) as stub:
            with ScanServer(
                ServeConfig(
                    port=0,
                    root=str(corpus),
                    remote_map={"warm": stub.base_url},
                )
            ) as server:
                server.start_background()
                status, _, body = _scan(
                    server, {"paths": "warm/../../../etc/passwd"}
                )
                assert status == 403
                assert _error_code(body) == "path_outside_root"

    def test_cli_rejects_malformed_remote_map_spec(self, capsys):
        from parquet_tpu.tools.parquet_tool import main as tool_main

        rc = tool_main(
            ["serve", "--port", "0", "--remote-map", "no-equals-here"]
        )
        assert rc == 2
        assert "remote-map" in capsys.readouterr().err
        rc = tool_main(
            ["serve", "--port", "0", "--remote-map", "p=ftp://nope"]
        )
        assert rc == 2


class TestRequestHygiene:
    """Connection-level contracts: bounded body buffering, keep-alive
    integrity after typed errors, and config validation at startup."""

    def test_oversized_body_413_before_buffering(self, corpus):
        # the DECLARED Content-Length is rejected before a byte is
        # buffered — a client cannot make the daemon hold its body in RAM
        with ScanServer(
            ServeConfig(port=0, root=str(corpus), max_body_bytes=64)
        ) as server:
            server.start_background()
            big = {"paths": "a.parquet", "columns": ["id", "v", "name"] * 20}
            status, _h, body = _scan(server, big)
            assert status == 413 and _error_code(body) == "body_too_large"
            # the daemon stays healthy for right-sized requests
            assert _scan(server, {"paths": "a.parquet", "limit": 1})[0] == 200

    def test_keepalive_survives_error_with_unread_body(self, server):
        # a typed error sent BEFORE the route read the POST body must not
        # leave body bytes behind for the next request on the connection
        conn = http.client.HTTPConnection(
            server.host, server.port, timeout=WATCHDOG_S
        )
        try:
            payload = json.dumps({"paths": "a.parquet"}).encode()
            conn.request("POST", "/v1/nope", body=payload)
            resp = conn.getresponse()
            body = resp.read()
            assert resp.status == 404 and _error_code(body) == "no_such_route"
            # SAME connection: the next request must parse cleanly, not be
            # read out of leftover body bytes (stdlib HTML 400)
            conn.request("GET", "/healthz")
            resp = conn.getresponse()
            assert resp.status == 200
            assert json.loads(resp.read())["status"] == "ok"
        finally:
            conn.close()

    def test_bad_timeouts_rejected_at_startup(self):
        with pytest.raises(ValueError, match="default_timeout_s"):
            ServeConfig(default_timeout_s=-1)
        with pytest.raises(ValueError, match="max_timeout_s"):
            ServeConfig(max_timeout_s=0)
        with pytest.raises(ValueError, match="max_body_bytes"):
            ServeConfig(max_body_bytes=0)

    def test_cli_rejects_negative_timeout(self, corpus, capsys):
        # a user guessing -1 means "no timeout" (0 is the documented
        # disable) must fail at startup, not run a daemon that 504s
        # every request instantly
        from parquet_tpu.tools.parquet_tool import main as tool_main

        rc = tool_main(
            ["serve", "--port", "0", "--root", str(corpus), "--timeout-s", "-1"]
        )
        err = capsys.readouterr().err
        assert rc == 1 and "default_timeout_s" in err


# -- flight recorder + request correlation (parquet_tpu.obs over serve) --------


class TestFlightRecorder:
    """The PR-9 operator story: a client-supplied X-Request-Id is echoed,
    sanitized, and retrievable at /v1/debug/requests/<id> with status,
    tenant, pruning summary, queue-wait and stage rollup — plus a
    Perfetto-loadable trace when sampled/slow/errored. The ring and its
    trace retention stay bounded under a concurrent hammer."""

    @pytest.fixture()
    def sampled_server(self, corpus):
        with ScanServer(
            ServeConfig(
                port=0, root=str(corpus), cache_mb=32, trace_sample_rate=1.0
            )
        ) as s:
            s.start_background()
            yield s

    def test_request_id_roundtrip_record_and_trace(self, sampled_server):
        server = sampled_server
        body_spec = {"paths": "a.parquet", "columns": ["id"]}
        status, headers, body = _scan(
            server, body_spec, headers={"X-Request-Id": "demo"}
        )
        assert status == 200
        assert headers.get("X-Request-Id") == "demo"  # echoed verbatim
        # byte identity: the correlation header must not perturb the payload
        status2, headers2, body2 = _scan(server, body_spec)
        assert status2 == 200 and body2 == body
        assert headers2.get("X-Request-Id")  # generated when not supplied

        s, doc = _settled_record(server, "demo")
        assert s == 200
        assert doc["id"] == "demo"
        assert doc["endpoint"] == "/v1/scan"
        assert doc["tenant"] == "default"
        assert doc["status"] == 200
        assert doc["open"] is False
        assert doc["bytes"] == len(body)  # payload bytes, chunked framing off
        assert doc["duration_ms"] > 0
        assert doc["queue_wait_ms"] >= 0
        plan = doc["plan"]
        assert plan["files"] == 1 and plan["units_admitted"] >= 1
        assert "units_pruned_stats" in plan and "units_pruned_bloom" in plan
        stages = doc["stages"]
        assert stages and all(
            # nested_seconds rides sub-clocked stages only (the share of
            # a stage's time already billed to an enclosing stage)
            {"seconds", "bytes", "calls"}
            <= set(v)
            <= {"seconds", "bytes", "calls", "nested_seconds"}
            for v in stages.values()
        )
        assert "pool.wait" in stages  # the queue-wait rollup's source

        # rate 1.0: the span tree was kept, and it is Perfetto-shaped
        assert doc["has_trace"] and doc["trace_kind"] == "sampled"
        s, _h, b = _request(server, "GET", "/v1/debug/requests/demo/trace")
        assert s == 200
        tr = json.loads(b)
        assert tr["traceEvents"]
        for ev in tr["traceEvents"]:
            assert "ph" in ev and "name" in ev and "pid" in ev
        req_meta = dict(tr["otherData"]["request"])
        # the cross-process join key rides the debug trace (trace-merge
        # stitches per-process dumps on it); 32-hex, never the raw header
        tid = req_meta.pop("trace_id")
        assert isinstance(tid, str) and len(tid) == 32
        assert req_meta == {
            "id": "demo", "endpoint": "/v1/scan", "tenant": "default",
        }

        # the listing includes it, newest first
        s, _h, b = _request(server, "GET", "/v1/debug/requests")
        reqs = json.loads(b)["requests"]
        assert any(r["id"] == "demo" for r in reqs)

    def test_hostile_request_id_sanitized_everywhere(self, sampled_server):
        server = sampled_server
        raw = "e{vil}|id;" + "x" * 200
        status, headers, _b = _scan(
            server,
            {"paths": "a.parquet", "columns": ["id"]},
            headers={"X-Request-Id": raw},
        )
        assert status == 200
        rid = headers["X-Request-Id"]
        assert len(rid) <= 64
        assert all(c.isalnum() or c in "._:-" for c in rid)
        s, _h, b = _request(server, "GET", f"/v1/debug/requests/{rid}")
        assert s == 200 and json.loads(b)["id"] == rid

    def test_errored_request_always_keeps_trace(self, corpus):
        with ScanServer(
            ServeConfig(
                port=0, root=str(corpus), cache_mb=32, trace_sample_rate=0.0
            )
        ) as server:
            server.start_background()
            status, _h, body = _scan(
                server,
                {"paths": "missing.parquet"},
                headers={"X-Request-Id": "whoops"},
            )
            assert status == 404
            assert json.loads(body)["error"]["request_id"] == "whoops"
            s, doc = _settled_record(server, "whoops")
            assert doc["status"] == 404
            assert doc["error"]  # the truncated message, retrievable later
            assert doc["has_trace"] and doc["trace_kind"] == "error"
            s, _h, _b = _request(
                server, "GET", "/v1/debug/requests/whoops/trace"
            )
            assert s == 200

    def test_slow_request_counts_and_keeps_trace(self, corpus):
        with ScanServer(
            ServeConfig(
                port=0, root=str(corpus), cache_mb=32,
                trace_sample_rate=0.0, slow_ms=0.0001,
            )
        ) as server:
            server.start_background()
            snap = metrics.snapshot()
            status, _h, _b = _scan(
                server,
                {"paths": "a.parquet", "columns": ["id"]},
                headers={"X-Request-Id": "tortoise"},
            )
            assert status == 200
            d = _settled_delta(
                snap, 'serve_slow_requests_total{endpoint="/v1/scan"}'
            )
            assert d.get('serve_slow_requests_total{endpoint="/v1/scan"}', 0) >= 1
            s, doc = _settled_record(server, "tortoise")
            assert doc["trace_kind"] == "slow" and doc["has_trace"]

    def test_unsampled_fast_request_has_no_trace(self, corpus):
        with ScanServer(
            ServeConfig(
                port=0, root=str(corpus), cache_mb=32, trace_sample_rate=0.0
            )
        ) as server:
            server.start_background()
            status, _h, _b = _scan(
                server,
                {"paths": "a.parquet", "columns": ["id"]},
                headers={"X-Request-Id": "quick"},
            )
            assert status == 200
            s, _h, b = _request(server, "GET", "/v1/debug/requests/quick")
            assert s == 200 and json.loads(b)["has_trace"] is False
            s, _h, b = _request(
                server, "GET", "/v1/debug/requests/quick/trace"
            )
            assert s == 404 and _error_code(b) == "no_trace"

    def test_unknown_id_and_bad_limit_are_typed(self, server):
        s, _h, b = _request(server, "GET", "/v1/debug/requests/never-seen")
        assert s == 404 and _error_code(b) == "no_such_request"
        s, _h, b = _request(server, "GET", "/v1/debug/requests?limit=banana")
        assert s == 400 and _error_code(b) == "bad_request"
        s, _h, b = _request(server, "GET", "/v1/debug/requests?limit=0")
        assert s == 400 and _error_code(b) == "bad_request"
        s, _h, b = _request(server, "GET", "/v1/debug/requests/a/b/c")
        assert s == 404 and _error_code(b) == "no_such_route"

    def test_plan_requests_are_recorded_per_endpoint(self, sampled_server):
        server = sampled_server
        snap = metrics.snapshot()
        s, h, _b = _request(
            server, "POST", "/v1/plan", {"paths": "a.parquet"},
            {"X-Request-Id": "dry-run"},
        )
        assert s == 200 and h.get("X-Request-Id") == "dry-run"
        s, doc = _settled_record(server, "dry-run")
        assert doc["endpoint"] == "/v1/plan" and doc["plan"]["files"] == 1
        d = _settled_delta(
            snap, 'serve_request_seconds_count{endpoint="/v1/plan"}'
        )
        assert d.get('serve_request_seconds_count{endpoint="/v1/plan"}', 0) >= 1

    def test_ring_stays_bounded_under_http_requests(self, corpus):
        with ScanServer(
            ServeConfig(
                port=0, root=str(corpus), cache_mb=32, debug_ring_size=8,
                trace_sample_rate=1.0,
            )
        ) as server:
            server.start_background()
            for i in range(20):
                s, _h, _b = _request(
                    server, "GET", "/v1/plan?paths=a.parquet",
                    headers={"X-Request-Id": f"r{i:02d}"},
                )
                assert s == 200
            # the PROCESS-wide recorder may hold library one-shots from
            # other tests in this run; the REQUEST ring is what this
            # daemon's 20 plans hammer, and both rings share the bound
            stats = server.service.recorder.stats()
            assert stats["requests"] <= 8
            assert stats["library"] <= 8
            assert stats["indexed"] <= stats["records"]
            s, _h, b = _request(
                server, "GET", "/v1/debug/requests?limit=1000&endpoint=/v1/plan"
            )
            reqs = json.loads(b)["requests"]
            assert len(reqs) <= 8
            assert reqs[0]["id"] == "r19"  # newest first
            # evicted ids 404, retained ones resolve
            s, _h, _b = _request(server, "GET", "/v1/debug/requests/r00")
            assert s == 404
            s, _h, _b = _request(server, "GET", "/v1/debug/requests/r19")
            assert s == 200

    def test_eviction_under_hammer_bounds_memory(self):
        """8 writer threads churn a tiny ring (every record slow+traced, the
        worst case for trace retention) while readers list/get — occupancy
        never exceeds the configured bounds."""
        from parquet_tpu.obs.recorder import FlightRecorder, ObsConfig
        from parquet_tpu.utils.trace import decode_trace

        rec = FlightRecorder(
            ObsConfig(ring_size=16, trace_sample_rate=1.0, slow_ms=0.001,
                      max_traces=4)
        )
        stop = threading.Event()
        violations = []

        def writer(k):
            for i in range(200):
                with decode_trace() as tr:
                    pass
                r = rec.begin("/v1/scan", f"t{k}", request_id=f"w{k}-{i}")
                rec.finish(r, 200, nbytes=64, trace=tr, duration_s=0.01)

        def reader():
            while not stop.is_set():
                rec.list(limit=50)
                rec.get("w0-5")
                st = rec.stats()
                if st["records"] > 16 or st["traces"] > 4:
                    violations.append(st)

        threads = [
            threading.Thread(target=writer, args=(k,)) for k in range(8)
        ] + [threading.Thread(target=reader) for _ in range(2)]
        for t in threads:
            t.start()
        for t in threads[:8]:
            t.join(WATCHDOG_S)
        stop.set()
        for t in threads[8:]:
            t.join(WATCHDOG_S)
        assert not any(t.is_alive() for t in threads)
        assert not violations, violations[:3]
        st = rec.stats()
        assert st["records"] <= 16 and st["indexed"] <= 16
        assert st["traces"] <= 4
        # every retained record is one of the newest; the ring dropped
        # ~1584 records without the index leaking any of them
        assert len(rec.list(limit=100)) <= 16


class TestDebugCli:
    """`parquet-tool debug <url>` — the operator client for the daemon's
    flight recorder (list / one record / Perfetto trace export)."""

    @pytest.fixture()
    def live(self, corpus):
        with ScanServer(
            ServeConfig(
                port=0, root=str(corpus), cache_mb=32, trace_sample_rate=1.0
            )
        ) as s:
            s.start_background()
            _scan(
                s, {"paths": "a.parquet", "columns": ["id"]},
                headers={"X-Request-Id": "cli-demo"},
            )
            yield s, f"http://{s.host}:{s.port}"

    def test_list_table(self, live, capsys):
        from parquet_tpu.tools.parquet_tool import main as tool_main

        _server, url = live
        assert tool_main(["debug", url]) == 0
        out = capsys.readouterr().out
        assert "ID" in out and "ENDPOINT" in out and "WAIT_MS" in out
        assert "cli-demo" in out and "/v1/scan" in out and "sampled" in out

    def test_one_record_json(self, live, capsys):
        from parquet_tpu.tools.parquet_tool import main as tool_main

        _server, url = live
        assert tool_main(["debug", url, "--id", "cli-demo"]) == 0
        doc = json.loads(capsys.readouterr().out)
        assert doc["id"] == "cli-demo" and doc["status"] == 200
        assert doc["plan"]["files"] == 1 and doc["stages"]

    def test_trace_export_is_perfetto_loadable(self, live, tmp_path, capsys):
        from parquet_tpu.tools.parquet_tool import main as tool_main

        _server, url = live
        out_path = tmp_path / "trace.json"
        assert tool_main(
            ["debug", url, "--id", "cli-demo", "--trace", "-o", str(out_path)]
        ) == 0
        assert "trace events" in capsys.readouterr().out
        doc = json.loads(out_path.read_text())
        assert doc["traceEvents"]
        assert doc["otherData"]["request"]["id"] == "cli-demo"

    def test_slow_filter_and_scheme_default(self, live, capsys):
        from parquet_tpu.tools.parquet_tool import main as tool_main

        server, _url = live
        # bare host:port grows the http:// scheme; nothing is slow yet
        assert tool_main(
            ["debug", f"{server.host}:{server.port}", "--slow"]
        ) == 0
        assert "no recorded requests" in capsys.readouterr().out

    def test_unknown_id_is_typed_failure(self, live, capsys):
        from parquet_tpu.tools.parquet_tool import main as tool_main

        _server, url = live
        assert tool_main(["debug", url, "--id", "nope"]) == 1
        assert "no_such_request" in capsys.readouterr().err

    def test_trace_without_id_rejected(self, live, capsys):
        from parquet_tpu.tools.parquet_tool import main as tool_main

        _server, url = live
        assert tool_main(["debug", url, "--trace"]) == 1
        assert "--trace requires --id" in capsys.readouterr().err


class TestTraceEviction:
    def test_evicted_trace_404_names_the_right_knob(self, corpus):
        """A record that QUALIFIED for a trace but lost it to max_traces
        pressure must say so — not claim it was never sampled."""
        with ScanServer(
            ServeConfig(
                port=0, root=str(corpus), cache_mb=32, trace_sample_rate=0.0
            )
        ) as server:
            server.start_background()
            # every error keeps a trace; default max_traces=16, so the
            # 17th evicts the first record's tree (the record stays)
            for i in range(17):
                _scan(
                    server, {"paths": "missing.parquet"},
                    headers={"X-Request-Id": f"e{i:02d}"},
                )
            # settle the LAST qualifier first: its finish step (which
            # attaches the tree and evicts e00's) runs post-flush
            _settled_record(server, "e16")
            s, doc = _settled_record(server, "e00")
            assert s == 200
            assert doc["trace_kind"] == "error" and doc["has_trace"] is False
            s, _h, b = _request(server, "GET", "/v1/debug/requests/e00/trace")
            assert s == 404 and _error_code(b) == "trace_evicted"
            assert "--debug-max-traces" in json.loads(b)["error"]["message"]
            # the newest qualifier still serves its tree
            s, _h, _b = _request(
                server, "GET", "/v1/debug/requests/e16/trace"
            )
            assert s == 200


class TestObsKnobOwnership:
    def test_serve_defaults_mirror_obsconfig(self):
        """ObsConfig owns the observability numbers; ServeConfig must not
        restate them (restated copies drift silently)."""
        from parquet_tpu.obs.recorder import ObsConfig

        cfg, obs = ServeConfig(), ObsConfig()
        assert cfg.trace_sample_rate == obs.trace_sample_rate
        assert cfg.slow_ms == obs.slow_ms
        assert cfg.debug_ring_size == obs.ring_size
        assert cfg.debug_max_traces == obs.max_traces

    def test_admission_rejections_rate_limit_per_code(self):
        """A queue_full flood must not absorb the log line for a DIFFERENT
        rejection code — the limiter keys on admission_rejected:<code>."""
        adm = AdmissionController(max_inflight=1)
        before = metrics.get(
            "log_events_total", event="admission_rejected:queue_full"
        ) + metrics.get(
            "log_suppressed_total", event="admission_rejected:queue_full"
        )
        with adm.admit("t0"), pytest.raises(ServeError, match="max in-flight"):
            adm.admit("t")
        after = metrics.get(
            "log_events_total", event="admission_rejected:queue_full"
        ) + metrics.get(
            "log_suppressed_total", event="admission_rejected:queue_full"
        )
        assert after == before + 1  # keyed per code, counted either way

"""Bit-packed level storage (PackedLevels): unit + end-to-end.

The reference stores R/D levels bit-packed at width bits(max_level)
(reference: packed_array.go:13-101) for ~1/8 the memory of widened arrays.
FileReader(compact_levels=True) restores that footprint here; these tests pin
the packing roundtrip, windowed widening, the ndarray-operator interop the
consumers rely on, and end-to-end equality of rows/batches against the
widened default.
"""

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from parquet_tpu import FileReader
from parquet_tpu.ops.packed_levels import PackedLevels, widen_levels


class TestPackedLevelsUnit:
    @pytest.mark.parametrize("max_level", [1, 2, 3, 5, 7, 8, 100, 4000, 65535])
    def test_roundtrip_random(self, max_level):
        rng = np.random.default_rng(max_level)
        arr = rng.integers(0, max_level + 1, size=1_337, dtype=np.uint16)
        p = PackedLevels.from_array(arr, max_level)
        np.testing.assert_array_equal(p.widen(), arr)
        assert len(p) == len(arr)
        # footprint: ceil(n*width/8) bytes, not 2 bytes per level
        width = int(max_level).bit_length()
        assert p.nbytes == (len(arr) * width + 7) // 8  # 16/width x smaller

    def test_width_zero_and_empty(self):
        p = PackedLevels.from_array(np.zeros(5, dtype=np.uint16), 0)
        np.testing.assert_array_equal(p.widen(), np.zeros(5, dtype=np.uint16))
        assert p.nbytes == 0
        e = PackedLevels.from_array(np.empty(0, dtype=np.uint16), 3)
        assert len(e) == 0 and e.widen().shape == (0,)

    def test_value_exceeding_max_level_rejected(self):
        with pytest.raises(ValueError):
            PackedLevels.from_array(np.array([0, 4], dtype=np.uint16), 3)
        # width-0 must not silently zero nonzero levels (review regression)
        with pytest.raises(ValueError):
            PackedLevels.from_array(np.array([2, 3], dtype=np.uint16), 0)
        # level 3 fits width 2 but exceeds max_level 2
        with pytest.raises(ValueError):
            PackedLevels.from_array(np.array([3], dtype=np.uint16), 2)

    def test_negative_step_slicing(self):
        arr = np.array([0, 1, 2, 3, 3, 0, 1], dtype=np.uint16)
        p = PackedLevels.from_array(arr, 3)
        np.testing.assert_array_equal(p[::-1], arr[::-1])
        np.testing.assert_array_equal(p[5:1:-2], arr[5:1:-2])
        np.testing.assert_array_equal(p[-1::-3], arr[-1::-3])

    @pytest.mark.parametrize("width_max", [1, 3, 7])
    def test_windowed_widen_unaligned(self, width_max):
        # windows starting mid-byte must unpack correctly (bit offset != 0)
        rng = np.random.default_rng(42)
        arr = rng.integers(0, width_max + 1, size=509, dtype=np.uint16)
        p = PackedLevels.from_array(arr, width_max)
        for s, e in [(0, 509), (1, 8), (3, 200), (77, 78), (500, 509), (9, 9)]:
            np.testing.assert_array_equal(p.widen(s, e), arr[s:e])
        # clamping
        np.testing.assert_array_equal(p.widen(400, 10_000), arr[400:])

    def test_ndarray_interop(self):
        arr = np.array([0, 1, 2, 3, 3, 0, 1], dtype=np.uint16)
        p = PackedLevels.from_array(arr, 3)
        np.testing.assert_array_equal(np.asarray(p), arr)
        np.testing.assert_array_equal(p == 3, arr == 3)
        np.testing.assert_array_equal(p < 2, arr < 2)
        np.testing.assert_array_equal(p >= 1, arr >= 1)
        assert int(p.max()) == 3
        assert p.tolist() == arr.tolist()
        assert p[2] == 2 and p[-1] == 1
        np.testing.assert_array_equal(p[1:5], arr[1:5])
        np.testing.assert_array_equal(p[::2], arr[::2])
        np.testing.assert_array_equal(p[np.array([0, 4, 6])], arr[[0, 4, 6]])
        assert p.shape == (7,) and p.dtype == np.uint16
        with pytest.raises(IndexError):
            p[7]
        assert widen_levels(None) is None
        assert widen_levels(arr) is arr
        assert isinstance(widen_levels(p), np.ndarray)


def _nested_nullable_table(n=3_000):
    rng = np.random.default_rng(7)
    ints = [None if i % 7 == 0 else int(rng.integers(0, 1 << 30)) for i in range(n)]
    lists = [
        None
        if i % 11 == 0
        else [int(x) for x in rng.integers(0, 100, size=i % 4)]
        for i in range(n)
    ]
    return pa.table(
        {
            "a": pa.array(ints, pa.int64()),
            "tags": pa.array(lists, pa.list_(pa.int32())),
        }
    )


class TestCompactLevelsEndToEnd:
    def test_chunkdata_levels_are_packed_and_rows_match(self, tmp_path):
        path = str(tmp_path / "nested.parquet")
        pq.write_table(_nested_nullable_table(), path, row_group_size=1_024)
        with FileReader(path) as plain, FileReader(
            path, compact_levels=True
        ) as compact:
            cd_plain = plain.read_row_group(0)
            cd_comp = compact.read_row_group(0)
            for p, cd in cd_comp.items():
                assert isinstance(cd.def_levels, PackedLevels)
                np.testing.assert_array_equal(
                    np.asarray(cd.def_levels), cd_plain[p].def_levels
                )
                if cd.rep_levels is not None:
                    assert isinstance(cd.rep_levels, PackedLevels)
                    np.testing.assert_array_equal(
                        np.asarray(cd.rep_levels), cd_plain[p].rep_levels
                    )
                # at-rest footprint: widths here are 1-2 bits, so the packed
                # form sits >= 8x below the uint16 arrays (ceiling slack)
                assert cd.def_levels.nbytes * 4 <= cd_plain[p].def_levels.nbytes
            assert list(plain.iter_rows()) == list(compact.iter_rows())

    def test_roundtrip_backend_and_filters(self, tmp_path):
        path = str(tmp_path / "nested2.parquet")
        pq.write_table(_nested_nullable_table(1_000), path, row_group_size=256)
        with FileReader(path, backend="tpu_roundtrip", compact_levels=True) as r:
            cd = r.read_row_group(0)
            assert isinstance(cd[("a",)].def_levels, PackedLevels)
            rows = list(r.iter_rows(filters=[("a", ">", 1 << 29)]))
        with FileReader(path) as r:
            expect = list(r.iter_rows(filters=[("a", ">", 1 << 29)]))
        assert rows == expect

    def test_device_batches_masked_with_compact_levels(self, tmp_path):
        from parquet_tpu import MaskedColumn

        n = 4_096
        vals = [None if i % 5 == 0 else i for i in range(n)]
        t = pa.table({"x": pa.array(vals, pa.int64())})
        path = str(tmp_path / "nullable.parquet")
        pq.write_table(t, path, row_group_size=2_048, use_dictionary=False)
        with FileReader(path, compact_levels=True) as r:
            b = next(r.iter_device_batches(2_048, nullable="mask"))
            col = b[("x",)]
            assert isinstance(col, MaskedColumn)
            got = np.asarray(col.values)
            mask = np.asarray(col.mask)
        ref = np.array([0 if v is None else v for v in vals[:2_048]])
        np.testing.assert_array_equal(got, ref)
        np.testing.assert_array_equal(mask, [v is not None for v in vals[:2_048]])

    def test_device_column_levels_packed(self, tmp_path):
        t = pa.table({"x": pa.array([1, None, 3, 4, None, 6], pa.int64())})
        path = str(tmp_path / "dev.parquet")
        pq.write_table(t, path, use_dictionary=False)
        with FileReader(path, compact_levels=True) as r:
            dc = r.read_row_group_device(0)[("x",)]
            assert isinstance(dc.def_levels, PackedLevels)
            np.testing.assert_array_equal(
                np.asarray(dc.def_levels), [1, 0, 1, 1, 0, 1]
            )

"""PR 19: parquet_tpu.serve.mesh — multi-host sharded serve.

Pinned here:
  * byte-identity: a routed /v1/scan (jsonl AND arrow-ipc, with columns,
    filters, limits) and a routed /v1/query (aggregates, group_by) over a
    3-replica mesh answer byte-for-byte what ONE daemon over the same
    corpus answers — the scatter/merge is exact, floats included, because
    the router replays the daemon's own per-unit merge sequence;
  * resilience: a replica killed mid-hammer, a draining replica, and a
    breaker-opened replica cost typed retries only — every client-visible
    response is byte-identical or a typed error record, never a torn
    stream, never a splice;
  * chaos: a FlakyReplica proxy injecting seeded 503s, connection resets,
    and TORN payloads between router and replica changes nothing the
    client can see;
  * consistent hashing: the ring is deterministic across instances and
    its preference walk visits every node exactly once;
  * satellites: --shard validation is typed at config time and visible in
    /v1/debug/vars; /healthz while draining carries the remaining
    in-flight count and a Retry-After hint; every mesh_* metric family
    renders with HELP + TYPE; /v1/debug/mesh answers the fleet's state.
"""

from __future__ import annotations

import http.client
import json
import threading
import time

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from parquet_tpu.serve import ScanServer, ServeConfig
from parquet_tpu.serve.mesh import (
    HashRing,
    MeshConfig,
    MeshRouter,
    ReplicaTable,
)
from parquet_tpu.testing.flaky_replica import FlakyReplica

WATCHDOG_S = 30.0

ROWS_PER_FILE = 800
ROW_GROUP = 200
FILES = ("a.parquet", "b.parquet", "c.parquet")
GROUPS = ("alpha", "beta", "gamma", "delta", "epsilon")


@pytest.fixture(scope="module")
def corpus(tmp_path_factory):
    d = tmp_path_factory.mktemp("mesh_router_corpus")
    rng = np.random.default_rng(19)
    for f, name in enumerate(FILES):
        base = f * ROWS_PER_FILE
        t = pa.table(
            {
                "id": pa.array(
                    np.arange(base, base + ROWS_PER_FILE, dtype=np.int64)
                ),
                "v": pa.array(
                    rng.standard_normal(ROWS_PER_FILE).astype(np.float64)
                ),
                "g": pa.array(
                    [GROUPS[i % len(GROUPS)] for i in range(ROWS_PER_FILE)]
                ),
            }
        )
        pq.write_table(t, str(d / name), row_group_size=ROW_GROUP)
    return d


@pytest.fixture(scope="module")
def fleet(corpus):
    """One reference daemon, three replicas, one router — all over the
    same corpus. The router is constructed LAST so its obs config owns
    the process-wide recorder."""
    direct = ScanServer(
        ServeConfig(port=0, root=str(corpus))
    ).start_background()
    replicas = [
        ScanServer(ServeConfig(port=0, root=str(corpus))).start_background()
        for _ in range(3)
    ]
    router = MeshRouter(
        MeshConfig(
            port=0,
            replicas=tuple(r.url for r in replicas),
            trace_sample_rate=1.0,
        )
    ).start_background()
    try:
        yield direct, replicas, router
    finally:
        router.close()
        for s in replicas + [direct]:
            s.close()


def _request(server, method, path, body=None, headers=None):
    conn = http.client.HTTPConnection(
        server.host, server.port, timeout=WATCHDOG_S
    )
    try:
        conn.request(
            method,
            path,
            body=json.dumps(body).encode() if body is not None else None,
            headers=headers or {},
        )
        resp = conn.getresponse()
        return resp.status, dict(resp.getheaders()), resp.read()
    finally:
        conn.close()


def _error_code(body: bytes) -> str:
    return json.loads(body)["error"]["code"]


def _differential(direct, router, method, path, body=None):
    """One request to the single daemon and to the router: both 200, both
    byte-identical. Returns the (shared) payload."""
    s1, _h1, b1 = _request(direct, method, path, body)
    s2, _h2, b2 = _request(router, method, path, body)
    assert s1 == 200, b1
    assert s2 == 200, b2
    assert b1 == b2, (len(b1), len(b2))
    return b1


# -- consistent hashing --------------------------------------------------------


class TestHashRing:
    NODES = ("http://h0:1", "http://h1:1", "http://h2:1", "http://h3:1")

    def test_lookup_deterministic_across_instances(self):
        a = HashRing(self.NODES, vnodes=64)
        b = HashRing(self.NODES, vnodes=64)
        for k in range(200):
            assert a.lookup(f"sig#{k}") == b.lookup(f"sig#{k}")

    def test_preference_visits_every_node_once(self):
        ring = HashRing(self.NODES, vnodes=64)
        for k in range(50):
            pref = ring.preference(f"unit#{k}")
            assert sorted(pref) == sorted(self.NODES)
            assert pref[0] == ring.lookup(f"unit#{k}")

    def test_keys_spread_over_all_nodes(self):
        ring = HashRing(self.NODES, vnodes=64)
        owners = {ring.lookup(f"plan#{k}") for k in range(500)}
        assert owners == set(self.NODES)

    def test_empty_and_bad_vnodes_are_typed(self):
        with pytest.raises(ValueError):
            HashRing((), vnodes=64)
        with pytest.raises(ValueError):
            HashRing(self.NODES, vnodes=0)

    def test_table_rejects_empty_and_bad_urls(self):
        with pytest.raises(ValueError):
            ReplicaTable(())
        with pytest.raises(ValueError):
            ReplicaTable(("ftp://nope:1",))
        with pytest.raises(ValueError):
            ReplicaTable(("http://host:1/path",))


# -- satellite: --shard validation at startup ----------------------------------


class TestShardValidation:
    def test_out_of_range_index_is_typed(self):
        with pytest.raises(ValueError, match="out of range"):
            ServeConfig(port=0, shard=(5, 2))

    def test_zero_count_is_typed(self):
        with pytest.raises(ValueError, match="out of range"):
            ServeConfig(port=0, shard=(0, 0))

    def test_negative_index_is_typed(self):
        with pytest.raises(ValueError, match="out of range"):
            ServeConfig(port=0, shard=(-1, 2))

    def test_malformed_shard_is_typed(self):
        with pytest.raises(ValueError, match="shard"):
            ServeConfig(port=0, shard=("x", "y"))

    def test_valid_shard_normalizes_and_rides_debug_vars(self, corpus):
        cfg = ServeConfig(port=0, root=str(corpus), shard=("1", "3"))
        assert cfg.shard == (1, 3)
        with ScanServer(cfg) as server:
            server.start_background()
            status, _h, body = _request(server, "GET", "/v1/debug/vars")
            assert status == 200
            assert json.loads(body)["serve"]["shard"] == [1, 3]


# -- satellite: /healthz while draining ----------------------------------------


class TestHealthzDraining:
    def test_draining_healthz_reports_inflight_and_retry_after(self, corpus):
        with ScanServer(ServeConfig(port=0, root=str(corpus))) as server:
            server.start_background()
            ticket = server.service.admission.admit("held")
            t = threading.Thread(
                target=server.drain, kwargs={"timeout": WATCHDOG_S}
            )
            t.start()
            try:
                deadline = time.monotonic() + WATCHDOG_S
                while not server.service.admission.draining:
                    assert time.monotonic() < deadline, "drain never started"
                    time.sleep(0.005)
                status, headers, body = _request(server, "GET", "/healthz")
                assert status == 503
                doc = json.loads(body)
                assert doc["status"] == "draining"
                assert doc["in_flight"] == 1
                assert doc["retry_after_s"] == 2  # min(30, 1 + in_flight)
                assert headers["Retry-After"] == "2"
            finally:
                ticket.release()
                t.join(WATCHDOG_S)
            assert not t.is_alive()


# -- byte-identity: routed == direct -------------------------------------------


class TestRoutedByteIdentity:
    def test_mesh_smoke_routed_equals_direct(self, fleet):
        """The make mesh-smoke headline: scan (both formats) and query
        through the router answer byte-for-byte what one daemon answers."""
        direct, _replicas, router = fleet
        _differential(direct, router, "POST", "/v1/scan",
                      {"paths": "*.parquet"})
        _differential(direct, router, "POST", "/v1/scan",
                      {"paths": "*.parquet", "format": "arrow-ipc"})
        _differential(
            direct, router, "POST", "/v1/query",
            {"paths": "*.parquet",
             "aggregates": [["count"], ["sum", "v"]]},
        )

    def test_scan_jsonl_columns_filters_byte_identical(self, fleet):
        direct, _replicas, router = fleet
        body = _differential(
            direct, router, "POST", "/v1/scan",
            {"paths": "*.parquet", "columns": ["id", "g"],
             "filters": [["id", ">=", 700], ["id", "<", 1900]]},
        )
        rows = [json.loads(line) for line in body.splitlines()]
        assert [r["id"] for r in rows] == list(range(700, 1900))

    def test_scan_arrow_filtered_byte_identical(self, fleet):
        direct, _replicas, router = fleet
        body = _differential(
            direct, router, "POST", "/v1/scan",
            {"paths": "*.parquet", "format": "arrow-ipc",
             "filters": [["v", ">", 0.25]]},
        )
        # the merged frame is one VALID IPC stream, not a concatenation
        table = pa.ipc.open_stream(pa.py_buffer(body)).read_all()
        assert table.num_rows > 0
        assert np.all(table.column("v").to_numpy() > 0.25)

    def test_query_group_by_float_sums_byte_identical(self, fleet):
        direct, _replicas, router = fleet
        body = _differential(
            direct, router, "POST", "/v1/query",
            {"paths": "*.parquet", "group_by": ["g"],
             "aggregates": [["count"], ["sum", "v"], ["min", "id"],
                            ["max", "v"]]},
        )
        doc = json.loads(body)
        assert doc["rows_scanned"] == ROWS_PER_FILE * len(FILES)
        assert len(doc["groups"]) == len(GROUPS)

    def test_scan_limit_passthrough_byte_identical(self, fleet):
        direct, _replicas, router = fleet
        body = _differential(
            direct, router, "POST", "/v1/scan",
            {"paths": "*.parquet", "limit": 123},
        )
        assert len(body.splitlines()) == 123

    def test_scan_explicit_shard_passthrough_byte_identical(self, fleet):
        direct, _replicas, router = fleet
        stripes = [
            _differential(direct, router, "POST", "/v1/scan",
                          {"paths": "*.parquet", "shard": [i, 3]})
            for i in range(3)
        ]
        whole = _differential(direct, router, "POST", "/v1/scan",
                              {"paths": "*.parquet"})
        assert sum(len(s.splitlines()) for s in stripes) == len(
            whole.splitlines()
        )

    def test_plan_matches_single_daemon(self, fleet):
        direct, _replicas, router = fleet
        s1, _h, b1 = _request(direct, "POST", "/v1/plan",
                              {"paths": "*.parquet"})
        s2, _h, b2 = _request(router, "POST", "/v1/plan",
                              {"paths": "*.parquet"})
        assert s1 == s2 == 200
        p1, p2 = json.loads(b1), json.loads(b2)
        assert p1["units"] == p2["units"]
        assert p1["files"] == p2["files"]

    def test_typed_errors_forward_through_the_router(self, fleet):
        _direct, _replicas, router = fleet
        status, _h, body = _request(
            router, "POST", "/v1/scan", {"paths": "../escape.parquet"}
        )
        assert status == 403
        assert _error_code(body) == "path_outside_root"
        status, _h, body = _request(
            router, "POST", "/v1/scan", {"paths": "missing.parquet"}
        )
        assert status == 404

    def test_append_is_typed_not_routable(self, fleet):
        """Ingest targets ONE replica's lake table; the router refuses
        /v1/append with a typed 501 instead of hashing rows somewhere."""
        _direct, _replicas, router = fleet
        status, _h, body = _request(
            router,
            "POST",
            "/v1/append",
            {"k": 1},
            headers={"Content-Type": "application/x-ndjson"},
        )
        assert status == 501
        assert _error_code(body) == "not_routable"
        assert "replica" in json.loads(body)["error"]["message"]


# -- resilience: kill / drain / breaker ----------------------------------------


def _mini_fleet(corpus, n=3, **mesh_kw):
    replicas = [
        ScanServer(ServeConfig(port=0, root=str(corpus))).start_background()
        for _ in range(n)
    ]
    router = MeshRouter(
        MeshConfig(
            port=0, replicas=tuple(r.url for r in replicas), **mesh_kw
        )
    ).start_background()
    return replicas, router


class TestMeshResilience:
    def test_replica_killed_mid_hammer_typed_retries_only(self, corpus):
        replicas, router = _mini_fleet(corpus)
        try:
            want = _request(router, "POST", "/v1/scan",
                            {"paths": "*.parquet"})[2]
            results: list = []
            stop = threading.Event()

            def hammer():
                while not stop.is_set():
                    try:
                        st, _h, body = _request(
                            router, "POST", "/v1/scan",
                            {"paths": "*.parquet"},
                        )
                    except http.client.HTTPException as e:
                        results.append(("torn", repr(e)))
                        continue
                    if st == 200:
                        results.append(
                            ("ok", None) if body == want
                            else ("mismatch", len(body))
                        )
                    else:
                        # a typed error body or nothing at all
                        results.append(("typed", _error_code(body)))

            threads = [threading.Thread(target=hammer) for _ in range(4)]
            for t in threads:
                t.start()
            time.sleep(0.4)
            replicas[1].close()  # hard kill, requests in flight
            time.sleep(1.2)
            stop.set()
            for t in threads:
                t.join(WATCHDOG_S)
                assert not t.is_alive(), "hammer thread hung"
            kinds = {k for k, _ in results}
            assert "mismatch" not in kinds, results
            assert "torn" not in kinds, results
            assert ("ok", None) in results
            # the fleet keeps answering byte-identically after the kill
            st, _h, body = _request(router, "POST", "/v1/scan",
                                    {"paths": "*.parquet"})
            assert st == 200 and body == want
        finally:
            router.close()
            for s in replicas:
                s.close()

    def test_replica_draining_mid_scan_and_query_byte_identical(self, corpus):
        replicas, router = _mini_fleet(corpus)
        try:
            want_scan = _request(router, "POST", "/v1/scan",
                                 {"paths": "*.parquet"})[2]
            want_query = _request(
                router, "POST", "/v1/query",
                {"paths": "*.parquet", "group_by": ["g"],
                 "aggregates": [["sum", "v"]]},
            )[2]
            t = threading.Thread(
                target=replicas[0].drain, kwargs={"timeout": WATCHDOG_S}
            )
            t.start()
            deadline = time.monotonic() + WATCHDOG_S
            while not replicas[0].service.admission.draining:
                assert time.monotonic() < deadline
                time.sleep(0.005)
            for _ in range(3):
                st, _h, body = _request(router, "POST", "/v1/scan",
                                        {"paths": "*.parquet"})
                assert st == 200 and body == want_scan
                st, _h, body = _request(
                    router, "POST", "/v1/query",
                    {"paths": "*.parquet", "group_by": ["g"],
                     "aggregates": [["sum", "v"]]},
                )
                assert st == 200 and body == want_query
            # the fleet page knows who is draining
            st, _h, body = _request(router, "GET", "/v1/debug/mesh")
            assert st == 200
            states = [r["state"] for r in json.loads(body)["replicas"]]
            assert states.count("draining") == 1, states
            t.join(WATCHDOG_S)
        finally:
            router.close()
            for s in replicas:
                s.close()

    def test_dead_replica_opens_its_breaker(self, corpus):
        replicas, router = _mini_fleet(
            corpus, breaker_failures=2, breaker_open_s=WATCHDOG_S
        )
        try:
            replicas[2].close()
            dead = router.service.table.by_url[replicas[2].url.rstrip("/")]
            # distinct signatures spread distinct unit keys over the ring,
            # so the dead replica keeps getting (and failing) attempts
            for i in range(12):
                st, _h, _b = _request(
                    router, "POST", "/v1/scan",
                    {"paths": "*.parquet", "filters": [["id", ">=", i]]},
                )
                assert st == 200
                if dead.breaker.state == "open":
                    break
            assert dead.breaker.state == "open"
            assert dead.state() == "open-breaker"
            # an open breaker is a dict lookup, not a connect timeout
            t0 = time.monotonic()
            st, _h, _b = _request(router, "POST", "/v1/scan",
                                  {"paths": "*.parquet"})
            assert st == 200
            assert time.monotonic() - t0 < WATCHDOG_S / 2
        finally:
            router.close()
            for s in replicas[:2]:
                s.close()


# -- chaos: the flaky wire -----------------------------------------------------


class TestFlakyReplicaChaos:
    def test_seeded_wire_faults_change_nothing_visible(self, corpus):
        """503s, connection resets, and torn payloads between router and
        one replica: every routed answer stays byte-identical."""
        backend = ScanServer(
            ServeConfig(port=0, root=str(corpus))
        ).start_background()
        clean = ScanServer(
            ServeConfig(port=0, root=str(corpus))
        ).start_background()
        proxy = FlakyReplica(
            backend.url, seed=23,
            error_rate=0.2, drop_rate=0.15, short_rate=0.15,
        ).start()
        router = MeshRouter(
            MeshConfig(port=0, replicas=(proxy.url, clean.url))
        ).start_background()
        try:
            want_scan = _request(clean, "POST", "/v1/scan",
                                 {"paths": "*.parquet"})[2]
            want_query = _request(
                clean, "POST", "/v1/query",
                {"paths": "*.parquet", "aggregates": [["sum", "v"]]},
            )[2]
            for _ in range(8):
                st, _h, body = _request(router, "POST", "/v1/scan",
                                        {"paths": "*.parquet"})
                assert st == 200 and body == want_scan
                st, _h, body = _request(
                    router, "POST", "/v1/query",
                    {"paths": "*.parquet", "aggregates": [["sum", "v"]]},
                )
                assert st == 200 and body == want_query
            assert proxy.faults_injected > 0  # the chaos actually fired
        finally:
            router.close()
            proxy.close()
            backend.close()
            clean.close()

    def test_torn_replica_payload_is_refetched_never_spliced(self, corpus):
        """A truncated replica answer (declared N, delivered < N) must be
        re-fetched whole from another replica — a splice would show up as
        a byte-level mismatch."""
        backend = ScanServer(
            ServeConfig(port=0, root=str(corpus))
        ).start_background()
        clean = ScanServer(
            ServeConfig(port=0, root=str(corpus))
        ).start_background()
        proxy = FlakyReplica(backend.url, seed=5, short_rate=0.5).start()
        router = MeshRouter(
            MeshConfig(port=0, replicas=(proxy.url, clean.url))
        ).start_background()
        try:
            want = _request(clean, "POST", "/v1/scan",
                            {"paths": "*.parquet"})[2]
            for _ in range(6):
                st, _h, body = _request(router, "POST", "/v1/scan",
                                        {"paths": "*.parquet"})
                assert st == 200 and body == want
            assert proxy.faults_injected > 0
        finally:
            router.close()
            proxy.close()
            backend.close()
            clean.close()


# -- the fleet's debug and metrics surface -------------------------------------


class TestMeshDebugSurface:
    def test_debug_mesh_shape(self, fleet):
        _direct, replicas, router = fleet
        status, _h, body = _request(router, "GET", "/v1/debug/mesh")
        assert status == 200
        doc = json.loads(body)
        assert len(doc["replicas"]) == 3
        for row in doc["replicas"]:
            assert row["state"] in (
                "up", "degraded", "draining", "open-breaker", "down"
            )
            assert row["healthz"]["status"] == "ok"
        assert doc["counts"]["up"] == 3
        assert sorted(doc["ring"]["nodes"]) == sorted(
            r.url.rstrip("/") for r in replicas
        )
        assert doc["scatter"]["enabled"] is True
        assert doc["hedge"]["enabled"] is True

    def test_debug_vars_mesh_mode(self, fleet):
        _direct, replicas, router = fleet
        status, _h, body = _request(router, "GET", "/v1/debug/vars")
        assert status == 200
        doc = json.loads(body)
        assert doc["mode"] == "mesh"
        assert len(doc["replicas"]) == 3
        assert doc["mesh"]["scatter"] is True
        assert doc["mesh"]["vnodes"] == 64

    def test_mesh_metric_families_render_with_help_and_type(self, fleet):
        direct, _replicas, router = fleet
        # drive at least one scattered request so the counters exist
        _differential(direct, router, "POST", "/v1/scan",
                      {"paths": "a.parquet"})
        status, _h, body = _request(router, "GET", "/metrics")
        assert status == 200
        text = body.decode()
        for family in (
            "mesh_requests_total",
            "mesh_backend_requests_total",
            "mesh_scatter_units_total",
            "mesh_replica_state",
        ):
            name = f"parquet_tpu_{family}"
            assert f"# HELP {name} " in text, family
            assert f"# TYPE {name} " in text, family
        # state is a GAUGE keyed per replica, never summed
        assert "# TYPE parquet_tpu_mesh_replica_state gauge" in text

    def test_healthz_reports_replica_counts(self, fleet):
        _direct, _replicas, router = fleet
        status, _h, body = _request(router, "GET", "/healthz")
        assert status == 200
        doc = json.loads(body)
        assert doc["status"] in ("ok", "degraded")
        assert doc["replicas"]["up"] + doc["replicas"]["degraded"] >= 1

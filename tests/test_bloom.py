"""Split-block bloom filters — beyond-reference coverage.

The reference has no bloom support. These tests pin the XXH64 implementation
to public test vectors (and C/Python parity), the split-block insert/check
semantics, the wire form, cross-validation against pyarrow's
bloom_filter_options output (read side) and pyarrow readback of our files
(write side), and the equality-predicate pruning integration.
"""

import io

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from parquet_tpu import FileReader, FileWriter, parse_schema
from parquet_tpu.core.bloom import BloomFilter, bloom_hash_values, xxh64
from parquet_tpu.meta.parquet_types import Type
from parquet_tpu.utils.native import get_native

rng = np.random.default_rng(123)


class TestXxh64:
    # public xxHash test vectors, seed 0
    VECTORS = {
        b"": 0xEF46DB3751D8E999,
        b"a": 0xD24EC4F1A98C6E5B,
        b"abc": 0x44BC2CF5AD770999,
    }

    def test_vectors(self):
        for data, want in self.VECTORS.items():
            assert xxh64(data) == want

    def test_native_parity(self):
        lib = get_native()
        if lib is None or not lib.has_xxh64:
            pytest.skip("native lib not built")
        for data, want in self.VECTORS.items():
            assert lib.xxh64(data) == want
        for n in (3, 4, 7, 8, 9, 31, 32, 33, 63, 64, 65, 1024, 4097):
            data = rng.integers(0, 256, n, dtype=np.uint8).tobytes()
            assert lib.xxh64(data) == xxh64(data), n
        # batch paths agree with scalar
        vals = rng.integers(0, 1 << 60, 100).astype(np.int64)
        batch = lib.xxh64_fixed(vals, len(vals), 8)
        raw = vals.tobytes()
        assert [xxh64(raw[i * 8 : i * 8 + 8]) for i in range(100)] == batch.tolist()


class TestBloomCore:
    def test_no_false_negatives_and_fpp(self):
        vals = rng.integers(0, 1 << 50, 20_000).astype(np.int64)
        bf = BloomFilter.sized_for(len(vals), 0.01)
        bf.insert_hashes(bloom_hash_values(Type.INT64, vals))
        assert all(
            bf.might_contain(Type.INT64, int(v)) for v in vals[:: len(vals) // 500]
        )
        probes = rng.integers(1 << 51, 1 << 52, 3000)
        fp = sum(bf.might_contain(Type.INT64, int(v)) for v in probes)
        assert fp / len(probes) < 0.05  # target 0.01, wide margin

    def test_wire_roundtrip_and_validation(self):
        bf = BloomFilter.sized_for(100, 0.01)
        bf.insert_hashes(bloom_hash_values(Type.INT64, np.arange(100, dtype=np.int64)))
        back = BloomFilter.from_buffer(bf.to_bytes())
        assert np.array_equal(back.blocks, bf.blocks)
        with pytest.raises(ValueError):
            BloomFilter.from_buffer(bf.to_bytes()[:10])
        assert bf.num_bytes % 32 == 0 and bf.num_bytes >= 32

    def test_sizing_monotonic(self):
        small = BloomFilter.sized_for(100, 0.01).num_bytes
        big = BloomFilter.sized_for(1_000_000, 0.01).num_bytes
        tight = BloomFilter.sized_for(100, 0.5).num_bytes
        assert small < big and tight <= small

    def test_sizing_never_undershoots_optimal_bits(self):
        """The power-of-two round-up must start from ceil(optimal bytes):
        truncating first can yield a filter SMALLER than the formula asks
        for (e.g. optimal = 2^k + 0.4 bytes), quietly worsening the fpp."""
        import math

        for ndv in range(1, 4_000, 7):
            for fpp in (0.5, 0.1, 0.05, 0.01):
                bits = -8.0 * ndv / math.log(1.0 - fpp ** (1.0 / 8.0))
                nbytes = BloomFilter.sized_for(ndv, fpp).num_bytes
                assert nbytes >= min(
                    max(math.ceil(bits / 8.0), BloomFilter.MIN_BYTES),
                    BloomFilter.MAX_BYTES,
                ), (ndv, fpp, bits / 8.0, nbytes)


class TestPyarrowInterop:
    def test_read_pyarrow_blooms(self, tmp_path):
        n = 40_000
        ids = rng.integers(0, 1 << 40, n)
        path = str(tmp_path / "pa_bloom.parquet")
        pq.write_table(
            pa.table({"id": pa.array(ids), "s": pa.array([f"u{i}" for i in range(n)])}),
            path,
            row_group_size=20_000,
            use_dictionary=False,
            bloom_filter_options={"id": {"ndv": 20_000, "fpp": 0.01}, "s": True},
        )
        with FileReader(path) as r:
            for g in range(2):
                bf = r.read_bloom_filter(g, "id")
                assert bf is not None
                seg = ids[g * 20_000 : (g + 1) * 20_000]
                assert all(
                    bf.might_contain(Type.INT64, int(v)) for v in seg[::500]
                ), "false negative against pyarrow-written bloom"
            bs = r.read_bloom_filter(0, "s")
            assert bs.might_contain(Type.BYTE_ARRAY, "u17")
            # group pruning: equality on a value no bloom admits
            assert r.prune_row_groups([("id", "==", (1 << 45) + 3)]) == []
            hit = int(ids[25_000])
            assert 1 in r.prune_row_groups([("id", "==", hit)])

    def test_pyarrow_reads_our_bloom_files(self, tmp_path):
        schema = parse_schema(
            "message m { required int64 id; required binary s (UTF8); "
            "required double x; }"
        )
        n = 10_000
        path = str(tmp_path / "ours_bloom.parquet")
        with FileWriter(
            path, schema, codec="zstd", bloom_filters=True, use_dictionary=False
        ) as w:
            w.write_column("id", np.arange(n, dtype=np.int64))
            w.write_column("s", [f"v{i}" for i in range(n)])
            w.write_column("x", np.linspace(0, 1, n))
        t = pq.read_table(path)
        assert t.column("id").to_pylist() == list(range(n))
        md = pq.ParquetFile(path).metadata
        assert md.num_rows == n  # bloom blobs don't disturb the layout


class TestFilterIntegration:
    def test_equality_pruning_dictionary_and_plain(self, tmp_path):
        schema = parse_schema("message m { required binary s (UTF8); }")
        # dictionary chunk: bloom built over the dictionary values
        path = str(tmp_path / "dict_bloom.parquet")
        vals = [f"city_{i % 300}" for i in range(30_000)]
        with FileWriter(path, schema, bloom_filters=["s"]) as w:
            w.write_column("s", vals)
        with FileReader(path) as r:
            assert len(list(r.iter_rows(filters=[("s", "==", "city_7")]))) == 100
            assert list(r.iter_rows(filters=[("s", "==", "nocity")])) == []
            assert r.prune_row_groups([("s", "==", "nocity")]) == []

    def test_multi_group_selective(self, tmp_path):
        schema = parse_schema("message m { required int64 id; }")
        path = str(tmp_path / "multi.parquet")
        with FileWriter(
            path, schema, row_group_size=80_000, bloom_filters=True,
            use_dictionary=False,
        ) as w:
            for base in range(0, 40_000, 10_000):
                w.write_column(
                    "id", np.arange(base, base + 10_000, dtype=np.int64) * 1_000_003
                )
                w.flush_row_group()
        with FileReader(path) as r:
            assert r.num_row_groups == 4
            target = 25_123 * 1_000_003
            kept = r.prune_row_groups([("id", "==", target)])
            assert kept == [2]  # min/max overlap can't prove it; bloom can't either way here
            rows = list(r.iter_rows(filters=[("id", "==", target)]))
            assert [row["id"] for row in rows] == [target]
            # a value inside every group's [min, max] but present nowhere:
            # only the bloom can prune it
            ghost = 17 * 1_000_003 + 1
            assert r.prune_row_groups([("id", "==", ghost)]) == []

    def test_unsupported_types_rejected(self):
        schema = parse_schema("message m { required boolean b; }")
        with pytest.raises(ValueError, match="bloom"):
            FileWriter(io.BytesIO(), schema, bloom_filters=["b"])


class TestSignedZero:
    def test_negative_zero_not_pruned(self, tmp_path):
        """-0.0 == 0.0 but their bit patterns differ; both bloom sides
        normalize so the equality filter keeps the group (review
        regression: silent data loss)."""
        schema = parse_schema("message m { required double x; }")
        path = str(tmp_path / "zero.parquet")
        with FileWriter(
            path, schema, bloom_filters=["x"], use_dictionary=False
        ) as w:
            w.write_column("x", np.array([-0.0, 1.0, 2.0]))
        with FileReader(path) as r:
            rows = list(r.iter_rows(filters=[("x", "==", 0.0)]))
            assert len(rows) == 1  # the -0.0 row matches 0.0
            rows2 = list(r.iter_rows(filters=[("x", "==", -0.0)]))
            assert len(rows2) == 1

    def test_string_option_means_one_column(self):
        schema = parse_schema("message m { required int64 id; }")
        w = FileWriter(io.BytesIO(), schema, bloom_filters="id")
        assert list(w._bloom_specs) == [("id",)]

    def test_foreign_negative_zero_bloom(self, tmp_path):
        """pyarrow inserts raw -0.0 bit patterns into its blooms; our probe
        for == 0.0 must admit the group (review regression: one-sided
        normalization pruned it)."""
        path = str(tmp_path / "pa_zero.parquet")
        pq.write_table(
            pa.table({"x": pa.array([-0.0, 1.0])}),
            path,
            use_dictionary=False,
            bloom_filter_options={"x": True},
        )
        with FileReader(path) as r:
            rows = list(r.iter_rows(filters=[("x", "==", 0.0)]))
            assert len(rows) == 1
            assert r.prune_row_groups([("x", "==", 0.0)]) == [0]

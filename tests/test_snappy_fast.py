"""Native snappy decoder: the overshooting fast mode pinned by the suite.

The decoder switches on an out-of-band contract — >= 64 bytes of physical
destination slack past the stream's claimed uncompressed size buys
overshooting 8/16-byte copies and a one-load tag dispatch. These tests
drive ptq_snappy_decompress directly through ctypes at the slack boundary
(cap == expect+63 stays careful, +64 goes fast), over handcrafted streams
(short-period overlapping copies, 4-byte-offset tags, truncated tails) and
a fuzz sweep, asserting fast and careful modes agree byte-for-byte and
that no write ever lands beyond the permitted slack.
"""

import ctypes

import numpy as np
import pytest

from parquet_tpu.utils.native import get_native


@pytest.fixture(scope="module")
def lib():
    lib = get_native()
    if lib is None or not lib.has_snappy:
        pytest.skip("native snappy not built")
    return lib


GUARD = 0xAB  # canary byte pattern past the permitted region


def decompress(lib, comp: bytes, cap: int):
    """(rc, payload, canary_ok): decode `comp` into a buffer of physical
    size cap + 64 guard bytes; canary_ok = nothing wrote past cap + 15
    (the documented worst-case overshoot is 15 bytes past a copy's end,
    which itself is bounded by expect <= cap - 64 in fast mode; writes
    into [cap, cap+64) would mean the slack contract is violated)."""
    src = np.frombuffer(comp, dtype=np.uint8)
    out = np.full(cap + 64, GUARD, dtype=np.uint8)
    rc = lib._lib.ptq_snappy_decompress(
        ctypes.c_void_p(src.ctypes.data), len(src),
        ctypes.c_void_p(out.ctypes.data), cap,
    )
    canary_ok = bool((out[cap:] == GUARD).all())
    return rc, bytes(out[: max(rc, 0)]), canary_ok


def varint(n: int) -> bytes:
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        out.append(b | (0x80 if n else 0))
        if not n:
            return bytes(out)


def lit(data: bytes) -> bytes:
    n = len(data) - 1
    if n < 60:
        return bytes([n << 2]) + data
    enc = n.to_bytes(4, "little").rstrip(b"\x00") or b"\x00"
    return bytes([(59 + len(enc)) << 2]) + enc + data


def copy1(offset: int, length: int) -> bytes:
    assert 4 <= length <= 11 and offset < 2048
    return bytes([((offset >> 8) << 5) | ((length - 4) << 2) | 1, offset & 0xFF])


def copy2(offset: int, length: int) -> bytes:
    assert 1 <= length <= 64 and offset < 65536
    return bytes([((length - 1) << 2) | 2]) + offset.to_bytes(2, "little")


def copy4(offset: int, length: int) -> bytes:
    assert 1 <= length <= 64
    return bytes([((length - 1) << 2) | 3]) + offset.to_bytes(4, "little")


def ref_decode(stream: bytes):
    """Tiny reference decoder (spec semantics, byte-at-a-time)."""
    pos, expect, shift = 0, 0, 0
    while True:
        b = stream[pos]
        pos += 1
        expect |= (b & 0x7F) << shift
        if not b & 0x80:
            break
        shift += 7
    out = bytearray()
    while pos < len(stream):
        tag = stream[pos]
        pos += 1
        kind = tag & 3
        if kind == 0:
            n = tag >> 2
            if n >= 60:
                extra = n - 59
                n = int.from_bytes(stream[pos : pos + extra], "little")
                pos += extra
            n += 1
            out += stream[pos : pos + n]
            pos += n
        else:
            if kind == 1:
                length = ((tag >> 2) & 7) + 4
                offset = ((tag >> 5) << 8) | stream[pos]
                pos += 1
            elif kind == 2:
                length = (tag >> 2) + 1
                offset = int.from_bytes(stream[pos : pos + 2], "little")
                pos += 2
            else:
                length = (tag >> 2) + 1
                offset = int.from_bytes(stream[pos : pos + 4], "little")
                pos += 4
            for _ in range(length):
                out.append(out[-offset])
    assert len(out) == expect
    return bytes(out)


def check_both_modes(lib, stream: bytes, want: bytes):
    """Decode `stream` at every interesting capacity; all must agree."""
    expect = len(want)
    for cap in (expect, expect + 1, expect + 63, expect + 64, expect + 200):
        rc, got, canary_ok = decompress(lib, stream, cap)
        assert rc == expect, (cap, rc)
        assert got == want, f"cap={cap} (fast={cap >= expect + 64})"
        assert canary_ok, f"cap={cap}: write escaped the slack window"


class TestHandcraftedStreams:
    def test_short_period_overlapping_copies(self, lib):
        """RLE-style matches with offsets 1..7 and lengths crossing the
        period multiple several times — the fast path's strided copy must
        reproduce the byte-loop semantics exactly."""
        for offset in range(1, 8):
            seed = bytes(range(1, offset + 1))
            for length in (offset, offset + 1, 7, 8, 9, 15, 16, 17,
                           3 * offset + 5, 64, 61):
                if length > 64:
                    continue
                stream = (
                    varint(offset + length)
                    + lit(seed)
                    + copy2(offset, length)
                )
                check_both_modes(lib, stream, ref_decode(stream))

    def test_one_byte_offset_tags(self, lib):
        for offset in (1, 2, 7, 8, 9, 255, 1023, 2047):
            seed = bytes((i * 37 + 11) & 0xFF for i in range(offset))
            for length in (4, 7, 8, 11):
                stream = varint(offset + length) + lit(seed) + copy1(offset, length)
                check_both_modes(lib, stream, ref_decode(stream))

    def test_four_byte_offset_tags(self, lib):
        """kind-3 tags (rare in encoder output, legal in the format)."""
        seed = bytes((i * 13 + 5) & 0xFF for i in range(300))
        stream = varint(300 + 40 + 64) + lit(seed) + copy4(250, 40) + copy4(300, 64)
        check_both_modes(lib, stream, ref_decode(stream))

    def test_copy_trailer_at_stream_end(self, lib):
        """A 1-byte-trailer copy as the LAST bytes of the stream: the fast
        path's unconditional 4-byte trailer load must not be used there
        (pos+4 > src_len falls back to the ladder) and must still decode."""
        seed = b"abcdefgh"
        stream = varint(8 + 4) + lit(seed) + copy1(8, 4)
        assert stream[-2] & 3 == 1  # really ends on a kind-1 tag + trailer
        check_both_modes(lib, stream, ref_decode(stream))

    def test_literal_chain_and_mixed_ops(self, lib):
        rng = np.random.default_rng(9)
        body = bytes(rng.integers(0, 256, 70).astype(np.uint8))
        stream = (
            varint(70 + 64 + 10 + 30)
            + lit(body)
            + copy2(70, 64)
            + lit(b"0123456789")
            + copy2(3, 30)
        )
        check_both_modes(lib, stream, ref_decode(stream))


class TestCorruptStreams:
    @pytest.mark.parametrize("slack", [0, 63, 64, 200])
    def test_truncated_literal_tail(self, lib, slack):
        stream = varint(20) + lit(b"abc")[:2]  # literal claims 3, carries 1
        rc, _, canary_ok = decompress(lib, stream, 20 + slack)
        assert rc == -1 and canary_ok

    @pytest.mark.parametrize("slack", [0, 63, 64, 200])
    def test_zero_offset_copy(self, lib, slack):
        stream = varint(10) + lit(b"abcd") + copy2(0, 6)
        rc, _, canary_ok = decompress(lib, stream, 10 + slack)
        assert rc == -1 and canary_ok

    @pytest.mark.parametrize("slack", [0, 63, 64, 200])
    def test_offset_beyond_output(self, lib, slack):
        stream = varint(10) + lit(b"abcd") + copy2(5, 6)
        rc, _, canary_ok = decompress(lib, stream, 10 + slack)
        assert rc == -1 and canary_ok

    @pytest.mark.parametrize("slack", [0, 63, 64, 200])
    def test_output_overrun_claim(self, lib, slack):
        # stream writes more than its preamble claims
        stream = varint(4) + lit(b"abcdefgh")
        rc, _, canary_ok = decompress(lib, stream, 4 + slack)
        assert rc == -1 and canary_ok

    @pytest.mark.parametrize("slack", [0, 63, 64, 200])
    def test_truncated_copy_trailer(self, lib, slack):
        stream = varint(12) + lit(b"abcdefgh") + copy2(4, 4)[:2]
        rc, _, canary_ok = decompress(lib, stream, 12 + slack)
        assert rc == -1 and canary_ok

    def test_undersized_destination(self, lib):
        stream = varint(100) + lit(b"x" * 100)
        rc, _, canary_ok = decompress(lib, stream, 50)
        assert rc == -1 and canary_ok


class TestFuzzSweep:
    def test_fast_vs_careful_on_encoder_output(self, lib):
        """Round-trip sweep over data mixes through BOTH our encoder and
        pyarrow's (different emit patterns), decoded at careful and fast
        capacities — byte equality everywhere."""
        import pyarrow as pa

        codec = pa.Codec("snappy")
        rng = np.random.default_rng(17)
        cases = []
        for n in (1, 7, 64, 1000, 65_536, 262_144):
            cases.append(bytes(rng.integers(0, 256, n).astype(np.uint8)))  # random
            cases.append(bytes(n))  # zeros: long RLE matches, offset 1
            cases.append((b"abcdefgh" * (n // 8 + 1))[:n])  # period 8
            cases.append((b"abc" * (n // 3 + 1))[:n])  # period 3
            arr = (np.arange(n // 8 + 1, dtype=np.int64) * 977 + 13).tobytes()[:n]
            cases.append(arr)  # struct-like int64 payload
        for data in cases:
            for comp in (lib.snappy_compress(data), codec.compress(data)):
                comp = bytes(comp)
                for cap in (len(data), len(data) + 63, len(data) + 64,
                            len(data) + 256):
                    rc, got, canary_ok = decompress(lib, comp, cap)
                    assert rc == len(data)
                    assert got == data
                    assert canary_ok

    def test_mutation_sweep_never_escapes_slack(self, lib):
        """Random single-byte mutations of valid streams: any outcome is
        allowed except corruption of the canary or a claimed success with
        wrong length."""
        rng = np.random.default_rng(23)
        base = (b"abcdefgh" * 512) + bytes(rng.integers(0, 256, 1024).astype(np.uint8))
        comp = bytearray(lib.snappy_compress(base))
        for _ in range(400):
            mut = bytearray(comp)
            i = int(rng.integers(0, len(mut)))
            mut[i] ^= int(rng.integers(1, 256))
            for cap in (len(base), len(base) + 64):
                rc, got, canary_ok = decompress(lib, bytes(mut), cap)
                assert canary_ok, f"mutation at {i} escaped slack (cap={cap})"
                assert rc <= len(base)

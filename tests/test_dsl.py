"""Schema DSL parser/printer/validator tests (reference:
parquetschema/schema_parser_test.go table tests, SURVEY §2.2)."""

from pathlib import Path

import pytest

from parquet_tpu.core.schema import SchemaError
from parquet_tpu.meta.parquet_types import ConvertedType, Type
from parquet_tpu.schema.dsl import (
    SchemaParseError,
    parse_schema,
    schema_to_string,
    validate,
    validate_strict,
)

BIG = """
message taxi {
  required int64 trip_id;
  optional binary vendor (STRING);
  optional double fare;
  optional boolean flagged;
  optional int32 day (DATE);
  optional int64 ts (TIMESTAMP(MICROS,true));
  optional int32 small (INT(8,false));
  optional fixed_len_byte_array(16) uid (UUID);
  optional int32 price (DECIMAL(9,2));
  optional group tags (LIST) {
    repeated group list {
      optional binary element (STRING);
    }
  }
  optional group attrs (MAP) {
    repeated group key_value {
      required binary key (STRING);
      optional int64 value;
    }
  }
  required group pos {
    required double lat;
    required double lon;
  }
}
"""


class TestParse:
    def test_full_schema_parses(self):
        s = parse_schema(BIG)
        assert s.root.name == "taxi"
        assert len(s.leaves) == 14
        assert s.column("trip_id").type == Type.INT64
        assert s.column("tags.list.element").is_string()
        assert s.column("attrs.key_value.key").max_def == 2
        assert s.column("tags.list.element").max_rep == 1

    def test_roundtrip_through_printer(self):
        s = parse_schema(BIG)
        text = schema_to_string(s)
        s2 = parse_schema(text)
        assert schema_to_string(s2) == text
        assert [l.path for l in s2.leaves] == [l.path for l in s.leaves]

    def test_field_ids(self):
        s = parse_schema("message m { required int32 a = 7; }")
        assert s.column("a").element.field_id == 7

    def test_decimal_params(self):
        s = parse_schema("message m { optional int64 d (DECIMAL(18,4)); }")
        c = s.column("d")
        assert c.element.precision == 18
        assert c.element.scale == 4
        assert c.converted_type == ConvertedType.DECIMAL

    def test_legacy_converted_names(self):
        s = parse_schema(
            "message m { optional binary s (UTF8); optional int64 t (TIMESTAMP_MILLIS); }"
        )
        assert s.column("s").is_string()
        assert s.column("t").converted_type == ConvertedType.TIMESTAMP_MILLIS

    def test_used_with_writer_reader(self, tmp_path):
        import pyarrow.parquet as pq

        from parquet_tpu.core.reader import FileReader
        from parquet_tpu.core.writer import FileWriter

        s = parse_schema(
            "message m { required int64 id; optional group l (LIST) "
            "{ repeated group list { optional int32 element; } } }"
        )
        path = str(tmp_path / "dsl.parquet")
        with FileWriter(path, s) as w:
            w.write_rows([{"id": 1, "l": [1, 2]}, {"id": 2, "l": None}])
        assert pq.read_table(path).to_pylist() == [
            {"id": 1, "l": [1, 2]},
            {"id": 2, "l": None},
        ]
        assert list(FileReader(path).iter_rows()) == [
            {"id": 1, "l": [1, 2]},
            {"id": 2, "l": None},
        ]


class TestParseErrors:
    @pytest.mark.parametrize(
        "text,frag",
        [
            ("msg m { }", "message"),
            ("message m { required int32 a }", ";"),
            ("message m { int32 a; }", "repetition"),
            ("message m { required badtype a; }", "unknown type"),
            ("message m { required int32 a (WHAT); }", "unknown annotation"),
            ("message m { required fixed_len_byte_array(0) a; }", "fixed length"),
            ("message m { required group g (LIST) { } }", "no children"),
            ("message m { required int32 a (DECIMAL(0)); }", "precision"),
            ("message m { required int32 a (INT(7,true)); }", "bit width"),
            ("message m { required int64 t (TIME(WEEKS,true)); }", "unit"),
            ("message m {", "expected"),
        ],
    )
    def test_bad_inputs(self, text, frag):
        with pytest.raises(SchemaParseError) as ei:
            parse_schema(text)
        assert frag.lower() in str(ei.value).lower()


class TestValidate:
    def test_valid_schema_passes_strict(self):
        validate_strict(parse_schema(BIG))

    def test_list_not_group_rejected(self):
        s = parse_schema("message m { optional binary l (LIST); }")
        with pytest.raises(SchemaError):
            validate(s)

    def test_list_child_not_repeated_rejected(self):
        s = parse_schema(
            "message m { optional group l (LIST) { optional int32 list; } }"
        )
        with pytest.raises(SchemaError):
            validate(s)

    def test_athena_bag_ok_lenient_rejected_strict(self):
        s = parse_schema(
            "message m { optional group l (LIST) { repeated group bag "
            "{ optional int32 array_element; } } }"
        )
        validate(s)  # lenient ok (reference: schema_parser.go:776-833)
        with pytest.raises(SchemaError):
            validate_strict(s)

    def test_map_shape_rejected(self):
        s = parse_schema(
            "message m { optional group mp (MAP) { repeated group key_value "
            "{ required binary key; } } }"
        )
        with pytest.raises(SchemaError):
            validate(s)

    def test_utf8_on_int_rejected(self):
        s = parse_schema("message m { optional int32 s (UTF8); }")
        with pytest.raises(SchemaError):
            validate(s)

    def test_decimal_too_wide_rejected(self):
        s = parse_schema("message m { optional int32 d (DECIMAL(10,2)); }")
        with pytest.raises(SchemaError):
            validate(s)

    def test_uuid_wrong_length_rejected(self):
        s = parse_schema("message m { optional fixed_len_byte_array(8) u (UUID); }")
        with pytest.raises(SchemaError):
            validate(s)


class TestSchemaFileCorpus:
    """Every sample .schema file parses, validates, and round-trips through
    schema_to_string (the reference ships parquetschema/schema-files/)."""

    FILES = sorted(
        (Path(__file__).parent.parent / "parquet_tpu" / "schema" / "schema-files").glob(
            "*.schema"
        )
    )

    def test_corpus_present(self):
        assert len(self.FILES) >= 7

    @pytest.mark.parametrize("path", FILES, ids=lambda p: p.stem)
    def test_parse_validate_roundtrip(self, path):
        text = path.read_text()
        schema = parse_schema(text)
        if path.stem == "athena_lenient":
            validate(schema)  # lenient accepts bag/array_element
            with pytest.raises(SchemaError):
                validate_strict(schema)
        else:
            validate_strict(schema)
        # print -> reparse -> identical print (reference: schema_def.go:114-132)
        printed = schema_to_string(schema)
        again = schema_to_string(parse_schema(printed))
        assert printed == again

    @pytest.mark.parametrize("path", FILES, ids=lambda p: p.stem)
    def test_corpus_schemas_write_and_read(self, path, tmp_path):
        """Each corpus schema produces a writable file whose schema survives
        the thrift round-trip."""
        from parquet_tpu import FileReader, FileWriter

        schema = parse_schema(path.read_text())
        out = tmp_path / "empty.parquet"
        with FileWriter(out, schema=schema):
            pass  # zero rows: schema-only file
        with FileReader(out) as r:
            assert [c.path for c in r.schema.leaves] == [
                c.path for c in schema.leaves
            ]

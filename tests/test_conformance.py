"""Cross-implementation conformance matrix — the compatibility/ harness analogue.

The reference validates its written files with Java parquet-mr across
{none,gzip,snappy} x {v1,v2} (reference: compatibility/run_tests.bash:3-19,
Dockerfile:13-37) and reads the apache/parquet-testing corpus (SURVEY §4.5-4.6).
No JVM or network here, so pyarrow (Arrow C++, the most widely deployed
implementation) is the oracle, both directions:

  write-with-ours  -> read-with-pyarrow   (the parquet-mr direction)
  write-with-pyarrow -> read-with-ours    (the golden-corpus direction),
                                          host AND tpu decode backends

parameterized over page version x codec x dictionary x CRC, on a table that
exercises every physical type plus optional and LIST columns.
"""

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from parquet_tpu.core.arrays import ByteArrayData
from parquet_tpu.core.reader import FileReader
from parquet_tpu.core.writer import FileWriter
from parquet_tpu.meta.parquet_types import Type
from parquet_tpu.schema.builder import (
    list_of,
    message,
    optional,
    required,
    string,
)

N = 3000
rng = np.random.default_rng(99)

CODECS = ["uncompressed", "snappy", "gzip", "zstd", "lz4", "brotli"]
VERSIONS = [1, 2]


def _sample_columns():
    return {
        "i32": rng.integers(-(2**31), 2**31, N).astype(np.int32),
        "i64": rng.integers(-(2**62), 2**62, N).astype(np.int64),
        "f32": rng.standard_normal(N).astype(np.float32),
        "f64": rng.standard_normal(N),
        "flag": rng.random(N) < 0.5,
        "name": [f"name_{i % 101}" for i in range(N)],
    }


def _our_schema():
    return message(
        required("i32", Type.INT32),
        required("i64", Type.INT64),
        required("f32", Type.FLOAT),
        required("f64", Type.DOUBLE),
        required("flag", Type.BOOLEAN),
        required("name", string()),
    )


class TestOursToPyarrow:
    """Files we write must be readable by Arrow C++ — byte-exact values."""

    @pytest.mark.parametrize("codec", CODECS)
    @pytest.mark.parametrize("version", VERSIONS)
    def test_matrix(self, tmp_path, codec, version):
        cols = _sample_columns()
        path = str(tmp_path / f"ours_{codec}_{version}.parquet")
        with FileWriter(
            path, _our_schema(), codec=codec, data_page_version=version
        ) as w:
            for k, v in cols.items():
                if k == "name":
                    w.write_column(k, ByteArrayData.from_list([s.encode() for s in v]))
                else:
                    w.write_column(k, v)
            w.flush_row_group()
        t = pq.read_table(path)
        for k, v in cols.items():
            got = t.column(k).to_pylist()
            if k == "name":
                assert got == list(v)
            elif k == "flag":
                assert got == v.tolist()
            elif np.asarray(v).dtype.kind == "f":
                np.testing.assert_array_equal(
                    np.asarray(got, dtype=np.asarray(v).dtype), v
                )
            else:
                assert got == v.tolist()

    @pytest.mark.parametrize("with_crc", [False, True])
    def test_crc_variants(self, tmp_path, with_crc):
        cols = _sample_columns()
        path = str(tmp_path / f"crc_{with_crc}.parquet")
        with FileWriter(path, _our_schema(), codec="snappy", with_crc=with_crc) as w:
            for k, v in cols.items():
                if k == "name":
                    w.write_column(k, ByteArrayData.from_list([s.encode() for s in v]))
                else:
                    w.write_column(k, v)
            w.flush_row_group()
        assert pq.read_table(path).column("i64").to_pylist() == cols["i64"].tolist()
        # and our own reader validates the CRCs we wrote
        with FileReader(path, validate_crc=True) as r:
            np.testing.assert_array_equal(
                r.read_row_group(0)[("i64",)].values, cols["i64"]
            )

    def test_nested_list_to_pyarrow(self, tmp_path):
        schema = message(list_of("vals", required("element", Type.INT64)))
        rows = [{"vals": list(range(i % 5))} for i in range(500)]
        path = str(tmp_path / "list.parquet")
        with FileWriter(path, schema) as w:
            w.write_rows(rows)
        assert pq.read_table(path).column("vals").to_pylist() == [
            r["vals"] for r in rows
        ]


class TestPyarrowToOurs:
    """Files pyarrow writes must decode identically on both our backends."""

    @pytest.mark.parametrize("codec", CODECS)
    @pytest.mark.parametrize("version", ["1.0", "2.6"])
    def test_matrix(self, tmp_path, codec, version):
        cols = _sample_columns()
        t = pa.table(cols)
        path = str(tmp_path / f"pa_{codec}_{version}.parquet")
        pq.write_table(
            t,
            path,
            compression="none" if codec == "uncompressed" else codec,
            version=version,
            data_page_version="2.0" if version == "2.6" else "1.0",
        )
        for backend in ("host", "tpu"):
            with FileReader(path, backend=backend) as r:
                out = {}
                for i in range(r.num_row_groups):
                    for p, cd in r.read_row_group(i).items():
                        out.setdefault(p, []).append(cd)
            for k, v in cols.items():
                chunks = out[(k,)]
                if k == "name":
                    got = []
                    for c in chunks:
                        got.extend(
                            s.decode() for s in c.values.to_list()
                        )
                    assert got == list(v), (backend, k)
                else:
                    arr = np.concatenate([np.asarray(c.values) for c in chunks])
                    want = np.asarray(v)
                    if want.dtype.kind == "f":
                        u = np.uint32 if want.itemsize == 4 else np.uint64
                        np.testing.assert_array_equal(
                            arr.view(u), want.view(u), err_msg=f"{backend}:{k}"
                        )
                    else:
                        np.testing.assert_array_equal(
                            arr, want, err_msg=f"{backend}:{k}"
                        )

    def test_rows_roundtrip_through_assembly(self, tmp_path):
        cols = _sample_columns()
        t = pa.table(cols)
        path = str(tmp_path / "rows.parquet")
        pq.write_table(t, path, compression="snappy")
        with FileReader(path) as r:
            rows = list(r.iter_rows())
        assert rows == t.to_pylist()

    def test_optional_and_nested_from_pyarrow(self, tmp_path):
        t = pa.table(
            {
                "o": pa.array(
                    [i if i % 3 else None for i in range(1000)], pa.int64()
                ),
                "l": pa.array(
                    [list(range(i % 4)) if i % 5 else None for i in range(1000)],
                    pa.list_(pa.int32()),
                ),
            }
        )
        path = str(tmp_path / "on.parquet")
        pq.write_table(t, path, compression="zstd")
        with FileReader(path) as r:
            rows = list(r.iter_rows())
        assert rows == t.to_pylist()


class TestFullCircle:
    """ours -> pyarrow -> ours: values survive a round trip through Arrow."""

    def test_full_circle(self, tmp_path):
        cols = _sample_columns()
        p1 = str(tmp_path / "ours.parquet")
        with FileWriter(p1, _our_schema(), codec="snappy") as w:
            for k, v in cols.items():
                if k == "name":
                    w.write_column(k, ByteArrayData.from_list([s.encode() for s in v]))
                else:
                    w.write_column(k, v)
            w.flush_row_group()
        t = pq.read_table(p1)
        p2 = str(tmp_path / "back.parquet")
        pq.write_table(t, p2, compression="gzip")
        with FileReader(p2) as r:
            got = {p: cd for i in range(r.num_row_groups) for p, cd in r.read_row_group(i).items()}
        np.testing.assert_array_equal(got[("i64",)].values, cols["i64"])
        np.testing.assert_array_equal(
            np.asarray(got[("f64",)].values).view(np.uint64),
            cols["f64"].view(np.uint64),
        )
        assert [s.decode() for s in got[("name",)].values.to_list()] == list(cols["name"])

"""PLAIN->delta transfer repack (kernels/pipeline._repack_plain_as_delta)
pinned by the suite, not just bench.py.

The repack re-encodes large PLAIN int chunks host-side as delta-bitpacked
streams so the host->device wire carries the column's entropy; the device
delta kernel must reconstruct them BIT-exactly. These tests drive chunks
past the engage thresholds (>=64Ki values, >=512KiB) through the
tpu_roundtrip backend, assert byte equality against the host decode, and —
via the decode-trace counters — assert the repack really engaged (or
really declined for pathological columns that would inflate the wire).
"""

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from parquet_tpu.core.reader import FileReader
from parquet_tpu.utils.trace import decode_trace

N64 = 80_000  # int64: 640 KB raw, past both engage thresholds
N32 = 150_000  # int32: 600 KB raw


def _needs_native():
    from parquet_tpu.utils.native import get_native

    lib = get_native()
    if lib is None or not (lib.has_delta_encode and lib.has_prescan_delta):
        pytest.skip("native delta encode/prescan not built")
    return lib


def _write(tmp_path, arr, compression="none"):
    t = pa.table({"x": pa.array(arr)})
    p = str(tmp_path / "c.parquet")
    pq.write_table(
        t, p, use_dictionary=False, compression=compression,
        row_group_size=len(arr), data_page_size=1 << 30,
    )
    return p


def _roundtrip(path):
    """(host chunk values, device chunk values, trace) for column x."""
    with FileReader(path, backend="host") as r:
        host = np.asarray(r.read_row_group(0)[("x",)].values)
    with decode_trace() as t:
        with FileReader(path, backend="tpu_roundtrip") as r:
            dev = np.asarray(r.read_row_group(0)[("x",)].values)
    return host, dev, t


def _calls(t, name):
    s = t.stages.get(name)
    return 0 if s is None else s.calls


class TestRepackEngages:
    def test_structured_int64_bit_exact(self, tmp_path):
        _needs_native()
        rng = np.random.default_rng(1)
        vals = np.cumsum(rng.integers(-3, 50, N64)).astype(np.int64) + 10**15
        host, dev, t = _roundtrip(_write(tmp_path, vals))
        assert _calls(t, "repack_engaged") >= 1, t.stages
        np.testing.assert_array_equal(host, vals)
        np.testing.assert_array_equal(dev, vals)

    def test_structured_int32_bit_exact(self, tmp_path):
        _needs_native()
        rng = np.random.default_rng(2)
        vals = (np.arange(N32) * 7 + rng.integers(0, 13, N32)).astype(np.int32)
        host, dev, t = _roundtrip(_write(tmp_path, vals))
        assert _calls(t, "repack_engaged") >= 1, t.stages
        np.testing.assert_array_equal(dev, vals)

    def test_snappy_compressed_chunk_still_repacks(self, tmp_path):
        """Repack operates on the DECODED chunk — file compression upstream
        must not disable it."""
        _needs_native()
        vals = (np.arange(N64, dtype=np.int64) * 1_000) + 42
        host, dev, t = _roundtrip(_write(tmp_path, vals, compression="snappy"))
        assert _calls(t, "repack_engaged") >= 1, t.stages
        np.testing.assert_array_equal(dev, vals)

    def test_negative_and_near_int64_min(self, tmp_path):
        """Monotonic walk down to near INT64_MIN: large-magnitude values,
        small deltas — must engage and reconstruct exactly."""
        _needs_native()
        lo = np.iinfo(np.int64).min
        vals = (lo + 5 + np.arange(N64, dtype=np.int64) * 3)
        host, dev, t = _roundtrip(_write(tmp_path, vals))
        assert _calls(t, "repack_engaged") >= 1, t.stages
        np.testing.assert_array_equal(dev, vals)


class TestRepackDeclines:
    def test_incompressible_ships_raw(self, tmp_path):
        """Full-width random data: the width estimate must decline (wire
        would not shrink) and the raw upload must still be bit-exact."""
        _needs_native()
        rng = np.random.default_rng(3)
        vals = rng.integers(-(2**62), 2**62, N64).astype(np.int64)
        host, dev, t = _roundtrip(_write(tmp_path, vals))
        assert _calls(t, "repack_engaged") == 0, t.stages
        assert _calls(t, "repack_declined") >= 1, t.stages
        np.testing.assert_array_equal(dev, vals)

    def test_adversarial_sample_windows_bails_to_raw(self, tmp_path):
        """Wild deltas OUTSIDE the 4 sampled windows: the estimate says
        compressible, the encoder proves otherwise — the bail-out must ship
        raw bytes, never a bloated stream, and stay bit-exact."""
        _needs_native()
        rng = np.random.default_rng(4)
        n = N64
        vals = np.arange(n, dtype=np.int64)  # windows look ~1-bit
        wild = rng.integers(-(2**62), 2**62, n).astype(np.int64)
        keep = np.zeros(n, dtype=bool)
        for lo in (0, n // 3, (2 * n) // 3, n - 1024):  # the sampled windows
            keep[max(lo - 2048, 0) : lo + 1024 + 2048] = True
        vals[~keep] = wild[~keep]
        host, dev, t = _roundtrip(_write(tmp_path, vals))
        assert _calls(t, "repack_engaged") == 0, t.stages
        assert _calls(t, "repack_declined") >= 1, t.stages
        np.testing.assert_array_equal(dev, vals)

    def test_small_chunk_not_considered(self, tmp_path):
        """Below the 64Ki/512KiB thresholds the repack must not even be
        evaluated (latency-bound regime)."""
        _needs_native()
        vals = np.arange(50_000, dtype=np.int64)
        host, dev, t = _roundtrip(_write(tmp_path, vals))
        assert _calls(t, "repack_engaged") == 0
        assert _calls(t, "repack_declined") == 0
        np.testing.assert_array_equal(dev, vals)


class TestStagingBufferPool:
    """chunk_prepare's staging buffers recycle through a per-thread pool;
    the release contract is that NO view escapes into the plan. Two chunks
    prepared back-to-back on one thread must not alias."""

    def test_sequential_chunks_do_not_alias(self, tmp_path):
        _needs_native()
        rng = np.random.default_rng(11)
        a = np.cumsum(rng.integers(0, 9, N64)).astype(np.int64)   # repacks
        b = rng.integers(-(2**62), 2**62, N64).astype(np.int64)   # ships raw
        t = pa.table({"a": pa.array(a), "b": pa.array(b)})
        p = str(tmp_path / "two.parquet")
        pq.write_table(t, p, use_dictionary=False, compression="snappy",
                       row_group_size=N64, data_page_size=1 << 30)
        from parquet_tpu.core.chunk import ChunkWindow, chunk_byte_range
        from parquet_tpu.kernels.pipeline import prepare_chunk_plan

        with FileReader(p) as r:
            plans = []
            for path, cc, col in r._selected_chunks(0):
                off, tot = chunk_byte_range(cc)
                plans.append(
                    (path, prepare_chunk_plan(ChunkWindow(r._pread(off, tot), off), cc, col))
                )
            # prepare chunk b AFTER a: if a's release leaked a live view,
            # b's walk would have overwritten it
            (pa_, plan_a), (pb_, plan_b) = plans
            assert plan_a.plain_host is None  # a repacked: no raw view kept
            np.testing.assert_array_equal(np.asarray(plan_b.plain_host), b)
            # decode through the public API for the value check of column a
            got = np.asarray(r.read_row_group(0)[("a",)].values)
        np.testing.assert_array_equal(got, a)

    def test_dictionary_page_blocks_values_release(self, tmp_path):
        """HANDCRAFTED chunk: a dictionary page followed by PLAIN-only data
        pages big enough to trigger the transfer repack. The decoded
        dictionary aliases the values staging buffer zero-copy, so the
        repack's buffer release must SKIP it — preparing another chunk on
        the same thread must not overwrite the first chunk's dictionary."""
        _needs_native()
        import sys as _sys
        from pathlib import Path as _P

        _sys.path.insert(0, str(_P(__file__).parent / "golden"))
        from generate_foreign import _handcraft

        from parquet_tpu import parse_schema
        from parquet_tpu.core.chunk import ChunkWindow, chunk_byte_range
        from parquet_tpu.core.page import encode_data_page_v1, encode_dict_page
        from parquet_tpu.kernels.pipeline import prepare_chunk_plan
        from parquet_tpu.meta.parquet_types import Encoding

        schema = parse_schema("message m { required int64 a; }")
        col = schema.leaves[0]
        dict_vals = np.arange(100, dtype=np.int64) * 7 + 3
        vals = (np.arange(N64, dtype=np.int64) * 11) + 5  # repack-eligible
        pages = [
            encode_dict_page(col, dict_vals, 1),
            encode_data_page_v1(col, vals, None, None, Encoding.PLAIN, 1),
        ]
        p = str(tmp_path / "dictplain.parquet")
        _handcraft(
            p, schema,
            [(col, pages, N64,
              [int(Encoding.RLE), int(Encoding.PLAIN)])],
            N64, 1,
        )
        with decode_trace() as tr:
            with FileReader(p) as r:
                (path, cc, c), = list(r._selected_chunks(0))
                off, tot = chunk_byte_range(cc)
                plan = prepare_chunk_plan(ChunkWindow(r._pread(off, tot), off), cc, c)
                assert plan.dictionary is not None
                # another large prepare on this same thread: if the first
                # plan's values base was pooled, this overwrites it
                other = np.cumsum(np.ones(N64, np.int64))
                t2 = pa.table({"a": pa.array(other)})
                p2 = str(tmp_path / "second.parquet")
                pq.write_table(t2, p2, use_dictionary=False, compression="snappy",
                               row_group_size=N64, data_page_size=1 << 30)
                with FileReader(p2) as r2:
                    (path2, cc2, c2), = list(r2._selected_chunks(0))
                    off2, tot2 = chunk_byte_range(cc2)
                    prepare_chunk_plan(
                        ChunkWindow(r2._pread(off2, tot2), off2), cc2, c2
                    )
                np.testing.assert_array_equal(np.asarray(plan.dictionary), dict_vals)
                host = r.read_row_group(0)
        assert _calls(tr, "repack_engaged") >= 1, tr.stages
        np.testing.assert_array_equal(np.asarray(host[("a",)].values), vals)

    def test_pool_best_fit_leaves_big_buffers_for_big_chunks(self):
        lib = _needs_native()
        big = np.empty(8 << 20, np.uint8)
        small = np.empty(1 << 16, np.uint8)
        pool = []
        lib._chunk_tl.out_pool = pool
        pool.extend([big, small])
        got = lib._take_buf(1000)
        assert got is small  # not the 8MB buffer
        got2 = lib._take_buf(1 << 20)
        assert got2 is not big  # >4x cap: fresh allocation instead
        assert big in pool
        del lib._chunk_tl.out_pool


class TestAssemblyPathCounters:
    """The decode-trace counters distinguish which assembly engine served a
    read: the vectorized engine or the per-row cursor fallback. A 3-level
    list must be served VECTORIZED, not by the fallback."""

    def test_three_level_list_served_vectorized(self, tmp_path):
        t = pa.table({
            "lll": pa.array(
                [[[[1, 2], []], None], None, [], [[[3]]]] * 50,
                pa.list_(pa.list_(pa.list_(pa.int32()))),
            ),
        })
        p = str(tmp_path / "l3.parquet")
        pq.write_table(t, p)
        with decode_trace() as tr:
            with FileReader(p) as r:
                rows = list(r.iter_rows())
        assert _calls(tr, "assemble_vec") >= 1, tr.stages
        assert _calls(tr, "assemble_cursor") == 0, tr.stages
        assert rows[:4] == [
            {"lll": [[[1, 2], []], None]},
            {"lll": None},
            {"lll": []},
            {"lll": [[[3]]]},
        ]

    def test_canonical_list_served_fast(self, tmp_path):
        t = pa.table({"v": pa.array([[1, 2], None, []], pa.list_(pa.int64()))})
        p = str(tmp_path / "l1.parquet")
        pq.write_table(t, p)
        with decode_trace() as tr:
            with FileReader(p) as r:
                rows = list(r.iter_rows())
        assert _calls(tr, "assemble_vec") >= 1, tr.stages
        assert _calls(tr, "assemble_cursor") == 0
        assert rows == [{"v": [1, 2]}, {"v": None}, {"v": []}]

    def test_array_backed_spec_matches_list_backed(self, tmp_path):
        """The C dict_rows array-elems path (ints built straight from the
        numpy buffer) must produce rows identical to pyarrow's decode for
        every numeric dtype it covers, nulls included."""
        rng = np.random.default_rng(7)
        n = 3_000
        cols = {}
        for name, dtype, atype in [
            ("i32", np.int32, pa.int32()), ("i64", np.int64, pa.int64()),
            ("f32", np.float32, pa.float32()), ("f64", np.float64, pa.float64()),
        ]:
            lens = rng.integers(0, 4, n)
            flat = rng.integers(-1000, 1000, int(lens.sum())).astype(dtype)
            off = np.zeros(n + 1, np.int64)
            np.cumsum(lens, out=off[1:])
            cols[name] = pa.ListArray.from_arrays(
                pa.array(off, pa.int32()), pa.array(flat, atype)
            )
        # a column with NULL rows exercises the masked spec
        cols["masked"] = pa.array(
            [None if i % 5 == 0 else [i, i + 1] for i in range(n)],
            pa.list_(pa.int64()),
        )
        t = pa.table(cols)
        p = str(tmp_path / "arr.parquet")
        pq.write_table(t, p)
        with FileReader(p) as r:
            got = list(r.iter_rows())
        want = pq.read_table(p).to_pylist()
        assert got == want
        # every element came back as a plain Python scalar, not numpy
        probe = next(r for r in got if r["i32"] and r["f32"])
        assert type(probe["i32"][0]) is int and type(probe["f32"][0]) is float


class TestRepackEdgeCases:
    def test_uint64_wraparound_deltas(self, tmp_path):
        """Values crossing the int64 sign boundary (uint64-monotonic,
        int64-wrapping): whether the encoder engages (mod-2^64 zigzag) or
        declines, the delivered bytes must equal the host decode."""
        _needs_native()
        base = np.arange(N64, dtype=np.uint64) + np.uint64(2**63 - N64 // 2)
        vals = base.view(np.int64).copy()
        host, dev, t = _roundtrip(_write(tmp_path, vals))
        np.testing.assert_array_equal(host, vals)
        np.testing.assert_array_equal(dev, vals)

    def test_extreme_alternating_deltas(self, tmp_path):
        """INT64_MIN <-> INT64_MAX alternation between flat sample windows:
        delta magnitudes overflow int64; engage or decline, never corrupt."""
        _needs_native()
        n = N64
        vals = np.zeros(n, dtype=np.int64)
        info = np.iinfo(np.int64)
        alt = np.where(np.arange(n) % 2 == 0, info.min, info.max)
        keep = np.zeros(n, dtype=bool)
        for lo in (0, n // 3, (2 * n) // 3, n - 1024):
            keep[max(lo - 2048, 0) : lo + 1024 + 2048] = True
        vals[~keep] = alt[~keep]
        host, dev, t = _roundtrip(_write(tmp_path, vals))
        np.testing.assert_array_equal(host, vals)
        np.testing.assert_array_equal(dev, vals)

    def test_nullable_column_repack(self, tmp_path):
        """Definition levels present: the repack covers the DENSE values;
        levels and values must both survive."""
        _needs_native()
        rng = np.random.default_rng(5)
        n = N64 + 20_000
        pyvals = [
            None if i % 97 == 0 else int(i * 11 + (i % 7)) for i in range(n)
        ]
        t = pa.table({"x": pa.array(pyvals, pa.int64())})
        p = str(tmp_path / "nul.parquet")
        pq.write_table(
            t, p, use_dictionary=False, compression="none",
            row_group_size=n, data_page_size=1 << 30,
        )
        with FileReader(p, backend="host") as r:
            host_cd = r.read_row_group(0)[("x",)]
        with decode_trace() as tr:
            with FileReader(p, backend="tpu_roundtrip") as r:
                dev_cd = r.read_row_group(0)[("x",)]
        assert _calls(tr, "repack_engaged") >= 1, tr.stages
        np.testing.assert_array_equal(
            np.asarray(host_cd.values), np.asarray(dev_cd.values)
        )
        np.testing.assert_array_equal(
            np.asarray(host_cd.def_levels), np.asarray(dev_cd.def_levels)
        )

    def test_pool_total_retention_capped(self):
        lib = _needs_native()
        pool = []
        lib._chunk_tl.out_pool = pool
        # simulate releases up to the retention cap
        for _ in range(5):
            fresh = {"_bases": {"values": np.empty(60 << 20, np.uint8),
                                 "packed": None, "delta": None}}
            lib.release_buffers(fresh, ("values",))
        total = sum(len(b) for b in pool)
        assert total <= lib._POOL_MAX_TOTAL, total
        assert len(pool) == 3  # 3 x 60MB fits under 192MB, the 4th doesn't
        del lib._chunk_tl.out_pool

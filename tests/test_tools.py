"""CLI tool tests (reference: cmds/helpers_test.go, cmd/csv2parquet/main_test.go)."""

import json

import pyarrow.parquet as pq
import pytest

from parquet_tpu.core.reader import FileReader
from parquet_tpu.core.writer import FileWriter
from parquet_tpu.meta.parquet_types import Type
from parquet_tpu.schema.builder import message, optional, required, string
from parquet_tpu.tools.csv2parquet import main as csv_main, parse_type_hints
from parquet_tpu.tools.parquet_tool import main as tool_main


@pytest.fixture
def sample(tmp_path):
    path = str(tmp_path / "s.parquet")
    schema = message(required("id", Type.INT64), optional("name", string()))
    with FileWriter(path, schema, codec="snappy") as w:
        w.write_rows([{"id": i, "name": f"n{i}" if i % 3 else None} for i in range(20)])
    return path


class TestParquetTool:
    def test_rowcount(self, sample, capsys):
        assert tool_main(["rowcount", sample]) == 0
        assert capsys.readouterr().out.strip() == "20"

    def test_cat(self, sample, capsys):
        assert tool_main(["cat", sample]) == 0
        lines = capsys.readouterr().out.strip().splitlines()
        assert len(lines) == 20
        assert json.loads(lines[0]) == {"id": 0, "name": None}
        assert json.loads(lines[1]) == {"id": 1, "name": "n1"}

    def test_head(self, sample, capsys):
        assert tool_main(["head", "-n", "3", sample]) == 0
        assert len(capsys.readouterr().out.strip().splitlines()) == 3

    def test_schema(self, sample, capsys):
        assert tool_main(["schema", sample]) == 0
        out = capsys.readouterr().out
        assert "required int64 id;" in out
        assert "optional binary name (STRING);" in out

    def test_meta(self, sample, capsys):
        assert tool_main(["meta", sample]) == 0
        out = capsys.readouterr().out
        assert "rows: 20" in out
        assert "maxR=0 maxD=1" in out
        assert "codec=SNAPPY" in out

    def test_split(self, sample, tmp_path, capsys):
        out_pattern = str(tmp_path / "part_%d.parquet")
        assert tool_main(["split", "-n", "8", sample, out_pattern]) == 0
        sizes = [
            FileReader(str(tmp_path / f"part_{i}.parquet")).num_rows for i in range(3)
        ]
        assert sizes == [8, 8, 4]
        # parts readable by pyarrow too
        assert pq.read_table(str(tmp_path / "part_0.parquet")).num_rows == 8

    def test_missing_file_clean_error(self, capsys):
        assert tool_main(["rowcount", "/nonexistent.parquet"]) == 1
        assert "parquet-tool:" in capsys.readouterr().err


class TestCsv2Parquet:
    def test_type_hints_parse(self):
        assert parse_type_hints("a=int64, b=double") == {"a": "int64", "b": "double"}
        with pytest.raises(ValueError):
            parse_type_hints("a:int64")
        with pytest.raises(ValueError):
            parse_type_hints("a=quaternion")

    def test_conversion(self, tmp_path, capsys):
        src = tmp_path / "in.csv"
        src.write_text("id,name,score,ok\n1,alice,9.5,true\n2,bob,,false\n3,,7.5,\n")
        out = str(tmp_path / "out.parquet")
        rc = csv_main(["-o", out, "-typehints", "id=int64,score=double,ok=boolean", str(src)])
        assert rc == 0
        rows = list(FileReader(out).iter_rows())
        assert rows == [
            {"id": 1, "name": "alice", "score": 9.5, "ok": True},
            {"id": 2, "name": "bob", "score": None, "ok": False},
            {"id": 3, "name": None, "score": 7.5, "ok": None},
        ]
        assert pq.read_table(out).num_rows == 3

    def test_bad_value_reports_line(self, tmp_path, capsys):
        src = tmp_path / "in.csv"
        src.write_text("id\n1\nnope\n")
        rc = csv_main(["-o", str(tmp_path / "o.parquet"), "-typehints", "id=int64", str(src)])
        assert rc == 1
        assert "line 3" in capsys.readouterr().err

    def test_unknown_hint_column(self, tmp_path, capsys):
        src = tmp_path / "in.csv"
        src.write_text("a\n1\n")
        rc = csv_main(["-o", str(tmp_path / "o.parquet"), "-typehints", "zz=int64", str(src)])
        assert rc == 2

    def test_ragged_row_rejected(self, tmp_path, capsys):
        src = tmp_path / "in.csv"
        src.write_text("a,b\n1,2\n3\n")
        rc = csv_main(["-o", str(tmp_path / "o.parquet"), str(src)])
        assert rc == 1


class TestSplitBySize:
    def test_split_by_target_size(self, tmp_path):
        import pyarrow as pa
        import pyarrow.parquet as pq

        from parquet_tpu.tools.parquet_tool import main

        src = str(tmp_path / "src.parquet")
        pq.write_table(
            pa.table({"s": pa.array([f"row_{i:06d}" * 4 for i in range(20_000)])}),
            src,
            compression="none",
        )
        out = str(tmp_path / "part_%d.parquet")
        assert main(["split", "--size", "64K", "--codec", "uncompressed", src, out]) == 0
        parts = sorted(tmp_path.glob("part_*.parquet"))
        assert len(parts) > 2  # actually split
        total = 0
        from parquet_tpu.core.reader import FileReader

        for p in parts:
            with FileReader(p) as r:
                total += r.num_rows
            # each part lands in the target's ballpark (last may be smaller)
            assert p.stat().st_size < 3 * (64 << 10)
        assert total == 20_000

    def test_split_requires_exactly_one_mode(self, tmp_path, capsys):
        from parquet_tpu.tools.parquet_tool import main

        assert main(["split", "src", "out_%d"]) == 2
        assert main(["split", "-n", "5", "--size", "1M", "src", "out_%d"]) == 2

    def test_writer_string_size_estimate(self, tmp_path):
        """String-heavy rows must auto-flush near the row-group target
        instead of overshooting by the string length / 8 factor."""
        from parquet_tpu.core.reader import FileReader
        from parquet_tpu.core.writer import FileWriter
        from parquet_tpu.schema.dsl import parse_schema

        sch = parse_schema("message m { required binary s (STRING); }")
        path = str(tmp_path / "big_strings.parquet")
        with FileWriter(path, sch, row_group_size=1 << 20) as w:
            for i in range(4000):
                w.write_row({"s": "x" * 1000})  # ~4MB of string data
        with FileReader(path) as r:
            # size checks fire every 1000 rows; ~1MB/1000 rows -> a flush at
            # 2000 rows. The old flat 8B/value estimate saw ~32KB and never
            # flushed (1 row group).
            assert r.num_row_groups == 2
            assert r.num_rows == 4000


class TestColumnProjection:
    def test_cat_and_head_columns(self, sample, capsys):
        import json

        from parquet_tpu.tools.parquet_tool import main

        assert main(["head", "-n", "2", "--columns", "id", sample]) == 0
        lines = capsys.readouterr().out.strip().splitlines()
        assert len(lines) == 2 and set(json.loads(lines[0])) == {"id"}


class TestToolPageIndexBloom:
    @pytest.fixture
    def indexed(self, tmp_path):
        path = str(tmp_path / "idx.parquet")
        schema = message(required("id", Type.INT64), optional("name", string()))
        with FileWriter(
            path, schema, codec="snappy", write_page_index=True,
            bloom_filters=["id"], max_page_size=64,
        ) as w:
            w.write_rows(
                [{"id": i, "name": f"n{i}" if i % 3 else None} for i in range(50)]
            )
        return path

    def test_meta_shows_index_and_bloom(self, indexed, capsys):
        assert tool_main(["meta", indexed]) == 0
        out = capsys.readouterr().out
        assert "page-index" in out and "bloom" in out

    def test_pages(self, indexed, capsys):
        assert tool_main(["pages", indexed]) == 0
        out = capsys.readouterr().out
        assert "rg0 id page 0" in out and "min=" in out and "offset=" in out

    def test_pages_no_index(self, sample, capsys):
        assert tool_main(["pages", sample]) == 0
        assert "no page index" in capsys.readouterr().out

    def test_cat_filtered(self, indexed, capsys):
        assert tool_main(["cat", indexed, "--filter", "id >= 48"]) == 0
        rows = [json.loads(line) for line in capsys.readouterr().out.splitlines()]
        assert [r["id"] for r in rows] == [48, 49]
        assert tool_main(["head", "-n", "1", indexed, "--filter", "name == n7"]) == 0
        rows = [json.loads(line) for line in capsys.readouterr().out.splitlines()]
        assert rows == [{"id": 7, "name": "n7"}]

    def test_bad_filter_spec(self, indexed, capsys):
        assert tool_main(["cat", indexed, "--filter", "id>48"]) == 1
        assert "bad --filter" in capsys.readouterr().err

    def test_cat_filter_in(self, indexed, capsys):
        """Set membership through the CLI rides the same pruning stack
        (stats + page index + the reader's bloom consultation for 'in')."""
        assert tool_main(["cat", indexed, "--filter", "id in (3, 41, 7)"]) == 0
        rows = [json.loads(x) for x in capsys.readouterr().out.splitlines()]
        assert sorted(r["id"] for r in rows) == [3, 7, 41]
        assert tool_main(
            ["cat", indexed, "--filter", "id not_in (0,1)", "--filter", "id <= 3"]
        ) == 0
        rows = [json.loads(x) for x in capsys.readouterr().out.splitlines()]
        assert sorted(r["id"] for r in rows) == [2, 3]
        # quoted members keep their string type; empty 'in' list = no rows
        assert tool_main(["cat", indexed, "--filter", 'name in ("n5")']) == 0
        rows = [json.loads(x) for x in capsys.readouterr().out.splitlines()]
        assert [r["id"] for r in rows] == [5]
        assert tool_main(["cat", indexed, "--filter", "id in ()"]) == 0
        assert capsys.readouterr().out == ""

    def test_bad_in_filter_spec(self, indexed, capsys):
        assert tool_main(["cat", indexed, "--filter", "id in 1,2"]) == 1
        assert "parenthesized" in capsys.readouterr().err

    def test_in_parse_edge_cases(self):
        """Review regressions: quoted members may hold commas; a quoted
        comparison VALUE containing the word ' in ' is not a set op."""
        from parquet_tpu.tools.parquet_tool import _parse_filters

        assert _parse_filters(["name in ('a,b', 'c')"]) == [
            ("name", "in", ["a,b", "c"])
        ]
        assert _parse_filters(["msg == 'logged in now'"]) == [
            ("msg", "==", "logged in now")
        ]
        assert _parse_filters(["a not_in (1, 2)"]) == [("a", "not_in", [1, 2])]
        # a quoted set MEMBER containing a comparison token stays a member
        assert _parse_filters(["tag in ('a == b', 'x')"]) == [
            ("tag", "in", ["a == b", "x"])
        ]

    def test_quoted_filter_value_stays_string(self, tmp_path, capsys):
        path = str(tmp_path / "numstr.parquet")
        schema = message(required("id", Type.INT64), optional("name", string()))
        with FileWriter(path, schema) as w:
            w.write_rows([{"id": 7, "name": "7"}, {"id": 8, "name": "eight"}])
        assert tool_main(["cat", path, "--filter", 'name == "7"']) == 0
        rows = [json.loads(x) for x in capsys.readouterr().out.splitlines()]
        assert rows == [{"id": 7, "name": "7"}]

    def test_pages_decodes_numeric_bounds(self, indexed, capsys):
        assert tool_main(["pages", indexed]) == 0
        out = capsys.readouterr().out
        assert "min=0 max=" in out  # int64 bounds decoded, not raw bytes


class TestCsvToParquetAnalytics:
    def test_bloom_index_sort_flags(self, tmp_path, capsys):
        src = tmp_path / "in.csv"
        src.write_text(
            "id,name\n" + "".join(f"{i},n{i % 9}\n" for i in range(500))
        )
        out = str(tmp_path / "out.parquet")
        rc = csv_main([
            "-o", out, "-typehints", "id=int64", "--page-index",
            "--bloom", "id", "--sort", "id", str(src),
        ])
        assert rc == 0
        meta = pq.ParquetFile(out).metadata
        col = meta.row_group(0).column(0)
        assert col.has_column_index and col.has_offset_index
        assert tuple(meta.row_group(0).sorting_columns) == (
            pq.SortingColumn(0, descending=False, nulls_first=False),
        )
        with FileReader(out) as r:
            assert r.read_bloom_filter(0, "id") is not None
            assert [row["id"] for row in r.iter_rows(filters=[("id", "==", 42)])] == [42]


class TestMergeCli:
    def _mk(self, path, n):
        import numpy as np
        import pyarrow as pa

        pq.write_table(
            pa.table({"a": pa.array(np.arange(n, dtype=np.int64))}), str(path)
        )

    def test_canonical_inputs_first_form(self, tmp_path, capsys):
        from parquet_tpu.tools.parquet_tool import main as tool_main

        a, b = tmp_path / "a.parquet", tmp_path / "b.parquet"
        self._mk(a, 10)
        self._mk(b, 5)
        out = tmp_path / "m.parquet"
        assert tool_main(["merge", str(a), str(b), "-o", str(out)]) == 0
        assert "15 rows" in capsys.readouterr().out
        assert pq.read_table(str(out)).num_rows == 15

    def test_legacy_output_first_form_deprecated(self, tmp_path, capsys):
        from parquet_tpu.tools.parquet_tool import main as tool_main

        a, b = tmp_path / "a.parquet", tmp_path / "b.parquet"
        self._mk(a, 4)
        self._mk(b, 4)
        out = tmp_path / "legacy.parquet"
        assert tool_main(["merge", str(out), str(a), str(b)]) == 0
        err = capsys.readouterr().err
        assert "deprecated" in err
        assert pq.read_table(str(out)).num_rows == 8

    def test_refuses_to_overwrite_without_force(self, tmp_path, capsys):
        from parquet_tpu.tools.parquet_tool import main as tool_main

        a = tmp_path / "a.parquet"
        self._mk(a, 6)
        out = tmp_path / "exists.parquet"
        self._mk(out, 1)  # pre-existing output
        assert tool_main(["merge", str(a), str(a), "-o", str(out)]) == 1
        assert "already exists" in capsys.readouterr().err
        assert pq.read_table(str(out)).num_rows == 1  # untouched
        assert tool_main(["merge", str(a), str(a), "-o", str(out), "--force"]) == 0
        assert pq.read_table(str(out)).num_rows == 12


class TestVerifySalvage:
    """parquet-tool verify / salvage (the corruption triage lane)."""

    def _poisoned(self, tmp_path, n_groups=3):
        """(clean path, damaged path): one bit flipped in rg1's first chunk."""
        from parquet_tpu.core.chunk import chunk_byte_range

        schema = message(required("id", Type.INT64), optional("name", string()))
        clean = str(tmp_path / "clean.parquet")
        with FileWriter(clean, schema, codec="snappy", with_crc=True) as w:
            for g in range(n_groups):
                w.write_rows(
                    [
                        {"id": g * 50 + i, "name": f"n{i % 7}"}
                        for i in range(50)
                    ]
                )
                w.flush_row_group()
        data = bytearray(open(clean, "rb").read())
        with FileReader(clean) as r:
            cc = r.row_group(1).columns[0]
            off, total = chunk_byte_range(cc)
        data[off + total // 2] ^= 0x20
        bad = str(tmp_path / "bad.parquet")
        open(bad, "wb").write(bytes(data))
        return clean, bad

    def test_verify_clean(self, tmp_path, capsys):
        clean, _bad = self._poisoned(tmp_path)
        assert tool_main(["verify", clean]) == 0
        assert "OK" in capsys.readouterr().out

    def test_verify_reports_offset_stage_error(self, tmp_path, capsys):
        _clean, bad = self._poisoned(tmp_path)
        assert tool_main(["verify", bad]) == 1
        out = capsys.readouterr().out
        assert "rg1 id page 0" in out
        assert "@byte" in out
        assert "stage=crc" in out
        assert "ChunkError" in out
        assert "CORRUPT: 1 problem(s) in 1 row group(s)" in out

    def test_verify_corrupt_footer(self, tmp_path, capsys):
        p = tmp_path / "garbage.parquet"
        p.write_bytes(b"PAR1 this is not parquet PAR1")
        assert tool_main(["verify", str(p)]) == 1
        out = capsys.readouterr().out
        assert "stage=footer" in out

    def test_salvage_recovers_good_groups(self, tmp_path, capsys):
        clean, bad = self._poisoned(tmp_path)
        out = str(tmp_path / "saved.parquet")
        assert tool_main(["salvage", bad, "-o", out]) == 0
        cap = capsys.readouterr()
        assert "salvaged 2/3 row groups (100/150 rows)" in cap.out
        assert "dropped rg1" in cap.err
        # the salvaged file verifies clean and holds exactly rg0+rg2's rows
        assert tool_main(["verify", out]) == 0
        with FileReader(out, validate_crc=True) as r:
            rows = list(r.iter_rows())
        assert [row["id"] for row in rows] == list(range(50)) + list(range(100, 150))

    def test_salvage_refuses_overwrite(self, tmp_path, capsys):
        clean, bad = self._poisoned(tmp_path)
        out = tmp_path / "exists.parquet"
        out.write_bytes(b"x")
        assert tool_main(["salvage", bad, "-o", str(out)]) == 1
        assert "already exists" in capsys.readouterr().err
        assert tool_main(["salvage", bad, "-o", str(out), "--force"]) == 0


class TestProfile:
    """`parquet-tool profile`: Chrome trace-event JSON + per-stage report +
    metrics delta (the observability surface)."""

    def test_profile_writes_valid_chrome_trace(self, sample, tmp_path, capsys):
        out = str(tmp_path / "trace.json")
        assert tool_main(["profile", sample, "-o", out, "--metrics"]) == 0
        with open(out) as f:
            doc = json.load(f)
        events = doc["traceEvents"]
        assert events
        for ev in events:
            for key in ("ph", "ts", "dur", "pid", "tid", "name"):
                assert key in ev, ev
        # the device pipeline's hierarchy + lanes are present
        names = {e["name"] for e in events}
        assert "file" in names and "chunk.prepare" in names
        assert doc["otherData"]["metrics_delta"]
        text = capsys.readouterr().out
        assert "TOTAL" in text  # per-stage report footer
        assert "trace events" in text
        assert "pages decoded" in text  # --metrics summary

    def test_profile_host_backend(self, sample, tmp_path, capsys):
        out = str(tmp_path / "trace_host.json")
        assert tool_main(["profile", sample, "-o", out, "--host"]) == 0
        with open(out) as f:
            doc = json.load(f)
        names = {e["name"] for e in doc["traceEvents"]}
        # host path hierarchy: row groups, chunks, pages, leaf stages
        for expected in ("file", "row_group", "chunk", "page"):
            assert expected in names, names

    def test_profile_write_mode(self, sample, tmp_path, capsys):
        """--write profiles an ENCODE: the trace carries write.encode and,
        when the fused rung ran, its encode.* sub-clock lanes."""
        out = str(tmp_path / "trace_write.json")
        assert tool_main(["profile", sample, "-o", out, "--write"]) == 0
        with open(out) as f:
            doc = json.load(f)
        names = {e["name"] for e in doc["traceEvents"]}
        assert "write.encode" in names
        text = capsys.readouterr().out
        assert "write-encode" in text
        assert "encode ladder" in text

    def test_profile_write_rows_exclusive(self, sample, tmp_path, capsys):
        assert (
            tool_main(
                ["profile", sample, "-o", str(tmp_path / "t.json"),
                 "--write", "--rows"]
            )
            == 2
        )
        assert "mutually exclusive" in capsys.readouterr().err

    def test_meta_per_column_summary(self, sample, capsys):
        assert tool_main(["meta", sample]) == 0
        out = capsys.readouterr().out
        assert "column id:" in out
        assert "column name:" in out
        line = [x for x in out.splitlines() if x.startswith("column id:")][0]
        assert "encodings=[" in line
        assert "compressed=" in line and "uncompressed=" in line
        assert "ratio=" in line


class TestScan:
    @pytest.fixture
    def shards(self, tmp_path):
        import numpy as np
        import pyarrow as pa

        rng = np.random.default_rng(0)
        for i in range(3):
            t = pa.table(
                {
                    "a": pa.array(rng.integers(0, 100, 500).astype(np.int64)),
                    "b": pa.array(rng.standard_normal(500).astype(np.float32)),
                }
            )
            pq.write_table(
                t, tmp_path / f"s-{i}.parquet", row_group_size=200
            )
        return str(tmp_path / "s-*.parquet")

    def test_scan_reports_rows_and_wait_share(self, shards, capsys):
        assert tool_main(
            ["scan", shards, "--batch-size", "256", "--json"]
        ) == 0
        out = capsys.readouterr().out
        assert "3 files" in out and "1,500 rows" in out
        doc = json.loads(out.strip().splitlines()[-1])
        assert doc["rows"] == 1500
        assert doc["files"] == 3
        assert doc["rows_s"] > 0
        assert 0 <= doc["wait_share"] <= 1
        assert doc["units_skipped"] == 0

    def test_scan_on_error_skip_degrades(self, shards, tmp_path, capsys):
        (tmp_path / "s-zz.parquet").write_bytes(b"PAR1junkPAR1")
        pattern = str(tmp_path / "s-*.parquet")
        # default raise: the corrupt footer fails the scan (ParquetFileError
        # is a ValueError, so the CLI trap turns it into exit 1)
        assert tool_main(["scan", pattern, "--batch-size", "256"]) == 1
        assert "invalid footer" in capsys.readouterr().err
        assert tool_main(
            ["scan", pattern, "--batch-size", "256", "--on-error", "skip",
             "--json"]
        ) == 0
        captured = capsys.readouterr()
        assert "skipped" in captured.err
        doc = json.loads(captured.out.strip().splitlines()[-1])
        assert doc["rows"] == 1500

    def test_scan_projection_and_prefetch_zero(self, shards, capsys):
        assert tool_main(
            ["scan", shards, "--columns", "a", "--prefetch", "0",
             "--batch-size", "512", "--json"]
        ) == 0
        doc = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
        assert doc["rows"] == 1500 and doc["prefetch"] == 0
        # the synchronous path still measures its decode waits
        assert doc["wait_s"] > 0 and doc["wait_share"] > 0

    def test_scan_reports_io_bytes_and_cache(self, shards, capsys):
        import glob
        import os

        assert tool_main(
            ["scan", shards, "--batch-size", "256", "--cache-mb", "32",
             "--epochs", "2", "--json"]
        ) == 0
        out = capsys.readouterr().out
        assert "scan: io" in out and "of file bytes" in out
        doc = json.loads(out.strip().splitlines()[-1])
        assert doc["rows"] == 3000  # 2 epochs
        assert doc["file_bytes"] == sum(
            os.path.getsize(p) for p in glob.glob(shards)
        )
        assert doc["io_bytes_read"] > 0
        # epoch 2 decodes out of the shared block cache
        assert doc["io_cache_hit_rate"] is not None
        assert doc["io_cache_hit_rate"] > 0

    def test_scan_nullable_data_by_default(self, tmp_path, capsys):
        import numpy as np
        import pyarrow as pa

        rng = np.random.default_rng(0)
        t = pa.table({
            "a": pa.array(rng.standard_normal(400),
                          mask=rng.random(400) < 0.3),
        })
        pq.write_table(t, tmp_path / "n.parquet", row_group_size=200)
        # default --nullable zero: a throughput scan survives nullable data
        assert tool_main(
            ["scan", str(tmp_path / "n.parquet"), "--batch-size", "128",
             "--json"]
        ) == 0
        doc = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
        assert doc["rows"] == 400
        # explicit --nullable error keeps the strict behavior
        assert tool_main(
            ["scan", str(tmp_path / "n.parquet"), "--batch-size", "128",
             "--nullable", "error"]
        ) == 1
        assert "nulls" in capsys.readouterr().err


class TestBenchJson:
    def test_bench_json_round_trips(self, tmp_path):
        """`bench.py --phase prepare --json out.json` writes the structured
        per-stage breakdown; the artifact must round-trip through
        json.load (the BENCH_* trajectory files come from here now)."""
        import os
        import subprocess
        import sys
        from pathlib import Path

        out = tmp_path / "bench.json"
        root = Path(__file__).resolve().parent.parent
        env = dict(
            os.environ,
            JAX_PLATFORMS="cpu",
            PQT_BENCH_ROWS="20000",
            PQT_BENCH_REPEATS="1",
        )
        proc = subprocess.run(
            [
                sys.executable,
                str(root / "bench.py"),
                "--phase",
                "prepare",
                "--json",
                str(out),
            ],
            cwd=str(root),
            env=env,
            stdout=subprocess.PIPE,
            stderr=subprocess.PIPE,
            timeout=280,
        )
        assert proc.returncode == 0, proc.stderr.decode()[-2000:]
        with open(out) as f:
            doc = json.load(f)
        assert "prepare_serial_s" in doc
        assert "stage_ms" in doc
        # stdout keeps the one-line JSON contract too; the FILE artifact
        # additionally carries the run-time config fingerprint the trend
        # store records (bench_config — provenance, not a metric)
        assert doc["bench_config"]["fingerprint"]
        line = [
            x for x in proc.stdout.decode().splitlines() if x.strip().startswith("{")
        ][-1]
        assert json.loads(line) == {
            k: v for k, v in doc.items() if k != "bench_config"
        }


class TestBenchCompare:
    def _run_compare(self, tmp_path, old, new, extra=()):
        import subprocess
        import sys
        from pathlib import Path

        root = Path(__file__).resolve().parent.parent
        a, b = tmp_path / "old.json", tmp_path / "new.json"
        a.write_text(json.dumps(old))
        b.write_text(json.dumps(new))
        return subprocess.run(
            [sys.executable, str(root / "bench.py"), "--compare",
             str(a), str(b), *extra],
            cwd=str(root), capture_output=True, text=True, timeout=60,
        )

    def test_no_regression_exits_zero(self, tmp_path):
        old = {"scan": {"rows_s": 1000.0, "p50_ms": 10.0, "rows": 500}}
        new = {"scan": {"rows_s": 1050.0, "p50_ms": 9.0, "rows": 500}}
        proc = self._run_compare(tmp_path, old, new)
        assert proc.returncode == 0, proc.stdout + proc.stderr
        assert "no tracked regressions" in proc.stdout

    def test_throughput_drop_gates(self, tmp_path):
        old = {"scan": {"rows_s": 1000.0}}
        new = {"scan": {"rows_s": 800.0}}  # -20% past the 10% default
        proc = self._run_compare(tmp_path, old, new)
        assert proc.returncode == 1
        assert "REGRESSION scan.rows_s" in proc.stdout

    def test_latency_rise_gates_and_threshold_overrides(self, tmp_path):
        old = {"serve": {"p50_ms": 10.0}}
        new = {"serve": {"p50_ms": 11.5}}  # +15%
        proc = self._run_compare(tmp_path, old, new)
        assert proc.returncode == 1
        proc = self._run_compare(tmp_path, old, new, ("--threshold", "0.2"))
        assert proc.returncode == 0

    def test_untracked_leaves_never_gate(self, tmp_path):
        old = {"scan": {"rows_s": 100.0, "rows": 100, "prefetch": 2}}
        new = {"scan": {"rows_s": 100.0, "rows": 9, "prefetch": 8}}
        proc = self._run_compare(tmp_path, old, new)  # counts, not metrics
        assert proc.returncode == 0
        assert "untracked changed" in proc.stdout

    def test_disjoint_artifacts_fail_instead_of_green(self, tmp_path):
        # two artifacts with no tracked metric in common compared NOTHING;
        # a CI gate must not pass on that
        old = {"scan": {"rows_s": 100.0}}
        new = {"prepare": {"stage_ms": 5.0, "rows": 3}}
        proc = self._run_compare(tmp_path, old, new)
        assert proc.returncode != 0
        assert "no tracked metrics in common" in proc.stderr
        assert "WARNING tracked metric only in old: scan.rows_s" in proc.stdout

    def test_bad_usage_is_a_clean_message(self, tmp_path):
        import subprocess
        import sys
        from pathlib import Path

        root = Path(__file__).resolve().parent.parent
        a = tmp_path / "x.json"
        a.write_text('{"scan": {"rows_s": 1.0}}')

        def run(*args):
            return subprocess.run(
                [sys.executable, str(root / "bench.py"), "--compare", *args],
                cwd=str(root), capture_output=True, text=True, timeout=60,
            )

        proc = run(str(a), str(a), "--threshold", "abc")
        assert proc.returncode != 0
        assert "Traceback" not in proc.stderr
        assert "--threshold needs a number" in proc.stderr
        # flags-before-paths ordering still resolves the two paths
        proc = run("--threshold", "0.2", str(a), str(a))
        assert proc.returncode == 0, proc.stderr
        # ONE path is now the trend-store form: the old side defaults to
        # the latest recorded round — with no store, a clean typed message
        proc = run(str(a), "--history", str(tmp_path / "missing.jsonl"))
        assert proc.returncode != 0 and "no trend store" in proc.stderr
        proc = run()
        assert proc.returncode != 0 and "needs" in proc.stderr

    def test_matrix_lists_are_gated(self, tmp_path):
        # the full-run artifact stores the 5-config matrix as a LIST;
        # positional flattening must keep it inside the gate
        old = {"matrix": [{"t": 1.0, "vs_baseline": 2.0, "config": 1}]}
        new = {"matrix": [{"t": 2.0, "vs_baseline": 2.0, "config": 1}]}
        proc = self._run_compare(tmp_path, old, new)
        assert proc.returncode == 1
        assert "REGRESSION matrix.0.t" in proc.stdout


class TestQueryPushDown:
    """PR 12's CLI surfaces: `scan --aggregate` (the daemon's canonical
    bytes, locally) and the vectorized residual path behind every filtered
    CLI read — identical row counts under both engines, with the vec mask
    proven ENGAGED."""

    @pytest.fixture
    def shards(self, tmp_path):
        import numpy as np
        import pyarrow as pa

        rng = np.random.default_rng(9)
        for i in range(3):
            t = pa.table(
                {
                    "a": pa.array(rng.integers(0, 100, 500).astype(np.int64)),
                    "b": pa.array(rng.standard_normal(500)),
                    "g": pa.array([f"k{j % 4}" for j in range(500)]),
                }
            )
            pq.write_table(t, tmp_path / f"q-{i}.parquet", row_group_size=200)
        return str(tmp_path / "q-*.parquet")

    def test_filtered_cat_identical_row_counts_across_engines(
        self, shards, capsys, monkeypatch
    ):
        import glob

        from parquet_tpu.utils import metrics

        path = sorted(glob.glob(shards))[0]
        flt = ["--filter", "a >= 50", "--filter", "b > 0"]
        snap = metrics.snapshot()
        assert tool_main(["cat", path, *flt]) == 0
        vec_out = capsys.readouterr().out
        d = metrics.delta(snap)
        # the small fix pinned: residual rows route through the MASK
        # pipeline, not the scalar walker, when buffers are ndarray-backed
        assert d.get('query_rows_filtered_total{engine="vec"}', 0) > 0
        assert not d.get('query_rows_filtered_total{engine="scalar"}', 0)
        monkeypatch.setenv("PQT_VEC_FILTER", "0")
        assert tool_main(["cat", path, *flt]) == 0
        scalar_out = capsys.readouterr().out
        assert vec_out == scalar_out
        assert vec_out.count("\n") == scalar_out.count("\n")

    def test_scan_aggregate_matches_local_twin(self, shards, capsys):
        from parquet_tpu.serve import (
            parse_query_request,
            render_query_body,
            run_local_query,
        )

        spec = '["count", ["sum", "a"], ["max", "b"]]'
        filters = '[["a", ">=", 50]]'
        assert tool_main(
            ["scan", shards, "--aggregate", spec, "--filters", filters,
             "--group-by", "g"]
        ) == 0
        out = capsys.readouterr().out
        q = parse_query_request(
            json.dumps(
                {
                    "paths": [shards],
                    "aggregates": ["count", ["sum", "a"], ["max", "b"]],
                    "filters": [["a", ">=", 50]],
                    "group_by": ["g"],
                }
            ).encode()
        )
        assert out.encode() == render_query_body(run_local_query(q.paths, q))
        doc = json.loads(out)
        assert doc["group_by"] == ["g"] and doc["group_count"] == 4
        assert doc["rows_scanned"] == 1500
        assert sum(
            g["aggregates"]["count"] for g in doc["groups"]
        ) == doc["rows_matched"]

    def test_scan_aggregate_bad_spec_is_clean_error(self, shards, capsys):
        assert tool_main(["scan", shards, "--aggregate", '["median"]']) == 1
        assert "bad_aggregates" in capsys.readouterr().err

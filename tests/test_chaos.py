"""parquet_tpu.testing.chaos + serve brownout: scripted graceful degradation.

Pinned here:
  * FaultSchedule: phase lookup under fake time, last-phase hold, knob
    validation (a typo'd knob fails the script, not silently no-ops);
  * the FlakySource/FlakySink schedule hook is deterministic under fake
    time, and FlakySink has latency_spike parity with FlakySource;
  * ChaosHarness installs the resilience policy scoped to its block and
    restores the previous one (breakers reset on exit);
  * AdmissionController brownout: windowed pqt-serve queue-wait over the
    threshold sheds with typed 503 + Retry-After (counted
    serve_shed_total{reason="queue_wait"}), recovers when the pressure
    passes, and the depth trigger catches a wedged pool;
  * the serve path fast-fails a breaker-dark source as a typed 503
    (serve_shed_total{reason="breaker_open"}), and raw transport faults
    render as typed 503s, never 500 "internal";
  * the slow sweep: the full standard schedule against a live dataset
    under a watchdog — no hang, typed-errors-only (the loop completes),
    no torn batch (every batch internally consistent), faults actually
    injected and quarantined.
"""

from __future__ import annotations

import threading
import time

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from parquet_tpu.io.hedge import resilience_config
from parquet_tpu.io.source import MemorySource
from parquet_tpu.serve.admission import AdmissionController
from parquet_tpu.serve.protocol import ServeError
from parquet_tpu.testing.chaos import (
    ChaosHarness,
    FaultSchedule,
    Phase,
    percentile,
    run_dataset_chaos,
    standard_schedule,
)
from parquet_tpu.testing.flaky import FlakySink, FlakySource
from parquet_tpu.utils import metrics

WATCHDOG_SECONDS = 120.0


def with_watchdog(fn, timeout: float = WATCHDOG_SECONDS):
    out, err = [], []

    def run():
        try:
            out.append(fn())
        except BaseException as e:  # noqa: BLE001 - surfaced below
            err.append(e)

    t = threading.Thread(target=run, daemon=True)
    t.start()
    t.join(timeout)
    if t.is_alive():
        pytest.fail(f"watchdog: chaos still running after {timeout}s (hang)")
    if err:
        raise err[0]
    return out[0]


class TestFaultSchedule:
    def test_phase_lookup_under_fake_time(self):
        s = FaultSchedule([
            Phase("a", 1.0, {}),
            Phase("b", 2.0, {"error_rate": 0.5}),
            Phase("c", 1.0, {"permanent": True}),
        ])
        s.start(100.0)
        assert s.phase_at(100.5).name == "a"
        assert s.phase_at(101.0).name == "b"
        assert s.params_at(102.9) == {"error_rate": 0.5}
        assert s.phase_at(103.5).name == "c"
        # past the end: the LAST phase holds
        assert s.phase_at(1e9).name == "c"
        assert s.total_s == 4.0
        assert not s.done(103.9) and s.done(104.0)

    def test_self_arms_on_first_query(self):
        s = FaultSchedule([Phase("only", 1.0, {})])
        assert not s.started
        s.phase_at(50.0)
        assert s.started
        assert s.elapsed(50.5) == pytest.approx(0.5)

    def test_unknown_knob_rejected(self):
        with pytest.raises(ValueError, match="eror_rate"):
            Phase("typo", 1.0, {"eror_rate": 0.5})

    def test_nonpositive_duration_rejected(self):
        with pytest.raises(ValueError):
            Phase("p", 0.0, {})
        with pytest.raises(ValueError):
            FaultSchedule([])

    def test_standard_schedule_shape(self):
        s = standard_schedule(phase_s=1.0, base={"latency_s": 0.002})
        names = [p.name for p in s.phases]
        assert names == [
            "warmup", "latency_spike", "error_burst", "blackout", "recovery",
        ]
        assert all(p.params.get("latency_s") == 0.002 for p in s.phases)
        assert s.phases[3].params["permanent"] is True


class TestScheduledFlaky:
    def test_source_phases_deterministic_under_fake_time(self):
        t = [0.0]
        sleeps = []
        s = standard_schedule(phase_s=1.0, spike_p=1.0, spike_ms=40.0,
                              error_rate=1.0)
        fs = FlakySource(MemorySource(b"x" * 256), seed=3, schedule=s,
                         clock=lambda: t[0], sleep=sleeps.append)
        fs.read_at(0, 16)  # warmup: clean
        assert fs.faults_injected == 0 and fs.spikes_injected == 0
        t[0] = 1.5  # latency spike: every read stalls 40 ms
        fs.read_at(0, 16)
        assert fs.spikes_injected == 1 and sleeps == [0.04]
        t[0] = 2.5  # error burst
        with pytest.raises(OSError):
            fs.read_at(0, 16)
        t[0] = 3.5  # blackout
        with pytest.raises(OSError):
            fs.read_at(0, 16)
        t[0] = 4.5  # recovery
        assert fs.read_at(0, 16) == b"x" * 16
        # the SAME seed replays the SAME stream
        t[0] = 0.0
        sleeps2 = []
        fs2 = FlakySource(MemorySource(b"x" * 256), seed=3, schedule=(
            standard_schedule(phase_s=1.0, spike_p=1.0, spike_ms=40.0,
                              error_rate=1.0)
        ), clock=lambda: t[0], sleep=sleeps2.append)
        fs2.read_at(0, 16)
        t[0] = 1.5
        fs2.read_at(0, 16)
        assert sleeps2 == [0.04]

    def test_sink_latency_spike_parity(self):
        sleeps = []
        sink = FlakySink.latency_spike(
            _NullSink(), seed=1, p=1.0, ms=25.0, sleep=sleeps.append
        )
        sink.write(b"abc")
        assert sink.spikes_injected == 1 and sleeps == [0.025]

    def test_sink_schedule_hook(self):
        t = [0.0]
        s = FaultSchedule([
            Phase("ok", 1.0, {}),
            Phase("dark", 1.0, {"permanent": True}),
            Phase("flushy", 1.0, {"flush_error_rate": 1.0}),
        ])
        sink = FlakySink(_NullSink(), seed=2, schedule=s,
                         clock=lambda: t[0], sleep=lambda x: None)
        assert sink.write(b"abc") == 3
        sink.flush()
        t[0] = 1.5
        with pytest.raises(OSError):
            sink.write(b"abc")
        t[0] = 2.5
        sink.write(b"abc")  # write knobs clean again
        with pytest.raises(OSError):
            sink.flush()


class _NullSink:
    sink_id = "null"

    def __init__(self):
        self._n = 0

    def write(self, data):
        self._n += len(data)
        return len(data)

    def tell(self):
        return self._n

    def flush(self):
        pass

    def close(self):
        pass

    def abort(self):
        pass


class TestChaosHarness:
    def test_policy_scoped_to_block(self):
        assert not resilience_config().active
        sched = FaultSchedule([Phase("p", 1.0, {})])
        with ChaosHarness(sched, seed=1, retry=True) as chaos:
            cfg = resilience_config()
            assert cfg.active and cfg.retry
            assert cfg.chaos_wrapper == chaos.wrap
        assert not resilience_config().active

    def test_wrap_seeds_vary_by_open_ordinal(self):
        sched = FaultSchedule([Phase("p", 1.0, {"error_rate": 0.5})])
        with ChaosHarness(sched, seed=1) as chaos:
            a = chaos.wrap(MemorySource(b"x" * 64, source_id="m:1"))
            b = chaos.wrap(MemorySource(b"x" * 64, source_id="m:1"))
            c = chaos.wrap(MemorySource(b"x" * 64, source_id="m:2"))
            seeds = set()
            for fs in (a, b, c):
                seeds.add(fs._rng.bit_generator.seed_seq.entropy)
            assert len(seeds) == 3  # same id twice still differs (ordinal)

    def test_percentile(self):
        assert percentile([], 0.5) is None
        assert percentile([3.0], 0.99) == 3.0
        vals = list(range(100))
        assert percentile(vals, 0.5) == 50
        assert percentile(vals, 0.99) == 99


N_FILES = 3
ROWS = 1200
ROW_GROUP = 200


@pytest.fixture(scope="module")
def pattern(tmp_path_factory):
    d = tmp_path_factory.mktemp("chaos_shards")
    rng = np.random.default_rng(5)
    for i in range(N_FILES):
        t = pa.table(
            {"x": pa.array(rng.integers(0, 1 << 40, ROWS).astype(np.int64))}
        )
        pq.write_table(t, str(d / f"s-{i:02d}.parquet"), row_group_size=ROW_GROUP)
    return str(d / "s-*.parquet")


class TestDatasetChaos:
    def test_fast_schedule_smoke(self, pattern):
        """A compressed schedule against a real dataset: completes under
        the watchdog, injects real faults, quarantines typed-only, and the
        report carries every phase."""
        sched = standard_schedule(phase_s=0.25, error_rate=0.5)

        def run():
            with ChaosHarness(
                sched, seed=11, breaker=True, retry=True,
                retry_kw={"attempts": 2, "base_delay_s": 0.0005,
                          "max_delay_s": 0.002},
                breaker_kw={"failure_threshold": 4, "open_s": 0.2},
            ) as chaos:
                return run_dataset_chaos(
                    pattern, chaos=chaos, batch_size=256, slo_wait_ms=500.0,
                    prefetch=2,
                )

        rep = with_watchdog(run, 60.0)
        assert set(rep["phases"]) >= {
            "warmup", "latency_spike", "error_burst", "blackout", "recovery",
        }
        assert rep["batches"] > 0 and rep["rows"] > 0
        assert rep["faults_injected"] > 0
        assert rep["units_skipped"] > 0  # the blackout quarantined typed-only
        assert rep["controller"] is not None
        # chaos never leaks: the policy is gone, fresh reads are clean
        assert not resilience_config().active


class TestBrownout:
    def _admission(self, **kw):
        reg = metrics.MetricsRegistry()
        t = [0.0]
        kw.setdefault("brownout_wait_s", 0.05)
        kw.setdefault("brownout_window_s", 1.0)
        adm = AdmissionController(
            clock=lambda: t[0], registry=reg, **kw
        )
        return adm, reg, t

    def test_sheds_on_queue_wait_and_recovers(self):
        adm, reg, t = self._admission()
        before = metrics.get("serve_shed_total", reason="queue_wait")
        adm.admit("a").release()  # primes the window
        for _ in range(10):
            reg.observe("pool_queue_wait_seconds", 0.2, pool="pqt-serve")
        t[0] = 1.5
        with pytest.raises(ServeError) as ei:
            adm.admit("a")
        assert ei.value.status == 503 and ei.value.code == "brownout"
        assert ei.value.retry_after_s >= 1
        assert metrics.get("serve_shed_total", reason="queue_wait") - before == 1
        # pressure passes: the next window clears the brownout
        for _ in range(50):
            reg.observe("pool_queue_wait_seconds", 0.001, pool="pqt-serve")
        t[0] = 3.0
        adm.admit("a").release()

    def test_wait_in_other_pools_never_sheds(self):
        adm, reg, t = self._admission()
        adm.admit("a").release()
        for _ in range(10):
            reg.observe("pool_queue_wait_seconds", 5.0, pool="pqt-data")
        t[0] = 1.5
        adm.admit("a").release()  # pqt-data pressure is not serve pressure

    def test_depth_trigger_catches_wedged_pool(self):
        """A fully wedged pool produces NO new wait observations — the
        depth gauge is the only signal left."""
        adm, reg, t = self._admission(brownout_depth=4)
        adm.admit("a").release()
        reg.set("pool_queue_depth", 9, pool="pqt-serve")
        with pytest.raises(ServeError) as ei:
            adm.admit("a")
        assert ei.value.code == "brownout"
        reg.set("pool_queue_depth", 0, pool="pqt-serve")
        adm.admit("a").release()

    def test_disabled_by_default(self):
        reg = metrics.MetricsRegistry()
        adm = AdmissionController(registry=reg)
        for _ in range(10):
            reg.observe("pool_queue_wait_seconds", 9.0, pool="pqt-serve")
        adm.admit("a").release()

    def test_validation(self):
        with pytest.raises(ValueError):
            AdmissionController(brownout_wait_s=0)
        with pytest.raises(ValueError):
            AdmissionController(brownout_depth=-1)

    def test_serve_config_plumbs_brownout(self):
        from parquet_tpu.serve.server import ServeConfig

        with pytest.raises(ValueError):
            ServeConfig(brownout_wait_ms=-5)
        cfg = ServeConfig(port=0, brownout_wait_ms=250.0, brownout_depth=7)
        from parquet_tpu.serve.server import ScanService

        svc = ScanService(cfg)
        assert svc.admission.brownout_wait_s == pytest.approx(0.25)
        assert svc.admission.brownout_depth == 7


class TestServeBreakerFastFail:
    def test_dark_source_scan_is_typed_503(self, pattern, tmp_path):
        """A scan whose source's breaker is OPEN fails the request fast
        with a typed 503 source_unavailable (counted as a breaker shed) —
        not a 500, not a deadline burn."""
        import glob as _glob

        from parquet_tpu.io import BreakerSource, CircuitBreaker, LocalFileSource
        from parquet_tpu.serve.protocol import parse_scan_request
        from parquet_tpu.serve.server import ScanService, ServeConfig

        root = str(_glob.glob(pattern)[0]).rsplit("/", 1)[0]
        breaker = CircuitBreaker("dark", failure_threshold=1, open_s=600.0)
        breaker.record_failure()  # pre-tripped: the source is KNOWN dark

        def factory(p):
            return BreakerSource(LocalFileSource(p), breaker)

        svc = ScanService(
            ServeConfig(port=0, root=root, cache_mb=0, source_factory=factory)
        )
        req = parse_scan_request(
            b'{"paths": ["s-00.parquet"], "format": "jsonl"}'
        )
        before = metrics.get("serve_shed_total", reason="breaker_open")
        t0 = time.perf_counter()
        ticket, _ct, chunks = svc.scan(req, "t")
        with ticket:
            with pytest.raises(ServeError) as ei:
                next(chunks)
            chunks.close()
        elapsed = time.perf_counter() - t0
        assert ei.value.status == 503
        assert ei.value.code == "source_unavailable"
        assert ei.value.retry_after_s
        assert metrics.get("serve_shed_total", reason="breaker_open") > before
        assert elapsed < 2.0  # fast fail, not a deadline burn

    def test_raw_transport_fault_is_typed_503(self, pattern):
        """An un-breakered EIO from the source renders as a typed 503
        source_error (the daemon's environment, not a server bug)."""
        import glob as _glob

        from parquet_tpu.io import LocalFileSource
        from parquet_tpu.serve.protocol import parse_scan_request
        from parquet_tpu.serve.server import ScanService, ServeConfig

        root = str(_glob.glob(pattern)[0]).rsplit("/", 1)[0]

        def factory(p):
            return FlakySource(LocalFileSource(p), seed=1, permanent=True)

        svc = ScanService(
            ServeConfig(port=0, root=root, cache_mb=0, source_factory=factory)
        )
        req = parse_scan_request(
            b'{"paths": ["s-00.parquet"], "format": "jsonl"}'
        )
        ticket, _ct, chunks = svc.scan(req, "t")
        with ticket:
            with pytest.raises(ServeError) as ei:
                next(chunks)
            chunks.close()
        assert ei.value.status == 503 and ei.value.code == "source_error"


@pytest.mark.slow
class TestChaosSweepSlow:
    def test_full_schedule_no_hang_no_torn_stream_typed_only(self, pattern):
        """The extended sweep: a full-severity standard schedule, real
        sleeps, hedging + breakers + retries + controller all on, under a
        watchdog. The contract: the loop COMPLETES (typed-errors-only —
        any raw fault escaping the skip policy would raise), every batch
        is internally consistent (no torn batch), the controller moved,
        and real faults were injected and absorbed."""
        sched = standard_schedule(
            phase_s=1.0, spike_p=0.5, spike_ms=60.0, error_rate=0.4,
            base={"latency_s": 0.001},
        )

        def run():
            with ChaosHarness(
                sched, seed=29, breaker=True, retry=True, hedge=True,
                retry_kw={"attempts": 3, "base_delay_s": 0.001,
                          "max_delay_s": 0.01},
                breaker_kw={"failure_threshold": 5, "open_s": 0.5},
                hedge_kw={"min_delay_s": 0.005, "initial_delay_s": 0.02},
            ) as chaos:
                return run_dataset_chaos(
                    pattern, chaos=chaos, batch_size=200, slo_wait_ms=200.0,
                    prefetch=1, step_s=0.002,
                )

        rep = with_watchdog(run, WATCHDOG_SECONDS)
        # no hang (watchdog), typed-only (no raise), and the schedule
        # actually bit: faults injected, blackout units quarantined
        assert rep["faults_injected"] > 0
        assert rep["spikes_injected"] > 0
        assert rep["units_skipped"] > 0
        assert rep["batches"] > 0
        # no torn batch: the consumer counted rows off every delivered
        # batch — a torn/misaligned batch raises inside the dataset's
        # column-consistency checks and would have failed the run; the
        # count itself must be full batches (tails only at epoch edges)
        assert rep["rows"] >= rep["batches"] * 1
        assert rep["controller"]["ticks"] > 0

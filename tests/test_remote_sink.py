"""parquet_tpu.io.remote_sink tests: HttpSink's multipart protocol over
real loopback HTTP (testing/httpstub.py writable mode), the LocalFileSink
atomicity contract ported to object stores, the typed write-failure
taxonomy, request signing end to end, and the issue's acceptance pins:

  * a full FileWriter("https://...") -> FileReader(url) round trip over
    the stub, signed and unsigned;
  * ZERO torn objects: across every fault schedule, no object is visible
    before complete-multipart and none after abort — anything visible is
    the complete committed bytes;
  * the signed-mode stub rejects EVERY unsigned request while the same
    round trip passes with credentials.

The extended seed x fault write sweep runs under `slow` (`make fuzz`); a
seeded fast subset rides tier-1 (and `make remote-write-smoke`)."""

import numpy as np
import pytest

from parquet_tpu.core.reader import FileReader
from parquet_tpu.core.writer import FileWriter, WriterError
from parquet_tpu.io import (
    HttpSink,
    ObjectStoreSink,
    SigV4Signer,
    TransientSourceError,
    clear_signers,
    configure_signer,
)
from parquet_tpu.io.source import SourceError
from parquet_tpu.meta.parquet_types import Type
from parquet_tpu.schema.builder import message, optional, required, string
from parquet_tpu.sink.sink import SinkError, open_sink
from parquet_tpu.testing.httpstub import RangeHttpStub
from parquet_tpu.utils import metrics

NOSLEEP = lambda s: None
PART = 1 << 15  # 32 KiB parts force real multipart on the 128 KiB blob
CREDS = {"AK-test": "s3cr3t"}


def pinned_signer():
    return SigV4Signer("AK-test", CREDS["AK-test"])


@pytest.fixture(scope="module")
def blob():
    return (
        np.random.default_rng(29)
        .integers(0, 256, 1 << 17)
        .astype(np.uint8)
        .tobytes()
    )


@pytest.fixture(autouse=True)
def _no_signer_leak():
    yield
    clear_signers()


def stream(sink, data, chunk=1 << 14):
    for i in range(0, len(data), chunk):
        sink.write(data[i : i + chunk])


class TestAtomicityContract:
    def test_single_shot_put_byte_identical(self, blob):
        with RangeHttpStub(writable=True) as stub:
            with HttpSink(stub.url_for("one.bin"), sleep=NOSLEEP) as s:
                stream(s, blob)
                # nothing is visible before close() commits
                assert not stub.has_object("one.bin")
            assert stub.object_bytes("one.bin") == blob
            assert stub.objects_put == 1  # single-shot: no multipart dance

    def test_multipart_byte_identical_and_invisible_until_commit(self, blob):
        before = metrics.snapshot()
        with RangeHttpStub(writable=True) as stub:
            with HttpSink(
                stub.url_for("mp.bin"), part_bytes=PART, sleep=NOSLEEP
            ) as s:
                stream(s, blob)
                assert s.tell() == len(blob)
                # parts are in flight / stored, the OBJECT does not exist
                assert not stub.has_object("mp.bin")
            assert stub.object_bytes("mp.bin") == blob
            assert stub.uploads_completed == 1
            assert stub.live_uploads() == 0
        d = metrics.delta(before)
        assert d.get("sink_multipart_initiated_total") == 1
        assert d.get("sink_multipart_completed_total") == 1
        assert d.get("io_put_bytes_total") == len(blob)

    def test_abort_leaves_nothing_and_is_idempotent(self, blob):
        with RangeHttpStub(writable=True) as stub:
            s = HttpSink(stub.url_for("ab.bin"), part_bytes=PART, sleep=NOSLEEP)
            stream(s, blob)
            s.abort()
            s.abort()  # idempotent
            assert not stub.has_object("ab.bin")
            assert stub.live_uploads() == 0
            with pytest.raises(SinkError) as ei:
                s.write(b"more")
            assert ei.value.code == "sink_closed"

    def test_context_manager_exception_aborts(self, blob):
        with RangeHttpStub(writable=True) as stub:
            with pytest.raises(RuntimeError):
                with HttpSink(
                    stub.url_for("cm.bin"), part_bytes=PART, sleep=NOSLEEP
                ) as s:
                    stream(s, blob)
                    raise RuntimeError("caller blew up mid-write")
            assert not stub.has_object("cm.bin")
            assert stub.live_uploads() == 0

    def test_abort_after_close_never_destroys_committed_output(self, blob):
        with RangeHttpStub(writable=True) as stub:
            s = HttpSink(stub.url_for("keep.bin"), part_bytes=PART, sleep=NOSLEEP)
            stream(s, blob)
            s.close()
            s.abort()  # safe after close by contract
            assert stub.object_bytes("keep.bin") == blob

    def test_flush_is_a_no_op_not_a_part_seal(self, blob):
        with RangeHttpStub(writable=True) as stub:
            with HttpSink(stub.url_for("f.bin"), sleep=NOSLEEP) as s:
                s.write(b"abc")
                s.flush()
                assert stub.put_requests == 0  # nothing went over the wire
            assert stub.object_bytes("f.bin") == b"abc"

    def test_url_coercion_through_open_sink(self, blob):
        with RangeHttpStub(writable=True) as stub:
            sink, owned = open_sink(stub.url_for("oc.bin"))
            assert isinstance(sink, HttpSink) and owned
            with sink:
                sink.write(blob)
            assert stub.object_bytes("oc.bin") == blob

    def test_constructor_rejects_bad_urls(self):
        with pytest.raises(ValueError):
            HttpSink("ftp://x/y")
        with pytest.raises(ValueError):
            HttpSink("http://h/k?versionId=7")  # query is protocol-reserved
        with pytest.raises(ValueError):
            HttpSink("http://h/k", part_bytes=16)  # below the part floor


class TestFailureTaxonomy:
    def test_transient_burst_is_absorbed(self, blob):
        with RangeHttpStub(
            writable=True, seed=5, error_rate=0.3
        ) as stub:
            with HttpSink(
                stub.url_for("e.bin"),
                part_bytes=PART,
                attempts=6,
                sleep=NOSLEEP,
            ) as s:
                stream(s, blob)
            assert stub.object_bytes("e.bin") == blob
            assert stub.faults_injected > 0

    def test_terminal_4xx_latches_and_aborts(self, blob):
        # a read-only stub answers every write 405: terminal on attempt 1
        with RangeHttpStub(writable=False, files={"x": b"r"}) as stub:
            s = HttpSink(stub.url_for("t.bin"), sleep=NOSLEEP)
            s.write(b"data")
            with pytest.raises(SinkError) as ei:
                s.close()
            assert ei.value.code == "http_405"
            assert not stub.has_object("t.bin")

    def test_blackout_exhausts_the_ladder_typed(self, blob):
        with RangeHttpStub(writable=True, permanent=True) as stub:
            s = HttpSink(
                stub.url_for("b.bin"), part_bytes=PART, attempts=3, sleep=NOSLEEP
            )
            with pytest.raises((SinkError, SourceError)) as ei:
                stream(s, blob)
                s.close()
            assert getattr(ei.value, "code", None) in (
                "put_retry_exhausted",
                "put_failed",
                "breaker_open",
            )
            s.abort()
            assert not stub.has_object("b.bin")

    def test_commit_500_is_retried_to_success(self, blob):
        with RangeHttpStub(
            writable=True, seed=1, complete_error_rate=1.0
        ) as stub:

            def heal(_):  # the sink's backoff sleep flips the fault off
                stub.complete_error_rate = 0.0

            with HttpSink(
                stub.url_for("c.bin"), part_bytes=PART, sleep=heal
            ) as s:
                stream(s, blob)
            assert stub.object_bytes("c.bin") == blob

    def test_permanent_commit_fault_leaves_no_object(self, blob):
        with RangeHttpStub(
            writable=True, seed=2, complete_error_rate=1.0
        ) as stub:
            s = HttpSink(
                stub.url_for("pc.bin"), part_bytes=PART, attempts=3, sleep=NOSLEEP
            )
            stream(s, blob)
            with pytest.raises(SinkError) as ei:
                s.close()
            assert ei.value.code == "put_retry_exhausted"
            # close() auto-aborted: the upload is gone, nothing visible
            assert not stub.has_object("pc.bin")
            assert stub.live_uploads() == 0

    def test_ambiguous_acks_are_idempotent(self, blob):
        # acks drop AFTER the state change: every retry must land in the
        # same slot (parts by number, complete by replay map, PUT by name)
        with RangeHttpStub(
            writable=True, seed=3, ack_drop_rate=0.4
        ) as stub:
            with HttpSink(
                stub.url_for("aa.bin"),
                part_bytes=PART,
                attempts=8,
                sleep=NOSLEEP,
            ) as s:
                stream(s, blob)
            assert stub.object_bytes("aa.bin") == blob

    def test_corrupt_part_etag_is_never_trusted(self, blob):
        # the store acks success but its CRC disagrees with what we sent:
        # a torn transfer shaped like success must NOT commit
        with RangeHttpStub(writable=True, corrupt_part_etag=True) as stub:
            s = HttpSink(
                stub.url_for("ce.bin"), part_bytes=PART, attempts=2, sleep=NOSLEEP
            )
            with pytest.raises(SinkError) as ei:
                stream(s, blob)
                s.close()
            assert ei.value.code in ("put_retry_exhausted", "put_failed")
            s.abort()
            assert not stub.has_object("ce.bin")
            retries = metrics.snapshot()
            assert any("part_etag_mismatch" in k for k in retries)


class TestSignedMode:
    def test_unsigned_write_is_rejected_with_403(self, blob):
        with RangeHttpStub(writable=True, credentials=CREDS) as stub:
            s = HttpSink(stub.url_for("u.bin"), sleep=NOSLEEP)
            s.write(b"data")
            with pytest.raises(SinkError) as ei:
                s.close()
            assert ei.value.code == "http_403"
            assert stub.auth_rejects > 0
            assert not stub.has_object("u.bin")

    def test_signed_multipart_roundtrip_zero_rejects(self, blob):
        with RangeHttpStub(writable=True, credentials=CREDS) as stub:
            with HttpSink(
                stub.url_for("s.bin"),
                part_bytes=PART,
                signer=pinned_signer(),
                sleep=NOSLEEP,
            ) as s:
                stream(s, blob)
            assert stub.object_bytes("s.bin") == blob
            assert stub.auth_rejects == 0

    def test_object_store_sink_requires_a_signer(self):
        with pytest.raises(ValueError):
            ObjectStoreSink("http://h/k")
        configure_signer(pinned_signer(), prefix="http://h/")
        ObjectStoreSink("http://h/k")  # registry satisfies the requirement

    def test_registry_signs_bare_open_sink_coercion(self, blob):
        with RangeHttpStub(writable=True, credentials=CREDS) as stub:
            configure_signer(pinned_signer(), prefix=stub.base_url)
            sink, _ = open_sink(stub.url_for("r.bin"))
            with sink:
                stream(sink, blob)
            assert stub.object_bytes("r.bin") == blob
            assert stub.auth_rejects == 0


SCHEMA = message(
    required("id", Type.INT64),
    optional("name", string()),
    optional("score", Type.DOUBLE),
)
ROWS = [
    {"id": i, "name": f"n{i % 97}", "score": float(i) * 0.5}
    for i in range(20_000)
]


class TestWriterIntegration:
    def test_filewriter_url_roundtrip(self):
        # the acceptance pin: FileWriter straight at a URL, FileReader
        # straight back off it, both through bare coercion
        with RangeHttpStub(writable=True) as stub:
            url = stub.url_for("t.parquet")
            with FileWriter(url, SCHEMA, row_group_size=4096) as w:
                w.write_rows(ROWS)
            with FileReader(url) as r:
                assert list(r.iter_rows()) == ROWS

    def test_signed_filewriter_roundtrip(self):
        # signed WRITES and signed READS through one registry entry — the
        # stub rejects anything unsigned, so a pass proves every request
        # carried a verifying signature
        with RangeHttpStub(writable=True, credentials=CREDS) as stub:
            configure_signer(pinned_signer(), prefix=stub.base_url)
            url = stub.url_for("signed.parquet")
            with FileWriter(url, SCHEMA, row_group_size=4096) as w:
                w.write_rows(ROWS)
            with FileReader(url) as r:
                assert list(r.iter_rows()) == ROWS
            assert stub.auth_rejects == 0

    def test_writer_blackout_auto_aborts_no_torn_object(self):
        with RangeHttpStub(writable=True) as stub:
            url = stub.url_for("dead.parquet")
            w = FileWriter(
                HttpSink(url, part_bytes=PART, attempts=2, sleep=NOSLEEP),
                SCHEMA,
                row_group_size=2048,
            )
            stub.permanent = True  # the store goes dark mid-write
            with pytest.raises((WriterError, OSError)):
                w.write_rows(ROWS)
                w.close()
            assert not stub.has_object("dead.parquet")

    def test_merge_to_url_and_abort_on_failure(self, tmp_path):
        from parquet_tpu.core.merge import merge_files

        a, b = str(tmp_path / "a.parquet"), str(tmp_path / "b.parquet")
        for path, lo in ((a, 0), (b, 1000)):
            with FileWriter(path, SCHEMA) as w:
                w.write_rows(ROWS[lo : lo + 1000])
        with RangeHttpStub(writable=True) as stub:
            url = stub.url_for("m.parquet")
            merge_files(url, [a, b])
            with FileReader(url) as r:
                assert list(r.iter_rows()) == ROWS[:2000]
            # a failing merge must abort the remote upload, not publish a
            # partial object: file b2 has a different schema
            b2 = str(tmp_path / "b2.parquet")
            other = message(required("other", Type.INT32))
            with FileWriter(b2, other) as w:
                w.write_rows([{"other": 1}])
            url2 = stub.url_for("bad.parquet")
            with pytest.raises(Exception):
                merge_files(url2, [a, b2])
            assert not stub.has_object("bad.parquet")
            assert stub.live_uploads() == 0


class TestScheduleOverlay:
    def test_error_burst_then_recovery_commits_identical(self, blob):
        # a FaultSchedule drives the stub: every write op 503s during the
        # burst; the sink's backoff ladder advances the SAME fake clock,
        # so the retries deterministically walk into recovery and commit
        from parquet_tpu.testing.chaos import FaultSchedule, Phase

        t = [0.0]
        sched = FaultSchedule(
            [Phase("burst", 0.5, {"error_rate": 1.0}), Phase("recovery", 1.0)]
        )

        def advance(s):
            t[0] += s

        with RangeHttpStub(
            writable=True, schedule=sched, clock=lambda: t[0]
        ) as stub:
            with HttpSink(
                stub.url_for("sch.bin"),
                part_bytes=PART,
                attempts=8,
                sleep=advance,
            ) as s:
                stream(s, blob)
            assert stub.object_bytes("sch.bin") == blob
            assert stub.faults_injected > 0

    def test_flaky_sink_overlay_composes(self, blob):
        # FlakySink wraps the remote sink exactly like a local one: its
        # injected EIO surfaces before bytes reach the store, and the
        # wrapper's abort propagates — no torn object either way
        from parquet_tpu.testing.flaky import FlakySink

        with RangeHttpStub(writable=True) as stub:
            inner = HttpSink(
                stub.url_for("fk.bin"), part_bytes=PART, sleep=NOSLEEP
            )
            flaky = FlakySink(inner, seed=7, error_rate=1.0)
            with pytest.raises(OSError):
                flaky.write(blob[:PART])
            inner.abort()
            assert not stub.has_object("fk.bin")
            assert flaky.faults_injected == 1


class TestChaosWriteSweep:
    """Seeded write sweep mirroring test_remote.py's read sweep: every
    write of a faulty remote either commits BYTE-IDENTICAL or raises a
    TYPED error — and in both cases, zero torn objects: anything visible
    is the complete committed bytes. The fast subset rides tier-1; the
    extended seed matrix runs under `slow`."""

    FAST = [
        (1, {"error_rate": 0.3}),
        (2, {"ack_drop_rate": 0.3}),
        (3, {"error_rate": 0.2, "drop_rate": 0.2, "complete_error_rate": 0.3}),
    ]
    SLOW = [
        (seed, faults)
        for seed in (7, 11, 13, 17)
        for faults in (
            {"error_rate": 0.4},
            {"drop_rate": 0.4},
            {"ack_drop_rate": 0.5},
            {"complete_error_rate": 0.6},
            {
                "error_rate": 0.25,
                "drop_rate": 0.15,
                "ack_drop_rate": 0.25,
                "complete_error_rate": 0.25,
            },
            {"permanent": True},
        )
    ]

    def _sweep_one(self, blob, seed, faults):
        with RangeHttpStub(writable=True, seed=seed, **faults) as stub:
            sink = HttpSink(
                stub.url_for("out.bin"),
                part_bytes=PART,
                attempts=6,
                sleep=NOSLEEP,
            )
            try:
                with sink:
                    stream(sink, blob)
            except (SinkError, SourceError, TransientSourceError):
                verdict = "typed"
            else:
                verdict = "identical"
                assert stub.object_bytes("out.bin") == blob
            # the zero-torn pin, unconditionally: an object either does
            # not exist or is the COMPLETE committed bytes (a typed
            # failure may still have committed if only the final ack was
            # lost — ambiguous, but never torn)
            if stub.has_object("out.bin"):
                assert stub.object_bytes("out.bin") == blob
            return verdict

    @pytest.mark.parametrize("seed,faults", FAST)
    def test_fast_subset(self, blob, seed, faults):
        assert self._sweep_one(blob, seed, faults) in ("typed", "identical")

    @pytest.mark.slow
    @pytest.mark.parametrize("seed,faults", SLOW)
    def test_extended_sweep(self, blob, seed, faults):
        verdict = self._sweep_one(blob, seed, faults)
        if faults.get("permanent"):
            assert verdict == "typed"
        else:
            assert verdict in ("typed", "identical")

"""parquet_tpu.obs: the operator-facing observability layer's contracts.

Pinned here:
  * structured logging: silent-by-default library discipline, JSON-lines
    shape, request-id/tenant context injection (including across pool
    workers), per-event-key token-bucket rate limiting with an exact
    `suppressed` carry, and the always-on log_events_total /
    log_suppressed_total counters;
  * pool visibility: instrumented_submit's queue-depth/active gauges
    return to zero, its wait/task histograms observe per pool label, the
    queue wait is credited to the submitting request's trace as the
    pool.wait stage, and cancelled futures release their depth;
  * flight recorder unit contracts: id sanitization, config validation,
    ring/index/trace bounds, the deterministic accumulator sampler, and
    always-kept traces for errored/slow requests.

The HTTP-level debug endpoints and the eviction-under-hammer stress live
in tests/test_serve.py next to the daemon they exercise.
"""

from __future__ import annotations

import io
import json
import logging
import threading
import time
from concurrent.futures import ThreadPoolExecutor

import pytest

from parquet_tpu.obs.log import (
    LOGGER_NAME,
    JsonLinesFormatter,
    TokenBucketLimiter,
    configure_logging,
    log_context,
    log_event,
    set_limiter,
)
from parquet_tpu.obs.pool import instrumented_submit, pool_depths
from parquet_tpu.obs.recorder import (
    FlightRecorder,
    ObsConfig,
    sanitize_request_id,
)
from parquet_tpu.utils import metrics
from parquet_tpu.utils.trace import decode_trace

WATCHDOG_S = 30.0


@pytest.fixture()
def wide_open_limiter():
    """A limiter that admits everything (tests that pin line content must
    not race the process-wide bucket other tests drained)."""
    prev = set_limiter(TokenBucketLimiter(rate=1e9, burst=10**6))
    yield
    set_limiter(prev)


@pytest.fixture()
def log_capture(wide_open_limiter):
    """configure_logging into a StringIO; detach after."""
    buf = io.StringIO()
    handler = configure_logging(stream=buf)
    yield buf
    logging.getLogger(LOGGER_NAME).removeHandler(handler)


# -- structured logging --------------------------------------------------------


class TestSilentByDefault:
    def test_library_logger_never_propagates(self):
        logger = logging.getLogger(LOGGER_NAME)
        assert logger.propagate is False
        assert any(
            isinstance(h, logging.NullHandler) for h in logger.handlers
        )

    def test_configure_twice_does_not_stack_handlers(self):
        logger = logging.getLogger(LOGGER_NAME)
        h1 = configure_logging(stream=io.StringIO())
        h2 = configure_logging(stream=io.StringIO())
        try:
            obs = [
                h for h in logger.handlers
                if getattr(h, "_pqt_obs_handler", False)
            ]
            assert obs == [h2]  # h1 was replaced, not stacked
        finally:
            for h in (h1, h2):
                logger.removeHandler(h)


class TestJsonLines:
    def test_line_shape_and_fields(self, log_capture):
        admitted = log_event(
            "pqt_test_shape", level="warning", file="a.parquet", group=3
        )
        assert admitted
        doc = json.loads(log_capture.getvalue())
        assert doc["event"] == "pqt_test_shape"
        assert doc["level"] == "warning"
        assert doc["file"] == "a.parquet" and doc["group"] == 3
        assert doc["ts"].endswith("Z")
        assert "request_id" not in doc  # no context bound

    def test_context_injection(self, log_capture):
        with log_context(request_id="r42", tenant="acme"):
            log_event("pqt_test_ctx")
        doc = json.loads(log_capture.getvalue())
        assert doc["request_id"] == "r42" and doc["tenant"] == "acme"
        # and the binding does not leak past the block
        log_capture.truncate(0)
        log_capture.seek(0)
        log_event("pqt_test_ctx_after")
        assert "request_id" not in json.loads(log_capture.getvalue())

    def test_context_carries_into_pool_workers(self, log_capture):
        with ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="pqt-test-log"
        ) as pool:
            with log_context(request_id="r-pool", tenant="t"):
                fut = instrumented_submit(
                    pool, log_event, "pqt_test_pool_ctx", pool="pqt-test-log"
                )
            fut.result(timeout=WATCHDOG_S)
        [line] = [
            ln for ln in log_capture.getvalue().splitlines()
            if "pqt_test_pool_ctx" in ln
        ]
        assert json.loads(line)["request_id"] == "r-pool"

    def test_unserializable_field_renders_via_str(self, log_capture):
        log_event("pqt_test_unser", blob=object())  # must not raise
        doc = json.loads(log_capture.getvalue())
        assert "object object at" in doc["blob"]

    def test_reserved_keys_win_over_fields(self, log_capture):
        log_event("pqt_test_reserved", ts="fake", extra=1)
        doc = json.loads(log_capture.getvalue())
        assert doc["ts"] != "fake" and doc["extra"] == 1

    def test_formatter_without_obs_extras(self):
        # a foreign record routed through the formatter still renders
        rec = logging.LogRecord(
            "x", logging.INFO, __file__, 1, "plain message", None, None
        )
        doc = json.loads(JsonLinesFormatter().format(rec))
        assert doc["event"] == "plain message"


class TestRateLimiting:
    def test_token_bucket_admits_burst_then_suppresses(self):
        t = [0.0]
        lim = TokenBucketLimiter(rate=1.0, burst=3, clock=lambda: t[0])
        assert [lim.admit("k")[0] for _ in range(5)] == [
            True, True, True, False, False,
        ]
        # refill: one token per second; the admitted line carries the
        # exact count the limiter absorbed
        t[0] = 1.0
        admitted, suppressed = lim.admit("k")
        assert admitted and suppressed == 2

    def test_keys_are_independent(self):
        t = [0.0]
        lim = TokenBucketLimiter(rate=1.0, burst=1, clock=lambda: t[0])
        assert lim.admit("a") == (True, 0)
        assert lim.admit("a") == (False, 1)
        assert lim.admit("b") == (True, 0)

    def test_bad_limiter_config_rejected(self):
        with pytest.raises(ValueError):
            TokenBucketLimiter(rate=0)
        with pytest.raises(ValueError):
            TokenBucketLimiter(burst=0)

    def test_log_event_counts_both_ways(self):
        t = [0.0]
        prev = set_limiter(
            TokenBucketLimiter(rate=1.0, burst=2, clock=lambda: t[0])
        )
        try:
            e0 = metrics.get("log_events_total", event="pqt_test_rl")
            s0 = metrics.get("log_suppressed_total", event="pqt_test_rl")
            results = [log_event("pqt_test_rl") for _ in range(5)]
            assert results == [True, True, False, False, False]
            assert metrics.get("log_events_total", event="pqt_test_rl") == e0 + 2
            assert (
                metrics.get("log_suppressed_total", event="pqt_test_rl")
                == s0 + 3
            )
        finally:
            set_limiter(prev)

    def test_suppressed_count_rides_next_admitted_line(self, log_capture):
        t = [0.0]
        prev = set_limiter(
            TokenBucketLimiter(rate=1.0, burst=1, clock=lambda: t[0])
        )
        try:
            for _ in range(4):
                log_event("pqt_test_gap")
            t[0] = 1.0
            log_event("pqt_test_gap")
        finally:
            set_limiter(prev)
        lines = [json.loads(ln) for ln in log_capture.getvalue().splitlines()]
        assert len(lines) == 2  # burst line + the post-refill line
        assert "suppressed" not in lines[0]
        assert lines[1]["suppressed"] == 3


# -- pool visibility -----------------------------------------------------------


class TestPoolGauges:
    def test_gauges_rise_and_return_to_zero(self):
        gate = threading.Event()
        started = threading.Event()

        def task():
            started.set()
            assert gate.wait(WATCHDOG_S)
            return 7

        with ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="pqt-testpool"
        ) as pool:
            futs = [
                instrumented_submit(pool, task, pool="pqt-testpool")
                for _ in range(3)
            ]
            assert started.wait(WATCHDOG_S)
            d = pool_depths()["pqt-testpool"]
            assert d["active"] == 1 and d["queued"] == 2
            assert metrics.get("pool_active_workers", pool="pqt-testpool") == 1
            assert metrics.get("pool_queue_depth", pool="pqt-testpool") == 2
            gate.set()
            assert [f.result(timeout=WATCHDOG_S) for f in futs] == [7, 7, 7]
        d = pool_depths()["pqt-testpool"]
        assert d == {"queued": 0, "active": 0}
        assert metrics.get("pool_queue_depth", pool="pqt-testpool") == 0
        assert metrics.get("pool_active_workers", pool="pqt-testpool") == 0

    def test_wait_and_task_histograms_observe(self):
        snap = metrics.snapshot()
        with ThreadPoolExecutor(
            max_workers=2, thread_name_prefix="pqt-testhist"
        ) as pool:
            futs = [
                instrumented_submit(
                    pool, time.sleep, 0.002, pool="pqt-testhist"
                )
                for _ in range(4)
            ]
            for f in futs:
                f.result(timeout=WATCHDOG_S)
        d = metrics.delta(snap)
        assert d.get('pool_queue_wait_seconds_count{pool="pqt-testhist"}') == 4
        assert d.get('pool_task_seconds_count{pool="pqt-testhist"}') == 4
        assert d.get('pool_task_seconds_sum{pool="pqt-testhist"}') >= 0.008

    def test_pool_label_defaults_to_thread_name_prefix(self):
        with ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="pqt-testauto"
        ) as pool:
            instrumented_submit(pool, int).result(timeout=WATCHDOG_S)
        assert metrics.get(
            "pool_queue_wait_seconds", pool="pqt-testauto"
        ) == 0  # histograms aren't counters; presence shows via snapshot
        assert (
            'pool_queue_wait_seconds_count{pool="pqt-testauto"}'
            in metrics.snapshot()
        )

    def test_queue_wait_credited_to_submitting_trace(self):
        with ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="pqt-testwait"
        ) as pool:
            with decode_trace() as tr:
                futs = [
                    instrumented_submit(
                        pool, time.sleep, 0.001, pool="pqt-testwait"
                    )
                    for _ in range(3)
                ]
                for f in futs:
                    f.result(timeout=WATCHDOG_S)
            rollup = tr.stage_rollup()
        assert rollup["pool.wait"]["calls"] == 3
        assert rollup["pool.wait"]["seconds"] >= 0

    def test_cancelled_future_releases_queue_depth(self):
        gate = threading.Event()
        started = threading.Event()

        def blocker():
            started.set()
            assert gate.wait(WATCHDOG_S)

        with ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="pqt-testcancel"
        ) as pool:
            head = instrumented_submit(pool, blocker, pool="pqt-testcancel")
            assert started.wait(WATCHDOG_S)
            queued = instrumented_submit(pool, int, pool="pqt-testcancel")
            assert pool_depths()["pqt-testcancel"]["queued"] == 1
            assert queued.cancel()
            gate.set()
            head.result(timeout=WATCHDOG_S)
        assert pool_depths()["pqt-testcancel"] == {"queued": 0, "active": 0}

    def test_worker_exception_still_balances_gauges(self):
        with ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="pqt-testboom"
        ) as pool:
            fut = instrumented_submit(
                pool, [].pop, pool="pqt-testboom"  # IndexError in the worker
            )
            with pytest.raises(IndexError):
                fut.result(timeout=WATCHDOG_S)
        assert pool_depths()["pqt-testboom"] == {"queued": 0, "active": 0}


# -- flight recorder unit contracts --------------------------------------------


class TestSanitizeRequestId:
    def test_passthrough_and_none(self):
        assert sanitize_request_id("r-1.2:3_ok") == "r-1.2:3_ok"
        assert sanitize_request_id(None) is None
        assert sanitize_request_id("   ") is None
        assert sanitize_request_id("") is None

    def test_hostile_values_bounded_and_cleaned(self):
        assert sanitize_request_id("a b{c}") == "a_b_c_"
        assert len(sanitize_request_id("x" * 500)) == 64
        assert sanitize_request_id('"\n\\') == "___"
        assert sanitize_request_id(12345) == "12345"  # coerced, not crashed


class TestObsConfigValidation:
    def test_rejects_bad_knobs(self):
        with pytest.raises(ValueError, match="ring_size"):
            ObsConfig(ring_size=0)
        with pytest.raises(ValueError, match="trace_sample_rate"):
            ObsConfig(trace_sample_rate=1.5)
        with pytest.raises(ValueError, match="slow_ms"):
            ObsConfig(slow_ms=0)
        with pytest.raises(ValueError, match="max_traces"):
            ObsConfig(max_traces=-1)


class TestRecorderBounds:
    def test_ring_and_index_evict_together(self):
        rec = FlightRecorder(ObsConfig(ring_size=4, trace_sample_rate=0.0))
        for i in range(10):
            r = rec.begin("/v1/scan", "t", request_id=f"r{i}")
            rec.finish(r, 200)
        st = rec.stats()
        assert st["records"] == 4 and st["indexed"] == 4
        assert rec.get("r0") is None and rec.get("r9") is not None
        assert [r["id"] for r in rec.list()] == ["r9", "r8", "r7", "r6"]

    def test_duplicate_id_newest_wins_lookup(self):
        rec = FlightRecorder(ObsConfig(ring_size=8))
        rec.finish(rec.begin("/v1/scan", "t", request_id="dup"), 200)
        second = rec.begin("/v1/plan", "t", request_id="dup")
        rec.finish(second, 404)
        assert rec.get("dup") is second
        # evicting the OLD duplicate must not drop the index entry that
        # now points at the new record
        for i in range(8):
            rec.finish(rec.begin("/v1/scan", "t", request_id=f"f{i}"), 200)
        assert rec.get("dup") is None  # both generations evicted by now

    def test_trace_budget_drops_oldest_keeps_summary(self):
        rec = FlightRecorder(
            ObsConfig(ring_size=32, trace_sample_rate=1.0, max_traces=2)
        )
        records = []
        for i in range(5):
            with decode_trace() as tr:
                pass
            r = rec.begin("/v1/scan", "t", request_id=f"tr{i}")
            rec.finish(r, 200, trace=tr)
            records.append(r)
        assert rec.stats()["traces"] == 2
        assert records[0]._trace is None  # oldest trace dropped...
        assert rec.get("tr0") is records[0]  # ...but the record remains
        assert records[4]._trace is not None

    def test_max_traces_zero_keeps_no_trees(self):
        rec = FlightRecorder(
            ObsConfig(ring_size=8, trace_sample_rate=1.0, max_traces=0)
        )
        with decode_trace() as tr:
            pass
        r = rec.finish(rec.begin("/v1/scan", "t"), 200, trace=tr)
        assert r._trace is None and r.stages is not None

    def test_shrinking_config_trims_immediately(self):
        rec = FlightRecorder(ObsConfig(ring_size=16))
        for i in range(16):
            rec.finish(rec.begin("/v1/scan", "t", request_id=f"s{i}"), 200)
        rec.configure(ObsConfig(ring_size=3))
        st = rec.stats()
        assert st["records"] == 3 and st["indexed"] == 3


class TestTraceRetention:
    def _finish_with_trace(self, rec, status, duration_s, rid):
        with decode_trace() as tr:
            pass
        r = rec.begin("/v1/scan", "t", request_id=rid)
        return rec.finish(r, status, trace=tr, duration_s=duration_s)

    def test_error_and_slow_always_keep(self):
        rec = FlightRecorder(
            ObsConfig(ring_size=8, trace_sample_rate=0.0, slow_ms=100.0)
        )
        err = self._finish_with_trace(rec, 500, 0.001, "err")
        slow = self._finish_with_trace(rec, 200, 0.5, "slow")
        fast = self._finish_with_trace(rec, 200, 0.001, "fast")
        assert err.trace_kind == "error" and err._trace is not None
        assert slow.trace_kind == "slow" and slow._trace is not None
        assert fast.trace_kind is None and fast._trace is None

    def test_string_error_status_counts_as_error(self):
        rec = FlightRecorder(ObsConfig(trace_sample_rate=0.0))
        r = self._finish_with_trace(rec, "error", 0.001, "estr")
        assert r.trace_kind == "error"

    def test_accumulator_sampler_is_exact(self):
        rec = FlightRecorder(
            ObsConfig(ring_size=64, trace_sample_rate=0.25, slow_ms=1e9)
        )
        kinds = [
            self._finish_with_trace(rec, 200, 0.0, f"a{i}").trace_kind
            for i in range(8)
        ]
        assert kinds.count("sampled") == 2  # exactly rate * n, no PRNG

    def test_error_message_truncates(self):
        rec = FlightRecorder(ObsConfig())
        r = rec.record("dataset.unit", status="error", error="x" * 10_000)
        assert len(r.error) == 300

    def test_one_shot_record_lands_in_ring(self):
        rec = FlightRecorder(ObsConfig())
        r = rec.record(
            "encode.group", duration_s=0.25, nbytes=1024,
            detail={"group": 3, "rows": 100},
        )
        assert r.open is False and r.duration_ms == 250.0
        got = rec.get(r.id)
        assert got is r
        d = got.to_dict()
        assert d["detail"] == {"group": 3, "rows": 100}
        assert d["bytes"] == 1024


class TestLibraryRingIsolation:
    def test_pipeline_churn_cannot_evict_request_evidence(self):
        """The 14:02 story survives a busy co-resident pipeline: hundreds
        of dataset.unit one-shots evict only each other, never the serve
        request records the debug endpoints exist to retain."""
        rec = FlightRecorder(ObsConfig(ring_size=8, trace_sample_rate=0.0))
        for i in range(4):
            rec.finish(rec.begin("/v1/scan", "t", request_id=f"req{i}"), 200)
        for i in range(500):
            rec.record("dataset.unit", detail={"group": i})
        st = rec.stats()
        assert st["requests"] == 4 and st["library"] == 8
        for i in range(4):
            assert rec.get(f"req{i}") is not None  # all still retrievable
        # one merged listing, newest first, both kinds present
        ids = [r["endpoint"] for r in rec.list(limit=100)]
        assert ids.count("dataset.unit") == 8
        assert ids.count("/v1/scan") == 4
        assert ids[0] == "dataset.unit"  # the newest record overall

"""parquet_tpu.io.hedge: hedged reads, circuit breakers, resilience wiring.

Pinned here:
  * HedgedSource: a fast primary never hedges; a stalled primary races a
    duplicate and the first success wins (either side); both failing
    re-raises the primary's error; every outcome lands in
    io_hedges_total{outcome=};
  * CircuitBreaker: the closed -> open -> half-open machine under a fake
    clock — threshold trips, typed fast-fail (SourceError code
    "breaker_open"), exactly ONE half-open probe, success closes, failure
    re-arms;
  * BreakerRegistry: bounded like every externally-keyed table (LRU-evict
    closed breakers, overflow when everything is open);
  * composition: the breaker's fast-fail is TERMINAL to RetryingSource
    (no pointless backoff on a known-dark source), in both stack orders;
  * open_source wiring: configure_resilience() makes every constructed
    source come back wrapped (and the default policy is the identity).
"""

from __future__ import annotations

import threading
import time

import pytest

from parquet_tpu.io.hedge import (
    BreakerRegistry,
    BreakerSource,
    CircuitBreaker,
    HedgedSource,
    ResilienceConfig,
    configure_resilience,
    resilience_config,
    wrap_resilient,
)
from parquet_tpu.io.source import (
    ByteSource,
    LocalFileSource,
    MemorySource,
    RetryingSource,
    SourceError,
    open_source,
)
from parquet_tpu.utils import metrics


class ScriptedSource(ByteSource):
    """A source whose successive read_at calls follow a script: each entry
    is bytes (return), an Exception (raise), or a threading.Event (block
    until set, then return). Deterministic concurrency for hedge races."""

    def __init__(self, script, data=b"x" * 64):
        self._script = list(script)
        self._data = data
        self._lock = threading.Lock()
        self.calls = 0

    def size(self) -> int:
        return len(self._data)

    def read_at(self, offset: int, n: int) -> bytes:
        with self._lock:
            idx = self.calls
            self.calls += 1
            step = (
                self._script[idx] if idx < len(self._script) else self._data
            )
        if isinstance(step, threading.Event):
            assert step.wait(10.0), "scripted block never released"
            return self._data[offset : offset + n]
        if isinstance(step, Exception):
            raise step
        return step[offset : offset + n]


def _hedge_outcomes(before):
    d = metrics.delta(before)
    return {
        k.split('"')[1]: v for k, v in d.items()
        if k.startswith("io_hedges_total")
    }


class TestHedgedSource:
    def test_fast_primary_never_hedges(self):
        src = ScriptedSource([])
        h = HedgedSource(src, min_delay_s=0.05, initial_delay_s=0.05)
        before = metrics.snapshot()
        for _ in range(4):
            assert h.read_at(0, 8) == b"x" * 8
        assert src.calls == 4
        assert h.hedges_launched == 0
        assert _hedge_outcomes(before) == {}

    def test_hedge_wins_when_primary_stalls(self):
        gate = threading.Event()
        src = ScriptedSource([gate])  # call 1 blocks; call 2 returns fast
        h = HedgedSource(src, min_delay_s=0.01, initial_delay_s=0.01)
        before = metrics.snapshot()
        try:
            assert h.read_at(0, 8) == b"x" * 8
        finally:
            gate.set()  # release the absorbed loser
        assert src.calls == 2
        assert h.hedges_launched == 1 and h.hedges_won == 1
        out = _hedge_outcomes(before)
        assert out.get("launched") == 1 and out.get("win_hedge") == 1

    def test_primary_wins_when_hedge_is_slower(self):
        g1, g2 = threading.Event(), threading.Event()
        src = ScriptedSource([g1, g2])
        h = HedgedSource(src, min_delay_s=0.01, initial_delay_s=0.01)
        before = metrics.snapshot()

        # release the primary shortly after the hedge launches
        def release():
            time.sleep(0.05)
            g1.set()

        t = threading.Thread(target=release, daemon=True)
        t.start()
        try:
            assert h.read_at(0, 8) == b"x" * 8
        finally:
            g1.set()
            g2.set()
        t.join()
        assert _hedge_outcomes(before).get("win_primary") == 1

    def test_hedge_failure_waits_for_primary(self):
        gate = threading.Event()
        src = ScriptedSource([gate, OSError(5, "hedge fails")])
        h = HedgedSource(src, min_delay_s=0.01, initial_delay_s=0.01)

        def release():
            time.sleep(0.05)
            gate.set()

        t = threading.Thread(target=release, daemon=True)
        t.start()
        assert h.read_at(0, 8) == b"x" * 8
        t.join()

    def test_both_failing_raises_primary_error(self):
        """A hedged read where the PRIMARY stalls past the bar and then
        both copies fail re-raises the primary's error (the hedge's is the
        same fault again, not new information)."""

        class _SlowThenFail(ScriptedSource):
            # primary: stall past the hedge bar, then fail; hedge: fail fast
            def read_at(self, offset, n):
                with self._lock:
                    idx = self.calls
                    self.calls += 1
                if idx == 0:
                    time.sleep(0.05)
                    raise OSError(5, "primary fault")
                raise OSError(5, "hedge fault")

        h = HedgedSource(
            _SlowThenFail([]), min_delay_s=0.01, initial_delay_s=0.01
        )
        before = metrics.snapshot()
        with pytest.raises(OSError, match="primary fault"):
            h.read_at(0, 8)
        assert _hedge_outcomes(before).get("failed") == 1

    def test_fast_failure_propagates_without_hedge(self):
        src = ScriptedSource([OSError(5, "boom")])
        h = HedgedSource(src, min_delay_s=0.5, initial_delay_s=0.5)
        before = metrics.snapshot()
        with pytest.raises(OSError, match="boom"):
            h.read_at(0, 8)
        assert src.calls == 1
        assert _hedge_outcomes(before) == {}

    def test_delay_tracks_latency_quantile(self):
        src = ScriptedSource([])
        h = HedgedSource(
            src, min_delay_s=0.001, max_delay_s=10.0, initial_delay_s=0.25
        )
        assert h.hedge_delay() == 0.25  # no samples yet
        for _ in range(16):
            h._window.record(0.002)
        assert h.hedge_delay() == pytest.approx(0.002)
        # the clamp floors it
        h2 = HedgedSource(src, min_delay_s=0.05)
        for _ in range(16):
            h2._window.record(0.001)
        assert h2.hedge_delay() == 0.05

    def test_validation(self):
        src = ScriptedSource([])
        with pytest.raises(ValueError):
            HedgedSource(src, delay_quantile=1.5)
        with pytest.raises(ValueError):
            HedgedSource(src, min_delay_s=0.5, max_delay_s=0.1)


class TestCircuitBreaker:
    def test_trip_fast_fail_and_recover(self):
        t = [0.0]
        b = CircuitBreaker("s", failure_threshold=3, open_s=5.0,
                           clock=lambda: t[0])
        assert b.state == "closed"
        for _ in range(2):
            b.record_failure()
        assert b.state == "closed"  # below threshold
        b.record_failure()
        assert b.state == "open"
        with pytest.raises(SourceError) as ei:
            b.before_read()
        assert ei.value.code == "breaker_open"
        # time passes -> half-open, ONE probe admitted
        t[0] = 5.0
        assert b.state == "half_open"
        b.before_read()  # the probe slot
        with pytest.raises(SourceError):
            b.before_read()  # concurrent readers keep fast-failing
        b.record_success()
        assert b.state == "closed"
        b.before_read()  # closed again

    def test_probe_failure_rearms(self):
        t = [0.0]
        b = CircuitBreaker("s", failure_threshold=1, open_s=2.0,
                           clock=lambda: t[0])
        b.record_failure()
        assert b.state == "open"
        t[0] = 2.0
        b.before_read()  # probe
        b.record_failure()
        assert b.state == "open"
        with pytest.raises(SourceError):
            b.before_read()  # timer re-armed from t=2
        t[0] = 3.9
        with pytest.raises(SourceError):
            b.before_read()
        t[0] = 4.0
        b.before_read()  # next probe window

    def test_valueerror_probe_releases_slot(self):
        # a ValueError (caller bug) during the half-open probe must not
        # latch _probing: the NEXT read still gets a probe slot
        t = [0.0]
        b = CircuitBreaker("s", failure_threshold=1, open_s=2.0,
                           clock=lambda: t[0])
        src = BreakerSource(
            ScriptedSource([OSError("boom"), ValueError("bad range")]), b
        )
        with pytest.raises(OSError):
            src.read_at(0, 8)  # trips the breaker
        t[0] = 2.0
        assert b.state == "half_open"
        with pytest.raises(ValueError):
            src.read_at(0, 8)  # probe dies pre-flight: slot released
        assert src.read_at(0, 8) == b"x" * 8  # next read IS the probe
        assert b.state == "closed"

    def test_success_resets_failure_streak(self):
        b = CircuitBreaker("s", failure_threshold=3)
        b.record_failure()
        b.record_failure()
        b.record_success()
        b.record_failure()
        b.record_failure()
        assert b.state == "closed"  # never 3 CONSECUTIVE

    def test_state_gauge(self):
        b = CircuitBreaker("gauge-pin", failure_threshold=1, label="gauge-pin")
        assert metrics.get("io_breaker_state", source="gauge-pin") == 0
        b.record_failure()
        assert metrics.get("io_breaker_state", source="gauge-pin") == 1


class TestBreakerRegistry:
    def test_shared_per_source_id(self):
        reg = BreakerRegistry()
        assert reg.breaker_for("a") is reg.breaker_for("a")
        assert reg.breaker_for("a") is not reg.breaker_for("b")

    def test_bounded_evicts_closed(self):
        reg = BreakerRegistry(max_sources=2)
        reg.breaker_for("a")
        reg.breaker_for("b")
        reg.breaker_for("c")  # evicts a closed breaker
        assert len(reg.states()) == 2

    def test_overflow_when_all_open(self):
        reg = BreakerRegistry(max_sources=2, failure_threshold=1)
        for sid in ("a", "b"):
            reg.breaker_for(sid).record_failure()
        b = reg.breaker_for("c")
        assert reg.breaker_for("d") is b  # both land in the overflow slot
        assert BreakerRegistry.OVERFLOW in reg.states()

    def test_reset(self):
        reg = BreakerRegistry(failure_threshold=1)
        reg.breaker_for("a").record_failure()
        reg.reset()
        assert reg.states() == {}


class _AlwaysFails(ByteSource):
    def __init__(self):
        self.calls = 0

    def size(self) -> int:
        return 64

    @property
    def source_id(self) -> str:
        return "always-fails"

    def read_at(self, offset: int, n: int) -> bytes:
        self.calls += 1
        raise OSError(5, "injected")


class TestComposition:
    def test_breaker_under_retry_is_terminal(self):
        """Retrying(Breaker(src)): once the breaker opens mid-ladder, the
        typed fast-fail aborts the remaining attempts — no backoff is
        spent on a source the breaker already called dark."""
        inner = _AlwaysFails()
        b = CircuitBreaker("c1", failure_threshold=2, open_s=60.0)
        src = RetryingSource(
            BreakerSource(inner, b), attempts=10, base_delay_s=0.0001,
            sleep=lambda s: None,
        )
        with pytest.raises(SourceError) as ei:
            src.read_at(0, 8)
        assert ei.value.code == "breaker_open"
        assert inner.calls == 2  # threshold, not attempts

    def test_breaker_over_retry_counts_exhaustion(self):
        """Breaker(Retrying(src)): the breaker sees one failure per
        EXHAUSTED ladder, so it trips after threshold x attempts raw
        faults."""
        inner = _AlwaysFails()
        b = CircuitBreaker("c2", failure_threshold=2, open_s=60.0)
        src = BreakerSource(
            RetryingSource(inner, attempts=3, base_delay_s=0.0001,
                           sleep=lambda s: None),
            b,
        )
        for _ in range(2):
            with pytest.raises(SourceError):
                src.read_at(0, 8)
        assert b.state == "open"
        assert inner.calls == 6
        calls_before = inner.calls
        with pytest.raises(SourceError) as ei:
            src.read_at(0, 8)
        assert ei.value.code == "breaker_open"
        assert inner.calls == calls_before  # fast fail: no transport touch

    def test_value_error_never_counts(self):
        b = CircuitBreaker("c3", failure_threshold=1)
        src = BreakerSource(MemorySource(b"abc"), b)
        with pytest.raises(ValueError):
            src.read_at(-1, 2)
        assert b.state == "closed"


class TestResilienceWiring:
    def teardown_method(self):
        configure_resilience(None)

    def test_default_policy_is_identity(self):
        assert not resilience_config().active
        src = MemorySource(b"abc")
        assert wrap_resilient(src) is src

    def test_open_source_applies_policy(self, tmp_path):
        p = tmp_path / "f.bin"
        p.write_bytes(b"hello world")
        reg = BreakerRegistry(failure_threshold=1)
        configure_resilience(
            ResilienceConfig(breaker=True, retry=True, hedge=True,
                             registry=reg,
                             retry_kw={"sleep": lambda s: None})
        )
        src, owns = open_source(str(p))
        assert owns
        # outermost hedge, then retry, then breaker, then the local source
        assert isinstance(src, HedgedSource)
        assert isinstance(src.inner, RetryingSource)
        assert isinstance(src.inner.inner, BreakerSource)
        assert isinstance(src.inner.inner.inner, LocalFileSource)
        assert src.read_at(0, 5) == b"hello"
        src.close()

    def test_passed_through_sources_stay_unwrapped(self, tmp_path):
        configure_resilience(ResilienceConfig(retry=True))
        src = MemorySource(b"abc")
        got, owns = open_source(src)
        assert got is src and not owns

    def test_configure_returns_previous(self):
        prev = configure_resilience(ResilienceConfig(retry=True))
        assert not prev.active
        back = configure_resilience(prev)
        assert back.active
        assert not resilience_config().active

    def test_chaos_wrapper_is_innermost(self):
        wrapped = []

        def chaos(s):
            wrapped.append(s)
            return s

        configure_resilience(
            ResilienceConfig(retry=True, chaos_wrapper=chaos,
                             retry_kw={"sleep": lambda s: None})
        )
        src = wrap_resilient(MemorySource(b"abc"))
        assert isinstance(src, RetryingSource)
        assert isinstance(wrapped[0], MemorySource)

    def test_reader_reads_through_policy(self, tmp_path):
        """The whole point of the choke-point wiring: a FileReader opened
        by PATH picks the policy up with no per-callsite code."""
        import numpy as np

        from parquet_tpu.core.writer import FileWriter
        from parquet_tpu.schema.dsl import parse_schema

        p = str(tmp_path / "t.parquet")
        w = FileWriter(p, parse_schema("message m { required int64 x; }"))
        w.write_column("x", np.arange(100, dtype=np.int64))
        w.close()
        seen = []
        configure_resilience(ResilienceConfig(chaos_wrapper=lambda s: (seen.append(s) or s)))
        from parquet_tpu.core.reader import FileReader

        with FileReader(p) as r:
            cols = r.read_row_group(0)
        assert next(iter(cols.values())).num_values == 100
        assert seen  # the policy saw the open

"""to_arrow logical-type fidelity: the Arrow types pyarrow.read_table gives
its users (timestamp/date/time/decimal128/uint*/float16, INT96->ns) must
come out of our to_arrow too — flat, in lists, and inside structs — with
equal values. Reference analogue: the reference converts logical types in
its row model (reference: helpers.go time conversions, schema.go); the
columnar lane must not lose them."""

import datetime as dt
import decimal

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from parquet_tpu import FileReader, FileWriter, parse_schema

BACKENDS = ["host", "tpu_roundtrip"]


def _norm(t):
    """Collapse our large_* container convention for type comparison; the
    LEAF types (the logical conversions under test) stay exact."""
    if pa.types.is_large_list(t) or pa.types.is_list(t):
        return pa.list_(_norm(t.value_type))
    if pa.types.is_large_string(t):
        return pa.string()
    if pa.types.is_large_binary(t):
        return pa.binary()
    if pa.types.is_struct(t):
        return pa.struct([pa.field(f.name, _norm(f.type), f.nullable) for f in t])
    if pa.types.is_map(t):
        return pa.map_(_norm(t.key_type), _norm(t.item_type))
    return t


def _cmp(path, backend, cols=None):
    want = pq.read_table(path)
    with FileReader(path, backend=backend) as r:
        out = r.to_arrow(columns=cols)
    for name in want.column_names if cols is None else cols:
        w = want.column(name)
        g = out.column(name)
        assert _norm(g.type) == _norm(w.type), (name, g.type, w.type)
        assert g.to_pylist() == w.to_pylist(), name
    return out


@pytest.mark.parametrize("backend", BACKENDS)
class TestFlatLogicalTypes:
    def test_temporal_and_ints(self, tmp_path, backend):
        n = 3_000
        rng = np.random.default_rng(1)
        base = dt.datetime(2020, 1, 2, 3, 4, 5, 123456)
        t = pa.table({
            "ts_us": pa.array(
                [None if i % 11 == 0 else base + dt.timedelta(seconds=int(s))
                 for i, s in enumerate(rng.integers(0, 10**6, n))],
                pa.timestamp("us"),
            ),
            "ts_tz": pa.array(
                [base.replace(tzinfo=dt.timezone.utc)] * n, pa.timestamp("us", tz="UTC")
            ),
            "ts_ms": pa.array([base] * n, pa.timestamp("ms")),
            "ts_ns": pa.array([base] * n, pa.timestamp("ns")),
            "d": pa.array(
                [None if i % 7 == 0 else dt.date(2021, 1, 1) + dt.timedelta(int(x))
                 for i, x in enumerate(rng.integers(0, 3000, n))],
                pa.date32(),
            ),
            "t32": pa.array([dt.time(1, 2, 3, 5000)] * n, pa.time32("ms")),
            "t64": pa.array([dt.time(23, 59, 59, 999999)] * n, pa.time64("us")),
            "u8": pa.array(rng.integers(0, 256, n), pa.uint8()),
            "u16": pa.array(rng.integers(0, 1 << 16, n), pa.uint16()),
            "u32": pa.array(rng.integers(0, 1 << 32, n, dtype=np.uint64), pa.uint32()),
            "u64": pa.array(
                rng.integers(0, 1 << 63, n, dtype=np.uint64) * 2 + 1, pa.uint64()
            ),
            "i8": pa.array(rng.integers(-128, 128, n), pa.int8()),
            "i16": pa.array(rng.integers(-(1 << 15), 1 << 15, n), pa.int16()),
        })
        p = str(tmp_path / "tl.parquet")
        pq.write_table(t, p)
        _cmp(p, backend)

    def test_decimals(self, tmp_path, backend):
        vals = [
            decimal.Decimal("123.45"), None, decimal.Decimal("-0.01"),
            decimal.Decimal("99999.99"), decimal.Decimal("-99999.99"),
        ] * 50
        t = pa.table({
            "d32": pa.array(vals, pa.decimal128(7, 2)),     # int32-backed
            "d64": pa.array(vals, pa.decimal128(15, 2)),    # int64-backed
            "dbig": pa.array(
                [None if v is None else v * 10**15 for v in vals],
                pa.decimal128(35, 2),                       # FLBA-backed
            ),
        })
        p = str(tmp_path / "dec.parquet")
        pq.write_table(t, p)
        _cmp(p, backend)

    def test_float16(self, tmp_path, backend):
        arr = np.array([0.5, -2.0, 65504.0, 0.0], np.float16)
        t = pa.table({"h": pa.array(arr, pa.float16())})
        p = str(tmp_path / "f16.parquet")
        pq.write_table(t, p)
        _cmp(p, backend)

    def test_int96_timestamps(self, tmp_path, backend):
        """INT96 (Impala convention) -> timestamp[ns], matching pyarrow."""
        schema = parse_schema("message m { required int96 ts; }")
        base = dt.datetime(2001, 2, 3, 4, 5, 6, 789123, tzinfo=dt.timezone.utc)
        rows = [
            {"ts": base + dt.timedelta(seconds=int(s))}
            for s in np.random.default_rng(2).integers(0, 10**7, 500)
        ]
        p = str(tmp_path / "i96.parquet")
        with FileWriter(p, schema, codec="snappy") as w:
            w.write_rows(rows)
        out = _cmp(p, backend)
        assert out.column("ts").type == pa.timestamp("ns")


@pytest.mark.parametrize("backend", BACKENDS)
class TestNestedLogicalTypes:
    def test_lists_of_logical(self, tmp_path, backend):
        t = pa.table({
            "ld": pa.array(
                [[dt.date(2020, 1, 1), None], None, [dt.date(1999, 12, 31)]],
                pa.list_(pa.date32()),
            ),
            "lu": pa.array(
                [[1, 2], [], [2**63 + 5]], pa.list_(pa.uint64())
            ),
            "lts": pa.array(
                [[dt.datetime(2020, 5, 6, 7, 8, 9)], None, []],
                pa.list_(pa.timestamp("us")),
            ),
        })
        p = str(tmp_path / "ll.parquet")
        pq.write_table(t, p)
        _cmp(p, backend)

    def test_struct_with_logical_members(self, tmp_path, backend):
        st = pa.struct([
            ("when", pa.timestamp("ms")),
            ("amount", pa.decimal128(10, 2)),
            ("day", pa.date32()),
        ])
        t = pa.table({
            "s": pa.array(
                [
                    {"when": dt.datetime(2022, 3, 4, 5, 6), "amount": decimal.Decimal("12.34"), "day": dt.date(2022, 3, 4)},
                    None,
                    {"when": None, "amount": None, "day": None},
                ],
                st,
            ),
        })
        p = str(tmp_path / "slog.parquet")
        pq.write_table(t, p)
        _cmp(p, backend)

    def test_map_with_logical_values(self, tmp_path, backend):
        t = pa.table({
            "m": pa.array(
                [[("a", dt.date(2020, 2, 2))], None, []],
                pa.map_(pa.string(), pa.date32()),
            ),
        })
        p = str(tmp_path / "mlog.parquet")
        pq.write_table(t, p)
        _cmp(p, backend)

    def test_zero_group_schema_matches(self, tmp_path, backend):
        t = pa.table({
            "ts": pa.array([dt.datetime(2020, 1, 1)], pa.timestamp("us")),
            "ld": pa.array([[dt.date(2020, 1, 1)]], pa.list_(pa.date32())),
            "dec": pa.array([decimal.Decimal("1.5")], pa.decimal128(6, 1)),
        })
        p = str(tmp_path / "zg.parquet")
        pq.write_table(t, p)
        with FileReader(p, backend=backend) as r:
            full = r.to_arrow()
            empty = r.to_arrow(row_groups=[])
        for name in t.column_names:
            assert empty.column(name).type == full.column(name).type, name

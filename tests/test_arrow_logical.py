"""to_arrow logical-type fidelity: the Arrow types pyarrow.read_table gives
its users (timestamp/date/time/decimal128/uint*/float16, INT96->ns) must
come out of our to_arrow too — flat, in lists, and inside structs — with
equal values. Reference analogue: the reference converts logical types in
its row model (reference: helpers.go time conversions, schema.go); the
columnar lane must not lose them."""

import datetime as dt
import decimal

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from parquet_tpu import FileReader, FileWriter, parse_schema

BACKENDS = ["host", "tpu_roundtrip"]


def _norm(t):
    """Collapse our large_* container convention for type comparison; the
    LEAF types (the logical conversions under test) stay exact."""
    if pa.types.is_large_list(t) or pa.types.is_list(t):
        return pa.list_(_norm(t.value_type))
    if pa.types.is_large_string(t):
        return pa.string()
    if pa.types.is_large_binary(t):
        return pa.binary()
    if pa.types.is_struct(t):
        return pa.struct([pa.field(f.name, _norm(f.type), f.nullable) for f in t])
    if pa.types.is_map(t):
        return pa.map_(_norm(t.key_type), _norm(t.item_type))
    return t


def _cmp(path, backend, cols=None):
    want = pq.read_table(path)
    with FileReader(path, backend=backend) as r:
        out = r.to_arrow(columns=cols)
    for name in want.column_names if cols is None else cols:
        w = want.column(name)
        g = out.column(name)
        assert _norm(g.type) == _norm(w.type), (name, g.type, w.type)
        assert g.to_pylist() == w.to_pylist(), name
    return out


@pytest.mark.parametrize("backend", BACKENDS)
class TestFlatLogicalTypes:
    def test_temporal_and_ints(self, tmp_path, backend):
        n = 3_000
        rng = np.random.default_rng(1)
        base = dt.datetime(2020, 1, 2, 3, 4, 5, 123456)
        t = pa.table({
            "ts_us": pa.array(
                [None if i % 11 == 0 else base + dt.timedelta(seconds=int(s))
                 for i, s in enumerate(rng.integers(0, 10**6, n))],
                pa.timestamp("us"),
            ),
            "ts_tz": pa.array(
                [base.replace(tzinfo=dt.timezone.utc)] * n, pa.timestamp("us", tz="UTC")
            ),
            "ts_ms": pa.array([base] * n, pa.timestamp("ms")),
            "ts_ns": pa.array([base] * n, pa.timestamp("ns")),
            "d": pa.array(
                [None if i % 7 == 0 else dt.date(2021, 1, 1) + dt.timedelta(int(x))
                 for i, x in enumerate(rng.integers(0, 3000, n))],
                pa.date32(),
            ),
            "t32": pa.array([dt.time(1, 2, 3, 5000)] * n, pa.time32("ms")),
            "t64": pa.array([dt.time(23, 59, 59, 999999)] * n, pa.time64("us")),
            "u8": pa.array(rng.integers(0, 256, n), pa.uint8()),
            "u16": pa.array(rng.integers(0, 1 << 16, n), pa.uint16()),
            "u32": pa.array(rng.integers(0, 1 << 32, n, dtype=np.uint64), pa.uint32()),
            "u64": pa.array(
                rng.integers(0, 1 << 63, n, dtype=np.uint64) * 2 + 1, pa.uint64()
            ),
            "i8": pa.array(rng.integers(-128, 128, n), pa.int8()),
            "i16": pa.array(rng.integers(-(1 << 15), 1 << 15, n), pa.int16()),
        })
        p = str(tmp_path / "tl.parquet")
        pq.write_table(t, p)
        _cmp(p, backend)

    def test_decimals(self, tmp_path, backend):
        vals = [
            decimal.Decimal("123.45"), None, decimal.Decimal("-0.01"),
            decimal.Decimal("99999.99"), decimal.Decimal("-99999.99"),
        ] * 50
        t = pa.table({
            "d32": pa.array(vals, pa.decimal128(7, 2)),     # int32-backed
            "d64": pa.array(vals, pa.decimal128(15, 2)),    # int64-backed
            "dbig": pa.array(
                [None if v is None else v * 10**15 for v in vals],
                pa.decimal128(35, 2),                       # FLBA-backed
            ),
        })
        p = str(tmp_path / "dec.parquet")
        pq.write_table(t, p)
        _cmp(p, backend)

    def test_float16(self, tmp_path, backend):
        arr = np.array([0.5, -2.0, 65504.0, 0.0], np.float16)
        t = pa.table({"h": pa.array(arr, pa.float16())})
        p = str(tmp_path / "f16.parquet")
        pq.write_table(t, p)
        _cmp(p, backend)

    def test_int96_timestamps(self, tmp_path, backend):
        """INT96 (Impala convention) -> timestamp[ns], matching pyarrow."""
        schema = parse_schema("message m { required int96 ts; }")
        base = dt.datetime(2001, 2, 3, 4, 5, 6, 789123, tzinfo=dt.timezone.utc)
        rows = [
            {"ts": base + dt.timedelta(seconds=int(s))}
            for s in np.random.default_rng(2).integers(0, 10**7, 500)
        ]
        p = str(tmp_path / "i96.parquet")
        with FileWriter(p, schema, codec="snappy") as w:
            w.write_rows(rows)
        out = _cmp(p, backend)
        assert out.column("ts").type == pa.timestamp("ns")


@pytest.mark.parametrize("backend", BACKENDS)
class TestNestedLogicalTypes:
    def test_lists_of_logical(self, tmp_path, backend):
        t = pa.table({
            "ld": pa.array(
                [[dt.date(2020, 1, 1), None], None, [dt.date(1999, 12, 31)]],
                pa.list_(pa.date32()),
            ),
            "lu": pa.array(
                [[1, 2], [], [2**63 + 5]], pa.list_(pa.uint64())
            ),
            "lts": pa.array(
                [[dt.datetime(2020, 5, 6, 7, 8, 9)], None, []],
                pa.list_(pa.timestamp("us")),
            ),
        })
        p = str(tmp_path / "ll.parquet")
        pq.write_table(t, p)
        _cmp(p, backend)

    def test_struct_with_logical_members(self, tmp_path, backend):
        st = pa.struct([
            ("when", pa.timestamp("ms")),
            ("amount", pa.decimal128(10, 2)),
            ("day", pa.date32()),
        ])
        t = pa.table({
            "s": pa.array(
                [
                    {"when": dt.datetime(2022, 3, 4, 5, 6), "amount": decimal.Decimal("12.34"), "day": dt.date(2022, 3, 4)},
                    None,
                    {"when": None, "amount": None, "day": None},
                ],
                st,
            ),
        })
        p = str(tmp_path / "slog.parquet")
        pq.write_table(t, p)
        _cmp(p, backend)

    def test_map_with_logical_values(self, tmp_path, backend):
        t = pa.table({
            "m": pa.array(
                [[("a", dt.date(2020, 2, 2))], None, []],
                pa.map_(pa.string(), pa.date32()),
            ),
        })
        p = str(tmp_path / "mlog.parquet")
        pq.write_table(t, p)
        _cmp(p, backend)

    def test_zero_group_schema_matches(self, tmp_path, backend):
        t = pa.table({
            "ts": pa.array([dt.datetime(2020, 1, 1)], pa.timestamp("us")),
            "ld": pa.array([[dt.date(2020, 1, 1)]], pa.list_(pa.date32())),
            "dec": pa.array([decimal.Decimal("1.5")], pa.decimal128(6, 1)),
        })
        p = str(tmp_path / "zg.parquet")
        pq.write_table(t, p)
        with FileReader(p, backend=backend) as r:
            full = r.to_arrow()
            empty = r.to_arrow(row_groups=[])
        for name in t.column_names:
            assert empty.column(name).type == full.column(name).type, name


class TestWriteSideLogicalIngest:
    """The reverse direction: write_column ingests the logical-typed Arrow
    arrays to_arrow produces (timestamp/date/uint zero-copy, decimal128
    narrowed back to INT32/INT64/FLBA storage, float16 -> FLBA(2)), and
    pyarrow reads the result back identically — columns hand both ways
    without a rewrite."""

    def test_roundtrip_ours_write(self, tmp_path):
        t = pa.table({
            "ts": pa.array(
                [dt.datetime(2024, 6, 1), dt.datetime(1999, 1, 1, 2, 3)],
                pa.timestamp("us"),
            ),
            "d": pa.array([dt.date(2024, 6, 1), dt.date(1970, 1, 2)], pa.date32()),
            "dec": pa.array(
                [decimal.Decimal("12.34"), decimal.Decimal("-0.07")],
                pa.decimal128(10, 2),
            ),
            "decbig": pa.array(
                [decimal.Decimal("-123456789012345678.99"), decimal.Decimal("7.00")],
                pa.decimal128(30, 2),
            ),
            "u32": pa.array([2**31 + 5, 3], pa.uint32()),
            "u64": pa.array([2**64 - 1, 0], pa.uint64()),
            "h": pa.array(np.array([1.5, -2.25], np.float16), pa.float16()),
        })
        src = str(tmp_path / "src.parquet")
        pq.write_table(t, src)
        with FileReader(src) as r:
            ours = r.to_arrow()
        schema = parse_schema("""message m {
          required int64 ts (TIMESTAMP(MICROS, false));
          required int32 d (DATE);
          required int64 dec (DECIMAL(10, 2));
          required fixed_len_byte_array(13) decbig (DECIMAL(30, 2));
          required int32 u32 (UINT_32);
          required int64 u64 (UINT_64);
          required fixed_len_byte_array(2) h (FLOAT16);
        }""")
        out = str(tmp_path / "out.parquet")
        with FileWriter(out, schema) as w:
            for name in ours.column_names:
                w.write_column(name, ours.column(name).combine_chunks())
        back = pq.read_table(out)
        for c in t.column_names:
            assert back.column(c).type == t.column(c).type, c
            assert back.column(c).to_pylist() == t.column(c).to_pylist(), c
        # and OUR reader agrees with pyarrow on our own file
        with FileReader(out) as r:
            again = r.to_arrow()
        for c in t.column_names:
            assert again.column(c).to_pylist() == t.column(c).to_pylist(), c

    def test_decimal_ingest_validation(self, tmp_path):
        """Review regressions: values that don't fit the physical storage
        and scale mismatches must raise, never truncate or rescale."""
        import io

        from parquet_tpu.core.column_store import StoreError

        schema = parse_schema("message m { required int32 d (DECIMAL(9, 2)); }")
        with pytest.raises(StoreError, match="fit"):
            with FileWriter(io.BytesIO(), schema) as w:
                w.write_column(
                    "d",
                    pa.array([decimal.Decimal("99999999999.99")], pa.decimal128(13, 2)),
                )
        with pytest.raises(StoreError, match="scale"):
            with FileWriter(io.BytesIO(), schema) as w:
                w.write_column(
                    "d", pa.array([decimal.Decimal("12.3456")], pa.decimal128(10, 4))
                )
        sfl = parse_schema(
            "message m { required fixed_len_byte_array(3) d (DECIMAL(7, 1)); }"
        )
        with pytest.raises(StoreError, match="fit"):
            with FileWriter(io.BytesIO(), sfl) as w:
                w.write_column(
                    "d", pa.array([decimal.Decimal("999999.9")], pa.decimal128(7, 1))
                )

    def test_wide_flba_decimal_writes_but_stays_binary(self, tmp_path):
        """FLBA(>16) decimals: legal to WRITE (row path decodes them), but
        the Arrow lane keeps raw binary — pyarrow itself refuses
        FromBigEndian beyond 16 bytes, so there is no pyarrow type to
        mirror."""
        import io

        schema = parse_schema(
            "message m { required fixed_len_byte_array(20) d (DECIMAL(38, 3)); }"
        )
        vals = pa.array(
            [decimal.Decimal("-123.678"), decimal.Decimal("0.001")],
            pa.decimal128(38, 3),
        )
        buf = io.BytesIO()
        with FileWriter(buf, schema) as w:
            w.write_column("d", vals)
        buf.seek(0)
        with FileReader(buf) as r:
            out = r.to_arrow()
        assert out.column("d").type == pa.binary(20)
        buf.seek(0)
        with FileReader(buf) as r:
            rows = list(r.iter_rows())
        assert [x["d"] for x in rows] == vals.to_pylist()


class TestToArrowFilters:
    """to_arrow(filters=...) mirrors pyarrow.parquet.read_table's filters:
    pruned by statistics/bloom, then EXACT — equal rows to pyarrow on the
    same predicate, including DNF (OR of ANDs), logical-typed literals,
    set membership, and predicates on projected-out columns."""

    def _file(self, tmp_path):
        n = 50_000
        rng = np.random.default_rng(9)
        t = pa.table({
            "id": pa.array(np.arange(n, dtype=np.int64)),
            "cat": pa.array([f"c{i % 23}" for i in range(n)]),
            "when": pa.array(
                [dt.datetime(2024, 1, 1) + dt.timedelta(minutes=int(i)) for i in range(n)],
                pa.timestamp("us"),
            ),
            "x": pa.array(rng.standard_normal(n)),
        })
        p = str(tmp_path / "f.parquet")
        pq.write_table(t, p, row_group_size=8_000, compression="snappy")
        return p

    @pytest.mark.parametrize("flt", [
        [("id", ">=", 49_000)],
        [("cat", "==", "c7"), ("id", "<", 10_000)],
        [[("id", "<", 100)], [("id", ">=", 49_900)]],           # OR of ANDs
        [("cat", "in", ["c1", "c2"]), ("id", ">", 40_000)],
        [("when", ">=", dt.datetime(2024, 1, 30))],             # logical literal
        [("id", "<", 0)],                                       # empty result
    ])
    def test_matches_pyarrow(self, tmp_path, flt):
        p = self._file(tmp_path)
        want = pq.read_table(p, filters=flt)
        with FileReader(p) as r:
            got = r.to_arrow(filters=flt)
        assert got.num_rows == want.num_rows, flt
        for c in want.column_names:
            assert got.column(c).to_pylist() == want.column(c).to_pylist(), (flt, c)

    def test_filter_on_projected_out_column(self, tmp_path):
        p = self._file(tmp_path)
        want = pq.read_table(p, columns=["id"], filters=[("cat", "==", "c3")])
        with FileReader(p) as r:
            got = r.to_arrow(columns=["id"], filters=[("cat", "==", "c3")])
        assert got.column_names == ["id"]
        assert got.column("id").to_pylist() == want.column("id").to_pylist()

    def test_bad_filter_column_raises(self, tmp_path):
        from parquet_tpu.core.filter import FilterError

        p = self._file(tmp_path)
        with FileReader(p) as r:
            with pytest.raises(FilterError):
                r.to_arrow(filters=[("nope", "==", 1)])

    def test_nested_filter_column_does_not_leak(self, tmp_path):
        """Review regression: a predicate on a projected-out NESTED sibling
        leaf filters without appearing in the output struct."""
        t = pa.table({
            "g": pa.array(
                [{"b": 1, "c": 10}, {"b": 2, "c": 20}, {"b": 3, "c": 30}],
                pa.struct([("b", pa.int64()), ("c", pa.int64())]),
            ),
        })
        p = str(tmp_path / "nf.parquet")
        pq.write_table(t, p)
        with FileReader(p) as r:
            got = r.to_arrow(columns=["g.b"], filters=[("g.c", "==", 20)])
        assert got.column("g").to_pylist() == [{"b": 2}]

    def test_empty_filters_vacuously_true(self, tmp_path):
        p = self._file(tmp_path)
        with FileReader(p) as r:
            got = r.to_arrow(filters=[])
            assert got.num_rows == 50_000

    def test_null_aware_filtering(self, tmp_path):
        t = pa.table({"x": pa.array([1, None, 3, None, 5], pa.int64())})
        p = str(tmp_path / "nulls.parquet")
        pq.write_table(t, p)
        want = pq.read_table(p, filters=[("x", ">", 1)])
        with FileReader(p) as r:
            got = r.to_arrow(filters=[("x", ">", 1)])
            nulls = r.to_arrow(filters=[("x", "is_null")])
        assert got.column("x").to_pylist() == want.column("x").to_pylist()
        assert nulls.column("x").to_pylist() == [None, None]

    def test_not_in_null_semantics_match_pyarrow(self, tmp_path):
        """to_arrow(filters=) promises pyarrow parity: not_in KEEPS null
        rows (pc.is_in maps null->false, inverted to true) while iter_rows'
        SQL-ish row predicate drops them — both pinned intentionally."""
        t = pa.table({"x": pa.array([1, None, 3], pa.int64())})
        p = str(tmp_path / "ni.parquet")
        pq.write_table(t, p)
        want = pq.read_table(p, filters=[("x", "not in", [1])])
        with FileReader(p) as r:
            got = r.to_arrow(filters=[("x", "not_in", [1])])
            rows = list(r.iter_rows(filters=[("x", "not_in", [1])]))
        assert got.column("x").to_pylist() == want.column("x").to_pylist() == [None, 3]
        assert [x["x"] for x in rows] == [3]

    def test_projected_filter_column_not_decoded_twice(self, tmp_path):
        """Flat filter columns already in the projection evaluate off the
        main table (no second read of their chunks)."""
        from parquet_tpu.utils.trace import decode_trace

        p = self._file(tmp_path)
        with decode_trace() as tr:
            with FileReader(p) as r:
                r.to_arrow(filters=[("id", ">=", 0)])  # admits every group
        one_pass = tr.stages["decode"].bytes
        with decode_trace() as tr2:
            with FileReader(p) as r:
                r.to_arrow()
        assert one_pass <= tr2.stages["decode"].bytes * 1.05  # no double decode


class TestReadDictionary:
    """to_arrow(read_dictionary=) — pyarrow's dictionary-preserving read:
    the column arrives as dictionary<int32, large_string> with indices and
    the (small) dictionary passing through unmaterialized."""

    def test_matches_pyarrow(self, tmp_path):
        n = 60_000
        t = pa.table({
            "s": pa.array(
                [None if i % 13 == 0 else f"key_{i % 500:04d}" for i in range(n)]
            ),
            "i": pa.array(np.arange(n, dtype=np.int64)),
        })
        p = str(tmp_path / "rd.parquet")
        pq.write_table(t, p, use_dictionary=["s"], compression="snappy",
                       row_group_size=20_000)
        want = pq.read_table(p, read_dictionary=["s"])
        with FileReader(p) as r:
            out = r.to_arrow(read_dictionary=["s"])
            empty = r.to_arrow(read_dictionary=["s"], row_groups=[])
            plain = r.to_arrow()
        assert pa.types.is_dictionary(out.column("s").type)
        assert out.column("s").to_pylist() == want.column("s").to_pylist()
        assert out.column("i").type == pa.int64()  # others untouched
        assert pa.types.is_dictionary(empty.column("s").type)
        assert not pa.types.is_dictionary(plain.column("s").type)

    def test_dict_overflow_normalizes_to_plain(self, tmp_path):
        """A chunk whose dictionary overflowed into PLAIN fallback pages
        cannot stay dictionary-typed; the whole column normalizes so the
        chunked type is uniform — values still exact."""
        n = 120_000
        rng = np.random.default_rng(3)
        # high-cardinality strings blow pyarrow's default 1MB dict ceiling
        t = pa.table({
            "s": pa.array([f"u{int(x):08d}" + "p" * 40 for x in rng.integers(0, n, n)]),
        })
        p = str(tmp_path / "ov.parquet")
        pq.write_table(t, p, use_dictionary=["s"], compression="snappy",
                       row_group_size=n)
        with FileReader(p) as r:
            out = r.to_arrow(read_dictionary=["s"])
        assert out.column("s").to_pylist() == t.column("s").to_pylist()

    def test_non_dictable_columns_ignored_and_unknown_raises(self, tmp_path):
        from parquet_tpu.meta import ParquetFileError

        t = pa.table({"i": pa.array([1, 2, 3], pa.int64())})
        p = str(tmp_path / "nd.parquet")
        pq.write_table(t, p)
        with FileReader(p) as r:
            out = r.to_arrow(read_dictionary=["i"])  # not BYTE_ARRAY: ignored
            assert out.column("i").type == pa.int64()
            with pytest.raises(ParquetFileError, match="read_dictionary"):
                r.to_arrow(read_dictionary=["nope"])

    def test_both_backends_and_memory_ceiling(self, tmp_path):
        """Review regressions: tpu_roundtrip honors read_dictionary (the
        device-decoded indices pass through), and a memory-bounded reader
        does NOT charge the never-performed gather — a dict-preserving read
        fits where a materializing one would trip the ceiling."""
        n = 200_000
        uniq = [f"v{i:03d}" + "x" * 1000 for i in range(20)]
        t = pa.table({"s": pa.array([uniq[i % 20] for i in range(n)])})
        p = str(tmp_path / "big.parquet")
        pq.write_table(t, p, use_dictionary=["s"], compression="snappy",
                       row_group_size=n)
        for backend in BACKENDS:
            with FileReader(p, backend=backend) as r:
                out = r.to_arrow(read_dictionary=["s"])
            assert pa.types.is_dictionary(out.column("s").type), backend
            assert out.column("s").to_pylist() == t.column("s").to_pylist(), backend
        # ~200MB materialized vs ~1MB as indices+dict: the ceiling only
        # blocks the materializing read
        with FileReader(p, max_memory=40_000_000) as r:
            out = r.to_arrow(read_dictionary=["s"])
            assert out.column("s").num_chunks >= 1
        from parquet_tpu.core.alloc import AllocError

        with FileReader(p, max_memory=40_000_000) as r:
            with pytest.raises(AllocError):
                r.to_arrow()


class TestRowPathLogicalIngest:
    """write_row/write_rows accept the ROW-DOMAIN values iter_rows returns
    (datetime/date/time/Decimal/uint ints), converting to storage by the
    leaf's logical annotation — our own read output round-trips."""

    SCHEMA = """message m {
      required int64 ts (TIMESTAMP(MICROS, true));
      optional int64 tsn (TIMESTAMP(NANOS, false));
      required int32 d (DATE);
      required int32 tm (TIME_MILLIS);
      required int64 dec (DECIMAL(10, 2));
      required fixed_len_byte_array(13) decbig (DECIMAL(30, 2));
      optional binary decba (DECIMAL(20, 3));
      required int64 u64 (UINT_64);
    }"""

    def _rows(self):
        return [
            {
                "ts": dt.datetime(2024, 5, 6, 7, 8, 9, 123456, tzinfo=dt.timezone.utc),
                "tsn": np.datetime64("2021-03-04T05:06:07.123456789", "ns"),
                "d": dt.date(2024, 5, 6),
                "tm": dt.time(12, 34, 56, 789000),
                "dec": decimal.Decimal("12.34"),
                "decbig": decimal.Decimal("-123456789012345678.99"),
                "decba": decimal.Decimal("-7.125"),
                "u64": 2**64 - 3,
            },
            {
                "ts": dt.datetime(1999, 1, 1, tzinfo=dt.timezone.utc),
                "tsn": None,
                "d": dt.date(1970, 1, 2),
                "tm": dt.time(0, 0, 0, 1000),
                "dec": decimal.Decimal("-0.01"),
                "decbig": decimal.Decimal("7.00"),
                "decba": None,
                "u64": 0,
            },
        ]

    def test_row_domain_roundtrip(self, tmp_path):
        import io

        schema = parse_schema(self.SCHEMA)
        buf = io.BytesIO()
        with FileWriter(buf, schema) as w:
            w.write_rows(self._rows())
        buf.seek(0)
        with FileReader(buf) as r:
            back = list(r.iter_rows())
        # pyarrow agrees on the typed values
        buf.seek(0)
        pa_rows = pq.read_table(buf).to_pylist()
        assert pa_rows[0]["dec"] == decimal.Decimal("12.34")
        assert pa_rows[0]["u64"] == 2**64 - 3
        assert pa_rows[1]["d"] == dt.date(1970, 1, 2)
        # our own read output writes back and reads identically
        buf2 = io.BytesIO()
        with FileWriter(buf2, schema) as w:
            w.write_rows(back)
        buf2.seek(0)
        with FileReader(buf2) as r:
            assert list(r.iter_rows()) == back

    def test_inexact_decimal_scale_raises(self, tmp_path):
        import io

        from parquet_tpu.core.column_store import StoreError

        schema = parse_schema("message m { required int64 dec (DECIMAL(10, 2)); }")
        with pytest.raises(StoreError, match="exactly"):
            with FileWriter(io.BytesIO(), schema) as w:
                w.write_rows([{"dec": decimal.Decimal("1.999")}])

    def test_raw_storage_ints_still_accepted(self, tmp_path):
        import io

        schema = parse_schema(
            "message m { required int64 ts (TIMESTAMP(MICROS, true)); }"
        )
        buf = io.BytesIO()
        with FileWriter(buf, schema) as w:
            w.write_rows([{"ts": 1_700_000_000_000_000}])  # already micros
        buf.seek(0)
        with FileReader(buf) as r:
            (row,) = list(r.iter_rows())
        assert row["ts"] == dt.datetime(
            2023, 11, 14, 22, 13, 20, tzinfo=dt.timezone.utc
        )

    def test_far_timestamps_exact(self, tmp_path):
        """Review regression: epoch micros compute with exact integer
        arithmetic — float total_seconds() drifted microseconds for dates
        centuries from epoch."""
        import io

        schema = parse_schema(
            "message m { required int64 ts (TIMESTAMP(MICROS, true)); }"
        )
        vals = [
            dt.datetime(1683, 8, 21, 18, 28, 30, 953893, tzinfo=dt.timezone.utc),
            dt.datetime(3772, 2, 3, 4, 5, 6, 7, tzinfo=dt.timezone.utc),
        ]
        buf = io.BytesIO()
        with FileWriter(buf, schema) as w:
            w.write_rows([{"ts": v} for v in vals])
        buf.seek(0)
        with FileReader(buf) as r:
            back = [row["ts"] for row in r.iter_rows()]
        assert back == vals

    def test_decimal_width_overflow_is_store_error(self, tmp_path):
        import io

        from parquet_tpu.core.column_store import StoreError

        schema = parse_schema(
            "message m { required fixed_len_byte_array(3) d (DECIMAL(12, 2)); }"
        )
        with pytest.raises(StoreError, match="does not fit"):
            with FileWriter(io.BytesIO(), schema) as w:
                w.write_rows([{"d": decimal.Decimal("9999999999.99")}])

    def test_split_groups_rejects_codec(self, tmp_path, capsys):
        from parquet_tpu.tools.parquet_tool import main as tool_main

        src = str(tmp_path / "s.parquet")
        t = pa.table({"a": pa.array([1, 2, 3], pa.int64())})
        pq.write_table(t, src)
        rc = tool_main(
            ["split", "--groups", "1", "--codec", "zstd", src,
             str(tmp_path / "p_%d.parquet")]
        )
        assert rc == 2
        assert "verbatim" in capsys.readouterr().err


class TestExtensionTypes:
    """UUID/JSON extension TYPES are deliberately not surfaced by to_arrow
    (they cannot ride the zero-group/nested/dictionary lanes), but
    write_column ACCEPTS pyarrow extension arrays — the storage unwraps."""

    def test_extension_array_ingest(self, tmp_path):
        import io
        import uuid

        schema = parse_schema("""message m {
          required binary j (JSON);
          required fixed_len_byte_array(16) u (UUID);
        }""")
        u1, u2 = uuid.uuid4(), uuid.uuid4()
        j = pa.ExtensionArray.from_storage(
            pa.json_(pa.string()), pa.array(['{"a": 1}', "[]"], pa.string())
        )
        u = pa.ExtensionArray.from_storage(
            pa.uuid(), pa.array([u1.bytes, u2.bytes], pa.binary(16))
        )
        buf = io.BytesIO()
        with FileWriter(buf, schema) as w:
            w.write_column("j", j)
            w.write_column("u", u)
        buf.seek(0)
        got = pq.read_table(buf)
        assert got.column("j").to_pylist() == ['{"a": 1}', "[]"]
        assert got.column("u").to_pylist() == [u1, u2]  # pyarrow yields UUIDs
        # our reader keeps raw binary (documented convention, incl. for
        # foreign non-UTF-8 JSON payloads pyarrow's extension would reject)
        buf.seek(0)
        with FileReader(buf) as r:
            out = r.to_arrow()
        assert out.column("j").type == pa.large_binary()
        assert out.column("u").type == pa.binary(16)


class TestSpecInvalidAnnotations:
    """Malformed FOREIGN annotations must fail safe: spec-invalid TIME
    unit/physical combos keep raw storage (never silently misread a unit),
    and out-of-range narrowing casts fail through ParquetFileError."""

    def _leaf(self, ptype, lt=None, ct=None):
        from parquet_tpu.core.schema import Column
        from parquet_tpu.meta.parquet_types import SchemaElement

        el = SchemaElement(
            name="c", type=int(ptype), logicalType=lt,
            converted_type=None if ct is None else int(ct),
        )
        return Column(element=el, path=("c",), leaf_index=0)

    def test_time_unit_physical_matrix(self):
        from parquet_tpu.core.arrow_nested import _logical_target
        from parquet_tpu.meta.parquet_types import (
            LogicalType,
            TimeType,
            TimeUnit,
            Type,
        )

        def time_lt(unit):
            return LogicalType(TIME=TimeType(isAdjustedToUTC=True, unit=unit))

        cases = [
            (TimeUnit.millis(), Type.INT32, pa.time32("ms")),
            (TimeUnit.millis(), Type.INT64, None),  # millis stored as int64: invalid
            (TimeUnit.micros(), Type.INT64, pa.time64("us")),
            (TimeUnit.micros(), Type.INT32, None),
            (TimeUnit.nanos(), Type.INT64, pa.time64("ns")),
            (TimeUnit.nanos(), Type.INT32, None),
            (None, Type.INT64, None),  # missing unit: invalid
            (None, Type.INT32, None),
        ]
        for unit, ptype, want in cases:
            got = _logical_target(pa, self._leaf(ptype, lt=time_lt(unit)))
            assert got == want, (unit, ptype, got)

    def test_narrowing_overflow_raises_parquet_error(self):
        from parquet_tpu.core.arrow_nested import retype_leaf
        from parquet_tpu.meta.file_meta import ParquetFileError
        from parquet_tpu.meta.parquet_types import IntType, LogicalType, Type

        leaf = self._leaf(
            Type.INT32, lt=LogicalType(INTEGER=IntType(bitWidth=8, isSigned=True))
        )
        ok = retype_leaf(pa, leaf, pa.array([1, -7, 127], pa.int32()))
        assert ok.type == pa.int8() and ok.to_pylist() == [1, -7, 127]
        with pytest.raises(ParquetFileError, match="overflow"):
            retype_leaf(pa, leaf, pa.array([1, 300], pa.int32()))

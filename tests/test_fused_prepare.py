"""The fused GIL-free native chunk prepare (ptq_chunk_prepare via
_native_ext.chunk_prepare / ctypes).

Three contracts pinned here:
  * byte-identical ChunkData between the fused walk and the staged per-page
    Python walk (PQT_FUSED_PREPARE=0) across the encoding x codec x page
    version x nullable/nested matrix, with read_chunk as a third oracle;
  * observability: prepare_fused_engaged / prepare_fused_declined trace
    counters say which path a chunk took, and the fused walk's internal
    stage split lands in prepare.* stages;
  * thread-safety + GIL release: concurrent prepares from >= 4 threads are
    correct, and on a multi-core host the walk delivers more than one
    effective core of throughput.
"""

from __future__ import annotations

import concurrent.futures as cf
import os
import time
from contextlib import contextmanager

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from parquet_tpu.core.arrays import ByteArrayData
from parquet_tpu.core.chunk import ChunkWindow, chunk_byte_range, read_chunk
from parquet_tpu.core.reader import FileReader
from parquet_tpu.kernels.pipeline import plan_chunk_tpu, prepare_chunk_plan
from parquet_tpu.utils.native import get_native
from parquet_tpu.utils.trace import decode_trace

_lib = get_native()
requires_native = pytest.mark.skipif(
    _lib is None or not _lib.has_chunk_prepare,
    reason="native chunk_prepare not built",
)


@contextmanager
def _env(**kv):
    old = {k: os.environ.get(k) for k in kv}
    os.environ.update(kv)
    try:
        yield
    finally:
        for k, v in old.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


# -- the differential matrix ---------------------------------------------------

ROWS = 20_000


def _column(kind):
    """(arrow array, write kwargs) for one matrix shape."""
    rng = np.random.default_rng(11)
    if kind == "plain_i64":
        return pa.array(rng.integers(-(1 << 40), 1 << 40, ROWS), pa.int64()), {
            "use_dictionary": False,
            "column_encoding": {"v": "PLAIN"},
        }
    if kind == "plain_f32":
        return pa.array(rng.random(ROWS).astype(np.float32)), {
            "use_dictionary": False,
            "column_encoding": {"v": "PLAIN"},
        }
    if kind == "dict_str":
        return pa.array([f"val_{i % 97}" for i in range(ROWS)]), {
            "use_dictionary": ["v"],
        }
    if kind == "delta_i64":
        return pa.array(np.cumsum(rng.integers(0, 50, ROWS)).astype(np.int64)), {
            "use_dictionary": False,
            "column_encoding": {"v": "DELTA_BINARY_PACKED"},
        }
    if kind == "bss_f32":
        return pa.array(rng.random(ROWS).astype(np.float32)), {
            "use_dictionary": False,
            "column_encoding": {"v": "BYTE_STREAM_SPLIT"},
        }
    if kind == "nullable_i64":
        mask = rng.random(ROWS) < 0.25
        return pa.array(
            rng.integers(0, 1 << 30, ROWS), pa.int64(), mask=mask
        ), {"use_dictionary": False, "column_encoding": {"v": "PLAIN"}}
    if kind == "nested_list":
        lengths = rng.integers(0, 5, ROWS // 4)
        vals = rng.integers(0, 1 << 20, int(lengths.sum())).astype(np.int32)
        offs = np.zeros(len(lengths) + 1, dtype=np.int64)
        np.cumsum(lengths, out=offs[1:])
        rows = [
            None if i % 7 == 0 else vals[offs[i] : offs[i + 1]].tolist()
            for i in range(len(lengths))
        ]
        return pa.array(rows, pa.list_(pa.int32())), {"use_dictionary": False}
    raise AssertionError(kind)


def _build(tmp_path, kind, codec, version):
    arr, kw = _column(kind)
    p = str(tmp_path / f"{kind}_{codec}_{version.replace('.', '')}.parquet")
    pq.write_table(
        pa.table({"v": arr}),
        p,
        compression=codec,
        data_page_version=version,  # pyarrow spells them "1.0"/"2.0"
        row_group_size=ROWS // 3,  # several pages/chunks per file
        **kw,
    )
    return p


def _prepare_chunks(path, fused: bool):
    """Every chunk's ChunkData via the device-plan pipeline, fused or staged."""
    env = {"PQT_FUSED_PREPARE": "1" if fused else "0"}
    out = []
    with _env(**env), decode_trace() as tr:
        with FileReader(path) as r:
            for i in range(r.num_row_groups):
                for _p, cc, col in r._selected_chunks(i):
                    off, total = chunk_byte_range(cc)
                    win = ChunkWindow(r._pread(off, total), off)
                    out.append(plan_chunk_tpu(win, cc, col).finalize())
    return out, tr


def _host_chunks(path):
    out = []
    with FileReader(path) as r:
        for i in range(r.num_row_groups):
            for _p, cc, col in r._selected_chunks(i):
                off, total = chunk_byte_range(cc)
                win = ChunkWindow(r._pread(off, total), off)
                out.append(read_chunk(win, cc, col))
    return out


def _assert_chunkdata_equal(a, b, ctx):
    assert a.num_values == b.num_values, ctx
    va, vb = a.values, b.values
    if isinstance(va, ByteArrayData) or isinstance(vb, ByteArrayData):
        assert isinstance(va, ByteArrayData) and isinstance(vb, ByteArrayData), ctx
        assert np.array_equal(va.offsets, vb.offsets), ctx
        assert bytes(va.data) == bytes(vb.data), ctx
    else:
        na, nb = np.asarray(va), np.asarray(vb)
        assert na.dtype == nb.dtype, (ctx, na.dtype, nb.dtype)
        assert np.array_equal(
            na.view((np.uint8, na.dtype.itemsize)) if na.itemsize > 1 else na,
            nb.view((np.uint8, nb.dtype.itemsize)) if nb.itemsize > 1 else nb,
        ), ctx
    for attr in ("def_levels", "rep_levels"):
        la, lb = getattr(a, attr), getattr(b, attr)
        assert (la is None) == (lb is None), (ctx, attr)
        if la is not None:
            assert np.array_equal(np.asarray(la), np.asarray(lb)), (ctx, attr)


@requires_native
@pytest.mark.parametrize("codec", ["none", "snappy", "gzip"])
@pytest.mark.parametrize("version", ["1.0", "2.0"])
@pytest.mark.parametrize(
    "kind",
    [
        "plain_i64",
        "plain_f32",
        "dict_str",
        "delta_i64",
        "bss_f32",
        "nullable_i64",
        "nested_list",
    ],
)
def test_fused_matches_staged_and_host(tmp_path, kind, codec, version):
    path = _build(tmp_path, kind, codec, version)
    fused, tr_fused = _prepare_chunks(path, fused=True)
    staged, tr_staged = _prepare_chunks(path, fused=False)
    host = _host_chunks(path)
    ctx = (kind, codec, version)
    assert len(fused) == len(staged) == len(host), ctx
    for a, b, c in zip(fused, staged, host):
        _assert_chunkdata_equal(a, b, ctx)
        _assert_chunkdata_equal(a, c, ctx)
    # the fused run must actually have taken the fused path for every chunk
    engaged = tr_fused.stages.get("prepare_fused_engaged")
    assert engaged is not None and engaged.calls == len(fused), ctx
    assert "prepare_fused_declined" not in tr_fused.stages, ctx
    # the kill-switch run must not have touched the fused walk
    assert "prepare_fused_engaged" not in tr_staged.stages, ctx


@requires_native
def test_fused_stage_breakdown_collected(tmp_path):
    """Under an active trace the walk reports its internal stage split."""
    path = _build(tmp_path, "dict_str", "snappy", "2.0")
    _, tr = _prepare_chunks(path, fused=True)
    assert tr.stages["prepare.decompress"].seconds > 0
    # dict-index pages prescan their run headers inside the walk
    assert "prepare.prescan" in tr.stages


@requires_native
def test_fused_crc_validation_stays_engaged(tmp_path):
    """validate_crc no longer forfeits the fused walk: stored CRCs verify
    INSIDE the native prepare, so clean chunks stay on the fast path (the
    counters say so), and the decode matches the staged walk exactly."""
    import pyarrow.parquet as _pq

    arr, kw = _column("plain_i64")
    path = str(tmp_path / "crc.parquet")
    _pq.write_table(
        pa.table({"v": arr}), path, compression="snappy",
        write_page_checksum=True, row_group_size=ROWS // 3, **kw,
    )
    with decode_trace() as tr:
        with FileReader(path) as r:
            plans = []
            for i in range(r.num_row_groups):
                for _p, cc, col in r._selected_chunks(i):
                    off, total = chunk_byte_range(cc)
                    win = ChunkWindow(r._pread(off, total), off)
                    plans.append(
                        prepare_chunk_plan(win, cc, col, validate_crc=True)
                        .dispatch_device()
                        .finalize()
                    )
    engaged = tr.stages.get("prepare_fused_engaged")
    assert engaged is not None and engaged.calls == len(plans)
    assert "prepare_fused_declined" not in tr.stages
    assert "prepare.crc" in tr.stages
    host = _host_chunks(path)
    for a, b in zip(plans, host):
        _assert_chunkdata_equal(a, b, "crc-validated fused")


@requires_native
def test_fused_prepare_reader_end_to_end(tmp_path):
    """read_row_group through the device backend equals the host backend with
    the fused walk engaged (the whole-reader differential)."""
    path = _build(tmp_path, "dict_str", "snappy", "1.0")
    with decode_trace() as tr:
        with FileReader(path, backend="tpu_roundtrip") as r:
            dev = [r.read_row_group(i) for i in range(r.num_row_groups)]
    assert tr.stages["prepare_fused_engaged"].calls > 0
    with FileReader(path, backend="host") as r:
        host = [r.read_row_group(i) for i in range(r.num_row_groups)]
    for rg_d, rg_h in zip(dev, host):
        assert rg_d.keys() == rg_h.keys()
        for p in rg_d:
            _assert_chunkdata_equal(rg_d[p], rg_h[p], p)


# -- multi-thread stress (the released-GIL contract) ---------------------------


def _stress_work(tmp_path, n_groups=12):
    rng = np.random.default_rng(3)
    rows = 240_000
    t = pa.table(
        {
            "a": pa.array(rng.integers(0, 1 << 40, rows), pa.int64()),
            "s": pa.array([f"k{i % 211}" for i in range(rows)]),
        }
    )
    p = str(tmp_path / "stress.parquet")
    pq.write_table(
        t,
        p,
        compression="snappy",
        use_dictionary=["s"],
        column_encoding={"a": "PLAIN"},
        row_group_size=rows // n_groups,
    )
    work = []
    with FileReader(p) as r:
        for i in range(r.num_row_groups):
            for _p, cc, col in r._selected_chunks(i):
                off, total = chunk_byte_range(cc)
                work.append((r._pread(off, total), off, cc, col))
    return work


def _prep_item(item):
    buf, off, cc, col = item
    return prepare_chunk_plan(ChunkWindow(buf, off), cc, col)


@requires_native
def test_multithreaded_fused_prepare_correct(tmp_path):
    """>= 4 threads hammering the fused walk concurrently produce exactly the
    serial results (thread-local scratch, no shared mutable state)."""
    work = _stress_work(tmp_path)
    serial = [_prep_item(it).dispatch_device().finalize() for it in work]
    with cf.ThreadPoolExecutor(max_workers=4) as pool:
        for _round in range(3):
            plans = list(pool.map(_prep_item, work))
            for plan, want, it in zip(plans, serial, work):
                got = plan.dispatch_device().finalize()
                _assert_chunkdata_equal(got, want, it[2].meta_data.path_in_schema)


@requires_native
@pytest.mark.skipif((os.cpu_count() or 1) < 2, reason="single-core host")
def test_multithreaded_fused_prepare_scales(tmp_path):
    """The fused walk holds no GIL while crunching: on a multi-core host,
    4 prepare threads must beat 1 (best-of-7 each, > 1 effective core).

    Chunks are sized so the GIL-free C walk dominates each prepare — tiny
    chunks measure executor overhead and the GIL-held plan assembly instead
    (Amdahl), which is not the contract under test."""
    rng = np.random.default_rng(5)
    rows = 1_000_000
    t = pa.table({"v": pa.array(rng.integers(0, 1000, rows).astype(np.int64))})
    p = str(tmp_path / "scale.parquet")
    pq.write_table(
        t, p, compression="snappy", use_dictionary=False,
        column_encoding={"v": "PLAIN"}, row_group_size=rows // 8,
    )
    work = []
    with FileReader(p) as r:
        for i in range(r.num_row_groups):
            for _pp, cc, col in r._selected_chunks(i):
                off, total = chunk_byte_range(cc)
                work.append((r._pread(off, total), off, cc, col))
    for it in work:
        _prep_item(it)  # warm native buffers + page cache

    def serial():
        for it in work:
            _prep_item(it)

    with cf.ThreadPoolExecutor(max_workers=4) as pool:

        def threaded():
            list(pool.map(_prep_item, work))

        threaded()  # per-thread scratch warmup
        # A held GIL serializes the C walks, so threaded can NEVER beat
        # serial; a shared/loaded CI host merely makes any single sample
        # noisy. Retrying distinguishes the two: real parallelism wins some
        # attempt, a serialized walk wins none. (8 attempts: on cgroup
        # cpu-shares-throttled 2-vCPU boxes the quiet windows where threads
        # can actually run side by side are minutes apart — observed 2-of-4
        # spurious failures at 3 attempts with the walk fully GIL-free.)
        ts = tp = None
        for _attempt in range(8):
            ts = min(_walltime(serial) for _ in range(7))
            tp = min(_walltime(threaded) for _ in range(7))
            if tp < ts:
                break
    if tp >= ts and not _host_can_thread():
        # the PREMISE failed, not the contract: this host (throttled
        # shared vCPUs) cannot run even two known-GIL-free zlib threads
        # side by side right now, so no walk could demonstrate scaling
        pytest.skip("host cannot run 2 GIL-free C threads concurrently")
    assert tp < ts, f"no scaling: serial {ts * 1e3:.1f}ms threaded {tp * 1e3:.1f}ms"


def _host_can_thread() -> bool:
    """Calibration: can THIS host, RIGHT NOW, run two threads of plain C
    work (zlib.compress — drops the GIL unconditionally) faster than the
    same work serially? Distinguishes 'the fused walk holds the GIL' (a
    real bug, fails everywhere) from 'this CI box has no second core to
    give' (cgroup shares / SMT-sibling vCPUs / noisy neighbors)."""
    import threading
    import zlib

    data = bytes(range(256)) * 8192  # ~2 MiB, big enough to dwarf overhead

    def crunch():
        for _ in range(4):
            zlib.compress(data, 6)

    crunch()
    best_serial = min(_walltime(lambda: (crunch(), crunch())) for _ in range(3))

    def pair():
        threads = [threading.Thread(target=crunch) for _ in range(2)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()

    best_pair = min(_walltime(pair) for _ in range(3))
    return best_pair < best_serial * 0.85


def _walltime(fn):
    t0 = time.perf_counter()
    fn()
    return time.perf_counter() - t0

"""parquet_tpu.data.controller: the elastic-SLO AIMD controller.

Pinned here:
  * the fake-clock step response: a wait spike grows the prefetch target
    within k control windows (additive), a sustained idle decays it
    (multiplicative), mixed traffic holds;
  * no traffic = no movement (windowed deltas of zero observations);
  * dataset_slo_violations_total counts over-SLO observations;
  * dataset wiring: slo_wait_ms attaches a controller, targets reach the
    pool and the fill loop, and — the stream contract — the delivered
    batch stream and checkpoint/resume stay BYTE-IDENTICAL with the
    controller on, off, or mid-adaptation;
  * parquet-tool scan --slo-ms: the CI gate passes on a generous SLO and
    exits non-zero (one-line report) on an impossible one.
"""

from __future__ import annotations

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from parquet_tpu.data import AIMDController, ParquetDataset
from parquet_tpu.utils import metrics


class FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


def make_controller(**kw):
    clock = FakeClock()
    reg = metrics.MetricsRegistry()
    kw.setdefault("slo_wait_ms", 10.0)
    kw.setdefault("window_s", 1.0)
    kw.setdefault("initial_depth", 2)
    kw.setdefault("max_workers", 8)
    ctl = AIMDController(clock=clock, registry=reg, **kw)
    return ctl, clock, reg


def tick_window(ctl, clock, step=1.0):
    clock.t += step
    return ctl.tick()


class TestControlLaw:
    def test_arms_then_evaluates_per_window(self):
        ctl, clock, reg = make_controller()
        assert ctl.tick() is False  # arming
        assert ctl.tick() is False  # same window
        clock.t += 0.5
        assert ctl.tick() is False  # window not elapsed
        clock.t += 0.6
        assert ctl.tick() is True
        assert ctl.ticks == 1

    def test_spike_grows_depth_within_k_ticks(self):
        ctl, clock, reg = make_controller(initial_depth=1, increase_step=1)
        ctl.tick()  # arm
        for k in range(4):
            # every window: waits far over the 10 ms SLO
            for _ in range(20):
                reg.observe("dataset_wait_seconds", 0.05)
            tick_window(ctl, clock)
        assert ctl.prefetch_target == 1 + 4  # additive, one step per window
        assert ctl.increases == 4

    def test_depth_caps_at_max(self):
        ctl, clock, reg = make_controller(initial_depth=1, max_depth=3)
        ctl.tick()
        for _ in range(6):
            for _ in range(10):
                reg.observe("dataset_wait_seconds", 0.05)
            tick_window(ctl, clock)
        assert ctl.prefetch_target == 3

    def test_idle_decays_multiplicatively_after_streak(self):
        ctl, clock, reg = make_controller(
            initial_depth=8, idle_windows=3, decrease_factor=0.5
        )
        ctl.tick()
        for k in range(3):
            for _ in range(20):
                reg.observe("dataset_wait_seconds", 0.0001)  # far under SLO
            tick_window(ctl, clock)
        assert ctl.prefetch_target == 4  # 8 * 0.5 after the 3-window streak
        assert ctl.decreases == 1

    def test_depth_floors_at_min(self):
        ctl, clock, reg = make_controller(
            initial_depth=2, min_depth=1, idle_windows=1
        )
        ctl.tick()
        for _ in range(6):
            for _ in range(5):
                reg.observe("dataset_wait_seconds", 0.0001)
            tick_window(ctl, clock)
        assert ctl.prefetch_target == 1

    def test_slo_below_smallest_bucket_drives_on_mean_only(self):
        # an SLO under every histogram bound has no bucket witness: healthy
        # waits must NOT all count as violations (depth would climb to max
        # on a perfectly fine source) — the mean-wait signal drives alone
        ctl, clock, reg = make_controller(
            slo_wait_ms=0.4, initial_depth=2, idle_windows=1
        )
        ctl.tick()
        for _ in range(3):
            for _ in range(20):
                reg.observe("dataset_wait_seconds", 0.00001)  # healthy
            tick_window(ctl, clock)
        assert ctl.increases == 0  # never pressured
        assert reg.get("dataset_slo_violations_total") == 0
        # mean wait over the SLO still pressures
        for _ in range(20):
            reg.observe("dataset_wait_seconds", 0.005)
        tick_window(ctl, clock)
        assert ctl.increases == 1

    def test_no_traffic_holds(self):
        ctl, clock, reg = make_controller(initial_depth=4, idle_windows=1)
        ctl.tick()
        for _ in range(5):
            tick_window(ctl, clock)  # zero observations in every window
        assert ctl.prefetch_target == 4
        assert ctl.increases == 0 and ctl.decreases == 0

    def test_moderate_traffic_holds_and_resets_idle_streak(self):
        ctl, clock, reg = make_controller(
            initial_depth=4, idle_windows=2, idle_fraction=0.1
        )
        ctl.tick()
        for _ in range(4):
            # mean wait between idle_fraction*SLO and SLO: neither signal
            for _ in range(10):
                reg.observe("dataset_wait_seconds", 0.005)
            tick_window(ctl, clock)
        assert ctl.prefetch_target == 4

    def test_violations_counter(self):
        # reads AND writes go through the injected registry: a test (or a
        # second dataset) with its own registry is fully isolated
        ctl, clock, reg = make_controller()
        before = metrics.get("dataset_slo_violations_total")
        ctl.tick()
        for _ in range(7):
            reg.observe("dataset_wait_seconds", 0.05)  # > 10 ms SLO
        for _ in range(3):
            reg.observe("dataset_wait_seconds", 0.0001)
        tick_window(ctl, clock)
        assert reg.get("dataset_slo_violations_total") == 7
        assert metrics.get("dataset_slo_violations_total") == before

    def test_worker_target_tracks_depth_clamped(self):
        ctl, clock, reg = make_controller(initial_depth=2, max_workers=4)
        assert ctl.worker_target == 2
        ctl.tick()
        for _ in range(8):
            for _ in range(10):
                reg.observe("dataset_wait_seconds", 0.05)
            tick_window(ctl, clock)
        assert ctl.prefetch_target == 10
        assert ctl.worker_target == 4  # clamped

    def test_readahead_budget_scales_with_depth(self):
        ctl, clock, reg = make_controller(
            initial_depth=3, readahead_unit_bytes=1 << 20
        )
        assert ctl.readahead_budget == 3 << 20

    def test_prefetch_target_gauge(self):
        ctl, clock, reg = make_controller(initial_depth=5)
        assert reg.get("dataset_prefetch_target") == 5

    def test_validation(self):
        with pytest.raises(ValueError):
            AIMDController(slo_wait_ms=0)
        with pytest.raises(ValueError):
            AIMDController(slo_wait_ms=5, min_depth=0)
        with pytest.raises(ValueError):
            AIMDController(slo_wait_ms=5, decrease_factor=1.5)
        with pytest.raises(ValueError):
            AIMDController(slo_wait_ms=5, window_s=0)


# -- dataset wiring -------------------------------------------------------------

N_FILES = 4
ROWS = 900
ROW_GROUP = 150


@pytest.fixture(scope="module")
def pattern(tmp_path_factory):
    d = tmp_path_factory.mktemp("ctl_shards")
    rng = np.random.default_rng(3)
    for i in range(N_FILES):
        t = pa.table(
            {
                "x": pa.array(rng.integers(0, 1 << 40, ROWS).astype(np.int64)),
            }
        )
        pq.write_table(
            t, str(d / f"s-{i:02d}.parquet"), row_group_size=ROW_GROUP
        )
    return str(d / "s-*.parquet")


def _drain(it):
    return [{k: np.asarray(v) for k, v in b.items()} for b in it]


def _batches_equal(a, b):
    assert len(a) == len(b)
    for ba, bb in zip(a, b):
        assert ba.keys() == bb.keys()
        for k in ba:
            assert np.array_equal(ba[k], bb[k]), k


class TestDatasetWiring:
    def test_slo_attaches_controller(self, pattern):
        ds = ParquetDataset(pattern, batch_size=128, slo_wait_ms=5.0)
        assert ds._controller is not None
        assert ds._controller.slo_wait_ms == 5.0
        ds2 = ParquetDataset(pattern, batch_size=128)
        assert ds2._controller is None

    def test_stream_identical_with_and_without_controller(self, pattern):
        """THE advisory contract: the controller changes speed, never the
        stream."""
        kw = dict(batch_size=128, shuffle=True, seed=11, remainder="keep")
        with ParquetDataset(pattern, **kw) as plain:
            ref = _drain(iter(plain))
        with ParquetDataset(pattern, slo_wait_ms=1.0, **kw) as elastic:
            got = _drain(iter(elastic))
        _batches_equal(ref, got)

    def test_resume_byte_identical_with_controller_mid_adaptation(self, pattern):
        """Checkpoint mid-epoch while the controller is live (and has
        moved the depth), resume on a dataset with DIFFERENT controller
        settings: the remaining stream must be byte-identical — controller
        state is advisory and absent from state_dict."""
        kw = dict(batch_size=100, shuffle=True, seed=7, remainder="keep")
        clock = FakeClock()
        ctl = AIMDController(
            slo_wait_ms=0.001, initial_depth=1, window_s=0.001, clock=clock
        )
        with ParquetDataset(pattern, controller=ctl, **kw) as ds:
            it = iter(ds)
            first = [next(it) for _ in range(9)]
            clock.t += 10  # force control windows to elapse between waits
            state = it.state_dict()
            rest_live = _drain(it)
        assert "controller" not in state and "prefetch" not in state
        # resume WITHOUT a controller
        with ParquetDataset(pattern, **kw) as ds2:
            it2 = ds2.iterator(state)
            rest_resumed = _drain(it2)
        _batches_equal(rest_live, rest_resumed)
        # and the full stream from scratch agrees
        with ParquetDataset(pattern, slo_wait_ms=5000.0, **kw) as ds3:
            full = _drain(iter(ds3))
        _batches_equal(first + rest_live, full)

    def test_pool_grows_with_target(self, pattern):
        clock = FakeClock()
        reg = metrics.MetricsRegistry()
        ctl = AIMDController(
            slo_wait_ms=10.0, initial_depth=1, window_s=1.0,
            max_workers=4, clock=clock, registry=reg,
        )
        ds = ParquetDataset(pattern, batch_size=128, controller=ctl)
        with ds:
            pool = ds._worker_pool()
            assert pool._max_workers == 1
            ctl.tick()
            for _ in range(3):
                for _ in range(10):
                    reg.observe("dataset_wait_seconds", 0.05)
                clock.t += 1.0
                ctl.tick()
            ds._apply_controller_targets()
            assert pool._max_workers == ctl.worker_target > 1


class TestScanSloGate:
    def test_generous_slo_passes(self, pattern, capsys):
        from parquet_tpu.tools.parquet_tool import main as tool_main

        rc = tool_main(["scan", pattern, "--slo-ms", "60000"])
        out = capsys.readouterr().out
        assert rc == 0
        assert "slo held" in out

    def test_impossible_slo_fails_with_one_line_report(self, pattern, capsys):
        from parquet_tpu.tools.parquet_tool import main as tool_main

        rc = tool_main(
            ["scan", pattern, "--slo-ms", "0.000001", "--json"]
        )
        out = capsys.readouterr().out
        assert rc != 0
        [line] = [ln for ln in out.splitlines() if "slo VIOLATED" in ln]
        assert "p99 wait" in line
        # the --json artifact carries the same verdict
        import json as _json

        blob = next(
            _json.loads(ln) for ln in out.splitlines() if ln.startswith("{")
        )
        assert blob["slo"]["held"] is False

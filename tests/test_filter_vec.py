"""The vectorized residual filter (core/filter_vec) vs the scalar oracle.

Differential matrix, same contract as tests/test_assembly_vec.py: every
predicate the mask pipeline claims must produce BYTE-IDENTICAL rows to the
scalar row_matches walk (PQT_VEC_FILTER=0) across the type zoo — ints,
floats (incl. NaN), unsigned, decimal (int- and binary-backed), strings
and raw binary (incl. embedded/trailing NULs), timestamps, dates, bools,
nulls everywhere, and nested LIST 'contains' predicates — and corrupt
inputs must fail typed-or-identical under either engine. The arrow path
(`to_arrow(filters=)`) is pinned the same way: the buffer-level-take fast
path must match the pyarrow-compute fallback, including not_in's
null-keeping convention. The device twins (kernels/device_ops) are pinned
against the host masks.
"""

from __future__ import annotations

import datetime as dt
import decimal
import glob
import os

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from parquet_tpu.core.filter import FilterError, normalize_dnf
from parquet_tpu.core.filter_vec import (
    VecFilterError,
    dnf_mask,
    group_row_count,
    mask_to_ranges,
    masked_flat_columns,
)
from parquet_tpu.core.reader import PARQUET_ERRORS, FileReader
from parquet_tpu.utils import metrics

CORPUS_DIR = os.path.join(os.path.dirname(__file__), "data", "corrupt")

N = 800


def _zoo_table() -> pa.Table:
    rng = np.random.default_rng(29)

    def some(i, v, k=7):
        return None if i % k == 0 else v

    f = rng.standard_normal(N)
    f[::13] = np.nan
    return pa.table(
        {
            "i64": pa.array([some(i, i - N // 2) for i in range(N)], pa.int64()),
            "i32": pa.array(np.arange(N, dtype=np.int32)),
            "u32": pa.array(
                [some(i, (1 << 31) + i, 11) for i in range(N)], pa.uint32()
            ),
            "u64": pa.array(
                [(1 << 63) + i for i in range(N)], pa.uint64()
            ),
            "f": pa.array([some(i, float(x), 5) for i, x in enumerate(f)]),
            "s": pa.array([some(i + 1, f"v{i % 23}") for i in range(N)]),
            "b": pa.array(
                [
                    some(i, [b"a", b"a\x00", b"a\x00b", b"", b"ab"][i % 5], 9)
                    for i in range(N)
                ],
                pa.binary(),
            ),
            "dec": pa.array(
                [some(i, decimal.Decimal(i - 40) / 4) for i in range(N)],
                pa.decimal128(9, 2),
            ),
            "bigdec": pa.array(
                [some(i, decimal.Decimal(i) / 100) for i in range(N)],
                pa.decimal128(30, 2),  # binary-backed: vec must decline
            ),
            "ts": pa.array(
                [
                    some(i, dt.datetime(2024, 1, 1) + dt.timedelta(seconds=i))
                    for i in range(N)
                ],
                pa.timestamp("us"),
            ),
            "day": pa.array(
                [some(i, dt.date(2024, 1, 1) + dt.timedelta(days=i % 90))
                 for i in range(N)],
                pa.date32(),
            ),
            "flag": pa.array([some(i, i % 3 == 0) for i in range(N)]),
            "tags": pa.array(
                [some(i, [f"t{j % 6}" for j in range(i % 5)]) for i in range(N)],
                pa.list_(pa.string()),
            ),
            "nums": pa.array(
                [some(i, [some(j, j, 4) for j in range(i % 4)], 6)
                 for i in range(N)],
                pa.list_(pa.int64()),
            ),
        }
    )


@pytest.fixture(scope="module")
def zoo(tmp_path_factory):
    p = tmp_path_factory.mktemp("filter_vec") / "zoo.parquet"
    pq.write_table(_zoo_table(), str(p), row_group_size=256)
    return str(p)


ZOO_FILTERS = [
    [("i64", "==", 0)],
    [("i64", "!=", 0)],
    [("i64", "<", -100)],
    [("i64", ">=", 2.5)],  # inexact bracket on an int column
    [("i32", ">", 400)],
    [("u32", ">=", (1 << 31) + 500)],
    [("u64", ">", (1 << 63) + 700)],
    [("f", ">", 0.5)],
    [("f", "<=", float("nan"))],  # NaN comparisons: everything fails
    [("f", "!=", float("nan"))],
    [("f", "is_null")],
    [("f", "not_null"), ("i32", "<", 50)],
    [("s", "==", "v3")],
    [("s", ">=", "v20")],
    [("s", "in", ["v1", "v9", "zzz"])],
    [("s", "not_in", ["v1"])],
    [("b", "==", b"a\x00")],
    [("b", "<", b"a\x00b")],
    [("b", ">=", b"a")],
    [("b", "in", [b"", b"ab"])],
    [("dec", ">=", decimal.Decimal("1.505"))],  # between representables
    [("dec", "==", decimal.Decimal("1.50"))],
    [("bigdec", ">", decimal.Decimal("1.0"))],  # binary-backed: scalar path
    [("ts", "<", dt.datetime(2024, 1, 1, 0, 5))],
    [("ts", ">=", dt.datetime(2024, 1, 1, 0, 5, 0, 500_000))],
    [("day", "==", dt.date(2024, 1, 10))],
    [("flag", "==", True)],
    [("flag", "!=", False)],
    [("tags", "contains", "t4")],
    [("nums", "contains", 2)],
    [[("i32", "<", 20)], [("s", "==", "v7"), ("i32", ">", 700)]],
    [("i64", "not_null"), ("s", "not_null"), ("f", ">", -0.5), ("i32", "<", 600)],
]


def _rows(path, filt, engine, **kw):
    os.environ["PQT_VEC_FILTER"] = "1" if engine == "vec" else "0"
    try:
        with FileReader(path, **kw) as r:
            return list(r.iter_rows(filters=filt))
    finally:
        os.environ.pop("PQT_VEC_FILTER", None)


def _norm(rows):
    """NaN-aware equality form: NaN cells must count as identical across
    engines (x != x would fail dict equality on genuinely matching rows)."""
    import math

    def nv(v):
        if isinstance(v, float) and math.isnan(v):
            return "__nan__"
        if isinstance(v, list):
            return [nv(x) for x in v]
        if isinstance(v, dict):
            return {k: nv(x) for k, x in v.items()}
        return v

    return [nv(r) for r in rows]


class TestDifferential:
    @pytest.mark.parametrize("filt", ZOO_FILTERS, ids=[str(f) for f in ZOO_FILTERS])
    def test_rows_identical(self, zoo, filt):
        assert _norm(_rows(zoo, filt, "vec")) == _norm(_rows(zoo, filt, "scalar"))

    def test_vec_engine_engages(self, zoo):
        snap = metrics.snapshot()
        rows = _rows(zoo, [("i32", ">", 100)], "vec")
        d = metrics.delta(snap)
        assert rows
        assert d.get('query_rows_filtered_total{engine="vec"}', 0) > 0
        assert not d.get('query_rows_filtered_total{engine="scalar"}', 0)

    def test_scalar_fallback_counts_and_matches(self, zoo):
        # binary-backed decimal has no orderable physical form: the mask
        # pipeline must decline and the scalar oracle must be counted
        snap = metrics.snapshot()
        filt = [("bigdec", ">", decimal.Decimal("1.0"))]
        vec = _rows(zoo, filt, "vec")
        d = metrics.delta(snap)
        assert d.get('query_rows_filtered_total{engine="scalar"}', 0) > 0
        assert _norm(vec) == _norm(_rows(zoo, filt, "scalar"))

    def test_projection_strips_filter_columns(self, zoo):
        filt = [("s", "==", "v3")]
        vec = _rows(zoo, filt, "vec", columns=["i32"])
        assert vec == _rows(zoo, filt, "scalar", columns=["i32"])
        assert vec and all(set(row) == {"i32"} for row in vec)

    def test_raw_mode_rejected_with_filters(self, zoo):
        with FileReader(zoo) as r:
            with pytest.raises(FilterError):
                list(r.iter_rows(raw=True, filters=[("i32", ">", 1)]))


class TestArrowPath:
    @pytest.mark.parametrize(
        "filt",
        [
            [("i32", ">", 400)],
            [("f", ">", 0.5)],
            [("s", "not_in", ["v1"])],  # nulls KEPT: pyarrow convention
            [("s", "in", ["v1", "v9"])],
            [("f", "is_null")],
            [("tags", "contains", "t4")],
            [[("i32", "<", 20)], [("s", "==", "v7")]],
        ],
        ids=str,
    )
    def test_fast_path_matches_fallback(self, zoo, filt):
        with FileReader(zoo) as r:
            fast = r.to_arrow(filters=filt)
        os.environ["PQT_VEC_FILTER"] = "0"
        try:
            with FileReader(zoo) as r:
                slow = r.to_arrow(filters=filt)
        finally:
            os.environ.pop("PQT_VEC_FILTER", None)
        # Table.equals treats NaN cells as unequal even when both sides
        # carry the identical NaN: compare schema + NaN-normalized values
        assert fast.schema.equals(slow.schema)
        assert _norm(fast.to_pylist()) == _norm(slow.to_pylist())

    def test_not_in_keeps_nulls_unlike_rows(self, zoo):
        # the pinned convention split: arrow keeps nulls on not_in, the
        # row predicate drops them
        filt = [("s", "not_in", ["v1"])]
        with FileReader(zoo) as r:
            t = r.to_arrow(filters=filt)
        rows = _rows(zoo, filt, "vec")
        nulls = sum(1 for v in t.column("s").to_pylist() if v is None)
        assert nulls > 0
        assert t.num_rows == len(rows) + nulls

    def test_float32_in_list_engines_agree(self, tmp_path):
        """pc.is_in CASTS the value set to the column type, so a float64
        member inexact in float32 matches under pyarrow but not under
        exact semantics — the vec fast path must decline (fallback
        decides) so to_arrow is engine-independent, while iter_rows keeps
        the scalar walk's exact convention on both engines."""
        p = str(tmp_path / "f32.parquet")
        pq.write_table(
            pa.table({"x": pa.array(np.array([0.1, 0.2, 0.3, 1.5], np.float32))}),
            p,
        )
        for filt, arrow_rows, row_rows in (
            ([("x", "in", [0.1, 1.5])], 2, 1),
            ([("x", "not_in", [0.1])], 3, 4),
        ):
            with FileReader(p) as r:
                fast = r.to_arrow(filters=filt)
            os.environ["PQT_VEC_FILTER"] = "0"
            try:
                with FileReader(p) as r:
                    slow = r.to_arrow(filters=filt)
            finally:
                os.environ.pop("PQT_VEC_FILTER", None)
            assert fast.equals(slow)
            assert fast.num_rows == arrow_rows, filt
            assert len(_rows(p, filt, "vec")) == row_rows, filt
            assert len(_rows(p, filt, "scalar")) == row_rows, filt

    def test_matches_pyarrow_read_table(self, zoo):
        import pyarrow.parquet as pqm

        for filt, ora in [
            ([("i32", ">", 400)], [("i32", ">", 400)]),
            ([("s", "in", ["v1", "v9"])], [("s", "in", ["v1", "v9"])]),
            ([("s", "not_in", ["v1"])], [("s", "not in", ["v1"])]),
        ]:
            with FileReader(zoo) as r:
                mine = r.to_arrow(filters=filt)
            assert mine.num_rows == pqm.read_table(zoo, filters=ora).num_rows


class TestContains:
    def test_contains_requires_list_column(self, zoo):
        with FileReader(zoo) as r:
            with pytest.raises(FilterError):
                list(r.iter_rows(filters=[("i32", "contains", 1)]))

    def test_contains_prunes_conservatively(self, tmp_path):
        # element stats bracket membership: a value outside every group's
        # min/max range prunes the group, a present one keeps it
        p = tmp_path / "lists.parquet"
        t = pa.table(
            {"xs": pa.array([[i, i + 1] for i in range(100)], pa.list_(pa.int64()))}
        )
        pq.write_table(t, str(p), row_group_size=25)
        with FileReader(p) as r:
            assert r.prune_row_groups([("xs", "contains", 1_000_000)]) == []
            assert list(r.iter_rows(filters=[("xs", "contains", 30)])) == [
                {"xs": [29, 30]},
                {"xs": [30, 31]},
            ]

    def test_null_and_empty_lists_never_match(self, zoo):
        for row in _rows(zoo, [("tags", "contains", "t0")], "vec"):
            assert row["tags"] and "t0" in row["tags"]


class TestCorruptCorpus:
    """Typed-or-identical on the corrupt corpus, under both engines."""

    @pytest.mark.parametrize(
        "path", sorted(glob.glob(os.path.join(CORPUS_DIR, "*.parquet")))
    )
    def test_typed_or_identical(self, path):
        filt = [("id", ">=", 0)] if "empty" not in path else [("id", ">=", 0)]
        results = {}
        for engine in ("vec", "scalar"):
            os.environ["PQT_VEC_FILTER"] = "1" if engine == "vec" else "0"
            try:
                with FileReader(path) as r:
                    try:
                        flt = filt
                        # pick a real column when 'id' isn't in this file
                        names = [c.name for c in r.schema.root.children]
                        if "id" not in names and names:
                            flt = [(names[0], "not_null")]
                        results[engine] = ("rows", list(r.iter_rows(filters=flt)))
                    except PARQUET_ERRORS as e:
                        results[engine] = ("error", type(e).__name__)
                    except FilterError as e:
                        results[engine] = ("filter_error", str(e))
            except PARQUET_ERRORS as e:
                results[engine] = ("open_error", type(e).__name__)
            finally:
                os.environ.pop("PQT_VEC_FILTER", None)
        assert results["vec"] == results["scalar"], path


class TestMaskUnits:
    def _chunks(self, path):
        with FileReader(path) as r:
            return r.schema, r._read_row_group(0, None, pack=False)

    def test_mask_to_ranges(self):
        m = np.array([0, 1, 1, 0, 1, 0, 0, 1, 1, 1], dtype=bool)
        assert mask_to_ranges(m) == [(1, 3), (4, 5), (7, 10)]
        assert mask_to_ranges(np.zeros(4, dtype=bool)) == []
        assert mask_to_ranges(np.ones(3, dtype=bool)) == [(0, 3)]

    def test_unorderable_domain_declines(self, zoo):
        schema, chunks = self._chunks(zoo)
        dnf = normalize_dnf(schema, [("bigdec", ">", decimal.Decimal("1"))])
        with pytest.raises(VecFilterError):
            dnf_mask(chunks, dnf, group_row_count(chunks))

    def test_missing_column_declines(self, zoo):
        schema, chunks = self._chunks(zoo)
        dnf = normalize_dnf(schema, [("i32", ">", 1)])
        chunks = {p: c for p, c in chunks.items() if p != ("i32",)}
        with pytest.raises(VecFilterError):
            dnf_mask(chunks, dnf, 256)

    def test_vacuous_conjunction_admits_all(self, zoo):
        schema, chunks = self._chunks(zoo)
        n = group_row_count(chunks)
        assert dnf_mask(chunks, [[]], n).all()

    def test_masked_flat_columns_declines_lists(self, zoo):
        _schema, chunks = self._chunks(zoo)
        mask = np.ones(group_row_count(chunks), dtype=bool)
        assert masked_flat_columns(chunks, False, mask) is None  # has lists
        flat = {p: c for p, c in chunks.items() if p in (("i32",), ("s",))}
        names, cols, k = masked_flat_columns(flat, False, mask)
        assert set(names) == {"i32", "s"} and k == len(mask)


class TestDeviceTwins:
    def test_predicate_mask_device_matches_host(self):
        jax = pytest.importorskip("jax")
        jnp = jax.numpy
        from parquet_tpu.kernels.device_ops import predicate_mask_device

        vals = np.array([3, 9, 4, 4, 12, -1], dtype=np.int64)
        dv = jnp.asarray(vals)
        for op in ("==", "!=", "<", "<=", ">", ">="):
            # exact bracket (value 4) and inexact bracket (value 4.5)
            host_exact = {
                "==": vals == 4, "!=": vals != 4, "<": vals < 4,
                "<=": vals <= 4, ">": vals > 4, ">=": vals >= 4,
            }[op]
            got = np.asarray(predicate_mask_device(dv, op, 4, 4, True))
            assert np.array_equal(got, host_exact), op
            host_inexact = {
                "==": np.zeros(6, bool), "!=": np.ones(6, bool),
                "<": vals <= 4, "<=": vals <= 4,
                ">": vals >= 5, ">=": vals >= 5,
            }[op]
            got = np.asarray(predicate_mask_device(dv, op, 4, 5, False))
            assert np.array_equal(got, host_inexact), op

    def test_list_contains_device_matches_host(self, zoo):
        jax = pytest.importorskip("jax")
        jnp = jax.numpy
        from parquet_tpu.kernels.device_ops import list_contains_mask_device

        with FileReader(zoo) as r:
            schema = r.schema
            chunks = r._read_row_group(0, ["nums"], pack=False)
            dnf = normalize_dnf(schema, [("nums", "contains", 2)])
            n = group_row_count(chunks)
            host = dnf_mask(chunks, dnf, n)
        cd = chunks[("nums", "list", "element")]
        leaf = schema.column(("nums", "list", "element"))
        rl = np.asarray(cd.rep_levels, dtype=np.int32)
        dl = np.asarray(cd.def_levels, dtype=np.int32)
        dense = np.asarray(cd.values) == 2
        rows, n_rows = list_contains_mask_device(
            jnp.asarray(rl), jnp.asarray(dl), jnp.asarray(dense), leaf.max_def
        )
        assert int(n_rows) == n
        # row k's flag lives at index k; entries past n_rows are padding
        assert np.array_equal(np.asarray(rows)[:n], host)

    def test_mask_take_device(self):
        jax = pytest.importorskip("jax")
        jnp = jax.numpy
        from parquet_tpu.kernels.device_ops import mask_take_device

        vals = np.arange(10, dtype=np.int64) * 3
        mask = np.array([0, 1, 0, 0, 1, 1, 0, 0, 0, 1], dtype=bool)
        taken, count = mask_take_device(
            jnp.asarray(vals), jnp.asarray(mask), 8
        )
        assert int(count) == 4
        assert np.asarray(taken)[:4].tolist() == vals[mask].tolist()

"""parquet_tpu.io tests: sources, the retry ladder, range planning,
coalescing, block/footer caches, and the reader/dataset wiring.

The retry matrix (transient EIO / short reads / latency / permanent
failure) runs a fast subset in tier-1 and the extended seed sweep under
`slow` (`make fuzz` includes it). Acceptance pins from the issue:

  * a projected 2-of-8-column read through the planner fetches < 40% of
    the file's bytes (io_bytes_read_total vs file size);
  * re-opening a file against a warm footer + block cache performs ZERO
    source reads.
"""

import io as _stdio
import os
from pathlib import Path

import numpy as np
import pytest

from parquet_tpu.core.reader import FileReader
from parquet_tpu.io import (
    BlockCache,
    FooterCache,
    LocalFileSource,
    MemorySource,
    Readahead,
    RetryingSource,
    SourceError,
    SourceFile,
    coalesce,
    fetch_ranges,
    open_source,
    plan_ranges,
)
from parquet_tpu.io.source import FileObjectSource
from parquet_tpu.testing.flaky import FlakySource
from parquet_tpu.utils import metrics

NOSLEEP = lambda s: None  # retry ladders sweep in microseconds under test


@pytest.fixture(scope="module")
def eight_col(tmp_path_factory):
    """An 8-column incompressible file: projection leaves real byte gaps."""
    import pyarrow as pa
    import pyarrow.parquet as pq

    path = tmp_path_factory.mktemp("io") / "eight.parquet"
    rng = np.random.default_rng(3)
    t = pa.table(
        {
            f"c{k}": pa.array(rng.integers(0, 1 << 62, 30_000).astype(np.int64))
            for k in range(8)
        }
    )
    pq.write_table(t, path, compression="none", use_dictionary=False,
                   row_group_size=15_000)
    return str(path)


@pytest.fixture
def blob(tmp_path):
    data = np.random.default_rng(7).integers(0, 256, 1 << 16).astype(np.uint8)
    p = tmp_path / "blob.bin"
    p.write_bytes(data.tobytes())
    return p, data.tobytes()


class TestSources:
    def test_local_file_source(self, blob):
        p, data = blob
        with LocalFileSource(p) as src:
            assert src.size() == len(data)
            assert src.read_at(0, 16) == data[:16]
            assert src.read_at(100, 1000) == data[100:1100]
            assert src.read_at(5, 0) == b""
            got = src.read_ranges([(0, 4), (64, 8), (len(data) - 4, 4)])
            assert [bytes(b) for b in got] == [data[:4], data[64:72], data[-4:]]
            with pytest.raises(SourceError):
                src.read_at(len(data) - 2, 4)  # past EOF
            with pytest.raises(ValueError):
                src.read_at(-1, 4)
        with pytest.raises(SourceError):
            src.read_at(0, 4)  # closed
        src.close()  # idempotent

    def test_local_source_id_pins_generation(self, tmp_path):
        p = tmp_path / "g.bin"
        p.write_bytes(b"generation-one")
        id1 = LocalFileSource(p).source_id
        p.write_bytes(b"generation-two!!")  # different size
        id2 = LocalFileSource(p).source_id
        assert id1 != id2

    def test_memory_source(self):
        src = MemorySource(b"hello world")
        assert src.size() == 11
        assert src.read_at(6, 5) == b"world"
        with pytest.raises(SourceError):
            src.read_at(8, 10)

    def test_file_object_source_without_fileno(self, blob):
        _p, data = blob

        class NoFd:  # a seekable file-like with no real fd
            def __init__(self, b):
                self._b = _stdio.BytesIO(b)

            def read(self, n=-1):
                return self._b.read(n)

            def seek(self, *a):
                return self._b.seek(*a)

            def tell(self):
                return self._b.tell()

        src = FileObjectSource(NoFd(data))
        assert src.size() == len(data)
        assert src.read_at(10, 20) == data[10:30]

    def test_source_file_adapter(self, blob):
        p, data = blob
        f = SourceFile(LocalFileSource(p))
        assert f.read(4) == data[:4]
        assert f.tell() == 4
        assert f.seek(0, 2) == len(data)
        assert f.read(10) == b""  # EOF clamps, file semantics
        f.seek(-4, 2)
        assert f.read() == data[-4:]
        f.seek(2, 0)
        f.seek(2, 1)
        assert f.tell() == 4

    def test_open_source_shapes(self, blob):
        p, data = blob
        src, owns = open_source(str(p))
        assert isinstance(src, LocalFileSource) and owns
        src.close()
        src, owns = open_source(Path(p))
        assert isinstance(src, LocalFileSource) and owns
        src.close()
        src, owns = open_source(data)
        assert isinstance(src, MemorySource) and owns
        src, owns = open_source(_stdio.BytesIO(data))
        assert isinstance(src, MemorySource) and owns
        ms = MemorySource(data)
        src, owns = open_source(ms)
        assert src is ms and not owns
        with open(p, "rb") as fobj:
            src, owns = open_source(fobj)
            assert isinstance(src, FileObjectSource) and not owns
        with pytest.raises(TypeError):
            open_source(42)


class TestRetryLadder:
    def test_transient_eio_recovers_byte_identical(self, blob):
        p, data = blob
        inner = FlakySource(LocalFileSource(p), seed=2, error_rate=0.5)
        src = RetryingSource(inner, attempts=16, sleep=NOSLEEP, seed=1)
        s0 = metrics.snapshot()
        got = b"".join(
            bytes(b) for b in src.read_ranges([(0, 1 << 12), (1 << 12, 1 << 12)])
        )
        assert got == data[: 1 << 13]
        d = metrics.delta(s0)
        assert d.get('io_retries_total{reason="EIO"}', 0) >= 1
        assert inner.faults_injected >= 1

    def test_short_read_recovers_byte_identical(self, blob):
        p, data = blob
        src = RetryingSource(
            FlakySource(LocalFileSource(p), seed=3, short_rate=0.6),
            attempts=32, sleep=NOSLEEP, seed=2,
        )
        s0 = metrics.snapshot()
        assert src.read_at(128, 4096) == data[128 : 128 + 4096]
        d = metrics.delta(s0)
        assert d.get('io_retries_total{reason="short_read"}', 0) >= 1

    def test_permanent_failure_raises_typed_after_budget(self, blob):
        p, _data = blob
        inner = FlakySource(LocalFileSource(p), seed=0, permanent=True)
        src = RetryingSource(inner, attempts=5, sleep=NOSLEEP, seed=3)
        s0 = metrics.snapshot()
        with pytest.raises(SourceError) as exc:
            src.read_at(0, 64)
        assert "5 attempt" in str(exc.value)
        assert inner.reads == 5
        d = metrics.delta(s0)
        assert d.get('io_retries_total{reason="EIO"}', 0) == 5

    def test_deadline_cuts_the_ladder_short(self, blob):
        p, _data = blob
        inner = FlakySource(LocalFileSource(p), seed=0, permanent=True)
        src = RetryingSource(
            inner, attempts=100, deadline_s=0.05, base_delay_s=0.1,
            jitter=0.0, sleep=NOSLEEP,
        )
        with pytest.raises(SourceError):
            src.read_at(0, 64)
        assert inner.reads == 1  # first backoff would already blow the deadline

    def test_terminal_source_error_not_retried(self, blob):
        """A SourceError from the inner source (past-EOF, closed, an inner
        ladder's exhausted budget) is deterministic — backing off cannot
        help, so it propagates on the FIRST attempt."""
        p, _data = blob
        inner = FlakySource(LocalFileSource(p))  # counts reads, no faults
        src = RetryingSource(inner, attempts=8, sleep=NOSLEEP)
        with pytest.raises(SourceError):
            src.read_at(1 << 20, 64)  # far past EOF
        assert inner.reads == 1

    def test_latency_injection_still_correct(self, blob):
        p, data = blob
        waited = []
        src = FlakySource(
            LocalFileSource(p), seed=4, latency_s=0.001,
            latency_jitter_s=0.001, sleep=waited.append,
        )
        assert src.read_at(0, 32) == data[:32]
        assert len(waited) == 1 and 0.001 <= waited[0] <= 0.002

    def test_reader_end_to_end_over_flaky_source(self, eight_col):
        with FileReader(eight_col) as r:
            want = [r.read_row_group(i) for i in range(r.num_row_groups)]
        src = RetryingSource(
            FlakySource(LocalFileSource(eight_col), seed=6, error_rate=0.25,
                        short_rate=0.1),
            attempts=32, sleep=NOSLEEP, seed=4,
        )
        with FileReader(src) as r:
            got = [r.read_row_group(i) for i in range(r.num_row_groups)]
        src.close()
        assert len(got) == len(want)
        for g, w in zip(got, want):
            assert g.keys() == w.keys()
            for path in w:
                assert np.array_equal(
                    np.asarray(g[path].values), np.asarray(w[path].values)
                ), path

    @pytest.mark.slow
    @pytest.mark.parametrize("seed", range(8))
    @pytest.mark.parametrize(
        "faults",
        [
            dict(error_rate=0.4),
            dict(short_rate=0.4),
            dict(error_rate=0.25, short_rate=0.25),
            dict(error_rate=0.15, latency_s=0.0002),
        ],
    )
    def test_retry_matrix_typed_or_identical(self, eight_col, seed, faults):
        """Every (seed, fault mix): the read either raises the typed
        SourceError (budget exhausted) or returns byte-identical data —
        never a raw errno leak, never silently wrong bytes."""
        with FileReader(eight_col, columns=["c0"]) as r:
            want = np.asarray(r.read_row_group(0)[("c0",)].values)
        src = RetryingSource(
            FlakySource(LocalFileSource(eight_col), seed=seed, **faults),
            attempts=6, sleep=NOSLEEP, seed=seed,
        )
        try:
            with FileReader(src, columns=["c0"]) as r:
                got = np.asarray(r.read_row_group(0)[("c0",)].values)
        except SourceError:
            return  # typed exhaustion is a legal outcome
        finally:
            src.close()
        assert np.array_equal(got, want)


class TestCoalesce:
    def test_adjacent_and_gap_merge(self):
        runs = coalesce([(0, 10), (10, 10), (30, 10)], gap=10)
        assert [(o, n) for o, n, _ in runs] == [(0, 40)]
        runs = coalesce([(0, 10), (30, 10)], gap=9)
        assert [(o, n) for o, n, _ in runs] == [(0, 10), (30, 10)]

    def test_gap_boundary_inclusive(self):
        # gap exactly equal to the threshold merges; one byte more splits
        runs = coalesce([(0, 10), (74, 10)], gap=64)
        assert len(runs) == 1
        runs = coalesce([(0, 10), (75, 10)], gap=64)
        assert len(runs) == 2

    def test_max_run_caps_merging(self):
        runs = coalesce([(0, 60), (60, 60)], gap=1024, max_run=100)
        assert len(runs) == 2

    def test_overlap_and_duplicates_always_merge(self):
        runs = coalesce([(0, 100), (50, 100), (0, 100)], gap=0, max_run=10)
        assert [(o, n) for o, n, _ in runs] == [(0, 150)]

    def test_members_preserved(self):
        runs = coalesce([(100, 5), (0, 10), (12, 4)], gap=4)
        assert runs[0][2] == [(0, 10), (12, 4)]
        assert runs[1][2] == [(100, 5)]

    def test_empty(self):
        assert coalesce([]) == []
        assert coalesce([(0, 0)]) == []

    def test_fetch_ranges_slices_and_caches(self, blob):
        p, data = blob
        cache = BlockCache(1 << 20)
        with LocalFileSource(p) as src:
            s0 = metrics.snapshot()
            out = fetch_ranges(src, [(0, 8), (16, 8)], cache=cache, gap=64)
            assert bytes(out[(0, 8)]) == data[:8]
            assert bytes(out[(16, 8)]) == data[16:24]
            # coalesced: ONE source read covered both members
            assert metrics.delta(s0).get("io_read_calls_total") == 1
            s1 = metrics.snapshot()
            out = fetch_ranges(src, [(0, 8), (16, 8)], cache=cache, gap=64)
            assert bytes(out[(0, 8)]) == data[:8]
            assert "io_read_calls_total" not in metrics.delta(s1)  # all cached


class TestPlanRanges:
    def test_full_vs_projected(self, eight_col):
        meta = FileReader.open_metadata(eight_col)
        full = plan_ranges(meta)
        assert len(full) == 16  # 8 columns x 2 row groups
        proj = plan_ranges(meta, columns={("c0",), ("c1",)})
        assert len(proj) == 4
        assert set(proj) <= set(full)
        assert sum(n for _o, n in proj) < 0.3 * sum(n for _o, n in full)

    def test_row_group_subset(self, eight_col):
        meta = FileReader.open_metadata(eight_col)
        g0 = plan_ranges(meta, row_groups=[0])
        assert len(g0) == 8
        assert set(g0) <= set(plan_ranges(meta))

    def test_page_index_ranges(self, tmp_path):
        import pyarrow as pa
        import pyarrow.parquet as pq

        p = tmp_path / "idx.parquet"
        pq.write_table(
            pa.table({"v": np.arange(1000, dtype=np.int64)}), p,
            write_page_index=True,
        )
        meta = FileReader.open_metadata(str(p))
        base = plan_ranges(meta)
        with_idx = plan_ranges(meta, page_index=True)
        assert len(with_idx) > len(base)


class TestBlockCache:
    def test_hit_miss_and_gauge(self):
        c = BlockCache(1 << 10)
        s0 = metrics.snapshot()
        assert c.get("s", 0, 4) is None
        c.put("s", 0, 4, b"abcd")
        assert c.get("s", 0, 4) == b"abcd"
        d = metrics.delta(s0)
        assert d.get("io_cache_hits_total") == 1
        assert d.get("io_cache_misses_total") == 1
        assert metrics.get("io_cache_bytes") >= 4

    def test_lru_eviction_under_budget(self):
        c = BlockCache(100)
        for k in range(10):
            c.put("s", k * 40, 40, bytes(40))
        st = c.stats()
        assert st["bytes"] <= 100
        assert c.get("s", 0, 40) is None  # oldest evicted
        assert c.get("s", 9 * 40, 40) is not None

    def test_oversize_block_skipped(self):
        c = BlockCache(10)
        c.put("s", 0, 100, bytes(100))
        assert c.stats()["blocks"] == 0

    def test_invalidate_one_source(self):
        c = BlockCache(1 << 10)
        c.put("a", 0, 4, b"aaaa")
        c.put("b", 0, 4, b"bbbb")
        c.invalidate("a")
        assert c.get("a", 0, 4) is None
        assert c.get("b", 0, 4) == b"bbbb"


class TestFooterCache:
    def test_warm_hit_performs_zero_source_reads(self, eight_col):
        fc = FooterCache()
        m1 = FileReader.open_metadata(eight_col, footer_cache=fc)
        s0 = metrics.snapshot()
        m2 = FileReader.open_metadata(eight_col, footer_cache=fc)
        d = metrics.delta(s0)
        assert m2 is m1
        assert "io_bytes_read_total" not in d
        assert "io_read_calls_total" not in d
        assert d.get("io_footer_cache_hits_total") == 1

    def test_rewrite_invalidates(self, tmp_path):
        import pyarrow as pa
        import pyarrow.parquet as pq

        p = tmp_path / "r.parquet"
        pq.write_table(pa.table({"v": np.arange(10, dtype=np.int64)}), p)
        fc = FooterCache()
        FileReader.open_metadata(str(p), footer_cache=fc)
        pq.write_table(pa.table({"v": np.arange(999, dtype=np.int64)}), p)
        os.utime(p)  # force a fresh mtime even on coarse filesystems
        m = FileReader.open_metadata(str(p), footer_cache=fc)
        assert m.num_rows == 999

    def test_max_entries_lru(self, tmp_path):
        import pyarrow as pa
        import pyarrow.parquet as pq

        fc = FooterCache(max_entries=2)
        for i in range(3):
            p = tmp_path / f"f{i}.parquet"
            pq.write_table(pa.table({"v": np.arange(4, dtype=np.int64)}), p)
            FileReader.open_metadata(str(p), footer_cache=fc)
        assert len(fc) == 2


class TestReaderWiring:
    def test_projected_read_fetches_under_40pct(self, eight_col):
        """Acceptance: a 2-of-8-column read through the planner fetches
        < 40% of the file's bytes (io_bytes_read_total vs file size)."""
        fsize = os.path.getsize(eight_col)
        s0 = metrics.snapshot()
        with FileReader(eight_col, columns=["c0", "c1"]) as r:
            rows = sum(
                next(iter(r.read_row_group(i).values())).num_values
                for i in range(r.num_row_groups)
            )
        assert rows == 30_000
        read = metrics.delta(s0).get("io_bytes_read_total", 0)
        assert 0 < read < 0.4 * fsize, (read, fsize)

    def test_warm_caches_reopen_zero_source_reads(self, eight_col):
        """Acceptance: re-opening against a warm footer + block cache
        performs zero source reads (footer included)."""
        cache = BlockCache(64 << 20)
        fc = FooterCache()
        with FileReader(eight_col, block_cache=cache, footer_cache=fc) as r:
            want = [r.read_row_group(i) for i in range(r.num_row_groups)]
        s0 = metrics.snapshot()
        with FileReader(eight_col, block_cache=cache, footer_cache=fc) as r:
            got = [r.read_row_group(i) for i in range(r.num_row_groups)]
        d = metrics.delta(s0)
        assert "io_bytes_read_total" not in d, d
        assert "io_read_calls_total" not in d
        for g, w in zip(got, want):
            for path in w:
                assert np.array_equal(
                    np.asarray(g[path].values), np.asarray(w[path].values)
                )

    def test_reader_accepts_bytes_and_sources(self, eight_col):
        data = Path(eight_col).read_bytes()
        with FileReader(eight_col) as r:
            want = r.read_row_group(0)
        for source in (data, MemorySource(data), _stdio.BytesIO(data)):
            with FileReader(source) as r:
                got = r.read_row_group(0)
            for path in want:
                assert np.array_equal(
                    np.asarray(got[path].values), np.asarray(want[path].values)
                )

    def test_memory_ceiling_still_enforced(self, eight_col):
        from parquet_tpu.core.alloc import AllocError

        with FileReader(eight_col, max_memory=1024) as r:
            with pytest.raises(AllocError):
                r.read_row_group(0)

    def test_truncated_file_stays_typed(self, eight_col):
        """The planner path must not leak SourceError for a truncated file:
        corruption keeps the decode ladder's typed error family."""
        from parquet_tpu.core.reader import PARQUET_ERRORS

        data = Path(eight_col).read_bytes()
        with pytest.raises(PARQUET_ERRORS):
            with FileReader(data[: len(data) // 2]) as r:
                r.read_row_group(0)

    def test_zero_length_chunk_stays_typed_and_quarantines(self, eight_col):
        """A lying footer claiming total_compressed_size == 0 must surface
        as the typed decode error (and quarantine under on_error='skip'),
        not a raw KeyError out of the batched-fetch path."""
        from parquet_tpu.core.reader import PARQUET_ERRORS

        with FileReader(eight_col) as r:
            r.metadata.row_groups[0].columns[0].meta_data.total_compressed_size = 0
            with pytest.raises(PARQUET_ERRORS):
                r.read_row_group(0)
        with FileReader(eight_col, on_error="skip") as r:
            r.metadata.row_groups[0].columns[0].meta_data.total_compressed_size = 0
            assert r.read_row_group(0) == {}  # group quarantined, typed path

    def test_io_spans_land_in_trace(self, eight_col):
        from parquet_tpu.utils.trace import decode_trace

        with decode_trace() as t:
            with FileReader(eight_col, columns=["c0"]) as r:
                r.read_row_group(0)
        assert "io.read" in t.stages
        assert "io.coalesce" in t.stages
        names = {e[0] for e in t._events}
        assert "io.read" in names


class TestReadahead:
    def test_fetches_into_cache(self, eight_col):
        meta = FileReader.open_metadata(eight_col)
        ranges = plan_ranges(meta, row_groups=[0])
        cache = BlockCache(64 << 20)
        ra = Readahead(cache)
        assert ra.schedule(eight_col, ranges)
        ra.drain()
        assert cache.stats()["blocks"] >= 1
        # a reader over the same file now decodes group 0 with zero source
        # reads past the footer
        with FileReader(eight_col, block_cache=cache) as r:
            s0 = metrics.snapshot()
            r.read_row_group(0)
            assert "io_bytes_read_total" not in metrics.delta(s0)

    def test_budget_overflow_drops(self, eight_col):
        cache = BlockCache(64 << 20)
        ra = Readahead(cache, budget_bytes=16)
        s0 = metrics.snapshot()
        assert not ra.schedule(eight_col, [(0, 1 << 20)])
        assert metrics.delta(s0).get("io_readahead_dropped_total") == 1

    def test_errors_swallowed_and_counted(self, tmp_path):
        cache = BlockCache(1 << 20)
        ra = Readahead(cache)
        s0 = metrics.snapshot()
        assert ra.schedule(str(tmp_path / "missing.parquet"), [(0, 128)])
        ra.drain()
        assert metrics.delta(s0).get("io_readahead_errors_total") == 1


class TestDatasetIO:
    @pytest.fixture
    def shards(self, tmp_path):
        import pyarrow as pa
        import pyarrow.parquet as pq

        rng = np.random.default_rng(0)
        for i in range(4):
            t = pa.table(
                {
                    "a": pa.array(rng.integers(0, 100, 600).astype(np.int64)),
                    "b": pa.array(rng.standard_normal(600).astype(np.float32)),
                }
            )
            pq.write_table(t, tmp_path / f"s-{i}.parquet", row_group_size=200)
        return str(tmp_path / "s-*.parquet")

    def test_second_epoch_hits_block_cache(self, shards):
        from parquet_tpu.data import ParquetDataset

        ds = ParquetDataset(
            shards, batch_size=300, num_epochs=2, prefetch=2,
            cache_bytes=32 << 20,
        )
        s0 = metrics.snapshot()
        with ds:
            rows = sum(
                next(iter(b.values())).shape[0] for b in ds
            )
        assert rows == 2 * 4 * 600
        d = metrics.delta(s0)
        assert d.get("io_cache_hits_total", 0) > 0

    def test_readahead_scheduled_for_upcoming_units(self, shards):
        from parquet_tpu.data import ParquetDataset

        ds = ParquetDataset(
            shards, batch_size=300, num_epochs=1, prefetch=2,
            cache_bytes=32 << 20,
        )
        s0 = metrics.snapshot()
        with ds:
            for _ in ds:
                pass
            ds._readahead.drain()
        d = metrics.delta(s0)
        assert (
            d.get("io_readahead_fetched_total", 0)
            + d.get("io_readahead_dropped_total", 0)
        ) >= 1

    def test_stream_identical_with_and_without_cache(self, shards):
        from parquet_tpu.data import ParquetDataset

        def drain(**kw):
            ds = ParquetDataset(
                shards, batch_size=250, num_epochs=1, shuffle=True, seed=3,
                prefetch=2, **kw,
            )
            with ds:
                return [
                    {p: a.copy() for p, a in b.items()} for b in ds
                ]

        plain = drain()
        cached = drain(cache_bytes=32 << 20)
        assert len(plain) == len(cached)
        for b0, b1 in zip(plain, cached):
            assert b0.keys() == b1.keys()
            for p in b0:
                assert np.array_equal(b0[p], b1[p])

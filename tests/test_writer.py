"""FileWriter tests: self round-trip + cross-implementation conformance.

The write-side oracle is pyarrow re-reading our files (the analogue of the
reference's Docker harness that re-reads parquet-go output with Java parquet-mr,
reference: compatibility/run_tests.bash, SURVEY §4.6), parameterized over
codec x page version like readwrite_test.go.
"""

import math

import numpy as np
import pyarrow.parquet as pq
import pytest

from parquet_tpu.core.reader import FileReader
from parquet_tpu.core.writer import FileWriter, WriterError
from parquet_tpu.meta.parquet_types import Type
from parquet_tpu.schema.builder import (
    int_type,
    list_of,
    map_of,
    message,
    optional,
    repeated,
    required,
    group,
    string,
    timestamp,
)


def eq(a, b):
    if isinstance(a, float) and isinstance(b, float):
        return (math.isnan(a) and math.isnan(b)) or a == b
    if isinstance(a, dict) and isinstance(b, dict):
        return a.keys() == b.keys() and all(eq(a[k], b[k]) for k in a)
    if isinstance(a, (list, tuple)) and isinstance(b, (list, tuple)):
        return len(a) == len(b) and all(eq(x, y) for x, y in zip(a, b))
    return a == b


def roundtrip(tmp_path, schema, rows, **writer_kw):
    """Write rows, then (a) re-read with pyarrow, (b) re-read with ourselves."""
    path = str(tmp_path / "out.parquet")
    with FileWriter(path, schema, **writer_kw) as w:
        w.write_rows(rows)
    theirs = pq.read_table(path).to_pylist()
    with FileReader(path) as r:
        ours = list(r.iter_rows())
    return ours, theirs


SCHEMA = message(
    required("id", Type.INT64),
    optional("name", string()),
    optional("score", Type.DOUBLE),
    optional("flag", Type.BOOLEAN),
    optional("small", Type.INT32),
)

ROWS = [
    {"id": 1, "name": "alice", "score": 9.5, "flag": True, "small": 7},
    {"id": 2, "name": None, "score": None, "flag": None, "small": None},
    {"id": 3, "name": "carol", "score": float("nan"), "flag": False, "small": -1},
    {"id": 4, "name": "", "score": -0.0, "flag": True, "small": 2**31 - 1},
]


class TestFlatRoundtrip:
    @pytest.mark.parametrize("codec", ["uncompressed", "snappy", "gzip", "zstd"])
    @pytest.mark.parametrize("dpv", [1, 2])
    def test_codec_page_matrix(self, codec, dpv, tmp_path):
        ours, theirs = roundtrip(
            tmp_path, SCHEMA, ROWS, codec=codec, data_page_version=dpv
        )
        for o, t, r in zip(ours, theirs, ROWS):
            assert eq(o, t), f"ours {o} != pyarrow {t}"
            assert eq(o, r), f"ours {o} != input {r}"

    @pytest.mark.parametrize("with_crc", [False, True])
    def test_crc(self, with_crc, tmp_path):
        path = str(tmp_path / "crc.parquet")
        with FileWriter(path, SCHEMA, with_crc=with_crc) as w:
            w.write_rows(ROWS)
        with FileReader(path, validate_crc=True) as r:
            assert len(list(r.iter_rows())) == len(ROWS)
        assert pq.read_table(path).num_rows == len(ROWS)

    def test_dictionary_engages_for_low_cardinality(self, tmp_path):
        schema = message(required("cat", string()))
        rows = [{"cat": f"c{i % 5}"} for i in range(2000)]
        path = str(tmp_path / "dict.parquet")
        with FileWriter(path, schema) as w:
            w.write_rows(rows)
        meta = pq.read_metadata(path)
        col = meta.row_group(0).column(0)
        assert col.has_dictionary_page
        assert [r["cat"] for r in FileReader(path).iter_rows()] == [
            r["cat"] for r in rows
        ]

    def test_dictionary_skipped_for_high_cardinality_when_bigger(self, tmp_path):
        schema = message(required("x", Type.INT64))
        rows = [{"x": i} for i in range(40000)]  # > 32767 uniques
        path = str(tmp_path / "nodict.parquet")
        with FileWriter(path, schema) as w:
            w.write_rows(rows)
        meta = pq.read_metadata(path)
        assert not meta.row_group(0).column(0).has_dictionary_page

    def test_multiple_row_groups(self, tmp_path):
        path = str(tmp_path / "rg.parquet")
        with FileWriter(path, message(required("x", Type.INT64))) as w:
            for start in range(0, 1000, 100):
                for i in range(start, start + 100):
                    w.write_row({"x": i})
                w.flush_row_group()
        with FileReader(path) as r:
            assert r.num_row_groups == 10
            assert [row["x"] for row in r.iter_rows()] == list(range(1000))
        assert pq.read_table(path).column("x").to_pylist() == list(range(1000))

    def test_multi_page_chunks(self, tmp_path):
        path = str(tmp_path / "pages.parquet")
        schema = message(required("x", Type.INT64))
        with FileWriter(path, schema, max_page_size=512, enable_dictionary=False) as w:
            w.write_rows({"x": i} for i in range(5000))
        assert pq.read_table(path).column("x").to_pylist() == list(range(5000))
        with FileReader(path) as r:
            assert [row["x"] for row in r.iter_rows()] == list(range(5000))

    def test_int96_and_fixed(self, tmp_path):
        from parquet_tpu.schema.builder import _TypeSpec

        schema = message(
            required("f", _TypeSpec(Type.FIXED_LEN_BYTE_ARRAY, type_length=4)),
        )
        rows = [{"f": b"abcd"}, {"f": b"wxyz"}]
        ours, theirs = roundtrip(tmp_path, schema, rows)
        assert [o["f"] for o in ours] == [b"abcd", b"wxyz"]
        assert [t["f"] for t in theirs] == [b"abcd", b"wxyz"]

    def test_empty_file(self, tmp_path):
        path = str(tmp_path / "empty.parquet")
        with FileWriter(path, SCHEMA) as w:
            pass
        with FileReader(path) as r:
            assert r.num_rows == 0
        assert pq.read_table(path).num_rows == 0

    def test_required_null_rejected(self, tmp_path):
        path = str(tmp_path / "req.parquet")
        w = FileWriter(path, SCHEMA)
        with pytest.raises(ValueError):
            w.write_row({"id": None})


class TestNestedRoundtrip:
    def test_lists(self, tmp_path):
        schema = message(list_of("tags", optional("element", string())))
        rows = [
            {"tags": ["a", "b"]},
            {"tags": []},
            {"tags": None},
            {"tags": ["x", None, "z"]},
        ]
        ours, theirs = roundtrip(tmp_path, schema, rows, codec="snappy")
        for o, t, r in zip(ours, theirs, rows):
            assert eq(o, t) and eq(o, r)

    def test_maps(self, tmp_path):
        schema = message(
            map_of("attrs", required("key", string()), optional("value", Type.INT32))
        )
        rows = [{"attrs": {"a": 1, "b": None}}, {"attrs": {}}, {"attrs": None}]
        path = str(tmp_path / "m.parquet")
        with FileWriter(path, schema) as w:
            w.write_rows(rows)
        theirs = pq.read_table(path).to_pylist()
        for t, r in zip(theirs, rows):
            got = dict(t["attrs"]) if t["attrs"] is not None else None
            assert eq(got, r["attrs"])
        ours = list(FileReader(path).iter_rows())
        for o, r in zip(ours, rows):
            assert eq(o["attrs"], r["attrs"])

    def test_struct(self, tmp_path):
        schema = message(
            group(
                "person",
                required("name", string()),
                optional("age", Type.INT32),
            )
        )
        rows = [
            {"person": {"name": "ann", "age": 30}},
            {"person": {"name": "bob", "age": None}},
            {"person": None},
        ]
        ours, theirs = roundtrip(tmp_path, schema, rows)
        for o, t, r in zip(ours, theirs, rows):
            assert eq(o, t) and eq(o, r)

    def test_list_of_structs(self, tmp_path):
        schema = message(
            list_of(
                "events",
                group(
                    "element",
                    required("ts", Type.INT64),
                    optional("kind", string()),
                ),
            )
        )
        rows = [
            {"events": [{"ts": 1, "kind": "a"}, {"ts": 2, "kind": None}]},
            {"events": []},
            {"events": None},
        ]
        ours, theirs = roundtrip(tmp_path, schema, rows, codec="zstd")
        for o, t, r in zip(ours, theirs, rows):
            assert eq(o, t) and eq(o, r)

    def test_nested_multi_page(self, tmp_path):
        schema = message(list_of("l", required("element", Type.INT32)))
        rows = [{"l": list(range(i % 7))} for i in range(3000)]
        path = str(tmp_path / "np.parquet")
        with FileWriter(path, schema, max_page_size=256) as w:
            w.write_rows(rows)
        theirs = pq.read_table(path).to_pylist()
        ours = list(FileReader(path).iter_rows())
        for o, t, r in zip(ours, theirs, rows):
            assert eq(o, t) and eq(o, r)

    def test_repeated_primitive_legacy(self, tmp_path):
        # bare repeated leaf (2-level list, no LIST annotation)
        schema = message(repeated("vals", Type.INT32))
        rows = [{"vals": [1, 2, 3]}, {"vals": []}, {"vals": [9]}]
        path = str(tmp_path / "rep.parquet")
        with FileWriter(path, schema) as w:
            w.write_rows(rows)
        ours = list(FileReader(path).iter_rows())
        assert [o["vals"] for o in ours] == [[1, 2, 3], [], [9]]
        assert pq.read_table(path).column("vals").to_pylist() == [[1, 2, 3], [], [9]]


class TestColumnarPath:
    def test_flat_columnar_write(self, tmp_path):
        schema = message(
            required("a", Type.INT64),
            required("b", Type.DOUBLE),
        )
        path = str(tmp_path / "col.parquet")
        a = np.arange(10_000, dtype=np.int64)
        b = np.linspace(0, 1, 10_000)
        with FileWriter(path, schema, codec="snappy") as w:
            w.write_column("a", a)
            w.write_column("b", b)
            w.flush_row_group()
        t = pq.read_table(path)
        np.testing.assert_array_equal(np.asarray(t.column("a")), a)
        np.testing.assert_array_equal(np.asarray(t.column("b")), b)
        with FileReader(path) as r:
            cd = r.read_row_group(0)
            np.testing.assert_array_equal(cd[("a",)].values, a)

    def test_columnar_optional_with_levels(self, tmp_path):
        schema = message(optional("x", Type.INT64))
        path = str(tmp_path / "colo.parquet")
        values = np.array([10, 30], dtype=np.int64)  # non-null cells only
        def_levels = np.array([1, 0, 1, 0], dtype=np.uint16)
        with FileWriter(path, schema) as w:
            w.write_column("x", values, def_levels=def_levels)
            w.flush_row_group()
        assert pq.read_table(path).column("x").to_pylist() == [10, None, 30, None]

    def test_row_count_mismatch_rejected(self, tmp_path):
        schema = message(required("a", Type.INT64), required("b", Type.INT64))
        w = FileWriter(str(tmp_path / "mm.parquet"), schema)
        w.write_column("a", np.arange(5))
        with pytest.raises(WriterError):
            w.write_column("b", np.arange(6))

    def test_mixing_modes_rejected(self, tmp_path):
        schema = message(required("a", Type.INT64))
        w = FileWriter(str(tmp_path / "mix.parquet"), schema)
        w.write_row({"a": 1})
        with pytest.raises(WriterError):
            w.write_column("a", np.arange(5))


class TestMetadataOut:
    def test_stats_written(self, tmp_path):
        path = str(tmp_path / "st.parquet")
        with FileWriter(path, SCHEMA) as w:
            w.write_rows(ROWS)
        meta = pq.read_metadata(path)
        col = meta.row_group(0).column(0)  # id
        assert col.statistics.min == 1
        assert col.statistics.max == 4
        name_col = meta.row_group(0).column(1)
        assert name_col.statistics.null_count == 1

    def test_kv_metadata(self, tmp_path):
        path = str(tmp_path / "kv.parquet")
        with FileWriter(path, SCHEMA, key_value_metadata={"k": "v"}) as w:
            w.write_rows(ROWS)
        assert pq.read_metadata(path).metadata[b"k"] == b"v"
        with FileReader(path) as r:
            assert r.key_value_metadata["k"] == "v"

    def test_created_by(self, tmp_path):
        path = str(tmp_path / "cb.parquet")
        with FileWriter(path, SCHEMA, created_by="my-writer 1.0") as w:
            w.write_rows(ROWS)
        assert pq.read_metadata(path).created_by == "my-writer 1.0"

    def test_timestamp_logical_type_roundtrip(self, tmp_path):
        schema = message(optional("ts", timestamp("micros")))
        rows = [{"ts": 1_600_000_000_000_000}, {"ts": None}]
        path = str(tmp_path / "ts.parquet")
        with FileWriter(path, schema) as w:
            w.write_rows(rows)
        t = pq.read_table(path)
        assert str(t.schema.field("ts").type) == "timestamp[us, tz=UTC]"

    def test_int_logical_types(self, tmp_path):
        schema = message(
            optional("u8", int_type(8, signed=False)),
            optional("i16", int_type(16)),
        )
        rows = [{"u8": 200, "i16": -30000}]
        path = str(tmp_path / "it.parquet")
        with FileWriter(path, schema) as w:
            w.write_rows(rows)
        assert pq.read_table(path).to_pylist() == rows


class TestColumnEncodings:
    """Per-column encoding selection — the reference's New*Store(enc, useDict)
    choice (data_store.go:364-461) as writer options, validated against the
    encoder matrix (chunk_writer.go:13-128)."""

    def _roundtrip(self, tmp_path, schema, col, values, enc, version=1, codec="uncompressed"):
        path = str(tmp_path / f"{col}_{enc}.parquet")
        with FileWriter(
            path, schema, codec=codec, data_page_version=version,
            use_dictionary=False, column_encodings={col: enc},
        ) as w:
            w.write_column(col, values)
            w.flush_row_group()
        # our reader and pyarrow both decode it
        with FileReader(path) as r:
            got = r.read_row_group(0)[(col,)].values
        pa_vals = pq.read_table(path).column(col).to_pylist()
        return got, pa_vals, path

    @pytest.mark.parametrize("version", [1, 2])
    def test_delta_int64(self, tmp_path, version):
        rng = np.random.default_rng(3)
        v = np.cumsum(rng.integers(-1000, 1000, 20_000)).astype(np.int64)
        schema = message(required("ts", Type.INT64))
        got, pa_vals, path = self._roundtrip(
            tmp_path, schema, "ts", v, "DELTA_BINARY_PACKED", version
        )
        np.testing.assert_array_equal(got, v)
        assert pa_vals == v.tolist()
        # the chunk metadata must advertise the encoding
        with FileReader(path) as r:
            md = r.row_group(0).columns[0].meta_data
            from parquet_tpu.meta.parquet_types import Encoding
            assert int(Encoding.DELTA_BINARY_PACKED) in (md.encodings or [])

    def test_delta_int32_negative(self, tmp_path):
        v = np.array([5, -3, 2**30, -(2**30), 0, 7], dtype=np.int32)
        schema = message(required("x", Type.INT32))
        got, pa_vals, _ = self._roundtrip(tmp_path, schema, "x", v, "DELTA_BINARY_PACKED")
        np.testing.assert_array_equal(got, v)
        assert pa_vals == v.tolist()

    def test_delta_length_byte_array(self, tmp_path):
        vals = [f"s{'x' * (i % 9)}{i}".encode() for i in range(5000)]
        schema = message(required("s", Type.BYTE_ARRAY))
        from parquet_tpu.core.arrays import ByteArrayData
        got, pa_vals, _ = self._roundtrip(
            tmp_path, schema, "s", ByteArrayData.from_list(vals),
            "DELTA_LENGTH_BYTE_ARRAY",
        )
        assert got.to_list() == vals
        assert pa_vals == vals

    def test_delta_byte_array_shared_prefixes(self, tmp_path):
        vals = [f"common/prefix/{i // 10}/{i}".encode() for i in range(5000)]
        schema = message(required("s", Type.BYTE_ARRAY))
        from parquet_tpu.core.arrays import ByteArrayData
        got, pa_vals, _ = self._roundtrip(
            tmp_path, schema, "s", ByteArrayData.from_list(vals), "DELTA_BYTE_ARRAY"
        )
        assert got.to_list() == vals
        assert pa_vals == vals

    def test_boolean_rle(self, tmp_path):
        rng = np.random.default_rng(4)
        v = rng.random(4000) < 0.3
        schema = message(required("b", Type.BOOLEAN))
        got, pa_vals, _ = self._roundtrip(tmp_path, schema, "b", v, "RLE")
        np.testing.assert_array_equal(got, v)
        assert pa_vals == v.tolist()

    def test_use_dictionary_column_list(self, tmp_path):
        schema = message(required("a", Type.INT64), required("b", Type.INT64))
        path = str(tmp_path / "ud.parquet")
        v = np.tile(np.arange(10, dtype=np.int64), 1000)
        with FileWriter(path, schema, use_dictionary=["a"]) as w:
            w.write_column("a", v)
            w.write_column("b", v)
            w.flush_row_group()
        from parquet_tpu.meta.parquet_types import Encoding
        with FileReader(path) as r:
            md = {tuple(c.meta_data.path_in_schema): c.meta_data
                  for c in r.row_group(0).columns}
            assert int(Encoding.RLE_DICTIONARY) in md[("a",)].encodings
            assert int(Encoding.RLE_DICTIONARY) not in md[("b",)].encodings
            cd = r.read_row_group(0)
        np.testing.assert_array_equal(cd[("a",)].values, v)
        np.testing.assert_array_equal(cd[("b",)].values, v)
        assert pq.read_table(path).column("a").to_pylist() == v.tolist()

    def test_dict_overrides_fallback_encoding(self, tmp_path):
        # dictionary still wins when it pays; fallback encoding applies only
        # when the dict is disabled or overflows (reference: chunk_writer.go:174-209)
        schema = message(required("x", Type.INT64))
        path = str(tmp_path / "dw.parquet")
        v = np.tile(np.arange(5, dtype=np.int64), 2000)
        with FileWriter(path, schema, column_encodings={"x": "DELTA_BINARY_PACKED"}) as w:
            w.write_column("x", v)
            w.flush_row_group()
        from parquet_tpu.meta.parquet_types import Encoding
        with FileReader(path) as r:
            md = r.row_group(0).columns[0].meta_data
            assert int(Encoding.RLE_DICTIONARY) in md.encodings
            np.testing.assert_array_equal(r.read_row_group(0)[("x",)].values, v)

    def test_invalid_encoding_rejected(self, tmp_path):
        schema = message(required("f", Type.FLOAT))
        with pytest.raises(WriterError, match="not supported for FLOAT"):
            FileWriter(str(tmp_path / "x.parquet"), schema,
                       column_encodings={"f": "DELTA_BINARY_PACKED"})

    def test_unknown_column_rejected(self, tmp_path):
        schema = message(required("a", Type.INT64))
        with pytest.raises(WriterError, match="not a leaf"):
            FileWriter(str(tmp_path / "x.parquet"), schema,
                       column_encodings={"zz": "PLAIN"})

    def test_tpu_backend_reads_our_delta_files(self, tmp_path):
        # our writer's delta output through the device decode path
        rng = np.random.default_rng(5)
        v = np.cumsum(rng.integers(-50, 50, 30_000)).astype(np.int64)
        schema = message(required("ts", Type.INT64))
        path = str(tmp_path / "towntpu.parquet")
        with FileWriter(path, schema, use_dictionary=False,
                        column_encodings={"ts": "DELTA_BINARY_PACKED"},
                        max_page_size=4096) as w:
            w.write_column("ts", v)
            w.flush_row_group()
        with FileReader(path, backend="tpu_roundtrip") as r:
            np.testing.assert_array_equal(r.read_row_group(0)[("ts",)].values, v)

    def test_use_dictionary_bare_string(self, tmp_path):
        # a bare string names one column, not its characters
        schema = message(required("ab", Type.INT64), required("cd", Type.INT64))
        path = str(tmp_path / "uds.parquet")
        v = np.tile(np.arange(4, dtype=np.int64), 100)
        with FileWriter(path, schema, use_dictionary="ab") as w:
            w.write_column("ab", v)
            w.write_column("cd", v)
            w.flush_row_group()
        from parquet_tpu.meta.parquet_types import Encoding
        with FileReader(path) as r:
            md = {tuple(c.meta_data.path_in_schema): c.meta_data
                  for c in r.row_group(0).columns}
        assert int(Encoding.RLE_DICTIONARY) in md[("ab",)].encodings
        assert int(Encoding.RLE_DICTIONARY) not in md[("cd",)].encodings

    def test_flba_delta_rejected(self, tmp_path):
        # DELTA_BYTE_ARRAY on FIXED_LEN is rejected: the read path doesn't
        # decode that combination, so the writer must not produce it
        from parquet_tpu.schema.builder import _TypeSpec
        schema = message(required("f", _TypeSpec(Type.FIXED_LEN_BYTE_ARRAY, type_length=4)))
        with pytest.raises(WriterError, match="not supported for FIXED_LEN"):
            FileWriter(str(tmp_path / "x.parquet"), schema,
                       column_encodings={"f": "DELTA_BYTE_ARRAY"})


class TestFlushMetadata:
    def test_per_flush_column_kv(self, tmp_path):
        # per-row-group KV metadata on column chunks
        # (reference: file_writer.go:156-226 FlushRowGroupOption)
        schema = message(required("a", Type.INT64), required("b", Type.INT64))
        path = str(tmp_path / "kv.parquet")
        v = np.arange(100, dtype=np.int64)
        with FileWriter(path, schema) as w:
            w.write_column("a", v)
            w.write_column("b", v)
            w.flush_row_group(metadata={"batch": "1"}, column_metadata={"a": {"x": "y"}})
            w.write_column("a", v)
            w.write_column("b", v)
            w.flush_row_group()  # no metadata on the second group
        with FileReader(path) as r:
            rg0 = {tuple(c.meta_data.path_in_schema):
                   {kv.key: kv.value for kv in (c.meta_data.key_value_metadata or [])}
                   for c in r.row_group(0).columns}
            rg1_kv = [c.meta_data.key_value_metadata for c in r.row_group(1).columns]
            rows = list(r.iter_rows())
        assert rg0[("a",)] == {"batch": "1", "x": "y"}
        assert rg0[("b",)] == {"batch": "1"}
        assert rg1_kv == [None, None]
        assert len(rows) == 200
        assert pq.read_table(path).num_rows == 200

    def test_flush_metadata_with_empty_buffer_rejected(self, tmp_path):
        schema = message(required("a", Type.INT64))
        with FileWriter(str(tmp_path / "e.parquet"), schema) as w:
            w.write_column("a", np.arange(5, dtype=np.int64))
            w.flush_row_group()
            with pytest.raises(WriterError, match="nothing buffered"):
                w.flush_row_group(metadata={"k": "v"})
            w.write_column("a", np.arange(5, dtype=np.int64))


class TestSchemaNavigation:
    def test_sub_schema_and_clone(self, tmp_path):
        from parquet_tpu.schema.dsl import parse_schema, schema_to_string

        schema = parse_schema("""
            message doc {
              required int64 id;
              optional group meta {
                required binary name (STRING);
                optional int32 rank;
              }
            }
        """)
        sub = schema.sub_schema("meta")
        assert [l.path_str for l in sub.leaves] == ["name", "rank"]
        clone = schema.clone()
        assert schema_to_string(clone) == schema_to_string(schema)
        # mutating the clone must not touch the original
        clone.column("id").element.name = "renamed"
        assert schema.column("id").name == "id"


class TestSchemaClone:
    def test_clone_deep_copies_logical_type(self):
        from parquet_tpu.schema.builder import message as msg, required as req, string
        from parquet_tpu.core.schema import SchemaError
        s = msg(req("name", string()), req("id", Type.INT64))
        c = s.clone()
        assert c.column("name").element.logicalType is not s.column("name").element.logicalType
        with pytest.raises(SchemaError, match="is a leaf"):
            s.sub_schema("id")


class TestInt96Write:
    def test_datetime_into_int96_column(self, tmp_path):
        """Writing datetime into an INT96 column converts like the
        reference's floor writer (reference: floor/writer.go INT96 path)."""
        import datetime as dt

        from parquet_tpu.schema.dsl import parse_schema

        sch = parse_schema("message m { optional int96 ts; }")
        ts = dt.datetime(1999, 12, 31, 23, 59, 59, 999999, tzinfo=dt.timezone.utc)
        path = str(tmp_path / "i96.parquet")
        with FileWriter(path, sch) as w:
            w.write_row({"ts": ts})
            w.write_row({"ts": None})
        with FileReader(path) as r:
            rows = list(r.iter_rows())
        assert rows[0]["ts"] == ts and rows[1]["ts"] is None
        got = pq.read_table(path).to_pylist()
        assert got[0]["ts"].to_pydatetime().replace(tzinfo=dt.timezone.utc) == ts


class TestWriterInputValidation:
    """Adversarial user values must raise clean StoreError/ShredError —
    never silently truncate (1.5 into an int64 column) or leak TypeError."""

    @pytest.mark.parametrize(
        "bad",
        [{"a": "not-int"}, {"a": 1.5}, {"a": 2**70}, {"a": [1]}, {"a": {"x": 1}},
         {"a": float("nan")}],
        ids=["str", "fractional", "overflow", "list", "dict", "nan"],
    )
    def test_bad_int64_values_rejected(self, tmp_path, bad):
        from parquet_tpu.schema.dsl import parse_schema

        sch = parse_schema("message m { required int64 a; }")
        w = FileWriter(str(tmp_path / "bad.parquet"), sch)
        with pytest.raises(ValueError):
            w.write_rows([bad])
            w.close()

    def test_exact_valued_floats_and_bools_accepted(self, tmp_path):
        from parquet_tpu.schema.dsl import parse_schema

        sch = parse_schema("message m { required int64 a; }")
        path = str(tmp_path / "ok.parquet")
        with FileWriter(path, sch) as w:
            w.write_rows([{"a": 7}, {"a": True}, {"a": 2.0}])
        assert pq.read_table(path).column("a").to_pylist() == [7, 1, 2]


class TestNativeExtension:
    """CPython extension hot loops (native/pyext.c) must agree with the
    pure-Python fallbacks exactly."""

    def test_encode_items_parity(self):
        import numpy as np

        from parquet_tpu.core.arrays import ByteArrayData, byte_array_from_items

        items = ["héllo", "", "x" * 300, "日本語", "plain"] * 50 + [b"\x00raw\xff"]
        got = byte_array_from_items(items)
        want = ByteArrayData.from_list(
            [x if isinstance(x, bytes) else x.encode("utf-8") for x in items]
        )
        assert np.array_equal(got.offsets, want.offsets) and got.data == want.data

    def test_encode_items_exotic_fallback(self):
        from parquet_tpu.core.arrays import byte_array_from_items

        got = byte_array_from_items([memoryview(b"ab"), bytearray(b"cd")])
        assert got.data == b"abcd"

    def test_dict_indices_parity(self):
        pytest.importorskip("parquet_tpu._native_ext")
        import numpy as np

        from parquet_tpu import _native_ext as ext

        vals = [f"k{i % 37}".encode() for i in range(10_000)]
        uniques, idx_b = ext.dict_indices(vals, 32767)
        idx = np.frombuffer(idx_b, dtype="<u4")
        assert len(uniques) == 37
        assert all(uniques[idx[i]] == vals[i] for i in range(0, 10_000, 997))
        # cutoff: exceeding max_uniques returns None
        many = [str(i).encode() for i in range(100)]
        assert ext.dict_indices(many, 50) is None


class TestArrowInput:
    """write_column accepts pyarrow Array/ChunkedArray (zero-copy ingest where
    layouts agree) — the same input class pq.write_table consumes."""

    def _roundtrip(self, schema_text, name, arr, expect):
        import io

        import pyarrow as pa  # noqa: F401

        from parquet_tpu.schema.dsl import parse_schema

        out = io.BytesIO()
        with FileWriter(out, parse_schema(schema_text)) as w:
            w.write_column(name, arr)
        out.seek(0)
        assert pq.read_table(out).column(name).to_pylist() == expect

    def test_numeric_and_temporal(self):
        import pyarrow as pa

        ints = list(range(1000))
        self._roundtrip("message m { required int64 a; }", "a", pa.array(ints), ints)
        self._roundtrip(
            "message m { required int32 a; }",
            "a",
            pa.array(ints, pa.int32()),
            ints,
        )
        fl = [i / 7 for i in range(1000)]
        self._roundtrip(
            "message m { required double a; }", "a", pa.array(fl, pa.float64()), fl
        )
        raw = [1_600_000_000_000_000 + i for i in range(100)]
        ts = pa.array(raw, pa.timestamp("us"))
        import io

        from parquet_tpu.schema.dsl import parse_schema

        out = io.BytesIO()
        with FileWriter(
            out,
            parse_schema("message m { required int64 a (TIMESTAMP_MICROS); }"),
        ) as w:
            w.write_column("a", ts)
        out.seek(0)
        got = pq.read_table(out).column("a").cast(pa.int64()).to_pylist()
        assert got == raw  # integer micros preserved exactly

    def test_strings_binary_chunked_sliced(self):
        import pyarrow as pa

        vals = [f"s{i % 13}" for i in range(2000)]
        self._roundtrip(
            "message m { required binary a (UTF8); }", "a", pa.array(vals), vals
        )
        self._roundtrip(
            "message m { required binary a (UTF8); }",
            "a",
            pa.array(vals, pa.large_string()),
            vals,
        )
        chunked = pa.chunked_array([vals[:800], vals[800:]])
        self._roundtrip(
            "message m { required binary a (UTF8); }", "a", chunked, vals
        )
        sliced = pa.array(vals).slice(37, 555)  # nonzero offset path
        self._roundtrip(
            "message m { required binary a (UTF8); }", "a", sliced, vals[37:592]
        )
        bins = [bytes([i % 256, (i * 3) % 256]) for i in range(500)]
        self._roundtrip("message m { required binary a; }", "a", pa.array(bins), bins)

    def test_bool_and_fixed(self):
        import pyarrow as pa

        flags = [i % 3 == 0 for i in range(333)]
        self._roundtrip("message m { required boolean a; }", "a", pa.array(flags), flags)
        fxd = [bytes([i % 256] * 4) for i in range(100)]
        self._roundtrip(
            "message m { required fixed_len_byte_array(4) a; }",
            "a",
            pa.array(fxd, pa.binary(4)),
            fxd,
        )

    def test_nulls_rejected_with_clear_error(self):
        import io

        import pyarrow as pa

        from parquet_tpu.schema.dsl import parse_schema

        with pytest.raises(ValueError, match="null"):
            with FileWriter(
                io.BytesIO(), parse_schema("message m { optional int64 a; }")
            ) as w:
                w.write_column("a", pa.array([1, None, 3]))
                w.flush_row_group()
        # nulls hiding in a dictionary array's VALUE buffer (indices report
        # null_count 0) must be rejected too, not written as empty strings
        dict_arr = pa.DictionaryArray.from_arrays(
            pa.array([0, 1, 0]), pa.array(["a", None])
        )
        with pytest.raises(ValueError, match="null"):
            with FileWriter(
                io.BytesIO(),
                parse_schema("message m { required binary a (UTF8); }"),
            ) as w:
                w.write_column("a", dict_arr)
                w.flush_row_group()

    def test_dictionary_array_decodes(self):
        import io

        import pyarrow as pa

        from parquet_tpu.schema.dsl import parse_schema

        vals = ["x", "y", "x", "z", "y"] * 100
        dict_arr = pa.array(vals).dictionary_encode()
        out = io.BytesIO()
        with FileWriter(
            out, parse_schema("message m { required binary a (UTF8); }")
        ) as w:
            w.write_column("a", dict_arr)
        out.seek(0)
        assert pq.read_table(out).column("a").to_pylist() == vals


class TestMetadataCompleteness:
    def test_sorting_columns_distinct_count_file_offset(self, tmp_path):
        import numpy as np

        from parquet_tpu.schema.dsl import parse_schema

        schema = parse_schema(
            "message m { required int64 id; required binary s (UTF8); }"
        )
        path = str(tmp_path / "meta.parquet")
        with FileWriter(
            path, schema, codec="snappy",
            sorting_columns=[("id", False, False)],
        ) as w:
            w.write_column("id", np.arange(5_000, dtype=np.int64))
            w.write_column("s", [f"v{i % 40}" for i in range(5_000)])
        md = pq.ParquetFile(path).metadata
        rg = md.row_group(0)
        assert tuple(rg.sorting_columns) == (
            pq.SortingColumn(column_index=0, descending=False, nulls_first=False),
        )
        # exact distinct count recorded for the dictionary-encoded column
        assert rg.column(1).statistics.distinct_count == 40
        # file_offset points at the chunk's first page, not 0
        assert rg.column(0).file_offset > 0
        from parquet_tpu.core.reader import FileReader

        with FileReader(path) as r:
            sc = r.row_group(0).sorting_columns
            assert sc and sc[0].column_idx == 0 and sc[0].descending is False

    def test_bad_sorting_spec_rejected(self, tmp_path):
        import io as _io

        from parquet_tpu.schema.dsl import parse_schema

        schema = parse_schema("message m { required int64 id; }")
        with pytest.raises(WriterError, match="sorting_columns"):
            FileWriter(_io.BytesIO(), schema, sorting_columns=[(1, 2)])

    def test_bad_option_does_not_truncate_existing_file(self, tmp_path):
        """Option validation happens BEFORE the sink opens: a typo'd option
        must never destroy an existing file (review regression)."""
        from parquet_tpu.schema.dsl import parse_schema

        schema = parse_schema("message m { required int64 id; }")
        path = tmp_path / "precious.parquet"
        path.write_bytes(b"IRREPLACEABLE")
        for bad_kw in (
            {"codec": "nope"},
            {"sorting_columns": ["typo"]},
            {"bloom_filters": ["typo"]},
            {"column_encodings": {"typo": "PLAIN"}},
            {"data_page_version": 3},
        ):
            with pytest.raises(WriterError):
                FileWriter(str(path), schema, **bad_kw)
            assert path.read_bytes() == b"IRREPLACEABLE", bad_kw


class TestSizeIntrospection:
    """Size-based flushing signals (reference: file_writer.go:352-363
    CurrentRowGroupSize/CurrentFileSize)."""

    def test_current_row_group_size_tracks_buffered_data(self, tmp_path):
        import numpy as np

        from parquet_tpu import FileWriter, parse_schema

        schema = parse_schema(
            "message m { required int64 a; optional binary s (UTF8); }"
        )
        path = str(tmp_path / "sz.parquet")
        with FileWriter(path, schema) as w:
            assert w.current_row_group_size == 0
            w.write_rows([{"a": i, "s": "x" * 10} for i in range(1000)])
            est = w.current_row_group_size
            # 8B ints + 10B strings (+len prefixes, levels): sane bracket
            assert 18_000 <= est <= 40_000, est
            before_flush = w.current_file_size
            w.flush_row_group()
            assert w.current_row_group_size == 0
            assert w.current_file_size > before_flush
            # columnar input tracks too
            w.write_column("a", np.arange(500, dtype=np.int64))
            w.write_column("s", ["yy"] * 500)
            est2 = w.current_row_group_size
            assert 4_000 <= est2 <= 12_000, est2

    def test_size_based_flush_loop(self, tmp_path):
        """The reference's canonical use: flush whenever the buffered group
        passes a target size."""
        from parquet_tpu import FileReader, FileWriter, parse_schema

        schema = parse_schema("message m { required int64 a; }")
        path = str(tmp_path / "szloop.parquet")
        with FileWriter(path, schema) as w:
            for i in range(20_000):
                w.write_row({"a": i})
                if w.current_row_group_size >= 32_000:
                    w.flush_row_group()
        with FileReader(path) as r:
            assert r.num_row_groups > 2
            assert [x["a"] for x in r.iter_rows()] == list(range(20_000))

"""parquet_tpu.io.sign tests: the PQT4-HMAC-SHA256 request signer and its
server-side verifier share one canonicalization, so every property is
provable without a network: sign -> verify round trips, every tamper
dimension (body, path, query order vs content, method, credentials, date)
flips the right rejection reason, and the registry resolves signers by
longest URL prefix for the open_source/open_sink coercion path."""

import pytest

from parquet_tpu.io.sign import (
    SigV4Signer,
    clear_signers,
    configure_signer,
    sign_headers,
    signer_for,
    verify_request,
)
from parquet_tpu.utils import metrics

T0 = 1_700_000_000.0  # 2023-11-14T22:13:20Z — a pinned signing instant
CLOCK = lambda: T0
CREDS = {"AK1": "secret-one", "AK2": "secret-two"}


def make_signer(key="AK1", **kw):
    kw.setdefault("clock", CLOCK)
    return SigV4Signer(key, CREDS[key], **kw)


def verify(method, target, headers, payload=b"", *, host="store.local", **kw):
    kw.setdefault("clock", CLOCK)
    return verify_request(
        method, target, headers, payload, CREDS.get, host=host, **kw
    )


class TestSignVerifyRoundTrip:
    def test_get_and_put_verify(self):
        s = make_signer()
        for method, payload in (("GET", b""), ("PUT", b"part bytes")):
            h = s.headers(method, "http://store.local/bucket/key", payload)
            assert verify(method, "/bucket/key", h, payload) is None

    def test_signature_is_deterministic_under_a_pinned_clock(self):
        a = make_signer().headers("GET", "http://store.local/k")
        b = make_signer().headers("GET", "http://store.local/k")
        assert a == b

    def test_query_pair_order_does_not_change_the_signature(self):
        # clients build query strings in whatever order; canonicalization
        # sorts the PAIRS so both orders verify...
        s = make_signer()
        h = s.headers("PUT", "http://store.local/k?b=2&a=1", b"x")
        assert verify("PUT", "/k?a=1&b=2", h, b"x") is None

    def test_query_pair_content_does_change_the_signature(self):
        # ...but swapping a VALUE (e.g. partNumber between uploads) must not
        s = make_signer()
        h = s.headers("PUT", "http://store.local/k?partNumber=1", b"x")
        assert verify("PUT", "/k?partNumber=2", h, b"x") == "signature_mismatch"

    def test_explicit_port_is_part_of_the_signed_host(self):
        s = make_signer()
        h = s.headers("GET", "http://store.local:8080/k")
        assert verify("GET", "/k", h, host="store.local:8080") is None
        assert verify("GET", "/k", h, host="store.local") == "signature_mismatch"

    def test_sign_headers_functional_core_matches_the_class(self):
        h = sign_headers(
            "GET",
            "http://store.local/k",
            access_key="AK1",
            secret_key=CREDS["AK1"],
            clock=CLOCK,
        )
        assert h == make_signer().headers("GET", "http://store.local/k")


class TestRejections:
    def _h(self, method="PUT", payload=b"body"):
        return make_signer().headers(method, "http://store.local/k", payload)

    def test_unsigned_request_is_rejected(self):
        assert (
            verify("PUT", "/k", {}, b"body")
            == "missing_or_malformed_authorization"
        )

    def test_tampered_body_fails_the_payload_hash(self):
        h = self._h()
        assert verify("PUT", "/k", h, b"tampered") == "payload_hash_mismatch"

    def test_tampered_path_fails_the_signature(self):
        h = self._h()
        assert verify("PUT", "/other", h, b"body") == "signature_mismatch"

    def test_replayed_signature_on_another_method_fails(self):
        h = self._h("PUT")
        assert verify("DELETE", "/k", h, b"body") == "signature_mismatch"

    def test_unknown_access_key(self):
        s = SigV4Signer("AK-GHOST", "whatever", clock=CLOCK)
        h = s.headers("GET", "http://store.local/k")
        assert verify("GET", "/k", h) == "unknown_access_key"

    def test_wrong_secret_fails_the_signature(self):
        s = SigV4Signer("AK1", "not-the-secret", clock=CLOCK)
        h = s.headers("GET", "http://store.local/k")
        assert verify("GET", "/k", h) == "signature_mismatch"

    def test_date_skew_beyond_the_window(self):
        h = self._h()
        assert (
            verify("PUT", "/k", h, b"body", clock=lambda: T0 + 3600)
            == "date_skew"
        )
        # inside the window the same request still verifies
        assert verify("PUT", "/k", h, b"body", clock=lambda: T0 + 60) is None

    def test_mangled_date_header(self):
        h = dict(self._h())
        h["x-pqt-date"] = "yesterday-ish0000"
        assert verify("PUT", "/k", h, b"body") == "missing_or_malformed_date"

    def test_mangled_authorization_scheme(self):
        h = dict(self._h())
        h["Authorization"] = "AWS4-HMAC-SHA256 " + h["Authorization"].split(" ", 1)[1]
        assert (
            verify("PUT", "/k", h, b"body")
            == "missing_or_malformed_authorization"
        )

    def test_repr_never_leaks_the_secret(self):
        assert CREDS["AK1"] not in repr(make_signer())


class TestMetricsAndRegistry:
    def test_every_sign_counts_by_method(self):
        before = metrics.snapshot()
        s = make_signer()
        s.headers("GET", "http://store.local/k")
        s.headers("PUT", "http://store.local/k", b"x")
        s.headers("PUT", "http://store.local/k", b"y")
        d = metrics.delta(before)
        assert d.get('io_sign_requests_total{method="GET"}') == 1
        assert d.get('io_sign_requests_total{method="PUT"}') == 2

    def test_longest_prefix_wins_and_none_removes(self):
        wide = make_signer("AK1")
        narrow = make_signer("AK2")
        try:
            configure_signer(wide, prefix="http://store.local/")
            configure_signer(narrow, prefix="http://store.local/hot/")
            assert signer_for("http://store.local/cold/k") is wide
            assert signer_for("http://store.local/hot/k") is narrow
            assert signer_for("http://elsewhere/k") is None
            configure_signer(None, prefix="http://store.local/hot/")
            assert signer_for("http://store.local/hot/k") is wide
        finally:
            clear_signers()

    def test_empty_prefix_is_the_catch_all(self):
        s = make_signer()
        try:
            configure_signer(s)
            assert signer_for("https://anything.example/x") is s
        finally:
            clear_signers()


@pytest.fixture(autouse=True)
def _no_registry_leak():
    yield
    clear_signers()

"""Driver-entry smoke tests: entry() compiles, dryrun_multichip is hermetic.

dryrun_multichip must succeed with NO environment preparation at all (the
round-1 driver run died dispatching an eager op to a broken default TPU
runtime), so the key test here runs it in a clean subprocess without
JAX_PLATFORMS/XLA_FLAGS and expects rc=0.
"""

import os
import subprocess
import sys
from pathlib import Path

import jax
import numpy as np

REPO = Path(__file__).resolve().parent.parent


def test_entry_compiles_and_runs():
    sys.path.insert(0, str(REPO))
    from __graft_entry__ import entry

    fn, args = entry()
    out, stats = jax.jit(fn)(*args)
    jax.block_until_ready((out, stats))
    assert int(stats["count"]) > 0
    assert out.shape[0] == 4  # n_pages


def test_dryrun_multichip_in_process():
    sys.path.insert(0, str(REPO))
    from __graft_entry__ import dryrun_multichip

    dryrun_multichip(8)


def test_dryrun_multichip_hermetic_subprocess():
    """No env prep at all: the entry must pin itself to CPU and set the
    host-platform device count on its own."""
    env = {k: v for k, v in os.environ.items() if k not in ("XLA_FLAGS", "JAX_PLATFORMS")}
    proc = subprocess.run(
        [
            sys.executable,
            "-c",
            # hermeticity proof: the whole run must not have INITIALIZED any
            # non-CPU backend — a broken accelerator plugin (the round-1 and
            # round-3 driver failures) then cannot poison the run even in
            # principle, on any thread. Probe the initialized-backend set via
            # the internal registry when present (exact), falling back to the
            # public device list on jax versions that moved it.
            "from __graft_entry__ import dryrun_multichip; dryrun_multichip(8)\n"
            "try:\n"
            "    import jax._src.xla_bridge as xb\n"
            "    inited = set(xb._backends)\n"
            "except Exception:\n"
            "    import jax\n"
            "    inited = {d.platform for d in jax.devices()}\n"
            "assert inited == {'cpu'}, sorted(inited)",
        ],
        cwd=REPO,
        env=env,
        capture_output=True,
        text=True,
        timeout=600,
    )
    assert proc.returncode == 0, f"stderr tail:\n{proc.stderr[-2000:]}"
    assert "dryrun_multichip OK" in proc.stdout


def test_dryrun_odd_device_count():
    sys.path.insert(0, str(REPO))
    from __graft_entry__ import dryrun_multichip

    dryrun_multichip(5)  # exercises the (n, 1) mesh-shape fallback

"""Unit tests for the host-path encoders/decoders (ops/).

Mirrors the reference's kernel-level strategy (SURVEY §4.1-4.2): exhaustive
widths for bit-pack, roundtrips with random data for every codec, plus scalar
reference decoders as independent oracles.
"""

import numpy as np
import pytest

from parquet_tpu.core.arrays import ByteArrayData
from parquet_tpu.core import compress
from parquet_tpu.meta import CompressionCodec, Type
from parquet_tpu.ops.bitpack import bit_width, pack_bits, unpack_bits
from parquet_tpu.ops.bytearray import (
    decode_delta_byte_array,
    decode_delta_length_byte_array,
    encode_delta_byte_array,
    encode_delta_length_byte_array,
)
from parquet_tpu.ops.delta import DeltaError, decode_delta, encode_delta
from parquet_tpu.ops.dictionary import DictError, decode_dict_indices, encode_dict_indices
from parquet_tpu.ops.levels import (
    decode_levels_v1,
    decode_levels_v2,
    encode_levels_v1,
    encode_levels_v2,
)
from parquet_tpu.ops.plain import decode_plain, encode_plain
from parquet_tpu.ops.rle_hybrid import (
    HybridError,
    decode_hybrid,
    encode_hybrid,
    prescan_hybrid,
)

rng = np.random.default_rng(42)


def _scalar_unpack(data: bytes, n: int, width: int) -> list[int]:
    """Independent scalar oracle: read bit i*W..(i+1)*W LSB-first."""
    out = []
    for i in range(n):
        v = 0
        for j in range(width):
            bitpos = i * width + j
            bit = (data[bitpos // 8] >> (bitpos % 8)) & 1
            v |= bit << j
        out.append(v)
    return out


class TestBitpack:
    @pytest.mark.parametrize("width", list(range(0, 65)))
    def test_roundtrip_every_width(self, width):
        n = 64
        hi = (1 << width) if width else 1
        vals = rng.integers(0, hi, size=n, dtype=np.uint64)
        packed = pack_bits(vals, width)
        out = unpack_bits(packed, n, width)
        np.testing.assert_array_equal(out, vals)

    @pytest.mark.parametrize("width", [1, 3, 7, 8, 13, 31, 32])
    def test_against_scalar_oracle(self, width):
        n = 24
        vals = rng.integers(0, 1 << width, size=n, dtype=np.uint64)
        packed = pack_bits(vals, width)
        assert _scalar_unpack(packed, n, width) == list(vals)

    def test_width_zero(self):
        assert unpack_bits(b"", 10, 0).tolist() == [0] * 10
        assert pack_bits(np.array([0, 0]), 0) == b""

    def test_too_short_raises(self):
        with pytest.raises(ValueError):
            unpack_bits(b"\x01", 9, 8)

    def test_bit_width(self):
        assert bit_width(0) == 0
        assert bit_width(1) == 1
        assert bit_width(255) == 8
        assert bit_width(256) == 9


class TestHybrid:
    @pytest.mark.parametrize("width", [1, 2, 5, 8, 12, 20, 32])
    def test_roundtrip_random(self, width):
        n = 1000
        vals = rng.integers(0, 1 << min(width, 31), size=n, dtype=np.uint32)
        data = encode_hybrid(vals, width)
        out = decode_hybrid(data, n, width)
        np.testing.assert_array_equal(out, vals)

    def test_roundtrip_rle_heavy(self):
        # Long constant stretches must roundtrip (and hit the RLE emit path).
        vals = np.repeat(np.array([3, 0, 7, 0], dtype=np.uint32), [100, 50, 9, 41])
        data = encode_hybrid(vals, 3)
        assert len(data) < len(vals)  # RLE actually engaged
        np.testing.assert_array_equal(decode_hybrid(data, len(vals), 3), vals)

    def test_roundtrip_alternating(self):
        vals = np.tile(np.array([0, 1], dtype=np.uint32), 500)
        data = encode_hybrid(vals, 1)
        np.testing.assert_array_equal(decode_hybrid(data, len(vals), 1), vals)

    def test_unaligned_tail(self):
        for n in [1, 7, 8, 9, 15, 17]:
            vals = rng.integers(0, 4, size=n, dtype=np.uint32)
            data = encode_hybrid(vals, 2)
            np.testing.assert_array_equal(decode_hybrid(data, n, 2), vals)

    def test_decodes_rle_run_stream(self):
        # Hand-built stream: RLE run of 300 x value 5 at width 3.
        out = bytearray()
        out.append((300 << 1) & 0x7F | 0x80)
        out.append((300 << 1) >> 7)
        out.append(5)
        vals = decode_hybrid(bytes(out), 300, 3)
        assert vals.tolist() == [5] * 300

    def test_rle_value_exceeding_width_rejected(self):
        # RLE run advertising value 9 at width 3 (max 7) must be rejected
        # (reference: hybrid_decoder.go:126-129).
        stream = bytes([2 << 1, 9])
        with pytest.raises(HybridError):
            decode_hybrid(stream, 2, 3)

    def test_truncated_stream_rejected(self):
        vals = rng.integers(0, 4, size=100, dtype=np.uint32)
        data = encode_hybrid(vals, 2)
        with pytest.raises(HybridError):
            decode_hybrid(data[: len(data) // 2], 100, 2)

    def test_prescan_structure(self):
        vals = np.concatenate(
            [np.full(64, 2, np.uint32), rng.integers(0, 8, 32, dtype=np.uint32)]
        )
        t = prescan_hybrid(encode_hybrid(vals, 3), len(vals), 3)
        assert t.total_values >= len(vals)
        assert t.is_rle.any()

    def test_width_zero_stream(self):
        data = encode_hybrid(np.zeros(100, np.uint32), 0)
        np.testing.assert_array_equal(decode_hybrid(data, 100, 0), np.zeros(100))


def _scalar_delta_decode(data: bytes, nbits: int):
    """Independent scalar oracle implementing the spec directly."""
    pos = 0

    def uvar():
        nonlocal pos
        r, s = 0, 0
        while True:
            b = data[pos]
            pos += 1
            r |= (b & 0x7F) << s
            if not b & 0x80:
                return r
            s += 7

    def zz():
        n = uvar()
        return (n >> 1) ^ -(n & 1)

    mask = (1 << nbits) - 1
    bs, mc, total, first = uvar(), uvar(), uvar(), zz()
    ml = bs // mc
    vals = [first & mask]
    while len(vals) < total:
        mind = zz()
        widths = list(data[pos : pos + mc])
        pos += mc
        for w in widths:
            payload = (ml // 8) * w
            if len(vals) >= total:
                pos += payload
                continue
            chunk = data[pos : pos + payload]
            pos += payload
            for i in range(min(ml, total - len(vals))):
                v = 0
                for j in range(w):
                    bitpos = i * w + j
                    v |= ((chunk[bitpos // 8] >> (bitpos % 8)) & 1) << j
                vals.append((vals[-1] + v + mind) & mask)
    sign = 1 << (nbits - 1)
    return [v - (1 << nbits) if v >= sign else v for v in vals[:total]]


class TestDelta:
    @pytest.mark.parametrize("nbits", [32, 64])
    def test_roundtrip_random(self, nbits):
        dt = np.int32 if nbits == 32 else np.int64
        vals = rng.integers(-(2**20), 2**20, size=1000).astype(dt)
        data = encode_delta(vals, nbits)
        out, consumed = decode_delta(data, nbits)
        np.testing.assert_array_equal(out, vals)
        assert consumed == len(data)

    @pytest.mark.parametrize("nbits", [32, 64])
    def test_overflow_extremes(self, nbits):
        # min-delta subtraction overflow semantics (reference: deltabp_encoder.go:58-61)
        dt = np.int32 if nbits == 32 else np.int64
        info = np.iinfo(dt)
        vals = np.array(
            [info.min, info.max, 0, info.min, info.max, -1, 1, info.max, info.min],
            dtype=dt,
        )
        data = encode_delta(vals, nbits)
        out, _ = decode_delta(data, nbits)
        np.testing.assert_array_equal(out, vals)

    def test_against_scalar_oracle(self):
        vals = rng.integers(-(2**30), 2**30, size=300).astype(np.int32)
        data = encode_delta(vals, 32)
        assert _scalar_delta_decode(data, 32) == vals.tolist()

    def test_sorted_timestamps(self):
        base = 1_600_000_000_000_000
        vals = (base + np.cumsum(rng.integers(0, 1000, size=5000))).astype(np.int64)
        data = encode_delta(vals, 64)
        out, _ = decode_delta(data, 64)
        np.testing.assert_array_equal(out, vals)
        assert len(data) < vals.nbytes // 4  # delta actually compresses

    @pytest.mark.parametrize("n", [0, 1, 2, 127, 128, 129, 257])
    def test_sizes(self, n):
        vals = rng.integers(-100, 100, size=n).astype(np.int64)
        out, _ = decode_delta(encode_delta(vals, 64), 64)
        np.testing.assert_array_equal(out, vals)

    def test_invalid_block_size_rejected(self):
        # header: block size 100 (not multiple of 128)
        data = bytes([100, 4, 1, 0])
        with pytest.raises(DeltaError):
            decode_delta(data, 32)

    def test_width_exceeding_type_rejected(self):
        vals = np.arange(10, dtype=np.int32)
        data = bytearray(encode_delta(vals, 32))
        # corrupt first miniblock width byte to 60 (> 32)
        # header is 4 varints: 128,4,10,zz(0) -> bytes [0x80 0x01, 0x04, 0x0a, 0x00]
        # then min-delta zigzag, then 4 width bytes
        data[6] = 60
        with pytest.raises(DeltaError):
            decode_delta(bytes(data), 32)


class TestPlain:
    @pytest.mark.parametrize(
        "ptype,dtype",
        [
            (Type.INT32, np.int32),
            (Type.INT64, np.int64),
            (Type.FLOAT, np.float32),
            (Type.DOUBLE, np.float64),
        ],
    )
    def test_numeric_roundtrip(self, ptype, dtype):
        if np.issubdtype(dtype, np.integer):
            vals = rng.integers(np.iinfo(dtype).min, np.iinfo(dtype).max, 500).astype(dtype)
        else:
            vals = rng.standard_normal(500).astype(dtype)
        data = encode_plain(vals, ptype)
        out, consumed = decode_plain(data, 500, ptype)
        np.testing.assert_array_equal(out, vals)
        assert consumed == len(data)

    def test_nan_bit_exact(self):
        # NaN payload preservation (reference CHANGELOG.md:31 NaN handling)
        v = np.array([np.nan, -np.nan, np.inf, -0.0], dtype=np.float64)
        out, _ = decode_plain(encode_plain(v, Type.DOUBLE), 4, Type.DOUBLE)
        np.testing.assert_array_equal(out.view(np.uint64), v.view(np.uint64))

    def test_boolean_roundtrip(self):
        for n in [1, 7, 8, 9, 100]:
            vals = rng.integers(0, 2, n).astype(bool)
            data = encode_plain(vals, Type.BOOLEAN)
            out, consumed = decode_plain(data, n, Type.BOOLEAN)
            np.testing.assert_array_equal(out, vals)
            assert consumed == (n + 7) // 8

    def test_int96_roundtrip(self):
        vals = rng.integers(0, 256, size=(20, 12)).astype(np.uint8)
        out, _ = decode_plain(encode_plain(vals, Type.INT96), 20, Type.INT96)
        np.testing.assert_array_equal(out, vals)

    def test_fixed_roundtrip(self):
        vals = rng.integers(0, 256, size=(20, 5)).astype(np.uint8)
        data = encode_plain(vals, Type.FIXED_LEN_BYTE_ARRAY, type_length=5)
        out, _ = decode_plain(data, 20, Type.FIXED_LEN_BYTE_ARRAY, type_length=5)
        np.testing.assert_array_equal(out, vals)

    def test_byte_array_roundtrip(self):
        items = [b"", b"a", b"hello world", bytes(rng.integers(0, 256, 100).astype(np.uint8))]
        ba = ByteArrayData.from_list(items)
        data = encode_plain(ba, Type.BYTE_ARRAY)
        out, consumed = decode_plain(data, len(items), Type.BYTE_ARRAY)
        assert out.to_list() == items
        assert consumed == len(data)

    def test_byte_array_bad_length_rejected(self):
        data = (1000).to_bytes(4, "little") + b"short"
        with pytest.raises(ValueError):
            decode_plain(data, 1, Type.BYTE_ARRAY)

    def test_truncated_numeric_rejected(self):
        with pytest.raises(ValueError):
            decode_plain(b"\x00" * 7, 1, Type.INT64)


class TestByteArrayDeltas:
    def test_delta_length_roundtrip(self):
        items = [b"alpha", b"", b"beta", b"gammagamma" * 10]
        ba = ByteArrayData.from_list(items)
        data = encode_delta_length_byte_array(ba)
        out, consumed = decode_delta_length_byte_array(data, len(items))
        assert out.to_list() == items
        assert consumed == len(data)

    def test_delta_byte_array_roundtrip(self):
        items = [b"apple", b"applesauce", b"application", b"banana", b"band", b""]
        ba = ByteArrayData.from_list(items)
        data = encode_delta_byte_array(ba)
        out, consumed = decode_delta_byte_array(data, len(items))
        assert out.to_list() == items
        assert consumed == len(data)

    def test_delta_byte_array_sorted_strings_compress(self):
        items = [f"user_{i:08d}".encode() for i in range(1000)]
        data = encode_delta_byte_array(ByteArrayData.from_list(items))
        assert len(data) < sum(len(x) for x in items) // 2
        out, _ = decode_delta_byte_array(data, 1000)
        assert out.to_list() == items


class TestDictIndices:
    def test_roundtrip(self):
        idx = rng.integers(0, 100_000, size=5000).astype(np.uint32)
        data = encode_dict_indices(idx, 100_000)
        out = decode_dict_indices(data, 5000, 100_000)
        np.testing.assert_array_equal(out, idx)

    def test_out_of_range_rejected(self):
        data = encode_dict_indices(np.array([0, 5], np.uint32), 6)
        with pytest.raises(DictError):
            decode_dict_indices(data, 2, 3)

    def test_invalid_width_rejected(self):
        with pytest.raises(DictError):
            decode_dict_indices(bytes([40, 0]), 1, 10)


class TestLevels:
    def test_v1_roundtrip(self):
        levels = rng.integers(0, 4, size=999).astype(np.uint16)
        data = encode_levels_v1(levels, 3)
        out, consumed = decode_levels_v1(data, 999, 3)
        np.testing.assert_array_equal(out, levels)
        assert consumed == len(data)

    def test_v2_roundtrip(self):
        levels = rng.integers(0, 2, size=512).astype(np.uint16)
        data = encode_levels_v2(levels, 1)
        np.testing.assert_array_equal(decode_levels_v2(data, 512, 1), levels)

    def test_max_level_zero(self):
        assert encode_levels_v1([], 0) == b""
        out, consumed = decode_levels_v1(b"anything", 5, 0)
        assert out.tolist() == [0] * 5
        assert consumed == 0

    def test_level_exceeding_max_rejected(self):
        # Hand-built RLE run of value 3 at width 2; max_level 2 makes 3 invalid.
        stream = bytes([4 << 1, 3])
        with pytest.raises(ValueError):
            decode_levels_v2(stream, 4, 2)


class TestCompress:
    @pytest.mark.parametrize(
        "codec",
        [
            CompressionCodec.UNCOMPRESSED,
            CompressionCodec.GZIP,
            CompressionCodec.SNAPPY,
            CompressionCodec.ZSTD,
        ],
    )
    def test_roundtrip(self, codec):
        data = b"parquet " * 1000 + bytes(rng.integers(0, 256, 1000).astype(np.uint8))
        comp = compress.compress_block(data, codec)
        out = compress.decompress_block(comp, codec, len(data))
        assert out == data
        if codec != CompressionCodec.UNCOMPRESSED:
            assert len(comp) < len(data)

    def test_snappy_interop_with_pyarrow(self):
        import pyarrow as pa

        data = b"the quick brown fox " * 500
        ours = compress.compress_block(data, CompressionCodec.SNAPPY)
        assert pa.Codec("snappy").decompress(ours, decompressed_size=len(data)).to_pybytes() == data
        theirs = pa.Codec("snappy").compress(data).to_pybytes()
        assert compress.decompress_block(theirs, CompressionCodec.SNAPPY, len(data)) == data

    def test_size_mismatch_rejected(self):
        comp = compress.compress_block(b"hello", CompressionCodec.GZIP)
        with pytest.raises(compress.CompressionError):
            compress.decompress_block(comp, CompressionCodec.GZIP, 999)

    def test_unregistered_codec_rejected(self):
        with pytest.raises(compress.CompressionError):
            compress.compress_block(b"x", CompressionCodec.LZO)


class TestNativeParity:
    """The C fast paths (native/parquet_tpu_native.cc) must agree bit-for-bit
    with the NumPy reference implementations on values, consumed counts, and
    error behavior. Skipped when the library isn't built."""

    @pytest.fixture()
    def native(self):
        from parquet_tpu.utils.native import get_native

        lib = get_native()
        if lib is None:
            pytest.skip("native library not built")
        return lib

    @pytest.fixture()
    def numpy_only(self):
        """Force the pure-NumPy implementations for the duration of a test."""
        from parquet_tpu.utils import native as native_mod

        old = native_mod._cached, native_mod._probed
        native_mod._cached, native_mod._probed = None, True
        yield
        native_mod._cached, native_mod._probed = old

    def test_delta_decode_matches_numpy(self, native):
        from parquet_tpu.ops.delta import prescan_delta

        r = np.random.default_rng(7)
        for nbits, dt in ((32, np.int32), (64, np.int64)):
            for n in (0, 1, 2, 127, 128, 129, 4096):
                v = r.integers(-(10**6), 10**6, n).astype(dt)
                enc = encode_delta(v, nbits)
                got, consumed = native.delta_decode(enc, nbits, n)
                assert np.array_equal(got.view(dt), v)
                if n:
                    assert consumed == prescan_delta(enc, nbits, n).consumed

    def test_delta_decode_wrapping(self, native):
        v = np.array([2**62, -(2**62), 5, 2**62 - 1, -1], dtype=np.int64)
        enc = encode_delta(v, 64)
        got, _ = native.delta_decode(enc, 64, len(v))
        assert np.array_equal(got, v)

    def test_hybrid_encode_matches_numpy(self, native, numpy_only):
        """The C hybrid encoder must be byte-identical to encode_hybrid:
        same RLE run selection, same 8-alignment, same trailing padding."""
        from parquet_tpu.ops.rle_hybrid import encode_hybrid

        r = np.random.default_rng(13)
        for trial in range(120):
            w = int(r.integers(1, 33))
            n = int(r.integers(0, 600))
            style = trial % 4
            if style == 0:
                v = r.integers(0, 1 << w, n, dtype=np.uint64)
            elif style == 1:  # one long run
                v = np.full(n, int(r.integers(0, 1 << w)), dtype=np.uint64)
            elif style == 2:  # short runs straddling 8-boundaries
                reps = r.integers(0, 1 << w, max(n // 9, 1), dtype=np.uint64)
                v = np.repeat(reps, 9)[:n]
            else:
                v = np.zeros(n, dtype=np.uint64)
            ref = encode_hybrid(v, w)  # numpy path (native forced off)
            assert native.hybrid_encode(v, w) == ref, (trial, w, n)

    def test_delta_encode_matches_numpy(self, native, numpy_only):
        from parquet_tpu.ops.delta import encode_delta as enc_py

        r = np.random.default_rng(17)
        for nbits, dt in ((32, np.int32), (64, np.int64)):
            for n in (0, 1, 2, 100, 127, 128, 129, 513, 4096):
                v = r.integers(np.iinfo(dt).min // 2, np.iinfo(dt).max // 2, n).astype(dt)
                assert native.delta_encode(v, nbits, 128, 4) == enc_py(v, nbits)
            # wrap-around deltas
            v = np.array(
                [np.iinfo(dt).min, np.iinfo(dt).max, 0, -1, 1], dtype=dt
            )
            assert native.delta_encode(v, nbits, 128, 4) == enc_py(v, nbits)

    def test_delta_encode_exotic_mini_count_no_crash(self, native):
        """mini_count > 512 exceeds every decoder's cap (and the C encoder's
        widths buffer — a stack overflow before the guard): it must take the
        NumPy path, not crash."""
        from parquet_tpu.ops.delta import encode_delta as enc_py

        v = np.arange(20_000, dtype=np.int64)
        enc = enc_py(v, 64, block_size=8192, mini_count=1024)
        assert len(enc) > 0
        # and the C entry point itself rejects it instead of overflowing
        import ctypes

        out = np.empty(1 << 20, dtype=np.uint8)
        rc = native._lib.ptq_delta_encode(
            ctypes.c_void_p(v.ctypes.data), len(v), 64, 8192, 1024,
            ctypes.c_void_p(out.ctypes.data), len(out),
        )
        assert rc == -1

    def test_bytes_dict_probe_matches_python(self, native):
        from parquet_tpu.core.arrays import ByteArrayData

        r = np.random.default_rng(23)
        for trial in range(20):
            n = int(r.integers(0, 1500))
            items = [f"v{int(x)}".encode() for x in r.integers(0, 60, n)]
            ba = ByteArrayData.from_list(items)
            firsts, indices = native.bytes_dict_indices(ba.data, ba.offsets, 32767)
            uniq: dict = {}
            for i, it in enumerate(items):
                uniq.setdefault(it, len(uniq))
            assert [items[f] for f in firsts] == list(uniq)
            assert [int(i) for i in indices] == [uniq[it] for it in items]
        # cutoff: more uniques than the cap returns None
        items = [f"u{i}".encode() for i in range(40_000)]
        ba = ByteArrayData.from_list(items)
        assert native.bytes_dict_indices(ba.data, ba.offsets, 32767) is None

    def test_u64_dict_probe_and_minmax(self, native):
        r = np.random.default_rng(29)
        v = r.integers(0, 50, 3000, dtype=np.uint64)
        firsts, indices = native.u64_dict_indices(v, 32767)
        uniq: dict = {}
        for x in v.tolist():
            uniq.setdefault(x, len(uniq))
        assert [int(v[f]) for f in firsts] == list(uniq)
        assert all(int(v[firsts[i]]) == int(x) for x, i in zip(v.tolist(), indices))
        assert native.u64_dict_indices(
            np.arange(40_000, dtype=np.uint64), 32767
        ) is None
        # byte-array lexicographic minmax incl. prefix ties
        from parquet_tpu.core.arrays import ByteArrayData

        items = [b"bb", b"b", b"ba", b"bbb", b"a", b"ab"]
        ba = ByteArrayData.from_list(items)
        i_mn, i_mx = native.bytes_minmax(ba.data, ba.offsets)
        assert items[i_mn] == min(items) and items[i_mx] == max(items)

    def test_delta_rejects_oversized_claim(self, native):
        v = np.arange(100, dtype=np.int32)
        enc = encode_delta(v, 32)
        with pytest.raises(OverflowError):
            native.delta_decode(enc, 32, 50)

    def test_delta_rejects_implausible_header_before_alloc(self, native):
        out = bytearray()
        from parquet_tpu.ops.varint import emit_uvarint, emit_zigzag

        emit_uvarint(out, 128)  # block size
        emit_uvarint(out, 4)  # miniblocks
        emit_uvarint(out, 1 << 40)  # absurd value count for a tiny stream
        emit_zigzag(out, 0)
        with pytest.raises(ValueError):
            native.delta_decode(bytes(out), 32, None)

    def test_hybrid_decode_matches_numpy(self, native):
        r = np.random.default_rng(8)
        for width in (0, 1, 3, 8, 13, 24, 32, 47, 64):
            n = 1000
            hi = 1 << min(width, 48) if width else 1
            vals = r.integers(0, hi, n, dtype=np.uint64)
            enc = encode_hybrid(vals, width)
            nbits = 32 if width <= 32 else 64
            got, _ = native.hybrid_decode(enc, n, width, nbits)
            assert np.array_equal(got.astype(np.uint64), vals), width

    def test_hybrid_rejects_rle_value_over_width(self, native):
        # RLE run header (count 8, low bit 0) with a 1-byte value of 7 at width 2
        bad = bytes([8 << 1, 7])
        with pytest.raises(ValueError):
            native.hybrid_decode(bad, 8, 2, 32)

    def test_bytearray_take_matches_numpy(self, native, numpy_only):
        r = np.random.default_rng(9)
        items = [bytes([65 + i % 26]) * (i % 17) for i in range(300)]
        ba = ByteArrayData.from_list(items)
        idx = r.integers(0, 300, 5000)
        want = ba.take(idx)  # numpy path (fixture forces it)
        from parquet_tpu.utils import native as native_mod

        native_mod._cached, native_mod._probed = native, True
        got = ba.take(idx)
        assert got == want

    def test_varint_overflow_rejected_both_paths(self, native, numpy_only):
        from parquet_tpu.ops.varint import read_uvarint

        # 10-byte varint encoding a value >= 2**64
        bad = bytes([0xFF] * 9 + [0x7F])
        with pytest.raises(ValueError):
            read_uvarint(bad, 0, len(bad))


class TestPlainByteArrayEncodeNative:
    def test_c_encode_matches_python_loop(self):
        """The C PLAIN byte-array encoder is byte-identical to the Python
        oracle, including empty strings, empty columns, and long values."""
        from parquet_tpu.core.arrays import byte_array_from_items
        from parquet_tpu.ops.plain import encode_plain
        from parquet_tpu.utils.native import get_native

        lib = get_native()
        if lib is None or not lib.has_plain_encode_ba:
            pytest.skip("native plain encoder not built")
        for items in (
            [b"", b"a", b"bb" * 500, b"", b"xyz"],
            [],
            [b"\x00" * 7] * 100,
            [bytes([i % 256]) * (i % 13) for i in range(1000)],
        ):
            ba = byte_array_from_items(items)
            want = bytearray()
            for it in items:
                want += len(it).to_bytes(4, "little") + it
            got = encode_plain(ba, Type.BYTE_ARRAY)
            assert got == bytes(want), len(items)
            assert lib.plain_encode_bytearray(ba.data, ba.offsets) == bytes(want)
